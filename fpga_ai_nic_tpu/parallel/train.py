"""Data-parallel training loop with the fused scatter-update-gather
collective — the TPU rebuild of the reference's MPI training driver
(sw/mlp_mpi_example_f32.cpp:682-827).

Reference structure: each rank computes fwd/bwd on its batch shard; per-layer
gradients are handed to the NIC (async all-reduce + fused SGD); the host
never runs the optimizer (its calls are commented out, :765,780,787) and the
canonical weights live device-resident (FPGA DDR).  Here:

- the batch is sharded over the ``dp`` mesh axis (MPI_Scatter equivalent,
  :452-460);
- ``jax.grad`` replaces the hand-written bwd GEMM chain;
- the fused collective (`ops.fused_update`) reduce-scatters gradients,
  applies the optimizer on the owned f32 master shard, and all-gathers
  updated working weights — ZeRO-1 semantics, matching the reference's
  "gather phase distributes updated weights" design;
- issue/wait overlap (:752-764) is XLA's latency-hiding scheduler's job;
  the async-queue API for explicit overlap lives in `runtime.queue`.

Everything is one jitted step with donated state: the "updated weights
written over the gradient buffer" aliasing trick of the reference
(hw/all_reduce.sv:240,1286-1311) becomes XLA buffer donation — same memory
win, no aliasing confusion.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import accum
from . import mesh as mesh_lib
from .. import optim
from ..obs import metrics as obs_metrics
from ..ops import fused_update
from ..runtime import chaos
from ..utils.config import TrainConfig


class TrainState(NamedTuple):
    params: Any            # replicated working weights (model dtype)
    w_own: jax.Array       # this device's f32 master shard [L/n] (ZeRO-1)
    opt_state: Any         # sharded optimizer state (ZeRO-1)
    step: jax.Array
    # error-feedback residual of the configured compression codec: each
    # device's locally-dropped gradient mass [L_pad], re-added next step
    # (compress.Codec.state_init; None when the codec carries no state).
    # Checkpoint restore re-zeros it — EF is self-healing, the residual
    # is a bounded accumulator, not part of the optimization state proper.
    codec_state: Any = None


class DPTrainer:
    """Builds jitted init/step functions for a loss_fn over a 1-D dp mesh.

    loss_fn(params, batch) -> scalar; batch leaves have a leading
    global-batch axis that is sharded over dp.
    """

    def __init__(self, loss_fn: Callable, mesh: Mesh, cfg: TrainConfig,
                 axis_name: str = "dp"):
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.cfg = cfg
        self.ax = axis_name
        self.n = mesh.shape[axis_name]
        self._meta = None
        # codec="auto": codec / pipeline_depth / bucket_elems / topology
        # resolve ONCE at the first _ensure_meta (the payload size is
        # known there), from the ring_cost model under calibrated rates
        # (fpga_ai_nic_tpu.tune) — static thereafter, R2-clean, and the
        # plan lands in obs_static_metrics() for obs-gate to diff
        self._tuned_plan = None
        self._tune_calib = None
        # trace counters: the traced Python bodies below bump these once
        # per TRACE (cache miss), so the adaptation plane (tune.adapt)
        # and graftlint J13 can hold "a plan switch causes zero new
        # traces" as a counted fact, the J10 discipline applied to
        # training
        self.step_traces = 0
        self.gather_traces = 0
        self._set_codec_flags()
        if cfg.collective.fused_optimizer \
                and cfg.optimizer.clip_norm is not None:
            raise ValueError(
                "fused_optimizer cannot honor clip_norm: a global-norm "
                "clip needs a cross-replica barrier BETWEEN the "
                "reduce-scatter and the update — exactly the exposed "
                "optimizer time the fused path removes; clip before the "
                "collective or run unfused")

    def _set_codec_flags(self) -> None:
        """(Re)derive the codec object + error-feedback flag from the
        CURRENT collective config — called at construction and again
        after autotune resolution replaces the config."""
        coll = self.cfg.collective
        from .. import tune as tune_lib
        if tune_lib.needs_autotune(coll):
            # unresolved "auto": no codec to instantiate yet (resolution
            # happens at _ensure_meta, where the payload size is known)
            self._codec, self._ef = None, False
            return
        # error-feedback residual carry (compress codecs that declare it,
        # e.g. top-k): threaded through TrainState.codec_state
        codec = fused_update.resolve_codec(coll)
        self._codec = codec
        self._ef = (coll.impl == "ring" and codec is not None
                    and codec.error_feedback)

    def _resolve_auto(self, params_like) -> None:
        """One-shot autotune resolution of a codec='auto' template (no-op
        otherwise): deterministic in the banked artifacts, done in plain
        Python before any tracing.  The calibration is kept so the
        padded-length rescore prices with the SAME artifacts.  With
        ``cfg.adapt`` armed for live calibration, the banked rates are
        first upgraded by the startup mesh microbenches
        (tune.adapt.live_calibrate) — the `live` provenance tier: the
        plan is priced for the mesh the job actually landed on, not the
        mesh some artifact was banked on."""
        from .. import tune as tune_lib
        calibration = None
        acfg = getattr(self.cfg, "adapt", None)
        if (acfg is not None and acfg.enabled and acfg.live_calibration
                and tune_lib.needs_autotune(self.cfg.collective)):
            from ..tune import adapt as adapt_lib
            calibration = adapt_lib.live_calibrate(self.mesh, self.ax)
        cfg, plan, calib = tune_lib.resolve_train_config(
            self.cfg, self.n, params_like, calibration=calibration)
        if plan is None:
            return
        self.cfg = cfg
        self._tuned_plan, self._tune_calib = plan, calib
        self._set_codec_flags()

    # -- init ---------------------------------------------------------------

    def _ensure_meta(self, params_like) -> None:
        """Flat-master layout from a params tree or ShapeDtypeStructs —
        meta is static, derived without touching device memory; invalidate
        any step_fn cached against a previous model's meta."""
        self._resolve_auto(params_like)
        self._meta = fused_update.flat_meta(params_like,
                                            self.cfg.collective, self.n)
        if self._tuned_plan is not None \
                and self._tuned_plan.payload_elems != self._meta.padded_len:
            # re-price the chosen plan at the PADDED length (padding
            # depends on the resolved codec) so the banked wire-byte
            # declaration matches the collective bit for bit — under the
            # SAME calibration and slice plan the argmin scored with
            from .. import tune as tune_lib
            self._tuned_plan = tune_lib.rescore(
                self._tuned_plan, self._meta.padded_len,
                calibration=self._tune_calib,
                slice_elems=self.cfg.collective.slice_elems)
        self.__dict__.pop("step_fn", None)
        self.__dict__.pop("_gather_fn", None)

    def init_state(self, params) -> TrainState:
        """Split replicated params into ZeRO-1 master shards (the analogue
        of the first-iteration weight download to FPGA DDR, flags=1 path,
        sw/mlp_mpi_example_f32.cpp:700; hw/weight_update.sv MEM_INIT)."""
        # _ensure_meta FIRST: it resolves a codec='auto' template into
        # the concrete config _init must close over
        self._ensure_meta(params)
        coll, opt_cfg = self.cfg.collective, self.cfg.optimizer

        def _init(params):
            w_own, opt_state, meta = fused_update.init_master_shard(
                params, self.ax, coll, opt_cfg)
            return w_own, opt_state

        w_own, opt_state = jax.jit(jax.shard_map(
            _init, mesh=self.mesh, in_specs=P(),
            out_specs=P(self.ax), check_vma=False))(params)
        return TrainState(params=params, w_own=w_own, opt_state=opt_state,
                          step=jnp.zeros((), jnp.int32),
                          codec_state=self._init_codec_state())

    def _init_codec_state(self):
        """Zeroed per-device error-feedback residuals ([n * L_pad] global,
        sharded over the axis so each device carries its own [L_pad])."""
        if not self._ef:
            return None
        return jax.device_put(
            jnp.zeros((self.n * self._meta.padded_len,), jnp.float32),
            NamedSharding(self.mesh, P(self.ax)))

    # -- step ---------------------------------------------------------------

    @functools.cached_property
    def step_fn(self):
        coll, opt_cfg = self.cfg.collective, self.cfg.optimizer
        meta = self._meta
        assert meta is not None, "call init_state first"
        ax = self.ax

        codec, ef = self._codec, self._ef
        # trace-time metrics gate: False adds NOTHING to the jaxpr (the
        # obs.metrics compiled-out contract, asserted by tests/test_obs.py)
        obs_on = self.cfg.obs_metrics

        # Phase 1 (check_vma=True): gradients + reduce-scatter + optimizer.
        # Variance tracking must stay ON anywhere jax.grad runs inside
        # shard_map — with check_vma=False the transposes of collectives
        # inside the loss are silently wrong.
        def shard_update(params, w_own, opt_state, step, batch,
                         *maybe_resid):
            # Cast params dp-varying BEFORE grad: otherwise vma-typed
            # autodiff auto-inserts a full psum over dp for every gradient
            # (params are dp-invariant), which both double-counts once we
            # reduce-scatter and forfeits the fused-ring/BFP wire path.
            params_v = jax.tree_util.tree_map(
                lambda x: lax.pcast(x, ax, to="varying"), params)
            loss, grads = accum.accumulated_value_and_grad(
                self.loss_fn, self.cfg.accum_steps)(params_v, batch)
            flat_g, _ = fused_update.flatten_tree(grads, coll, self.n)
            m = {}      # in-graph metrics (obs_on only; else stays empty)
            if ef:
                # compensate-then-compress: the wire sees the locally
                # quantized gradient; what it dropped carries to the next
                # step (TrainState.codec_state)
                resid = maybe_resid[0]
                flat_raw = flat_g
                flat_g, new_resid = fused_update.error_feedback_encode(
                    codec, flat_g, resid)
                if obs_on:
                    # flat_g IS roundtrip(flat_raw + resid) here, so the
                    # declared-vs-observed check costs no extra roundtrip
                    m["codec_obs_rel_err"] = lax.pmax(
                        obs_metrics.codec_observed_error(
                            codec, flat_raw + resid, quantized=flat_g), ax)
                    m["ef_resid_norm"] = obs_metrics.l2_norm(new_resid, ax)
            elif obs_on and codec is not None:
                m["codec_obs_rel_err"] = lax.pmax(
                    obs_metrics.codec_observed_error(codec, flat_g), ax)
            diag = {}
            icheck = coll.integrity_check
            if icheck:
                # checksums guard the COLLECTIVE (what actually rides the
                # wire), so under EF they see the post-compression vector
                # — local compression is intentional, not corruption
                expect, l1 = chaos.chunk_checksums(flat_g, ax, self.n)
                tol = (coll.integrity_tol if coll.integrity_tol is not None
                       else chaos.integrity_tol(coll, self.n))
            if coll.fused_optimizer:
                # decode+accumulate+update in one pass (in-kernel on the
                # TPU fused-ring path; the same formula fused after the
                # reduce elsewhere — ops.fused_update.reduce_scatter_
                # update): the optimizer runs on zero exposed time, and
                # the EF residual carry above is untouched by the fusion
                # (it compensates the LOCAL encode, before the wire)
                res = fused_update.reduce_scatter_update(
                    flat_g, w_own, opt_state, step, ax, coll, opt_cfg,
                    integrity=icheck)
                if icheck:
                    g_sum, w_new, opt_state2, wire_ok = res
                    # BOTH tiers ride the fused path since PR 12: the
                    # value band compares the returned raw sum shard, the
                    # exact tier is the in-graph/in-kernel frame verdict
                    diag = chaos.collective_integrity(
                        expect, l1, g_sum, ax, self.n, tol)
                    diag["wire_ok"] = wire_ok
                    if fused_update.update_route_gatable(coll, self.n):
                        # pre-step state still materialized on this
                        # route: a tripped verdict gates the update to a
                        # no-op (the in-kernel route cannot — its state
                        # is donated; check_step_diag invalidates the
                        # step instead)
                        ok = diag["integrity_ok"] & wire_ok
                        w_new = jnp.where(ok, w_new, w_own)
                        opt_state2 = jax.tree_util.tree_map(
                            lambda new, old: jnp.where(ok, new, old),
                            opt_state2, opt_state)
                        if ef:
                            new_resid = jnp.where(ok, new_resid,
                                                  maybe_resid[0])
                else:
                    g_sum, w_new, opt_state2 = res
                g_own = g_sum / self.n
                if icheck:
                    diag["grad_norm"] = jnp.sqrt(lax.psum(
                        jnp.sum(g_own.astype(jnp.float32) ** 2), ax))
                if obs_on:
                    # same definition as the diag norm — reuse it (as
                    # the unfused path below does) instead of paying a
                    # second psum on the hot fused path
                    m["grad_norm"] = (diag["grad_norm"] if icheck
                                      else obs_metrics.l2_norm(g_own, ax))
                loss_m = lax.pmean(loss, ax)
                if obs_on:
                    m["loss"] = loss_m
                out = (w_new, opt_state2, loss_m, diag)
                return out + ((new_resid,) if ef else ()) + (
                    (m,) if obs_on else ())
            if icheck:
                g_red, wire_ok = fused_update.reduce_scatter(
                    flat_g, ax, coll, integrity=True)
                diag = chaos.collective_integrity(expect, l1, g_red, ax,
                                                  self.n, tol)
                # the EXACT tier (ops.integrity): bit-conservation of the
                # encoded frames — the finite wrong-value class the value
                # band above is provably blind to
                diag["wire_ok"] = wire_ok
            else:
                g_red = fused_update.reduce_scatter(flat_g, ax, coll)
            g_own = g_red / self.n
            if icheck:
                diag["grad_norm"] = jnp.sqrt(
                    lax.psum(jnp.sum(g_own.astype(jnp.float32) ** 2), ax))
            if obs_on:
                # captured HERE, pre-clip (the documented definition):
                # below this point g_own may be rescaled by clipping
                m["grad_norm"] = diag["grad_norm"] if "grad_norm" in diag \
                    else jnp.sqrt(lax.psum(
                        jnp.sum(g_own.astype(jnp.float32) ** 2), ax))
            g_own = optim.clip_by_global_norm(opt_cfg, g_own, (ax,))
            w_new, opt_state2 = optim.apply(opt_cfg, w_own, g_own,
                                            opt_state, step)
            if icheck:
                # gate the update: a corrupted reduce-scatter must not
                # reach the master weights — the step becomes a no-op and
                # the host decides (retry / restore) from the diag verdict
                ok = diag["integrity_ok"] & diag["wire_ok"]
                w_new = jnp.where(ok, w_new, w_own)
                opt_state2 = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(ok, new, old),
                    opt_state2, opt_state)
                if ef:
                    # a gated (replayed) step must not mutate the residual
                    # either, or the retry would double-count this step's
                    # dropped mass
                    new_resid = jnp.where(ok, new_resid, maybe_resid[0])
            loss_m = lax.pmean(loss, ax)
            if obs_on:
                if coll.integrity_check:
                    m["integrity_err"] = diag["integrity_err"]
                m["loss"] = loss_m
            out = (w_new, opt_state2, loss_m, diag)
            return out + ((new_resid,) if ef else ()) + ((m,) if obs_on
                                                         else ())

        # Phase 2 (no autodiff): all-gather updated weights -> replicated
        # working params (the reference's host write-back of w_new,
        # hw/all_reduce.sv:1286-1311).  With integrity on, this wire is
        # checksummed too: a corrupted weight gather poisons the
        # REPLICATED params (the masters are safe), so the verdict is
        # surfaced for check_step_diag — the elastic ladder rebuilds the
        # params from the still-clean masters.
        def shard_gather(w_new):
            if coll.integrity_check:
                flat_w, ag_ok = fused_update.all_gather_flat(
                    w_new, ax, coll, integrity=True)
                return fused_update.unflatten_tree(flat_w, meta), ag_ok
            flat_w = fused_update.all_gather_flat(w_new, ax, coll)
            return fused_update.unflatten_tree(flat_w, meta)

        def _step(state: TrainState, batch):
            self.step_traces += 1           # trace-count bookkeeping only
            in_specs = (P(), P(ax), P(ax), P(), P(ax)) + (
                (P(ax),) if ef else ())
            out_specs = (P(ax), P(ax), P(), P()) + (
                (P(ax),) if ef else ()) + ((P(),) if obs_on else ())
            args = (state.params, state.w_own, state.opt_state, state.step,
                    batch) + ((state.codec_state,) if ef else ())
            res = jax.shard_map(
                shard_update, mesh=self.mesh,
                in_specs=in_specs, out_specs=out_specs)(*args)
            w_own, opt_state, loss, diag = res[:4]
            codec_state = res[4] if ef else state.codec_state
            if obs_on:
                # route the loss through the metrics tap: the callback
                # delivers the step's metric scalars to the ambient
                # MetricsSink; consuming the tapped loss keeps it alive
                loss = obs_metrics.tap(loss, res[-1])
            if coll.integrity_check:
                new_params, ag_ok = jax.shard_map(
                    shard_gather, mesh=self.mesh, in_specs=P(ax),
                    out_specs=(P(), P()), check_vma=False)(w_own)
                diag = dict(diag, wire_ok=diag["wire_ok"] & ag_ok)
            else:
                new_params = jax.shard_map(
                    shard_gather, mesh=self.mesh, in_specs=P(ax),
                    out_specs=P(), check_vma=False)(w_own)
            new_state = TrainState(new_params, w_own, opt_state,
                                   state.step + 1, codec_state)
            if coll.integrity_check:
                # metrics dict instead of the bare loss: the elastic loop
                # (parallel.elastic) reads the integrity verdict from here
                return new_state, dict(diag, loss=loss)
            return new_state, loss

        return jax.jit(_step, donate_argnums=(0,))

    def step(self, state: TrainState, batch) -> Tuple[TrainState, jax.Array]:
        return self.step_fn(state, batch)

    # -- telemetry ----------------------------------------------------------

    def obs_static_metrics(self) -> dict:
        """Trace-time-constant telemetry facts for ``MetricsSink(static=)``:
        flat layout, declared codec properties, wire bytes per all-reduce
        (the flit-counter arithmetic of hw/bfp_adapter.sv:705-729)."""
        meta = self._meta
        assert meta is not None, "call init_state first"
        coll = self.cfg.collective
        d = {"padded_len": meta.padded_len, "n_devices": self.n,
             "impl": coll.impl, "topology": coll.topology}
        d.update(obs_metrics.codec_static_metrics(self._codec,
                                                  meta.padded_len))
        d["wire_bytes_per_allreduce"] = fused_update.wire_bytes_for(
            coll, meta.padded_len, self.n)
        d["raw_bytes_per_allreduce"] = fused_update.wire_bytes_for(
            coll, meta.padded_len, self.n, codec=None)
        if coll.topology == "hier":
            from ..ops import ring_hier
            d["hier_plan"] = ring_hier.plan_hier(
                meta.padded_len, self.n, coll.intra_size,
                self._codec).describe()
        if self._tuned_plan is not None:
            # the banked tuning decision: obs-gate diffs the declared
            # wire bytes (tune.* keys) across PRs, so a silent change of
            # plan or accounting fails CI, not a doc
            d["tune"] = self._tuned_plan.describe()
        return d

    # -- restore ------------------------------------------------------------

    @functools.cached_property
    def _gather_fn(self):
        """The jitted master->params gather, built ONCE per layout: a
        fresh closure per call would re-enter jax's jit cache (and
        recompile) on every restore/reshard — recovery-path time that is
        pure waste.  Invalidated with step_fn by _ensure_meta."""
        meta = self._meta
        assert meta is not None, "call init_state first (defines the layout)"
        coll, ax = self.cfg.collective, self.ax

        def _gather(w):
            self.gather_traces += 1         # trace-count bookkeeping only
            flat = fused_update.all_gather_flat(w, ax, coll)
            return fused_update.unflatten_tree(flat, meta)

        return jax.jit(jax.shard_map(
            _gather, mesh=self.mesh, in_specs=P(self.ax), out_specs=P(),
            check_vma=False))

    def params_from_master(self, w_own: jax.Array):
        """Rebuild the replicated working params from the sharded f32 master
        vector — the checkpoint-restore analogue of the fused step's gather
        phase.  Needed because checkpoints persist only the master shards."""
        return self._gather_fn(w_own)

    def restore_state(self, restored: dict,
                      params_like=None) -> TrainState:
        """TrainState from a Checkpointer.restore() payload.  Layout must
        be known: call init_state first or pass params_like (a params tree
        or jax.eval_shape output — zero device work)."""
        if params_like is not None:
            self._ensure_meta(params_like)
        assert self._meta is not None, (
            "flat layout unknown: call init_state first or pass params_like")
        # re-pad onto THIS mesh's flat layout: the checkpoint may have
        # been written at a different dp width (fused_update.repad_flat),
        # so restore re-gathers the same live elements under new padding
        sh = NamedSharding(self.mesh, P(self.ax))
        w_own = jax.device_put(
            fused_update.repad_flat(restored["w_own"], self._meta), sh)
        opt_state = {
            k: jax.device_put(fused_update.repad_flat(v, self._meta), sh)
            for k, v in restored["opt_state"].items()}
        return TrainState(
            params=self.params_from_master(w_own), w_own=w_own,
            opt_state=opt_state, step=jnp.asarray(restored["step"]),
            # EF residual restarts at zero: it is a bounded local
            # accumulator, and checkpoints persist only the masters
            codec_state=self._init_codec_state())

    # -- live resharding (parallel.reshard) ---------------------------------

    def reshard_leaves(self, state: TrainState) -> dict:
        """The state's flat-vector leaves in the shared transfer naming
        (reshard.pack_state_leaves) — what a live mesh move must
        transport (masters + optimizer moments; the replicated working
        params are REBUILT from the landed masters, not moved, and the
        EF residual rides its own per-device plan)."""
        from . import reshard as reshard_lib
        return reshard_lib.pack_state_leaves(state.w_own, state.opt_state)

    def state_from_reshard(self, leaves: dict, step,
                           codec_state) -> TrainState:
        """Assemble this trainer's state from landed reshard leaves (the
        inverse of ``reshard_leaves`` on the TARGET mesh): params are
        rematerialized by the same gather phase a checkpoint restore
        uses, so a resharded state and a restored one are constructed
        identically — the bit-parity contract."""
        from . import reshard as reshard_lib
        w_own, opt_state = reshard_lib.split_state_leaves(leaves)
        return TrainState(params=self.params_from_master(w_own),
                          w_own=w_own, opt_state=opt_state,
                          step=jnp.asarray(step), codec_state=codec_state)

    # -- data ---------------------------------------------------------------

    @property
    def batch_spec(self):
        """PartitionSpec for batch leaves (loaders pass this to
        ShardedLoader) — same public handle as ShardedTrainer."""
        return P(self.ax)

    def shard_batch(self, batch):
        """Place a host batch with sharding over dp (MPI_Scatter analogue)."""
        return mesh_lib.shard_host_batch(batch, self.mesh, self.batch_spec)
