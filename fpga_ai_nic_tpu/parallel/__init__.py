from . import multihost, pipeline, reshard
from .ddp import DDPState, DDPTrainer
from .elastic import (ElasticConfig, ElasticTrainer, RecoveryExhausted,
                      ReshardPolicy)
from .fsdp import FSDPState, FSDPTrainer
from .mesh import make_mesh
from .queued import QueuedDDPTrainer
from .sharded import ShardedState, ShardedTrainer
from .train import DPTrainer, TrainState

__all__ = ["make_mesh", "DPTrainer", "TrainState",
           "ShardedTrainer", "ShardedState",
           "DDPTrainer", "DDPState", "QueuedDDPTrainer",
           "FSDPTrainer", "FSDPState", "pipeline", "multihost",
           "ElasticTrainer", "ElasticConfig", "RecoveryExhausted",
           "ReshardPolicy", "reshard"]
