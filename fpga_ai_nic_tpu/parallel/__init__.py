from .mesh import make_mesh
from .train import DPTrainer, TrainState

__all__ = ["make_mesh", "DPTrainer", "TrainState"]
