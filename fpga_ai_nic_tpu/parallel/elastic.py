"""Elastic recovery loop — the supervised step driver the reference lacks.

The reference's failure story ends at detection zero: an OPAE read that
never completes hangs the training loop forever (hw/README:3-5), the
`kill_syn_e0` kill CSR is declared but never wired (hw/all_reduce.sv:83),
and the documented remedy is a human running a full shell reset
(sw/mlp_mpi_example_f32.cpp:54-57).  ``runtime.watchdog`` ships detection
primitives and ``utils.checkpoint`` ships restore; this module composes
them — plus ``parallel.multihost`` control-plane re-init and the
``runtime.chaos`` integrity guards — into one supervised loop that turns
every detected fault into a bounded recovery instead of a lost job:

    ElasticTrainer.run:
        for each step:
            plan.begin_step(step)                  # chaos only: arm faults
            watchdog.run(                          # hang -> DeviceHangError
                queue.issue(state, batch)          # host issue boundary
                queue.wait(ticket))                # host wait boundary
            check_step_diag(metrics)               # wire corruption -> raise
            drift_guard(loss / grad_norm)          # garbage-in -> raise
            heartbeat.beat(); maybe checkpoint
        on failure:
            classify -> record fault (observability.RecoveryStats)
            shrinkable (preemption, state intact, ReshardPolicy armed):
                multihost re-init -> LIVE mesh reshard onto the shrink
                target (parallel.reshard: collective redistribution, no
                disk, no replay) -> retry the same step on the new mesh
            preemption: multihost re-init
            restore last-good checkpoint -> retry with backoff

Detection layers and what each catches:

  watchdog timeout      the reference's infinite hang (a wedged dispatch,
                        a straggler that never returns)
  IntegrityError        collective corruption (chaos.collective_integrity
                        inside the jitted step — NaN/inf or checksum
                        drift on the reduce-scatter; the update was
                        already gated out in-graph, so master weights
                        stay clean)
  NormDriftGuard        host-visible garbage: non-finite or exploding
                        loss / gradient norm, e.g. a corrupted batch or
                        host-side payload damage the wire checks cannot
                        see
  InjectedPreemption /  transient driver or control-plane loss; the
  other exceptions      preemption path re-runs multihost.initialize
                        before restoring

Because the fused trainers jit their step with ``donate_argnums=(0,)``, a
failed attempt may have consumed the input state's buffers — retrying from
the in-memory pytree is not generally possible.  The loop therefore
checkpoints every ``ckpt_every`` steps (plus once before the first step)
and recovers by restoring the last-good checkpoint, replaying the steps
since: the loop is keyed on ``int(state.step)``, so a rewind re-requests
the same batches from ``batch_fn`` and re-arms nothing (a FaultPlan fires
each spec at most once — injected faults are transient by construction,
like the hang they model).

Every event lands in ``Profiler.recovery`` (utils.observability), so the
stats dump carries fault counts, restore counts and MTTR next to the
collective counters — the observable proof that the gap vs the reference
is closed, not merely argued.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from . import multihost
from ..runtime import chaos as chaos_lib
from ..runtime.queue import CollectiveQueue
from ..runtime.watchdog import DeviceHangError, Heartbeat, Watchdog
from ..utils.checkpoint import Checkpointer
from ..utils.observability import Profiler

__all__ = ["ElasticConfig", "ElasticTrainer", "RecoveryExhausted",
           "ReshardPolicy"]


class RecoveryExhausted(RuntimeError):
    """A step kept failing after max_retries recoveries — the fault is not
    transient (or the recovery path itself is broken); escalate instead of
    looping forever the way the reference's wait() poll does."""


@dataclass
class ReshardPolicy:
    """Arms the FIRST recovery tier: survive a preemption by migrating the
    live TrainState to a different mesh width (parallel.reshard) instead
    of a checkpoint restore + replay.

    ``trainer_factory(n) -> trainer`` builds an API-compatible trainer of
    axis width ``n`` (same loss/model/codec — reshard keeps the wire
    format fixed across the move).  ``shrink_to`` is the explicit target
    width, or a LADDER of widths (e.g. ``(4, 2)``): the caller knows its
    batch-divisibility and capacity constraints; the supervisor does not
    guess.  A target LARGER than the current width is a scale-OUT — the
    grow path's union seeding (``plan.seed_bytes``) applies, the
    recovery semantics are identical.  With ``prewarm`` (the
    spare-capacity discipline), ``ElasticTrainer.prewarm_reshard``
    compiles the transfer program and the target trainer's step AHEAD of
    the fault on a zeros ghost state, so the measured MTTR is the
    migration itself, not a compile.

    After a *successful* tier-1 recovery the tier RE-ARMS onto the next
    rung automatically (a second preemption in a long job must not
    silently fall back to the slow restore tier), bounded by
    ``max_reshards`` — at most that many reshards per supervisor (None =
    the ladder length is the bound).  A rung equal to the CURRENT width
    is skipped, not an error, so a ladder written as the full descent
    ``(8, 4, 2)`` on a dp8 trainer works (8 is a no-op rung, 4 is the
    first real target) — it must never silently wedge the tier.  When
    the ladder (or the bound) is exhausted the policy disarms and the
    next fault takes the restore tier."""

    trainer_factory: Callable[[int], Any]
    shrink_to: Union[int, Sequence[int]]
    prewarm: bool = True
    max_reshards: Optional[int] = None

    def __post_init__(self) -> None:
        if not self.rungs():
            raise ValueError("shrink_to needs at least one target width")
        bad = [n for n in self.rungs() if n <= 0]
        if bad:
            raise ValueError(f"non-positive target width(s) {bad} in "
                             f"shrink_to={self.shrink_to}")

    def rungs(self) -> Tuple[int, ...]:
        if isinstance(self.shrink_to, int):
            return (self.shrink_to,)
        return tuple(int(n) for n in self.shrink_to)


@dataclass(frozen=True)
class ElasticConfig:
    """Knobs for the supervised loop.  Defaults suit production cadence;
    tests and the chaos bench shrink the timeouts to sub-second."""

    step_timeout_s: float = 300.0     # watchdog limit per step attempt
    stall_after_s: float = 600.0      # heartbeat staleness for monitors
    max_retries: int = 3              # recoveries per step before giving up
    backoff_s: float = 0.05           # exponential backoff base
    ckpt_every: int = 1               # checkpoint cadence (steps)
    # durability plane (utils.checkpoint v2): retention GC bound (None =
    # keep everything), peer mirroring of the stored shards (the
    # redundancy the repair tier fetches from — ON for the supervised
    # loop: a restore target that cannot survive a single flipped bit
    # is not a recovery tier), and the watchdog-trip emergency dump
    # ("dump before dying": when the ladder exhausts, persist the live
    # state if its buffers survived, flagged emergency in the manifest)
    ckpt_keep_last: Optional[int] = None
    ckpt_mirror: bool = True
    emergency_dump: bool = True
    drift_factor: float = 1e3         # NormDriftGuard trip factor
    drift_warmup: int = 3             # clean samples before drift arms
    # master-shard guard: validate what the checkpoint will persist
    # (w_own + opt_state) for finiteness and norm drift BEFORE a step's
    # state is accepted.  Catches host-side payload corruption the loss
    # cannot see until the NEXT step — by which time the poisoned state
    # would already be the restore target.  Costs a device->host pull of
    # the master shard per step, so: None = on only when a FaultPlan is
    # armed (chaos runs), True/False = forced.
    master_guard: Optional[bool] = None


class ElasticTrainer:
    """Supervised elastic wrapper around a fused trainer (``DPTrainer`` or
    API-compatible: ``step_fn``, ``restore_state``, ``cfg.collective``).

    ``plan`` (a ``runtime.chaos.FaultPlan``) is optional and only for
    fault-injection runs: the loop arms it per step and routes the step
    dispatch through a ``CollectiveQueue`` carrying the plan, so the
    queue.issue / queue.wait host boundaries fire; the collective site
    fires via the ring tap (``chaos.install_collective_tap``) compiled
    into the step, and the staging site via ``stage_fn`` (a host batch
    pass, e.g. a ``runtime.staging.Stager`` roundtrip).

    The loop itself is chaos-agnostic: with ``plan=None`` it is a plain
    production supervisor — watchdog, integrity/drift checks, heartbeat,
    checkpoint cadence, restore-on-failure.
    """

    def __init__(self, trainer, ckpt_dir: str,
                 cfg: Optional[ElasticConfig] = None, *,
                 plan: Optional[chaos_lib.FaultPlan] = None,
                 stage_fn: Optional[Callable[[Any], Any]] = None,
                 profiler: Optional[Profiler] = None,
                 reshard: Optional[ReshardPolicy] = None):
        self.trainer = trainer
        self.cfg = cfg or ElasticConfig()
        self.plan = plan
        self.stage_fn = stage_fn
        self.reshard_policy = reshard
        self._reshard_trainer = None     # (target_width, trainer), lazy
        self._rung_idx = 0               # ladder position (skips no-ops)
        self._reshards_done = 0          # ACTUAL moves (max_reshards)
        # set once a reshard moved the loop onto a different mesh: every
        # later batch may still be placed for the OLD mesh (callers'
        # batch_fn pre-shards), so step() re-places through the current
        # trainer — a no-op for correctly placed batches
        self._mesh_moved = False
        self.profiler = profiler or Profiler()
        self.watchdog = Watchdog(self.cfg.step_timeout_s)
        self.heartbeat = Heartbeat(stall_after_s=self.cfg.stall_after_s)
        # the hardened last tier: audited manifests, per-shard peer
        # mirrors (trainer.n dp peers), bounded retention, durability
        # chaos sites armed from the same plan as every other site
        self.ckpt = Checkpointer(
            ckpt_dir, shards=getattr(trainer, "n", None),
            mirror=self.cfg.ckpt_mirror, keep_last=self.cfg.ckpt_keep_last,
            chaos=plan, recovery=self.profiler.recovery,
            events=self.profiler.events)
        self.loss_guard = chaos_lib.NormDriftGuard(
            factor=self.cfg.drift_factor, warmup=self.cfg.drift_warmup)
        self.gnorm_guard = chaos_lib.NormDriftGuard(
            factor=self.cfg.drift_factor, warmup=self.cfg.drift_warmup)
        self.wnorm_guard = chaos_lib.NormDriftGuard(
            factor=self.cfg.drift_factor, warmup=self.cfg.drift_warmup)
        self._guard_state = (self.cfg.master_guard if self.cfg.master_guard
                             is not None else plan is not None)
        # one dispatch in flight at a time; the queue exists for its
        # issue/wait boundaries (stall attribution + chaos hooks), the
        # same two host-visible points the reference ABI exposes
        self.queue = CollectiveQueue(
            lambda state, batch: self.trainer.step_fn(state, batch),
            trainer.cfg.collective, self.profiler, chaos=plan)
        if plan is not None and plan.events is None:
            # injected faults land in the same event stream as the spans
            # and ticket intervals they perturb — the timeline shows the
            # fault AND the recovery it provoked on one axis
            plan.events = self.profiler.events

    # -- one attempt (runs inside the watchdog worker thread) ---------------

    def _attempt(self, state, batch):
        if self.stage_fn is not None:
            batch = self.stage_fn(batch)
        ticket = self.queue.issue(state, batch)
        return self.queue.wait(ticket)

    # -- detection ----------------------------------------------------------

    def _check(self, metrics, step: int) -> Dict:
        """Host verdict on a completed step's outputs; raises
        IntegrityError on any tripped guard.  Returns metrics as a dict."""
        if not isinstance(metrics, dict):
            metrics = {"loss": metrics}
        chaos_lib.check_step_diag(metrics, step)
        self.loss_guard.check(float(metrics["loss"]), "loss")
        if "grad_norm" in metrics:
            self.gnorm_guard.check(float(metrics["grad_norm"]), "grad_norm")
        return metrics

    def _check_state(self, state, step: int) -> None:
        """Validate exactly what a checkpoint would persist (the master
        shard + optimizer state): non-finite values or a norm jump mean
        the state must not become the restore target.  The working params
        are NOT checked — checkpoints drop them and restore rematerializes
        from the masters, so params damage is covered by the next step's
        loss guard against a still-clean checkpoint."""
        if not self._guard_state:
            return
        total = 0.0
        for name in ("w_own", "w_master"):
            leaf = getattr(state, name, None)
            if leaf is None:
                continue
            host = np.asarray(jax.device_get(leaf), np.float32)
            bad = int(np.size(host) - np.isfinite(host).sum())
            if bad:
                raise chaos_lib.IntegrityError(
                    f"master shard '{name}' holds {bad} non-finite "
                    f"values after step {step} — refusing to accept "
                    "(a checkpoint of this state would poison recovery)")
            total += float(np.sum(host * host, dtype=np.float64))
        if total:
            self.wnorm_guard.check(np.sqrt(total), "master_norm")
        for k, v in (getattr(state, "opt_state", None) or {}).items():
            host = np.asarray(jax.device_get(v))
            if np.issubdtype(host.dtype, np.floating) and \
                    not np.isfinite(host).all():
                raise chaos_lib.IntegrityError(
                    f"optimizer state '{k}' went non-finite at step {step}")

    # -- recovery -----------------------------------------------------------

    def _classify(self, err: BaseException, state: Any = None) -> str:
        if isinstance(err, chaos_lib.InjectedPreemption):
            # a preemption whose pre-step state is still intact AND for
            # which a shrink target is armed is SHRINKABLE: tier-1
            # recovery migrates the live state to the smaller mesh
            # (parallel.reshard) — no disk, no replay.  One detected at
            # the wait boundary may have donated the state into the
            # failed attempt; only checkpoint restore can rebuild that.
            if self._reshard_available(state):
                return "shrinkable"
            return "preemption"
        if isinstance(err, DeviceHangError):
            return "hang"
        if isinstance(err, chaos_lib.WireIntegrityError):
            # the EXACT tier (encoded-frame / page checksums) — its own
            # RecoveryStats fault class, so artifacts can prove WHICH
            # tier caught a finite corruption the value band cannot see
            return "wire-corruption"
        if isinstance(err, chaos_lib.IntegrityError):
            return "corruption"
        if isinstance(err, chaos_lib.InjectedFault):
            return err.kind
        return "error"

    # -- tier 1: live mesh reshard ------------------------------------------

    def _next_width(self) -> Optional[int]:
        """The armed target width, or None when the ladder / bound is
        exhausted (the next fault then takes the restore tier).  Rungs
        equal to the CURRENT width are skipped — a no-op rung must
        never wedge the tier into silent restore-only recovery."""
        pol = self.reshard_policy
        if pol is None:
            return None
        if pol.max_reshards is not None \
                and self._reshards_done >= pol.max_reshards:
            return None
        for w in pol.rungs()[self._rung_idx:]:
            if w != self.trainer.n:
                return w
        return None

    def _reshard_available(self, state) -> bool:
        return (self._next_width() is not None
                and state is not None
                and chaos_lib.state_buffers_alive(state))

    def _ensure_reshard_trainer(self):
        target = self._next_width()
        assert target is not None, "no reshard rung armed"
        if self._reshard_trainer is None \
                or self._reshard_trainer[0] != target:
            pol = self.reshard_policy
            self._reshard_trainer = (target, pol.trainer_factory(target))
        return self._reshard_trainer[1]

    def _do_reshard(self, state):
        """Migrate the live state to the armed target width and swap the
        loop onto the new trainer.  The queue's dispatch closure reads
        ``self.trainer`` at call time, so the swap re-routes every
        subsequent attempt.  After a SUCCESSFUL move the tier re-arms
        onto the next ladder rung (bounded by ``max_reshards``);
        exhausting the ladder disarms the policy."""
        from . import reshard as reshard_lib
        tgt = self._ensure_reshard_trainer()
        rungs = self.reshard_policy.rungs()
        while rungs[self._rung_idx] == self.trainer.n:
            self._rung_idx += 1          # the no-op rungs being skipped
        new_state = reshard_lib.reshard_state(
            self.trainer, tgt, state, events=self.profiler.events)
        self.trainer = tgt
        self._rung_idx += 1              # past the rung just used
        self._reshards_done += 1
        self._reshard_trainer = None
        if self._next_width() is None:
            self.reshard_policy = None   # ladder/bound exhausted
        self._mesh_moved = True
        return new_state

    def prewarm_reshard(self, state, batch=None) -> None:
        """Compile the whole tier-1 path ahead of the fault (the
        spare-capacity discipline): the transfer program, the target
        trainer's params gather and — given a representative ``batch`` —
        its step.  Runs on a zeros GHOST of ``state`` (same shapes/
        shardings) so the live state is never donated into a warmup."""
        from . import reshard as reshard_lib
        pol = self.reshard_policy
        if pol is None or not pol.prewarm:
            return
        tgt = self._ensure_reshard_trainer()

        def ghost_leaf(a):
            if isinstance(a, jax.Array):
                return jax.device_put(
                    np.zeros(a.shape, a.dtype), a.sharding)
            return a

        ghost = jax.tree_util.tree_map(ghost_leaf, state)
        with self.profiler.bucket("reshard.prewarm"):
            gstate = reshard_lib.reshard_state(self.trainer, tgt, ghost)
            if batch is not None:
                # EXECUTE one ghost step (not .lower().compile(): the
                # AOT path does not populate the jit dispatch cache the
                # fault-time retry will hit)
                out = tgt.step_fn(gstate, tgt.shard_batch(batch))
                jax.block_until_ready(out)

    # -- tier 2: checkpoint restore -----------------------------------------

    def _restore(self):
        """Last-good VERIFIED state from the checkpoint directory: every
        leaf audited against its manifest, corrupt shards peer-repaired
        where a clean mirror exists, and the walk falling back past
        corrupt/torn steps to the previous verified one.  A restore
        target that fails its audit with no clean source is REFUSED
        (CheckpointIntegrityError propagates — training on silently
        corrupted masters is worse than dying loudly).  The loop saved a
        checkpoint before the first step, so this normally has a
        target."""
        if self.ckpt.latest_step() is None:
            raise RuntimeError(
                f"no checkpoint under {self.ckpt.directory} to restore "
                "from (run() saves step 0 before the loop; direct step() "
                "callers must checkpoint() first)")
        _step, tree = self.ckpt.restore_latest_verified()
        return self.trainer.restore_state(tree)

    def checkpoint(self, state) -> Optional[str]:
        """Persist ``state`` under the audited commit protocol.  A save
        interrupted by an injected durability fault (kill-during-save /
        disk-full) or a real OSError is absorbed and recorded — the
        commit protocol guarantees the directory still restores to the
        previous verified step, and the next cadence save retries —
        rather than killing a training loop that is otherwise healthy.
        The absorption is LEGAL only while a verified restore target
        exists: a failed FIRST save (no step on disk at all) re-raises,
        because swallowing it would let run() proceed uncheckpointed
        and die unrecoverably at the first fault, steps away from the
        disk problem that caused it."""
        try:
            return self.ckpt.save(int(state.step), state,
                                  shards=getattr(self.trainer, "n", None))
        except (OSError, chaos_lib.InjectedFault) as err:
            if isinstance(err, chaos_lib.InjectedFault) and \
                    err.kind not in chaos_lib.DURABILITY_KINDS:
                raise
            self.profiler.recovery.record_ckpt_save_failure()
            self.profiler.events.instant(
                "ckpt.save_failed", step=int(state.step),
                error=repr(err)[:200])
            if self.ckpt.latest_step(verified=True) is None:
                raise
            return None

    def _emergency_dump(self, state, step_i: int) -> Optional[str]:
        """The 'dump before dying' tier: when the recovery ladder
        exhausts, persist the live pre-step state (if its buffers were
        not donated into the failed attempt) flagged ``emergency`` in
        the manifest, so a post-mortem restart can resume from the trip
        point instead of the last cadence checkpoint."""
        if not self.cfg.emergency_dump or state is None \
                or not chaos_lib.state_buffers_alive(state):
            return None
        try:
            path = self.ckpt.save(int(state.step), state, emergency=True,
                                  shards=getattr(self.trainer, "n", None))
        except Exception as err:  # noqa: BLE001 — dying anyway; stay loud
            self.profiler.events.instant(
                "ckpt.emergency_failed", step=step_i,
                error=repr(err)[:200])
            return None
        self.ckpt.wait_until_finished()
        self.profiler.recovery.record_emergency_dump()
        self.profiler.events.instant("ckpt.emergency", step=step_i,
                                     path=path)
        return path

    # -- the supervised step ------------------------------------------------

    def step(self, state, batch,
             batch_fn: Optional[Callable[[int], Any]] = None
             ) -> Tuple[Any, Dict]:
        """One training step that survives detected faults: attempt ->
        detect -> (record, re-init if preempted, restore, backoff) ->
        retry, up to cfg.max_retries recoveries.

        ``batch_fn`` (step -> batch) lets a restore that rewinds to an
        EARLIER step (ckpt_every > 1) re-fetch that step's batch; without
        it the retry can only reuse ``batch``, which is wrong data for a
        rewound step — run() always passes it."""
        step_i = int(state.step)
        if self._mesh_moved and hasattr(self.trainer, "shard_batch"):
            # the loop lives on a different mesh than the caller's
            # batch_fn placed for: re-place (no-op when already right)
            batch = self.trainer.shard_batch(batch)
        if self.plan is not None:
            self.plan.begin_step(step_i)
        t_fault = None
        event = None
        restored = False
        resharded = False
        for attempt in range(self.cfg.max_retries + 1):
            try:
                new_state, metrics = self.watchdog.run(
                    self._attempt, state, batch,
                    timeout_s=self.cfg.step_timeout_s)
                metrics = self._check(metrics, step_i)
                self._check_state(new_state, step_i)
            except Exception as err:  # noqa: BLE001 — the recovery boundary
                kind = self._classify(err, state)
                now = time.monotonic()
                t_fault = t_fault if t_fault is not None else now
                ev = self.profiler.recovery.record_fault(
                    kind, step_i, site=getattr(err, "site", ""),
                    error=repr(err))
                event = event or ev
                self.profiler.events.instant(
                    "fault", kind=kind, step=step_i,
                    site=getattr(err, "site", ""))
                # a failed attempt's ticket may be un-waitable (a wedged
                # dispatch): drop the window or stale tickets eventually
                # wedge issue() itself
                self.queue.abandon()
                if attempt >= self.cfg.max_retries:
                    self.profiler.recovery.record_failed_recovery()
                    self._emergency_dump(state, step_i)
                    raise RecoveryExhausted(
                        f"step {step_i} failed {attempt + 1} times "
                        f"(last: {kind}); giving up after max_retries="
                        f"{self.cfg.max_retries}") from err
                if kind in ("preemption", "shrinkable"):
                    # the process 'lost its slice': control-plane re-init
                    # before touching devices again (idempotent; a no-op
                    # single-process, the real thing on a pod restart)
                    multihost.initialize()
                if kind == "shrinkable":
                    # tier 1: migrate the LIVE state onto the shrink
                    # target by collective redistribution — no disk IO,
                    # no step replay; the retry re-runs THIS step on the
                    # new mesh.  Any failure falls through to tier 2.
                    try:
                        with self.profiler.bucket("reshard"):
                            state = self._do_reshard(state)
                        resharded = True
                        # the batch was placed for the OLD mesh: re-place
                        # it for the new trainer's sharding
                        raw = batch_fn(step_i) if batch_fn is not None \
                            else batch
                        batch = self.trainer.shard_batch(raw)
                        time.sleep(self.cfg.backoff_s * (2 ** attempt))
                        continue
                    except Exception as rerr:  # noqa: BLE001 — tier fallback
                        self.profiler.events.instant(
                            "reshard.failed", step=step_i,
                            error=repr(rerr)[:200])
                with self.profiler.bucket("restore"):
                    state = self._restore()
                restored = True
                if int(state.step) != step_i:
                    # the restore rewound past this step (ckpt_every > 1):
                    # the retry now trains the REWOUND step, so it needs
                    # that step's batch and fault arming, not this one's
                    step_i = int(state.step)
                    if batch_fn is not None:
                        batch = batch_fn(step_i)
                    if self.plan is not None:
                        self.plan.begin_step(step_i)
                if resharded:
                    # a restore AFTER a reshard lands on the new mesh:
                    # repad_flat re-fits the checkpoint bytes, but the
                    # batch placement must follow the current trainer
                    batch = self.trainer.shard_batch(batch)
                time.sleep(self.cfg.backoff_s * (2 ** attempt))
            else:
                if t_fault is not None:
                    self.profiler.recovery.record_recovery(
                        time.monotonic() - t_fault, restored=restored,
                        resharded=resharded, event=event)
                    self.profiler.events.instant(
                        "recovered", step=step_i, restored=restored,
                        resharded=resharded)
                if resharded and self.reshard_policy is not None:
                    # the tier re-armed onto the next rung: compile that
                    # path NOW, outside the measured recovery window —
                    # the prewarm guarantee must hold for every rung,
                    # not just the first (a second preemption's MTTR
                    # must be the migration, never a fault-time compile)
                    self.prewarm_reshard(new_state, batch)
                self.heartbeat.beat()
                return new_state, metrics
        raise AssertionError("unreachable")

    # -- the supervised loop ------------------------------------------------

    def run(self, state, batch_fn: Union[Callable[[int], Any], list],
            n_steps: int) -> Tuple[Any, Dict]:
        """Drive training to ``state.step == n_steps`` under supervision.

        ``batch_fn(step) -> sharded batch`` (a list works too); it is
        re-invoked for replayed steps after a checkpoint restore, so it
        must be deterministic per step for exact replay (the loaders'
        seeded-shuffle contract already guarantees this).
        """
        if callable(batch_fn):
            get_batch = batch_fn
        else:
            batches = list(batch_fn)
            get_batch = lambda i: batches[i]  # noqa: E731
        if self.ckpt.latest_step(verified=True) is None:
            # a VERIFIED restore target always exists before the loop (a
            # directory holding only corrupt/torn leftovers counts as
            # empty — restoring from it would refuse anyway)
            self.checkpoint(state)
        metrics: Dict = {}
        while int(state.step) < n_steps:
            step_i = int(state.step)
            state, metrics = self.step(state, get_batch(step_i),
                                       batch_fn=get_batch)
            if (int(state.step) % self.cfg.ckpt_every == 0
                    or int(state.step) >= n_steps):
                self.checkpoint(state)
        return state, metrics
