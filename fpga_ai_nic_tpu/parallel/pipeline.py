"""Pipeline parallelism (pp): GPipe microbatch schedule over a mesh axis.

The reference has no pipeline axis (its only strategy is DP over a ring of
FPGAs, SURVEY.md §2 "Parallelism strategies"), but its defining mechanism —
a static ring whose stages each own a slice of state and forward partial
results to the next hop (hw/all_reduce.sv st_eth_t, SEND_LOCAL/REDUCE/
FORWARD) — is exactly what a TPU pipeline stage does with activations.  We
reuse that shape: each device owns a contiguous slice of the layer stack,
processes one microbatch per tick, and `lax.ppermute`s its activation to the
next stage, keeping the ring full (1 bubble of pp-1 ticks per batch, the
GPipe schedule).

Everything is a single `lax.scan` inside `shard_map`, so XLA sees static
control flow; autodiff through ppermute gives the reverse-ring backward
schedule for free.

Layout contract:
- stage params: any pytree whose leaves are stacked [n_local_layers, ...]
  slices of the global [n_layers, ...] stack, sharded P(pp_axis, ...).
- activations: replicated over pp on entry; microbatching is temporal
  (B is split into num_microbatches chunks), so batch specs never mention pp.
- output: valid on the LAST stage; use `from_last_stage` (scalar-cheap psum
  mask) to make it pp-invariant.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax


def _pcast_to(x: jax.Array, vma) -> jax.Array:
    """Widen x's varying-manual-axes set to `vma` (scan carries must enter
    with the vma type their loop body produces)."""
    missing = tuple(sorted(set(vma) - set(jax.typeof(x).vma)))
    return lax.pcast(x, missing, to="varying") if missing else x


def _tree_vma(*trees) -> set:
    vma = set()
    for t in trees:
        for leaf in jax.tree_util.tree_leaves(t):
            vma |= set(jax.typeof(leaf).vma)
    return vma


def stack_layers(layers: List[Any]):
    """[{w: [..]}, ...] -> {w: [L, ..]}: stack a homogeneous list-of-pytrees
    along a new leading layer axis (shardable over pp)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layers(stacked) -> List[Any]:
    leaves = jax.tree_util.tree_leaves(stacked)
    n = leaves[0].shape[0]
    return [jax.tree_util.tree_map(lambda x: x[i], stacked)
            for i in range(n)]


def scan_layers(block_fn: Callable, stacked_params, x, *,
                remat: bool = False):
    """Apply block_fn(layer_params, x) -> x over a stacked [L, ...] slice."""
    def fn(lyr, h):
        return block_fn(lyr, h), jnp.float32(0.0)

    out, _ = scan_layers_aux(fn, stacked_params, x, remat=remat)
    return out


def scan_layers_aux(block_fn: Callable, stacked_params, x, *,
                    remat: bool = False):
    """Apply block_fn(layer_params, x) -> (x, aux) over a stacked [L, ...]
    slice, summing the per-layer aux scalars (MoE load-balance loss)."""
    fn = jax.checkpoint(block_fn) if remat else block_fn

    # carry must enter varying over every axis the block output varies over
    # (block_fn is assumed vma-monotone, e.g. residual-style)
    vma = _tree_vma(x, stacked_params)

    def body(carry, lyr):
        h, acc = carry
        h, aux = fn(lyr, h)
        return (h, acc + aux.astype(jnp.float32)), None

    (out, aux), _ = lax.scan(
        body, (_pcast_to(x, vma), _pcast_to(jnp.float32(0.0), vma)),
        stacked_params)
    return out, aux


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array,
                   num_microbatches: int, pp_axis: str) -> jax.Array:
    """`pipeline_apply_aux` for aux-free stage_fn(stage_params, mb) -> mb."""
    out, _ = pipeline_apply_aux(
        lambda p, mb: (stage_fn(p, mb), jnp.float32(0.0)),
        stage_params, x, num_microbatches, pp_axis)
    return out


def pipeline_apply_aux(stage_fn: Callable, stage_params, x: jax.Array,
                       num_microbatches: int, pp_axis: str):
    """Run x through the full pipeline; call inside shard_map.

    stage_fn(stage_params, mb) -> (mb, aux) applies this device's layer
    slice to one microbatch, returning an auxiliary scalar (MoE
    load-balance loss; 0.0 for dense stacks).  x: [B, ...] replicated over
    pp, B % num_microbatches == 0.  Returns (out [B, ...], aux scalar) —
    out valid ONLY on the last stage (mask with `from_last_stage`); aux is
    already pp-invariant (psum over stages) and averaged over microbatches,
    matching the unpipelined path's one-full-batch aux up to the
    per-microbatch routing granularity.

    Schedule (per tick t of num_microbatches + pp - 1):
      stage 0 injects microbatch t; every stage applies its slice; the
      result rotates one hop down the ring (ppermute), exactly the
      reference's SEND_LOCAL -> REDUCE -> FORWARD slice rotation
      (hw/all_reduce.sv:891-1086) with layers in place of partial sums.
    Ticks where a stage holds no real microbatch compute on ring garbage;
    those results land in output slots that a later tick overwrites, and
    their aux contributions are masked out (stage s holds real microbatch
    t - s only when 0 <= t - s < num_microbatches).
    """
    n = lax.axis_size(pp_axis)
    stage = lax.axis_index(pp_axis)
    B = x.shape[0]
    assert B % num_microbatches == 0, (B, num_microbatches)
    mb = B // num_microbatches
    x_mb = x.reshape((num_microbatches, mb) + x.shape[1:])
    perm = [(i, (i + 1) % n) for i in range(n)]

    # scan carries enter with the vma type the tick body produces: varying
    # over pp (stage index / ppermute) plus everything x or the params carry
    vma = _tree_vma(x, stage_params) | {pp_axis}
    state = _pcast_to(jnp.zeros_like(x_mb[0]), vma)
    outputs = _pcast_to(jnp.zeros_like(x_mb), vma)
    aux0 = _pcast_to(jnp.float32(0.0), vma)

    def tick(carry, t):
        state, outputs, aux_acc = carry
        inject = lax.dynamic_index_in_dim(x_mb, t % num_microbatches, 0,
                                          keepdims=False)
        cur = jnp.where(stage == 0, inject, state)
        out, aux = stage_fn(stage_params, cur)
        real = ((t >= stage) & (t - stage < num_microbatches))
        aux_acc = aux_acc + jnp.where(real, aux.astype(jnp.float32), 0.0)
        # Last stage finished microbatch t-(n-1); earlier ticks write garbage
        # at wrapped indices that tick t+num_microbatches overwrites.
        outputs = lax.dynamic_update_index_in_dim(
            outputs, out, (t - (n - 1)) % num_microbatches, 0)
        state = lax.ppermute(out, pp_axis, perm)
        return (state, outputs, aux_acc), None

    ticks = jnp.arange(num_microbatches + n - 1)
    (_, outputs, aux_acc), _ = lax.scan(tick, (state, outputs, aux0), ticks)
    aux = lax.psum(aux_acc, pp_axis) / num_microbatches
    return outputs.reshape(x.shape), aux


def pipeline_train_1f1b(stage_fn: Callable, loss_head_fn: Callable,
                        stage_params, head_params, x: jax.Array,
                        ctx, num_microbatches: int,
                        pp_axis: str, report_len: int = 0):
    """One fused forward+backward pass under the 1F1B schedule — explicit
    per-tick scheduling of forwards, backwards, and both ring directions,
    returning gradients directly (no outer jax.grad).

    Why it exists: differentiating ``pipeline_apply`` (GPipe) makes jax
    save the forward scan's carries — O(num_microbatches) live
    activations per stage.  1F1B caps the in-flight window at the ring
    depth: stage s never holds more than pp - s microbatch activations,
    so the buffer here is a static [pp, ...] ring regardless of
    num_microbatches (the standard perf-grade schedule for deep stacks
    at large microbatch counts; beyond-reference — the reference has no
    pipeline axis at all).

    Schedule (derived; all stages lockstep, one work unit per tick):
      fwd of microbatch m at stage s:  tick  s + 2m
      bwd of microbatch m at stage s:  tick  2*pp - 1 - s + 2m
    Forward ticks have parity s, backward ticks parity s + 1 — each
    stage strictly alternates F,B,F,B with no same-tick collision, the
    activation arrives exactly one tick after the upstream forward, and
    the cotangent one tick after the downstream backward.  Total ticks
    2*(M + pp) - 3 vs GPipe's 2*(M + pp - 1) forward+backward units —
    same bubble, O(pp) memory.

    Backward recompute: at a backward tick the stage re-runs its forward
    under jax.vjp from the SAVED INPUT activation (stage-granular
    rematerialization, like GPipe-with-remat) — the ring buffer then
    stores one known-shape activation per in-flight microbatch instead
    of arbitrary vjp residuals.

    Contracts (call inside shard_map):
      stage_fn(stage_params, head_params, x_in, ctx_mb)
          -> (x_out, stage_loss)
        this stage's layer slice on one microbatch plus the stage's OWN
        per-microbatch scalar loss contribution (MoE load-balance aux —
        every stage's loss channel is seeded in its backward, not just
        the last).  stage_loss must carry the same varying type as
        x_out; plain stacks return the zero-gradient
        ``jnp.sum(x_out) * 0.0``, NOT an invariant literal (mixing an
        invariant scalar into the varying loss channel inserts a pvary
        whose transpose is a psum inside the divergent cond).  head_params carries
        replicated leaves stages may need, e.g. stage 0's embedding —
        gate stage-specific work on lax.axis_index(pp_axis), keeping any
        collectives over OTHER axes, never over pp_axis.
      loss_head_fn(head_params, x_out, ctx_mb) -> scalar per-microbatch
        loss (applied on the LAST stage only, ADDED to that stage's
        contribution)
      x:   [B, ...] initial activations, replicated over pp, B % M == 0
      ctx: pytree of [B, ...] arrays (tokens/labels/masks), microbatched
        alongside x and handed to every stage + the head

    report_len > 0 switches both callables to a three-output contract —
    stage_fn -> (x_out, stage_loss, report [report_len]) and
    loss_head_fn -> (loss, report [report_len]) — where `report` is a
    NON-differentiated f32 vector accumulated across stages and
    microbatches (summed, psum'd over pp, NOT divided by M) and returned
    as a fifth output.  This is the display channel: a wrapper can fold
    per-term gradient scales into the differentiated loss channel while
    reconstructing exact unscaled values (e.g. raw token-NLL sum and raw
    MoE aux) from the report.

    Returns (loss, d_stage_params, d_head_params, d_x[, report]):
      loss   microbatch-mean of the summed per-stage contributions +
             head losses (pp-invariant: psum over stages — identical to
             the last stage's value for plain stacks)
      d_*    gradient trees matching the params; each leaf is psum'd over
             EXACTLY the axes it was widened over on entry (an
             already-varying leaf — dp-varying grads for a manual dp
             reduce-scatter, tp-sharded weights — keeps its per-shard
             cotangent, so this composes with any outer mesh)
      d_x    [B, ...] cotangent of the initial activations (for an
             embedding vjp outside), invariantized the same way
    The per-stage loss channel + report channel carry MoE: every
    stage's load-balance aux differentiates locally with its gradient
    scale folded into the objective, and the raw values ride the report
    for exact display (llama.loss_and_grads_pp_1f1b).
    """
    n = lax.axis_size(pp_axis)
    stage = lax.axis_index(pp_axis)
    M = num_microbatches
    B = x.shape[0]
    assert B % M == 0, (B, M)
    mb = B // M
    tmap = jax.tree_util.tree_map

    def to_mb(v):
        return v.reshape((M, mb) + v.shape[1:])

    x_mb = to_mb(x)
    ctx_mb = tmap(to_mb, ctx)
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]
    is_last = stage == n - 1
    act_shape = (mb,) + x.shape[1:]
    vma = _tree_vma(x, ctx, stage_params, head_params) | {pp_axis}

    # Widen EVERY input to the full varying set BEFORE the schedule runs,
    # RECORDING the widened axes per leaf.  The scheduling conds are
    # stage-divergent, and jax.vjp transposes an invariant-used-in-
    # varying-math widening into a psum — a collective inside a divergent
    # branch deadlocks the whole mesh (observed as an XLA rendezvous
    # abort: 3 devices in collective-permute, 1 in all-reduce).  With all
    # inputs varying, every vjp inside the conds is collective-free;
    # invariantization happens exactly once after the scan — each
    # gradient leaf psum'd over precisely its recorded widened axes (the
    # manual transpose of the entry pcast).
    def widen(tree):
        axes = tmap(lambda v: tuple(sorted(set(vma)
                                           - set(jax.typeof(v).vma))), tree)
        return tmap(lambda v: _pcast_to(v, vma), tree), axes

    def unwiden_grads(grads, axes):
        return tmap(lambda d, a: lax.psum(d, a) if a else d, grads, axes)

    sp_v, sp_axes = widen(stage_params)
    hp_v, hp_axes = widen(head_params)
    x_axes = tuple(sorted(set(vma) - set(jax.typeof(x).vma)))
    x_mb = _pcast_to(x_mb, vma)
    ctx_mb = tmap(lambda v: _pcast_to(v, vma), ctx_mb)

    R = report_len

    def g(sp, hp, x_in, c_in):
        """The per-stage primal: layer slice (+ its own loss
        contribution), then the loss head on the last stage.  The false
        branch derives its (varying) type from h with a zero-gradient
        sum, NOT a pcast — a pcast's transpose is a psum, which must not
        exist inside this divergent cond.  The report channel rides
        along stop-gradiented (display only, never differentiated)."""
        if R:
            h, stage_loss, rep_s = stage_fn(sp, hp, x_in, c_in)
            head_loss, head_rep = lax.cond(
                is_last,
                lambda: [o.astype(jnp.float32) for o in
                         loss_head_fn(hp, h, c_in)],
                lambda: [jnp.sum(h).astype(jnp.float32) * 0.0,
                         jnp.zeros((R,), jnp.float32)
                         + jnp.sum(h).astype(jnp.float32) * 0.0])
            rep = lax.stop_gradient(rep_s.astype(jnp.float32) + head_rep)
        else:
            h, stage_loss = stage_fn(sp, hp, x_in, c_in)
            head_loss = lax.cond(
                is_last,
                lambda: loss_head_fn(hp, h, c_in).astype(jnp.float32),
                lambda: jnp.sum(h).astype(jnp.float32) * 0.0)
            rep = jnp.zeros((0,), jnp.float32)
        loss = stage_loss.astype(jnp.float32) + head_loss
        return h, (loss, rep)

    f32 = functools.partial(tmap, lambda p: jnp.zeros(p.shape, jnp.float32))

    def pc(v):
        return _pcast_to(v, vma)

    carry0 = (
        pc(jnp.zeros(act_shape, x.dtype)),            # act in flight (down)
        pc(jnp.zeros(act_shape, jnp.float32)),        # ct in flight (up)
        pc(jnp.zeros((n,) + act_shape, x.dtype)),     # saved inputs ring
        tmap(pc, f32(stage_params)),
        tmap(pc, f32(head_params)),
        pc(jnp.zeros((M,) + act_shape, jnp.float32)),  # d_x per microbatch
        pc(jnp.float32(0.0)),                         # loss accumulator
        pc(jnp.zeros((report_len,), jnp.float32)),    # report accumulator
    )

    def ctx_at(mi):
        return tmap(lambda v: lax.dynamic_index_in_dim(v, mi, 0, False),
                    ctx_mb)

    def tick(carry, t):
        act_in, ct_in, saved, d_sp, d_hp, d_x, loss_acc, rep_acc = carry

        m_f = (t - stage) // 2
        fwd_work = ((t - stage) % 2 == 0) & (m_f >= 0) & (m_f < M)
        m_b = (t - (2 * n - 1 - stage)) // 2
        bwd_work = (((t - (2 * n - 1 - stage)) % 2 == 0)
                    & (m_b >= 0) & (m_b < M))

        # ---- forward unit (parity-s ticks) ----
        def do_fwd(op):
            act_in, saved, loss_acc, rep_acc = op
            mi = jnp.clip(m_f, 0, M - 1)
            x_in = jnp.where(stage == 0,
                             lax.dynamic_index_in_dim(x_mb, mi, 0, False),
                             act_in.astype(x.dtype))
            h, (loss, rep) = g(sp_v, hp_v, x_in, ctx_at(mi))
            saved = lax.dynamic_update_index_in_dim(
                saved, x_in, mi % n, 0)
            return h, saved, loss_acc + loss / M, rep_acc + rep

        def skip_fwd(op):
            act_in, saved, loss_acc, rep_acc = op
            return act_in.astype(x.dtype), saved, loss_acc, rep_acc

        act_out, saved, loss_acc, rep_acc = lax.cond(
            fwd_work, do_fwd, skip_fwd, (act_in, saved, loss_acc, rep_acc))

        # ---- backward unit (parity-(s+1) ticks) ----
        def do_bwd(op):
            ct_in, d_sp, d_hp, d_x = op
            mi = jnp.clip(m_b, 0, M - 1)
            x_in = lax.dynamic_index_in_dim(saved, mi % n, 0, False)
            _, pull = jax.vjp(g, sp_v, hp_v, x_in, ctx_at(mi))
            # seeds must carry g's full output vma type; the pcast here
            # feeds a cotangent INTO pull (it is never itself transposed,
            # so no psum materializes inside this divergent branch)
            ct_h = pc(jnp.where(is_last,
                                jnp.zeros(act_shape, jnp.float32),
                                ct_in).astype(x.dtype))
            # EVERY stage seeds its loss channel (its own per-stage
            # contribution differentiates locally; the head rides the
            # last stage's channel)
            ct_loss = pc(jnp.full((), 1.0 / M, jnp.float32))
            # report: no grad; the R=0 dummy channel is an invariant
            # empty array, so its seed must be too
            ct_rep = (pc(jnp.zeros((R,), jnp.float32)) if R
                      else jnp.zeros((0,), jnp.float32))
            g_sp, g_hp, g_x, _ = pull((ct_h, (ct_loss, ct_rep)))
            d_sp = tmap(lambda a, b: a + b.astype(jnp.float32), d_sp, g_sp)
            d_hp = tmap(lambda a, b: a + b.astype(jnp.float32), d_hp, g_hp)
            # d_x is meaningful on stage 0 only (its x_in came from x_mb,
            # not the ring); other stages contribute zeros
            d_x = lax.dynamic_update_index_in_dim(
                d_x, jnp.where(stage == 0, g_x.astype(jnp.float32), 0.0),
                mi, 0)
            return g_x.astype(jnp.float32), d_sp, d_hp, d_x

        def skip_bwd(op):
            ct_in, d_sp, d_hp, d_x = op
            return ct_in, d_sp, d_hp, d_x

        ct_out, d_sp, d_hp, d_x = lax.cond(
            bwd_work, do_bwd, skip_bwd, (ct_in, d_sp, d_hp, d_x))

        # both ring directions rotate every tick (collectives must stay
        # outside the conds: every stage participates every tick)
        act_next = lax.ppermute(act_out, pp_axis, fwd_perm)
        ct_next = lax.ppermute(ct_out, pp_axis, bwd_perm)
        return (act_next, ct_next, saved, d_sp, d_hp, d_x, loss_acc,
                rep_acc), None

    ticks = jnp.arange(2 * (M + n) - 2)     # last: stage-0 bwd of M-1
    (_, _, _, d_sp, d_hp, d_x, loss_acc, rep_acc), _ = lax.scan(
        tick, carry0, ticks)
    loss = lax.psum(loss_acc, pp_axis)      # per-stage contributions + head
    # transpose of the entry widening: psum each grad leaf over exactly
    # the axes it was widened over (head/replicated leaves got per-stage
    # partials; stage-sharded and dp-varying leaves stay per-shard)
    d_sp = unwiden_grads(d_sp, sp_axes)
    d_hp = unwiden_grads(d_hp, hp_axes)
    # d_x: stage-0 rows + zeros elsewhere; pp-psum selects stage 0's and
    # the recorded widening handles any other axes
    d_x = lax.psum(d_x, tuple(sorted(set(x_axes) | {pp_axis})))
    if report_len:
        report = lax.psum(rep_acc, pp_axis)
        return loss, d_sp, d_hp, d_x.reshape(x.shape), report
    return loss, d_sp, d_hp, d_x.reshape(x.shape)


def cost_model(num_microbatches: int, pp: int,
               schedule: str = "gpipe") -> dict:
    """Pipeline schedule cost report — the bubble/memory arithmetic users
    need to size num_microbatches.

    schedule="gpipe" (forward pass of `pipeline_apply`; this
    implementation computes on ring garbage during bubble ticks, so
    `bubble_fraction` IS the wasted-compute fraction):
      ticks            M + pp - 1 forward ticks
      bubble_ticks     pp - 1
      live_activations M per stage once differentiated (jax saves every
                       forward carry for the backward)

    schedule="1f1b" (`pipeline_train_1f1b`, fused fwd+bwd):
      ticks            2*(M + pp) - 2 work units (fwd and bwd counted 1)
      bubble_ticks     2*pp - 2 per stage
      live_activations <= pp per stage — the whole point: the in-flight
                       window is the ring depth, independent of M
    """
    if num_microbatches < 1 or pp < 1:
        raise ValueError((num_microbatches, pp))
    M = num_microbatches
    if schedule == "gpipe":
        ticks = M + pp - 1
        return {
            "schedule": "gpipe",
            "num_microbatches": M,
            "pp": pp,
            "ticks": ticks,
            "bubble_ticks": pp - 1,
            "bubble_fraction": (pp - 1) / ticks,
            "utilization": M / ticks,
            "live_activations_per_stage": M,
        }
    if schedule == "1f1b":
        ticks = 2 * (M + pp) - 2
        return {
            "schedule": "1f1b",
            "num_microbatches": M,
            "pp": pp,
            "ticks": ticks,
            "bubble_ticks": 2 * pp - 2,
            "bubble_fraction": (2 * pp - 2) / ticks,
            "utilization": 2 * M / ticks,
            "live_activations_per_stage": min(M, pp),
        }
    raise ValueError(f"unknown schedule {schedule!r}")


def from_last_stage(val: jax.Array, pp_axis: str) -> jax.Array:
    """psum-broadcast a value that is only valid on the last pp stage.
    Cheap for scalars (per-microbatch losses); use sparingly on big tensors."""
    n = lax.axis_size(pp_axis)
    is_last = (lax.axis_index(pp_axis) == n - 1).astype(val.dtype)
    return lax.psum(val * is_last, pp_axis)
