"""Pipeline parallelism (pp): GPipe microbatch schedule over a mesh axis.

The reference has no pipeline axis (its only strategy is DP over a ring of
FPGAs, SURVEY.md §2 "Parallelism strategies"), but its defining mechanism —
a static ring whose stages each own a slice of state and forward partial
results to the next hop (hw/all_reduce.sv st_eth_t, SEND_LOCAL/REDUCE/
FORWARD) — is exactly what a TPU pipeline stage does with activations.  We
reuse that shape: each device owns a contiguous slice of the layer stack,
processes one microbatch per tick, and `lax.ppermute`s its activation to the
next stage, keeping the ring full (1 bubble of pp-1 ticks per batch, the
GPipe schedule).

Everything is a single `lax.scan` inside `shard_map`, so XLA sees static
control flow; autodiff through ppermute gives the reverse-ring backward
schedule for free.

Layout contract:
- stage params: any pytree whose leaves are stacked [n_local_layers, ...]
  slices of the global [n_layers, ...] stack, sharded P(pp_axis, ...).
- activations: replicated over pp on entry; microbatching is temporal
  (B is split into num_microbatches chunks), so batch specs never mention pp.
- output: valid on the LAST stage; use `from_last_stage` (scalar-cheap psum
  mask) to make it pp-invariant.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax


def _pcast_to(x: jax.Array, vma) -> jax.Array:
    """Widen x's varying-manual-axes set to `vma` (scan carries must enter
    with the vma type their loop body produces)."""
    missing = tuple(sorted(set(vma) - set(jax.typeof(x).vma)))
    return lax.pcast(x, missing, to="varying") if missing else x


def _tree_vma(*trees) -> set:
    vma = set()
    for t in trees:
        for leaf in jax.tree_util.tree_leaves(t):
            vma |= set(jax.typeof(leaf).vma)
    return vma


def stack_layers(layers: List[Any]):
    """[{w: [..]}, ...] -> {w: [L, ..]}: stack a homogeneous list-of-pytrees
    along a new leading layer axis (shardable over pp)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layers(stacked) -> List[Any]:
    leaves = jax.tree_util.tree_leaves(stacked)
    n = leaves[0].shape[0]
    return [jax.tree_util.tree_map(lambda x: x[i], stacked)
            for i in range(n)]


def scan_layers(block_fn: Callable, stacked_params, x, *,
                remat: bool = False):
    """Apply block_fn(layer_params, x) -> x over a stacked [L, ...] slice."""
    def fn(lyr, h):
        return block_fn(lyr, h), jnp.float32(0.0)

    out, _ = scan_layers_aux(fn, stacked_params, x, remat=remat)
    return out


def scan_layers_aux(block_fn: Callable, stacked_params, x, *,
                    remat: bool = False):
    """Apply block_fn(layer_params, x) -> (x, aux) over a stacked [L, ...]
    slice, summing the per-layer aux scalars (MoE load-balance loss)."""
    fn = jax.checkpoint(block_fn) if remat else block_fn

    # carry must enter varying over every axis the block output varies over
    # (block_fn is assumed vma-monotone, e.g. residual-style)
    vma = _tree_vma(x, stacked_params)

    def body(carry, lyr):
        h, acc = carry
        h, aux = fn(lyr, h)
        return (h, acc + aux.astype(jnp.float32)), None

    (out, aux), _ = lax.scan(
        body, (_pcast_to(x, vma), _pcast_to(jnp.float32(0.0), vma)),
        stacked_params)
    return out, aux


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array,
                   num_microbatches: int, pp_axis: str) -> jax.Array:
    """`pipeline_apply_aux` for aux-free stage_fn(stage_params, mb) -> mb."""
    out, _ = pipeline_apply_aux(
        lambda p, mb: (stage_fn(p, mb), jnp.float32(0.0)),
        stage_params, x, num_microbatches, pp_axis)
    return out


def pipeline_apply_aux(stage_fn: Callable, stage_params, x: jax.Array,
                       num_microbatches: int, pp_axis: str):
    """Run x through the full pipeline; call inside shard_map.

    stage_fn(stage_params, mb) -> (mb, aux) applies this device's layer
    slice to one microbatch, returning an auxiliary scalar (MoE
    load-balance loss; 0.0 for dense stacks).  x: [B, ...] replicated over
    pp, B % num_microbatches == 0.  Returns (out [B, ...], aux scalar) —
    out valid ONLY on the last stage (mask with `from_last_stage`); aux is
    already pp-invariant (psum over stages) and averaged over microbatches,
    matching the unpipelined path's one-full-batch aux up to the
    per-microbatch routing granularity.

    Schedule (per tick t of num_microbatches + pp - 1):
      stage 0 injects microbatch t; every stage applies its slice; the
      result rotates one hop down the ring (ppermute), exactly the
      reference's SEND_LOCAL -> REDUCE -> FORWARD slice rotation
      (hw/all_reduce.sv:891-1086) with layers in place of partial sums.
    Ticks where a stage holds no real microbatch compute on ring garbage;
    those results land in output slots that a later tick overwrites, and
    their aux contributions are masked out (stage s holds real microbatch
    t - s only when 0 <= t - s < num_microbatches).
    """
    n = lax.axis_size(pp_axis)
    stage = lax.axis_index(pp_axis)
    B = x.shape[0]
    assert B % num_microbatches == 0, (B, num_microbatches)
    mb = B // num_microbatches
    x_mb = x.reshape((num_microbatches, mb) + x.shape[1:])
    perm = [(i, (i + 1) % n) for i in range(n)]

    # scan carries enter with the vma type the tick body produces: varying
    # over pp (stage index / ppermute) plus everything x or the params carry
    vma = _tree_vma(x, stage_params) | {pp_axis}
    state = _pcast_to(jnp.zeros_like(x_mb[0]), vma)
    outputs = _pcast_to(jnp.zeros_like(x_mb), vma)
    aux0 = _pcast_to(jnp.float32(0.0), vma)

    def tick(carry, t):
        state, outputs, aux_acc = carry
        inject = lax.dynamic_index_in_dim(x_mb, t % num_microbatches, 0,
                                          keepdims=False)
        cur = jnp.where(stage == 0, inject, state)
        out, aux = stage_fn(stage_params, cur)
        real = ((t >= stage) & (t - stage < num_microbatches))
        aux_acc = aux_acc + jnp.where(real, aux.astype(jnp.float32), 0.0)
        # Last stage finished microbatch t-(n-1); earlier ticks write garbage
        # at wrapped indices that tick t+num_microbatches overwrites.
        outputs = lax.dynamic_update_index_in_dim(
            outputs, out, (t - (n - 1)) % num_microbatches, 0)
        state = lax.ppermute(out, pp_axis, perm)
        return (state, outputs, aux_acc), None

    ticks = jnp.arange(num_microbatches + n - 1)
    (_, outputs, aux_acc), _ = lax.scan(tick, (state, outputs, aux0), ticks)
    aux = lax.psum(aux_acc, pp_axis) / num_microbatches
    return outputs.reshape(x.shape), aux


def cost_model(num_microbatches: int, pp: int) -> dict:
    """GPipe schedule cost report — the bubble arithmetic users need to
    size num_microbatches (this implementation computes on ring garbage
    during bubble ticks, so `bubble_fraction` IS the wasted-compute
    fraction, not just idle time).

    ticks            total schedule ticks (M + pp - 1)
    bubble_ticks     ticks any given stage spends on garbage (pp - 1)
    bubble_fraction  wasted fraction of stage compute
    utilization      1 - bubble_fraction
    """
    if num_microbatches < 1 or pp < 1:
        raise ValueError((num_microbatches, pp))
    ticks = num_microbatches + pp - 1
    return {
        "num_microbatches": num_microbatches,
        "pp": pp,
        "ticks": ticks,
        "bubble_ticks": pp - 1,
        "bubble_fraction": (pp - 1) / ticks,
        "utilization": num_microbatches / ticks,
    }


def from_last_stage(val: jax.Array, pp_axis: str) -> jax.Array:
    """psum-broadcast a value that is only valid on the last pp stage.
    Cheap for scalars (per-microbatch losses); use sparingly on big tensors."""
    n = lax.axis_size(pp_axis)
    is_last = (lax.axis_index(pp_axis) == n - 1).astype(val.dtype)
    return lax.psum(val * is_last, pp_axis)
