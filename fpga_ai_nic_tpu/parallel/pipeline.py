"""Pipeline parallelism (pp): GPipe microbatch schedule over a mesh axis.

The reference has no pipeline axis (its only strategy is DP over a ring of
FPGAs, SURVEY.md §2 "Parallelism strategies"), but its defining mechanism —
a static ring whose stages each own a slice of state and forward partial
results to the next hop (hw/all_reduce.sv st_eth_t, SEND_LOCAL/REDUCE/
FORWARD) — is exactly what a TPU pipeline stage does with activations.  We
reuse that shape: each device owns a contiguous slice of the layer stack,
processes one microbatch per tick, and `lax.ppermute`s its activation to the
next stage, keeping the ring full (1 bubble of pp-1 ticks per batch, the
GPipe schedule).

Everything is a single `lax.scan` inside `shard_map`, so XLA sees static
control flow; autodiff through ppermute gives the reverse-ring backward
schedule for free.

Layout contract:
- stage params: any pytree whose leaves are stacked [n_local_layers, ...]
  slices of the global [n_layers, ...] stack, sharded P(pp_axis, ...).
- activations: replicated over pp on entry; microbatching is temporal
  (B is split into num_microbatches chunks), so batch specs never mention pp.
- output: valid on the LAST stage; use `from_last_stage` (scalar-cheap psum
  mask) to make it pp-invariant.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .. import compat


def _pcast_to(x: jax.Array, vma) -> jax.Array:
    """Widen x's varying-manual-axes set to `vma` (scan carries must enter
    with the vma type their loop body produces)."""
    missing = tuple(sorted(set(vma) - set(jax.typeof(x).vma)))
    return lax.pcast(x, missing, to="varying") if missing else x


def _tree_vma(*trees) -> set:
    vma = set()
    for t in trees:
        for leaf in jax.tree_util.tree_leaves(t):
            vma |= set(jax.typeof(leaf).vma)
    return vma


def stack_layers(layers: List[Any]):
    """[{w: [..]}, ...] -> {w: [L, ..]}: stack a homogeneous list-of-pytrees
    along a new leading layer axis (shardable over pp)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layers(stacked) -> List[Any]:
    leaves = jax.tree_util.tree_leaves(stacked)
    n = leaves[0].shape[0]
    return [jax.tree_util.tree_map(lambda x: x[i], stacked)
            for i in range(n)]


def scan_layers(block_fn: Callable, stacked_params, x, *,
                remat: bool = False):
    """Apply block_fn(layer_params, x) -> x over a stacked [L, ...] slice."""
    def fn(lyr, h):
        return block_fn(lyr, h), jnp.float32(0.0)

    out, _ = scan_layers_aux(fn, stacked_params, x, remat=remat)
    return out


def scan_layers_aux(block_fn: Callable, stacked_params, x, *,
                    remat: bool = False):
    """Apply block_fn(layer_params, x) -> (x, aux) over a stacked [L, ...]
    slice, summing the per-layer aux scalars (MoE load-balance loss)."""
    fn = jax.checkpoint(block_fn) if remat else block_fn

    # carry must enter varying over every axis the block output varies over
    # (block_fn is assumed vma-monotone, e.g. residual-style)
    vma = _tree_vma(x, stacked_params)

    def body(carry, lyr):
        h, acc = carry
        h, aux = fn(lyr, h)
        return (h, acc + aux.astype(jnp.float32)), None

    (out, aux), _ = lax.scan(
        body, (_pcast_to(x, vma), _pcast_to(jnp.float32(0.0), vma)),
        stacked_params)
    return out, aux


def pipeline_apply(stage_fn: Callable, stage_params, x: jax.Array,
                   num_microbatches: int, pp_axis: str) -> jax.Array:
    """`pipeline_apply_aux` for aux-free stage_fn(stage_params, mb) -> mb."""
    out, _ = pipeline_apply_aux(
        lambda p, mb: (stage_fn(p, mb), jnp.float32(0.0)),
        stage_params, x, num_microbatches, pp_axis)
    return out


def pipeline_apply_aux(stage_fn: Callable, stage_params, x: jax.Array,
                       num_microbatches: int, pp_axis: str):
    """Run x through the full pipeline; call inside shard_map.

    stage_fn(stage_params, mb) -> (mb, aux) applies this device's layer
    slice to one microbatch, returning an auxiliary scalar (MoE
    load-balance loss; 0.0 for dense stacks).  x: [B, ...] replicated over
    pp, B % num_microbatches == 0.  Returns (out [B, ...], aux scalar) —
    out valid ONLY on the last stage (mask with `from_last_stage`); aux is
    already pp-invariant (psum over stages) and averaged over microbatches,
    matching the unpipelined path's one-full-batch aux up to the
    per-microbatch routing granularity.

    Schedule (per tick t of num_microbatches + pp - 1):
      stage 0 injects microbatch t; every stage applies its slice; the
      result rotates one hop down the ring (ppermute), exactly the
      reference's SEND_LOCAL -> REDUCE -> FORWARD slice rotation
      (hw/all_reduce.sv:891-1086) with layers in place of partial sums.
    Ticks where a stage holds no real microbatch compute on ring garbage;
    those results land in output slots that a later tick overwrites, and
    their aux contributions are masked out (stage s holds real microbatch
    t - s only when 0 <= t - s < num_microbatches).
    """
    n = lax.axis_size(pp_axis)
    stage = lax.axis_index(pp_axis)
    B = x.shape[0]
    assert B % num_microbatches == 0, (B, num_microbatches)
    mb = B // num_microbatches
    x_mb = x.reshape((num_microbatches, mb) + x.shape[1:])
    perm = [(i, (i + 1) % n) for i in range(n)]

    # scan carries enter with the vma type the tick body produces: varying
    # over pp (stage index / ppermute) plus everything x or the params carry
    vma = _tree_vma(x, stage_params) | {pp_axis}
    state = _pcast_to(jnp.zeros_like(x_mb[0]), vma)
    outputs = _pcast_to(jnp.zeros_like(x_mb), vma)
    aux0 = _pcast_to(jnp.float32(0.0), vma)

    def tick(carry, t):
        state, outputs, aux_acc = carry
        inject = lax.dynamic_index_in_dim(x_mb, t % num_microbatches, 0,
                                          keepdims=False)
        cur = jnp.where(stage == 0, inject, state)
        out, aux = stage_fn(stage_params, cur)
        real = ((t >= stage) & (t - stage < num_microbatches))
        aux_acc = aux_acc + jnp.where(real, aux.astype(jnp.float32), 0.0)
        # Last stage finished microbatch t-(n-1); earlier ticks write garbage
        # at wrapped indices that tick t+num_microbatches overwrites.
        outputs = lax.dynamic_update_index_in_dim(
            outputs, out, (t - (n - 1)) % num_microbatches, 0)
        state = lax.ppermute(out, pp_axis, perm)
        return (state, outputs, aux_acc), None

    ticks = jnp.arange(num_microbatches + n - 1)
    (_, outputs, aux_acc), _ = lax.scan(tick, (state, outputs, aux0), ticks)
    aux = lax.psum(aux_acc, pp_axis) / num_microbatches
    return outputs.reshape(x.shape), aux


def _widen(tree, vma, polyfill_vma=()):
    """Widen every leaf to the full varying set, RECORDING the widened
    axes per leaf — the 1F1B schedulers' entry pcast whose manual
    transpose is the exit psum in ``_unwiden_grads`` (the reason is
    documented in pipeline_train_1f1b: a vjp-inserted psum inside a
    stage-divergent cond deadlocks the mesh).

    ``polyfill_vma``: the tree's CONTRACT varying axes, used when the
    jaxlib has no vma typing (compat.HAS_VMA False: ``jax.typeof`` is
    polyfilled to an EMPTY vma for every leaf).  Without it the recorded
    widened axes claimed every leaf was invariant, and the exit transpose
    psum'd STAGE-SHARDED gradients across the pp ring — elementwise
    summing different layers' gradients, the collective-transpose /
    gradient-scale class of docs/KNOWN_FAILURES.md #5-16 (frozen as
    graftlint rule J7).  On vma-typed jaxlibs the leaf types carry the
    exact answer (including extra axes like dp) and the contract default
    is ignored."""
    tmap = jax.tree_util.tree_map

    def leaf_vma(v):
        return (set(jax.typeof(v).vma) if compat.HAS_VMA
                else set(polyfill_vma))

    axes = tmap(lambda v: tuple(sorted(set(vma) - leaf_vma(v))), tree)
    return tmap(lambda v: _pcast_to(v, vma), tree), axes


def _unwiden_grads(grads, axes):
    """Transpose of ``_widen``: psum each gradient leaf over exactly the
    axes it was widened over on entry."""
    return jax.tree_util.tree_map(
        lambda d, a: lax.psum(d, a) if a else d, grads, axes)


def _unit_fn(stage_fn, loss_head_fn, R: int):
    """The per-unit primal shared by both 1F1B schedulers: stage slice
    (+ its own loss contribution), then the loss head when `is_last`
    says this unit produces the final activations (the v=1 scheduler
    passes its stage==pp-1 flag; the interleaved one its per-tick
    virtual-stage-P-1 table flag).  The false branch derives its
    (varying) type from h with a zero-gradient sum, NOT a pcast — a
    pcast's transpose is a psum, which must not exist inside the
    schedulers' divergent conds.  The report channel rides along
    stop-gradiented (display only, never differentiated)."""
    def g(sp, hp, x_in, c_in, is_last):
        if R:
            h, stage_loss, rep_s = stage_fn(sp, hp, x_in, c_in)
            head_loss, head_rep = lax.cond(
                is_last,
                lambda: [o.astype(jnp.float32) for o in
                         loss_head_fn(hp, h, c_in)],
                lambda: [jnp.sum(h).astype(jnp.float32) * 0.0,
                         jnp.zeros((R,), jnp.float32)
                         + jnp.sum(h).astype(jnp.float32) * 0.0])
            rep = lax.stop_gradient(rep_s.astype(jnp.float32) + head_rep)
        else:
            h, stage_loss = stage_fn(sp, hp, x_in, c_in)
            head_loss = lax.cond(
                is_last,
                lambda: loss_head_fn(hp, h, c_in).astype(jnp.float32),
                lambda: jnp.sum(h).astype(jnp.float32) * 0.0)
            rep = jnp.zeros((0,), jnp.float32)
        return h, (stage_loss.astype(jnp.float32) + head_loss, rep)
    return g


def pipeline_train_1f1b(stage_fn: Callable, loss_head_fn: Callable,
                        stage_params, head_params, x: jax.Array,
                        ctx, num_microbatches: int,
                        pp_axis: str, report_len: int = 0):
    """One fused forward+backward pass under the 1F1B schedule — explicit
    per-tick scheduling of forwards, backwards, and both ring directions,
    returning gradients directly (no outer jax.grad).

    Why it exists: differentiating ``pipeline_apply`` (GPipe) makes jax
    save the forward scan's carries — O(num_microbatches) live
    activations per stage.  1F1B caps the in-flight window at the ring
    depth: stage s never holds more than pp - s microbatch activations,
    so the buffer here is a static [pp, ...] ring regardless of
    num_microbatches (the standard perf-grade schedule for deep stacks
    at large microbatch counts; beyond-reference — the reference has no
    pipeline axis at all).

    Schedule (derived; all stages lockstep, one work unit per tick):
      fwd of microbatch m at stage s:  tick  s + 2m
      bwd of microbatch m at stage s:  tick  2*pp - 1 - s + 2m
    Forward ticks have parity s, backward ticks parity s + 1 — each
    stage strictly alternates F,B,F,B with no same-tick collision, the
    activation arrives exactly one tick after the upstream forward, and
    the cotangent one tick after the downstream backward.  Total ticks
    2*(M + pp) - 3 vs GPipe's 2*(M + pp - 1) forward+backward units —
    same bubble, O(pp) memory.

    Backward recompute: at a backward tick the stage re-runs its forward
    under jax.vjp from the SAVED INPUT activation (stage-granular
    rematerialization, like GPipe-with-remat) — the ring buffer then
    stores one known-shape activation per in-flight microbatch instead
    of arbitrary vjp residuals.

    Contracts (call inside shard_map):
      stage_fn(stage_params, head_params, x_in, ctx_mb)
          -> (x_out, stage_loss)
        this stage's layer slice on one microbatch plus the stage's OWN
        per-microbatch scalar loss contribution (MoE load-balance aux —
        every stage's loss channel is seeded in its backward, not just
        the last).  stage_loss must carry the same varying type as
        x_out; plain stacks return the zero-gradient
        ``jnp.sum(x_out) * 0.0``, NOT an invariant literal (mixing an
        invariant scalar into the varying loss channel inserts a pvary
        whose transpose is a psum inside the divergent cond).  head_params carries
        replicated leaves stages may need, e.g. stage 0's embedding —
        gate stage-specific work on lax.axis_index(pp_axis), keeping any
        collectives over OTHER axes, never over pp_axis.
      loss_head_fn(head_params, x_out, ctx_mb) -> scalar per-microbatch
        loss (applied on the LAST stage only, ADDED to that stage's
        contribution)
      x:   [B, ...] initial activations, replicated over pp, B % M == 0
      ctx: pytree of [B, ...] arrays (tokens/labels/masks), microbatched
        alongside x and handed to every stage + the head

    report_len > 0 switches both callables to a three-output contract —
    stage_fn -> (x_out, stage_loss, report [report_len]) and
    loss_head_fn -> (loss, report [report_len]) — where `report` is a
    NON-differentiated f32 vector accumulated across stages and
    microbatches (summed, psum'd over pp, NOT divided by M) and returned
    as a fifth output.  This is the display channel: a wrapper can fold
    per-term gradient scales into the differentiated loss channel while
    reconstructing exact unscaled values (e.g. raw token-NLL sum and raw
    MoE aux) from the report.

    Returns (loss, d_stage_params, d_head_params, d_x[, report]):
      loss   microbatch-mean of the summed per-stage contributions +
             head losses (pp-invariant: psum over stages — identical to
             the last stage's value for plain stacks)
      d_*    gradient trees matching the params; each leaf is psum'd over
             EXACTLY the axes it was widened over on entry (an
             already-varying leaf — dp-varying grads for a manual dp
             reduce-scatter, tp-sharded weights — keeps its per-shard
             cotangent, so this composes with any outer mesh)
      d_x    [B, ...] cotangent of the initial activations (for an
             embedding vjp outside), invariantized the same way
    The per-stage loss channel + report channel carry MoE: every
    stage's load-balance aux differentiates locally with its gradient
    scale folded into the objective, and the raw values ride the report
    for exact display (llama.loss_and_grads_pp_1f1b).
    """
    n = lax.axis_size(pp_axis)
    stage = lax.axis_index(pp_axis)
    M = num_microbatches
    B = x.shape[0]
    assert B % M == 0, (B, M)
    mb = B // M
    tmap = jax.tree_util.tree_map

    def to_mb(v):
        return v.reshape((M, mb) + v.shape[1:])

    x_mb = to_mb(x)
    ctx_mb = tmap(to_mb, ctx)
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]
    is_last = stage == n - 1
    act_shape = (mb,) + x.shape[1:]
    vma = _tree_vma(x, ctx, stage_params, head_params) | {pp_axis}

    # Widen EVERY input to the full varying set BEFORE the schedule runs,
    # RECORDING the widened axes per leaf.  The scheduling conds are
    # stage-divergent, and jax.vjp transposes an invariant-used-in-
    # varying-math widening into a psum — a collective inside a divergent
    # branch deadlocks the whole mesh (observed as an XLA rendezvous
    # abort: 3 devices in collective-permute, 1 in all-reduce).  With all
    # inputs varying, every vjp inside the conds is collective-free;
    # invariantization happens exactly once after the scan — each
    # gradient leaf psum'd over precisely its recorded widened axes (the
    # manual transpose of the entry pcast).
    # contract vma defaults (polyfill jaxlibs — see _widen): stage params
    # are pp-sharded, head params and x replicated over pp
    sp_v, sp_axes = _widen(stage_params, vma, polyfill_vma=(pp_axis,))
    hp_v, hp_axes = _widen(head_params, vma)
    x_axes = tuple(sorted(set(vma) - (set(jax.typeof(x).vma)
                                      if compat.HAS_VMA else set())))
    x_mb = _pcast_to(x_mb, vma)
    ctx_mb = tmap(lambda v: _pcast_to(v, vma), ctx_mb)

    R = report_len

    g5 = _unit_fn(stage_fn, loss_head_fn, R)

    def g(sp, hp, x_in, c_in):
        return g5(sp, hp, x_in, c_in, is_last)

    f32 = functools.partial(tmap, lambda p: jnp.zeros(p.shape, jnp.float32))

    def pc(v):
        return _pcast_to(v, vma)

    carry0 = (
        pc(jnp.zeros(act_shape, x.dtype)),            # act in flight (down)
        pc(jnp.zeros(act_shape, jnp.float32)),        # ct in flight (up)
        pc(jnp.zeros((n,) + act_shape, x.dtype)),     # saved inputs ring
        tmap(pc, f32(stage_params)),
        tmap(pc, f32(head_params)),
        pc(jnp.zeros((M,) + act_shape, jnp.float32)),  # d_x per microbatch
        pc(jnp.float32(0.0)),                         # loss accumulator
        pc(jnp.zeros((report_len,), jnp.float32)),    # report accumulator
    )

    def ctx_at(mi):
        return tmap(lambda v: lax.dynamic_index_in_dim(v, mi, 0, False),
                    ctx_mb)

    def tick(carry, t):
        act_in, ct_in, saved, d_sp, d_hp, d_x, loss_acc, rep_acc = carry

        m_f = (t - stage) // 2
        fwd_work = ((t - stage) % 2 == 0) & (m_f >= 0) & (m_f < M)
        m_b = (t - (2 * n - 1 - stage)) // 2
        bwd_work = (((t - (2 * n - 1 - stage)) % 2 == 0)
                    & (m_b >= 0) & (m_b < M))

        # ---- forward unit (parity-s ticks) ----
        def do_fwd(op):
            act_in, saved, loss_acc, rep_acc = op
            mi = jnp.clip(m_f, 0, M - 1)
            x_in = jnp.where(stage == 0,
                             lax.dynamic_index_in_dim(x_mb, mi, 0, False),
                             act_in.astype(x.dtype))
            h, (loss, rep) = g(sp_v, hp_v, x_in, ctx_at(mi))
            saved = lax.dynamic_update_index_in_dim(
                saved, x_in, mi % n, 0)
            return h, saved, loss_acc + loss / M, rep_acc + rep

        def skip_fwd(op):
            act_in, saved, loss_acc, rep_acc = op
            return act_in.astype(x.dtype), saved, loss_acc, rep_acc

        act_out, saved, loss_acc, rep_acc = lax.cond(
            fwd_work, do_fwd, skip_fwd, (act_in, saved, loss_acc, rep_acc))

        # ---- backward unit (parity-(s+1) ticks) ----
        def do_bwd(op):
            ct_in, d_sp, d_hp, d_x = op
            mi = jnp.clip(m_b, 0, M - 1)
            x_in = lax.dynamic_index_in_dim(saved, mi % n, 0, False)
            _, pull = jax.vjp(g, sp_v, hp_v, x_in, ctx_at(mi))
            # seeds must carry g's full output vma type; the pcast here
            # feeds a cotangent INTO pull (it is never itself transposed,
            # so no psum materializes inside this divergent branch)
            ct_h = pc(jnp.where(is_last,
                                jnp.zeros(act_shape, jnp.float32),
                                ct_in).astype(x.dtype))
            # EVERY stage seeds its loss channel (its own per-stage
            # contribution differentiates locally; the head rides the
            # last stage's channel)
            ct_loss = pc(jnp.full((), 1.0 / M, jnp.float32))
            # report: no grad; the R=0 dummy channel is an invariant
            # empty array, so its seed must be too
            ct_rep = (pc(jnp.zeros((R,), jnp.float32)) if R
                      else jnp.zeros((0,), jnp.float32))
            g_sp, g_hp, g_x, _ = pull((ct_h, (ct_loss, ct_rep)))
            d_sp = tmap(lambda a, b: a + b.astype(jnp.float32), d_sp, g_sp)
            d_hp = tmap(lambda a, b: a + b.astype(jnp.float32), d_hp, g_hp)
            # d_x is meaningful on stage 0 only (its x_in came from x_mb,
            # not the ring); other stages contribute zeros
            d_x = lax.dynamic_update_index_in_dim(
                d_x, jnp.where(stage == 0, g_x.astype(jnp.float32), 0.0),
                mi, 0)
            return g_x.astype(jnp.float32), d_sp, d_hp, d_x

        def skip_bwd(op):
            ct_in, d_sp, d_hp, d_x = op
            return ct_in, d_sp, d_hp, d_x

        ct_out, d_sp, d_hp, d_x = lax.cond(
            bwd_work, do_bwd, skip_bwd, (ct_in, d_sp, d_hp, d_x))

        # both ring directions rotate every tick (collectives must stay
        # outside the conds: every stage participates every tick)
        act_next = lax.ppermute(act_out, pp_axis, fwd_perm)
        ct_next = lax.ppermute(ct_out, pp_axis, bwd_perm)
        return (act_next, ct_next, saved, d_sp, d_hp, d_x, loss_acc,
                rep_acc), None

    ticks = jnp.arange(2 * (M + n) - 2)     # last: stage-0 bwd of M-1
    (_, _, _, d_sp, d_hp, d_x, loss_acc, rep_acc), _ = lax.scan(
        tick, carry0, ticks)
    loss = lax.psum(loss_acc, pp_axis)      # per-stage contributions + head
    # transpose of the entry widening: psum each grad leaf over exactly
    # the axes it was widened over (head/replicated leaves got per-stage
    # partials; stage-sharded and dp-varying leaves stay per-shard)
    d_sp = _unwiden_grads(d_sp, sp_axes)
    d_hp = _unwiden_grads(d_hp, hp_axes)
    # d_x: stage-0 rows + zeros elsewhere; pp-psum selects stage 0's and
    # the recorded widening handles any other axes
    d_x = lax.psum(d_x, tuple(sorted(set(x_axes) | {pp_axis})))
    if report_len:
        report = lax.psum(rep_acc, pp_axis)
        return loss, d_sp, d_hp, d_x.reshape(x.shape), report
    return loss, d_sp, d_hp, d_x.reshape(x.shape)


def cost_model(num_microbatches: int, pp: int,
               schedule: str = "gpipe", virtual_stages: int = 1) -> dict:
    """Pipeline schedule cost report — the bubble/memory arithmetic users
    need to size num_microbatches.

    schedule="gpipe" (forward pass of `pipeline_apply`; this
    implementation computes on ring garbage during bubble ticks, so
    `bubble_fraction` IS the wasted-compute fraction):
      ticks            M + pp - 1 forward ticks
      bubble_ticks     pp - 1
      live_activations M per stage once differentiated (jax saves every
                       forward carry for the backward)

    schedule="1f1b" (`pipeline_train_1f1b`, fused fwd+bwd):
      ticks            2*(M + pp) - 2 work units (fwd and bwd counted 1)
      bubble_ticks     2*pp - 2 per stage
      live_activations <= pp per stage — the whole point: the in-flight
                       window is the ring depth, independent of M

    Design note — zero-bubble (ZB-H1) schedules: splitting the backward
    into input-grad (B) and weight-grad (W) units lets W units fill
    bubble ticks.  Considered and NOT implemented here: this module's
    lockstep execution model (every device, one unit per tick, two
    ppermutes per tick) synchronizes each tick on the SLOWEST unit, and
    F/B/W have unequal costs (~1x/2x/1x of a forward), so the bubble
    ticks ZB reclaims are largely returned as per-tick stalls.  Getting
    ZB's real win needs per-edge asynchronous p2p sends, which the
    shard_map + ppermute paradigm deliberately does not use (static
    lockstep is what makes the schedules verifiable at trace time).
    """
    if num_microbatches < 1 or pp < 1:
        raise ValueError((num_microbatches, pp))
    M = num_microbatches
    if schedule == "gpipe":
        ticks = M + pp - 1
        return {
            "schedule": "gpipe",
            "num_microbatches": M,
            "pp": pp,
            "ticks": ticks,
            "bubble_ticks": pp - 1,
            "bubble_fraction": (pp - 1) / ticks,
            "utilization": M / ticks,
            "live_activations_per_stage": M,
        }
    if schedule == "1f1b":
        ticks = 2 * (M + pp) - 2
        return {
            "schedule": "1f1b",
            "num_microbatches": M,
            "pp": pp,
            "ticks": ticks,
            "bubble_ticks": 2 * pp - 2,
            "bubble_fraction": (2 * pp - 2) / ticks,
            "utilization": 2 * M / ticks,
            "live_activations_per_stage": min(M, pp),
        }
    if schedule == "1f1b-interleaved":
        # measured from the verified static schedule, not a formula —
        # each tick is 1/v of a full stage, so compare bubble in
        # FULL-STAGE units against plain 1f1b
        v = virtual_stages
        t = _interleaved_tables(pp, v, M)
        ticks = t["T"]
        ideal = 2 * v * M
        return {
            "schedule": "1f1b-interleaved",
            "num_microbatches": M,
            "pp": pp,
            "virtual_stages": v,
            "ticks": ticks,
            "bubble_ticks": ticks - ideal,
            "bubble_fraction": (ticks - ideal) / ticks,
            "bubble_full_stage_units": (ticks - ideal) / v,
            "utilization": ideal / ticks,
            "live_activations_per_stage": t["n_aslots"],
        }
    raise ValueError(f"unknown schedule {schedule!r}")


def from_last_stage(val: jax.Array, pp_axis: str) -> jax.Array:
    """psum-broadcast a value that is only valid on the last pp stage.
    Cheap for scalars (per-microbatch losses); use sparingly on big tensors.

    The psum sits on the gradient path, so differentiating through this
    inherits the jaxlib's psum-transpose convention.  That is the correct
    pairing when the grad is taken OUTSIDE shard_map (the polyfill
    boundary hands each replica ct/n for a replicated output, and the
    psum transpose restores the factor); losses differentiated INSIDE
    shard_map must use ``from_last_stage_local_grad`` instead — with the
    psum on their gradient path, this container's psum-as-transpose
    scaled every pipeline gradient by n_pp (docs/KNOWN_FAILURES.md #5-16
    family, frozen as graftlint rule J7)."""
    n = lax.axis_size(pp_axis)
    is_last = (lax.axis_index(pp_axis) == n - 1).astype(val.dtype)
    return lax.psum(val * is_last, pp_axis)


def from_last_stage_local_grad(val: jax.Array, pp_axis: str) -> jax.Array:
    """``from_last_stage`` for losses differentiated INSIDE shard_map: the
    psum carries the VALUE only, the gradient path rides the local masked
    value — so the cotangent reaching ``val`` is exactly ct * is_last on
    every jaxlib, independent of its psum-transpose convention (the J7
    gradient-scale class).  Per-stage gradients of pp-replicated leaves
    then come out as clean per-stage PARTIALS; the trainer supplies the
    cross-stage psum (ShardedTrainer's manual pvary-transpose stand-in on
    polyfill jaxlibs; vma autodiff inserts it on typed ones)."""
    n = lax.axis_size(pp_axis)
    is_last = (lax.axis_index(pp_axis) == n - 1).astype(val.dtype)
    masked = val * is_last
    return lax.stop_gradient(lax.psum(masked, pp_axis)) + (
        masked - lax.stop_gradient(masked))


# -- interleaved (virtual-stage) 1F1B ----------------------------------------


def _alloc_slots(intervals):
    """Greedy interval-graph coloring: intervals = [(start, end, key)]
    inclusive; returns ({key: slot}, n_slots).  Used to map each in-flight
    activation/cotangent to a static buffer slot with disjoint lifetimes."""
    import heapq
    assign, free, n = {}, [], 0
    for start, end, key in sorted(intervals):
        # pop every slot freed strictly before `start`, reuse the lowest
        ready = []
        while free and free[0][0] < start:
            ready.append(heapq.heappop(free)[1])
        if ready:
            slot = min(ready)
            for r in ready:
                if r != slot:
                    heapq.heappush(free, (start - 1, r))
        else:
            slot = n
            n += 1
        assign[key] = slot
        heapq.heappush(free, (end, slot))
    # verify disjointness per slot — allocation is load-bearing for the
    # scheduler's correctness, so check, don't trust
    by_slot = {}
    for start, end, key in intervals:
        by_slot.setdefault(assign[key], []).append((start, end))
    for sl, ivs in by_slot.items():
        ivs.sort()
        for (s1, e1), (s2, e2) in zip(ivs, ivs[1:]):
            assert e1 < s2, ("slot lifetime overlap", sl, (s1, e1), (s2, e2))
    return assign, n


def _interleaved_tables(pp: int, v: int, M: int):
    """Static lockstep schedule for interleaved 1F1B (Megatron order).

    Virtual stage u in [0, v*pp) holds layer chunk u of the model; device
    of u is u % pp, so EVERY virtual hop u -> u+1 is the uniform ring
    step s -> s+1 (including chunk transitions pp-1 -> 0) and the two
    ppermute rings of the non-interleaved scheduler carry the traffic
    unchanged.  Per device the unit ORDER is Megatron's: W(s) warmup
    forwards (W = 2*(pp-s-1) + (v-1)*pp, capped), then strict 1F1B
    alternation, then cooldown backwards; chunk index cycles every pp
    consecutive microbatch slots.  Ticks are assigned by earliest-feasible
    list scheduling under the ring dependencies (fwd(m,u) strictly after
    fwd(m,u-1); bwd(m,u) strictly after bwd(m,u+1); bwd(m,P-1) strictly
    after fwd(m,P-1)) and one-unit-per-device-per-tick; the result is
    VERIFIED here (every unit scheduled once, strict orderings, slot
    lifetimes disjoint), not trusted.

    Phase changes (1-spaced warmup vs 2-spaced steady state) mean an
    arriving activation is not always consumed on its arrival tick, so
    unlike the closed-form v=1 scheduler, arrivals land in statically
    allocated SLOTS: one act buffer doubles as arrival buffer and saved
    input (lifetime: arrival -> that unit's backward), one ct buffer for
    in-flight cotangents.  Returns numpy tables [T, pp] driving the scan:
    KIND (0 idle / 1 fwd / 2 bwd), MB, CH, ASLOT (the unit's act slot),
    CTSLOT (bwd cotangent slot; -1 = loss-head seed), ISU0 (input from
    x_mb), ISHEAD (unit is virtual stage P-1), RA / RC (slot to store the
    act / ct arriving this tick; -1 none), plus (T, n_aslots, n_cslots).
    """
    import numpy as np
    P = v * pp
    if M % pp:
        raise ValueError(
            f"interleaved 1F1B needs num_microbatches {M} % pp {pp} == 0 "
            f"(the chunk rotation covers pp microbatches per segment)")
    vM = v * M

    def chunk_of(vmid, fwd):
        c = (vmid % (v * pp)) // pp
        return c if fwd else v - 1 - c

    def mb_of(vmid):
        return (vmid // (v * pp)) * pp + vmid % pp

    orders = []
    for s in range(pp):
        W = min(pp - s - 1 if v == 1
                else 2 * (pp - s - 1) + (v - 1) * pp, vM)
        seq, fi, bi = [], 0, 0
        for _ in range(W):
            seq.append(("F", mb_of(fi), chunk_of(fi, True))); fi += 1
        while fi < vM:
            seq.append(("F", mb_of(fi), chunk_of(fi, True))); fi += 1
            seq.append(("B", mb_of(bi), chunk_of(bi, False))); bi += 1
        while bi < vM:
            seq.append(("B", mb_of(bi), chunk_of(bi, False))); bi += 1
        orders.append(seq)

    tick_f, tick_b = {}, {}
    ptr = [0] * pp
    rows = []
    t = 0
    while any(p < 2 * vM for p in ptr):
        row = {}
        for s in range(pp):
            if ptr[s] >= 2 * vM:
                continue
            kind, m, c = orders[s][ptr[s]]
            u = c * pp + s
            if kind == "F":
                ok = u == 0 or tick_f.get((m, u - 1), t) < t
            elif u == P - 1:
                ok = tick_f.get((m, u), t) < t
            else:
                ok = tick_b.get((m, u + 1), t) < t
            if ok:
                row[s] = (kind, m, c)
                (tick_f if kind == "F" else tick_b)[(m, u)] = t
                ptr[s] += 1
        rows.append(row)
        t += 1
        if t > 100 * vM + 100:
            raise AssertionError(f"schedule non-convergence pp={pp} v={v}")
    T = t

    for m in range(M):                       # verify, don't trust
        for u in range(P):
            assert (m, u) in tick_f and (m, u) in tick_b, (m, u)
            if u > 0:
                assert tick_f[(m, u)] > tick_f[(m, u - 1)]
                assert tick_b[(m, u)] < tick_b[(m, u - 1)]
            assert tick_b[(m, u)] > tick_f[(m, u)]

    # slot allocation per device (all devices share the buffer SIZES)
    aslot, cslot = {}, {}
    n_as = n_cs = 0
    for s in range(pp):
        a_iv, c_iv = [], []
        for c in range(v):
            u = c * pp + s
            for m in range(M):
                a0 = tick_f[(m, u - 1)] + 1 if u > 0 else tick_f[(m, u)]
                a_iv.append((a0, tick_b[(m, u)], (m, u)))
                if u < P - 1:
                    c_iv.append((tick_b[(m, u + 1)] + 1,
                                 tick_b[(m, u)], (m, u)))
        amap, na = _alloc_slots(a_iv)
        cmap, nc = _alloc_slots(c_iv)
        aslot.update({(s,) + k: sl for k, sl in amap.items()})
        cslot.update({(s,) + k: sl for k, sl in cmap.items()})
        n_as, n_cs = max(n_as, na), max(n_cs, nc)

    shape = (T, pp)
    KIND = np.zeros(shape, np.int32)
    MB = np.zeros(shape, np.int32)
    CH = np.zeros(shape, np.int32)
    ASLOT = np.zeros(shape, np.int32)
    CTSLOT = np.full(shape, -1, np.int32)
    ISU0 = np.zeros(shape, np.int32)
    ISHEAD = np.zeros(shape, np.int32)
    RA = np.full(shape, -1, np.int32)
    RC = np.full(shape, -1, np.int32)
    for t2, row in enumerate(rows):
        for s, (kind, m, c) in row.items():
            u = c * pp + s
            KIND[t2, s] = 1 if kind == "F" else 2
            MB[t2, s] = m
            CH[t2, s] = c
            ASLOT[t2, s] = aslot[(s, m, u)]
            ISU0[t2, s] = int(u == 0)
            ISHEAD[t2, s] = int(u == P - 1)
            if kind == "F" and u < P - 1:
                sd = (u + 1) % pp          # arrival lands downstream next tick
                assert RA[t2 + 1, sd] == -1
                RA[t2 + 1, sd] = aslot[(sd, m, u + 1)]
            if kind == "B":
                if u < P - 1:
                    CTSLOT[t2, s] = cslot[(s, m, u)]
                if u > 0:
                    su = (u - 1) % pp      # cotangent lands upstream next tick
                    assert RC[t2 + 1, su] == -1
                    RC[t2 + 1, su] = cslot[(su, m, u - 1)]
    return dict(T=T, n_aslots=n_as, n_cslots=n_cs, KIND=KIND, MB=MB, CH=CH,
                ASLOT=ASLOT, CTSLOT=CTSLOT, ISU0=ISU0, ISHEAD=ISHEAD,
                RA=RA, RC=RC)


def pipeline_train_1f1b_interleaved(stage_fn: Callable,
                                    loss_head_fn: Callable,
                                    stage_params, head_params,
                                    x: jax.Array, ctx,
                                    num_microbatches: int, pp_axis: str,
                                    virtual_stages: int,
                                    report_len: int = 0):
    """Interleaved (virtual-stage) 1F1B: ``pipeline_train_1f1b`` with each
    device holding `virtual_stages` non-adjacent layer chunks — chunk c on
    device s is virtual stage u = c*pp + s, so a microbatch crosses every
    device v times and the warm-up/cool-down bubble costs 1/v of a full
    stage per tick: the standard Megatron bubble-cutting schedule
    (beyond-reference; the reference has no pipeline axis at all).

    Contract differences from pipeline_train_1f1b:
      stage_params   leaves carry a leading [virtual_stages] chunk axis;
                     stage_fn receives ONE chunk's params (axis dropped)
      num_microbatches must be a multiple of pp (the Megatron chunk
                     rotation covers pp microbatches per segment)
      d_stage_params returned with the same [virtual_stages] leading axis
    Everything else (loss/report channels, widening/invariantization,
    ctx microbatching, the two ppermute rings) matches — the schedule is
    a static table (_interleaved_tables), verified at trace time, driving
    which unit each device runs per tick; arrivals land in statically
    allocated slots because warm-up forwards are 1-tick spaced while
    steady state is 2-spaced, so consumption is not always on the arrival
    tick (the closed-form v=1 scheduler's single in-flight register would
    drop them)."""
    n = lax.axis_size(pp_axis)
    stage = lax.axis_index(pp_axis)
    M = num_microbatches
    v = virtual_stages
    B = x.shape[0]
    assert B % M == 0, (B, M)
    mb = B // M
    tmap = jax.tree_util.tree_map
    tbls = _interleaved_tables(n, v, M)
    T = tbls["T"]
    n_as, n_cs = tbls["n_aslots"], tbls["n_cslots"]
    jt = {k: jnp.asarray(tbls[k]) for k in
          ("KIND", "MB", "CH", "ASLOT", "CTSLOT", "ISU0", "ISHEAD",
           "RA", "RC")}

    def to_mb(val):
        return val.reshape((M, mb) + val.shape[1:])

    x_mb = to_mb(x)
    ctx_mb = tmap(to_mb, ctx)
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]
    act_shape = (mb,) + x.shape[1:]
    vma = _tree_vma(x, ctx, stage_params, head_params) | {pp_axis}

    # same contract vma defaults as pipeline_train_1f1b (see _widen)
    sp_v, sp_axes = _widen(stage_params, vma, polyfill_vma=(pp_axis,))
    hp_v, hp_axes = _widen(head_params, vma)
    x_axes = tuple(sorted(set(vma) - (set(jax.typeof(x).vma)
                                      if compat.HAS_VMA else set())))
    x_mb = _pcast_to(x_mb, vma)
    ctx_mb = tmap(lambda val: _pcast_to(val, vma), ctx_mb)

    R = report_len

    g = _unit_fn(stage_fn, loss_head_fn, R)

    f32z = functools.partial(tmap,
                             lambda p: jnp.zeros(p.shape, jnp.float32))

    def pc(val):
        return _pcast_to(val, vma)

    carry0 = (
        pc(jnp.zeros(act_shape, x.dtype)),              # act ring register
        pc(jnp.zeros(act_shape, jnp.float32)),          # ct ring register
        pc(jnp.zeros((n_as,) + act_shape, x.dtype)),    # act slots
        pc(jnp.zeros((n_cs,) + act_shape, jnp.float32)),  # ct slots
        tmap(pc, f32z(stage_params)),
        tmap(pc, f32z(head_params)),
        pc(jnp.zeros((M,) + act_shape, jnp.float32)),   # d_x per microbatch
        pc(jnp.float32(0.0)),
        pc(jnp.zeros((report_len,), jnp.float32)),
    )

    def ctx_at(mi):
        return tmap(lambda val: lax.dynamic_index_in_dim(val, mi, 0, False),
                    ctx_mb)

    def tick(carry, t):
        act_in, ct_in, abuf, cbuf, d_sp, d_hp, d_x, loss_acc, rep_acc = carry

        def tbl(name):
            return jt[name][t, stage]

        # arrivals first: whatever landed on either ring this tick goes
        # into its statically assigned slot (-1: ring carries garbage)
        ra, rc = tbl("RA"), tbl("RC")
        a_up = lax.dynamic_update_index_in_dim(
            abuf, act_in.astype(x.dtype), jnp.clip(ra, 0, n_as - 1), 0)
        abuf = jnp.where(ra >= 0, a_up, abuf)
        c_up = lax.dynamic_update_index_in_dim(
            cbuf, ct_in, jnp.clip(rc, 0, n_cs - 1), 0)
        cbuf = jnp.where(rc >= 0, c_up, cbuf)

        kind = tbl("KIND")
        mi = tbl("MB")
        c = tbl("CH")
        sl = tbl("ASLOT")
        csl = tbl("CTSLOT")
        isu0 = tbl("ISU0") == 1
        ishead = tbl("ISHEAD") == 1
        sp_c = tmap(lambda p: lax.dynamic_index_in_dim(p, c, 0, False),
                    sp_v)
        c_in = ctx_at(mi)

        def do_fwd(op):
            abuf, loss_acc, rep_acc = op
            x_arr = lax.dynamic_index_in_dim(abuf, sl, 0, False)
            x_in = jnp.where(
                isu0, lax.dynamic_index_in_dim(x_mb, mi, 0, False),
                x_arr.astype(x.dtype))
            abuf2 = lax.dynamic_update_index_in_dim(abuf, x_in, sl, 0)
            h, (loss, rep) = g(sp_c, hp_v, x_in, c_in, ishead)
            return h, abuf2, loss_acc + loss / M, rep_acc + rep

        def skip_fwd(op):
            abuf, loss_acc, rep_acc = op
            return act_in.astype(x.dtype), abuf, loss_acc, rep_acc

        act_out, abuf, loss_acc, rep_acc = lax.cond(
            kind == 1, do_fwd, skip_fwd, (abuf, loss_acc, rep_acc))

        def do_bwd(op):
            ct_in, d_sp, d_hp, d_x = op
            x_in = lax.dynamic_index_in_dim(abuf, sl, 0, False)
            _, pull = jax.vjp(
                lambda a, b, xx: g(a, b, xx, c_in, ishead),
                sp_c, hp_v, x_in)
            ct_arr = lax.dynamic_index_in_dim(
                cbuf, jnp.clip(csl, 0, n_cs - 1), 0, False)
            ct_h = pc(jnp.where(ishead,
                                jnp.zeros(act_shape, jnp.float32),
                                ct_arr).astype(x.dtype))
            ct_loss = pc(jnp.full((), 1.0 / M, jnp.float32))
            ct_rep = (pc(jnp.zeros((R,), jnp.float32)) if R
                      else jnp.zeros((0,), jnp.float32))
            g_sp_c, g_hp, g_x = pull((ct_h, (ct_loss, ct_rep)))
            d_sp = tmap(
                lambda acc, gc: lax.dynamic_update_index_in_dim(
                    acc,
                    lax.dynamic_index_in_dim(acc, c, 0, False)
                    + gc.astype(jnp.float32), c, 0),
                d_sp, g_sp_c)
            d_hp = tmap(lambda a, b2: a + b2.astype(jnp.float32),
                        d_hp, g_hp)
            d_x = lax.dynamic_update_index_in_dim(
                d_x, jnp.where(isu0, g_x.astype(jnp.float32), 0.0), mi, 0)
            return g_x.astype(jnp.float32), d_sp, d_hp, d_x

        def skip_bwd(op):
            ct_in, d_sp, d_hp, d_x = op
            return ct_in, d_sp, d_hp, d_x

        ct_out, d_sp, d_hp, d_x = lax.cond(
            kind == 2, do_bwd, skip_bwd, (ct_in, d_sp, d_hp, d_x))

        act_next = lax.ppermute(act_out, pp_axis, fwd_perm)
        ct_next = lax.ppermute(ct_out, pp_axis, bwd_perm)
        return (act_next, ct_next, abuf, cbuf, d_sp, d_hp, d_x, loss_acc,
                rep_acc), None

    ticks = jnp.arange(T)
    (_, _, _, _, d_sp, d_hp, d_x, loss_acc, rep_acc), _ = lax.scan(
        tick, carry0, ticks)
    loss = lax.psum(loss_acc, pp_axis)
    d_sp = _unwiden_grads(d_sp, sp_axes)
    d_hp = _unwiden_grads(d_hp, hp_axes)
    d_x = lax.psum(d_x, tuple(sorted(set(x_axes) | {pp_axis})))
    if report_len:
        report = lax.psum(rep_acc, pp_axis)
        return loss, d_sp, d_hp, d_x.reshape(x.shape), report
    return loss, d_sp, d_hp, d_x.reshape(x.shape)


def interleave_layers(stacked, pp: int, v: int):
    """Permute a model-order stacked [L, ...] layer tree into the
    device-major order the interleaved scheduler shards: global stack row
    s*(L/pp) + c*Lc + j  <-  model layer (c*pp + s)*Lc + j, so a plain
    P(pp) contiguous shard hands device s exactly its chunks c*pp+s.
    Apply OUTSIDE shard_map (checkpoints/exports stay in model order via
    ``deinterleave_layers``)."""
    def one(a):
        L = a.shape[0]
        Lc = L // (v * pp)
        assert L % (v * pp) == 0, (L, v, pp)
        perm = [(c * pp + s) * Lc + j
                for s in range(pp) for c in range(v) for j in range(Lc)]
        return a[jnp.asarray(perm)]
    return jax.tree_util.tree_map(one, stacked)


def deinterleave_layers(stacked, pp: int, v: int):
    """Inverse of ``interleave_layers`` (gradients/params back to model
    order)."""
    def one(a):
        L = a.shape[0]
        Lc = L // (v * pp)
        assert L % (v * pp) == 0, (L, v, pp)
        perm = [(c * pp + s) * Lc + j
                for s in range(pp) for c in range(v) for j in range(Lc)]
        inv = [0] * L
        for newp, oldp in enumerate(perm):
            inv[oldp] = newp
        return a[jnp.asarray(inv)]
    return jax.tree_util.tree_map(one, stacked)
