"""Gradient accumulation: split the per-device batch into sequential
microbatches inside one jitted step.

The reference's knob for this trade-off is per-node minibatch size alone
(global MB / n_procs, sw/mlp_mpi_example_f32.cpp:301); accumulation lets a
fixed device memory train an arbitrarily large global batch — the fused
collective still runs ONCE per step on the averaged gradient, preserving
the reduce-scatter -> update -> gather structure (and its wire compression)
unchanged.

Accumulation runs in f32 regardless of the compute dtype (bf16 partial sums
lose ~8 bits over long accumulations).  The scan carry is seeded with the
first microbatch's real outputs so its vma type matches the loop body under
shard_map variance tracking.

Weighting: microbatches are averaged uniformly, so with -100-masked labels
token weighting is exact within a microbatch but uniform across microbatch
boundaries (the standard accumulation semantics).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def accumulated_value_and_grad(loss_fn: Callable, accum_steps: int):
    """value_and_grad(loss_fn) that averages over accum_steps sequential
    microbatches.  Batch leaves split on their leading axis, which must be
    divisible by accum_steps."""
    if accum_steps == 1:
        return jax.value_and_grad(loss_fn)

    def fn(params, batch):
        def split(x):
            assert x.shape[0] % accum_steps == 0, (x.shape, accum_steps)
            return x.reshape((accum_steps, x.shape[0] // accum_steps)
                             + x.shape[1:])

        micro = jax.tree_util.tree_map(split, batch)
        first = jax.tree_util.tree_map(lambda x: x[0], micro)
        rest = jax.tree_util.tree_map(lambda x: x[1:], micro)

        def one(mb):
            return jax.value_and_grad(loss_fn)(params, mb)

        loss0, g0 = one(first)
        carry = (loss0.astype(jnp.float32),
                 jax.tree_util.tree_map(
                     lambda g: g.astype(jnp.float32), g0))

        def body(c, mb):
            loss, grads = one(mb)
            acc_l, acc_g = c
            return (acc_l + loss.astype(jnp.float32),
                    jax.tree_util.tree_map(
                        lambda a, g: a + g.astype(jnp.float32),
                        acc_g, grads)), None

        (loss, grads), _ = lax.scan(body, carry, rest)
        inv = jnp.float32(1.0 / accum_steps)
        return loss * inv, jax.tree_util.tree_map(lambda g: g * inv, grads)

    return fn


def accumulated_loss(loss_fn: Callable, accum_steps: int):
    """Mean loss over accum_steps sequential microbatches, differentiable as
    a whole — for trainers (parallel.fsdp) that take gradients of an outer
    function wrapping the loss, where the grad accumulation falls out of
    autodiff through the scan instead of the explicit carry above."""
    if accum_steps == 1:
        return loss_fn

    def fn(params, batch):
        def split(x):
            assert x.shape[0] % accum_steps == 0, (x.shape, accum_steps)
            return x.reshape((accum_steps, x.shape[0] // accum_steps)
                             + x.shape[1:])

        micro = jax.tree_util.tree_map(split, batch)
        # seed the carry from microbatch 0 (not a fresh 0.0): under
        # shard_map a scan carry's variance type must match its output,
        # and the loss of a device-varying batch is varying
        first = jax.tree_util.tree_map(lambda x: x[0], micro)
        rest = jax.tree_util.tree_map(lambda x: x[1:], micro)

        def body(acc, mb):
            return acc + loss_fn(params, mb).astype(jnp.float32), None

        total, _ = lax.scan(body, loss_fn(params, first).astype(jnp.float32),
                            rest)
        return total / accum_steps

    return fn
