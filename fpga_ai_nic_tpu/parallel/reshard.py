"""Live mesh resharding — recover from a shrink/grow by collective state
redistribution instead of checkpoint-restore.

The reference keeps training alive only while its ring is intact: losing
one FPGA means a full shell reset and a cold restart
(sw/mlp_mpi_example_f32.cpp:54-57).  Our ElasticTrainer (PR 1) survives
faults, but every recovery is checkpoint-restore + replay — a preempted
replica costs cold-start MTTR.  This module is ROADMAP item 5: migrate
the **live** TrainState between mesh shapes (dp8 -> dp4 after a
preemption, a scale-up under load) with portable collective
redistribution (arXiv:2112.01075, memory-efficient array
redistribution), reusing the ring's ppermute hop as the transfer
primitive.  No disk, no replay: the state never leaves device memory.

What moves, and how:

  flat master / moment shards   Every ZeRO-1 leaf is one flat f32 vector
      (``ops.fused_update.flat_meta``): ``live`` model elements plus a
      mesh-shape-dependent zero tail (``pad_multiple(coll, n)``).  The
      live range is mesh-invariant, so a mesh change is *exactly* an
      array redistribution: cut [0, live) at every source-chunk and
      target-chunk boundary; each resulting segment has one source owner
      and one target owner — that is the **intersection table**.  The
      lowering emits one ``lax.ppermute`` per owner-changing segment
      with the segment's EXACT length as the operand (zero padding
      waste), and a local slice-copy for segments that stay put.
      graftlint rule J8 pins this statically: the traced program's
      ppermute operand bytes must sum to precisely the bytes the table
      says change owner.

  EF codec residuals            ``codec_state`` is per-DEVICE state (the
      gradient mass device i's local quantization dropped), not a shard
      of one logical vector — so it redistributes by OWNERSHIP TRANSFER,
      not by slicing: old device i's residual is assigned to new device
      ``i * n_tgt // n_src`` and summed there in ascending-i order
      (``golden_redistribute_residual`` is the bit-exact numpy twin).
      Checkpoint restore re-zeros the residual (EF is self-healing, so
      that is *correct* but loses one step's worth of compensated mass);
      the reshard path preserves it bit-for-bit — the error-feedback
      fixed point survives the migration.

The whole transfer is ONE jitted program over a flat 1-D "union" mesh
(``parallel.mesh.flat_union_mesh``) with every source buffer DONATED —
the reference's updated-weights-over-gradient-buffer aliasing trick
(hw/all_reduce.sv:240), applied to recovery.  For a shrink the union is
the source mesh and nothing moves before the program runs; for a grow
the source vector is first re-laid onto the union mesh (an XLA
``device_put`` — recorded honestly as ``seed_bytes``, outside the J8
ppermute accounting) and the collective program finishes the job.

``reshard_state(src_trainer, tgt_trainer, state)`` is the one-stop API
the elastic loop's first recovery tier calls (docs/RESHARD.md).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib
from ..ops import fused_update
from ..ops import integrity as integrity_lib
from ..ops import ring as ring_ops
# the shared protocol IR: the intersection table, owner map, union
# layout and the transfer-action program (with its conservation message
# ids) are defined once there and consumed both by the lowering below
# and by graftmc's checked streams — no second definition to drift
from ..verify import opstream as _opstream

__all__ = [
    "Transfer", "FlatPlan", "ResidualPlan", "ReshardPlan",
    "intersection_table", "residual_owners", "make_plan", "lower_apply",
    "golden_redistribute_residual", "reshard_state", "abstract_operands",
    "pack_state_leaves", "split_state_leaves",
]


def pack_state_leaves(w_own: jax.Array,
                      opt_state: Optional[Dict[str, jax.Array]]
                      ) -> Dict[str, Any]:
    """THE flat-leaf naming convention of a live move (w_own + sorted
    ``opt.<k>`` moments) — one definition shared by every trainer's
    ``reshard_leaves`` so the transfer set cannot drift between trainer
    kinds (``reshard_state`` asserts its length against the plan)."""
    d = {"w_own": w_own}
    d.update({f"opt.{k}": v for k, v in sorted((opt_state or {}).items())})
    return d


def split_state_leaves(leaves: Dict[str, Any]
                       ) -> Tuple[Any, Dict[str, Any]]:
    """Inverse of ``pack_state_leaves``: (w_own, opt_state)."""
    return leaves["w_own"], {k[len("opt."):]: v for k, v in leaves.items()
                             if k.startswith("opt.")}


# One intersection-table segment: ``length`` contiguous live elements
# moving from source device ``src`` (at chunk-local ``src_off``) to
# target device ``dst`` (at chunk-local ``dst_off``); ``src == dst``
# means the bytes stay resident.  Transfer IS the IR's segment type,
# and `intersection_table` IS the IR's partition function (cut [0, live)
# at every chunk boundary of either layout; the segments PARTITION the
# live range, asserted there) — one definition, consumed by this
# lowering and explored by graftmc.  tests pin the delegation by
# identity.
Transfer = _opstream.Seg
intersection_table = _opstream.reshard_segments


class FlatPlan(NamedTuple):
    """Redistribution plan for ONE flat-vector layout (all master/moment
    leaves of a state share it).  ``chunk_src`` is the per-device chunk
    in the UNION layout the program reads (== the trainer layout's chunk
    for a shrink); ``chunk_tgt`` the target trainer layout's chunk."""

    live: int
    n_src: int
    n_tgt: int
    n_union: int
    chunk_src: int
    chunk_tgt: int
    padded_src: int          # source trainer layout length (n_src chunks)
    padded_tgt: int          # target trainer layout length (n_tgt chunks)
    seed_len: int            # union input layout length (n_union chunks)
    table: Tuple[Transfer, ...]

    @property
    def wire_elems(self) -> int:
        """Elements that change owner — what the ppermutes move."""
        return sum(t.length for t in self.table if t.src != t.dst)

    @property
    def local_elems(self) -> int:
        return self.live - self.wire_elems

    @property
    def seed_elems(self) -> int:
        """Elements the grow-path seeding re-lays out BEFORE the program
        — counted with the same intersection rule (source layout vs
        union layout; only owner changes move).  0 for a shrink: the
        union layout IS the source layout."""
        if self.n_union == self.n_src:
            return 0
        c_src_trainer = self.padded_src // self.n_src
        return sum(t.length for t in intersection_table(
            self.live, c_src_trainer, self.chunk_src) if t.src != t.dst)


class ResidualPlan(NamedTuple):
    """Redistribution plan for per-device EF residuals: old device i's
    [pad_src] residual (live prefix) is summed into new device
    ``owners[i]``'s [pad_tgt] residual, ascending-i order."""

    live: int
    n_src: int
    n_tgt: int
    n_union: int
    pad_src: int             # source per-device residual length
    pad_tgt: int             # target per-device residual length
    owners: Tuple[int, ...]

    @property
    def wire_elems(self) -> int:
        return self.live * sum(1 for i, o in enumerate(self.owners)
                               if i != o)


# Old device -> new owner assignment: contiguous groups, every old
# residual has exactly one new home (mass is conserved), fresh devices
# beyond the assignment start at zero (a new replica has dropped
# nothing yet).  THE definition lives in the IR.
residual_owners = _opstream.reshard_owners


class ReshardPlan(NamedTuple):
    """The full mesh-shape change as a static collective program
    description: one FlatPlan shared by ``n_flat_leaves`` state vectors
    (master + optimizer moments) plus an optional ResidualPlan."""

    flat: FlatPlan
    n_flat_leaves: int
    residual: Optional[ResidualPlan]

    def wire_bytes(self, itemsize: int = 4) -> int:
        """EXACTLY the bytes that change owner per the intersection table
        — the number graftlint J8 holds the lowered program's ppermute
        operands to."""
        n = self.n_flat_leaves * self.flat.wire_elems
        if self.residual is not None:
            n += self.residual.wire_elems
        return n * itemsize

    def seed_bytes(self, itemsize: int = 4) -> int:
        """Bytes the grow-path union seeding moves via device_put before
        the collective program (0 for a shrink) — reported, never hidden
        inside the ppermute accounting."""
        return self.n_flat_leaves * self.flat.seed_elems * itemsize

    def describe(self) -> Dict[str, Any]:
        f = self.flat
        return {
            "n_src": f.n_src, "n_tgt": f.n_tgt, "live_elems": f.live,
            "n_flat_leaves": self.n_flat_leaves,
            "transfers": len(f.table),
            "wire_bytes": self.wire_bytes(),
            "seed_bytes": self.seed_bytes(),
            "residual_moved": (0 if self.residual is None
                               else self.residual.wire_elems // max(
                                   self.residual.live, 1)),
        }


def make_plan(live: int, n_src: int, padded_src: int, n_tgt: int,
              padded_tgt: int, *, n_flat_leaves: int,
              residual: bool = False) -> ReshardPlan:
    """Plan a mesh-shape change for a state of ``n_flat_leaves`` flat
    vectors (source layout [padded_src] over n_src devices, target
    [padded_tgt] over n_tgt) plus, with ``residual=True``, per-device EF
    residuals ([padded_src] each -> [padded_tgt] each)."""
    assert 0 < live <= min(padded_src, padded_tgt)
    assert n_flat_leaves >= 1
    # shrink: the union layout IS the source layout — no seeding; grow:
    # the source re-lays onto n_union devices first (seed device_put).
    # THE arithmetic lives in the IR (one definition with the checker's
    # grid cells).
    chunk_src, chunk_tgt, n_union, seed_len = _opstream.union_layout(
        live, n_src, padded_src, n_tgt, padded_tgt)
    flat = FlatPlan(live=live, n_src=n_src, n_tgt=n_tgt, n_union=n_union,
                    chunk_src=chunk_src, chunk_tgt=chunk_tgt,
                    padded_src=padded_src, padded_tgt=padded_tgt,
                    seed_len=seed_len,
                    table=intersection_table(live, chunk_src, chunk_tgt))
    rp = None
    if residual:
        # the EF residual is per-DEVICE state: each device carries a FULL
        # padded-model vector ([padded_len], not a chunk) — see
        # DPTrainer._init_codec_state
        rp = ResidualPlan(live=live, n_src=n_src, n_tgt=n_tgt,
                          n_union=n_union,
                          pad_src=padded_src, pad_tgt=padded_tgt,
                          owners=residual_owners(n_src, n_tgt))
    return ReshardPlan(flat=flat, n_flat_leaves=n_flat_leaves, residual=rp)


# ---------------------------------------------------------------------------
# lowering: the plan as one jitted shard_map program (donated sources)
# ---------------------------------------------------------------------------

def _move_chunk(plan: FlatPlan, ax: str, chunk: jax.Array,
                idx: jax.Array,
                chk: Optional[Tuple[jax.Array, jax.Array]] = None,
                base: int = 0) -> Any:
    """SPMD body for one flat leaf: [chunk_src] -> [chunk_tgt].  Each
    intersection segment is one exact-length hop: a single-pair ppermute
    when the owner changes (receivers outside the pair get zeros — the
    where-mask keeps only the true destination's write), a resident
    slice-copy when it does not.  All offsets/lengths are static, so the
    program is a fixed DAG the J8 sweep can account byte-for-byte.

    ``chk`` (None = integrity off) is the (send_acc, recv_acc) uint32
    conservation carry (ops.integrity): every owner-changing segment is
    checksummed on the SOURCE device before its ppermute and on the
    TARGET device after it (post-wire-tap), with one odd weight per
    (leaf, segment) — ``base`` is the leaf's offset into a single
    program-wide message counter, so every message in the transfer gets
    a DISTINCT odd weight and distinct messages never alias (a product
    of two odd per-axis weights would collide across leaves).  Resident
    copies never touch a wire and are not checksummed.  No checksum
    rides the wire: the J8 ppermute byte accounting is identical either
    way.  The segment order, wire-vs-resident classification and message
    ids are CONSUMED from the IR's action program
    (`opstream.reshard_leaf_actions`) — the same list the checked
    per-node streams expand."""
    out = jnp.zeros((plan.chunk_tgt,), chunk.dtype)
    for act in _opstream.reshard_leaf_actions(plan.table, base):
        payload = lax.dynamic_slice_in_dim(chunk, act.src_off, act.length)
        if act.kind == "xfer":
            if chk is not None:
                w = integrity_lib.hop_weight(act.msg)
                sa, ra = chk
                sa = sa + jnp.where(
                    idx == act.src,
                    w * integrity_lib.word_checksum(payload), jnp.uint32(0))
            payload = lax.ppermute(payload, ax, [(act.src, act.dst)])
            payload = ring_ops._tap_wire((payload,), "reshard.wire",
                                         consumed=idx == act.dst)[0]
            if chk is not None:
                ra = ra + jnp.where(
                    idx == act.dst,
                    w * integrity_lib.word_checksum(payload), jnp.uint32(0))
                chk = (sa, ra)
        upd = lax.dynamic_update_slice_in_dim(out, payload, act.dst_off, 0)
        out = jnp.where(idx == act.dst, upd, out)
    return out if chk is None else (out, chk)


def _move_residual(plan: ResidualPlan, ax: str, resid: jax.Array,
                   idx: jax.Array,
                   chk: Optional[Tuple[jax.Array, jax.Array]] = None,
                   base: int = 0) -> Any:
    """SPMD body for the EF residual: old device i's live residual lands
    (summed, ascending-i order — the golden twin's order) on new device
    ``owners[i]``.  Devices with no assignment keep zeros: a fresh
    replica has dropped nothing yet.  ``chk``: the same conservation
    carry as ``_move_chunk`` (wire moves only, ``base`` continuing the
    program-wide message counter past the flat leaves' segments)."""
    live = lax.dynamic_slice_in_dim(resid, 0, plan.live)
    out = jnp.zeros((plan.pad_tgt,), resid.dtype)
    for ra_ in _opstream.reshard_residual_actions(plan.owners, base):
        if ra_.kind == "keep":
            payload = live
        else:
            if chk is not None:
                w = integrity_lib.hop_weight(ra_.msg)
                sa, ra = chk
                sa = sa + jnp.where(
                    idx == ra_.src,
                    w * integrity_lib.word_checksum(live), jnp.uint32(0))
            payload = lax.ppermute(live, ax, [(ra_.src, ra_.dst)])
            payload = ring_ops._tap_wire((payload,), "reshard.wire",
                                         consumed=idx == ra_.dst)[0]
            if chk is not None:
                ra = ra + jnp.where(
                    idx == ra_.dst,
                    w * integrity_lib.word_checksum(payload), jnp.uint32(0))
                chk = (sa, ra)
        upd = out.at[:plan.live].add(payload)
        out = jnp.where(idx == ra_.dst, upd, out)
    return out if chk is None else (out, chk)


def lower_apply(plan: ReshardPlan, union_mesh: Mesh, ax: str, *,
                donate: bool = True,
                integrity: bool = False
                ) -> Callable[..., Tuple[jax.Array, ...]]:
    """The plan as ONE jitted transfer program over the union mesh.

    Positional args: ``n_flat_leaves`` flat vectors in the union-source
    layout ([seed_len], sharded P(ax)) then, if planned, the residual
    global ([n_union * pad_src], sharded P(ax)).  Returns the same
    leaves in the union-target layout ([n_union * chunk_tgt] each).
    Every input is donated by default: the sources are dead the moment
    the transfer lands (the elastic loop never touches them again), so
    the program runs in ~one state's footprint, not two.

    ``integrity=True`` appends a replicated ``wire_ok`` bool output:
    every owner-changing segment of every leaf (and every residual
    move) checksummed bit-exactly on both sides of its ppermute
    (ops.integrity conservation over the union axis).  The landed bytes
    and the J8 ppermute accounting are identical either way — only the
    verdict is added."""
    fp = plan.flat
    n_ops = plan.n_flat_leaves + (1 if plan.residual is not None else 0)

    # the program-wide message counter (one DISTINCT odd weight per
    # message across all leaves + residual) — from the IR, shared with
    # the checked streams and audited by M2
    leaf_bases, resid_base = _opstream.reshard_msg_bases(
        len(fp.table), plan.n_flat_leaves)

    def body(*chunks: jax.Array) -> Tuple[jax.Array, ...]:
        idx = lax.axis_index(ax)
        chk = integrity_lib.zero_carry() if integrity else None
        outs = []
        for li, c in enumerate(chunks[:plan.n_flat_leaves]):
            res = _move_chunk(fp, ax, c, idx, chk=chk,
                              base=leaf_bases[li])
            if integrity:
                res, chk = res
            outs.append(res)
        if plan.residual is not None:
            res = _move_residual(plan.residual, ax, chunks[-1], idx,
                                 chk=chk, base=resid_base)
            if integrity:
                res, chk = res
            outs.append(res)
        if integrity:
            outs.append(integrity_lib.conservation_ok(chk[0], chk[1], ax))
        return tuple(outs)

    out_specs = (P(ax),) * n_ops + ((P(),) if integrity else ())
    sm = jax.shard_map(body, mesh=union_mesh, in_specs=(P(ax),) * n_ops,
                       out_specs=out_specs, check_vma=False)
    return jax.jit(sm, donate_argnums=(tuple(range(n_ops)) if donate
                                       else ()))


@functools.lru_cache(maxsize=32)
def _cached_apply(plan: ReshardPlan, union_mesh: Mesh, ax: str,
                  donate: bool,
                  integrity: bool = False
                  ) -> Callable[..., Tuple[jax.Array, ...]]:
    """Memoized ``lower_apply``: a supervisor reshards against a handful
    of (plan, mesh) pairs at most, and reusing the jitted callable lets a
    prewarmed transfer hit the compile cache at fault time — the MTTR
    the recovery tier is measured on (plans and meshes are hashable
    value types, so the key is exact)."""
    return lower_apply(plan, union_mesh, ax, donate=donate,
                       integrity=integrity)


def abstract_operands(plan: ReshardPlan,
                      dtype: Any = jnp.float32
                      ) -> Tuple[jax.ShapeDtypeStruct, ...]:
    """ShapeDtypeStructs matching ``lower_apply``'s positional args — the
    zero-device-work handle the graftlint J8 sweep traces the program
    through."""
    fp = plan.flat
    ops = [jax.ShapeDtypeStruct((fp.seed_len,), dtype)
           for _ in range(plan.n_flat_leaves)]
    if plan.residual is not None:
        rp = plan.residual
        ops.append(jax.ShapeDtypeStruct((rp.n_union * rp.pad_src,), dtype))
    return tuple(ops)


# ---------------------------------------------------------------------------
# numpy golden twin (residual redistribution is the only value-changing
# part of a reshard — flat leaves move bytes, residuals SUM)
# ---------------------------------------------------------------------------

def golden_redistribute_residual(res: np.ndarray, live: int, n_tgt: int,
                                 pad_tgt: int) -> np.ndarray:
    """Bit-exact twin of ``_move_residual`` over the whole mesh:
    ``res[n_src, pad_src]`` -> ``[n_tgt, pad_tgt]``, f32 sums in
    ascending-source order (the lowered program's order — sequential
    dependent adds XLA may not reassociate)."""
    res = np.asarray(res, np.float32)
    n_src = res.shape[0]
    out = np.zeros((n_tgt, pad_tgt), np.float32)
    for i, owner in enumerate(residual_owners(n_src, n_tgt)):
        out[owner, :live] = out[owner, :live] + res[i, :live]
    return out


# ---------------------------------------------------------------------------
# the one-stop API: reshard a live trainer state between mesh shapes
# ---------------------------------------------------------------------------

def _wire_format(trainer: Any) -> Tuple[Any, ...]:
    """Everything that parameterizes the trainer's wire format — name
    AND options AND the legacy BFPConfig.  A name-only comparison would
    let e.g. an int8+error_feedback source reshard onto an int8 no-EF
    target: the residual would be moved, handed over, and silently never
    consumed (the target's step takes the non-EF path)."""
    coll = trainer.cfg.collective
    return (coll.codec, tuple(coll.codec_opts or ()), coll.compression,
            bool(getattr(trainer, "_ef", False)))


def plan_for(src_trainer: Any, tgt_trainer: Any) -> ReshardPlan:
    """Build the ReshardPlan for a src->tgt trainer pair (both metas must
    be known — the source trained, the target gets its layout derived
    from the source's via ``fused_update.params_like_from_meta``)."""
    if type(src_trainer) is not type(tgt_trainer):
        raise ValueError(
            f"reshard moves state between mesh SHAPES, not trainer kinds: "
            f"{type(src_trainer).__name__} -> "
            f"{type(tgt_trainer).__name__}")
    if src_trainer.ax != tgt_trainer.ax:
        raise ValueError(
            f"axis mismatch: {src_trainer.ax!r} -> {tgt_trainer.ax!r}")
    if _wire_format(src_trainer) != _wire_format(tgt_trainer):
        raise ValueError(
            "reshard keeps the wire format fixed across the move "
            f"(codec/opts/EF {_wire_format(src_trainer)} -> "
            f"{_wire_format(tgt_trainer)}); change codecs via "
            "checkpoint-restore")
    src_meta = src_trainer._meta
    assert src_meta is not None, "source trainer has no layout (init first)"
    if tgt_trainer._meta is None:
        tgt_trainer._ensure_meta(fused_update.params_like_from_meta(src_meta))
    tgt_meta = tgt_trainer._meta
    live = sum(src_meta.sizes)
    if live != sum(tgt_meta.sizes):
        raise ValueError(
            f"layout mismatch: {live} live elements at the source vs "
            f"{sum(tgt_meta.sizes)} at the target — different models")
    from .. import optim
    n_flat = 1 + len(optim.OptimizerSpec.from_optimizer(
        src_trainer.cfg.optimizer).state_keys)
    ef = bool(getattr(src_trainer, "_ef", False))
    return make_plan(live, src_trainer.n, src_meta.padded_len,
                     tgt_trainer.n, tgt_meta.padded_len,
                     n_flat_leaves=n_flat, residual=ef)


def _to_union(v: jax.Array, plan: FlatPlan,
              sharding: NamedSharding) -> jax.Array:
    """Source-layout [padded_src] -> union-source layout [seed_len] on
    the union mesh.  Shrink: identity layout, free placement.  Grow: the
    seed device_put (plan.seed_bytes) — XLA's resharding, counted apart
    from the collective program's wire bytes."""
    if plan.seed_len < plan.padded_src:
        v = lax.slice_in_dim(v, 0, plan.seed_len)
    elif plan.seed_len > plan.padded_src:
        v = jnp.pad(v, (0, plan.seed_len - plan.padded_src))
    return jax.device_put(v, sharding)


def reshard_state(src_trainer: Any, tgt_trainer: Any, state: Any, *,
                  events: Any = None, donate: bool = True,
                  integrity: Optional[bool] = None) -> Any:
    """Move a live TrainState/FSDPState from ``src_trainer``'s mesh to
    ``tgt_trainer``'s in one collective transfer program (see module
    docstring).  Returns the target trainer's state, step preserved,
    masters/moments value-exact (the live elements only ever move),
    EF residual redistributed (not re-zeroed).  With ``donate`` the
    source buffers are consumed.

    ``integrity`` (None = follow the source trainer's
    ``collective.integrity_check``) runs the transfer with the exact
    wire-checksum verdict (``lower_apply(integrity=True)``); a tripped
    verdict raises ``runtime.chaos.WireIntegrityError`` BEFORE the
    landed state is handed to the target trainer — the elastic ladder
    then falls through to the checkpoint-restore tier instead of
    training on silently corrupted masters."""
    if integrity is None:
        integrity = bool(getattr(src_trainer.cfg.collective,
                                 "integrity_check", False))
    plan = plan_for(src_trainer, tgt_trainer)
    fp = plan.flat
    ax = src_trainer.ax
    union_mesh = mesh_lib.flat_union_mesh(src_trainer.mesh,
                                          tgt_trainer.mesh, ax)
    assert union_mesh.shape[ax] >= fp.n_union
    if union_mesh.shape[ax] > fp.n_union:
        union_mesh = mesh_lib.single_axis_mesh(
            ax, fp.n_union, list(union_mesh.devices.reshape(-1)))
    u_shard = NamedSharding(union_mesh, P(ax))

    leaves = src_trainer.reshard_leaves(state)
    names = list(leaves)
    assert len(names) == plan.n_flat_leaves, (names, plan.n_flat_leaves)
    ops = [_to_union(leaves[k], fp, u_shard) for k in names]
    if plan.residual is not None:
        resid = state.codec_state
        assert resid is not None, "EF codec with no residual state"
        rp = plan.residual
        if rp.n_union > rp.n_src:
            resid = jnp.pad(
                resid, (0, (rp.n_union - rp.n_src) * rp.pad_src))
        ops.append(jax.device_put(resid, u_shard))

    run = _cached_apply(plan, union_mesh, ax, donate, bool(integrity))
    span = (events.span("reshard.transfer", **plan.describe())
            if events is not None else None)
    if span is not None:
        with span:
            outs = run(*ops)
            jax.block_until_ready(outs)
    else:
        outs = run(*ops)
    if integrity:
        wire_ok = outs[-1]
        outs = outs[:-1]
        if not bool(jax.device_get(wire_ok)):
            from ..runtime.chaos import WireIntegrityError
            raise WireIntegrityError(
                "reshard transfer wire checksum tripped: a ppermute "
                "segment landed with different bytes than were sent "
                f"({plan.flat.n_src}->{plan.flat.n_tgt}); refusing the "
                "landed state — fall through to checkpoint restore")

    # union-target layout -> the target trainer's mesh (shards 0..n_tgt-1
    # are already resident on the right devices; the tail shards are the
    # union's scratch and are dropped)
    t_shard = NamedSharding(tgt_trainer.mesh, P(ax))

    def land(v: jax.Array) -> jax.Array:
        if fp.n_union > fp.n_tgt:
            v = v[:fp.padded_tgt]
        return jax.device_put(v, t_shard)

    landed = {k: land(v) for k, v in zip(names, outs[:plan.n_flat_leaves])}
    codec_state = None
    if plan.residual is not None:
        rp = plan.residual
        r = outs[-1]
        if rp.n_union > rp.n_tgt:
            r = r[:rp.n_tgt * rp.pad_tgt]
        codec_state = jax.device_put(r, t_shard)
    elif getattr(tgt_trainer, "_ef", False):
        codec_state = tgt_trainer._init_codec_state()
    step = jnp.asarray(jax.device_get(state.step))
    new_state = tgt_trainer.state_from_reshard(landed, step, codec_state)
    if events is not None:
        events.instant("reshard.done", n_src=fp.n_src, n_tgt=fp.n_tgt,
                       wire_bytes=plan.wire_bytes(),
                       seed_bytes=plan.seed_bytes())
    return new_state
