"""Queued DDP trainer — the reference's host-side issue/wait loop, live.

`parallel.ddp.DDPTrainer` fuses grads + bucketed collectives + optimizer
into one jitted program and lets XLA's latency-hiding scheduler overlap
them — the right default on TPU.  This trainer instead reproduces the
reference's *host-driven* structure (sw/mlp_mpi_example_f32.cpp:735-787):
backward produces per-bucket gradient buffers, thread 0 issues one async
all-reduce per buffer through a bounded window (<= 8 in flight,
hw/all_reduce.sv:1228,1373), waits land one step behind, and the optimizer
consumes reduced buffers as they complete.

Here each phase is its own jitted program and every bucket's collective is
a separate dispatch through `runtime.queue.CollectiveQueue`:

    grads_fn   : shard_map'd fwd+bwd -> per-bucket local f32 vectors
    reduce[b]  : shard_map'd mean all-reduce of one bucket (psum or the
                 BFP ring per CollectiveConfig) — issued via queue.issue()
    update_fn  : flat f32 master optimizer + working-param rematerialize

Because JAX dispatch is async, issue() returns while the device still runs
backward; the issue->wait gap measured by the queue is genuine overlap and
the time blocked in wait() is genuine network-bound stall — the profiler
counters the reference reads over CSRs (lpbk_latency / stall_host,
sw/mlp_mpi_example_f32.cpp:100-112) come out of a real training run, not a
unit test.  The fused trainer remains the throughput king; this one exists
for observability and for parity with the reference's programming model.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import accum
from .ddp import DDPState, DDPTrainer
from .. import optim
from ..obs import metrics as obs_metrics
from ..ops import bucketed, fused_update
from ..runtime.queue import CollectiveQueue
from ..utils.config import TrainConfig
from ..utils.observability import Profiler


class QueuedDDPTrainer(DDPTrainer):
    """loss_fn(params, batch) -> scalar; batch leaves shard over dp.

    Same state/numerics as DDPTrainer (identical bucket plan, add order and
    per-hop quantization), different execution: 2 + n_buckets dispatches per
    step through a CollectiveQueue instead of one fused program.
    """

    def __init__(self, loss_fn: Callable, mesh: Mesh, cfg: TrainConfig,
                 axis_name: str = "dp", profiler: Optional[Profiler] = None):
        super().__init__(loss_fn, mesh, cfg, axis_name)
        self.profiler = profiler or Profiler()
        self.queue = CollectiveQueue(lambda fn, g: fn(g), cfg.collective,
                                     self.profiler)
        self._bucket_telemetry_done = False

    # -- init ---------------------------------------------------------------

    def _ensure_meta(self, params_like) -> None:
        # invalidate this subclass's jitted phases whenever the flat
        # layout changes (init_state AND restore_state(params_like=...))
        super()._ensure_meta(params_like)
        self.__dict__.pop("grads_fn", None)
        self.__dict__.pop("reduce_fn", None)
        self.__dict__.pop("update_fn", None)

    # -- jitted phases ------------------------------------------------------

    @functools.cached_property
    def grads_fn(self):
        plan, ax = self._plan, self.ax
        assert plan is not None, "call init_state first"

        def shard_grads(params, batch):
            params_v = jax.tree_util.tree_map(
                lambda x: lax.pcast(x, ax, to="varying"), params)
            loss, grads = accum.accumulated_value_and_grad(
                self.loss_fn, self.cfg.accum_steps)(params_v, batch)
            return tuple(bucketed.bucket_locals(grads, plan)), \
                lax.pmean(loss, ax)

        nb = len(plan.buckets)
        return jax.jit(jax.shard_map(
            shard_grads, mesh=self.mesh, in_specs=(P(), P(ax)),
            out_specs=((P(ax),) * nb, P())))

    @functools.cached_property
    def reduce_fn(self):
        """The per-buffer mean-all-reduce collective the queue issues; one
        jitted function, recompiled per bucket shape by jax.jit's own
        cache."""
        coll, ax, n = self.cfg.collective, self.ax, self.n
        # route through the shared definition (flat ring / hierarchical)
        # but PIN the separate-op path: the fused Pallas kernel's RDMA
        # frames carry tile padding beyond wire_bytes_per_device, so
        # letting fused_kernel ride here would silently break the exact
        # per-bucket declarations this trainer's telemetry banks
        if coll.fused_kernel:
            import dataclasses
            coll_r = dataclasses.replace(coll, fused_kernel=False)
        else:
            coll_r = coll

        def shard_reduce(g):
            if coll.impl == "xla":
                red = lax.pcast(lax.psum(g, ax), ax, to="varying")
            else:
                red = fused_update.ring_all_reduce_routed(
                    g, ax, coll_r, g.shape[0] // n)
            return red / n

        return jax.jit(jax.shard_map(shard_reduce, mesh=self.mesh,
                                     in_specs=P(ax), out_specs=P(ax)))

    @functools.cached_property
    def update_fn(self):
        opt_cfg = self.cfg.optimizer
        meta, plan = self._meta, self._plan
        nb = len(plan.buckets)

        def shard_update(bucket_means, w_master, opt_state, step):
            flat_g = bucketed.assemble_flat(list(bucket_means), plan)
            flat_g = optim.clip_by_global_norm(opt_cfg, flat_g)
            w_new, opt_state2 = optim.apply(opt_cfg, w_master, flat_g,
                                            opt_state, step)
            params2 = fused_update.unflatten_tree(w_new, meta)
            return params2, w_new, opt_state2

        ax = self.ax
        # donate the master/opt buffers (the fused trainer donates its whole
        # state): without this each step holds two replicated f32 copies
        return jax.jit(jax.shard_map(
            shard_update, mesh=self.mesh,
            in_specs=((P(ax),) * nb, P(), P(), P()),
            out_specs=(P(), P(), P()), check_vma=False),
            donate_argnums=(1, 2))

    # -- step ---------------------------------------------------------------

    def step(self, state: DDPState, batch) -> Tuple[DDPState, jax.Array]:
        coll, plan, n = self.cfg.collective, self._plan, self.n
        with self.profiler.bucket("grads"):
            bucket_g, loss = self.grads_fn(state.params, batch)
        tickets = []
        with self.profiler.bucket("issue"):
            for i, (b, g) in enumerate(zip(plan.buckets, bucket_g)):
                raw = fused_update.wire_bytes_for(coll, b.padded_len, n,
                                                  codec=None)
                wire = fused_update.wire_bytes_for(coll, b.padded_len, n)
                if not self._bucket_telemetry_done:
                    # per-bucket wire accounting, once (static per plan):
                    # the flit-counter view the reference exposes per
                    # collective (hw/bfp_adapter.sv:705-729).  Named per
                    # bucket: the stream summary keeps latest-per-name,
                    # so one shared name would collapse the plan to its
                    # last bucket
                    self.profiler.events.counter(
                        f"bucket{i}.compression_ratio", raw / wire,
                        bucket=i, padded_len=b.padded_len,
                        wire_bytes=wire, raw_bytes=raw)
                tickets.append(self.queue.issue(
                    self.reduce_fn, g, raw_bytes=raw, wire_bytes=wire))
            self._bucket_telemetry_done = True
        means = tuple(self.queue.wait(t) for t in tickets)
        with self.profiler.bucket("update"):
            params, w_master, opt_state = self.update_fn(
                means, state.w_master, state.opt_state, state.step)
        if self.cfg.obs_metrics:
            # host-side delivery (this trainer's phases are separate
            # dispatches; the loss fetch syncs an already-waited value)
            obs_metrics.host_observe({"loss": float(loss)})
        return DDPState(params, w_master, opt_state, state.step + 1), loss
