// Native batch-staging engine: the host-side buffer plane of the runtime.
//
// Role parity: the reference's C++ driver owns host buffer staging — OPAE
// pinned allocations plus the per-iteration activation layout loops that
// feed the device DMA (sw/mlp_mpi_example_f32.cpp:381-424,452-460).  The
// TPU-native equivalent is assembling shuffled minibatches: dst[i, :] =
// src[idx[i], :], the row-gather every epoch loop performs before
// device_put.  In Python/numpy that gather is a single-threaded memcpy
// holding the GIL; here it runs on an OpenMP team inside a worker thread,
// so batch k+1 stages while the interpreter dispatches batch k — the same
// copy/compute overlap the reference gets from its 4-CL read bursts
// running behind the ring (readme.pdf §2.1).
//
// Design: a fixed pool of reusable aligned slot buffers + one worker
// thread draining a job queue (gathers are internally OpenMP-parallel, so
// one drain thread saturates memory bandwidth).  States: FREE -> QUEUED ->
// READY -> (release) FREE.  The C ABI below is loaded via ctypes
// (runtime/staging.py); no Python headers involved.
//
// Build: make -C fpga_ai_nic_tpu/csrc   (libstaging.so)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

enum class SlotState : int { FREE = 0, QUEUED = 1, READY = 2 };

struct Job {
  int slot;
  const unsigned char* src;
  const int64_t* idx;     // caller keeps alive until wait() returns
  int64_t n_rows;
  int64_t row_bytes;
};

struct Pool {
  std::vector<unsigned char*> buffers;
  std::vector<size_t> capacity;    // per-slot byte capacity
  std::vector<SlotState> state;
  std::deque<Job> queue;
  std::mutex mu;
  std::condition_variable cv;      // slot state changes / queue pushes
  std::thread worker;
  bool stop = false;

  Pool(const int64_t* sizes, int n_slots) {
    buffers.reserve(n_slots);
    for (int i = 0; i < n_slots; ++i) {
      void* p = nullptr;
      // 4096: page alignment so the runtime's host->device DMA never
      // straddles a partial first page
      if (posix_memalign(&p, 4096, static_cast<size_t>(sizes[i])) != 0)
        p = nullptr;
      buffers.push_back(static_cast<unsigned char*>(p));
      capacity.push_back(static_cast<size_t>(sizes[i]));
      state.push_back(SlotState::FREE);
    }
    worker = std::thread([this] { run(); });
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> g(mu);
      stop = true;
    }
    cv.notify_all();
    worker.join();
    for (auto* b : buffers) free(b);
  }

  void run() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> g(mu);
        cv.wait(g, [this] { return stop || !queue.empty(); });
        if (stop) return;
        job = queue.front();
        queue.pop_front();
      }
      gather(job);
      {
        std::lock_guard<std::mutex> g(mu);
        state[job.slot] = SlotState::READY;
      }
      cv.notify_all();
    }
  }

  void gather(const Job& j) {
    unsigned char* dst = buffers[j.slot];
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < j.n_rows; ++i) {
      std::memcpy(dst + i * j.row_bytes, j.src + j.idx[i] * j.row_bytes,
                  static_cast<size_t>(j.row_bytes));
    }
  }
};

}  // namespace

extern "C" {

// Per-slot sizes: mixed-width batch pytrees get right-sized slots (a
// uniform max-size pool would waste ~row_bytes ratio per small leaf).
void* stage_create_sized(const int64_t* slot_bytes, int n_slots) {
  if (n_slots < 1) return nullptr;
  for (int i = 0; i < n_slots; ++i)
    if (slot_bytes[i] < 1) return nullptr;
  Pool* p = new Pool(slot_bytes, n_slots);
  for (auto* b : p->buffers)
    if (b == nullptr) {
      delete p;
      return nullptr;
    }
  return p;
}

void* stage_create(int n_slots, int64_t slot_bytes) {
  if (n_slots < 1) return nullptr;
  std::vector<int64_t> sizes(n_slots, slot_bytes);
  return stage_create_sized(sizes.data(), n_slots);
}

void stage_destroy(void* pool) { delete static_cast<Pool*>(pool); }

// Claim the smallest FREE slot that fits (blocking) and enqueue the
// gather.  Returns slot id, or -1 if no slot could ever fit the job.
int stage_submit(void* pool, const void* src, const int64_t* idx,
                 int64_t n_rows, int64_t row_bytes) {
  Pool* p = static_cast<Pool*>(pool);
  const size_t need = static_cast<size_t>(n_rows * row_bytes);
  bool fits_any = false;
  for (size_t cap : p->capacity) fits_any |= (cap >= need);
  if (!fits_any) return -1;
  std::unique_lock<std::mutex> g(p->mu);
  int slot = -1;
  p->cv.wait(g, [&] {
    size_t best = SIZE_MAX;
    for (size_t i = 0; i < p->state.size(); ++i)
      if (p->state[i] == SlotState::FREE && p->capacity[i] >= need &&
          p->capacity[i] < best) {
        best = p->capacity[i];
        slot = static_cast<int>(i);
      }
    return slot >= 0;
  });
  p->state[slot] = SlotState::QUEUED;
  p->queue.push_back(Job{slot, static_cast<const unsigned char*>(src), idx,
                         n_rows, row_bytes});
  g.unlock();
  p->cv.notify_all();
  return slot;
}

// Block until the slot's gather completes; returns the buffer pointer.
void* stage_wait(void* pool, int slot) {
  Pool* p = static_cast<Pool*>(pool);
  std::unique_lock<std::mutex> g(p->mu);
  p->cv.wait(g, [&] { return p->state[slot] == SlotState::READY; });
  return p->buffers[slot];
}

// Return a READY slot to the pool (its buffer may be overwritten after).
void stage_release(void* pool, int slot) {
  Pool* p = static_cast<Pool*>(pool);
  {
    std::lock_guard<std::mutex> g(p->mu);
    p->state[slot] = SlotState::FREE;
  }
  p->cv.notify_all();
}

}  // extern "C"
