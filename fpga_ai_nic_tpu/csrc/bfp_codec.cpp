// Native host-side BFP codec — bit-for-bit identical to the Python golden
// model (fpga_ai_nic_tpu/ops/bfp_golden.py), which is the repo's codec spec
// (derived from hw/bf16_to_bfp_core.sv / hw/bfp_to_bf16_core.sv as
// instantiated by hw/bfp_adapter.sv; see the golden model's docstring).
//
// Role: the host-runtime equivalent of the reference's C++ layer — used for
// checkpoint (de)compression off the hot path and as an independent parity
// check against the numpy/JAX/Pallas implementations in tests.
//
// Build: make -C fpga_ai_nic_tpu/csrc   (produces libbfp_codec.so)
// ABI: plain C, loaded via ctypes (fpga_ai_nic_tpu/runtime/native.py).

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

inline int32_t biased_exp(float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, sizeof(bits));
  return static_cast<int32_t>((bits >> 23) & 0xFF);
}

inline int32_t clampi(int32_t v, int32_t lo, int32_t hi) {
  return v < lo ? lo : (v > hi ? hi : v);
}

}  // namespace

extern "C" {

// rounding: 0 = nearest-even (rintf), 1 = truncate toward zero (rtz).
// n must be a multiple of block. mant: n int8; scale: n/block int8.
void bfp_encode_f32(const float* x, int64_t n, int32_t block,
                    int32_t mant_bits, int32_t rounding, int8_t* mant,
                    int8_t* scale) {
  const float lim = static_cast<float>((1 << (mant_bits - 1)) - 1);
  const int64_t nblocks = n / block;
#pragma omp parallel for schedule(static)
  for (int64_t b = 0; b < nblocks; ++b) {
    const float* xb = x + b * block;
    int32_t emax = 0;
    for (int32_t i = 0; i < block; ++i) {
      int32_t e = biased_exp(xb[i]);
      if (e > emax) emax = e;
    }
    // [-126, 126]: both 2^s and 2^-s stay normal fp32 (see bfp_golden.py)
    int32_t scale_exp = clampi(emax - 127 - (mant_bits - 2), -126, 126);
    const float inv_scale = std::ldexp(1.0f, -scale_exp);
    for (int32_t i = 0; i < block; ++i) {
      float q = xb[i] * inv_scale;
      q = rounding == 0 ? std::rint(q) : std::trunc(q);
      if (q > lim) q = lim;
      if (q < -lim) q = -lim;
      mant[b * block + i] = static_cast<int8_t>(q);
    }
    scale[b] = static_cast<int8_t>(scale_exp);
  }
}

void bfp_decode_f32(const int8_t* mant, const int8_t* scale, int64_t n,
                    int32_t block, float* out) {
  const int64_t nblocks = n / block;
#pragma omp parallel for schedule(static)
  for (int64_t b = 0; b < nblocks; ++b) {
    const float s = std::ldexp(1.0f, static_cast<int32_t>(scale[b]));
    for (int32_t i = 0; i < block; ++i) {
      out[b * block + i] = static_cast<float>(mant[b * block + i]) * s;
    }
  }
}

}  // extern "C"
