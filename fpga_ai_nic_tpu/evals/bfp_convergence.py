"""BFP convergence evaluation — back-compat shim.

The implementation generalized into `evals.codec_convergence` when the
codec subsystem landed (the BFP mantissa sweep is now one slice of the
codec x model matrix); every public name this module historically exported
resolves there unchanged, and the committed artifact
(docs/bfp_convergence.json) keeps its schema.  New code should import
`evals.codec_convergence` directly.
"""

from __future__ import annotations

from .codec_convergence import (  # noqa: F401
    MODELS, codec_error_table, run_comparison, run_comparison_multiseed,
    run_curve)

__all__ = ["MODELS", "run_curve", "run_comparison",
           "run_comparison_multiseed", "codec_error_table"]
