"""Codec convergence evaluation — measured training-quality bounds for
EVERY registered gradient-compression codec, generalizing the BFP-only
eval this module grew out of (`evals.bfp_convergence`, now a thin
back-compat shim over this one).

The reference ships lossy compression with ZERO accuracy evaluation
(readme.pdf §3.3: its own golden compare is expected to FAIL with BFP on).
We measure instead of assert: train the same model through the same
explicit ring, compressed vs uncompressed, and compare final losses.

Isolation discipline (unchanged from the BFP eval): both arms use
``impl='ring'`` (identical hop/add order and bucket plan) and are PAIRED
on common random numbers (identical init + batch stream per seed), so the
final-loss ratio isolates exactly one variable — the wire codec.  For
error-feedback codecs (top-k) the arm also exercises the residual carry
through ``TrainState.codec_state``: the ratio measures compensate-then-
compress as deployed, not the codec in a vacuum.

Entry points:
  run_curve             one arm (codec=None is the uncompressed baseline)
  run_comparison        BFP mantissa sweep (legacy shape, kept byte-
                        compatible for the committed artifact's schema)
  run_codec_comparison  codec x opts sweep — the codec-subsystem eval
  run_comparison_multiseed   multi-seed paired aggregation (BFP)
  codec_error_table     static BFP roundtrip error per mantissa width
  codec_static_table    static per-codec roundtrip error / wire rate
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..models import bert, mlp, resnet
from ..parallel import DDPTrainer, FSDPTrainer, make_mesh
from ..parallel.train import DPTrainer  # noqa: F401 (re-export convenience)
from ..utils.config import (BFPConfig, CollectiveConfig, MeshConfig,
                            MLPConfig, OptimizerConfig, TrainConfig)

# "mlp_fsdp" = the MLP trained under ZeRO-3 with the compressed custom-VJP
# gather (quantized weight all-gather + per-hop-compressed gradient
# reduce-scatter) — the wire trick on EVERY stream, hw/bfp_adapter.sv.
MODELS = ("mlp", "bert", "resnet", "mlp_canonical", "mlp_fsdp")

# the codec arms the subsystem eval sweeps by default: top-k exercises
# error feedback, int8 exercises stochastic rounding; both at their
# registered defaults plus a bucket size small enough that the tiny eval
# models span multiple buckets
DEFAULT_CODECS: Tuple[Tuple[str, Tuple], ...] = (
    ("topk", (("bucket_elems", 256), ("k", 64))),
    ("int8", ()),
)


# ---------------------------------------------------------------------------
# synthetic fixed datasets (cycled; loss must go down for ratios to mean
# anything)
# ---------------------------------------------------------------------------

def _make_batches(model: str, n_batches: int, batch: int, seed: int):
    rng = np.random.default_rng(seed)
    out = []
    if model in ("mlp", "mlp_canonical", "mlp_fsdp"):
        # canonical = the reference benchmark's 2048-wide layers
        # (sw/run.sh:16), depth cut to 3 so the CPU-mesh eval stays cheap
        canonical = model == "mlp_canonical"
        width = 2048 if canonical else 128
        hidden = 2048 if canonical else 256
        n_cls = 128 if canonical else 32
        cfg = MLPConfig(layer_sizes=(width, hidden, hidden, n_cls),
                        dtype="float32")
        for _ in range(n_batches):
            x = jnp.asarray(rng.standard_normal((batch, width)), jnp.float32)
            y = jnp.asarray(rng.integers(0, n_cls, batch), jnp.int32)
            out.append((x, y))
        loss = lambda p, b: mlp.loss_fn(p, b, cfg)  # noqa: E731
        params = mlp.init(jax.random.PRNGKey(seed), cfg)
    elif model == "bert":
        cfg = bert.BertConfig.tiny()
        S = 32
        for _ in range(n_batches):
            toks = rng.integers(1, cfg.vocab, (batch, S)).astype(np.int32)
            labels = np.full((batch, S), -100, np.int32)
            m = rng.random((batch, S)) < 0.15
            m[:, 0] = True
            labels[m] = toks[m]
            toks[m] = 3
            out.append((jnp.asarray(toks), jnp.asarray(labels)))
        loss = lambda p, b: bert.loss_fn(p, b, cfg, dp_axis="dp")  # noqa
        params = bert.init(jax.random.PRNGKey(seed), cfg)
    elif model == "resnet":
        cfg = resnet.ResNetConfig.tiny()
        for _ in range(n_batches):
            x = jnp.asarray(rng.standard_normal((batch, 16, 16, 3)),
                            jnp.float32)
            y = jnp.asarray(rng.integers(0, cfg.num_classes, batch),
                            jnp.int32)
            out.append((x, y))
        loss = lambda p, b: resnet.loss_fn(p, b, cfg, bn_axis="dp")  # noqa
        params = resnet.init(jax.random.PRNGKey(seed), cfg)
    else:
        raise ValueError(model)
    return params, loss, out


# ---------------------------------------------------------------------------
# one training curve
# ---------------------------------------------------------------------------

def run_curve(model: str, steps: int = 200, *, batch: int = 32,
              codec: Optional[str] = None, codec_opts: Tuple = (),
              mantissa_bits: Optional[int] = None, n_dev: int = 8,
              seed: int = 0, record_every: int = 5,
              n_batches: int = 4, tail_k: int = 1,
              trainer: str = "ddp") -> Dict:
    """Train `model` for `steps` on an n_dev mesh through the explicit
    ring.  The arm is selected by ``codec``/``codec_opts`` (registry
    names); ``codec=None`` is the uncompressed baseline, and the legacy
    ``mantissa_bits=m`` spelling still means BFP at that width.  Returns
    {"losses": [...], "final_loss": float, "steps": [...]}, losses recorded
    every `record_every` steps.

    trainer: "ddp" (bucketed all-reduce + replicated optimizer — the
    legacy BFP eval's arm, kept so the committed artifact's semantics are
    unchanged) or "dp" (ZeRO-1 DPTrainer — REQUIRED for error-feedback
    codecs, whose residual threads through TrainState.codec_state; the
    codec comparison uses it for every arm so pairing stays clean).
    ``*_fsdp`` models override either with the ZeRO-3 trainer.

    tail_k: `final_loss` is the mean of the last `tail_k` RECORDED losses
    — a time-averaged endpoint.  Late in training the per-step loss
    wiggles chaotically (two CRN-paired arms differing only in per-hop
    quantization still diverge trajectory-wise), so a single-step
    endpoint ratio measures wiggle phase, not optimization quality; this
    was the round-3 m4-ratio-0.4 anomaly.  tail_k=1 preserves the raw
    endpoint."""
    if mantissa_bits is not None:
        assert codec is None, "pass codec= OR legacy mantissa_bits=, not both"
        codec = "bfp"
        codec_opts = tuple(codec_opts) + (("mantissa_bits", mantissa_bits),)
    fsdp = model.endswith("_fsdp")
    cfg = TrainConfig(
        iters=steps, global_batch=batch,
        mesh=MeshConfig(fsdp=n_dev) if fsdp else MeshConfig(dp=n_dev),
        collective=CollectiveConfig(impl="ring", codec=codec,
                                    codec_opts=tuple(codec_opts),
                                    bucket_elems=1 << 16),
        optimizer=OptimizerConfig(kind="adamw", learning_rate=3e-3))
    params, loss_fn, batches = _make_batches(model, n_batches, batch, seed)
    if fsdp:
        tr = FSDPTrainer(loss_fn, make_mesh(cfg.mesh), cfg)
    elif trainer == "dp":
        tr = DPTrainer(loss_fn, make_mesh(cfg.mesh), cfg)
    else:
        assert trainer == "ddp", trainer
        from ..ops.fused_update import resolve_codec
        c = resolve_codec(cfg.collective)
        assert c is None or not c.error_feedback, (
            "error-feedback codecs need trainer='dp'/'fsdp' (DDPTrainer "
            "does not thread the residual)")
        tr = DDPTrainer(loss_fn, make_mesh(cfg.mesh), cfg)
    state = tr.init_state(params)
    sharded = [tr.shard_batch(b) for b in batches]
    losses: List[float] = []
    rec_steps: List[int] = []
    for i in range(steps):
        state, loss = tr.step(state, sharded[i % len(sharded)])
        if (i + 1) % record_every == 0 or i == steps - 1:
            losses.append(float(loss))
            rec_steps.append(i + 1)
    final = float(np.mean(losses[-max(tail_k, 1):]))
    return {"losses": losses, "steps": rec_steps, "final_loss": final}


# ---------------------------------------------------------------------------
# comparisons (paired on common random numbers)
# ---------------------------------------------------------------------------

def run_comparison(model: str, steps: int = 200, *,
                   mantissa_sweep: Sequence[int] = (8, 6, 4),
                   batch: int = 32, n_dev: int = 8, seed: int = 0,
                   n_batches: int = 4, tail_k: int = 1) -> Dict:
    """Uncompressed baseline + one BFP arm per mantissa width, PAIRED on
    common random numbers: every arm at a given seed shares the identical
    init and batch stream (_make_batches is seeded), so
    `final_loss_ratio` (arm/baseline) is a per-seed paired statistic —
    the only difference inside a pair is per-hop quantization.  The
    regression test bounds it (<= 1.05 at the reference's 8-bit
    config)."""
    out = {"model": model, "steps": steps, "tail_k": tail_k,
           "baseline": run_curve(model, steps, batch=batch, n_dev=n_dev,
                                 seed=seed, n_batches=n_batches,
                                 tail_k=tail_k)}
    base = out["baseline"]["final_loss"]
    for m in mantissa_sweep:
        arm = run_curve(model, steps, batch=batch, mantissa_bits=m,
                        n_dev=n_dev, seed=seed, n_batches=n_batches,
                        tail_k=tail_k)
        arm["final_loss_ratio"] = arm["final_loss"] / base
        out[f"bfp_m{m}"] = arm
    return out


def run_codec_comparison(model: str, steps: int = 200, *,
                         codecs: Sequence[Tuple[str, Tuple]] = DEFAULT_CODECS,
                         batch: int = 32, n_dev: int = 8, seed: int = 0,
                         n_batches: int = 4, tail_k: int = 4) -> Dict:
    """The codec-subsystem convergence eval: uncompressed baseline + one
    arm per (codec, opts), CRN-paired exactly like run_comparison.  Arm
    keys are the codec names (``topk``, ``int8``, ``bfp``...); each arm
    carries its paired ``final_loss_ratio`` plus the codec's static
    description (rate, error bound, EF) for the artifact."""
    from .. import compress
    out: Dict = {"model": model, "steps": steps, "tail_k": tail_k,
                 "pairing": "common-random-numbers",
                 "baseline": run_curve(model, steps, batch=batch,
                                       n_dev=n_dev, seed=seed,
                                       n_batches=n_batches, tail_k=tail_k,
                                       trainer="dp")}
    base = out["baseline"]["final_loss"]
    for name, opts in codecs:
        arm = run_curve(model, steps, batch=batch, codec=name,
                        codec_opts=tuple(opts), n_dev=n_dev, seed=seed,
                        n_batches=n_batches, tail_k=tail_k, trainer="dp")
        arm["final_loss_ratio"] = arm["final_loss"] / base
        arm["codec"] = compress.get_codec(name, dict(opts)).describe()
        out[name] = arm
    return out


def run_comparison_multiseed(model: str, steps: int = 200, *,
                             seeds: Sequence[int] = (0, 1, 2, 3, 4),
                             mantissa_sweep: Sequence[int] = (8, 6, 4),
                             batch: int = 32, n_dev: int = 8,
                             n_batches: int = 4, tail_k: int = 8) -> Dict:
    """`run_comparison` over >= 5 seeds, aggregating the PER-SEED PAIRED
    final-loss ratio (common random numbers within each seed: identical
    init + batch stream across arms; time-averaged endpoints via tail_k).
    The round-3 artifact gated on a 3-sample mean with sigma ~= 40% of
    the mean — no statistical power; pairing was already in place, so the
    variance was endpoint chaos, which tail averaging + 5 seeds
    suppresses.  The regression gate binds on the mean paired ratio AND
    on sigma(paired ratio) being small enough for the mean to carry
    meaning."""
    runs = [run_comparison(model, steps, mantissa_sweep=mantissa_sweep,
                           batch=batch, n_dev=n_dev, seed=s,
                           n_batches=n_batches, tail_k=tail_k)
            for s in seeds]
    out = {"model": model, "steps": steps, "seeds": list(seeds),
           "tail_k": tail_k, "pairing": "common-random-numbers",
           "per_seed": runs}
    for m in mantissa_sweep:
        ratios = [r[f"bfp_m{m}"]["final_loss_ratio"] for r in runs]
        out[f"bfp_m{m}"] = {
            "paired_ratios": ratios,
            "ratio_mean": float(np.mean(ratios)),
            "ratio_std": float(np.std(ratios)),
            "ratio_min": float(np.min(ratios)),
            "ratio_max": float(np.max(ratios)),
        }
    return out


# ---------------------------------------------------------------------------
# static codec error tables (no training)
# ---------------------------------------------------------------------------

def codec_error_table(mantissa_sweep: Sequence[int] = (2, 3, 4, 6, 8),
                      n: int = 1 << 16, seed: int = 0) -> List[Dict]:
    """Roundtrip relative error of one BFP encode/decode pass on N(0,1)
    data per mantissa width — the error a gradient suffers per ring hop."""
    from ..ops import bfp
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)
    rows = []
    for m in mantissa_sweep:
        cfg = dataclasses.replace(BFPConfig(), mantissa_bits=m)
        mant, se = bfp.bfp_encode(x, cfg.block_size, cfg.mantissa_bits,
                                  cfg.rounding)
        y = bfp.bfp_decode(mant, se, cfg.block_size, jnp.float32)
        err = np.asarray(y) - np.asarray(x)
        denom = float(np.linalg.norm(np.asarray(x)))
        rows.append({
            "mantissa_bits": m,
            "rel_l2_error": float(np.linalg.norm(err)) / denom,
            "max_abs_error": float(np.max(np.abs(err))),
            "wire_bytes_per_value": bfp.wire_bytes(n, cfg) / n,
        })
    return rows


def codec_static_table(codecs: Sequence[Tuple[str, Tuple]] = (
        ("bfp", ()),) + DEFAULT_CODECS,
        n: int = 1 << 16, seed: int = 0) -> List[Dict]:
    """One-pass roundtrip error + wire rate per codec on N(0,1) data —
    the per-hop cost/accuracy point each codec occupies.  Top-k's large
    one-shot error here is exactly why it ships with error feedback; the
    training ratio (run_codec_comparison), not this number, is its
    quality metric."""
    from .. import compress
    rng = np.random.default_rng(seed)
    rows = []
    for name, opts in codecs:
        c = compress.get_codec(name, dict(opts))
        n_use = n - n % c.pad_elems
        x = jnp.asarray(rng.standard_normal(n_use), jnp.float32)
        y = np.asarray(c.roundtrip(x))
        err = y - np.asarray(x)
        rows.append(dict(
            c.describe(),
            rel_l2_error=float(np.linalg.norm(err)
                               / np.linalg.norm(np.asarray(x))),
            max_abs_error=float(np.max(np.abs(err))),
            wire_bytes_per_value=c.wire_bytes(n_use) / n_use,
        ))
    return rows
