from . import bfp_convergence

__all__ = ["bfp_convergence"]
