from . import bfp_convergence, codec_convergence  # noqa: F401

__all__ = ["bfp_convergence", "codec_convergence"]
