"""The reference benchmark model: an N-layer fully-connected MLP trained
with softmax cross-entropy (sw/mlp_mpi_example_f32.cpp:492-541 sets up
libxsmm fc fwd/bwd + smax fwd/bwd kernels; canonical config is 10 layers of
2048x2048 f32, sw/run.sh:16).

TPU-first: we do not reimplement libxsmm's blocked GEMM (bn/bk/bc CLI knobs,
sw/mlp_mpi_example_f32.cpp:284-296) — tiling onto the MXU is XLA's job; the
model is plain jnp matmuls with a configurable compute dtype (bf16 keeps
the MXU fed at full rate; f32 matches the reference numerics).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from ..utils.config import MLPConfig

Params = Dict[str, List[jax.Array]]


def init(key: jax.Array, cfg: MLPConfig) -> Params:
    sizes = cfg.layer_sizes
    dtype = jnp.dtype(cfg.dtype)
    ws, bs = [], []
    for i in range(cfg.n_layers):
        key, sub = jax.random.split(key)
        fan_in = sizes[i]
        w = jax.random.normal(sub, (sizes[i], sizes[i + 1]), jnp.float32)
        ws.append((w * jnp.sqrt(2.0 / fan_in)).astype(dtype))
        bs.append(jnp.zeros((sizes[i + 1],), dtype))
    return {"w": ws, "b": bs}


def apply(params: Params, x: jax.Array, cfg: MLPConfig) -> jax.Array:
    """Forward pass -> logits. ReLU between layers, none after the last
    (the reference fuses ReLU masks into its fc kernels; the last layer
    feeds softmax, sw/mlp_mpi_example_f32.cpp:707-728)."""
    dtype = jnp.dtype(cfg.dtype)
    h = x.astype(dtype)
    n = len(params["w"])
    for i, (w, b) in enumerate(zip(params["w"], params["b"])):
        h = h @ w
        if cfg.fuse_bias:
            h = h + b
        if i < n - 1:
            h = jax.nn.relu(h)
    return h


def softmax_xent(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy (ref: libxsmm_dnn_smax_fwd/bwd_exec_f32,
    sw/mlp_mpi_example_f32.cpp:718-728). labels: int class ids [B]."""
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logz, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def loss_fn(params: Params, batch, cfg: MLPConfig) -> jax.Array:
    x, y = batch
    return softmax_xent(apply(params, x, cfg), y)


def flops_per_sample(cfg: MLPConfig) -> float:
    """Reference FLOP accounting: 6*C_i*C_{i+1} per middle layer
    (fwd 2 + bwd 2 + upd 2), 4* for layer 0 (no input-grad GEMM)
    (sw/mlp_mpi_example_f32.cpp:794-798)."""
    sizes = cfg.layer_sizes
    total = 4.0 * sizes[0] * sizes[1]
    for i in range(1, cfg.n_layers):
        total += 6.0 * sizes[i] * sizes[i + 1]
    return total
