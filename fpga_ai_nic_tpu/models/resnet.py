"""ResNet-50 for data-parallel training with the fused collective —
BASELINE.json config 3 ("ResNet-50 DP with fused SGD").

The reference has no conv nets (MLP only, sw/mlp_mpi_example_f32.cpp); this
model exists to exercise the framework's DP + fused scatter-update-gather
path on a conv workload, per the north-star configs.

TPU-first choices:
- NHWC layout + HWIO filters — the layouts XLA lowers to MXU convolutions
  without transposes.
- Batch norm is *sync-BN over the dp axis* in train mode (lax.pmean of
  batch moments inside shard_map): with per-device batches split N ways
  (the reference's MB = global_MB / n_procs, sw/mlp_mpi_example_f32.cpp:301)
  this reproduces single-device numerics exactly.
- Running statistics are not threaded through the gradient step (they are
  non-gradient state; the fused ZeRO-1 update streams one flat *gradient*
  vector, SURVEY.md §3.2).  Eval stats come from `compute_stats`, an EMA
  calibration pass — the standard functional-JAX split.

Functional pytree params, like models.mlp / models.llama.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)   # ResNet-50
    width: int = 64                               # stem / stage-0 bottleneck
    num_classes: int = 1000
    dtype: str = "bfloat16"
    bn_eps: float = 1e-5
    bn_momentum: float = 0.9

    @staticmethod
    def resnet50(dtype: str = "bfloat16") -> "ResNetConfig":
        return ResNetConfig(dtype=dtype)

    @staticmethod
    def tiny(stage_sizes=(1, 1), width=8, num_classes=10,
             dtype="float32") -> "ResNetConfig":
        return ResNetConfig(stage_sizes=tuple(stage_sizes), width=width,
                            num_classes=num_classes, dtype=dtype)


# -- init --------------------------------------------------------------------

def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    w = jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
    return (w * jnp.sqrt(2.0 / fan_in)).astype(dtype)


def _bn_init(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _block_widths(cfg: ResNetConfig, stage: int) -> Tuple[int, int]:
    """(bottleneck width, output width) of a stage."""
    w = cfg.width * (2 ** stage)
    return w, 4 * w


def init(key: jax.Array, cfg: ResNetConfig) -> Dict:
    dt = jnp.dtype(cfg.dtype)
    keys = iter(jax.random.split(key, 4 + 16 * sum(cfg.stage_sizes)))
    params = {
        "stem": {"conv": _conv_init(next(keys), 7, 7, 3, cfg.width, dt),
                 "bn": _bn_init(cfg.width, dt)},
        "stages": [],
    }
    cin = cfg.width
    for s, n_blocks in enumerate(cfg.stage_sizes):
        mid, cout = _block_widths(cfg, s)
        blocks = []
        for b in range(n_blocks):
            blk = {
                "conv1": _conv_init(next(keys), 1, 1, cin, mid, dt),
                "bn1": _bn_init(mid, dt),
                "conv2": _conv_init(next(keys), 3, 3, mid, mid, dt),
                "bn2": _bn_init(mid, dt),
                "conv3": _conv_init(next(keys), 1, 1, mid, cout, dt),
                "bn3": _bn_init(cout, dt),
            }
            if b == 0:
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout, dt)
                blk["proj_bn"] = _bn_init(cout, dt)
            blocks.append(blk)
            cin = cout
        params["stages"].append(blocks)
    params["fc"] = {
        "w": (jax.random.normal(next(keys), (cin, cfg.num_classes),
                                jnp.float32)
              * jnp.sqrt(1.0 / cin)).astype(dt),
        "b": jnp.zeros((cfg.num_classes,), dt),
    }
    return params


# -- batch norm --------------------------------------------------------------

def _bn(x, bn, cfg: ResNetConfig, bn_axis: Optional[str],
        stats: Optional[Dict]):
    """Train mode (stats=None): moments over (N, H, W), pmean'd over bn_axis
    (sync-BN == single-device numerics under dp batch split).  Eval mode:
    use the provided running stats."""
    xf = x.astype(jnp.float32)
    if stats is None:
        mean = jnp.mean(xf, axis=(0, 1, 2))
        m2 = jnp.mean(jnp.square(xf), axis=(0, 1, 2))
        if bn_axis is not None:
            mean = lax.pmean(mean, bn_axis)
            m2 = lax.pmean(m2, bn_axis)
        var = m2 - jnp.square(mean)
    else:
        mean, var = stats["mean"], stats["var"]
    inv = lax.rsqrt(var + cfg.bn_eps)
    out = (xf - mean) * inv
    return (out.astype(x.dtype) * bn["scale"] + bn["bias"])


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


# -- forward -----------------------------------------------------------------

def _forward(params: Dict, x: jax.Array, cfg: ResNetConfig, bn_fn):
    """The single source of truth for the network topology.  ``bn_fn(h, bn)``
    is called once per BN layer, in a fixed visit order (stem, then per block
    bn1..bn3 [+ proj_bn on block 0 of each stage]) — init_stats and
    compute_stats rely on that order."""
    dt = jnp.dtype(cfg.dtype)
    h = _conv(x.astype(dt), params["stem"]["conv"], stride=2)
    h = jax.nn.relu(bn_fn(h, params["stem"]["bn"]))
    h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")
    for s, blocks in enumerate(params["stages"]):
        for b, blk in enumerate(blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            r = _conv(h, blk["conv1"])
            r = jax.nn.relu(bn_fn(r, blk["bn1"]))
            r = _conv(r, blk["conv2"], stride=stride)
            r = jax.nn.relu(bn_fn(r, blk["bn2"]))
            r = _conv(r, blk["conv3"])
            r = bn_fn(r, blk["bn3"])
            if "proj" in blk:
                h = _conv(h, blk["proj"], stride=stride)
                h = bn_fn(h, blk["proj_bn"])
            h = jax.nn.relu(h + r)
    h = jnp.mean(h.astype(jnp.float32), axis=(1, 2))      # global avg pool
    return h.astype(dt) @ params["fc"]["w"] + params["fc"]["b"]


def apply(params: Dict, x: jax.Array, cfg: ResNetConfig, *,
          bn_axis: Optional[str] = None,
          stats: Optional[Dict] = None) -> jax.Array:
    """x: [B, H, W, 3] -> logits [B, num_classes].

    Train mode: stats=None (batch statistics; pass bn_axis="dp" inside
    shard_map for sync-BN).  Eval: pass the stats pytree from compute_stats.
    """
    st = iter(stats["bn"]) if stats is not None else None
    bn_fn = (lambda h, bn: _bn(h, bn, cfg, bn_axis,
                               next(st) if st is not None else None))
    return _forward(params, x, cfg, bn_fn)


def loss_fn(params: Dict, batch, cfg: ResNetConfig, *,
            bn_axis: Optional[str] = None) -> jax.Array:
    """Softmax cross-entropy; batch = (images [B,H,W,3], labels [B])."""
    x, y = batch
    logits = apply(params, x, cfg, bn_axis=bn_axis)
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logz, y[:, None], axis=-1)[:, 0])


# -- eval statistics ---------------------------------------------------------

def init_stats(cfg: ResNetConfig) -> Dict:
    """Zero-initialized running-stats pytree, ordered exactly as the shared
    forward visits BN layers (derived by abstractly tracing _forward, so it
    can never desync from the topology)."""
    chans = []

    def bn_probe(h, bn):
        chans.append(h.shape[-1])
        return h

    params = jax.eval_shape(lambda: init(jax.random.PRNGKey(0), cfg))
    x = jax.ShapeDtypeStruct((1, 32, 32, 3), jnp.float32)
    jax.eval_shape(lambda p, xb: _forward(p, xb, cfg, bn_probe), params, x)
    return {"bn": [{"mean": jnp.zeros((c,), jnp.float32),
                    "var": jnp.ones((c,), jnp.float32)} for c in chans]}


def compute_stats(params: Dict, x: jax.Array, cfg: ResNetConfig,
                  stats: Dict) -> Dict:
    """One EMA calibration step of the running statistics on a batch.
    Runs the shared forward in train mode while capturing each BN's
    moments (same visit order as apply, by construction)."""
    captured = []

    def bn_cap(h, bn):
        hf = h.astype(jnp.float32)
        mean = jnp.mean(hf, axis=(0, 1, 2))
        m2 = jnp.mean(jnp.square(hf), axis=(0, 1, 2))
        st = {"mean": mean, "var": m2 - jnp.square(mean)}
        captured.append(st)
        return _bn(h, bn, cfg, None, st)     # one BN implementation only

    _forward(params, x, cfg, bn_cap)

    m = cfg.bn_momentum
    new_bn = [{"mean": m * old["mean"] + (1 - m) * cap["mean"],
               "var": m * old["var"] + (1 - m) * cap["var"]}
              for old, cap in zip(stats["bn"], captured)]
    return {"bn": new_bn}


def num_params(cfg: ResNetConfig) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(
        jax.eval_shape(lambda: init(jax.random.PRNGKey(0), cfg))))
