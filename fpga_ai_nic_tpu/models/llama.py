"""Llama-3-family decoder, written for explicit mesh parallelism.

BASELINE.json config 5 ("Llama-3 8B ZeRO-1 ... BFP optimizer-state
compression") is the north-star; the reference itself has no transformer —
this model exists to exercise the framework's parallel axes at scale:

- tp: attention heads and FFN hidden are column/row sharded; row-parallel
  projections end in one ``lax.psum`` over the tp axis (Megatron-style,
  expressed directly in the model because shard_map makes collectives
  first-class, the way the reference made its ring explicit in RTL).
- sp: the sequence axis is sharded; attention runs `ops.ring_attention`
  (K/V blocks rotating the ring) and RoPE positions are offset per shard.
- dp/ZeRO-1: handled outside by the trainer (`parallel.sharded`).

Functional pytree params, like models.mlp.  GQA, RMSNorm, SwiGLU, RoPE.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops import moe as moe_ops
from ..ops.ring_attention import (flash_attention_remat, full_attention,
                                  gathered_attention, pallas_route,
                                  ring_attention)


@dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    rope_theta: float = 500000.0
    # Llama-3.1-style NTK rope scaling for context extension (the
    # long-context regime ring attention exists for): frequencies whose
    # wavelength exceeds old_context are stretched by rope_scaling; the
    # high-frequency band is untouched; in between interpolates smoothly.
    # rope_scaling=1.0 disables (exact parity with unscaled rope).
    rope_scaling: float = 1.0
    rope_old_context: int = 8192
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    # flash-blocked single-device attention (ops.ring_attention.
    # flash_attention): score memory O(S * attn_block) instead of
    # full_attention's O(S^2); None keeps the exact direct softmax.
    # sp-sharded paths (ring/gathered) block independently of this knob.
    attn_block: "Optional[int]" = None
    # which flash implementation backs attn_block: "auto" = the fused
    # Pallas kernels on TPU (ops.flash_pallas, custom-vjp backward),
    # XLA-blocked scan elsewhere; "pallas"/"xla" pin one for A/B runs
    attn_impl: str = "auto"
    # MoE: when moe_experts > 0, every FFN becomes a top-k routed expert
    # layer (ops.moe); dense SwiGLU otherwise.  Not composable with the
    # pipelined path yet (apply_pp raises).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def moe(self) -> Optional["moe_ops.MoEConfig"]:
        if self.moe_experts == 0:
            return None
        return moe_ops.MoEConfig(
            num_experts=self.moe_experts, top_k=self.moe_top_k,
            capacity_factor=self.moe_capacity_factor,
            aux_weight=self.moe_aux_weight)

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def tiny(vocab: int = 256, dim: int = 64, n_layers: int = 2,
             n_heads: int = 4, n_kv_heads: int = 2, ffn_dim: int = 128,
             dtype: str = "float32") -> "LlamaConfig":
        return LlamaConfig(vocab=vocab, dim=dim, n_layers=n_layers,
                           n_heads=n_heads, n_kv_heads=n_kv_heads,
                           ffn_dim=ffn_dim, dtype=dtype)


def init(key: jax.Array, cfg: LlamaConfig) -> Dict:
    """Global (unsharded) parameter pytree; shard with param_specs."""
    dt = jnp.dtype(cfg.dtype)
    D, Hd = cfg.dim, cfg.head_dim

    def dense(k, fan_in, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                * jnp.sqrt(1.0 / fan_in)).astype(dt)

    keys = iter(jax.random.split(key, 4 + cfg.n_layers * 7))
    params = {
        "tok_emb": dense(next(keys), D, (cfg.vocab, D)),
        "final_norm": jnp.ones((D,), dt),
        "lm_head": dense(next(keys), D, (D, cfg.vocab)),
        "layers": [],
    }
    for _ in range(cfg.n_layers):
        lyr = {
            "attn_norm": jnp.ones((D,), dt),
            "wq": dense(next(keys), D, (D, cfg.n_heads * Hd)),
            "wk": dense(next(keys), D, (D, cfg.n_kv_heads * Hd)),
            "wv": dense(next(keys), D, (D, cfg.n_kv_heads * Hd)),
            "wo": dense(next(keys), cfg.n_heads * Hd, (cfg.n_heads * Hd, D)),
            "mlp_norm": jnp.ones((D,), dt),
        }
        if cfg.moe is not None:
            lyr["moe"] = moe_ops.init_ffn(next(keys), D, cfg.ffn_dim,
                                          cfg.moe, dtype=dt)
        else:
            lyr.update({
                "w1": dense(next(keys), D, (D, cfg.ffn_dim)),
                "w3": dense(next(keys), D, (D, cfg.ffn_dim)),
                "w2": dense(next(keys), cfg.ffn_dim, (cfg.ffn_dim, D)),
            })
        params["layers"].append(lyr)
    return params


def param_specs(cfg: LlamaConfig, tp_axis: Optional[str] = "tp",
                ep_axis: Optional[str] = None,
                tp_size: Optional[int] = None) -> Dict:
    """PartitionSpecs: Megatron column/row sharding over the tp axis
    (tp_axis=None replicates — for meshes without a tp axis); MoE expert
    weights shard over ep_axis (per-expert hidden over tp, see
    moe_ops.param_specs).

    tp_size: pass the mesh's tp extent when it may exceed n_kv_heads —
    wk/wv then REPLICATE over tp and each rank slices its kv group's head
    inside the block (kv-head replication; Llama-3-8B's 8 kv heads cap
    head-sharded tp at 8, this lifts it to tp = any multiple of n_kv that
    divides n_heads)."""
    col, row, rep = P(None, tp_axis), P(tp_axis, None), P()
    kv = col
    if (tp_axis is not None and tp_size is not None
            and cfg.n_kv_heads % tp_size != 0):
        kv = rep    # kv-head replication: sliced per rank in _block
    layer = {"attn_norm": rep, "wq": col, "wk": kv, "wv": kv, "wo": row,
             "mlp_norm": rep}
    if cfg.moe is not None:
        layer["moe"] = moe_ops.param_specs(cfg.moe, ep_axis, tp_axis)
    else:
        layer.update({"w1": col, "w3": col, "w2": row})
    return {"tok_emb": rep, "final_norm": rep, "lm_head": col,
            "layers": [{k: dict(v) if isinstance(v, dict) else v
                        for k, v in layer.items()}
                       for _ in range(cfg.n_layers)]}


def _rmsnorm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * w


def _rope_freqs(cfg: LlamaConfig, half: int) -> jax.Array:
    """Inverse frequencies, optionally NTK-scaled for context extension
    (Llama-3.1 recipe): wavelengths longer than old_context/low_factor are
    divided by rope_scaling, shorter than old_context/high_factor are
    kept, the band between interpolates linearly in 1/wavelength."""
    freqs = cfg.rope_theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    if cfg.rope_scaling == 1.0:
        return freqs
    wavelen = 2.0 * jnp.pi / freqs
    low = cfg.rope_old_context / cfg.rope_low_freq_factor    # long cutoff
    high = cfg.rope_old_context / cfg.rope_high_freq_factor  # short cutoff
    if cfg.rope_low_freq_factor == cfg.rope_high_freq_factor:
        smooth = jnp.zeros_like(wavelen)
    else:
        # 0 at wavelen == low cutoff (-> fully scaled), 1 at the high
        # cutoff (-> original) — the Llama-3.1 interpolation
        smooth = jnp.clip(
            (cfg.rope_old_context / wavelen - cfg.rope_low_freq_factor)
            / (cfg.rope_high_freq_factor - cfg.rope_low_freq_factor),
            0.0, 1.0)
    scaled = freqs / cfg.rope_scaling
    mid = (1.0 - smooth) * scaled + smooth * freqs
    return jnp.where(wavelen > low, scaled,
                     jnp.where(wavelen < high, freqs, mid))


def _rope(x: jax.Array, pos: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """x: [B, H, S, dh]; pos: [S] global token positions (rotate-half)."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = _rope_freqs(cfg, half)
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]     # [S, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(x.dtype)


def _psum_if(x: jax.Array, axis: Optional[str]) -> jax.Array:
    return lax.psum(x, axis) if axis is not None else x


def _kv_rep_slice(lyr: Dict, cfg: LlamaConfig, tp_axis: str):
    """kv-head replication (tp > n_kv): wk/wv arrive replicated; this
    rank slices the ONE kv head serving its query group (head
    g = r*n_kv//tp — rank r's n_heads/tp query heads all map to it
    because n_kv | tp).  The slice transpose scatter-adds the cotangent
    back into the replicated weight, and vma-typed autodiff inserts the
    tp-psum that ties the replicas — the same mechanism every
    tp-replicated leaf (norms, embeddings) uses.  Shared by training
    (_block) and decode (llama_decode.forward) so the mapping can never
    diverge between them.  Returns (wk, wv) sliced to ONE head."""
    Hd = cfg.head_dim
    tp = lax.axis_size(tp_axis)
    if lyr["wk"].shape[1] != cfg.n_kv_heads * Hd:
        raise ValueError(
            f"tp={tp} > n_kv_heads={cfg.n_kv_heads} needs wk/wv "
            f"REPLICATED over tp (local width {lyr['wk'].shape[1]}, "
            f"expected {cfg.n_kv_heads * Hd}) — pass tp_size to "
            f"param_specs/stacked_param_specs")
    g = (lax.axis_index(tp_axis) * cfg.n_kv_heads) // tp
    wk = lax.dynamic_slice_in_dim(lyr["wk"], g * Hd, Hd, axis=1)
    wv = lax.dynamic_slice_in_dim(lyr["wv"], g * Hd, Hd, axis=1)
    return wk, wv


def _block(lyr: Dict, x: jax.Array, pos: jax.Array, cfg: LlamaConfig,
           n_heads: int, n_kv: int, tp_axis: Optional[str],
           sp_axis: Optional[str], ep_axis: Optional[str] = None,
           batch_axes=(), sp_attn: str = "ring") -> "tuple[jax.Array, jax.Array]":
    """One decoder layer (pre-norm attention + SwiGLU or MoE FFN) on local
    shards; n_heads/n_kv are the per-tp-shard head counts.  Returns
    (x, aux) — aux is the MoE load-balance loss (0 for dense layers)."""
    B, S = x.shape[:2]
    Hd = cfg.head_dim
    h = _rmsnorm(x, lyr["attn_norm"], cfg.norm_eps)
    if n_kv == 0:
        wk, wv = _kv_rep_slice(lyr, cfg, tp_axis)
        n_kv = 1
    else:
        wk, wv = lyr["wk"], lyr["wv"]
    q = (h @ lyr["wq"]).reshape(B, S, n_heads, Hd).transpose(0, 2, 1, 3)
    k = (h @ wk).reshape(B, S, n_kv, Hd).transpose(0, 2, 1, 3)
    v = (h @ wv).reshape(B, S, n_kv, Hd).transpose(0, 2, 1, 3)
    q = _rope(q, pos, cfg)
    k = _rope(k, pos, cfg)
    if n_kv != n_heads:
        # GQA: the fused Pallas kernels take grouped K/V natively (each
        # KV head read once per group — 1/G the KV traffic/memory and,
        # on the sp ring, 1/G the rotated bytes); the XLA paths' einsum
        # math needs the repeat-expanded copy.  Grouped form is only
        # reachable through branches that can route pallas (sp, or
        # attn_block-flash) — full_attention has no kernel path — and
        # the route decision is the same pallas_route(impl, q_shape) the
        # ops make, so the two can't diverge.
        kernel_branch = sp_axis is not None or cfg.attn_block is not None
        if not (kernel_branch
                and pallas_route(cfg.attn_impl, (B, n_heads, S, Hd))):
            rep = n_heads // n_kv
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
    if sp_axis is not None:
        # "gather": KV all-gather variant — the only form sound inside the
        # 1F1B schedulers' stage-divergent conds (ring's ppermute pairs
        # span the whole mesh; see ops.ring_attention.gathered_attention)
        att = (gathered_attention(q, k, v, sp_axis, causal=True,
                                  impl=cfg.attn_impl)
               if sp_attn == "gather"
               else ring_attention(q, k, v, sp_axis, causal=True,
                                   impl=cfg.attn_impl))
    elif cfg.attn_block is not None:
        # memory-bounded single-device attention; the remat/backward
        # choice (fused Pallas kernel vs checkpointed XLA scan) lives in
        # ops.ring_attention.flash_attention_remat
        att = flash_attention_remat(q, k, v, causal=True,
                                    k_block=cfg.attn_block,
                                    impl=cfg.attn_impl)
    else:
        att = full_attention(q, k, v, causal=True)
    att = att.transpose(0, 2, 1, 3).reshape(B, S, n_heads * Hd)
    x = x + _psum_if(att @ lyr["wo"], tp_axis)

    h = _rmsnorm(x, lyr["mlp_norm"], cfg.norm_eps)
    if "moe" in lyr:
        ff, aux = moe_ops.moe_ffn(lyr["moe"], h, cfg.moe, ep_axis=ep_axis,
                                  batch_axes=batch_axes)
    else:
        gate = jax.nn.silu((h @ lyr["w1"]).astype(jnp.float32)).astype(x.dtype)
        ff = (gate * (h @ lyr["w3"])) @ lyr["w2"]
        aux = jnp.float32(0.0)
    return x + _psum_if(ff, tp_axis), aux


def _shard_counts(cfg: LlamaConfig, tp_axis: Optional[str]):
    """Per-rank (n_heads, n_kv) head counts; n_kv == 0 flags kv-head
    replication (tp > n_kv: wk/wv replicate and each rank slices ONE kv
    head — its query group's — inside _block)."""
    n_heads, n_kv = cfg.n_heads, cfg.n_kv_heads
    if tp_axis is not None:
        tp = lax.axis_size(tp_axis)
        if n_heads % tp:
            raise ValueError(f"tp={tp} must divide n_heads={n_heads}")
        n_heads //= tp
        if n_kv % tp == 0:
            n_kv //= tp
        elif tp % n_kv == 0:
            n_kv = 0        # replicated-kv mode: 1 sliced head per rank
        else:
            raise ValueError(
                f"tp={tp} must divide n_kv_heads={cfg.n_kv_heads}, or be a "
                f"multiple of it (kv-head replication)")
    return n_heads, n_kv


def _positions(S: int, sp_axis: Optional[str]) -> jax.Array:
    sp_off = (lax.axis_index(sp_axis) * S) if sp_axis is not None else 0
    return sp_off + lax.broadcasted_iota(jnp.int32, (S, 1), 0)[:, 0]


def apply(params: Dict, tokens: jax.Array, cfg: LlamaConfig, *,
          tp_axis: Optional[str] = None,
          sp_axis: Optional[str] = None,
          ep_axis: Optional[str] = None,
          batch_axes=(),
          gather_logits: bool = True,
          with_aux: bool = False,
          remat: bool = False) -> jax.Array:
    """tokens [B, S_local] -> logits [B, S_local, vocab] (vocab/tp when
    gather_logits=False under tp); (logits, moe_aux) when with_aux.

    Call inside shard_map with params pre-sharded per ``param_specs`` when
    tp_axis is set; sequence shards must be contiguous when sp_axis is set;
    batch_axes lists every token-sharding axis for MoE aux statistics.
    remat rematerializes each decoder block in backward (activation memory
    O(1 block) instead of O(n_layers) at ~1/3 extra FLOPs — the standard
    long-context/deep-model trade; the pipelined path has the same knob).
    """
    B, S = tokens.shape
    n_heads, n_kv = _shard_counts(cfg, tp_axis)
    pos = _positions(S, sp_axis)

    def block(lyr, x):
        return _block(lyr, x, pos, cfg, n_heads, n_kv, tp_axis, sp_axis,
                      ep_axis, batch_axes)

    if remat:
        block = jax.checkpoint(block)

    x = params["tok_emb"][tokens]                       # [B, S, D]
    aux = jnp.float32(0.0)
    for lyr in params["layers"]:
        x, a = block(lyr, x)
        aux = aux + a

    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = x @ params["lm_head"]                      # [B, S, V/tp]
    if tp_axis is not None and gather_logits:
        logits = lax.all_gather(logits, tp_axis, axis=2, tiled=True)
    return (logits, aux) if with_aux else logits


def _vocab_parallel_nll(logits: jax.Array, labels: jax.Array,
                        tp_axis: str) -> jax.Array:
    """Per-token NLL from vocab-sharded logits [B, S, V/tp] without
    gathering — Megatron-style distributed softmax cross-entropy.

    Every reduction over the vocab runs through psum/pmax, so the result is
    tp-invariant: each rank holds ONE copy of the loss and vma-typed
    autodiff counts each rank's logit shard exactly once.  (Computing the
    loss redundantly from all-gathered logits double-counts every gradient
    by a factor of tp — the all_gather transpose sums the identical
    per-rank cotangents.)
    """
    lf = logits.astype(jnp.float32)
    Vl = lf.shape[-1]
    off = lax.axis_index(tp_axis) * Vl
    # stability shift only — it cancels in the softmax gradient, and pmax
    # has no differentiation rule anyway
    m = lax.pmax(lax.stop_gradient(jnp.max(lf, axis=-1)), tp_axis)  # [B, S]
    z = lax.psum(jnp.sum(jnp.exp(lf - m[..., None]), axis=-1), tp_axis)
    local = labels - off
    in_range = (local >= 0) & (local < Vl)
    safe = jnp.clip(local, 0, Vl - 1)
    tgt = jnp.take_along_axis(lf, safe[..., None], axis=-1)[..., 0]
    tgt = lax.psum(jnp.where(in_range, tgt, 0.0), tp_axis)      # [B, S]
    return jnp.log(z) + m - tgt


def _token_nll(logits: jax.Array, safe_labels: jax.Array,
               tp_axis: Optional[str]) -> jax.Array:
    """Per-token NLL [B, S]; logits vocab-sharded when tp_axis is set."""
    if tp_axis is not None:
        return _vocab_parallel_nll(logits, safe_labels, tp_axis)
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logz, safe_labels[..., None], axis=-1)[..., 0]


def _grad_scale(x: jax.Array, n: int) -> jax.Array:
    """Value-preserving gradient scale by n (cancels a trainer's uniform
    /n_dp gradient average)."""
    return lax.stop_gradient(x) + n * (x - lax.stop_gradient(x))


def _weighted_loss(local_sum: jax.Array, count: jax.Array,
                   batch_axes: Tuple[Optional[str], ...],
                   dp_axis: Optional[str]) -> jax.Array:
    """Token-weighted global mean over the token-sharding axes (sp/dp/ep).
    With dp_axis, the gradient carries an n_dp factor that cancels the
    trainer's uniform /n_dp average so the effective update is the true
    global-mean gradient (see loss_fn docstring).

    The loss VALUE is the psum'd global mean, but the gradient path rides
    the LOCAL sum only: per-replica gradient = scale * d(local_sum)/denom
    with no collective on the gradient path, so the result is invariant to
    the jaxlib's psum-transpose convention (the n_dp-scaled-gradient class
    of docs/KNOWN_FAILURES.md #1-4, frozen as graftlint rule J7)."""
    axes = tuple(a for a in batch_axes if a is not None)
    if not axes:
        return local_sum / jnp.maximum(count, 1)
    total = lax.psum(local_sum, axes)
    denom = lax.stop_gradient(
        jnp.maximum(lax.psum(count, axes), 1).astype(jnp.float32))
    loss = lax.stop_gradient(total / denom)
    scale = lax.axis_size(dp_axis) if dp_axis is not None else 1
    return loss + scale * (local_sum
                           - lax.stop_gradient(local_sum)) / denom


def loss_fn(params: Dict, batch, cfg: LlamaConfig, *,
            tp_axis: Optional[str] = None,
            sp_axis: Optional[str] = None,
            dp_axis: Optional[str] = None,
            ep_axis: Optional[str] = None,
            remat: bool = False) -> jax.Array:
    """Next-token cross-entropy.  batch = (tokens, labels), both [B, S_local]
    — labels are the globally-shifted targets (shift crosses sequence-shard
    boundaries, so the data pipeline provides them; -100 entries are
    ignored).

    Pass dp_axis when training under a dp-sharded trainer with masked
    labels: the trainers average gradients uniformly over dp
    (reduce_scatter/n), which mis-weights tokens when shards hold unequal
    valid-token counts.  With dp_axis set, the loss *value* is the exact
    global token-weighted mean and the *gradient* carries an n_dp factor
    that cancels the trainer's /n_dp — so the effective update is the true
    global-mean gradient.  (With uniformly valid labels the two coincide
    and dp_axis may be omitted.)
    """
    tokens, labels = batch
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    batch_axes = (sp_axis, dp_axis, ep_axis)
    logits, aux = apply(params, tokens, cfg, tp_axis=tp_axis,
                        sp_axis=sp_axis, ep_axis=ep_axis,
                        batch_axes=tuple(a for a in batch_axes
                                         if a is not None),
                        gather_logits=False, with_aux=True, remat=remat)
    nll = jnp.where(valid, _token_nll(logits, safe, tp_axis), 0.0)
    loss = _weighted_loss(jnp.sum(nll), jnp.sum(valid), batch_axes, dp_axis)
    if dp_axis is not None:     # same /n_dp cancellation as the ce term
        aux = _grad_scale(aux, lax.axis_size(dp_axis))
    return loss + aux


# -- pipeline-parallel path ---------------------------------------------------


def stack_params(params: Dict) -> Dict:
    """List-of-layers pytree -> stacked [n_layers, ...] leaves, shardable
    over a pp mesh axis (parallel.pipeline layout contract)."""
    from ..parallel import pipeline as pl
    out = dict(params)
    out["layers"] = pl.stack_layers(params["layers"])
    return out


def stacked_param_specs(cfg: LlamaConfig, pp_axis: str = "pp",
                        tp_axis: Optional[str] = "tp",
                        ep_axis: Optional[str] = None,
                        tp_size: Optional[int] = None) -> Dict:
    """PartitionSpecs for stack_params output: the layer stack's leading axis
    shards over pp; within a layer, Megatron col/row over tp (MoE experts
    over ep, hidden over tp); embedding and head replicated over pp (they
    run on every stage, only stage 0 / the last stage contribute
    gradients)."""
    base = param_specs(cfg, tp_axis, ep_axis, tp_size)
    layers = jax.tree_util.tree_map(lambda spec: P(pp_axis, *spec),
                                    base["layers"][0],
                                    is_leaf=lambda x: isinstance(x, P))
    return {"tok_emb": base["tok_emb"], "final_norm": base["final_norm"],
            "lm_head": base["lm_head"], "layers": layers}


def apply_pp(params: Dict, tokens: jax.Array, cfg: LlamaConfig, *,
             pp_axis: str, num_microbatches: int,
             tp_axis: Optional[str] = None,
             sp_axis: Optional[str] = None,
             ep_axis: Optional[str] = None,
             batch_axes=(),
             with_aux: bool = False,
             sp_attn: str = "ring",
             remat: bool = False) -> jax.Array:
    """Pipelined forward; call inside shard_map with stack_params params
    sharded per ``stacked_param_specs``.  Returns logits valid on the LAST
    pp stage only (loss_fn handles the mask; see parallel.pipeline);
    (logits, moe_aux) when with_aux — aux rides the microbatch scan with
    garbage ticks masked (parallel.pipeline.pipeline_apply_aux)."""
    from ..parallel import pipeline as pl

    S = tokens.shape[1]
    n_heads, n_kv = _shard_counts(cfg, tp_axis)
    pos = _positions(S, sp_axis)

    def block(lyr, x):
        return _block(lyr, x, pos, cfg, n_heads, n_kv, tp_axis, sp_axis,
                      ep_axis, batch_axes, sp_attn=sp_attn)

    def stage_fn(stacked, x):
        return pl.scan_layers_aux(block, stacked, x, remat=remat)

    x = params["tok_emb"][tokens]                       # [B, S, D]
    x, aux = pl.pipeline_apply_aux(stage_fn, params["layers"], x,
                                   num_microbatches, pp_axis)
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = x @ params["lm_head"]                      # [B, S, V/tp]
    return (logits, aux) if with_aux else logits


def loss_fn_pp(params: Dict, batch, cfg: LlamaConfig, *,
               pp_axis: str, num_microbatches: int,
               tp_axis: Optional[str] = None,
               sp_axis: Optional[str] = None,
               dp_axis: Optional[str] = None,
               ep_axis: Optional[str] = None,
               sp_attn: str = "ring",
               remat: bool = False) -> jax.Array:
    """Next-token cross-entropy through the pipeline.  Every pp stage
    computes the head on its own (mostly garbage) activations — unavoidable
    under SPMD — so the token NLL sum is psum-masked from the last stage
    before the global token-weighted reduction; gradients flow only through
    real activations.  dp_axis as in loss_fn (masked-label weighting);
    the MoE aux loss rides the microbatch scan (apply_pp with_aux)."""
    from ..parallel import pipeline as pl

    tokens, labels = batch
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    batch_axes = tuple(a for a in (sp_axis, dp_axis, ep_axis)
                       if a is not None)
    logits, aux = apply_pp(params, tokens, cfg, pp_axis=pp_axis,
                           num_microbatches=num_microbatches, tp_axis=tp_axis,
                           sp_axis=sp_axis, ep_axis=ep_axis,
                           batch_axes=batch_axes, with_aux=True,
                           sp_attn=sp_attn, remat=remat)
    if batch_axes:
        # Value-preserving: the per-rank aux copies are identical over the
        # batch axes (moe_ffn psums its statistics over them), but the
        # pipeline scan carry leaves aux TYPED varying.  Without this
        # pmean, adding a varying-typed scalar to the invariant ce loss
        # makes the loss varying, and vma autodiff then seeds one cotangent
        # per rank whose pvary-transpose psum silently multiplies every ce
        # gradient by the axis size.
        aux = lax.pmean(aux, batch_axes)
    nll = jnp.where(valid, _token_nll(logits, safe, tp_axis), 0.0)
    # local-grad variant: this loss is differentiated INSIDE shard_map, so
    # the last-stage mask must not put a psum on the gradient path (J7)
    local_sum = pl.from_last_stage_local_grad(jnp.sum(nll), pp_axis)
    # ep shards the batch alongside dp (ShardedTrainer._bspec), so the
    # token-weighted reduction must span it too — matching loss_fn
    loss = _weighted_loss(local_sum, jnp.sum(valid),
                          (sp_axis, dp_axis, ep_axis), dp_axis)
    if dp_axis is not None:     # same /n_dp cancellation as the ce term
        aux = _grad_scale(aux, lax.axis_size(dp_axis))
    return loss + aux


def loss_and_grads_pp_1f1b(params: Dict, batch, cfg: LlamaConfig, *,
                           pp_axis: str, num_microbatches: int,
                           tp_axis: Optional[str] = None,
                           sp_axis: Optional[str] = None,
                           dp_axis: Optional[str] = None,
                           ep_axis: Optional[str] = None,
                           virtual_stages: int = 1,
                           remat: bool = False):
    """`loss_fn_pp`'s loss AND gradients under the 1F1B schedule
    (parallel.pipeline.pipeline_train_1f1b): O(pp) live activations per
    stage instead of GPipe's O(num_microbatches), gradients produced by
    the explicit fwd/bwd ring — no outer jax.grad.

    Exact-parity construction: the head computes the per-microbatch token
    NLL SUM; the scheduler returns the microbatch MEAN, so M * mean is
    loss_fn_pp's local_sum, fed through the same `_weighted_loss` (and
    its dp gradient-scale contract).  The scheduler seeds d(mean)=1, so
    every gradient is rescaled by d loss/d mean = M * w, where w is
    _weighted_loss's (token-count) linear coefficient.  The embedding is
    differentiated OUTSIDE the schedule via the returned d_x.

    tp composes: _block's tp psums sit inside stage-divergent schedule
    conds, but every participant of a tp group shares one pp stage (and
    therefore one branch), so the rendezvous is uniform — only pp-axis
    collectives are forbidden inside stages.  MoE composes the same way
    (dp/sp routing-stat psums are uniform per stage): each stage's aux
    differentiates through its own seeded loss channel with the
    gradient-scale folded in (aux coefficient 1/(M*w*n_rep), uniform
    post-scale M*w — reproducing loss_fn_pp's ce and _grad_scale(aux)
    gradients exactly; n_rep is the replication of the aux value over
    the non-dp batch axes, whose pmean seed GPipe's autodiff applies),
    while the scheduler's non-differentiated report channel carries the
    RAW nll and aux sums so the displayed loss is reconstructed
    unscaled.  ep composes like tp: the all_to_all expert exchange and
    routing-stat psums sit inside stage-divergent schedule conds, but
    every ep-group member shares one pp stage and therefore one branch;
    expert leaves enter ep-varying (sharded) and keep per-shard
    cotangents, ep-replicated leaves are widened on entry and psum'd
    over ep on exit.

    virtual_stages > 1 selects the INTERLEAVED schedule
    (pipeline.pipeline_train_1f1b_interleaved): each device runs v
    non-adjacent layer chunks, cutting the bubble to 1/v of a full
    stage per warm-up tick.  The stacked layer tree must then be in the
    interleaved (device-major) order — permute it with
    pipeline.interleave_layers before sharding, and map gradients back
    with pipeline.deinterleave_layers; num_microbatches must be a
    multiple of pp.  Returns (loss, grads) with grads matching the
    stack_params pytree; tp/pp-replicated leaves arrive correctly
    psum'd (the scheduler transposes its own entry widening), dp-varying
    leaves stay per-shard for the trainer's manual dp reduction.
    """
    from ..parallel import pipeline as pl

    tokens, labels = batch
    S = tokens.shape[1]
    n_heads, n_kv = _shard_counts(cfg, tp_axis)
    pos = _positions(S, sp_axis)
    M = num_microbatches
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)

    moe = cfg.moe is not None
    batch_axes = tuple(a for a in (sp_axis, dp_axis, ep_axis)
                       if a is not None)

    # the explicit schedulers run stages inside stage-divergent lax.conds,
    # where ring attention's sp ppermutes are unsound (whole-mesh
    # collective-permute pairs); the KV-all-gather variant is the
    # replica-grouped, cond-safe form
    sp_attn = ("gather" if sp_axis is not None
               and lax.axis_size(sp_axis) > 1 else "ring")

    def block(lyr, x):
        return _block(lyr, x, pos, cfg, n_heads, n_kv, tp_axis, sp_axis,
                      ep_axis, batch_axes if moe else (), sp_attn=sp_attn)

    # d loss / d (scheduler mean): _weighted_loss is linear in local_sum
    # with coefficient 1/denom (times the n_dp gradient-scale when dp is
    # on); computed BEFORE the schedule so per-term gradient scales can
    # fold into the differentiated loss channel
    count = jnp.sum(valid)
    axes = batch_axes
    if axes:
        denom = jnp.maximum(lax.psum(count, axes), 1).astype(jnp.float32)
        w = (lax.axis_size(dp_axis) if dp_axis is not None else 1.0) / denom
    else:
        w = 1.0 / jnp.maximum(count, 1).astype(jnp.float32)
    scale = M * w
    # aux's gradient contract: GPipe's aux path is
    # _grad_scale(pmean_batch(psum_pp(sum_m aux)/M), n_dp) — the pmean
    # seeds each shard with 1/(n_dp * n_rep) where n_rep is the product
    # of the NON-dp batch-axis sizes (sp, ep); the grad-scale's n_dp
    # cancels the dp factor, leaving d total/d aux_sm = 1/(M * n_rep)
    # per shard.  The uniform post-scale M*w then requires the fold
    # c = 1/(M * w * n_rep).  (The exit psums over sp/ep for replicated
    # router leaves are identical in both paths, so the SEEDS must
    # match shard-for-shard.)
    n_rep = 1
    for a in batch_axes:
        if a != dp_axis:
            n_rep *= lax.axis_size(a)
    c_aux = 1.0 / jnp.maximum(scale * n_rep, 1e-30)

    def stage_fn(sp, hp, x_in, c_in):
        def blk(lyr, h):
            return block(lyr, h)
        h, aux = pl.scan_layers_aux(blk, sp, x_in, remat=remat)
        if moe:
            return (h, c_aux * aux.astype(jnp.float32),
                    jnp.stack([jnp.sum(h).astype(jnp.float32) * 0.0,
                               aux.astype(jnp.float32)]))
        return h, jnp.sum(h).astype(jnp.float32) * 0.0

    def loss_head_fn(hp, h, c_in):
        safe_mb, valid_mb = c_in
        h = _rmsnorm(h, hp["final_norm"], cfg.norm_eps)
        logits = h @ hp["lm_head"]
        nll = jnp.where(valid_mb, _token_nll(logits, safe_mb, tp_axis), 0.0)
        nll_sum = jnp.sum(nll)              # SUM — weighting applied below
        if moe:
            return nll_sum, jnp.stack([nll_sum,
                                       nll_sum.astype(jnp.float32) * 0.0])
        return nll_sum

    x, emb_vjp = jax.vjp(lambda e: e[tokens], params["tok_emb"])
    head_params = {"final_norm": params["final_norm"],
                   "lm_head": params["lm_head"]}
    v = virtual_stages
    if v > 1:
        # interleaved layout: the local [L/pp] shard splits into v chunks,
        # chunk c being global virtual stage c*pp + s — the GLOBAL stack
        # must be permuted with pipeline.interleave_layers OUTSIDE the
        # shard_map (gradients return in the same interleaved order)
        layer_chunks = jax.tree_util.tree_map(
            lambda a: a.reshape((v, a.shape[0] // v) + a.shape[1:]),
            params["layers"])

        def run_sched(*a, **kw2):
            return pl.pipeline_train_1f1b_interleaved(
                *a, virtual_stages=v, **kw2)
    else:
        layer_chunks = params["layers"]
        run_sched = pl.pipeline_train_1f1b
    if moe:
        obj_mean, d_layers, d_hp, d_x, report = run_sched(
            stage_fn, loss_head_fn, layer_chunks, head_params,
            x, (safe, valid), M, pp_axis, report_len=2)
        # display from the RAW report: weighted ce + aux_total (value
        # identity of _grad_scale; gradient already folded into obj)
        loss = (_weighted_loss(report[0], count, batch_axes, dp_axis)
                + report[1] / M)
    else:
        mean_nll_sum, d_layers, d_hp, d_x = run_sched(
            stage_fn, loss_head_fn, layer_chunks, head_params,
            x, (safe, valid), M, pp_axis)
        local_sum = M * mean_nll_sum
        loss = _weighted_loss(local_sum, count, batch_axes, dp_axis)
    if v > 1:
        d_layers = jax.tree_util.tree_map(
            lambda a: a.reshape((-1,) + a.shape[2:]), d_layers)
    d_emb, = emb_vjp(d_x.astype(x.dtype))
    # tok_emb is replicated over axes its cotangent may still vary over
    # (sp-sharded tokens feed a replicated table; GPipe's vma autodiff
    # inserts this psum automatically, the explicit path does it here)
    extra = tuple(sorted(set(jax.typeof(d_emb).vma)
                         - set(jax.typeof(params["tok_emb"]).vma)))
    if extra:
        d_emb = lax.psum(d_emb, extra)
    grads = {"tok_emb": d_emb, "final_norm": d_hp["final_norm"],
             "lm_head": d_hp["lm_head"], "layers": d_layers}
    grads = jax.tree_util.tree_map(
        lambda g2: g2.astype(jnp.float32) * scale, grads)
    return loss, grads


def num_params(cfg: LlamaConfig) -> int:
    D, Hd = cfg.dim, cfg.head_dim
    if cfg.moe is not None:
        ffn = D * cfg.moe_experts + 3 * cfg.moe_experts * D * cfg.ffn_dim
    else:
        ffn = 3 * D * cfg.ffn_dim
    per_layer = (2 * D + D * cfg.n_heads * Hd + 2 * D * cfg.n_kv_heads * Hd
                 + cfg.n_heads * Hd * D + ffn)
    return cfg.vocab * D * 2 + D + cfg.n_layers * per_layer


def active_params(cfg: LlamaConfig) -> int:
    """Parameters a TOKEN's matmuls actually touch: for MoE, only the
    top_k routed experts' FFN weights count (plus the router), so the
    6*P*tokens/s FLOP model stays honest — num_params would overstate
    MoE FLOPs by num_experts/top_k on the FFN term.  Equal to num_params
    for dense configs."""
    if cfg.moe is None:
        return num_params(cfg)
    D = cfg.dim
    all_ffn = 3 * cfg.moe_experts * D * cfg.ffn_dim
    active_ffn = 3 * cfg.moe_top_k * D * cfg.ffn_dim
    return num_params(cfg) - cfg.n_layers * (all_ffn - active_ffn)
