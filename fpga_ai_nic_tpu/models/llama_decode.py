"""Incremental (KV-cache) decoding for the Llama family.

The reference is a training-only system (SURVEY.md: an MLP trainer with a
hardware all-reduce; no inference path exists to mirror), but a framework
whose flagship model is a decoder owes its users generation.  TPU-first
shape of the problem:

- **Static shapes everywhere.**  The cache is allocated at ``max_seq`` up
  front and written with ``dynamic_update_slice``; attention always scores
  against the full cache with an ``iota <= pos`` mask.  Nothing recompiles
  as the sequence grows — the XLA contract (one trace, one binary) that
  data-dependent cache growth would break.
- **The decode loop is a ``lax.scan``** over generated positions: one
  compiled program for the whole generation, host round-trip free.
- **tp composes** exactly as in training: heads shard over tp, the cache
  shards with them ([B, n_kv/tp, max_seq, hd] per rank), and the same
  row-parallel psum closes each block (call inside shard_map with
  ``llama.param_specs`` shardings).  kv-head replication (tp > n_kv)
  works the same way training's does (llama._block): wk/wv arrive
  replicated, each rank slices the ONE kv head serving its query group,
  and the cache holds that single head per rank — a config that trains
  can always generate.

Layer-stack params use the same pytree as ``llama.init``; weights trained
by any trainer in `parallel/` drop straight in.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import llama
from .llama import LlamaConfig


def kv_local_heads(cfg: LlamaConfig, tp_size: int = 1) -> int:
    """Per-rank KV head count: n_kv/tp, or 1 under kv-head replication
    (tp > n_kv — each rank slices the ONE head serving its query group)."""
    if cfg.n_kv_heads % tp_size == 0:
        return cfg.n_kv_heads // tp_size
    if tp_size % cfg.n_kv_heads == 0:
        return 1                      # replicated-kv: one sliced head/rank
    raise ValueError(
        f"tp={tp_size} must divide n_kv_heads={cfg.n_kv_heads}, or be "
        f"a multiple of it (kv-head replication)")


def init_cache(cfg: LlamaConfig, batch: int, max_seq: int, *,
               tp_size: int = 1, dtype=None) -> List[Dict]:
    """Per-layer K/V cache [B, kv_local, max_seq, head_dim], zero-filled;
    kv_local = n_kv/tp, or 1 under kv-head replication (tp > n_kv).

    HBM cost caveat: the WHOLE [B, kv_local, max_seq, hd] extent is
    allocated and zero-filled up front, per layer, per K and V — a batch
    of short sequences pays for max_seq anyway, and B concurrent
    sequences cannot share a byte.  That is the right trade for a single
    fixed-shape generate() call; it is the wrong one for a serving plane
    multiplexing thousands of requests (see `serve.paged.init_pool` +
    `forward_paged`: one shared page pool, per-sequence page tables,
    docs/PERF.md "Serving" for the measured comparison)."""
    kv_local = kv_local_heads(cfg, tp_size)
    dt = jnp.dtype(dtype or cfg.dtype)
    shape = (batch, kv_local, max_seq, cfg.head_dim)
    return [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            for _ in range(cfg.n_layers)]


def _cached_attend(q, ck, cv, pos, n_heads, n_kv, sm_scale):
    """q: [B,H,T,hd] (T = tokens this call, ending at position pos+T-1);
    ck/cv: [B,Hkv,Smax,hd] cache AFTER this call's keys were written.
    Scores the full static cache with a two-sided mask: key j visible to
    query t iff j <= pos + t (causal) and j < pos + T (written).

    ``pos`` is a scalar (whole batch at one position — the generate()
    path) or a [B] vector (each sequence at its own position — the
    serving plane's continuous-batching decode, where slots advance
    independently).  The scalar path is untouched: a uniform [B] vector
    computes the identical mask, so the two agree bitwise."""
    B, H, T, hd = q.shape
    Smax = ck.shape[2]
    # GQA via a grouped einsum — the cache is read ONCE per kv head
    # instead of jnp.repeat materializing a G-times copy every decode
    # step (decode is cache-bandwidth-bound, so the repeat was a direct
    # G-times throughput tax).  G == 1 (MHA) takes the same path with
    # identical contractions.
    G = n_heads // n_kv
    qg = q.astype(jnp.float32).reshape(B, n_kv, G, T, hd)
    s = jnp.einsum("bkgtd,bkjd->bkgtj", qg, ck.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    j = lax.broadcasted_iota(jnp.int32, (T, Smax), 1)
    t = lax.broadcasted_iota(jnp.int32, (T, Smax), 0)
    pos = jnp.asarray(pos, jnp.int32)
    if pos.ndim == 0:
        visible = j <= (pos + t)                   # causal + written bound
        s = jnp.where(visible[None, None, None], s, jnp.float32(-1e30))
    else:                                          # per-sequence positions
        visible = j[None] <= (pos[:, None, None] + t[None])  # [B,T,Smax]
        s = jnp.where(visible[:, None, None], s, jnp.float32(-1e30))
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgtj,bkjd->bkgtd", p, cv.astype(jnp.float32))
    return out.reshape(B, H, T, hd)


def forward(params: Dict, tokens: jax.Array, cache: List[Dict],
            pos: jax.Array, cfg: LlamaConfig, *,
            tp_axis: Optional[str] = None
            ) -> Tuple[jax.Array, List[Dict]]:
    """Run ``tokens [B, T]`` (their global positions are pos..pos+T-1)
    through the decoder, reading and extending the cache.

    T is static: call once with the whole prompt (prefill), then with
    T == 1 per generated token.  Returns (logits [B, T, vocab], cache').
    pos is a traced scalar — one compiled program serves every step.
    """
    B, T = tokens.shape
    Hd = cfg.head_dim
    n_heads, n_kv = llama._shard_counts(cfg, tp_axis)
    kv_rep = n_kv == 0
    if kv_rep:
        # kv-head replication (tp > n_kv), same mechanism as training
        # (llama._block): wk/wv arrive replicated over tp; each rank
        # slices the ONE kv head serving its query group and caches just
        # that head
        n_kv = 1
    sm_scale = Hd ** -0.5
    positions = pos + llama._positions(T, None)

    x = params["tok_emb"][tokens]
    new_cache: List[Dict] = []
    for lyr, c in zip(params["layers"], cache):
        if kv_rep:
            wk, wv = llama._kv_rep_slice(lyr, cfg, tp_axis)
        else:
            wk, wv = lyr["wk"], lyr["wv"]
        h = llama._rmsnorm(x, lyr["attn_norm"], cfg.norm_eps)
        q = (h @ lyr["wq"]).reshape(B, T, n_heads, Hd).transpose(0, 2, 1, 3)
        k = (h @ wk).reshape(B, T, n_kv, Hd).transpose(0, 2, 1, 3)
        v = (h @ wv).reshape(B, T, n_kv, Hd).transpose(0, 2, 1, 3)
        q = llama._rope(q, positions, cfg)
        k = llama._rope(k, positions, cfg)
        ck = lax.dynamic_update_slice(c["k"], k.astype(c["k"].dtype),
                                      (0, 0, pos, 0))
        cv = lax.dynamic_update_slice(c["v"], v.astype(c["v"].dtype),
                                      (0, 0, pos, 0))
        new_cache.append({"k": ck, "v": cv})
        att = _cached_attend(q, ck, cv, pos, n_heads, n_kv, sm_scale)
        att = att.astype(x.dtype).transpose(0, 2, 1, 3).reshape(
            B, T, n_heads * Hd)
        x = x + llama._psum_if(att @ lyr["wo"], tp_axis)

        h = llama._rmsnorm(x, lyr["mlp_norm"], cfg.norm_eps)
        if "moe" in lyr:
            from ..ops import moe as moe_ops
            ff, _ = moe_ops.moe_ffn(lyr["moe"], h, cfg.moe)
        else:
            gate = jax.nn.silu((h @ lyr["w1"]).astype(jnp.float32)
                               ).astype(x.dtype)
            ff = (gate * (h @ lyr["w3"])) @ lyr["w2"]
        x = x + llama._psum_if(ff, tp_axis)

    x = llama._rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = x @ params["lm_head"]                  # [B, T, V/tp]
    if tp_axis is not None:
        logits = lax.all_gather(logits, tp_axis, axis=2, tiled=True)
    return logits, new_cache


def _rope_rows(x: jax.Array, pos: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """Rotate-half rope with PER-SEQUENCE positions: x [B,H,T,dh],
    pos [B,T] global positions.  Same formula as llama._rope (which
    takes one shared [T] vector); a row-constant grid runs the identical
    elementwise ops, so the two agree bitwise — the parity seam between
    generate()'s uniform batch and the serving plane's mixed-position
    decode."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = llama._rope_freqs(cfg, half)
    ang = pos.astype(jnp.float32)[:, :, None] * freqs[None, None, :]
    cos, sin = jnp.cos(ang)[:, None], jnp.sin(ang)[:, None]  # [B,1,T,half]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(x.dtype)


def forward_paged(params: Dict, tokens: jax.Array, pool: List[Dict],
                  page_table: jax.Array, pos: jax.Array, cfg: LlamaConfig,
                  *, page_size: int, tp_axis: Optional[str] = None,
                  active: Optional[jax.Array] = None,
                  attend_impl: str = "reference"
                  ) -> Tuple[jax.Array, List[Dict]]:
    """Paged-KV forward — the serving plane's decode path.

    ``tokens [R, T]``: R request slots, T tokens each (T == 1 for decode,
    T == chunk for chunked prefill); ``pos [R]``: each slot's global
    position for its first token this call; ``pool``: per-layer
    ``{"k","v"}`` pages ``[n_pages, kv_local, page_size, hd]`` shared by
    every slot (``serve.paged.init_pool``); ``page_table [R, P]`` int32:
    ``page_table[r, i]`` is the pool page holding slot r's positions
    ``[i*page_size, (i+1)*page_size)``; ``active [R]`` bool (None = all)
    gates K/V writes — empty slots write zeros into the reserved null
    page 0 and their logits are garbage the host ignores.

    Bit-parity contract (pinned by tests/test_serve.py): for the same
    token stream and chunk schedule, with Smax == P*page_size, logits
    are BITWISE identical to ``forward()`` over the contiguous
    ``init_cache`` — for ANY page assignment, even into a dirty
    (recycled) pool.  Unwritten/garbage positions sit behind the same
    -1e30 mask in both paths; their exact-zero softmax weights multiply
    the garbage away in f32 (0 * finite == ±0, and a ±0 term never moves
    an f32 sum).

    Every shape is static in (R, T, P, page_size): admissions, evictions
    and page re-assignments change VALUES only, so a jitted step is
    trace-stable across any admit/evict schedule (frozen as graftlint
    J10).

    ``attend_impl`` picks how the pool is scored: ``"reference"``
    (default) materializes the gathered view below — the portable XLA
    path and the bitwise oracle; ``"pallas"`` runs
    `ops.paged_attend_pallas.paged_gather_attend`, which walks the page
    table and DMAs live pages HBM->VMEM inside the kernel instead.  The
    two are bitwise-identical on a given backend
    (tests/test_paged_attend.py), so the contract above holds for
    both."""
    if attend_impl not in ("reference", "pallas"):
        raise ValueError(
            f"forward_paged: unknown attend_impl={attend_impl!r}; "
            "expected 'reference' or 'pallas'")
    R, T = tokens.shape
    Hd = cfg.head_dim
    P = page_table.shape[1]
    n_heads, n_kv = llama._shard_counts(cfg, tp_axis)
    kv_rep = n_kv == 0
    if kv_rep:
        n_kv = 1
    sm_scale = Hd ** -0.5
    pos = jnp.asarray(pos, jnp.int32)
    pos_grid = pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    # scatter coordinates for this call's K/V rows: (page, in-page
    # offset) per (slot, token); the page index is clamped defensively —
    # the scheduler's bound is pos + T <= P*page_size for active slots,
    # and inactive slots sit at pos 0 in the null page
    page_of = jnp.take_along_axis(
        page_table, jnp.minimum(pos_grid // page_size, P - 1), axis=1)
    if active is None:
        act = jnp.ones((R,), bool)
    else:
        act = jnp.asarray(active, bool)
    # two classes of writes must be REDIRECTED to the null page, not
    # merely value-masked — their clamped/aliased page index would land
    # in a LIVE page otherwise: (a) inactive slots, whose table row may
    # hold a co-resident's pages; (b) positions beyond the table's range
    # (a final prefill chunk's zero-padding when pos+T overruns
    # P*page_size — the clamp above would alias them onto the LAST live
    # page and corrupt its K/V at the same in-page offsets)
    in_range = pos_grid < P * page_size
    page_of = jnp.where(act[:, None] & in_range, page_of, 0)
    flat_pages = page_of.reshape(-1)
    flat_offs = (pos_grid % page_size).reshape(-1)
    gate = act[:, None, None, None]

    x = params["tok_emb"][tokens]
    new_pool: List[Dict] = []
    for lyr, pl in zip(params["layers"], pool):
        if kv_rep:
            wk, wv = llama._kv_rep_slice(lyr, cfg, tp_axis)
        else:
            wk, wv = lyr["wk"], lyr["wv"]
        h = llama._rmsnorm(x, lyr["attn_norm"], cfg.norm_eps)
        q = (h @ lyr["wq"]).reshape(R, T, n_heads, Hd).transpose(0, 2, 1, 3)
        k = (h @ wk).reshape(R, T, n_kv, Hd).transpose(0, 2, 1, 3)
        v = (h @ wv).reshape(R, T, n_kv, Hd).transpose(0, 2, 1, 3)
        q = _rope_rows(q, pos_grid, cfg)
        k = _rope_rows(k, pos_grid, cfg)
        dt = pl["k"].dtype
        # inactive slots write zeros (all aimed at the null page, so the
        # duplicate scatter indices all carry the same value and the
        # result is deterministic regardless of write order)
        kw = jnp.where(gate, k, 0).astype(dt).transpose(0, 2, 1, 3)
        vw = jnp.where(gate, v, 0).astype(dt).transpose(0, 2, 1, 3)
        pk = pl["k"].at[flat_pages, :, flat_offs, :].set(
            kw.reshape(R * T, n_kv, Hd))
        pv = pl["v"].at[flat_pages, :, flat_offs, :].set(
            vw.reshape(R * T, n_kv, Hd))
        new_pool.append({"k": pk, "v": pv})
        if attend_impl == "pallas":
            # Pallas gather-attend: the gathered view is never formed —
            # the kernel walks page_table and DMAs each LIVE page
            # HBM->VMEM, so decode bytes/token follow the live KV
            # rather than the allocated page span (docs/SERVING.md).
            from ..ops import paged_attend_pallas as _paged_pallas
            att = _paged_pallas.paged_gather_attend(
                q, pk, pv, page_table, pos, page_size=page_size,
                sm_scale=sm_scale)
        else:
            # reference: gather each slot's paged view
            # [R, kv, P*page_size, hd] — the array forward() reads
            # straight out of the contiguous cache.  XLA materializes
            # it; bytes scale with the ALLOCATED span, which is why
            # this stays the portable oracle rather than the fast path.
            ck = pk[page_table].transpose(0, 2, 1, 3, 4).reshape(
                R, n_kv, P * page_size, Hd)
            cv = pv[page_table].transpose(0, 2, 1, 3, 4).reshape(
                R, n_kv, P * page_size, Hd)
            att = _cached_attend(q, ck, cv, pos, n_heads, n_kv, sm_scale)
        att = att.astype(x.dtype).transpose(0, 2, 1, 3).reshape(
            R, T, n_heads * Hd)
        x = x + llama._psum_if(att @ lyr["wo"], tp_axis)

        h = llama._rmsnorm(x, lyr["mlp_norm"], cfg.norm_eps)
        if "moe" in lyr:
            from ..ops import moe as moe_ops
            ff, _ = moe_ops.moe_ffn(lyr["moe"], h, cfg.moe)
        else:
            gate_act = jax.nn.silu((h @ lyr["w1"]).astype(jnp.float32)
                                   ).astype(x.dtype)
            ff = (gate_act * (h @ lyr["w3"])) @ lyr["w2"]
        x = x + llama._psum_if(ff, tp_axis)

    x = llama._rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = x @ params["lm_head"]                  # [R, T, V/tp]
    if tp_axis is not None:
        logits = lax.all_gather(logits, tp_axis, axis=2, tiled=True)
    return logits, new_pool


def generate(params: Dict, prompt: jax.Array, n_new: int,
             cfg: LlamaConfig, *, max_seq: Optional[int] = None,
             tp_axis: Optional[str] = None,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Greedy (temperature=0) or sampled generation.

    prompt: [B, S0] int32.  Returns [B, S0 + n_new].  One prefill call
    plus one scanned decode program; everything stays on device.
    """
    B, S0 = prompt.shape
    if n_new <= 0:
        return prompt
    max_seq = max_seq or (S0 + n_new)
    assert max_seq >= S0 + n_new, (max_seq, S0, n_new)
    tp = lax.axis_size(tp_axis) if tp_axis is not None else 1
    cache = init_cache(cfg, B, max_seq, tp_size=tp)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    logits, cache = forward(params, prompt, cache, jnp.int32(0), cfg,
                            tp_axis=tp_axis)

    def pick(logits_last, key):
        if temperature == 0.0:
            return jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logits_last.astype(jnp.float32) / temperature,
            axis=-1).astype(jnp.int32)

    first = pick(logits[:, -1], rng)

    def step(carry, key):
        tok, cache, pos = carry
        logits, cache = forward(params, tok[:, None], cache, pos, cfg,
                                tp_axis=tp_axis)
        nxt = pick(logits[:, -1], key)
        return (nxt, cache, pos + 1), tok

    keys = jax.random.split(jax.random.fold_in(rng, 1), max(n_new - 1, 1))
    (last, _, _), toks = lax.scan(step, (first, cache, jnp.int32(S0)),
                                  keys[:n_new - 1])
    out = jnp.concatenate([prompt, toks.T, last[:, None]], axis=1)
    return out
