from . import bert  # noqa: F401
from . import mlp  # noqa: F401
from . import llama  # noqa: F401
from . import resnet  # noqa: F401
from . import llama_decode  # noqa: F401
