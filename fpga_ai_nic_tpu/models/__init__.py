from . import mlp  # noqa: F401
