"""BERT-family bidirectional encoder with a masked-LM head.

BASELINE.json config 4 ("BERT-base DP bucketed ring all-reduce") is the
target: the model itself is plain data-parallel (no internal collectives);
its role is to exercise the bucketed gradient all-reduce path
(`ops.bucketed` + `parallel.ddp.DDPTrainer`) on a transformer whose layer
structure produces the many medium-sized gradient tensors that bucketing
exists for — the reference's per-layer all-reduce issue
(sw/mlp_mpi_example_f32.cpp:753-756) at transformer scale.

Architecture: post-LN encoder, learned positions, GELU FFN, tied MLM
decoder (logits through tok_emb^T), padding masked via ``pad_id``.
Functional pytree params like models.mlp / models.llama.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG = jnp.float32(-1e30)


@dataclass(frozen=True)
class BertConfig:
    vocab: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_pos: int = 512
    pad_id: int = 0
    norm_eps: float = 1e-12
    dtype: str = "bfloat16"
    # attention backend: "auto" = the fused Pallas flash kernels on TPU
    # when shapes tile (padding mask rides the kernels' key_bias
    # channel), XLA softmax elsewhere; "pallas"/"xla" pin for A/B
    attn_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def bert_base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny(vocab: int = 256, dim: int = 64, n_layers: int = 2,
             n_heads: int = 4, ffn_dim: int = 128, max_pos: int = 64,
             dtype: str = "float32") -> "BertConfig":
        return BertConfig(vocab=vocab, dim=dim, n_layers=n_layers,
                          n_heads=n_heads, ffn_dim=ffn_dim, max_pos=max_pos,
                          dtype=dtype)


def init(key: jax.Array, cfg: BertConfig) -> Dict:
    dt = jnp.dtype(cfg.dtype)
    D = cfg.dim

    def dense(k, fan_in, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                * jnp.sqrt(1.0 / fan_in)).astype(dt)

    def ln():
        return {"g": jnp.ones((D,), dt), "b": jnp.zeros((D,), dt)}

    keys = iter(jax.random.split(key, 4 + cfg.n_layers * 6))
    params = {
        "tok_emb": dense(next(keys), D, (cfg.vocab, D)),
        "pos_emb": dense(next(keys), D, (cfg.max_pos, D)),
        "emb_norm": ln(),
        "layers": [],
        "mlm_dense": dense(next(keys), D, (D, D)),
        "mlm_norm": ln(),
        "mlm_bias": jnp.zeros((cfg.vocab,), dt),
    }
    for _ in range(cfg.n_layers):
        params["layers"].append({
            "wq": dense(next(keys), D, (D, D)),
            "wk": dense(next(keys), D, (D, D)),
            "wv": dense(next(keys), D, (D, D)),
            "wo": dense(next(keys), D, (D, D)),
            "attn_norm": ln(),
            "w1": dense(next(keys), D, (D, cfg.ffn_dim)),
            "w2": dense(next(keys), cfg.ffn_dim, (cfg.ffn_dim, D)),
            "ffn_norm": ln(),
        })
    return params


def _layernorm(x: jax.Array, p: Dict, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps)).astype(x.dtype) * p["g"] + p["b"]


def apply(params: Dict, tokens: jax.Array, cfg: BertConfig,
          attention_mask: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] -> MLM logits [B, S, vocab].

    attention_mask: [B, S] bool/int, 1 = attend; derived from
    ``tokens != pad_id`` when omitted.
    """
    B, S = tokens.shape
    if S > cfg.max_pos:
        # JAX's clamping gather would silently repeat pos_emb[max_pos-1]
        raise ValueError(f"sequence length {S} exceeds max_pos={cfg.max_pos}")
    H, Hd = cfg.n_heads, cfg.head_dim
    if attention_mask is None:
        attention_mask = tokens != cfg.pad_id
    mask_bool = attention_mask.astype(bool)              # [B, S]
    key_bias2d = jnp.where(mask_bool, jnp.float32(0), _NEG)      # [B, S]
    from ..ops.ring_attention import pallas_route
    use_flash = pallas_route(cfg.attn_impl, (B, H, S, Hd))
    if not use_flash:
        key_bias = key_bias2d[:, None, None, :]          # [B, 1, 1, S]

    pos = lax.broadcasted_iota(jnp.int32, (S, 1), 0)[:, 0]
    x = params["tok_emb"][tokens] + params["pos_emb"][pos]
    x = _layernorm(x, params["emb_norm"], cfg.norm_eps)

    scale = Hd ** -0.5
    for lyr in params["layers"]:
        q = (x @ lyr["wq"]).reshape(B, S, H, Hd).transpose(0, 2, 1, 3)
        k = (x @ lyr["wk"]).reshape(B, S, H, Hd).transpose(0, 2, 1, 3)
        v = (x @ lyr["wv"]).reshape(B, S, H, Hd).transpose(0, 2, 1, 3)
        if use_flash:
            # the padding mask rides the fused kernels' key_bias channel
            from ..ops import flash_pallas
            att = flash_pallas.flash_attention(
                q, k, v, causal=False, sm_scale=scale,
                key_bias=key_bias2d)
        else:
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                           preferred_element_type=jnp.float32) * scale
            p = jax.nn.softmax(s + key_bias, axis=-1)
            att = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
        att = att.astype(x.dtype).transpose(0, 2, 1, 3).reshape(B, S, -1)
        x = _layernorm(x + att @ lyr["wo"], lyr["attn_norm"], cfg.norm_eps)

        h = jax.nn.gelu((x @ lyr["w1"]).astype(jnp.float32)).astype(x.dtype)
        x = _layernorm(x + h @ lyr["w2"], lyr["ffn_norm"], cfg.norm_eps)

    h = jax.nn.gelu((x @ params["mlm_dense"]).astype(jnp.float32)
                    ).astype(x.dtype)
    h = _layernorm(h, params["mlm_norm"], cfg.norm_eps)
    return h @ params["tok_emb"].T + params["mlm_bias"]   # tied decoder


def loss_fn(params: Dict, batch, cfg: BertConfig, *,
            dp_axis: Optional[str] = None) -> jax.Array:
    """Masked-LM cross-entropy.  batch = (tokens, labels), labels [B, S]
    with -100 on unmasked positions (standard MLM convention).

    dp_axis: as in models.llama.loss_fn — under a dp trainer that averages
    gradients uniformly (mean over dp), masked-token counts differ per
    shard; with dp_axis set the loss value is the exact global
    token-weighted mean and the gradient carries the n_dp factor that
    cancels the trainer's /n_dp.
    """
    tokens, labels = batch
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    logits = apply(params, tokens, cfg)
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logz, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    local_sum = jnp.sum(nll)
    count = jnp.sum(valid)
    if dp_axis is None:
        return local_sum / jnp.maximum(count, 1)
    total = lax.psum(local_sum, dp_axis)
    denom = lax.stop_gradient(
        jnp.maximum(lax.psum(count, dp_axis), 1).astype(jnp.float32))
    loss = total / denom
    n_dp = lax.axis_size(dp_axis)
    # Gradient path rides the LOCAL sum only: the per-replica gradient is
    # n_dp * d(local_sum)/denom by construction, so a trainer's uniform
    # sum/n_dp recovers the exact global token-weighted gradient — and no
    # collective sits on the gradient path, so the result cannot depend on
    # which psum-transpose convention (identity vs psum) the jaxlib uses.
    # The previous formulation differentiated through psum(local_sum) and
    # inherited exactly that convention: on jaxlibs whose transpose is a
    # psum, every replica's gradient came out n_dp x the reference (the
    # 8x-learning-rate bug of docs/KNOWN_FAILURES.md #1-2), frozen as
    # graftlint rule J7.
    return lax.stop_gradient(loss) + (
        n_dp * (local_sum - lax.stop_gradient(local_sum)) / denom)


def num_params(cfg: BertConfig) -> int:
    D = cfg.dim
    per_layer = 4 * D * D + 2 * D * cfg.ffn_dim + 4 * D
    head = D * D + 2 * D + cfg.vocab
    return (cfg.vocab * D + cfg.max_pos * D + 2 * D
            + cfg.n_layers * per_layer + head)
