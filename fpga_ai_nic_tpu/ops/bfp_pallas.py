"""Pallas TPU kernels for the BFP codec.

The reference implements the codec as a fully-pipelined RTL datapath:
exponent max-tree (hw/max_u.sv), per-lane barrel shift
(hw/barrel_shifter.sv), two's-complement pack (hw/bf16_to_bfp_core.sv:109),
and an LZC-based renormalizing decoder (hw/bfp_to_bf16_core.sv).  On TPU the
same dataflow maps onto the VPU: the kernel views the flat vector as
(tiles, block_size, 128) so each *lane column* of a (block_size, 128) tile
is one BFP block — the block max is a sublane reduction, and shift/round
becomes a scale-multiply (the "sublane" layout of ops.bfp_golden, which is
the bit-level spec these kernels must match; see tests/test_bfp_pallas.py).

Fusing encode (exponent extract -> block max -> scale -> round -> int8) into
one VMEM pass matters because the codec sits on the collective's critical
path: at HBM-bandwidth ~1 byte/flop there is no headroom for the 4+
materialized intermediates the XLA version produces.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import compat

LANES = 128
_DEF_TILES = 64  # (64, 16, 128) f32 tiles = 512 KiB per grid step in VMEM


def _is_tpu() -> bool:
    return jax.devices()[0].platform in ("tpu", "axon")


def _bcast_blocks(small, block_size, broadcast):
    """(T, 128) -> (T*B, 128) with each row repeated B times consecutively.

    "repeat": jnp.repeat on sublanes.  "reshape": broadcast through a 3D
    register view — (T,1,128) -> (T,B,128) -> (T*B,128); whether Mosaic
    lowers one better than the other is an on-hardware question
    (tools/codec_kernel_probe.py A/Bs them); both are bit-identical
    (tests/test_bfp_pallas.py)."""
    assert broadcast in ("repeat", "reshape"), broadcast
    T = small.shape[0]
    if broadcast == "reshape":
        return jnp.broadcast_to(small[:, None, :], (T, block_size, LANES)
                                ).reshape(T * block_size, LANES)
    return jnp.repeat(small, block_size, axis=0)


def _encode_kernel(x_ref, mant_ref, scale_ref, *, block_size, mantissa_bits,
                   rounding, broadcast="repeat"):
    # refs are 2D (T*B, 128) so every operand/result sits in NATIVE tiles —
    # f32 (8,128), int8 (32,128); a 3D (T, B=16, 128) int8 block would leave
    # each row-group half a native int8 tile and force packed relayouts on
    # every store.  The block view exists only on registers.
    x = x_ref[:]                                   # (T*B, 128) f32
    T = x.shape[0] // block_size
    bits = pltpu.bitcast(x, jnp.uint32)
    e = jnp.right_shift(bits, 23).astype(jnp.int32) & 0xFF
    emax = jnp.max(e.reshape(T, block_size, LANES), axis=1)   # (T, 128)
    scale_e = jnp.clip(emax - 127 - (mantissa_bits - 2), -126, 126)
    inv = pltpu.bitcast(((127 - scale_e) << 23).astype(jnp.uint32),
                        jnp.float32)               # 2.0**-scale_e, exact
    q = x * _bcast_blocks(inv, block_size, broadcast)
    q = jnp.round(q) if rounding == "nearest" else jnp.trunc(q)
    lim = float(2 ** (mantissa_bits - 1) - 1)
    mant_ref[:] = jnp.clip(q, -lim, lim).astype(jnp.int8)
    scale_ref[:] = scale_e.astype(jnp.int8)


def _decode_kernel(mant_ref, scale_ref, out_ref, *, block_size,
                   broadcast="repeat"):
    m = mant_ref[:].astype(jnp.float32)            # (T*B, 128)
    se = scale_ref[:].astype(jnp.int32)            # (T, 128)
    scale = pltpu.bitcast(((se + 127) << 23).astype(jnp.uint32), jnp.float32)
    out_ref[:] = m * _bcast_blocks(scale, block_size, broadcast)


def _grid(n_tiles: int, block_size: int, tiles_per_step: int):
    t = min(tiles_per_step, n_tiles)
    while n_tiles % t:
        t -= 1
    return t, n_tiles // t


def bfp_encode_inline(x: jax.Array, block_size: int = 16,
                      mantissa_bits: int = 8, rounding: str = "nearest",
                      interpret: Optional[bool] = None,
                      tiles_per_step: int = _DEF_TILES,
                      broadcast: str = "repeat"
                      ) -> Tuple[jax.Array, jax.Array]:
    """Flat f32/bf16 [N] (N % (block*128) == 0) -> (int8 [N], int8 [N/block])
    in the "sublane" layout (bit-identical to
    ``bfp_golden.bfp_encode(..., layout="sublane")``).

    Un-jitted entry for callers already inside jit/shard_map (a nested
    closed_call trips the vma checker); ``bfp_encode`` is the jitted
    public wrapper."""
    if interpret is None:
        interpret = not _is_tpu()
    n = x.shape[0]
    assert n % (block_size * LANES) == 0, (n, block_size * LANES)
    x2 = x.astype(jnp.float32).reshape(-1, LANES)       # (tiles*B, 128)
    n_tiles = x2.shape[0] // block_size
    t, steps = _grid(n_tiles, block_size, tiles_per_step)
    kern = functools.partial(_encode_kernel, block_size=block_size,
                             mantissa_bits=mantissa_bits, rounding=rounding,
                             broadcast=broadcast)
    mant, scale = pl.pallas_call(
        kern,
        grid=(steps,),
        in_specs=[pl.BlockSpec((t * block_size, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((t * block_size, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            compat.shape_dtype_struct(x2.shape, jnp.int8, vma=jax.typeof(x2).vma),
            compat.shape_dtype_struct((n_tiles, LANES), jnp.int8,
                                 vma=jax.typeof(x2).vma),
        ],
        interpret=interpret,
    )(x2)
    return mant.reshape(n), scale.reshape(n // block_size)


bfp_encode = functools.partial(jax.jit, static_argnames=(
    "block_size", "mantissa_bits", "rounding", "interpret",
    "tiles_per_step", "broadcast"))(bfp_encode_inline)


def bfp_decode_inline(mant: jax.Array, scale: jax.Array,
                      block_size: int = 16, dtype=jnp.float32,
                      interpret: Optional[bool] = None,
                      tiles_per_step: int = _DEF_TILES,
                      broadcast: str = "repeat") -> jax.Array:
    if interpret is None:
        interpret = not _is_tpu()
    n = mant.shape[0]
    m2 = mant.reshape(-1, LANES)
    s2 = scale.reshape(-1, LANES)
    t, steps = _grid(s2.shape[0], block_size, tiles_per_step)
    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_size=block_size,
                          broadcast=broadcast),
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((t * block_size, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t * block_size, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=compat.shape_dtype_struct(
            m2.shape, jnp.float32,
            vma=jax.typeof(m2).vma | jax.typeof(s2).vma),
        interpret=interpret,
    )(m2, s2)
    return out.reshape(n).astype(dtype)


bfp_decode = functools.partial(jax.jit, static_argnames=(
    "block_size", "dtype", "interpret", "tiles_per_step", "broadcast"))(
        bfp_decode_inline)
