"""Pallas TPU kernels for the BFP codec.

The reference implements the codec as a fully-pipelined RTL datapath:
exponent max-tree (hw/max_u.sv), per-lane barrel shift
(hw/barrel_shifter.sv), two's-complement pack (hw/bf16_to_bfp_core.sv:109),
and an LZC-based renormalizing decoder (hw/bfp_to_bf16_core.sv).  On TPU the
same dataflow maps onto the VPU: the kernel views the flat vector as
(tiles, block_size, 128) so each *lane column* of a (block_size, 128) tile
is one BFP block — the block max is a sublane reduction, and shift/round
becomes a scale-multiply (the "sublane" layout of ops.bfp_golden, which is
the bit-level spec these kernels must match; see tests/test_bfp_pallas.py).

Fusing encode (exponent extract -> block max -> scale -> round -> int8) into
one VMEM pass matters because the codec sits on the collective's critical
path: at HBM-bandwidth ~1 byte/flop there is no headroom for the 4+
materialized intermediates the XLA version produces.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
_DEF_TILES = 64  # (64, 16, 128) f32 tiles = 512 KiB per grid step in VMEM


def _is_tpu() -> bool:
    return jax.devices()[0].platform in ("tpu", "axon")


def _encode_kernel(x_ref, mant_ref, scale_ref, *, mantissa_bits, rounding):
    x = x_ref[:]                                   # (T, B, 128) f32
    bits = pltpu.bitcast(x, jnp.uint32)
    e = jnp.right_shift(bits, 23).astype(jnp.int32) & 0xFF
    emax = jnp.max(e, axis=1, keepdims=True)       # (T, 1, 128)
    scale_e = jnp.clip(emax - 127 - (mantissa_bits - 2), -126, 126)
    inv = pltpu.bitcast(((127 - scale_e) << 23).astype(jnp.uint32),
                        jnp.float32)               # 2.0**-scale_e, exact
    q = x * inv
    q = jnp.round(q) if rounding == "nearest" else jnp.trunc(q)
    lim = float(2 ** (mantissa_bits - 1) - 1)
    mant_ref[:] = jnp.clip(q, -lim, lim).astype(jnp.int8)
    scale_ref[:] = scale_e[:, 0, :].astype(jnp.int8)


def _decode_kernel(mant_ref, scale_ref, out_ref):
    m = mant_ref[:].astype(jnp.float32)            # (T, B, 128)
    se = scale_ref[:].astype(jnp.int32)[:, None, :]
    scale = pltpu.bitcast(((se + 127) << 23).astype(jnp.uint32), jnp.float32)
    out_ref[:] = m * scale


def _grid(n_tiles: int, block_size: int, tiles_per_step: int):
    t = min(tiles_per_step, n_tiles)
    while n_tiles % t:
        t -= 1
    return t, n_tiles // t


@functools.partial(jax.jit, static_argnames=(
    "block_size", "mantissa_bits", "rounding", "interpret", "tiles_per_step"))
def bfp_encode(x: jax.Array, block_size: int = 16, mantissa_bits: int = 8,
               rounding: str = "nearest", interpret: Optional[bool] = None,
               tiles_per_step: int = _DEF_TILES
               ) -> Tuple[jax.Array, jax.Array]:
    """Flat f32/bf16 [N] (N % (block*128) == 0) -> (int8 [N], int8 [N/block])
    in the "sublane" layout (bit-identical to
    ``bfp_golden.bfp_encode(..., layout="sublane")``)."""
    if interpret is None:
        interpret = not _is_tpu()
    n = x.shape[0]
    assert n % (block_size * LANES) == 0, (n, block_size * LANES)
    x3 = x.astype(jnp.float32).reshape(-1, block_size, LANES)
    t, steps = _grid(x3.shape[0], block_size, tiles_per_step)
    kern = functools.partial(_encode_kernel, mantissa_bits=mantissa_bits,
                             rounding=rounding)
    mant, scale = pl.pallas_call(
        kern,
        grid=(steps,),
        in_specs=[pl.BlockSpec((t, block_size, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((t, block_size, LANES), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x3.shape, jnp.int8),
            jax.ShapeDtypeStruct((x3.shape[0], LANES), jnp.int8),
        ],
        interpret=interpret,
    )(x3)
    return mant.reshape(n), scale.reshape(n // block_size)


@functools.partial(jax.jit, static_argnames=(
    "block_size", "dtype", "interpret", "tiles_per_step"))
def bfp_decode(mant: jax.Array, scale: jax.Array, block_size: int = 16,
               dtype=jnp.float32, interpret: Optional[bool] = None,
               tiles_per_step: int = _DEF_TILES) -> jax.Array:
    if interpret is None:
        interpret = not _is_tpu()
    n = mant.shape[0]
    m3 = mant.reshape(-1, block_size, LANES)
    s2 = scale.reshape(-1, LANES)
    t, steps = _grid(m3.shape[0], block_size, tiles_per_step)
    out = pl.pallas_call(
        _decode_kernel,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((t, block_size, LANES), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t, block_size, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(m3.shape, jnp.float32),
        interpret=interpret,
    )(m3, s2)
    return out.reshape(n).astype(dtype)
