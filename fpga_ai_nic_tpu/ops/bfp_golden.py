"""Numpy golden model of the BFP (block-floating-point) codec.

Bit-for-bit the specification that every other implementation in this repo
(JAX `ops.bfp`, Pallas `ops.bfp_pallas`, native C++ `csrc/bfp_codec.cpp`)
must match.  The reference has no such golden model — its RTL sim golden
compare is documented to FAIL when BFP is enabled (readme.pdf §3.3); we fix
that by making the codec itself the spec.

Semantics (derived from the reference RTL, not translated from it):
the encoder (hw/bf16_to_bfp_core.sv:30-132 as instantiated by
hw/bfp_adapter.sv:134 with MANTISSA_SIZE=24, then truncated to MANT_SIZE=8
at hw/bfp_adapter.sv:150) quantizes each block of ``block_size`` fp32 values
against the block's maximum biased exponent ``emax``:

    scale_exp = emax - 127 - (mantissa_bits - 2)      # int8 two's complement
    q_i       = round_mode(x_i * 2**(-scale_exp))     # fits in [-127, 127]
    x̂_i      = q_i * 2**(scale_exp)                  # decode

For mantissa_bits=8 this is scale_exp = emax - 133: the block maximum lands
in [64, 127], exactly the reference's layout (implicit-1 at bit 6, one bit
of headroom so the two's-complement negation cannot overflow —
hw/bf16_to_bfp_core.sv:109,125).  The decoder (hw/bfp_to_bf16_core.sv:30-125)
renormalizes via leading-zero count; in value terms it is exactly
``q * 2**scale_exp``, which is what we implement.

Deviations from the RTL (deliberate, documented):
- zero/denormal inputs decode to exactly 0 (the RTL feeds {1'b1, frac} even
  for exp=0, so an all-tiny block would decode garbage — known-bug class,
  see SURVEY.md §5 "known bugs"; we do not replicate it).
- rounding="nearest" (ties-to-even) is offered in addition to the RTL's
  truncation ("rtz"); nearest is the default because it halves the expected
  quantization error at identical wire cost.
- storage is (int8 mantissa, int8 scale_exp) rather than the RTL's biased
  uint8 shared exponent; scale_exp = shared_biased - 133 is a relabeling,
  wire size is identical (8 bits per block either way).  The RTL's NX_MODE
  parameter (hw/bf16_to_bfp_core.sv:34,100: report emax-6 instead of emax)
  is another constant relabeling of the same field, so it is subsumed —
  both conventions decode to identical values.
"""

from __future__ import annotations

import numpy as np


LANES = 128  # TPU vector-register lane count (the "sublane" layout's stride)


def _to_blocks(x: np.ndarray, block_size: int, layout: str) -> np.ndarray:
    """Partition into [n_blocks, block_size].

    layout="flat16":  consecutive elements form a block — the reference's
      grouping (one 512-bit beat of 16 fp32, hw/bfp_adapter.sv:129-131).
    layout="sublane": elements stride LANES apart form a block — the TPU
      hardware word: in a (block_size, 128) vector tile each *lane column*
      is one block, so the block max is a sublane reduction on the VPU.
      Used by the Pallas kernel (ops/bfp_pallas.py); same rate, same error
      bounds, different partition.  Scale order: block (tile b, lane l) is
      at index b*LANES + l.
    """
    if layout == "flat16":
        return _split_blocks(x, block_size)
    if layout == "sublane":
        if x.ndim != 1 or x.shape[0] % (block_size * LANES) != 0:
            raise ValueError(
                f"sublane layout needs a flat vector divisible by "
                f"{block_size * LANES}, got {x.shape}")
        return x.reshape(-1, block_size, LANES).transpose(0, 2, 1).reshape(
            -1, block_size)
    raise ValueError(layout)


def _from_blocks(blocks: np.ndarray, shape, block_size: int,
                 layout: str) -> np.ndarray:
    """Inverse of _to_blocks: back to the original element order/shape.
    flat16 keeps leading batch dims ([..., nb, bs]); sublane is flat-only."""
    if layout == "flat16":
        return blocks.reshape(shape)
    return blocks.reshape(-1, LANES, block_size).transpose(0, 2, 1).reshape(
        shape)


def _split_blocks(x: np.ndarray, block_size: int) -> np.ndarray:
    if x.shape[-1] % block_size != 0:
        raise ValueError(f"last dim {x.shape[-1]} not a multiple of block {block_size}")
    return x.reshape(*x.shape[:-1], x.shape[-1] // block_size, block_size)


def biased_exponent(x: np.ndarray) -> np.ndarray:
    """IEEE-754 biased exponent field of fp32 values (0..255)."""
    bits = np.asarray(x, np.float32).view(np.uint32)
    return ((bits >> 23) & 0xFF).astype(np.int32)


def bfp_encode(x: np.ndarray, block_size: int = 16, mantissa_bits: int = 8,
               rounding: str = "nearest", layout: str = "flat16"):
    """Encode fp32/bf16 array -> (mantissas int8 [x.shape], scale_exp int8
    [n/B]).  Value of element i in block b is ``mant[i] * 2.0**scale_exp[b]``.
    Mantissas keep the input element order for every layout; only the
    block *membership* (and hence the scale array order) depends on layout.
    """
    x = np.asarray(x, np.float32)
    xb = _to_blocks(x, block_size, layout)
    emax = biased_exponent(xb).max(axis=-1)
    scale_exp = emax - 127 - (mantissa_bits - 2)
    # [-126, 126]: int8-storable, exactly representable as a NORMAL fp32 on
    # both encode (2^-s) and decode (2^s) sides — +-127 would need a
    # subnormal reciprocal, which exponent-bitcast implementations (Pallas,
    # C++) cannot form.  Blocks of subnormals quantize to 0.
    scale_exp = np.clip(scale_exp, -126, 126).astype(np.int32)
    inv_scale = np.ldexp(np.float32(1.0), -scale_exp).astype(np.float32)
    q = xb * inv_scale[..., None]
    if rounding == "nearest":
        q = np.rint(q)
    elif rounding == "rtz":
        q = np.trunc(q)
    else:
        raise ValueError(rounding)
    lim = float(2 ** (mantissa_bits - 1) - 1)
    q = np.clip(q, -lim, lim)
    mant = _from_blocks(q.astype(np.int8), x.shape, block_size, layout)
    return mant, scale_exp.astype(np.int8)


def bfp_decode(mant: np.ndarray, scale_exp: np.ndarray, block_size: int = 16,
               dtype=np.float32, layout: str = "flat16") -> np.ndarray:
    """Decode (int8 mantissas, int8 per-block scale exponents) -> float array."""
    mb = _to_blocks(np.asarray(mant, np.int8), block_size, layout)
    scale = scale_exp.astype(np.int32)
    if layout == "sublane":
        scale = scale.reshape(-1)
    x = mb.astype(np.float32) * np.ldexp(np.float32(1.0), scale)[..., None]
    return _from_blocks(x, mant.shape, block_size, layout).astype(dtype)


def max_abs_error_bound(x: np.ndarray, block_size: int = 16,
                        mantissa_bits: int = 8) -> np.ndarray:
    """Per-element worst-case |x - decode(encode(x))| bound.

    One half ULP of the block grid for nearest, one ULP for rtz; callers
    asserting the bound should pick the mode's factor.  Returns the grid
    spacing 2**scale_exp per element (the "rtz" bound; halve for nearest).
    """
    xb = _split_blocks(np.asarray(x, np.float32), block_size)
    emax = biased_exponent(xb).max(axis=-1)
    scale_exp = np.clip(emax - 127 - (mantissa_bits - 2), -126, 126)
    grid = np.ldexp(np.float32(1.0), scale_exp)
    return np.broadcast_to(grid[..., None], xb.shape).reshape(x.shape)


def wire_bits(n_elems: int, block_size: int = 16, mantissa_bits: int = 8) -> int:
    """Bits on the wire for n_elems values (ref frame: 136b per 16 fp32,
    hw/bfp_adapter.sv:76 BFP_SIZE = EXP_SIZE + NUM_FP*MANT_SIZE)."""
    assert n_elems % block_size == 0
    return (n_elems // block_size) * (8 + block_size * mantissa_bits)
