"""Pallas TPU flash-attention: fused fwd + bwd kernels.

Round-4 verdict, weak #4: `ops.ring_attention.flash_attention` is
XLA-*blocked* attention — a lax.scan over k-blocks whose per-block
score/exp intermediates XLA materializes in HBM between fusions, leaving
llama MFU in the low 30s and S=16,384 at 0.036.  The fix is the same move
as round 4's fused ring collective: stop asking XLA to schedule what one
kernel should own.  Here the entire online-softmax accumulation for a
q-block lives in VMEM scratch across the k-block grid axis — scores,
exps, and rescales never touch HBM, and the backward recomputes p from
the saved logsumexp instead of saving O(S^2/k_block) residuals.

Kernel layout (one flash unit per (batch*head, q-block)):

  fwd   grid (BH, nq, nk)  k-axis sequential; scratch carries the
        running max m, normalizer l (as (block_q, 128) broadcast
        columns) and the f32 output accumulator; the final k step
        normalizes and writes out + lse = m + log l.
  dq    grid (BH, nq, nk)  recompute p = exp(s - lse); ds = p*(dp - D)
        with D = rowsum(dO*O) precomputed outside; accumulate dq.
  dkv   grid (BH, nk, nq)  transposed recomputation (s^T = k q^T) so the
        per-q-row lse/D broadcast along lanes for free; accumulate
        dk, dv.

Causal blocks strictly above the diagonal are skipped with `pl.when`
(the compute never issues; the same dead-beat elision the ring FSM gets
by construction, hw/all_reduce.sv:923-987 — the reference itself has no
attention, SURVEY.md §5).

Numerics: bf16 inputs feed the MXU natively with f32 accumulation
(preferred_element_type); p stays f32 through the PV/dV matmuls, so
results match the XLA path (`ring_attention._attend_chunk`) up to f32
reassociation only — enforced by tests/test_flash_pallas.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import compat

LANES = 128
_NEG = -1e30
_DEF_BLOCK = 512


def _is_tpu() -> bool:
    return jax.devices()[0].platform in ("tpu", "axon")


def _bias_spec(H: int, block_k: int, k_grid_dim: int):
    """BlockSpec for the (B, Sk) key-bias operand: batch row b // H of the
    collapsed BH grid axis, k-block from grid dim `k_grid_dim` — the ONE
    definition all three kernels share (the fwd/dq grids put the k axis
    at dim 2, the transposed dkv grid at dim 1; hand-copying the lambda
    between them is exactly the wrong-dimension trap this helper
    removes)."""
    def index_map(b, *grid):
        return (b // H, grid[k_grid_dim - 1])
    return pl.BlockSpec((1, block_k), index_map)


def _pick_block(S: int, want: int) -> int:
    """Largest divisor of S that is <= want (and a lane multiple when
    possible) — smaller blocks cost grid steps, never correctness."""
    want = min(want, S)
    for b in range(want, 0, -1):
        if S % b == 0 and (b % LANES == 0 or b == S or b < LANES):
            return b
    return S


def _vma(*arrs):
    out = set()
    for a in arrs:
        out |= set(jax.typeof(a).vma)
    return frozenset(out)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(off_ref, q_ref, k_ref, v_ref, *rest, sm_scale, causal,
                block_q, block_k, nk, has_bias):
    # off_ref: SMEM [2] int32 — (q_offset, k_offset) GLOBAL positions of
    # this call's first q row / k row.  (0, 0) for whole-sequence
    # attention; nonzero when the caller attends a local q shard against
    # a visiting K/V chunk (ring / gathered sequence parallelism) and
    # causality must follow global token positions.
    # has_bias compiles in an additive per-key bias row (B, Sk) — the
    # padding-mask path (models/bert.py key_bias); absent, the operand
    # and its load/add cost do not exist.
    if has_bias:
        bias_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    iq, ik = pl.program_id(1), pl.program_id(2)
    q0, k0 = off_ref[0], off_ref[1]

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[0]                                   # (bq, dh) native dtype
        k = k_ref[0]
        # bf16 x bf16 -> f32 runs the MXU at native rate; products are
        # exact, accumulation f32 (same math as casting inputs to f32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if has_bias:
            s = s + bias_ref[:]                        # (1, bk) broadcast
        if causal:
            qpos = (q0 + iq * block_q
                    + lax.broadcasted_iota(jnp.int32, s.shape, 0))
            kpos = (k0 + ik * block_k
                    + lax.broadcasted_iota(jnp.int32, s.shape, 1))
            s = jnp.where(kpos > qpos, _NEG, s)
        m_prev = m_scr[:, :1]                          # (bq, 1)
        l_prev = l_scr[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                         # (bq, bk) f32
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # blocks strictly above the diagonal see only masked scores: skip
        # (the diagonal block itself still computes, with the mask above)
        pl.when(k0 + ik * block_k
                <= q0 + iq * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        safe = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe).astype(o_ref.dtype)
        # fully-masked rows (possible when a causal chunk sits entirely in
        # the future) keep lse = _NEG + 0: exp(lse - anything) underflows
        # to 0, so logsumexp-merging such a chunk is a no-op — exactly
        # the semantics the ring hop needs
        lse = m_scr[:, :1] + jnp.log(safe)             # (bq, 1)
        lse_ref[0] = lse[:, 0]                         # (bq,)


def _fwd(q3, k3, v3, off, bias, n_heads, sm_scale, causal, block_q,
         block_k, interpret):
    """q3: (BH, Sq, dh), k3/v3: (BH/G, Sk, dh) for GQA group size G
    (G = 1 = multi-head), off: (2,) i32, bias: None | (B, Sk) f32
    (B = BH/n_heads) -> (out (BH,Sq,dh), lse (BH,Sq) f32).

    GQA rides the index maps alone: grid step b (a query head) reads KV
    row b // G, so grouped K/V are never materialized per query head —
    1/G the KV HBM traffic and memory of the repeat-then-attend form."""
    BH, Sq, dh = q3.shape
    Sk = k3.shape[1]
    G = BH // k3.shape[0]
    nq, nk = Sq // block_q, Sk // block_k
    has_bias = bias is not None
    vma = _vma(q3, k3, v3, off, *([bias] if has_bias else []))
    kern = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                             block_q=block_q, block_k=block_k, nk=nk,
                             has_bias=has_bias)
    H = n_heads
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b // G, j, 0)),
        pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b // G, j, 0)),
    ]
    args = [off, q3, k3, v3]
    if has_bias:
        in_specs.append(_bias_spec(H, block_k, k_grid_dim=2))
        args.append(bias)
    out, lse = pl.pallas_call(
        kern,
        grid=(BH, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            compat.shape_dtype_struct((BH, Sq, dh), q3.dtype, vma=vma),
            compat.shape_dtype_struct((BH, Sq), jnp.float32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),   # normalizer
            pltpu.VMEM((block_q, dh), jnp.float32),      # output acc
        ],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               *rest, sm_scale, causal, block_q, block_k, nk, has_bias):
    if has_bias:
        bias_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
    iq, ik = pl.program_id(1), pl.program_id(2)
    q0, k0 = off_ref[0], off_ref[1]

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if has_bias:
            s = s + bias_ref[:]
        lse_col = lse_ref[0].reshape(block_q, 1)       # (bq, 1)
        p = jnp.exp(s - lse_col)
        if causal:
            qpos = (q0 + iq * block_q
                    + lax.broadcasted_iota(jnp.int32, s.shape, 0))
            kpos = (k0 + ik * block_k
                    + lax.broadcasted_iota(jnp.int32, s.shape, 1))
            p = jnp.where(kpos > qpos, 0.0, p)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        delta_col = delta_ref[0].reshape(block_q, 1)
        ds = p * (dp - delta_col) * sm_scale           # (bq, bk) f32
        dq_scr[:] = dq_scr[:] + lax.dot(
            ds, k.astype(jnp.float32), preferred_element_type=jnp.float32)

    if causal:
        pl.when(k0 + ik * block_k
                <= q0 + iq * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                *rest, sm_scale, causal, block_q, block_k, nq, n_steps,
                has_bias):
    # grid (B*Hkv, nk, n_steps) with n_steps = G*nq: the sequential axis
    # enumerates (query head of the group, q block); dk/dv accumulate in
    # scratch across ALL of them — the GQA sum over the group's query
    # heads happens here, not as a post-kernel reshape-reduce
    if has_bias:
        bias_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
    ik, g = pl.program_id(1), pl.program_id(2)
    iq = g % nq                        # q block within the current head
    q0, k0 = off_ref[0], off_ref[1]

    @pl.when(g == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        # transposed recompute: s^T rows are k positions, so the per-q-row
        # lse/delta broadcast along lanes with no relayout
        s_t = lax.dot_general(k, q, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32) * sm_scale
        if has_bias:
            s_t = s_t + bias_ref[:].reshape(block_k, 1)
        lse_row = lse_ref[0].reshape(1, block_q)       # (1, bq)
        p_t = jnp.exp(s_t - lse_row)                   # (bk, bq)
        if causal:
            kpos = (k0 + ik * block_k
                    + lax.broadcasted_iota(jnp.int32, s_t.shape, 0))
            qpos = (q0 + iq * block_q
                    + lax.broadcasted_iota(jnp.int32, s_t.shape, 1))
            p_t = jnp.where(kpos > qpos, 0.0, p_t)
        dv_scr[:] = dv_scr[:] + lax.dot(
            p_t, do.astype(jnp.float32), preferred_element_type=jnp.float32)
        dp_t = lax.dot_general(v, do, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
        delta_row = delta_ref[0].reshape(1, block_q)
        ds_t = p_t * (dp_t - delta_row) * sm_scale     # (bk, bq)
        dk_scr[:] = dk_scr[:] + lax.dot(
            ds_t, q.astype(jnp.float32), preferred_element_type=jnp.float32)

    if causal:
        # skip q blocks entirely BEFORE this k block (no key visible)
        pl.when(q0 + iq * block_q + block_q - 1
                >= k0 + ik * block_k)(_compute)
    else:
        _compute()

    @pl.when(g == n_steps - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q3, k3, v3, off, bias, n_heads, out, lse, do, d_lse, sm_scale,
         causal, block_q, block_k, interpret):
    BH, Sq, dh = q3.shape
    Sk = k3.shape[1]
    G = BH // k3.shape[0]
    nq, nk = Sq // block_q, Sk // block_k
    has_bias = bias is not None
    H = n_heads
    # D = rowsum(dO * O) - d_lse: the standard flash delta, minus the
    # lse-output cotangent.  With z the scaled scores and p = exp(z-lse),
    # dL/dz = p*(dp - D) from the out path PLUS d_lse*p from the lse
    # path (d lse/dz = p), so the whole lse gradient folds into the
    # kernels' delta operand — this is what makes the per-hop kernels
    # exactly differentiable under the sequence-parallel logsumexp merge
    # (ring_flash_attention), where the merge weights depend on lse.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1) - d_lse                   # (BH, Sq)
    vma = _vma(q3, k3, v3, do, off, *([bias] if has_bias else []))

    dq_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b // G, j, 0)),
        pl.BlockSpec((1, block_k, dh), lambda b, i, j: (b // G, j, 0)),
        pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
    ]
    dq_args = [off, q3, k3, v3, do, lse, delta]
    if has_bias:
        dq_specs.append(_bias_spec(H, block_k, k_grid_dim=2))
        dq_args.append(bias)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          has_bias=has_bias),
        grid=(BH, nq, nk),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0)),
        out_shape=compat.shape_dtype_struct((BH, Sq, dh), q3.dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((block_q, dh), jnp.float32)],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dq_args)

    # dkv grid: leading dim is the KV head; the sequential axis g
    # enumerates (group member h = g // nq, q block i = g % nq) so the
    # scratch sums each group's contributions before the single write
    BHkv = BH // G
    n_steps = G * nq

    def qmap(b, j, g):                  # ONE definition of the group
        return (b * G + g // nq, g % nq)   # enumeration (same trap-
    # avoidance as _bias_spec): head g//nq of KV head b's group, q
    # block g % nq

    dkv_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_q, dh), lambda b, j, g: (*qmap(b, j, g), 0)),
        pl.BlockSpec((1, block_k, dh), lambda b, j, g: (b, j, 0)),
        pl.BlockSpec((1, block_k, dh), lambda b, j, g: (b, j, 0)),
        pl.BlockSpec((1, block_q, dh), lambda b, j, g: (*qmap(b, j, g), 0)),
        pl.BlockSpec((1, block_q), qmap),
        pl.BlockSpec((1, block_q), qmap),
    ]
    dkv_args = [off, q3, k3, v3, do, lse, delta]
    if has_bias:
        # leading grid dim is the KV head here: batch = b // (H/G)
        dkv_specs.append(_bias_spec(H // G, block_k, k_grid_dim=1))
        dkv_args.append(bias)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq,
                          n_steps=n_steps, has_bias=has_bias),
        grid=(BHkv, nk, n_steps),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, dh), lambda b, j, g: (b, j, 0)),
            pl.BlockSpec((1, block_k, dh), lambda b, j, g: (b, j, 0)),
        ],
        out_shape=[
            compat.shape_dtype_struct((BHkv, Sk, dh), k3.dtype, vma=vma),
            compat.shape_dtype_struct((BHkv, Sk, dh), v3.dtype, vma=vma),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, dh), jnp.float32),
                        pltpu.VMEM((block_k, dh), jnp.float32)],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry (custom_vjp over q, k, v; `off` is a traced i32 operand
# with a symbolic-zero cotangent)
# ---------------------------------------------------------------------------

# (out, lse) both come out of the vjp'd function so sequence-parallel
# callers can logsumexp-merge per-hop results and still differentiate.
# `bias` is a PRIMAL but deliberately gets a ZERO cotangent: the public
# wrappers stop_gradient it (it is the padding-mask channel, not a
# learned-bias channel — a learned attention bias needs the XLA path).
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q3, k3, v3, off, bias, n_heads, sm_scale, causal, block_q,
           block_k, interpret):
    return _fwd(q3, k3, v3, off, bias, n_heads, sm_scale, causal, block_q,
                block_k, interpret)


def _flash_fwd(q3, k3, v3, off, bias, n_heads, sm_scale, causal, block_q,
               block_k, interpret):
    out, lse = _fwd(q3, k3, v3, off, bias, n_heads, sm_scale, causal,
                    block_q, block_k, interpret)
    return (out, lse), (q3, k3, v3, off, bias, out, lse)


def _flash_bwd(n_heads, sm_scale, causal, block_q, block_k, interpret,
               res, cts):
    q3, k3, v3, off, bias, out, lse = res
    do, d_lse = cts
    dq, dk, dv = _bwd(q3, k3, v3, off, bias, n_heads, out, lse, do, d_lse,
                      sm_scale, causal, block_q, block_k, interpret)
    d_off = _np.zeros((2,), jax.dtypes.float0)    # integer operand
    d_bias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, d_off, d_bias


_flash.defvjp(_flash_fwd, _flash_bwd)


def supported(q_shape, dtype=None, kv_seq_len=None) -> bool:
    """Can the fused kernel take this attention?  [B,H,S,dh] with S a
    lane multiple (blocks divide S exactly) and a lane-friendly head dim.
    ``kv_seq_len`` (Sk, when it differs from Sq) must be a lane multiple
    too — the k/v blocks tile Sk the same way the q blocks tile Sq."""
    if len(q_shape) != 4:
        return False
    S, dh = q_shape[2], q_shape[3]
    if kv_seq_len is not None and kv_seq_len % LANES != 0:
        return False
    return S % LANES == 0 and dh % 8 == 0 and dh <= 256


def _flash4(q, k, v, q_offset, k_offset, sm_scale, causal, block_q,
            block_k, interpret, with_lse=False, key_bias=None):
    """q [B,H,Sq,dh] x k/v [B,Hkv,Sk,dh] entry shared by the public
    wrappers; Hkv may divide H (GQA — the kernels read each KV head once
    per group instead of attending a repeat-expanded copy)."""
    B, H, Sq, dh = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    assert H % Hkv == 0, (H, Hkv)
    if Sk % LANES != 0:
        # fail here with a real error: _pick_block would fall back to a
        # non-lane-multiple block (b == S admits any Sk), which only
        # detonates later as an opaque Mosaic layout error on real
        # hardware (ring/gathered callers keep Sk = Sl lane-tileable;
        # the public API has to enforce it for everyone else)
        raise ValueError(
            f"flash kernels need the K/V sequence length to be a multiple "
            f"of {LANES} lanes, got Sk={Sk} (k/v shape {k.shape}); pad the "
            "keys (with key_bias masking the padding) or use the XLA "
            "attention path")
    if sm_scale is None:
        sm_scale = dh ** -0.5
    bq, bk = _pick_block(Sq, block_q), _pick_block(Sk, block_k)
    off = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                     jnp.asarray(k_offset, jnp.int32)])
    if key_bias is not None:
        assert key_bias.shape == (B, Sk), (key_bias.shape, (B, Sk))
        # the fused kernels carry no d_bias path (see _flash docstring):
        # the bias channel is for padding masks, whose gradient is
        # discarded by construction
        key_bias = lax.stop_gradient(key_bias.astype(jnp.float32))
    out, lse = _flash(q.reshape(B * H, Sq, dh), k.reshape(B * Hkv, Sk, dh),
                      v.reshape(B * Hkv, Sk, dh), off, key_bias, H,
                      float(sm_scale), bool(causal), bq, bk,
                      bool(interpret))
    out = out.reshape(B, H, Sq, dh)
    if with_lse:
        return out, lse.reshape(B, H, Sq)
    return out


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = _DEF_BLOCK, block_k: int = _DEF_BLOCK,
                    q_offset=0, k_offset=0,
                    key_bias: Optional[jax.Array] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused-kernel exact attention, q: [B, H, Sq, dh], k/v: [B, H, Sk,
    dh] -> [B, H, Sq, dh].

    Differentiable (custom_vjp; the backward is the flash recompute from
    the saved lse — residual memory is O(B*H*Sq*(dh+1)), never O(S^2)).
    `q_offset`/`k_offset` (traced i32 ok) give the GLOBAL position of the
    first q/k row, so a sequence-sharded caller attending a visiting K/V
    chunk gets causality over global token positions.  `key_bias`
    ([B, Sk] f32, added to every query row's scores) is the padding-mask
    channel (0 / -1e30) — NON-differentiable by contract
    (stop_gradient'd; learned biases need the XLA path), and every query
    row must see >= 1 unmasked key: an all-masked row's FORWARD matches
    the XLA softmax (both degenerate to a uniform average), but the
    backward recompute p = exp(s - lse) evaluates to 1 per key instead
    of 1/Sk there, inflating that row's gradients ~Sk-fold.  Real masks
    satisfy this (a sequence with zero valid tokens carries no loss);
    the precondition is documented rather than paid for with a
    renormalization in every backward block.
    `interpret=None` auto-selects the Mosaic emulator off-TPU so parity
    tests run everywhere."""
    if interpret is None:
        interpret = not _is_tpu()
    assert supported(q.shape), (q.shape,)
    return _flash4(q, k, v, q_offset, k_offset, sm_scale, causal,
                   block_q, block_k, interpret, key_bias=key_bias)


def ring_flash_attention(q, k, v, axis_name: str, *, causal: bool = True,
                         sm_scale: Optional[float] = None,
                         block_q: int = _DEF_BLOCK,
                         block_k: int = _DEF_BLOCK,
                         interpret: Optional[bool] = None) -> jax.Array:
    """Sequence-parallel exact attention on the fused kernels: K/V chunks
    rotate the unidirectional device ring (the reference's
    stream-combine-forward dataflow, hw/all_reduce.sv REDUCE/FORWARD)
    while every hop's local attention runs the Pallas flash kernel;
    per-hop (out, lse) pairs combine by logsumexp merge — associative
    and order-independent up to f32 rounding, so the result matches
    ops.ring_attention.ring_attention up to reassociation.

    Differentiates by autodiff THROUGH the hop scan: each hop's kernel
    call carries its own custom flash vjp (recompute from that hop's
    lse), and ppermute transposes to the reverse rotation — no O(S^2)
    residual ever materializes; per-hop residuals total O(n * Sl) = O(S)
    rows per device, the same order as the gathered-KV path's forward
    buffers.

    Inside shard_map with `axis_name` a mesh axis; shards contiguous
    (device i holds global positions [i*Sl, (i+1)*Sl))."""
    if interpret is None:
        interpret = not _is_tpu()
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Sl, dh = q.shape
    assert supported(q.shape), (q.shape,)
    if sm_scale is None:
        sm_scale = dh ** -0.5
    q0 = idx * Sl
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop_attend(kc, vc, src):
        return _flash4(q, kc, vc, q0, src * Sl, sm_scale, causal,
                       block_q, block_k, interpret, with_lse=True)

    # hop 0: the local chunk (always causally visible to itself).  The
    # running output stays f32 across the whole scan — requantizing to a
    # bf16 carry every hop would accumulate ~n roundings where the XLA
    # ring (f32 accumulators, one cast in _finish) has one.
    out, lse = hop_attend(k, v, idx)
    out = out.astype(jnp.float32)

    def merge(out, lse, o_h, lse_h):
        # logsumexp merge of two normalized partial attentions; a fully
        # masked hop arrives as (0, -1e30) and merges as a no-op
        lse_n = jnp.logaddexp(lse, lse_h)              # (B,H,Sl)
        w, w_h = jnp.exp(lse - lse_n), jnp.exp(lse_h - lse_n)
        return (out * w[..., None]
                + o_h.astype(jnp.float32) * w_h[..., None]), lse_n

    def hop(carry, s_i):
        out, lse, kc, vc = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        src = (idx - s_i) % n                 # whose K/V we hold this hop

        def attend(args):
            out, lse = args
            o_h, lse_h = hop_attend(kc, vc, src)
            return merge(out, lse, o_h, lse_h)

        if causal:
            # chunks entirely in the future are fully masked: skip the
            # kernel, keep the rotation (same dead-beat elision as
            # ring_attention)
            out, lse = lax.cond(src > idx, lambda a: a, attend, (out, lse))
        else:
            out, lse = attend((out, lse))
        return (out, lse, kc, vc), None

    (out, lse, _, _), _ = lax.scan(hop, (out, lse, k, v),
                                   jnp.arange(1, n))
    return out.astype(q.dtype)
