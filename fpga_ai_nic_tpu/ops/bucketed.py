"""Bucketed gradient all-reduce — the reference's per-layer async collective
issue, generalized.

The reference issues one all-reduce per layer during backward, in backward
order, with at most 8 in flight (sw/mlp_mpi_example_f32.cpp:753-756;
hw/all_reduce.sv:110-244 command FIFOs, :1228,1373 round-robin done IDs).
Per-layer granularity is wasteful for small layers (each collective pays
fixed latency) and too coarse for huge ones; DDP-style *bucketing* keeps the
reference's overlap property — reductions of early buckets ride the wire
while later layers' backward still computes — at a tunable granularity.

TPU-first: buckets are formed in reverse leaf order (gradients materialize
in backward order), each bucket is flattened to one f32 vector and reduced
independently (``lax.psum`` or the BFP ring from `ops.ring`); XLA's
latency-hiding scheduler overlaps the per-bucket collectives with the
remaining backward compute — the issue/wait window the host code managed by
hand (:752-764) falls out of dataflow.  The bounded-window semantics for
eager host-side issue live in `runtime.queue`.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import ring as ring_ops
from .fused_update import pad_multiple
from ..utils.config import CollectiveConfig


class Bucket(NamedTuple):
    leaf_ids: Tuple[int, ...]          # indices into tree_leaves, in the
                                       # reverse-flatten (issue) order
                                       # buckets are packed in
    sizes: Tuple[int, ...]             # flat sizes of those leaves
    padded_len: int                    # bucket vector length after padding


class BucketPlan(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    buckets: Tuple[Bucket, ...]        # in issue (reverse-leaf) order


def plan_buckets(tree, coll: CollectiveConfig, n: int) -> BucketPlan:
    """Static bucket assignment from a pytree of arrays (or shape structs).

    Leaves are walked in REVERSE flatten order — the order their gradients
    become available during backward, which is the order the reference
    issues collectives (bwd loop i = L-1..0, sw/mlp_mpi_example_f32.cpp:
    735-787) — and greedily grouped until a bucket holds at least
    ``coll.bucket_elems`` elements.  Each bucket is padded so the BFP ring's
    per-device chunk is whole blocks (same rule as fused_update).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(l.shape for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    m = pad_multiple(coll, n)

    buckets: List[Bucket] = []
    cur_ids: List[int] = []
    cur_n = 0

    def finalize():
        buckets.append(Bucket(tuple(cur_ids),
                              tuple(sizes[j] for j in cur_ids),
                              cur_n + ((-cur_n) % m)))

    for i in reversed(range(len(leaves))):
        cur_ids.append(i)
        cur_n += sizes[i]
        if cur_n >= coll.bucket_elems:
            finalize()
            cur_ids, cur_n = [], 0
    if cur_ids:
        finalize()
    return BucketPlan(treedef, shapes, dtypes, tuple(buckets))


def _flatten_bucket(leaves: Sequence[jax.Array], b: Bucket) -> jax.Array:
    flat = jnp.concatenate(
        [leaves[i].astype(jnp.float32).reshape(-1) for i in b.leaf_ids])
    pad = b.padded_len - flat.shape[0]
    return jnp.pad(flat, (0, pad)) if pad else flat


def _reduce_bucket(leaves: Sequence[jax.Array], b: Bucket, axis_name: str,
                   n, coll: CollectiveConfig) -> jax.Array:
    """One bucket: flatten -> sum-collective -> mean.  Returns f32
    [b.padded_len]."""
    flat = _flatten_bucket(leaves, b)
    if coll.impl == "xla":
        red = lax.psum(flat, axis_name)
    else:
        from .fused_update import ring_all_reduce_routed
        red = ring_all_reduce_routed(flat, axis_name, coll,
                                     b.padded_len // lax.axis_size(axis_name))
    return red / n


def _scatter_bucket(out: List, flat: jax.Array, b: Bucket,
                    plan: BucketPlan) -> None:
    off = 0
    for i, size in zip(b.leaf_ids, b.sizes):
        out[i] = flat[off:off + size].reshape(plan.shapes[i]).astype(
            plan.dtypes[i])
        off += size


def all_reduce_bucketed(grads, axis_name: str, coll: CollectiveConfig,
                        plan: BucketPlan = None):
    """Mean all-reduce of a gradient pytree, one collective per bucket.

    Must run inside ``shard_map``.  Returns the tree with every leaf
    replaced by its dp-mean.  Under ``impl='ring'`` each bucket goes through
    the explicit (optionally BFP-compressed) ring — the per-bucket analogue
    of one reference collective (one grad buffer, one done flag).
    """
    n = lax.axis_size(axis_name)
    if plan is None:
        plan = plan_buckets(grads, coll, n)
    leaves = jax.tree_util.tree_leaves(grads)
    out: List = [None] * len(leaves)
    for b in plan.buckets:
        _scatter_bucket(out, _reduce_bucket(leaves, b, axis_name, n, coll),
                        b, plan)
    return jax.tree_util.tree_unflatten(plan.treedef, out)


def bucket_locals(grads, plan: BucketPlan) -> List[jax.Array]:
    """Per-bucket flat f32 local gradients, in issue (reverse-leaf) order —
    the pre-collective payloads the host-side queue (`runtime.queue`)
    dispatches one collective per (the reference's per-layer grad buffers,
    sw/mlp_mpi_example_f32.cpp:753-756)."""
    leaves = jax.tree_util.tree_leaves(grads)
    return [_flatten_bucket(leaves, b) for b in plan.buckets]


def assemble_flat(bucket_vecs: Sequence[jax.Array],
                  plan: BucketPlan) -> jax.Array:
    """Inverse of `bucket_locals` into the canonical flat layout: reduced
    bucket vectors -> one flat f32 vector in forward leaf order, padding
    dropped (the layout `fused_update.flatten_tree` gives the master)."""
    segs: List = [None] * len(plan.shapes)
    for b, red in zip(plan.buckets, bucket_vecs):
        off = 0
        for i, size in zip(b.leaf_ids, b.sizes):
            segs[i] = red[off:off + size]
            off += size
    return jnp.concatenate(segs)


def all_reduce_bucketed_flat(grads, axis_name: str, coll: CollectiveConfig,
                             plan: BucketPlan = None) -> jax.Array:
    """Bucketed mean all-reduce assembled directly into the canonical flat
    f32 vector (forward leaf order, no padding) — the layout
    `fused_update.flatten_tree` produces for the master copy.

    Unlike `all_reduce_bucketed`, reduced values are NEVER rounded back to
    the leaf dtype: a bf16 model's dp-mean gradients stay f32 all the way
    into the f32 master-weight update (the whole point of keeping an f32
    master; rounding here would discard the reduction's precision).
    """
    n = lax.axis_size(axis_name)
    if plan is None:
        plan = plan_buckets(grads, coll, n)
    leaves = jax.tree_util.tree_leaves(grads)
    return assemble_flat(
        [_reduce_bucket(leaves, b, axis_name, n, coll)
         for b in plan.buckets], plan)


def bucket_wire_bytes(plan: BucketPlan, n: int,
                      coll: CollectiveConfig) -> int:
    """Total per-device ring bytes for one bucketed all-reduce (flit-counter
    observability, hw/bfp_adapter.sv:705-729) — topology-aware, so the
    declaration matches the routed collective (flat or hierarchical)."""
    from .fused_update import wire_bytes_for
    return sum(
        wire_bytes_for(coll, b.padded_len, n)
        for b in plan.buckets)
