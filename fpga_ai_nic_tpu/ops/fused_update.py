"""The fused all-reduce + weight-update engine — the reference's defining
capability, rebuilt TPU-first.

Reference semantics (SURVEY.md §3.2): gradients stream through a ring
reduce-scatter; the *reduced* gradient shard feeds a fused SGD unit holding
the canonical weights (hw/weight_update.sv); the all-gather phase then
distributes **updated weights**, not gradients (hw/all_reduce.sv:996-1086).
That is exactly ZeRO-1: sharded optimizer + master weights, gather of the
updated parameters.  On TPU we express it as

    g_own   = reduce_scatter(flat_grads)        # XLA psum_scatter or BFP ring
    w_own'  = opt(w_own, g_own / n)             # owned f32 master shard
    params' = all_gather(cast(w_own'))          # replicated working copy

inside ``shard_map``; XLA overlaps the collectives with surrounding compute
the way the FPGA overlapped its ring with the host's backward GEMMs
(sw/mlp_mpi_example_f32.cpp:735-787).

Pytrees are flattened into one contiguous f32 vector (padded to a
lcm(n, bfp_block) multiple) before the collective, mirroring the reference's
treatment of the model as one long gradient stream sliced into 32 KiB
blocks (hw/all_reduce.sv:101-103,246-253).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import ring as ring_ops
from .. import optim
from ..utils.config import CollectiveConfig, OptimizerConfig


class FlatMeta(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    sizes: Tuple[int, ...]
    padded_len: int


def resolve_codec(coll: CollectiveConfig):
    """The compress.Codec this config asks for (None = uncompressed) —
    one definition so every consumer (ring routing, padding, trainers,
    integrity tolerance) resolves identically."""
    from ..compress import resolve
    return resolve(coll)


def pad_multiple(coll: CollectiveConfig, n: int) -> int:
    """Padding multiple for flat vectors fed to the n-way collective: the
    per-device chunk (len / n) must be a whole number of codec units (BFP
    block / top-k bucket / int8 block) — and of (block, 128)-lane tiles
    when the fused Pallas kernel carries the wire (its frames are native
    int8 tiles)."""
    codec = resolve_codec(coll)
    if codec is not None:
        if getattr(coll, "fused_kernel", False):
            from . import ring_pallas
            return n * codec.pad_elems * ring_pallas.LANES
        return n * codec.pad_elems
    return n


def wire_bytes_for(coll: CollectiveConfig, L: int, n: int,
                   codec="__resolve__") -> int:
    """Topology-aware per-device wire bytes for one all-reduce of an
    [L]-element flat f32 vector under this config — the flit-counter
    arithmetic every consumer (obs statics, queued telemetry, bucket
    accounting) must share so the declaration can never drift from the
    routing.  ``codec`` defaults to the config's own resolution; pass
    None explicitly for the raw-f32 accounting."""
    if codec == "__resolve__":
        codec = resolve_codec(coll)
    if getattr(coll, "topology", "flat") == "hier":
        from . import ring_hier
        return ring_hier.wire_bytes_per_device(L, n, coll.intra_size,
                                               codec)
    return ring_ops.wire_bytes_per_device(L, n, codec)


def flat_meta(tree, coll: CollectiveConfig, n: int) -> FlatMeta:
    """Static flattening metadata from a pytree of arrays (or shape structs)
    without touching device memory."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(l.shape for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    total = sum(sizes)
    m = pad_multiple(coll, n)
    padded = total + ((-total) % m)
    return FlatMeta(treedef, shapes, dtypes, sizes, padded)


def flatten_tree(tree, coll: CollectiveConfig, n: int) -> Tuple[jax.Array, FlatMeta]:
    """Concatenate a pytree into one flat f32 vector, zero-padded so the
    per-device chunk is a whole number of BFP blocks."""
    meta = flat_meta(tree, coll, n)
    leaves = jax.tree_util.tree_leaves(tree)
    flat = jnp.concatenate([l.astype(jnp.float32).reshape(-1) for l in leaves])
    pad = meta.padded_len - flat.shape[0]
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat, meta


def repad_flat(v, meta: FlatMeta) -> jax.Array:
    """Re-fit a saved flat master/optimizer vector to THIS layout's
    padded length.  The padding multiple depends on the collective's
    device count (``pad_multiple(coll, n)``), so a checkpoint written on
    one mesh shape carries a different tail padding than the mesh it
    restores onto (dp8 -> dp4 after a preemption, or a codec change);
    the LIVE elements (``sum(meta.sizes)``) are mesh-invariant, and every
    pad element is zero by construction (flatten_tree zero-pads, and the
    optimizers keep zero-gradient pad lanes at zero), so the re-fit is
    value-exact.  A vector with fewer than the live elements is a
    different model's checkpoint — loud error, never a truncation."""
    v = jnp.asarray(v)
    total = sum(meta.sizes)
    if v.shape[0] < total:
        raise ValueError(
            f"flat state of length {v.shape[0]} cannot hold this "
            f"layout's {total} live elements — wrong checkpoint/model")
    if v.shape[0] == meta.padded_len:
        return v
    # the stripped tail must be the zero padding — a NONZERO tail means
    # the vector belongs to a different model/layout whose live elements
    # extend past this layout's, and stripping it would silently corrupt
    # the restore (eager-only check: restore paths run outside jit)
    tail = v[total:]
    if tail.size and float(jnp.abs(tail).max()) != 0.0:
        raise ValueError(
            f"flat state of length {v.shape[0]} carries nonzero data "
            f"past this layout's {total} live elements — wrong "
            "checkpoint/model (refusing to truncate)")
    return jnp.pad(v[:total], (0, meta.padded_len - total))


def params_like_from_meta(meta: FlatMeta):
    """Rebuild a zero-device-work params pytree (ShapeDtypeStructs) from
    flattening metadata — the handle a TARGET trainer needs to derive its
    own layout (``_ensure_meta``) when the live state arrives from another
    mesh shape (parallel.reshard) instead of from ``init_state``."""
    leaves = [jax.ShapeDtypeStruct(s, d)
              for s, d in zip(meta.shapes, meta.dtypes)]
    return jax.tree_util.tree_unflatten(meta.treedef, leaves)


def unflatten_tree(flat: jax.Array, meta: FlatMeta):
    leaves, off = [], 0
    for shape, dtype, size in zip(meta.shapes, meta.dtypes, meta.sizes):
        leaves.append(flat[off:off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree_util.tree_unflatten(meta.treedef, leaves)


def shard_slice(flat: jax.Array, axis_name: str) -> jax.Array:
    """This device's chunk of a replicated flat vector (natural order)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    c = flat.shape[0] // n
    return lax.dynamic_slice_in_dim(flat, idx * c, c)


_warned_fused_fallback = False


def _warn_fused_fallback() -> None:
    """fused_kernel=True off TPU falls back to the separate-op ring with
    the CONFIGURED codec (default "xla": contiguous block grouping) — the
    pallas interpret codec cannot run inside vma-checked shard_maps — so
    the quantization bits differ from the TPU kernel's lane-layout
    partition.  Same wire rate and error bound, but training runs are not
    bit-reproducible across platforms; surface that once instead of
    silently diverging (round-3 advisor finding)."""
    global _warned_fused_fallback
    if not _warned_fused_fallback:
        _warned_fused_fallback = True
        import warnings
        warnings.warn(
            "CollectiveConfig.fused_kernel=True on a non-TPU backend: "
            "routing to the separate-op ring with the configured codec. "
            "Quantization block grouping (and therefore the exact bits) "
            "differs from the TPU fused kernel's lane layout; numerics "
            "are equivalent in rate/error but not bit-reproducible "
            "across platforms.", stacklevel=3)


def _fused_bfp_cfg(coll: CollectiveConfig):
    """The BFPConfig driving the fused Pallas kernels (config validation
    guarantees the resolved codec supports_fused, i.e. is BFP)."""
    return resolve_codec(coll).cfg


def ring_all_reduce_routed(flat: jax.Array, axis_name: str,
                           coll: CollectiveConfig,
                           chunk_len: int):
    """Explicit-ring all-reduce respecting the fused_kernel AND topology
    routing (one definition shared by all_reduce_mean and ops.bucketed so
    the fallback/slice/topology policy cannot drift between call sites).

    Carries no ``integrity=`` seam on purpose: every caller is a
    bucketed/queued DDP reduce, and those trainers reject
    integrity_check at construction until they thread the verdicts —
    an untestable flag here would be claimed-but-unverified coverage."""
    codec = resolve_codec(coll)
    if getattr(coll, "topology", "flat") == "hier":
        from . import ring_hier
        return ring_hier.hier_all_reduce(
            flat, axis_name, coll.intra_size, compression=codec,
            slice_elems=coll.slice_elems, unroll=coll.unroll_hops)
    if coll.fused_kernel:
        from . import ring_pallas
        bcfg = _fused_bfp_cfg(coll)
        slice_e = ring_pallas.pick_slice_elems(
            chunk_len, coll.slice_elems, bcfg.block_size)
        if ring_pallas._is_tpu():
            return ring_pallas.ring_all_reduce_fused(
                flat, axis_name, compression=bcfg,
                slice_elems=slice_e,
                pipeline_depth=coll.pipeline_depth)
        _warn_fused_fallback()
        return ring_ops.ring_all_reduce(
            flat, axis_name, compression=codec,
            slice_elems=slice_e, unroll=coll.unroll_hops)
    return ring_ops.ring_all_reduce(flat, axis_name,
                                    compression=codec,
                                    slice_elems=coll.slice_elems,
                                    unroll=coll.unroll_hops)


def reduce_scatter(flat_g: jax.Array, axis_name: str,
                   coll: CollectiveConfig, integrity: bool = False):
    """``integrity=True`` returns ``(owned, wire_ok)``; wire_ok is the
    exact frame-conservation verdict of the routed collective
    (ops.integrity).  impl='xla' owns its own wire (no explicit frames
    to checksum), so its verdict is constant True — the exact tier is a
    property of the explicit-ring routes."""
    if coll.impl == "xla":
        out = lax.psum_scatter(flat_g, axis_name, scatter_dimension=0,
                               tiled=True)
        return (out, jnp.bool_(True)) if integrity else out
    codec = resolve_codec(coll)
    if getattr(coll, "topology", "flat") == "hier":
        from . import ring_hier
        return ring_hier.hier_reduce_scatter(
            flat_g, axis_name, coll.intra_size, compression=codec,
            slice_elems=coll.slice_elems, unroll=coll.unroll_hops,
            integrity=integrity)
    if coll.fused_kernel:
        from . import ring_pallas
        n = lax.axis_size(axis_name)
        bcfg = _fused_bfp_cfg(coll)
        slice_e = ring_pallas.pick_slice_elems(
            flat_g.shape[0] // n, coll.slice_elems, bcfg.block_size)
        if ring_pallas._is_tpu():
            return ring_pallas.ring_reduce_scatter_fused(
                flat_g, axis_name, compression=bcfg,
                slice_elems=slice_e,
                pipeline_depth=coll.pipeline_depth,
                integrity=integrity)
        # off-TPU: the separate-op ring with the CONFIGURED codec (see
        # _warn_fused_fallback); the kernel's own bit-exactness story
        # lives in tests/test_ring_pallas.py
        _warn_fused_fallback()
        return ring_ops.ring_reduce_scatter(
            flat_g, axis_name, compression=codec,
            slice_elems=slice_e, unroll=coll.unroll_hops,
            integrity=integrity)
    return ring_ops.ring_reduce_scatter(flat_g, axis_name,
                                        compression=codec,
                                        slice_elems=coll.slice_elems,
                                        unroll=coll.unroll_hops,
                                        integrity=integrity)


def reduce_scatter_update(flat_g: jax.Array, w_own: jax.Array, opt_state,
                          step, axis_name: str, coll: CollectiveConfig,
                          opt_cfg: OptimizerConfig,
                          integrity: bool = False):
    """Fused gradient reduce + ZeRO-1 optimizer update: the reference's
    whole point (decode feeds hw/weight_update.sv, no separate optimizer
    pass over HBM) + cross-replica weight-update sharding (ZeRO-1).

    Routing (one definition so trainers cannot drift):
      - fused_kernel on TPU: the in-kernel path —
        ops.ring_pallas.ring_reduce_scatter_update_fused updates the
        owned shard as each final-hop slice decodes, inside the depth-D
        pipeline; w/state shards are donated kernel operands.
      - everything else (xla psum_scatter, separate-op ring with any
        codec, the off-TPU fallback, n == 1): the identical update
        formula (optim.fused_apply_flat) fused into the step right after
        the reduce — same hyper vector, same golden twin, so the
        numerics contract is uniform across routes.

    Returns ``(g_own_sum, w_new, opt_state_new)``; g_own_sum is the raw
    reduced SUM shard (callers /n for metrics), bit-identical to
    ``reduce_scatter`` on the same route.

    ``integrity=True`` appends the exact wire verdict: ``(g_own_sum,
    w_new, opt_state_new, wire_ok)``.  On the in-kernel TPU route the
    kernel accumulates the frame checksums itself (the update retires
    with the final-hop decode and the state is DONATED — a tripped
    verdict invalidates the STEP via the elastic ladder, see
    runtime.chaos.check_step_diag); every other route still holds the
    pre-step state, so callers can gate the update in-graph
    (``update_route_gatable`` tells them which situation they are
    in)."""
    from ..utils.config import OptimizerSpec
    spec = OptimizerSpec.from_optimizer(opt_cfg)
    n = lax.axis_size(axis_name)
    hyper = optim.fused_hyperparams(opt_cfg, step)
    # topology='hier' always takes the shared-formula route below: the
    # hierarchical reduce_scatter carries the codec only on the slow
    # inter hop and the update fuses right after the reduce — identical
    # golden contract, zero exposed optimizer pass either way
    if coll.fused_kernel and n > 1 \
            and getattr(coll, "topology", "flat") == "flat":
        from . import ring_pallas
        if ring_pallas._is_tpu():
            bcfg = _fused_bfp_cfg(coll)
            slice_e = ring_pallas.pick_slice_elems(
                flat_g.shape[0] // n, coll.slice_elems, bcfg.block_size)
            return ring_pallas.ring_reduce_scatter_update_fused(
                flat_g, w_own, opt_state, hyper, axis_name,
                opt_kind=spec.kind, compression=bcfg, slice_elems=slice_e,
                pipeline_depth=coll.pipeline_depth, integrity=integrity)
        # off-TPU: reduce_scatter itself warns and routes to the
        # separate-op ring; the update below stays the shared formula
    res = reduce_scatter(flat_g, axis_name, coll, integrity=integrity)
    g_own, wire_ok = res if integrity else (res, None)
    w_new, st2 = optim.fused_apply_flat(spec, w_own, g_own, opt_state,
                                        hyper, n)
    if integrity:
        return g_own, w_new, st2, wire_ok
    return g_own, w_new, st2


def update_route_gatable(coll: CollectiveConfig, n: int = 0) -> bool:
    """True when ``reduce_scatter_update`` takes a route that still
    materializes the pre-step state — i.e. a tripped integrity verdict
    can be gated IN-GRAPH (``jnp.where(ok, new, old)``).  False only on
    the in-kernel TPU route, where the master/moment shards are donated
    kernel operands updated in place: referencing the old value after
    the call would read the aliased (already-updated) buffer, so the
    only safe recovery is invalidating the step on the host
    (check_step_diag -> elastic restore/reshard).  ``n`` is the axis
    size when the caller knows it (``reduce_scatter_update`` only takes
    the in-kernel route for n > 1 — a single-device mesh always runs
    the shared formula, hence gatable); 0 = unknown, assume the
    in-kernel route is reachable."""
    from . import ring_pallas
    return not (coll.fused_kernel and n != 1
                and getattr(coll, "topology", "flat") == "flat"
                and ring_pallas._is_tpu())


def all_gather_flat(owned: jax.Array, axis_name: str,
                    coll: CollectiveConfig, integrity: bool = False):
    """``integrity=True`` returns ``(gathered, wire_ok)`` — per-hop
    frame conservation on the explicit rings; the replica-agreement
    exact check on the fused TPU kernel (its wire lives inside the
    kernel); constant True on impl='xla' (no explicit frames)."""
    if coll.impl == "xla":
        out = lax.all_gather(owned, axis_name, tiled=True)
        return (out, jnp.bool_(True)) if integrity else out
    codec = resolve_codec(coll)
    if getattr(coll, "topology", "flat") == "hier":
        from . import ring_hier
        return ring_hier.hier_all_gather(
            owned, axis_name, coll.intra_size, compression=codec,
            unroll=coll.unroll_hops, integrity=integrity)
    if coll.fused_kernel:
        from . import ring_pallas
        if ring_pallas._is_tpu():
            out = ring_pallas.ring_all_gather_fused(
                owned, axis_name, compression=_fused_bfp_cfg(coll))
            if not integrity:
                return out
            from . import integrity as integrity_lib
            return out, integrity_lib.replica_consistent(out, axis_name)
        _warn_fused_fallback()
        return ring_ops.ring_all_gather(owned, axis_name,
                                        compression=codec,
                                        unroll=coll.unroll_hops,
                                        integrity=integrity)
    return ring_ops.ring_all_gather(owned, axis_name,
                                    compression=codec,
                                    unroll=coll.unroll_hops,
                                    integrity=integrity)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def all_gather_flat_vjp(owned: jax.Array, axis_name: str,
                        coll: CollectiveConfig) -> jax.Array:
    """`all_gather_flat` with an explicit VJP: differentiable ring/BFP path.

    ZeRO-3's gather-on-use sits INSIDE autodiff, where the explicit ring is
    a dead end for jax's automatic transpose: the rolled ppermute fori_loop
    has no reverse-mode rule and the BFP codec's int8 casts have no
    gradient.  But the *mathematical* transpose of an all-gather is simply
    the reduce-scatter — so this custom VJP declares it directly:

      forward:  ring all-gather of the (optionally BFP-encoded-once)
                master shards — replicas see wire-identical quantized bytes
                (hw/bfp_adapter.sv compressing the weight-output stream,
                hw/all_reduce.sv FORWARD_OUTPUT:996-1086);
      backward: the per-hop-compressed ring reduce-scatter of the full
                gradient cotangent (the adapter on the gradient stream).

    Quantized-forward semantics: with compression, the loss/grad are
    evaluated at the BFP-rounded parameters while the optimizer updates the
    exact f32 master — straight-through estimation, the same contract as
    the ZeRO-1 trainers' compressed weight gather.
    """
    return all_gather_flat(owned, axis_name, coll)


def _gather_vjp_fwd(owned, axis_name, coll):
    return all_gather_flat(owned, axis_name, coll), None


def _gather_vjp_bwd(axis_name, coll, _res, ct):
    # same routing as the forward collectives (incl. the fused-kernel
    # path and its slice plan) — the gradient stream is where most of the
    # wire bytes are
    return (reduce_scatter(ct, axis_name, coll),)


all_gather_flat_vjp.defvjp(_gather_vjp_fwd, _gather_vjp_bwd)


def error_feedback_encode(codec, flat_g: jax.Array,
                          residual: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Compensate-then-compress (SparCML §3 / EF-SGD): returns
    ``(g_wire, new_residual)`` where ``g_wire = roundtrip(flat_g +
    residual)`` is the locally-quantized gradient handed to the collective
    and ``new_residual`` is what this pass dropped — carried to the next
    step in the train state, so every coordinate is eventually
    transmitted.

    The residual compensates the LOCAL quantization (the first wire pass
    of this device's contribution); per-hop requantization of partial sums
    inside the ring stays bounded by the codec's declared error_bound and
    is measured end-to-end by evals/codec_convergence.  For idempotent
    codecs (bfp, topk) the ring's first re-encode of ``g_wire`` is exact,
    so the local roundtrip costs no extra wire error at all."""
    g_comp = flat_g + residual
    g_wire = codec.roundtrip(g_comp)
    return g_wire, g_comp - g_wire


def all_reduce_mean(tree, axis_name: str, coll: CollectiveConfig):
    """Plain (unfused) mean all-reduce of a gradient pytree — for training
    loops that keep a separate optimizer.  Uses psum or the BFP ring."""
    n = lax.axis_size(axis_name)
    if coll.impl == "xla":
        return jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis_name) / n, tree)
    flat, meta = flatten_tree(tree, coll, n)
    red = ring_all_reduce_routed(flat, axis_name, coll, flat.shape[0] // n)
    return unflatten_tree(red / n, meta)


def init_master_shard(params_tree, axis_name: str, coll: CollectiveConfig,
                      opt_cfg: OptimizerConfig):
    """Build (w_own, opt_state, meta) from a replicated params pytree.
    Run inside shard_map once at startup — the analogue of the reference's
    first-iteration weight download into FPGA-local DDR (flags=1 path,
    hw/weight_update.sv MEM_INIT, sw/mlp_mpi_example_f32.cpp:700)."""
    n = lax.axis_size(axis_name)
    flat_w, meta = flatten_tree(params_tree, coll, n)
    w_own = shard_slice(flat_w, axis_name)
    opt_state = optim.init_state(opt_cfg, w_own.shape[0])
    return w_own, opt_state, meta
