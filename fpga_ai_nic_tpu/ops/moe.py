"""Mixture-of-experts FFN with expert parallelism (ep) over all-to-all.

The reference has no MoE (SURVEY.md §2 "Absent: ... EP, MoE"); this is the
north-star generalization of its core move — shard state across a ring and
move *data* to the state's owner instead of replicating state — applied to
FFN experts: expert weights shard over the ep mesh axis, and tokens travel
to their expert's owner via `lax.all_to_all` (ICI), the TPU analogue of the
reference streaming gradient slices to the slice's reducing node
(hw/all_reduce.sv slice rotation).

Design (GShard/Switch-style, static shapes for XLA):
- top-k routing with renormalized gates;
- fixed per-expert capacity C = ceil(T*k/E * capacity_factor); overflow
  tokens are dropped deterministically in token-major priority order (their
  residual path still carries them).  NOTE: under ep/sp sharding, capacity
  and drop priority are computed over each rank's LOCAL tokens (T = local
  token count), so once capacity binds, sharded and unsharded runs drop
  different tokens and diverge numerically — by design, matching how every
  capacity-based MoE shards; parity tests use generous capacity;
- dispatch/combine via scatter-add / gather, not [T,E,C] one-hot einsums —
  O(T*k*D) memory;
- load-balance aux loss computed over the *global* batch (psum over the
  batch axes) so sharded and unsharded training see the same regularizer.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0   # C = ceil(T*k/E * cf) per rank
    aux_weight: float = 0.01       # load-balance loss weight

    def __post_init__(self):
        assert 1 <= self.top_k <= self.num_experts

    def capacity(self, tokens: int) -> int:
        return max(1, math.ceil(tokens * self.top_k / self.num_experts
                                * self.capacity_factor))


def init_ffn(key: jax.Array, dim: int, ffn_dim: int, cfg: MoEConfig,
             dtype=jnp.float32) -> Dict:
    """Router + E SwiGLU experts.  wr stays f32 (routing logits are
    precision-sensitive); expert weights use the model dtype."""
    kr, k1, k3, k2 = jax.random.split(key, 4)
    E, D, F = cfg.num_experts, dim, ffn_dim

    def dense(k, fan_in, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                * jnp.sqrt(1.0 / fan_in)).astype(dtype)

    return {"wr": jax.random.normal(kr, (D, E), jnp.float32)
                  * jnp.sqrt(1.0 / D),
            "w1": dense(k1, D, (E, D, F)),
            "w3": dense(k3, D, (E, D, F)),
            "w2": dense(k2, F, (E, F, D))}


def param_specs(cfg: MoEConfig, ep_axis: Optional[str] = None,
                tp_axis: Optional[str] = None) -> Dict:
    """Experts shard over ep on their leading axis; the router replicates.

    With tp_axis, each expert's SwiGLU additionally Megatron-shards its
    hidden dim over tp (w1/w3 column, w2 row) — the same col/row split the
    dense FFN uses, applied per expert.  Every rank then computes a
    *partial* expert output over its hidden slice, and the model's existing
    row-parallel ``psum(tp)`` closes it; dispatch/routing run identically
    on every tp rank (tokens are tp-replicated), so tp composes with ep
    without touching the all_to_all."""
    return {"wr": P(), "w1": P(ep_axis, None, tp_axis),
            "w3": P(ep_axis, None, tp_axis), "w2": P(ep_axis, tp_axis, None)}


def _expert_ffn(params: Dict, h: jax.Array) -> jax.Array:
    """h: [E_local, C', D] -> [E_local, C', D], SwiGLU per expert."""
    g = jnp.einsum("ecd,edf->ecf", h, params["w1"])
    u = jnp.einsum("ecd,edf->ecf", h, params["w3"])
    g = jax.nn.silu(g.astype(jnp.float32)).astype(h.dtype)
    return jnp.einsum("ecf,efd->ecd", g * u, params["w2"])


def _route(params: Dict, xf: jax.Array, cfg: MoEConfig, C: int):
    """Top-k routing + token-major capacity assignment for local tokens
    xf [T, D].  Returns (gates [T,k], e_flat [T*k], onehot [T*k,E],
    keep [T*k] bool, slot [T*k], probs [T,E])."""
    E, k = cfg.num_experts, cfg.top_k
    logits = (xf.astype(jnp.float32) @ params["wr"])          # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gates, eidx = lax.top_k(probs, k)                         # [T, k]
    gates = gates / jnp.sum(gates, axis=-1, keepdims=True)

    # deterministic token-major priority: earlier tokens win capacity slots
    # (the reference drops nothing but orders everything by stream position;
    # same discipline here)
    e_flat = eidx.reshape(-1)                                 # [T*k]
    onehot = jax.nn.one_hot(e_flat, E, dtype=jnp.int32)       # [T*k, E]
    prio = jnp.cumsum(onehot, axis=0) - onehot
    pos = jnp.sum(prio * onehot, axis=-1)                     # [T*k]
    keep = (pos < C)
    slot = jnp.where(keep, pos, 0)
    return gates, e_flat, onehot, keep, slot, probs


def expert_stats(params: Dict, x: jax.Array, cfg: MoEConfig, *,
                 batch_axes: Sequence[str] = ()) -> Dict[str, jax.Array]:
    """Expert-utilization observability (the reference's flit/stall-counter
    discipline, hw/bfp_adapter.sv:705-729, applied to routing): per-expert
    load fractions, dropped-assignment fraction, and capacity occupancy for
    one batch.  Jit-safe; call inside the same shard_map/batch_axes setup as
    the training loss, or unsharded on a debug batch.  Standalone entry —
    reruns the router; inside a forward pass use
    ``moe_ffn(..., with_stats=True)``, which reuses the routing it already
    computed.

    Returns (E = num_experts):
      load_frac      [E]  fraction of kept assignments per expert (sums ~1)
      capacity_frac  [E]  kept assignments / capacity slots per expert
      drop_frac      []   fraction of routed assignments dropped
      capacity       []   per-expert capacity C used
    """
    B, S, D = x.shape
    T = B * S
    C = cfg.capacity(T)
    _, _, onehot, keep, _, _ = _route(params, x.reshape(T, D), cfg, C)
    return _stats_from_routing(onehot, keep, C, batch_axes)


def _stats_from_routing(onehot: jax.Array, keep: jax.Array, C: int,
                        batch_axes: Sequence[str] = ()
                        ) -> Dict[str, jax.Array]:
    kept = jnp.sum(onehot * keep[:, None].astype(jnp.int32),
                   axis=0).astype(jnp.float32)                # [E]
    total = jnp.float32(keep.size)                            # T*k local
    kept_total = jnp.sum(kept)
    n_ranks = jnp.float32(1.0)
    if batch_axes:
        axes = tuple(batch_axes)
        kept = lax.psum(kept, axes)
        total = lax.psum(total, axes)
        kept_total = lax.psum(kept_total, axes)
        n_ranks = lax.psum(n_ranks, axes)    # slots scale with rank count
    return {
        "load_frac": kept / jnp.maximum(kept_total, 1.0),
        "capacity_frac": kept / (C * n_ranks),
        "drop_frac": 1.0 - kept_total / total,
        "capacity": jnp.int32(C),
    }


def moe_ffn(params: Dict, x: jax.Array, cfg: MoEConfig, *,
            ep_axis: Optional[str] = None,
            batch_axes: Sequence[str] = (),
            with_stats: bool = False):
    """x: [B, S, D] local tokens -> (y [B, S, D], aux scalar)
    [, stats dict when with_stats — see `expert_stats`; reuses this pass's
    routing rather than rerunning the router].

    With ep_axis set (inside shard_map), expert leaves are the local
    [E/ep, ...] shards and tokens are exchanged with two all_to_alls
    (dispatch + return).  batch_axes: every mesh axis that shards tokens
    (dp/sp/ep) — used only for the global aux statistics.
    """
    B, S, D = x.shape
    T = B * S
    E, k = cfg.num_experts, cfg.top_k
    C = cfg.capacity(T)
    xf = x.reshape(T, D)
    gates, e_flat, onehot, keep, slot, probs = _route(params, xf, cfg, C)

    toks = jnp.repeat(xf, k, axis=0)                          # [T*k, D]
    buf = jnp.zeros((E, C, D), x.dtype).at[e_flat, slot].add(
        toks * keep[:, None].astype(x.dtype))

    if ep_axis is not None:
        ep = lax.axis_size(ep_axis)
        assert E % ep == 0, (E, ep)
        El = E // ep
        buf = buf.reshape(ep, El, C, D)
        buf = lax.all_to_all(buf, ep_axis, split_axis=0, concat_axis=0)
        h = buf.transpose(1, 0, 2, 3).reshape(El, ep * C, D)
        out = _expert_ffn(params, h)
        out = out.reshape(El, ep, C, D).transpose(1, 0, 2, 3)
        out = lax.all_to_all(out, ep_axis, split_axis=0, concat_axis=0)
        ybuf = out.reshape(E, C, D)
    else:
        ybuf = _expert_ffn(params, buf)

    w = (gates.reshape(-1) * keep.astype(jnp.float32)).astype(x.dtype)
    ytok = ybuf[e_flat, slot] * w[:, None]                    # [T*k, D]
    y = ytok.reshape(T, k, D).sum(axis=1).reshape(B, S, D)

    # load-balance aux (GShard): E * sum_i f_i * p_i over the GLOBAL batch.
    # f from hard assignments (zero grad), p from mean router probs.
    counts = jnp.sum(onehot, axis=0).astype(jnp.float32)      # [E]
    psum_p = jnp.sum(probs, axis=0)                           # [E]
    n_tok = jnp.float32(T)
    if batch_axes:
        axes = tuple(batch_axes)
        counts = lax.psum(counts, axes)
        psum_p = lax.psum(psum_p, axes)
        n_tok = lax.psum(n_tok, axes)
    f = counts / (n_tok * k)
    p = psum_p / n_tok
    aux = cfg.aux_weight * E * jnp.dot(f, p)
    if with_stats:
        return y, aux, _stats_from_routing(onehot, keep, C, batch_axes)
    return y, aux
