"""Hierarchical (intra x inter) 2-stage ring collectives — codec only on
the slow hop.

The flat ring (ops.ring) pays the codec on EVERY hop, including hops that
cross a fast boundary where full precision is free — the ICI links inside
a pod versus the DCN links between pods, or the tp axis versus the dp
axis of a dp x tp mesh.  EQuARX (arXiv:2506.17615) shows the right shape:
quantize only the slow phase of a hierarchical all-reduce.  This module
is that shape on our machinery:

  phase A (intra, FAST hop, codec-free):  ring reduce-scatter inside
      each group of ``n_intra`` consecutive ranks, full-precision f32 —
      after ni-1 hops, member j of every group holds the group-partial
      sums of the chunks whose intra index is j.
  phase B (inter, SLOW hop, codec ring):  ring reduce-scatter across
      groups (members with equal intra position form the inter rings),
      with the configured compress.Codec on the wire — the existing
      sliced double-buffered hop (`ops.ring._send`), so every codec that
      rides the flat ring rides the slow hop unchanged.

The all-gather runs the phases in reverse (inter codec gather of the
owned chunk — encoded once, forwarded verbatim, the ops.ring contract —
then the raw intra gather), so updated weights also cross the slow
boundary exactly once, quantized.

Device mapping over ONE flat mesh axis of n = ni * ng devices (the
"declared intra/inter factorization" of a flat dp axis; a dp x tp mesh
flattened major-to-minor has the same layout): device d is group
``d // ni``, intra position ``d % ni``.  Chunk ownership stays NATURAL
ORDER — device d ends with chunk d, exactly like the flat ring, so the
ZeRO-1 shard <-> device mapping is topology-invariant and a trainer can
switch topology without resharding.

Numerics contract: phase A's add order is the flat-ring schedule inside
the group; phase B's is the flat-ring schedule across groups.  For
codec=None the result is the same SUM as the flat ring under a different
association — bit-identical whenever the additions are exact (integer-
valued payloads; tests/test_ring_hier.py pins this), and spec'd bit-for-
bit by the numpy golden twin (`compress.golden.hier_reduce_scatter`) for
every codec.  Wire accounting is exact per hop and phase
(`HierarchicalPlan.wire_bytes`), pinned statically by graftlint J9:
intra ppermutes must move f32 and exactly the declared raw bytes, inter
ppermutes exactly the declared codec bytes.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from . import integrity as _integrity
from . import ring as ring_ops
# the shared protocol IR: the phase program (hop counts, subring
# permutations, conservation message ids) is emitted once there and
# consumed both by the lowerings below and by graftmc's checked streams
# (verify.opstream.hier_op_stream) — no second schedule definition
from ..verify import opstream as _opstream


# ---------------------------------------------------------------------------
# static plan / wire accounting
# ---------------------------------------------------------------------------

class HierarchicalPlan(NamedTuple):
    """Static shape + exact byte accounting of one hierarchical
    all-reduce (reduce-scatter and/or all-gather) of an [L]-element f32
    payload over n = n_intra * n_inter devices."""

    L: int                 # flat payload elements (padded, L % n == 0)
    n: int
    n_intra: int           # fast-hop group size (ni)
    n_inter: int           # slow-hop ring length (ng)
    codec_name: Optional[str]        # inter-hop wire format (None = f32)
    # exact per-device bytes on the wire, per phase and collective:
    rs_intra_bytes: int
    rs_inter_bytes: int
    ag_intra_bytes: int
    ag_inter_bytes: int

    def wire_bytes(self, which: str = "all_reduce") -> int:
        """Exact per-device wire bytes: "reduce_scatter", "all_gather" or
        "all_reduce" (= RS + AG).  The declaration graftlint J9 pins the
        lowered program's ppermute operands to."""
        rs = self.rs_intra_bytes + self.rs_inter_bytes
        ag = self.ag_intra_bytes + self.ag_inter_bytes
        return {"reduce_scatter": rs, "all_gather": ag,
                "all_reduce": rs + ag}[which]

    def intra_bytes(self, which: str = "all_reduce") -> int:
        return {"reduce_scatter": self.rs_intra_bytes,
                "all_gather": self.ag_intra_bytes,
                "all_reduce": self.rs_intra_bytes + self.ag_intra_bytes
                }[which]

    def inter_bytes(self, which: str = "all_reduce") -> int:
        return {"reduce_scatter": self.rs_inter_bytes,
                "all_gather": self.ag_inter_bytes,
                "all_reduce": self.rs_inter_bytes + self.ag_inter_bytes
                }[which]

    def describe(self) -> Dict[str, Any]:
        return {
            "topology": "hier",
            "n": self.n, "n_intra": self.n_intra, "n_inter": self.n_inter,
            "codec": self.codec_name or "none",
            "payload_elems": self.L,
            "rs_intra_bytes": self.rs_intra_bytes,
            "rs_inter_bytes": self.rs_inter_bytes,
            "ag_intra_bytes": self.ag_intra_bytes,
            "ag_inter_bytes": self.ag_inter_bytes,
            "wire_bytes_all_reduce": self.wire_bytes("all_reduce"),
        }


def check_factorization(n: int, n_intra: int) -> int:
    """Validate the declared factorization; returns n_inter."""
    if n_intra < 1 or n % n_intra != 0:
        raise ValueError(
            f"intra_size={n_intra} does not factor the {n}-device axis "
            "(need 1 <= intra_size dividing n)")
    return n // n_intra


def plan_hier(L: int, n: int, n_intra: int,
              compression=None) -> HierarchicalPlan:
    """Exact wire accounting for a hierarchical all-reduce of [L] f32.

    Per device: phase A sends (ni-1) raw-f32 units of L/ni elements each
    (reduce-scatter) and the same again for the gather; phase B sends
    (ng-1) codec payloads of the final chunk C = L/n per collective.
    ``compression`` is a Codec or (legacy) BFPConfig — same normalization
    as ops.ring."""
    ng = check_factorization(n, n_intra)
    if L % n != 0:
        raise ValueError(f"need L divisible by n={n}, got {L}")
    codec = ring_ops._as_codec(compression)
    C = L // n
    unit_a = L // n_intra                   # ng * C raw f32 elements
    inter_payload = (codec.wire_bytes(C) if codec is not None else C * 4)
    return HierarchicalPlan(
        L=L, n=n, n_intra=n_intra, n_inter=ng,
        codec_name=codec.name if codec is not None else None,
        rs_intra_bytes=(n_intra - 1) * unit_a * 4,
        rs_inter_bytes=(ng - 1) * inter_payload,
        ag_intra_bytes=(n_intra - 1) * unit_a * 4,
        ag_inter_bytes=(ng - 1) * inter_payload)


def wire_bytes_per_device(L: int, n: int, n_intra: int,
                          compression=None) -> int:
    """Hierarchical analogue of ops.ring.wire_bytes_per_device: exact
    per-device bytes for one ALL-REDUCE (RS + AG), both phases."""
    return plan_hier(L, n, n_intra, compression).wire_bytes("all_reduce")


# ---------------------------------------------------------------------------
# subring permutations — delegates to the shared protocol IR (one
# definition; tests pin the delegation by identity)
# ---------------------------------------------------------------------------

_intra_perm = _opstream.intra_perm
_inter_perm = _opstream.inter_perm


# ---------------------------------------------------------------------------
# collectives (inside shard_map over the flat axis)
# ---------------------------------------------------------------------------

def _split_idx(axis_name: str, ni: int):
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    return n, idx // ni, idx % ni


def hier_reduce_scatter(x: jax.Array, axis_name: str, n_intra: int, *,
                        compression=None,
                        slice_elems: Optional[int] = None,
                        unroll: bool = False,
                        integrity: bool = False):
    """2-stage ring reduce-scatter of a flat per-device vector: raw f32
    over the fast intra hop, the codec ring over the slow inter hop.

    x: [L] with L % n == 0.  Returns [L // n]: this device's fully
    reduced chunk, chunk index == device index (the flat ring's natural
    ownership, so callers are topology-agnostic).

    ``integrity=True`` checksums BOTH phases' wire payloads — the raw
    f32 intra words and the encoded inter frames — on both sides of
    every hop (ops.integrity conservation) and returns ``(owned,
    wire_ok)``.  No checksum rides the wire: the J9 per-phase byte
    accounting is unchanged.
    """
    codec = ring_ops._as_codec(compression)
    ni = int(n_intra)
    n, g, j = _split_idx(axis_name, ni)
    ng = check_factorization(n, ni)
    if x.ndim != 1 or x.shape[0] % n != 0:
        raise ValueError(f"need flat length divisible by {n}, got {x.shape}")
    if n == 1:
        return (x, jnp.bool_(True)) if integrity else x
    C = x.shape[0] // n
    x = ring_ops._tap(x, "ring_hier.reduce_scatter")
    chk = _integrity.zero_carry() if integrity else None
    # THE phase program (hop counts, perms, and — for integrity — the
    # single message counter spanning both phases: intra hop s is
    # message s, inter hop s slice k is (ni-1) + s*stride + k, so no
    # two messages in the shared carry ever share a weight).  graftmc's
    # checked hier streams expand the same program.
    stride_b = ring_ops._send_n_messages(codec, C, slice_elems)
    prog = _opstream.hier_program(n, ni, s_inter=stride_b)

    # phase A — intra ring over units [j'] = concat_g'(chunk g'*ni + j'),
    # raw f32 (the whole point: full precision is free on the fast hop)
    units = x.reshape(ng, ni, C).transpose(1, 0, 2).reshape(ni, ng * C)
    if ni > 1:
        pa = prog.rs_intra
        perm_a = list(pa.perm)

        if integrity:
            def hop_a_i(s, carry):
                u, ck = carry
                send = jnp.take(u, ((j - s - 1) % ni)[None], axis=0)[0]
                recv, ck = ring_ops._send(
                    send, axis_name, n, None, perm=perm_a, chk=ck,
                    msg_base=pa.msg(s))
                return u.at[(j - s - 2) % ni].add(recv), ck

            units, chk = lax.fori_loop(0, pa.hops, hop_a_i, (units, chk),
                                       unroll=unroll)
        else:
            def hop_a(s, u):
                send = jnp.take(u, ((j - s - 1) % ni)[None], axis=0)[0]
                recv = ring_ops._send(send, axis_name, n, None,
                                      perm=perm_a)
                return u.at[(j - s - 2) % ni].add(recv)

            units = lax.fori_loop(0, pa.hops, hop_a, units, unroll=unroll)
    # own[q] = sum over this group's members of chunk q*ni + j
    own = jnp.take(units, j[None], axis=0)[0].reshape(ng, C)

    # phase B — inter ring over the ng group-partial chunks, codec wire
    if ng > 1:
        pb = prog.rs_inter
        perm_b = list(pb.perm)

        if integrity:
            def hop_b_i(s, carry):
                u, ck = carry
                send = jnp.take(u, ((g - s - 1) % ng)[None], axis=0)[0]
                recv, ck = ring_ops._send(
                    send, axis_name, n, codec, slice_elems, perm=perm_b,
                    chk=ck, msg_base=pb.msg(s))
                return u.at[(g - s - 2) % ng].add(recv), ck

            own, chk = lax.fori_loop(0, pb.hops, hop_b_i, (own, chk),
                                     unroll=unroll)
        else:
            def hop_b(s, u):
                send = jnp.take(u, ((g - s - 1) % ng)[None], axis=0)[0]
                recv = ring_ops._send(send, axis_name, n, codec,
                                      slice_elems, perm=perm_b)
                return u.at[(g - s - 2) % ng].add(recv)

            own = lax.fori_loop(0, pb.hops, hop_b, own, unroll=unroll)
    # final ownership: chunk g*ni + j == this device's index
    owned = jnp.take(own, g[None], axis=0)[0]
    if not integrity:
        return owned
    return owned, _integrity.conservation_ok(chk[0], chk[1], axis_name)


def hier_all_gather(owned: jax.Array, axis_name: str, n_intra: int, *,
                    compression=None, unroll: bool = False,
                    integrity: bool = False):
    """2-stage ring all-gather: the codec inter gather first (each chunk
    crosses the slow boundary exactly once, encoded at first send and
    forwarded verbatim — the ops.ring replica-identity contract), then
    the raw intra gather.  owned: [C], device d contributes chunk d;
    returns [n * C] in natural chunk order (with ``integrity=True``:
    ``(gathered, wire_ok)`` — both phases' frames checksummed both
    sides, ops.integrity conservation)."""
    codec = ring_ops._as_codec(compression)
    ni = int(n_intra)
    n, g, j = _split_idx(axis_name, ni)
    ng = check_factorization(n, ni)
    owned = ring_ops._tap(owned, "ring_hier.all_gather")
    if n == 1:
        out1 = (codec.roundtrip(owned).astype(owned.dtype)
                if codec is not None else owned)
        return (out1, jnp.bool_(True)) if integrity else out1
    C = owned.shape[0]
    chk = _integrity.zero_carry() if integrity else None
    tap = ring_ops._tap_wire
    # THE phase program for the gather direction (its own conservation
    # carry: inter hop s is message s, intra hop s is (ng-1) + s)
    prog = _opstream.hier_program(n, ni)

    # phase B' — inter all-gather of the owned chunk across groups
    blocks = jnp.zeros((ng, C), owned.dtype)
    if ng > 1:
        perm_b = list(prog.ag_inter.perm)
        if codec is None:
            pay_b = (owned,)
            blocks = blocks.at[g].set(owned)
        else:
            pay_b = codec.encode(owned)
            # the contributor stores the same quantized bytes it sends:
            # every replica sees wire-identical values for every chunk
            blocks = blocks.at[g].set(codec.decode(pay_b, C, owned.dtype))

        def _landed_b(p):
            return p[0] if codec is None else codec.decode(p, C,
                                                           owned.dtype)

        if integrity:
            def hop_b_i(s, carry):
                out_, p, (sa, ra) = carry
                w = _integrity.hop_weight(prog.ag_inter.msg(s))
                sa = sa + w * _integrity.payload_checksum(p)
                p = tuple(lax.ppermute(q, axis_name, perm_b) for q in p)
                p = tap(p, "ring.wire")
                ra = ra + w * _integrity.payload_checksum(p)
                return (out_.at[(g - s - 1) % ng].set(_landed_b(p)), p,
                        (sa, ra))

            blocks, _, chk = lax.fori_loop(
                0, prog.ag_inter.hops, hop_b_i, (blocks, pay_b, chk),
                unroll=unroll)
        else:
            def hop_b(s, carry):
                out_, p = carry
                p = tuple(lax.ppermute(q, axis_name, perm_b) for q in p)
                p = tap(p, "ring.wire")
                return out_.at[(g - s - 1) % ng].set(_landed_b(p)), p

            blocks, _ = lax.fori_loop(0, prog.ag_inter.hops, hop_b,
                                      (blocks, pay_b), unroll=unroll)
    else:
        # no slow boundary to cross: nothing is quantized (the flat
        # ring's n == 1 quantize exists for replica identity, which the
        # raw intra hops below preserve by construction)
        blocks = blocks.at[g].set(owned)
    # member j now holds blocks[q] = chunk q*ni + j for every group q

    # phase A' — raw intra all-gather of the [ng * C] block
    flat_block = blocks.reshape(ng * C)
    out = jnp.zeros((ni, ng * C), owned.dtype).at[j].set(flat_block)
    if ni > 1:
        perm_a = list(prog.ag_intra.perm)

        if integrity:
            def hop_a_i(s, carry):
                out_, p, (sa, ra) = carry
                # continue the message counter past phase B's ng-1
                # inter frames so the shared carry never reuses a weight
                w = _integrity.hop_weight(prog.ag_intra.msg(s))
                sa = sa + w * _integrity.payload_checksum(p)
                p = tuple(lax.ppermute(q, axis_name, perm_a) for q in p)
                p = tap(p, "ring.wire")
                ra = ra + w * _integrity.payload_checksum(p)
                return out_.at[(j - s - 1) % ni].set(p[0]), p, (sa, ra)

            out, _, chk = lax.fori_loop(
                0, prog.ag_intra.hops, hop_a_i, (out, (flat_block,), chk),
                unroll=unroll)
        else:
            def hop_a(s, carry):
                out_, pay = carry
                pay = lax.ppermute(pay, axis_name, perm_a)
                # same wire-tap contract as every other hop (identity
                # when no tap is installed): a wirebit spec at
                # 'collective' must be able to fire on the intra AG
                # frames too, integrity trace or not
                pay = tap((pay,), "ring.wire")[0]
                return out_.at[(j - s - 1) % ni].set(pay), pay

            out, _ = lax.fori_loop(0, prog.ag_intra.hops, hop_a,
                                   (out, flat_block), unroll=unroll)
    # out[p] = blocks of member p = chunks {q*ni + p}; restore natural
    # chunk order (inverse of the reduce-scatter's regrouping)
    full = out.reshape(ni, ng, C).transpose(1, 0, 2).reshape(n * C)
    if not integrity:
        return full
    return full, _integrity.conservation_ok(chk[0], chk[1], axis_name)


def hier_all_reduce(x: jax.Array, axis_name: str, n_intra: int, *,
                    compression=None,
                    slice_elems: Optional[int] = None,
                    unroll: bool = False,
                    integrity: bool = False):
    """Full hierarchical all-reduce (sum) = 2-stage RS + 2-stage AG.
    With ``integrity=True`` returns ``(reduced, wire_ok)``."""
    if integrity:
        owned, ok_rs = hier_reduce_scatter(
            x, axis_name, n_intra, compression=compression,
            slice_elems=slice_elems, unroll=unroll, integrity=True)
        full, ok_ag = hier_all_gather(owned, axis_name, n_intra,
                                      compression=compression,
                                      unroll=unroll, integrity=True)
        return full, ok_rs & ok_ag
    owned = hier_reduce_scatter(x, axis_name, n_intra,
                                compression=compression,
                                slice_elems=slice_elems, unroll=unroll)
    return hier_all_gather(owned, axis_name, n_intra,
                           compression=compression, unroll=unroll)
