"""Exact wire-integrity checksums over ENCODED transfer payloads.

PR 1's collective integrity layer checks VALUE-space chunk sums against a
codec-derived tolerance band (`runtime.chaos.collective_integrity`) — the
right tool for "is the arithmetic sane", and provably blind to the class
the serving ledger documents: a FINITE wrong value.  A flipped mantissa
bit in a BFP/int8 frame decodes to a plausible, in-band number; a
wrong-KEY KV page yields wrong-but-normal-magnitude logits.  No tolerance
band, norm guard or logit guard can see either (docs/SERVING.md's honest
boundary, pre-PR-12).

This module is the exact tier underneath: a checksum over the BITS that
cross the wire — the encoded frames themselves (int8 mantissa/scale
tiles, int16 top-k indices, raw f32 words), not the decoded values — so
the check is bit-exact with NO tolerance band at all.  Quantization noise
cannot trip it (the checksum is computed on the post-encode frames both
sides agree on); any corruption of the frames in flight must.

The checksum is an odd-weighted wraparound word sum:

    chk(x) = sum_i (2*i + 1) * word_i(x)      (mod 2^32)

where ``word_i`` enumerates the payload's bytes widened to uint32 words
(4-byte dtypes bitcast directly; 1-/2-byte dtypes widened).  Properties
the wire plane leans on:

  exact        integer arithmetic, wraparound mod 2^32 — deterministic on
               every backend, inside jit/shard_map, at any slicing.
  additive     checksums of independent messages ADD, so a multi-hop
               collective can verify by CONSERVATION: every message is
               checksummed once at send and once at receive, and
               ``psum(send_acc - recv_acc) == 0`` iff every payload
               arrived bit-identical (hop/message weights keep distinct
               messages from aliasing).  No checksum ever rides the wire
               itself, so the exact ppermute byte accounting frozen by
               J4/J8/J9/J11 is UNCHANGED with integrity on.
  single-error never misses: the weights are odd, hence invertible mod
               2^32, so any single corrupted word changes the sum.
               Multi-word corruptions cancel only on contrived algebraic
               alignment (and the chaos battery injects real patterns).

Numpy golden twins live in `compress.golden` (``golden_word_checksum``,
``golden_payload_checksum``) — the same spec-first discipline as every
codec (tests/test_integrity.py holds them bit-for-bit equal).

The durable-state plane reuses the SAME checksum spec at rest:
`utils.checkpoint` manifests checksum every stored leaf/shard with the
odd-weighted u32 word sum over the post-compress bytes (u8-widened,
``bytes_checksum`` delegating to the golden twin), so the wire tier
and the disk tier (graftlint J12 / J14) trip on exactly the same
algebra — docs/DURABILITY.md.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["words_u32", "word_checksum", "payload_checksum",
           "hop_weight", "conservation_ok", "replica_consistent",
           "page_checksums"]


def words_u32(x: jax.Array) -> jax.Array:
    """A payload array as a flat vector of uint32 words — the canonical
    byte view the checksum is defined over.  4-byte dtypes bitcast
    word-for-word; 1-/2-byte dtypes widen (zero-extend) so every stored
    bit lands in exactly one word.  8-byte dtypes are rejected: nothing
    8-byte may ride the wire (graftlint J2)."""
    x = x.reshape(-1)
    size = jnp.dtype(x.dtype).itemsize
    if size == 4:
        return lax.bitcast_convert_type(x, jnp.uint32)
    if size == 2:
        return lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
    if size == 1:
        return lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.uint32)
    raise TypeError(f"no wire payload may have itemsize {size} "
                    f"(dtype {x.dtype}); J2 forbids 8-byte avals")


def word_checksum(x: jax.Array) -> jax.Array:
    """uint32 scalar: odd-weighted wraparound word sum of one array."""
    w = words_u32(x)
    weights = (jnp.arange(w.shape[0], dtype=jnp.uint32) << 1) | jnp.uint32(1)
    return jnp.sum(w * weights, dtype=jnp.uint32)


def payload_checksum(payload: Sequence[jax.Array]) -> jax.Array:
    """uint32 scalar over a hop's payload TUPLE (the codec's encode
    output, or a 1-tuple of the raw array).  Per-element odd multipliers
    keep a mantissa<->scale swap from aliasing."""
    acc = jnp.uint32(0)
    for k, p in enumerate(payload):
        acc = acc + jnp.uint32(2 * k + 1) * word_checksum(p)
    return acc


def hop_weight(s) -> jax.Array:
    """Odd per-hop message weight (odd => invertible mod 2^32, so a
    weighted single-word corruption can never vanish).  ``s`` may be a
    traced loop index."""
    return (jnp.asarray(s).astype(jnp.uint32) << 1) | jnp.uint32(1)


def conservation_ok(send_acc: jax.Array, recv_acc: jax.Array,
                    axis_name: str) -> jax.Array:
    """Replicated bool: every message sent on the axis arrived
    bit-identical.  Each payload is checksummed once on the send side and
    once on the receive side with the SAME hop weight; the ring topology
    delivers every message exactly once, so the global weighted sums must
    agree — ``psum`` over the axis of (send - recv) is 0 iff no frame
    changed in flight (wraparound arithmetic on both sides)."""
    delta = send_acc - recv_acc                  # u32 wraparound
    # psum in int32 (bit-identical reinterpretation): integer all-reduce
    # support is universal for i32, and wraparound addition commutes with
    # the bitcast
    total = lax.psum(lax.bitcast_convert_type(delta, jnp.int32), axis_name)
    return total == 0


def replica_consistent(x: jax.Array, axis_name: str) -> jax.Array:
    """Replicated bool: every device on the axis holds bit-identical
    ``x``.  The post-hoc exact check for REPLICATING collectives
    (all-gather): every replica's bytes must agree, and a frame corrupted
    in flight damages only the receiver and its downstream forwards —
    never the contributor's locally-stored copy — so any single wire
    corruption breaks the agreement.  Used where the hop-conservation
    carry cannot reach (the fused Pallas all-gather kernel, whose wire
    lives inside the kernel); checksum compare only, no payload rides
    the wire."""
    chk = lax.bitcast_convert_type(word_checksum(x), jnp.int32)
    return lax.pmax(chk, axis_name) == lax.pmin(chk, axis_name)


# ---------------------------------------------------------------------------
# per-page KV-pool checksums (the serving decode tick's exact tier)
# ---------------------------------------------------------------------------

def page_checksums(pool) -> jax.Array:
    """[n_pages] uint32 — one exact checksum per KV-pool page, summed
    over every layer's K and V bytes of that page (weights restart per
    page per array; per-array odd multipliers keep layer/K-V swaps from
    aliasing).  The serving engine records this ledger as each tick's
    program writes the pool, and the NEXT tick verifies its input pool
    against it — so a finite wrong-KEY page (bytes changed outside the
    programs that maintain the ledger) trips bit-exactly BEFORE the tick
    emits a token, closing the class the logit guard provably cannot see.
    The handoff program verifies landed pages against the same ledger
    (`serve.handoff.lower_apply(integrity=True)`), giving migrated KV
    end-to-end write-time -> land-time coverage.

    A zero-filled pool checksums to all-zeros (every term is 0), so a
    fresh ledger is ``jnp.zeros([n_pages], uint32)`` by construction.
    """
    return gathered_page_checksums(
        [layer[key] for layer in pool for key in ("k", "v")])


def gathered_page_checksums(blocks: Sequence[jax.Array]) -> jax.Array:
    """[n_pages] uint32 — one checksum per leading-axis page, summed
    over the blocks with per-array odd multipliers (weights restart per
    page per array, so layer/K-V swaps never alias).  THE per-page
    checksum definition: `page_checksums` flattens the pool into the
    same layer-major K-then-V block order the handoff program uses for
    its gathered ``[n_move, kvl, ps, hd]`` operands, so a landed page
    verifies bit-for-bit against the ledger entry recorded when the
    page was written — one spec, both call shapes."""
    acc = None
    for j, arr in enumerate(blocks):
        n_pages = arr.shape[0]
        w = words_u32(arr).reshape(n_pages, -1)
        weights = ((jnp.arange(w.shape[1], dtype=jnp.uint32) << 1)
                   | jnp.uint32(1))
        per_page = jnp.sum(w * weights[None, :], axis=1, dtype=jnp.uint32)
        term = jnp.uint32(2 * j + 1) * per_page
        acc = term if acc is None else acc + term
    return acc


ChkCarry = Tuple[jax.Array, jax.Array]


def zero_carry() -> ChkCarry:
    """(send_acc, recv_acc) uint32 accumulator pair for a collective."""
    return (jnp.uint32(0), jnp.uint32(0))
