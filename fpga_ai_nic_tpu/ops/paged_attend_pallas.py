"""Pallas TPU paged gather-attend: the serving plane's decode fast path.

`models.llama_decode.forward_paged` (the reference path, and the bitwise
oracle for this kernel) gathers each request's K/V pages into a
materialized ``[R, kv, P*page_size, hd]`` view every layer of every
decode step — bytes/token therefore scale with the ALLOCATED page span
of the table, not the live KV, and the gather write+readback doubles the
traffic on top.  This kernel walks the int32 page table and DMAs each
LIVE page HBM->VMEM inside the kernel instead, so the gathered view is
never formed: dead table slots move zero bytes, and a page's K/V tile is
read exactly once per (request, kv-head) cell.

One definition discipline (PR 14): the per-page DMA schedule — prologue
launch, depth-deep double buffer over dedicated VMEM spans with
semaphores cycling mod depth, wait-before-relaunch hazard order, dead
slot handling — is NOT written here.  It is emitted by
`verify.opstream.PagedAttendEmitter` through `_PagedSink`, the same
stream `verify.mc.build_gather` model-checks exhaustively (semaphore
slot aliasing under every landing interleaving) and
`verify.opstream.check_gather_coverage` pins statically (every live
(page, offset) covered exactly once, zero overlap, zero dead-page
bytes).

Kernel layout (one cell per (request slot, kv head)):

  grid (R, n_kv)   q arrives as the cell's [G*T, hd] f32 query group
                   (G = n_heads/n_kv — GQA and the kv_rep branch both
                   reduce to head-group mapping; MHA is G == 1); the
                   K/V pools stay un-blocked in HBM (memory_space ANY)
                   and are touched only by the emitter's DMAs.
  epilogue         ONE [G*T, hd] x [P*page_size, hd] score dot over the
                   whole landed K row, the exact masked softmax (into
                   the scores scratch, the softmax->PV handoff), then
                   one PV contraction — deliberately NOT the
                   online-rescale flash accumulation, and deliberately
                   not per-page score tiles either: full-row is the
                   reference einsum's per-(r, kv) gemm shape, which is
                   what makes the kernel BITWISE equal to
                   `forward_paged`'s `_cached_attend` on the same
                   backend (per-page tiles drift by an ulp at G*T == 1,
                   where XLA lowers the matvec differently;
                   tests/test_paged_attend.py pins parity across
                   GQA/MHA, ragged occupancy, dirty pools and tp).

Parity at the dead/live boundary rides the same mask-parity rule the
reference path documents: masked positions score exactly -1e30 in both
paths, their softmax weights underflow to exactly +0.0, and a +-0 term
never moves an f32 sum — so skipping a dead page's bytes (this kernel)
and attending its garbage behind the mask (the reference gather) agree
bit-for-bit.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import compat
from ..verify import opstream as _opstream

LANES = 128
_NEG = -1e30
_DEF_DEPTH = 2


def _is_tpu() -> bool:
    return jax.devices()[0].platform in ("tpu", "axon")


def _vma(*arrs):
    vma = frozenset()
    for a in arrs:
        vma = vma | jax.typeof(a).vma
    return vma


class _PagedSink(_opstream.OpSink):
    """Maps `PagedAttendEmitter`'s abstract ops onto one grid cell's
    DMA/semaphore/VPU resources.  The emitter owns the FULL schedule
    (launch depth, wait order, dead-slot handling); this sink only binds
    each abstract op to a real call and lowers ``when`` to `pl.when` —
    the liveness predicate is a traced bound here (n_live comes from the
    cell's SMEM position), so the rolled lowering is the only one.
    Hazard-predecessor annotations on dma_start are checker evidence
    (`check_dma_discipline`), not schedule — ignored, as in
    `ring_pallas._KernelSink`."""

    def __init__(self, *, dma_start, dma_wait, local):
        self._dma_start = dma_start
        self._dma_wait = dma_wait
        self._local = local

    def when(self, cond):
        return pl.when(cond)

    def dma_start(self, chan, i, *conf):
        self._dma_start(chan, i)

    def dma_wait(self, chan, i):
        self._dma_wait(chan, i)

    def local(self, name, *args):
        self._local(name, *args)


def _paged_kernel(table_ref, pos_ref, qg_ref, kp_ref, vp_ref, out_ref,
                  kbuf, vbuf, scores, sem, *, n_pages, page_size, n_t,
                  depth, sm_scale):
    """One (request, kv-head) cell: drive the shared emitter, then the
    exact epilogue.  n_pages/page_size/n_t(=T)/depth are static; the
    liveness bound is the cell's traced position."""
    r = pl.program_id(0)
    kh = pl.program_id(1)
    ps = page_size
    pos_r = pos_ref[r]
    # pages holding any visible position j <= pos + T - 1 (clamped to
    # the table width; inactive slots sit at pos 0 -> one live page)
    n_live = jnp.minimum((pos_r + n_t - 1) // ps + 1, n_pages)
    gt = qg_ref.shape[2]
    k_chan = _opstream.PagedAttendEmitter.K_CHAN

    def page_dma(chan, i):
        """THE transfer of table slot i's K or V page tile: HBM page
        [page, kh] -> this slot's dedicated VMEM span, on the slot's
        mod-depth semaphore.  Built identically by start and wait (the
        descriptor must match for the wait to pair)."""
        page = table_ref[r, i]
        if chan == k_chan:
            return pltpu.make_async_copy(
                kp_ref.at[page, kh], kbuf.at[pl.ds(i * ps, ps)],
                sem.at[i % depth, 0])
        return pltpu.make_async_copy(
            vp_ref.at[page, kh], vbuf.at[pl.ds(i * ps, ps)],
            sem.at[i % depth, 1])

    def local(name, *args):
        if name == "attend_tile":
            # page i's K/V tiles are landed (the emitter ordered this
            # marker after their waits); consumption is deferred to the
            # fused epilogue, which runs after EVERY wait — a sound
            # refinement of the abstract consume-here marker, and the
            # only lowering that stays bitwise: per-page score tiles
            # drift by an ulp at G*T == 1, where XLA lowers the matvec
            # differently than the reference's full-row contraction.
            pass
        elif name == "dead_fill":
            # a dead slot's V span must be FINITE zeros: its softmax
            # weights are exact +0 and +0 * 0 == +0, the same +-0
            # equivalence class as the reference's +0 * garbage.  Its
            # score span is never written — the mask overwrites it.
            i = args[0]
            vbuf[pl.ds(i * ps, ps), :] = jnp.zeros(
                (ps, vbuf.shape[1]), vbuf.dtype)
        elif name == "softmax":
            # the reference's exact contraction shape — ONE [G*T, hd] x
            # [P*ps, hd] score dot over the whole landed row (dead K
            # spans are read as garbage and land behind the mask) —
            # then its exact mask + softmax: row g*T + t sees key j iff
            # j <= pos + t
            kk = kbuf[...].astype(jnp.float32)
            s = lax.dot_general(qg_ref[0, 0], kk,
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            s = s * sm_scale
            jj = lax.broadcasted_iota(jnp.int32, (gt, n_pages * ps), 1)
            tt = lax.broadcasted_iota(jnp.int32, (gt, n_pages * ps),
                                      0) % n_t
            visible = jj <= pos_r + tt
            s = jnp.where(visible, s, jnp.float32(_NEG))
            scores[...] = jax.nn.softmax(s, axis=-1)
        else:                                        # "pv"
            p = scores[...]
            vv = vbuf[...].astype(jnp.float32)
            out_ref[0, 0] = lax.dot_general(
                p, vv, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    sink = _PagedSink(dma_start=lambda chan, i: page_dma(chan, i).start(),
                      dma_wait=lambda chan, i: page_dma(chan, i).wait(),
                      local=local)
    _opstream.PagedAttendEmitter(n_pages, depth).stream(
        sink, lambda i: i < n_live)


def supported(page_size: int, head_dim: int, *,
              interpret: Optional[bool] = None) -> bool:
    """Can the paged kernel take this pool geometry?  The hardware path
    needs lane-tileable page tiles (see `_validate`); interpret mode
    takes anything (how the CPU parity battery runs)."""
    if interpret is None:
        interpret = not _is_tpu()
    return bool(interpret) or (page_size % LANES == 0
                               and head_dim % LANES == 0)


def _validate(q, pool_k, pool_v, page_table, pos, page_size, depth,
              interpret) -> None:
    if q.ndim != 4:
        raise ValueError(f"paged_gather_attend: q must be [R, H, T, hd], "
                         f"got {q.shape}")
    R, H, _T, hd = q.shape
    if pool_k.shape != pool_v.shape or pool_k.ndim != 4:
        raise ValueError(
            "paged_gather_attend: K/V pools must share one "
            f"[n_pages, kv, page_size, hd] shape, got k={pool_k.shape} "
            f"v={pool_v.shape}")
    n_kv = pool_k.shape[1]
    if pool_k.shape[2] != page_size or pool_k.shape[3] != hd:
        raise ValueError(
            f"paged_gather_attend: pool pages {pool_k.shape} do not "
            f"match page_size={page_size}, head_dim={hd}")
    if n_kv == 0 or H % n_kv != 0:
        raise ValueError(
            f"paged_gather_attend: n_heads={H} must be a multiple of "
            f"the pool's kv heads={n_kv} (GQA head-group mapping)")
    if page_table.ndim != 2 or page_table.shape[0] != R:
        raise ValueError(
            f"paged_gather_attend: page_table must be [R={R}, P], got "
            f"{page_table.shape}")
    if page_table.dtype != jnp.int32:
        raise ValueError(
            "paged_gather_attend: page_table must be int32 (the walked "
            f"table), got {page_table.dtype}")
    if pos.shape != (R,):
        raise ValueError(
            f"paged_gather_attend: pos must be [R={R}], got {pos.shape}")
    if depth < 1:
        raise ValueError(f"paged_gather_attend: depth must be >= 1, "
                         f"got {depth}")
    if not interpret and (page_size % LANES or hd % LANES):
        # same contract as flash_pallas's Sk check: fail HERE with a
        # real error naming the config, not later as an opaque Mosaic
        # layout error — the page tile [page_size, hd] is the unit every
        # DMA, score column span and PV contraction tiles by
        bad = [f"page_size={page_size}"] if page_size % LANES else []
        bad += [f"head_dim={hd}"] if hd % LANES else []
        raise ValueError(
            "paged_gather_attend needs lane-tileable page tiles on "
            f"hardware: {' and '.join(bad)} not a multiple of {LANES} "
            f"(pool shape {pool_k.shape}); repack the pool geometry or "
            "use attend_impl='reference' (the XLA gathered-view path)")


def paged_gather_attend(q, pool_k, pool_v, page_table, pos, *,
                        page_size: int, sm_scale: Optional[float] = None,
                        depth: int = _DEF_DEPTH,
                        interpret: Optional[bool] = None) -> jax.Array:
    """Paged-KV decode attention without the gathered view.

    q: [R, H, T, hd] (post-rope, any float dtype — scored in f32 like
    the reference); pool_k/pool_v: [n_pages, kv, page_size, hd] (the
    serve pool AFTER this call's K/V scatter); page_table: [R, P] int32;
    pos: [R] int32, each slot's global position of its first token this
    call.  Returns f32 [R, H, T, hd], bitwise equal to
    `_cached_attend(q, gathered_k, gathered_v, pos, ...)` on the same
    backend — `forward_paged(..., attend_impl="pallas")` is the seam
    that slots it in, with the reference path staying the default-on
    oracle.
    """
    if interpret is None:
        interpret = not _is_tpu()
    pos = jnp.asarray(pos, jnp.int32)
    _validate(q, pool_k, pool_v, page_table, pos, page_size, depth,
              interpret)
    R, H, T, hd = q.shape
    n_kv = pool_k.shape[1]
    P = page_table.shape[1]
    G = H // n_kv
    if sm_scale is None:
        sm_scale = hd ** -0.5
    qg = q.astype(jnp.float32).reshape(R, n_kv, G * T, hd)
    kern = functools.partial(_paged_kernel, n_pages=P,
                             page_size=page_size, n_t=T, depth=depth,
                             sm_scale=sm_scale)
    vma = _vma(qg, pool_k, pool_v, page_table, pos)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    hbm = pl.BlockSpec(memory_space=pl.ANY)
    out = pl.pallas_call(
        kern,
        grid=(R, n_kv),
        in_specs=[smem, smem,
                  pl.BlockSpec((1, 1, G * T, hd),
                               lambda r, k: (r, k, 0, 0)),
                  hbm, hbm],
        out_specs=pl.BlockSpec((1, 1, G * T, hd),
                               lambda r, k: (r, k, 0, 0)),
        out_shape=compat.shape_dtype_struct((R, n_kv, G * T, hd),
                                            jnp.float32, vma=vma),
        scratch_shapes=[
            pltpu.VMEM((P * page_size, hd), pool_k.dtype),   # K tiles
            pltpu.VMEM((P * page_size, hd), pool_v.dtype),   # V tiles
            pltpu.VMEM((G * T, P * page_size), jnp.float32),  # scores
            pltpu.SemaphoreType.DMA((max(depth, 1), 2)),
        ],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel"),
            has_side_effects=True),
        interpret=bool(interpret),
    )(page_table, pos, qg, pool_k, pool_v)
    return out.reshape(R, H, T, hd)
