"""Explicit ring collectives over ``lax.ppermute`` — the TPU-native analogue
of the reference's ring dataflow FSM (hw/all_reduce.sv st_eth_t:
SEND_LOCAL → REDUCE ×(N-2) → REDUCE_OUTPUT → FORWARD_OUTPUT/OUTPUT,
lines 691-1183).

Why these exist when ``lax.psum_scatter`` does: the XLA collectives cannot
compress on the wire.  The reference's headline trick is BFP-compressing
every ring hop (hw/bfp_adapter.sv); here each hop's payload is whatever
tuple of arrays the configured `compress.Codec` emits — BFP's (int8
mantissa, int8 scale) pair cutting ICI bytes 3.76x vs f32, top-k's
(values, indices), int8's (q, scale) — the codec seam generalizing the
reference's single hard-wired trick.  ``compression=`` accepts a Codec or
(back-compat) a bare BFPConfig.  Uncompressed mode exists for parity
testing and as the building block the fused-update engine selects per
config (`CollectiveConfig.impl`).

Chunk ownership is *natural order* — device i ends with chunk i — unlike
the reference's rotated slice order (hw/all_reduce.sv:361), which existed
only to keep its host-write FSM streaming; on TPU natural order keeps
ZeRO-1 shard <-> device mapping stable across collective impls.

Slicing (the reference's BUF_SIZE=512-CL / 32 KiB streaming granularity,
hw/all_reduce.sv:101-103,330): a compressed hop whose chunk exceeds
``slice_elems`` is streamed slice-by-slice, double-buffered so slice k+1's
encode runs while slice k's ppermute is on the wire — the TPU analogue of
the bfp_adapter sitting *inside* the ring stream (hw/bfp_adapter.sv).
Because compression units (BFP blocks / top-k buckets / int8 blocks) are
independent and ``slice_elems`` is a unit multiple (`Codec.sliceable`),
sliced and whole-chunk hops are bit-identical; slicing changes the
schedule, never the numerics.  Uncompressed hops always send the whole
chunk in one ppermute: with no codec work to overlap, slicing would only
serialize the DMA that XLA already streams.

All functions must run inside ``jax.shard_map`` with `axis_name` a mesh
axis; per-device inputs must vary over that axis (JAX >= 0.8 VMA rules).
Bit-exactness vs `ops.ring_golden` (same add order, same per-hop
quantization) is enforced by tests/test_ring.py.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from . import integrity as _integrity
from ..utils.config import BFPConfig  # noqa: F401 — legacy compression= type


def _next_neighbor_perm(n: int):
    # unidirectional ring, node n sends to (n+1) % N — the IKL topology
    # (sw/setup_route.sh:12-40, readme.pdf §2.2)
    return [(i, (i + 1) % n) for i in range(n)]


# -- chaos tap (runtime.chaos) ----------------------------------------------
# When installed (fault-injection runs only), both explicit-ring
# collectives (reduce-scatter and all-gather; ring_all_reduce composes
# them) route their payload through the tap at trace time, so the chaos
# harness can straggle or corrupt the wire INSIDE the compiled step — the
# boundary the reference's bfp_adapter sits on.  None (the default) is
# zero-cost: the collectives are traced exactly as before.

_FAULT_TAP = None


def set_fault_tap(tap) -> None:
    """Install/remove (None) the trace-time payload tap.  Must be set
    before the consuming step function is first traced; installed taps are
    compiled into the program."""
    global _FAULT_TAP
    _FAULT_TAP = tap


def _tap(x: jax.Array, point: str) -> jax.Array:
    return x if _FAULT_TAP is None else _FAULT_TAP(x, point)


# -- wire tap (runtime.chaos, encoded-frame plane) ---------------------------
# The value tap above perturbs the collective's INPUT (pre-encode) — the
# surface the value-space integrity layer guards.  The wire tap sits on
# the ENCODED payload between ppermute and decode: exactly the boundary
# the reference's bfp_adapter owns, and exactly where a finite bit flip
# becomes invisible to any value-space guard (it decodes to a plausible
# number).  The exact frame checksums (ops.integrity) are computed on the
# send side BEFORE the wire and on the receive side AFTER this tap, so a
# tapped corruption must trip them.  None (default) is zero-cost.

_WIRE_TAP = None


def set_wire_tap(tap) -> None:
    """Install/remove (None) the trace-time ENCODED-payload tap.  Same
    contract as set_fault_tap: install before the consuming program is
    first traced."""
    global _WIRE_TAP
    _WIRE_TAP = tap


def _tap_wire(payload, point: str, consumed=None):
    """``consumed`` (traced bool, default True) tells the tap whether
    THIS device's received payload is actually consumed by the program —
    single-pair ppermutes (reshard segments, the KV handoff) execute the
    callback on every SPMD participant but deliver real bytes only to
    the destination, and a corruption spec must fire on a frame that
    matters, not on a bystander's zeros."""
    if _WIRE_TAP is None:
        return payload
    return tuple(_WIRE_TAP(p, point, consumed) for p in payload)


def _use_pallas(cfg: BFPConfig, n_elems: int) -> bool:
    # kept as a public-ish seam (bench_collective.py keys its consumption
    # strategy off it); the implementation moved to compress.bfp with the
    # codec subsystem
    from ..compress.bfp import use_pallas
    return use_pallas(cfg, n_elems)


def _codec(cfg: BFPConfig, n_elems: int):
    """(encode, decode) pair for a flat [n_elems] BFP payload — moved to
    compress.bfp.codec_pair (this delegate keeps the bench drivers' entry
    point stable)."""
    from ..compress.bfp import codec_pair
    return codec_pair(cfg, n_elems)


def _as_codec(compression):
    """Normalize ``compression=``: None | compress.Codec | bare BFPConfig
    (the pre-subsystem spelling, still honored everywhere)."""
    from ..compress import as_codec
    return as_codec(compression)


def _send_n_messages(codec, length: int,
                     slice_elems: Optional[int]) -> int:
    """How many distinct wire messages one ``_send`` call emits — the
    static message-counter stride callers use to give every (hop,
    slice) its own ``msg_base`` range, so every message in a collective
    carries a DISTINCT odd conservation weight (a product of two odd
    per-axis weights would collide across hops — the aliasing class
    the reshard transfer's per-segment counter also rules out)."""
    if codec is None or not codec.sliceable(length, slice_elems):
        return 1
    return length // slice_elems


def _send(payload: jax.Array, axis_name: str, n: int,
          codec, slice_elems: Optional[int] = None,
          perm=None, chk=None, msg_base=None):
    """One ring hop, optionally codec-compressed on the wire.  ``codec``
    is an already-normalized compress.Codec (or None).  ``perm``
    overrides the next-neighbor permutation — the seam `ops.ring_hier`
    drives its intra/inter SUBRING hops through, so the sliced
    double-buffered codec stream below is written exactly once.

    ``chk`` (None = integrity off) is a (send_acc, recv_acc) uint32
    carry: every payload element that crosses the wire is checksummed
    once on the send side (pre-ppermute) and once on the receive side
    (post-ppermute, post-wire-tap) with the SAME odd message weight
    ``integrity.hop_weight(msg_base + slice)`` — ``msg_base`` is this
    hop's offset into the collective's single message counter (stride
    ``_send_n_messages``), so no two messages in one conservation sum
    share a weight (messages at the same (hop, slice) on DIFFERENT
    devices still do — part of the conceded multi-corruption algebraic
    class, docs/KNOWN_FAILURES.md).  The collective closes the carry
    with ``integrity.conservation_ok``.  Returns ``received`` or
    ``(received, chk')``.  The checksums never ride the wire: ppermute
    operand bytes are IDENTICAL with integrity on or off (the J4/J9
    accounting is untouched)."""
    if perm is None:
        perm = _next_neighbor_perm(n)
    if codec is None:
        if chk is None and _WIRE_TAP is None:
            return lax.ppermute(payload, axis_name, perm)
        pay = (payload,)
        if chk is not None:
            w = _integrity.hop_weight(msg_base)
            sa = chk[0] + w * _integrity.payload_checksum(pay)
        pay = tuple(lax.ppermute(p, axis_name, perm) for p in pay)
        pay = _tap_wire(pay, "ring.wire")
        if chk is None:
            return pay[0]
        ra = chk[1] + w * _integrity.payload_checksum(pay)
        return pay[0], (sa, ra)
    C = payload.shape[0]
    if not codec.sliceable(C, slice_elems):
        # whole-chunk hop (also the fallback when slicing would change the
        # codec's unit partition — sliced and whole-chunk hops must be
        # bit-identical, so an incompatible slice_elems degrades to this)
        pay = codec.encode(payload)
        if chk is not None:
            w = _integrity.hop_weight(msg_base)
            sa = chk[0] + w * _integrity.payload_checksum(pay)
        pay = tuple(lax.ppermute(p, axis_name, perm) for p in pay)
        pay = _tap_wire(pay, "ring.wire")
        out = codec.decode(pay, C, payload.dtype)
        if chk is None:
            return out
        ra = chk[1] + w * _integrity.payload_checksum(pay)
        return out, (sa, ra)

    # Sliced, double-buffered stream: while slice k's compressed payload is
    # on the wire, encode slice k+1 (they are independent, so XLA's
    # latency-hiding scheduler overlaps codec compute with the permute DMA).
    # The final iteration's look-ahead encode (slice 0 again) is dead work
    # worth 1/S of one codec pass — the price of a uniform scan body.
    S = C // slice_elems
    slices = payload.reshape(S, slice_elems)

    if chk is None:
        def step(carry, k):
            received = tuple(lax.ppermute(p, axis_name, perm)
                             for p in carry)
            received = _tap_wire(received, "ring.wire")
            nxt = codec.encode(slices[(k + 1) % S])
            return nxt, codec.decode(received, slice_elems, payload.dtype)

        _, received = lax.scan(step, codec.encode(slices[0]),
                               jnp.arange(S))
        return received.reshape(C)

    def step(carry, k):
        pay, sa, ra = carry
        # slice k of this hop is message msg_base + k of the collective:
        # the same index on sender and receiver (the conservation sum
        # telescopes to zero when clean), distinct from every other
        # (hop, slice) in the same carry
        w = _integrity.hop_weight(msg_base + k)
        sa = sa + w * _integrity.payload_checksum(pay)
        received = tuple(lax.ppermute(p, axis_name, perm) for p in pay)
        received = _tap_wire(received, "ring.wire")
        ra = ra + w * _integrity.payload_checksum(received)
        nxt = codec.encode(slices[(k + 1) % S])
        return (nxt, sa, ra), codec.decode(received, slice_elems,
                                           payload.dtype)

    (_, sa, ra), received = lax.scan(
        step, (codec.encode(slices[0]), chk[0], chk[1]), jnp.arange(S))
    return received.reshape(C), (sa, ra)


def ring_reduce_scatter(x: jax.Array, axis_name: str, *,
                        compression=None,        # compress.Codec | BFPConfig | None
                        slice_elems: Optional[int] = None,
                        unroll: bool = False,
                        integrity: bool = False):
    """Sliced ring reduce-scatter of a flat per-device vector.

    x: [L] with L % n == 0 (pad upstream; the reference pads to slice
    multiples the same way, hw/all_reduce.sv:403-409).  Returns [L//n]:
    this device's fully-reduced chunk, chunk index == device index.

    Schedule (n-1 hops): at hop s device i sends partial chunk
    (i - s - 1) mod n and accumulates the received partial into chunk
    (i - s - 2) mod n; the last accumulation lands on chunk i.

    ``integrity=True`` additionally checksums every hop's ENCODED wire
    payload on both sides (ops.integrity) and returns ``(owned,
    wire_ok)`` with ``wire_ok`` a replicated bool: every frame arrived
    bit-identical.  The result bits are unchanged and no checksum rides
    the wire (ppermute bytes identical either way).
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    codec = _as_codec(compression)
    if x.ndim != 1 or x.shape[0] % n != 0:
        raise ValueError(f"need flat length divisible by {n}, got {x.shape}")
    if n == 1:
        return (x, jnp.bool_(True)) if integrity else x
    x = _tap(x, "ring.reduce_scatter")
    chunks = x.reshape(n, -1)

    if not integrity:
        def hop(s, ch):
            send = jnp.take(ch, ((idx - s - 1) % n)[None], axis=0)[0]
            recv = _send(send, axis_name, n, codec, slice_elems)
            return ch.at[(idx - s - 2) % n].add(recv)

        chunks = lax.fori_loop(0, n - 1, hop, chunks, unroll=unroll)
        return jnp.take(chunks, idx[None], axis=0)[0]

    stride = _send_n_messages(codec, x.shape[0] // n, slice_elems)

    def hop_i(s, carry):
        ch, chk = carry
        send = jnp.take(ch, ((idx - s - 1) % n)[None], axis=0)[0]
        recv, chk = _send(send, axis_name, n, codec, slice_elems,
                          chk=chk, msg_base=s * stride)
        return ch.at[(idx - s - 2) % n].add(recv), chk

    chunks, (sa, ra) = lax.fori_loop(0, n - 1, hop_i,
                                     (chunks, _integrity.zero_carry()),
                                     unroll=unroll)
    ok = _integrity.conservation_ok(sa, ra, axis_name)
    return jnp.take(chunks, idx[None], axis=0)[0], ok


def ring_all_gather(owned: jax.Array, axis_name: str, *,
                    compression=None,        # compress.Codec | BFPConfig | None
                    unroll: bool = False,
                    integrity: bool = False):
    """Ring all-gather: device i contributes chunk i, returns [n * C].

    This is the phase that distributes *updated weights* in the fused
    collective (hw/all_reduce.sv FORWARD_OUTPUT/OUTPUT_SEND, lines
    996-1086).  Under compression the chunk is encoded once at first
    send and the compressed payload is forwarded VERBATIM thereafter
    (decoding the same payload is deterministic even for non-idempotent
    codecs like stochastic int8), so every replica sees identical bytes.
    No per-hop slicing here: the payload is encoded exactly once, so there
    is no codec work to overlap with the forwarding permutes.

    ``integrity=True`` returns ``(gathered, wire_ok)`` — every forwarded
    frame checksummed on both sides of every hop (ops.integrity); a
    corrupted forward trips every downstream replica's receive sum.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    codec = _as_codec(compression)
    owned = _tap(owned, "ring.all_gather")
    if n == 1:
        # still quantize: replicas must see wire-identical bytes at any n,
        # and the golden model quantizes the owned chunk unconditionally
        out1 = (codec.roundtrip(owned).astype(owned.dtype)
                if codec is not None else owned)
        return (out1, jnp.bool_(True)) if integrity else out1
    C = owned.shape[0]
    out = jnp.zeros((n, C), owned.dtype).at[idx].set(owned)
    perm = _next_neighbor_perm(n)

    if codec is None:
        pay = (owned,)
        store = owned
    else:
        pay = codec.encode(owned)
        # the local replica stores the same quantized bytes it sends,
        # keeping replicas identical across devices
        store = codec.decode(pay, C, owned.dtype)
    out = out.at[idx].set(store)

    def _landed(pay_):
        return pay_[0] if codec is None else codec.decode(pay_, C,
                                                          owned.dtype)

    if not integrity:
        if codec is None and _WIRE_TAP is None:
            def hop(s, carry):
                out_, p = carry
                p = lax.ppermute(p, axis_name, perm)
                return out_.at[(idx - s - 1) % n].set(p), p

            out, _ = lax.fori_loop(0, n - 1, hop, (out, owned),
                                   unroll=unroll)
        else:
            def hop(s, carry):
                out_, p = carry
                p = tuple(lax.ppermute(q, axis_name, perm) for q in p)
                p = _tap_wire(p, "ring.wire")
                return out_.at[(idx - s - 1) % n].set(_landed(p)), p

            out, _ = lax.fori_loop(0, n - 1, hop, (out, pay),
                                   unroll=unroll)
        return out.reshape(n * C)

    def hop_i(s, carry):
        out_, p, (sa, ra) = carry
        w = _integrity.hop_weight(s)
        sa = sa + w * _integrity.payload_checksum(p)
        p = tuple(lax.ppermute(q, axis_name, perm) for q in p)
        p = _tap_wire(p, "ring.wire")
        ra = ra + w * _integrity.payload_checksum(p)
        return out_.at[(idx - s - 1) % n].set(_landed(p)), p, (sa, ra)

    out, _, (sa, ra) = lax.fori_loop(
        0, n - 1, hop_i, (out, pay, _integrity.zero_carry()),
        unroll=unroll)
    ok = _integrity.conservation_ok(sa, ra, axis_name)
    return out.reshape(n * C), ok


def ring_all_reduce(x: jax.Array, axis_name: str, *,
                    compression=None,        # compress.Codec | BFPConfig | None
                    slice_elems: Optional[int] = None,
                    unroll: bool = False,
                    integrity: bool = False):
    """Full all-reduce (sum) = reduce-scatter + all-gather.  With
    ``integrity=True`` returns ``(reduced, wire_ok)`` — the AND of both
    phases' frame-conservation verdicts."""
    if integrity:
        owned, ok_rs = ring_reduce_scatter(
            x, axis_name, compression=compression,
            slice_elems=slice_elems, unroll=unroll, integrity=True)
        full, ok_ag = ring_all_gather(owned, axis_name,
                                      compression=compression,
                                      unroll=unroll, integrity=True)
        return full, ok_rs & ok_ag
    owned = ring_reduce_scatter(x, axis_name, compression=compression,
                                slice_elems=slice_elems, unroll=unroll)
    return ring_all_gather(owned, axis_name, compression=compression,
                           unroll=unroll)


def wire_bytes_per_device(L: int, n: int,
                          compression=None,
                          dtype_bytes: int = 4) -> int:
    """Bytes each device puts on the ring for one all-reduce of L elements
    (observability parity with the reference's flit counters,
    hw/bfp_adapter.sv:705-729).  ``compression`` is a Codec or (legacy)
    a BFPConfig."""
    elems = 2 * (n - 1) * (L // n)
    codec = _as_codec(compression)
    if codec is None:
        return elems * dtype_bytes
    return codec.wire_bytes(elems)
