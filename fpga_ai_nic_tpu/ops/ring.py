"""Explicit ring collectives over ``lax.ppermute`` — the TPU-native analogue
of the reference's ring dataflow FSM (hw/all_reduce.sv st_eth_t:
SEND_LOCAL → REDUCE ×(N-2) → REDUCE_OUTPUT → FORWARD_OUTPUT/OUTPUT,
lines 691-1183).

Why these exist when ``lax.psum_scatter`` does: the XLA collectives cannot
compress on the wire.  The reference's headline trick is BFP-compressing
every ring hop (hw/bfp_adapter.sv); here each hop's payload is the
(int8 mantissa, int8 scale) pair from `ops.bfp`, cutting ICI bytes 3.76x
vs f32 / 1.88x vs bf16.  Uncompressed mode exists for parity testing and
as the building block the fused-update engine selects per config
(`CollectiveConfig.impl`).

Chunk ownership is *natural order* — device i ends with chunk i — unlike
the reference's rotated slice order (hw/all_reduce.sv:361), which existed
only to keep its host-write FSM streaming; on TPU natural order keeps
ZeRO-1 shard <-> device mapping stable across collective impls.

Slicing (the reference's BUF_SIZE=512-CL / 32 KiB streaming granularity,
hw/all_reduce.sv:101-103,330): a compressed hop whose chunk exceeds
``slice_elems`` is streamed slice-by-slice, double-buffered so slice k+1's
encode runs while slice k's ppermute is on the wire — the TPU analogue of
the bfp_adapter sitting *inside* the ring stream (hw/bfp_adapter.sv).
Because BFP blocks are independent and ``slice_elems`` is a block multiple,
sliced and whole-chunk hops are bit-identical; slicing changes the
schedule, never the numerics.  Uncompressed hops always send the whole
chunk in one ppermute: with no codec work to overlap, slicing would only
serialize the DMA that XLA already streams.

All functions must run inside ``jax.shard_map`` with `axis_name` a mesh
axis; per-device inputs must vary over that axis (JAX >= 0.8 VMA rules).
Bit-exactness vs `ops.ring_golden` (same add order, same per-hop
quantization) is enforced by tests/test_ring.py.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from . import bfp as _bfp_xla
from . import bfp_pallas as _bfp_pl
from ..utils.config import BFPConfig


def _next_neighbor_perm(n: int):
    # unidirectional ring, node n sends to (n+1) % N — the IKL topology
    # (sw/setup_route.sh:12-40, readme.pdf §2.2)
    return [(i, (i + 1) % n) for i in range(n)]


# -- chaos tap (runtime.chaos) ----------------------------------------------
# When installed (fault-injection runs only), both explicit-ring
# collectives (reduce-scatter and all-gather; ring_all_reduce composes
# them) route their payload through the tap at trace time, so the chaos
# harness can straggle or corrupt the wire INSIDE the compiled step — the
# boundary the reference's bfp_adapter sits on.  None (the default) is
# zero-cost: the collectives are traced exactly as before.

_FAULT_TAP = None


def set_fault_tap(tap) -> None:
    """Install/remove (None) the trace-time payload tap.  Must be set
    before the consuming step function is first traced; installed taps are
    compiled into the program."""
    global _FAULT_TAP
    _FAULT_TAP = tap


def _tap(x: jax.Array, point: str) -> jax.Array:
    return x if _FAULT_TAP is None else _FAULT_TAP(x, point)


def _use_pallas(cfg: BFPConfig, n_elems: int) -> bool:
    return cfg.codec == "pallas" or (
        cfg.codec == "auto" and _bfp_pl._is_tpu()
        and n_elems % (cfg.block_size * _bfp_pl.LANES) == 0)


def _codec(cfg: BFPConfig, n_elems: int):
    """(encode, decode) pair for a flat [n_elems] payload.

    codec="auto" picks the fused Pallas kernels on TPU when the payload
    tiles onto (block, 128)-lane registers, else the XLA ops; the default
    "xla" keeps golden bit-exactness on every platform (see BFPConfig)."""
    if _use_pallas(cfg, n_elems):
        # inline (un-jitted) kernels: a nested closed_call inside a
        # vma-checked shard_map trips the checker
        def enc(x):
            return _bfp_pl.bfp_encode_inline(x, cfg.block_size,
                                             cfg.mantissa_bits,
                                             cfg.rounding)

        def dec(mant, se, dtype):
            return _bfp_pl.bfp_decode_inline(mant, se, cfg.block_size,
                                             dtype)
    else:
        def enc(x):
            return _bfp_xla.bfp_encode(x, cfg.block_size,
                                       cfg.mantissa_bits, cfg.rounding)

        def dec(mant, se, dtype):
            return _bfp_xla.bfp_decode(mant, se, cfg.block_size, dtype)

    return enc, dec


def _send(payload: jax.Array, axis_name: str, n: int,
          cfg: Optional[BFPConfig],
          slice_elems: Optional[int] = None) -> jax.Array:
    """One ring hop, optionally BFP-compressed on the wire."""
    perm = _next_neighbor_perm(n)
    if cfg is None:
        return lax.ppermute(payload, axis_name, perm)
    C = payload.shape[0]
    if (slice_elems is None or C <= slice_elems or C % slice_elems
            or slice_elems % cfg.block_size
            # sliced and whole-chunk paths must resolve to the SAME codec,
            # or slicing would change the block partition (and the bits)
            or _use_pallas(cfg, slice_elems) != _use_pallas(cfg, C)
            # a pallas-bound slice must actually tile onto (block, 128)
            # lanes; fall back to the whole-chunk hop instead of tripping
            # the kernel's tiling assert (forced codec="pallas" case)
            or (_use_pallas(cfg, slice_elems)
                and slice_elems % (cfg.block_size * _bfp_pl.LANES))):
        enc, dec = _codec(cfg, C)
        mant, se = enc(payload)
        mant = lax.ppermute(mant, axis_name, perm)
        se = lax.ppermute(se, axis_name, perm)
        return dec(mant, se, payload.dtype)

    # Sliced, double-buffered stream: while slice k's compressed payload is
    # on the wire, encode slice k+1 (they are independent, so XLA's
    # latency-hiding scheduler overlaps codec compute with the permute DMA).
    # The final iteration's look-ahead encode (slice 0 again) is dead work
    # worth 1/S of one codec pass — the price of a uniform scan body.
    S = C // slice_elems
    slices = payload.reshape(S, slice_elems)
    enc, dec = _codec(cfg, slice_elems)

    def step(carry, k):
        mant_k, se_k = carry
        mant_r = lax.ppermute(mant_k, axis_name, perm)
        se_r = lax.ppermute(se_k, axis_name, perm)
        nxt = enc(slices[(k + 1) % S])
        return nxt, dec(mant_r, se_r, payload.dtype)

    _, received = lax.scan(step, enc(slices[0]), jnp.arange(S))
    return received.reshape(C)


def ring_reduce_scatter(x: jax.Array, axis_name: str, *,
                        compression: Optional[BFPConfig] = None,
                        slice_elems: Optional[int] = None,
                        unroll: bool = False) -> jax.Array:
    """Sliced ring reduce-scatter of a flat per-device vector.

    x: [L] with L % n == 0 (pad upstream; the reference pads to slice
    multiples the same way, hw/all_reduce.sv:403-409).  Returns [L//n]:
    this device's fully-reduced chunk, chunk index == device index.

    Schedule (n-1 hops): at hop s device i sends partial chunk
    (i - s - 1) mod n and accumulates the received partial into chunk
    (i - s - 2) mod n; the last accumulation lands on chunk i.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if x.ndim != 1 or x.shape[0] % n != 0:
        raise ValueError(f"need flat length divisible by {n}, got {x.shape}")
    if n == 1:
        return x
    x = _tap(x, "ring.reduce_scatter")
    chunks = x.reshape(n, -1)

    def hop(s, ch):
        send = jnp.take(ch, ((idx - s - 1) % n)[None], axis=0)[0]
        recv = _send(send, axis_name, n, compression, slice_elems)
        return ch.at[(idx - s - 2) % n].add(recv)

    chunks = lax.fori_loop(0, n - 1, hop, chunks, unroll=unroll)
    return jnp.take(chunks, idx[None], axis=0)[0]


def ring_all_gather(owned: jax.Array, axis_name: str, *,
                    compression: Optional[BFPConfig] = None,
                    unroll: bool = False) -> jax.Array:
    """Ring all-gather: device i contributes chunk i, returns [n * C].

    This is the phase that distributes *updated weights* in the fused
    collective (hw/all_reduce.sv FORWARD_OUTPUT/OUTPUT_SEND, lines
    996-1086).  Under compression the chunk is quantized once at first
    send and the compressed payload is forwarded verbatim thereafter
    (BFP roundtrip is idempotent), so every replica sees identical bytes.
    No per-hop slicing here: the payload is encoded exactly once, so there
    is no codec work to overlap with the forwarding permutes.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    owned = _tap(owned, "ring.all_gather")
    if n == 1:
        # still quantize: replicas must see wire-identical bytes at any n,
        # and the golden model quantizes the owned chunk unconditionally
        if compression is not None:
            enc, dec = _codec(compression, owned.shape[0])
            mant, se = enc(owned)
            return dec(mant, se, owned.dtype)
        return owned
    C = owned.shape[0]
    out = jnp.zeros((n, C), owned.dtype).at[idx].set(owned)

    if compression is None:
        def hop(s, carry):
            out_, pay = carry
            pay = lax.ppermute(pay, axis_name, _next_neighbor_perm(n))
            return out_.at[(idx - s - 1) % n].set(pay), pay

        out, _ = lax.fori_loop(0, n - 1, hop, (out, owned), unroll=unroll)
    else:
        enc, dec = _codec(compression, C)
        mant, se = enc(owned)
        # the local replica stores the same quantized bytes it sends,
        # keeping replicas identical across devices
        out = out.at[idx].set(dec(mant, se, owned.dtype))

        def hop(s, carry):
            out_, m, e = carry
            perm = _next_neighbor_perm(n)
            m = lax.ppermute(m, axis_name, perm)
            e = lax.ppermute(e, axis_name, perm)
            return out_.at[(idx - s - 1) % n].set(dec(m, e, owned.dtype)), m, e

        out, _, _ = lax.fori_loop(0, n - 1, hop, (out, mant, se),
                                  unroll=unroll)
    return out.reshape(n * C)


def ring_all_reduce(x: jax.Array, axis_name: str, *,
                    compression: Optional[BFPConfig] = None,
                    slice_elems: Optional[int] = None,
                    unroll: bool = False) -> jax.Array:
    """Full all-reduce (sum) = reduce-scatter + all-gather."""
    owned = ring_reduce_scatter(x, axis_name, compression=compression,
                                slice_elems=slice_elems, unroll=unroll)
    return ring_all_gather(owned, axis_name, compression=compression,
                           unroll=unroll)


def wire_bytes_per_device(L: int, n: int,
                          compression: Optional[BFPConfig] = None,
                          dtype_bytes: int = 4) -> int:
    """Bytes each device puts on the ring for one all-reduce of L elements
    (observability parity with the reference's flit counters,
    hw/bfp_adapter.sv:705-729)."""
    elems = 2 * (n - 1) * (L // n)
    if compression is None:
        return elems * dtype_bytes
    from .bfp import wire_bytes
    return wire_bytes(elems, compression)
