"""Explicit ring collectives over ``lax.ppermute`` — the TPU-native analogue
of the reference's ring dataflow FSM (hw/all_reduce.sv st_eth_t:
SEND_LOCAL → REDUCE ×(N-2) → REDUCE_OUTPUT → FORWARD_OUTPUT/OUTPUT,
lines 691-1183).

Why these exist when ``lax.psum_scatter`` does: the XLA collectives cannot
compress on the wire.  The reference's headline trick is BFP-compressing
every ring hop (hw/bfp_adapter.sv); here each hop's payload is the
(int8 mantissa, int8 scale) pair from `ops.bfp`, cutting ICI bytes 3.76x
vs f32 / 1.88x vs bf16.  Uncompressed mode exists for parity testing and
as the building block the fused-update engine selects per config
(`CollectiveConfig.impl`).

Chunk ownership is *natural order* — device i ends with chunk i — unlike
the reference's rotated slice order (hw/all_reduce.sv:361), which existed
only to keep its host-write FSM streaming; on TPU natural order keeps
ZeRO-1 shard <-> device mapping stable across collective impls.

All functions must run inside ``jax.shard_map`` with `axis_name` a mesh
axis; per-device inputs must vary over that axis (JAX >= 0.8 VMA rules).
Bit-exactness vs `ops.ring_golden` (same add order, same per-hop
quantization) is enforced by tests/test_ring.py.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .bfp import bfp_decode, bfp_encode
from ..utils.config import BFPConfig


def _next_neighbor_perm(n: int):
    # unidirectional ring, node n sends to (n+1) % N — the IKL topology
    # (sw/setup_route.sh:12-40, readme.pdf §2.2)
    return [(i, (i + 1) % n) for i in range(n)]


def _send(payload: jax.Array, axis_name: str, n: int,
          cfg: Optional[BFPConfig]) -> jax.Array:
    """One ring hop, optionally BFP-compressed on the wire."""
    perm = _next_neighbor_perm(n)
    if cfg is None:
        return lax.ppermute(payload, axis_name, perm)
    mant, se = bfp_encode(payload, cfg.block_size, cfg.mantissa_bits,
                          cfg.rounding)
    mant = lax.ppermute(mant, axis_name, perm)
    se = lax.ppermute(se, axis_name, perm)
    return bfp_decode(mant, se, cfg.block_size, payload.dtype)


def ring_reduce_scatter(x: jax.Array, axis_name: str, *,
                        compression: Optional[BFPConfig] = None) -> jax.Array:
    """Sliced ring reduce-scatter of a flat per-device vector.

    x: [L] with L % n == 0 (pad upstream; the reference pads to slice
    multiples the same way, hw/all_reduce.sv:403-409).  Returns [L//n]:
    this device's fully-reduced chunk, chunk index == device index.

    Schedule (n-1 hops): at hop s device i sends partial chunk
    (i - s - 1) mod n and accumulates the received partial into chunk
    (i - s - 2) mod n; the last accumulation lands on chunk i.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if x.ndim != 1 or x.shape[0] % n != 0:
        raise ValueError(f"need flat length divisible by {n}, got {x.shape}")
    if n == 1:
        return x
    chunks = x.reshape(n, -1)

    def hop(s, ch):
        send = jnp.take(ch, ((idx - s - 1) % n)[None], axis=0)[0]
        recv = _send(send, axis_name, n, compression)
        return ch.at[(idx - s - 2) % n].add(recv)

    chunks = lax.fori_loop(0, n - 1, hop, chunks, unroll=True)
    return jnp.take(chunks, idx[None], axis=0)[0]


def ring_all_gather(owned: jax.Array, axis_name: str, *,
                    compression: Optional[BFPConfig] = None) -> jax.Array:
    """Ring all-gather: device i contributes chunk i, returns [n * C].

    This is the phase that distributes *updated weights* in the fused
    collective (hw/all_reduce.sv FORWARD_OUTPUT/OUTPUT_SEND, lines
    996-1086).  Under compression the chunk is quantized once at first
    send and the compressed payload is forwarded verbatim thereafter
    (BFP roundtrip is idempotent), so every replica sees identical bytes.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if n == 1:
        # still quantize: replicas must see wire-identical bytes at any n,
        # and the golden model quantizes the owned chunk unconditionally
        if compression is not None:
            mant, se = bfp_encode(owned, compression.block_size,
                                  compression.mantissa_bits,
                                  compression.rounding)
            return bfp_decode(mant, se, compression.block_size, owned.dtype)
        return owned
    C = owned.shape[0]
    out = jnp.zeros((n, C), owned.dtype).at[idx].set(owned)

    if compression is None:
        def hop(s, carry):
            out_, pay = carry
            pay = lax.ppermute(pay, axis_name, _next_neighbor_perm(n))
            return out_.at[(idx - s - 1) % n].set(pay), pay

        out, _ = lax.fori_loop(0, n - 1, hop, (out, owned), unroll=True)
    else:
        cfg = compression
        mant, se = bfp_encode(owned, cfg.block_size, cfg.mantissa_bits,
                              cfg.rounding)
        # the local replica stores the same quantized bytes it sends,
        # keeping replicas identical across devices
        out = out.at[idx].set(bfp_decode(mant, se, cfg.block_size, owned.dtype))

        def hop(s, carry):
            out_, m, e = carry
            perm = _next_neighbor_perm(n)
            m = lax.ppermute(m, axis_name, perm)
            e = lax.ppermute(e, axis_name, perm)
            dec = bfp_decode(m, e, cfg.block_size, owned.dtype)
            return out_.at[(idx - s - 1) % n].set(dec), m, e

        out, _, _ = lax.fori_loop(0, n - 1, hop, (out, mant, se), unroll=True)
    return out.reshape(n * C)


def ring_all_reduce(x: jax.Array, axis_name: str, *,
                    compression: Optional[BFPConfig] = None) -> jax.Array:
    """Full all-reduce (sum) = reduce-scatter + all-gather."""
    owned = ring_reduce_scatter(x, axis_name, compression=compression)
    return ring_all_gather(owned, axis_name, compression=compression)


def wire_bytes_per_device(L: int, n: int,
                          compression: Optional[BFPConfig] = None,
                          dtype_bytes: int = 4) -> int:
    """Bytes each device puts on the ring for one all-reduce of L elements
    (observability parity with the reference's flit counters,
    hw/bfp_adapter.sv:705-729)."""
    elems = 2 * (n - 1) * (L // n)
    if compression is None:
        return elems * dtype_bytes
    from .bfp import wire_bytes
    return wire_bytes(elems, compression)
