"""Per-stage cost model for the fused ring pipeline (ops.ring_pallas).

A pipelined hop runs at the rate of its slowest RESOURCE, not the sum of
its stages — the reference reads exactly this split from its RTL stall
counters (hw/all_reduce.sv:94-97) to prove the 256b datapath stays busy
every beat.  Our instrument is the `ablate=` machinery: each variant runs
the SAME slice schedule with exactly one stage compiled in, so its
slope-measured time is that stage's schedule time with the loop/semaphore
skeleton included.  This module combines those timings into a predicted
pipeline time and a `pipeline_efficiency`, which bench_collective.py and
tools/first_contact.py report per loopback row.

Resource model (why the terms combine the way they do):

  VPU   encode and decode+accumulate execute in ONE instruction stream —
        they can never overlap each other, so they add.  Each ablated run
        carries the control skeleton once (measured by ablate="skeleton"),
        so the sum subtracts it once:  t_vpu = t_enc + t_dec - t_skel.
  RDMA  the wire chain is its own engine; fully overlappable with the
        VPU:  t_rdma as measured.
  HBM   the streaming kernel's slice load / store-load / writeback DMAs
        (ablate="hbm"); a third engine, overlappable with both.

  t_model             = max(t_vpu, t_rdma, t_hbm)
  pipeline_efficiency = t_model / t_full      (1.0 = perfectly hidden;
                        below ~0.8 the schedule is leaving overlap on
                        the table — the round-5 verdict's 10x gap)
  binding stage       = argmax of the terms

The same serial-VPU insight fixes the break-even model: the old table
used max(1/enc, 1/dec, wire) per byte, which assumed encode and decode
overlap — they share the VPU, so the compute bound is their SUM
(equivalently the harmonic combination of the rates).  That is why the
r04 numbers could never have been self-consistent: a roundtrip measured
at ~2x the harmonic sum of its own stages is impossible for a
compute-bound pipeline (bench_collective's consistency gate).
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

# stage names understood by ring_pallas's ablate= (skeleton = bare
# schedule: loop + slot bookkeeping, no stage work; update = the fused
# in-kernel optimizer stage, fused-opt kernels only)
STAGES_RESIDENT = ("skeleton", "encode", "rdma", "decode")
STAGES_STREAMING = ("skeleton", "encode", "rdma", "decode", "hbm")

# per-optimizer state-tensor count (w excluded) and rough update FLOPs
# per element — the static half of the fused-optimizer stage accounting
# (the measured half is ablate="update")
OPT_N_STATE = {"sgd": 0, "momentum": 1, "adamw": 2}
OPT_FLOPS_PER_ELEM = {"sgd": 4, "momentum": 6, "adamw": 14}


def stages_for(streaming: bool, fused_opt: bool = False) -> Sequence[str]:
    base = STAGES_STREAMING if streaming else STAGES_RESIDENT
    return base + ("update",) if fused_opt else base


def optimizer_roofline(opt_kind: str, chunk_bytes: int,
                       hbm_gbps: float = 0.0) -> dict:
    """Static accounting of the STANDALONE (unfused) ZeRO-1 optimizer
    pass the fused kernel absorbs: per step and replica it reads the
    reduced gradient shard + master shard and writes the master, plus a
    read+write of every moment-state shard — all over HBM, with nothing
    to overlap against.  That byte count / the HBM rate is the minimum
    exposed time `bench_collective --fused-optimizer` expects the fused
    path to win back (the success metric of ROADMAP item 4).

    chunk_bytes: the owned f32 shard (L/n * 4).  hbm_gbps <= 0 omits the
    time estimate (bytes are still exact)."""
    ns = OPT_N_STATE[opt_kind]
    # read g_own + read w + write w + (read + write) per moment tensor
    traffic = chunk_bytes * (3 + 2 * ns)
    out = {
        "opt_kind": opt_kind,
        "n_state_tensors": ns,
        "moment_state_bytes": chunk_bytes * ns,
        "standalone_hbm_bytes": traffic,
        "update_flops_per_elem": OPT_FLOPS_PER_ELEM[opt_kind],
        "model": ("standalone optimizer pass = (3 + 2*n_state) * "
                  "chunk_bytes over HBM (read g_own, read+write w, "
                  "read+write each moment); the fused kernel folds this "
                  "into the final-hop decodes where the remaining ring "
                  "hops hide it"),
    }
    if hbm_gbps and hbm_gbps > 0:
        out["standalone_roofline_s"] = traffic / (hbm_gbps * 1e9)
    return out


def model_pipeline(stage_s: Mapping[str, float],
                   full_s: Optional[float] = None,
                   expect_update: bool = False) -> dict:
    """Combine per-stage schedule times (seconds) into the predicted
    pipeline time.

    stage_s maps ablate names -> slope-measured seconds for the ablated
    schedule; non-positive or missing entries are treated as unmeasured
    (a non-positive slope means noise swamped the chain difference — the
    caller must not fabricate a rate from it).  full_s is the full
    pipeline's measured time; when given, pipeline_efficiency and the
    modeled-vs-measured error are included.

    Returns a dict with:
      modeled_s             predicted pipeline time (max over resources)
      binding_stage         "vpu" / "rdma" / "hbm" — the resource that
                            bounds the hop (vpu = encode+decode serial)
      terms_s               per-resource predicted times
      pipeline_efficiency   modeled_s / full_s (when full_s > 0)
      model_rel_err         (full_s - modeled_s) / modeled_s — how much
                            slower the real schedule runs than a
                            perfectly-overlapped one
      valid                 False when the VPU term could not be formed
    """
    def get(name):
        t = stage_s.get(name)
        return float(t) if t is not None and t > 0 else None

    skel, enc, dec = get("skeleton"), get("encode"), get("decode")
    upd = get("update")
    # the fused-optimizer update shares the VPU instruction stream with
    # encode/decode, so its schedule time ADDS to the serial VPU term
    # (same reasoning as encode+decode; its state-slice DMAs ride along
    # inside the measured stage).  expect_update marks a fused-opt
    # schedule whose update slope drowned — the model is then partial.
    vpu_parts = [p for p in (enc, dec, upd) if p is not None]
    n_expected = 3 if expect_update else 2
    terms = {}
    vpu_partial = False
    if len(vpu_parts) == n_expected:
        # each ablated run includes the skeleton once; the serial VPU sum
        # must count it once, not n_expected times
        terms["vpu"] = sum(vpu_parts) - (len(vpu_parts) - 1) * (skel or 0.0)
    elif vpu_parts:
        # part of the VPU cost is unmeasured: keep the MEASURED serial
        # sum (skeleton counted once) as a FLOOR for the display — the
        # tightest bound the surviving slopes support — but the model is
        # not valid: a confident modeled_t_ms from part of the serial
        # chain would be exactly the fabricated-rate failure this module
        # exists to prevent
        terms["vpu"] = sum(vpu_parts) - (len(vpu_parts) - 1) * (skel or 0.0)
        vpu_partial = True
    rdma, hbm = get("rdma"), get("hbm")
    if rdma is not None:
        terms["rdma"] = rdma
    if hbm is not None:
        terms["hbm"] = hbm
    # a resource can never run the schedule faster than the bare skeleton
    if skel is not None:
        terms = {k: max(v, skel) for k, v in terms.items()}

    out = {"stage_s": {k: v for k, v in stage_s.items()},
           "terms_s": terms,
           "valid": bool(terms) and ("vpu" in terms) and not vpu_partial}
    if vpu_partial:
        out["vpu_partial"] = True     # one codec stage's slope drowned
    if terms:
        binding = max(terms, key=lambda k: terms[k])
        out["binding_stage"] = binding
        # a confident modeled time / efficiency from an incomplete term
        # set would be a fabricated rate — emit them only when valid
        if out["valid"]:
            out["modeled_s"] = terms[binding]
            if full_s is not None and full_s > 0:
                out["full_s"] = float(full_s)
                out["pipeline_efficiency"] = terms[binding] / full_s
                out["model_rel_err"] = ((full_s - terms[binding])
                                        / terms[binding])
    return out


def codec_rates(stages: Mapping[str, Mapping[str, float]],
                payload_bytes: int):
    """(encode_gbps, decode_gbps) for break_even from a decomposition
    row's `stages` — SKELETON-CORRECTED: each ablated schedule time
    includes the bare control loop once, and break_even's serial model
    adds the two stage costs, so feeding it raw ablated rates would
    count the skeleton twice (understating the combined codec rate and
    biasing the verdict against BFP).  Per-byte the asymptotic stage
    cost is (t_stage - t_skeleton) / bytes.  Returns (0, 0) when either
    stage is missing or the subtraction is non-positive (skeleton-bound
    measurement: no honest asymptotic rate exists)."""
    skel = (stages.get("skeleton") or {}).get("t_ms", 0.0)
    rates = []
    for name in ("encode", "decode"):
        t = (stages.get(name) or {}).get("t_ms")
        if t is None or t - skel <= 0:
            return 0.0, 0.0
        rates.append(payload_bytes / ((t - skel) * 1e-3) / 1e9)
    return rates[0], rates[1]


# candidate per-direction link rates (GB/s): DCN-class multi-host, the
# reference's own 100GbE wire (hw/bfp_adapter.sv sat on a 100G MAC), and
# the ICI classes.  These are the DOCUMENTED FALLBACK — break-even
# tables and the autotuner route through `link_rate_candidates`, which
# prepends the MEASURED rate harvested from banked artifacts
# (tune.calibration) whenever one exists, and the outputs carry a
# `calibrated` flag so model-only rows can be badged (docs/TUNING.md).
DEFAULT_LINK_RATES = (5.0, 12.5, 45.0, 90.0, 180.0)


def link_rate_candidates(calibration=None) -> dict:
    """Per-direction link-rate candidates for break-even tables, routed
    through the calibration loader: the measured inter-axis rate (when a
    banked artifact carries one) joins the documented DEFAULT_LINK_RATES
    constants.  Returns {"rates", "calibrated", "measured_gbps",
    "source"}; with no banked measurement the rates are exactly the
    fallback constants and calibrated is False."""
    if calibration is None:
        try:
            from ..tune.calibration import load_calibration
            calibration = load_calibration()
        except Exception:  # noqa: BLE001 — the model must degrade, not die
            calibration = None
    if calibration is None or not calibration.inter_calibrated:
        return {"rates": tuple(DEFAULT_LINK_RATES), "calibrated": False,
                "measured_gbps": None,
                "source": "DEFAULT_LINK_RATES (documented fallback)"}
    w = round(float(calibration.inter_gbps), 3)
    rates = tuple(sorted(set(DEFAULT_LINK_RATES) | {w}))
    return {"rates": rates, "calibrated": True, "measured_gbps": w,
            "source": calibration.inter_source}


def hop_cost(raw_bytes: float, wire_bytes: float, link_gbps: float,
             encode_gbps: float = 0.0, decode_gbps: float = 0.0) -> dict:
    """Modeled seconds for one pipelined collective phase moving
    ``wire_bytes`` over a ``link_gbps`` wire while the VPU encodes AND
    decodes ``raw_bytes`` of f32 payload (serial — the stages share the
    VPU, module docstring): t = max(t_wire, t_vpu).  encode/decode <= 0
    means no codec on this hop (t_vpu = 0, the raw fast-hop case)."""
    t_wire = wire_bytes / (link_gbps * 1e9) if link_gbps > 0 else 0.0
    t_vpu = 0.0
    if encode_gbps and encode_gbps > 0 and encode_gbps != float("inf"):
        t_vpu += raw_bytes / (encode_gbps * 1e9)
    if decode_gbps and decode_gbps > 0 and decode_gbps != float("inf"):
        t_vpu += raw_bytes / (decode_gbps * 1e9)
    t = max(t_wire, t_vpu)
    return {"t_s": t, "t_wire_s": t_wire, "t_vpu_s": t_vpu,
            "binding": "wire" if t_wire >= t_vpu else "vpu"}


def hier_phase_bytes(payload_elems: int, n: int, n_intra: int,
                     wire_bytes_per_elems=None) -> dict:
    """Exact per-device elements/bytes per phase of one hierarchical
    ALL-REDUCE (RS + AG) of a [payload_elems] f32 vector: the topology
    terms of the cost model (ops.ring_hier owns the authoritative
    per-collective accounting via HierarchicalPlan; this is the model's
    float-friendly view).  ``wire_bytes_per_elems(elems) -> bytes``
    prices the inter hop (None = raw f32)."""
    ni = max(1, int(n_intra))
    ng = n // ni
    intra_elems = 2 * (ni - 1) * (payload_elems // ni)
    inter_elems = 2 * (ng - 1) * (payload_elems // n)
    price = wire_bytes_per_elems or (lambda e: e * 4)
    return {"n_intra": ni, "n_inter": ng,
            "intra_elems": intra_elems, "intra_bytes": intra_elems * 4,
            "inter_elems": inter_elems,
            "inter_raw_bytes": inter_elems * 4,
            "inter_wire_bytes": int(price(inter_elems)),
            "hops": 2 * (ni - 1) + 2 * (ng - 1)}


def break_even(encode_gbps: float, decode_gbps: float,
               wire_ratio_fused: float, wire_ratio_xla: float,
               link_rates: Sequence[float] = DEFAULT_LINK_RATES,
               source: str = "", calibrated: bool = False) -> dict:
    """Per-link-rate verdict: does the BFP wire path beat a bf16 psum?

    Per f32 payload byte and hop: the BFP ring pays the wire
    (1/r_fused)/W AND the serial VPU codec 1/enc + 1/dec (encode and
    decode share the VPU — see module docstring; this replaces the old
    max(1/enc, 1/dec) model, whose self-inconsistency round 4 proved);
    whichever is larger binds, because the fused kernel overlaps codec
    and wire.  The bf16 psum moves half the f32 bytes at the link rate:
    0.5/W.  To win at all the codec must sustain the harmonic-combined
    rate 1/(1/enc + 1/dec) > 2*W; the max speedup is r_fused/2.
    """
    rows = {}
    t_vpu = ((1.0 / encode_gbps if encode_gbps else 9e9)
             + (1.0 / decode_gbps if decode_gbps else 9e9))
    for W in link_rates:
        t_bf16 = 0.5 / W
        t_bfp = max((1.0 / wire_ratio_fused) / W, t_vpu)
        rows[f"link_{W:g}GBps"] = {
            "bfp_speedup_vs_bf16_psum": round(t_bf16 / t_bfp, 3),
            "bfp_wins": t_bfp < t_bf16,
            "required_codec_gbps_to_win": round(2 * W, 1),
        }
    combined = (1.0 / t_vpu) if t_vpu < 9e8 else 0.0
    return {
        "model": ("hop time per f32 byte = max(1/(r_fused*W), "
                  "1/encode + 1/decode) vs bf16 psum's 1/(2*W); encode "
                  "and decode SHARE the VPU so their costs add (the "
                  "harmonic-combined codec rate must exceed 2*W to win "
                  "at all), and the max speedup is r_fused/2 (fused wire "
                  "ratio includes the 8-row RDMA tile padding; the XLA "
                  "ring's unpadded ratio is wire_ratio_vs_f32)"),
        # False = every link rate below is a documented fallback
        # constant, not a measurement (gen_perf_md badges such rows
        # model-only; route rates through link_rate_candidates)
        "calibrated": bool(calibrated),
        "codec_rates_source": source,
        "encode_gbps": round(encode_gbps, 2),
        "decode_gbps": round(decode_gbps, 2),
        "combined_codec_gbps": round(combined, 2),
        "wire_ratio_vs_f32": round(wire_ratio_xla, 3),
        "wire_ratio_fused_vs_f32": round(wire_ratio_fused, 3),
        "per_link_rate": rows,
    }


def codec_break_even(codec, encode_gbps: float, decode_gbps: float,
                     link_rates: Sequence[float] = DEFAULT_LINK_RATES,
                     source: str = "", calibrated: bool = False) -> dict:
    """`break_even` parameterized by a registered compress.Codec: the wire
    ratio comes from the codec's own byte accounting instead of the
    hard-wired BFP frame math, so the per-link verdict table extends to
    topk/int8 (and any plugin) unchanged.  The serial-VPU model is
    codec-agnostic — encode and decode of ANY codec share the VPU, so
    their per-byte costs add."""
    r = float(codec.compression_ratio_vs_f32)
    out = break_even(encode_gbps, decode_gbps, r, r, link_rates,
                     source=source or f"codec '{codec.name}' slope chains",
                     calibrated=calibrated)
    out["codec"] = codec.describe()
    return out


def codec_table(n_elems: int = 1 << 16) -> list:
    """Static cost-model rows for every registered codec (wire ratio,
    bytes/value, declared error bound, EF) — the accounting half of the
    codec x {vmem, streaming} bench matrix (`make codec-bench`); the
    measured half comes from bench_collective.py's slope chains."""
    from ..compress import available_codecs, get_codec
    rows = []
    for name in available_codecs():
        c = get_codec(name)
        n_use = n_elems - n_elems % c.pad_elems
        rows.append(dict(c.describe(),
                         wire_bytes_per_value=c.wire_bytes(n_use) / n_use,
                         max_speedup_vs_bf16_psum=round(
                             c.compression_ratio_vs_f32 / 2, 3)))
    return rows


def decompose(measure, streaming: bool, payload_bytes: int,
              fused_opt: bool = False) -> dict:
    """Run the full per-stage decomposition of one loopback row.

    measure(ablate_or_None) -> seconds (slope-based; <= 0 means the
    measurement drowned in noise and is dropped).  Returns the
    model_pipeline dict extended with per-stage {t_ms, gbps} rows ready
    for the artifact, or {"valid": False, ...} when the full-pipeline
    measurement itself failed.  fused_opt adds the "update" stage (the
    in-kernel optimizer) to the sweep and to the serial-VPU term."""
    full_s = measure(None)
    stage_s, stage_errors = {}, {}
    for name in stages_for(streaming, fused_opt):
        # a stage variant that crashes (fresh compile path on a scarce
        # tunnel window) must not cost the already-measured full rate —
        # partial evidence is evidence
        try:
            t = measure(name)
        except Exception as e:  # noqa: BLE001 — per-stage best-effort
            stage_errors[name] = repr(e)[:200]
            continue
        if t is not None and t > 0:
            stage_s[name] = t
    out = model_pipeline(stage_s, full_s if full_s and full_s > 0 else None,
                         expect_update=fused_opt)
    out["stages"] = {
        k: {"t_ms": round(v * 1e3, 3),
            "gbps": round(payload_bytes / v / 1e9, 2)}
        for k, v in stage_s.items()}
    if stage_errors:
        out["stage_errors"] = stage_errors
        out["valid"] = False
        # a missing resource term could have been the binding one — no
        # confident model claims from an incomplete decomposition
        for k in ("modeled_s", "pipeline_efficiency", "model_rel_err",
                  "full_s"):
            out.pop(k, None)
    out["payload_bytes"] = payload_bytes
    del out["stage_s"]
    if full_s is not None and full_s > 0:
        out["t_ms"] = round(full_s * 1e3, 3)
        out["pipeline_gbps"] = round(payload_bytes / full_s / 1e9, 2)
    else:
        out["valid"] = False
        out["error"] = ("non-positive slope on the full pipeline "
                        "(noise swamped the chain-length difference)")
    if "modeled_s" in out:
        out["modeled_t_ms"] = round(out.pop("modeled_s") * 1e3, 3)
    if "pipeline_efficiency" in out:
        out["pipeline_efficiency"] = round(out["pipeline_efficiency"], 3)
    if "model_rel_err" in out:
        out["model_rel_err"] = round(out["model_rel_err"], 3)
    out.pop("full_s", None)
    return out
