"""Numpy golden model of the sliced ring all-reduce with fused update.

Simulates, device by device and hop by hop, exactly what the JAX ring in
`ops.ring` computes — including the per-hop BFP compress/decompress (so
quantization error accumulation is part of the spec, not an accident) and
the floating-point add order.  This is the "three-instance testbench with a
golden compare" the reference documents but does not ship
(readme.pdf §3.2-3.3; hw/sim absent per hw/README:1) — here it is real,
shipped, and runs in CI.

Ring schedule (identical to ops.ring; natural chunk ownership — device i
ends with chunk i — rather than the reference's rotated order,
hw/all_reduce.sv:361, which only served its host-write FSM):
  - reduce-scatter hop s (s = 0..n-2): device i sends partial chunk
    (i - s - 1) mod n to device (i+1) mod n and accumulates the received
    partial into chunk (i - s - 2) mod n; the final accumulation lands on
    chunk i.
  - all-gather hop s: device i forwards the most recently received chunk
    (starting from its own chunk i) and stores the arrival at index
    (i - s - 1) mod n.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from . import bfp_golden
from ..utils.config import BFPConfig


def _compress(x: np.ndarray, cfg: BFPConfig,
              layout: str = "flat16") -> Tuple[np.ndarray, np.ndarray]:
    return bfp_golden.bfp_encode(x, cfg.block_size, cfg.mantissa_bits,
                                 cfg.rounding, layout=layout)


def _roundtrip(x: np.ndarray, cfg: Optional[BFPConfig],
               layout: str = "flat16") -> np.ndarray:
    if cfg is None:
        return x
    mant, se = _compress(x, cfg, layout)
    return bfp_golden.bfp_decode(mant, se, cfg.block_size, layout=layout)


def ring_reduce_scatter(shards: np.ndarray,
                        compression: Optional[BFPConfig] = None,
                        layout: str = "flat16") -> np.ndarray:
    """shards: [n, L] per-device input vectors (L divisible by n).

    Returns [n, L//n]: device i's fully-reduced chunk i.

    layout picks the BFP block membership (bfp_golden): "flat16" is the
    reference's consecutive-element grouping (the XLA codec); "sublane"
    is the TPU lane layout the Pallas wire kernels quantize in — with it
    this golden model is the DIRECT bit spec of ops.ring_pallas's fused
    reduce-scatter (block-aligned slicing never changes block
    membership, so per-slice and whole-chunk quantization agree)."""
    n, L = shards.shape
    assert L % n == 0
    chunks = shards.reshape(n, n, L // n).astype(np.float32).copy()
    for s in range(n - 1):
        sends = [_roundtrip(chunks[i, (i - s - 1) % n], compression, layout)
                 for i in range(n)]
        for i in range(n):
            chunks[i, (i - s - 2) % n] += sends[(i - 1) % n]
    return np.stack([chunks[i, i] for i in range(n)])


def ring_all_gather(owned: np.ndarray,
                    compression: Optional[BFPConfig] = None) -> np.ndarray:
    """owned: [n, C] — device i contributes chunk i.  Returns [n, n*C]:
    each device's reassembled full vector.  With compression the chunk is
    quantized once on first send and forwarded verbatim (BFP roundtrip is
    idempotent), so replicas are identical — matching ops.ring."""
    n, C = owned.shape
    out = np.zeros((n, n, C), np.float32)
    carry = np.stack([_roundtrip(owned[i].astype(np.float32), compression)
                      for i in range(n)])
    for i in range(n):
        out[i, i] = carry[i]
    for s in range(n - 1):
        carry = carry[(np.arange(n) - 1) % n]          # hop to next neighbor
        for i in range(n):
            out[i, (i - s - 1) % n] = carry[i]
    return out.reshape(n, n * C)


def ring_all_reduce(shards: np.ndarray,
                    compression: Optional[BFPConfig] = None) -> np.ndarray:
    """Full all-reduce = reduce-scatter + all-gather. Returns [n, L]."""
    owned = ring_reduce_scatter(shards, compression)
    return ring_all_gather(owned, compression)


def fused_allreduce_sgd(grad_shards: np.ndarray, weights: np.ndarray,
                        lr: float,
                        compression: Optional[BFPConfig] = None) -> np.ndarray:
    """The reference's defining fusion: reduce-scatter gradients, apply the
    SGD update to the owned weight chunk, all-gather *updated weights*
    (hw/weight_update.sv:441-452 w_new = -lr*g + w; the gather phase
    distributes w_new, not gradients — hw/all_reduce.sv:996-1086).

    grad_shards: [n, L]; weights: [L] (replicated). Returns [n, L] updated
    replicas (identical across devices)."""
    n, L = grad_shards.shape
    g_owned = ring_reduce_scatter(grad_shards, compression)
    w_chunks = weights.reshape(n, L // n).astype(np.float32)
    w_new_owned = np.stack([w_chunks[i] - np.float32(lr) * g_owned[i]
                            for i in range(n)])
    return ring_all_gather(w_new_owned, compression)
