"""Fused BFP-compressed ring reduce-scatter — ONE Pallas kernel.

The reference's bfp_adapter sits *inside* the wire datapath: the engine
streams 512b groups through compress -> Ethernet -> decompress without ever
materializing the compressed frame in host-visible memory
(hw/bfp_adapter.sv:33-741 between hw/all_reduce.sv's engine and the IKL
shell).  `ops.ring` approximates that with separate XLA ops (encode /
ppermute / decode) and leaves the overlap to XLA's scheduler; THIS module
is the real analogue: a single kernel that, per 32 KiB-class slice, runs
a depth-D pipeline —

    encodes slice g+D into a send buffer        (VPU compute)
  while
    slices g+1 .. g+D-1 fly as RDMAs on the ICI (DMA engines)
  while
    decode + accumulate of slice g retires      (VPU compute)

over a (D+1)-slot comm window with explicit credit-based flow control —
the same producer/consumer discipline the reference implements with its
dual-clock FIFOs and valid/ready handshakes (hw/fifo.v,
hw/bfp_adapter.sv:57-98), generalized from the reference's fixed
double-buffer to a credit window sized by the pipeline depth (_rs_plan
states and proves the three schedule invariants; simulate_rs_protocol
race-checks them at model level up to n=8, and ops.ring_cost turns the
`ablate=` stage timings into a predicted pipeline time and a
pipeline_efficiency the loopback bench reports per row).

Wire format: one int8 frame per slice packing `R` mantissa rows followed
by `R/B` shared-exponent rows (B = block_size) — the live rows carry the
reference's exact 17-flit rate (16 mantissa flits : 1 exponent flit,
hw/bfp_adapter.sv:30,63-77), and the RDMA'd frame rounds up to the int8
8-row tile (_frame_rows; 72/68 of the live bytes at the default R=64
plan).  One RDMA moves the whole compressed slice.

Numerics are bit-identical to `ops.ring.ring_reduce_scatter` with
codec="pallas" and the same slice_elems (same add order, same per-hop
lane-layout quantization): slicing and fusion change the schedule, never
the bits (tests/test_ring_pallas.py enforces this on the CPU interpreter).

Residency: two reduce-scatter kernels share the schedule.  The
VMEM-resident one holds the whole per-device vector on-chip (fastest for
payloads up to a few MiB); `_rs_stream_kernel` keeps the vector in HBM
(aliased with the input) and streams two slices of working f32 through
VMEM with load/writeback DMAs — the reference's memory shape exactly:
arbitrarily long vectors through a fixed 32 KiB-class working set
(hw/all_reduce.sv:101-103,246-253).  `ring_reduce_scatter_fused` picks by
payload size; both are bit-identical.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import compat

from .bfp_pallas import LANES, _is_tpu
from .. import optim as _optim
from ..utils.config import BFPConfig, OptimizerSpec
# the shared protocol IR: the kernels below CONSUME its emitters — the
# schedule they execute and the stream graftmc explores are one
# definition (no jax inside verify.opstream; importing it here is free)
from ..verify import opstream as _opstream


def _encode_rows(x, block_size: int, mantissa_bits: int, rounding: str):
    """(R, 128) f32 -> ((R, 128) int8 mantissas, (R/B, 128) int8 scales).
    Register-level port of bfp_pallas._encode_kernel (the bit spec is
    bfp_golden layout="sublane"; hw/bf16_to_bfp_core.sv:30-132)."""
    R = x.shape[0]
    T = R // block_size
    bits = pltpu.bitcast(x, jnp.uint32)
    e = jnp.right_shift(bits, 23).astype(jnp.int32) & 0xFF
    emax = jnp.max(e.reshape(T, block_size, LANES), axis=1)
    scale_e = jnp.clip(emax - 127 - (mantissa_bits - 2), -126, 126)
    inv = pltpu.bitcast(((127 - scale_e) << 23).astype(jnp.uint32),
                        jnp.float32)                 # 2.0**-scale_e, exact
    q = x * jnp.repeat(inv, block_size, axis=0)
    q = jnp.round(q) if rounding == "nearest" else jnp.trunc(q)
    lim = float(2 ** (mantissa_bits - 1) - 1)
    return (jnp.clip(q, -lim, lim).astype(jnp.int8),
            scale_e.astype(jnp.int8))


def _decode_rows(mant, scale, block_size: int):
    """Inverse of _encode_rows (hw/bfp_to_bf16_core.sv:30-125)."""
    se = scale.astype(jnp.int32)
    s = pltpu.bitcast(((se + 127) << 23).astype(jnp.uint32), jnp.float32)
    return mant.astype(jnp.float32) * jnp.repeat(s, block_size, axis=0)


# the threaded per-device TPU interpreter (blocking semaphores, race
# detection) arrived after this container's jaxlib — under its original
# TPUInterpretParams name on older releases that do ship it; the
# flow-control battery skips without it (the discharge interpreter
# still runs)
_InterpretParams = getattr(pltpu, "InterpretParams",
                           getattr(pltpu, "TPUInterpretParams", None))
HAS_THREADED_INTERPRET = _InterpretParams is not None

_FRAME_ALIGN = 8     # int8 VMEM sublane tile: DMA slice row extents align


def _frame_rows(R: int, block_size: int) -> int:
    """Rows of one RDMA'd wire frame: R mantissa rows + R/B scale rows,
    padded up to the int8 (8,128) sublane tile — the Mosaic compiler
    rejects DMA slices whose row extent is not tile-aligned (first
    hardware contact, v5e: "Slice shape along dimension 1 must be aligned
    to tiling (8), but is 17").  Pad rows ride the wire but are never
    written or decoded; at the default slice plan (R=64, B=16: 68 -> 72
    rows) the overhead is 5.9%, and the live rows keep the reference's
    exact 16:1 mantissa:exponent rate (hw/bfp_adapter.sv:30,63-77)."""
    live = R + R // block_size
    return -(-live // _FRAME_ALIGN) * _FRAME_ALIGN


def _neighbor_barrier(left, right):
    """All ring members must have entered the kernel before the first RDMA
    lands in a neighbor's scratch (the analogue of ikl_setup's reset
    barrier, sw/mlp_mpi_example_f32.cpp:50-63)."""
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)


def _interp_args(interpret):
    """Map the public tri-state ``interpret`` flag to (pallas interpret
    argument, flow_control, unrolled).

    False       hardware: compiled kernel, rolled schedule, flow control ON
    True        discharge interpreter (fast lockstep emulation; copies
                materialize at dma_start in SPMD program order): flow
                control OFF — it cannot execute remote semaphore signals —
                and safety rests on the static schedule's program-order
                properties (_ag_schedule P1/P2)
    "threaded"  pltpu.InterpretParams: one thread per device, BLOCKING
                semaphores, remote signals, race detection — the real
                flow-control protocol (neighbor barrier + credit window)
                executes end-to-end; a protocol deadlock hangs the test
                and a data race is reported by the interpreter.  This is
                the strongest off-hardware evidence the credit protocol
                admits (tests/test_ring_pallas.py::TestFlowControl).
    """
    if interpret == "threaded":
        if not HAS_THREADED_INTERPRET:
            raise NotImplementedError(
                "interpret='threaded' needs pltpu.InterpretParams (or the "
                "older TPUInterpretParams — the threaded TPU interpreter), "
                "which this jaxlib does not ship — run the flow-control "
                "battery on a newer JAX, or use interpret=True for the "
                "discharge interpreter")
        return _InterpretParams(detect_races=True), True, True
    return bool(interpret), not interpret, bool(interpret)


def _when(cond, static: bool):
    """pl.when for the rolled (compiled) schedule; a plain python ``if``
    for the statically-unrolled schedule the interpreter runs — the
    vma-checked interpreter rejects lax.cond branch joins inside kernels
    (invariant vs varying branch outputs), and every schedule decision is
    a static counter comparison anyway."""
    if static:
        def deco(f):
            if cond:
                f()
        return deco
    return pl.when(cond)


class _KernelSink(_opstream.OpSink):
    """Maps the shared emitters' abstract ops (`verify.opstream`) onto
    one Pallas kernel's DMA/semaphore/VPU resources.  The emitter owns
    the FULL schedule — every wait/signal/transfer order decision; this
    sink only (a) binds each abstract op to a real call, (b) filters op
    classes for stage ablation (`do_*`) and the interpreter's
    flow-control limitation, and (c) lowers `when` to `pl.when` on the
    rolled path / a python ``if`` on the unrolled path (`_when`).  The
    kernels therefore carry no schedule text of their own to drift from
    the checked model — the PR-9 flat-route discipline, applied to every
    route."""

    def __init__(self, *, unrolled, flow_control, do_rdma=True,
                 do_enc=True, do_dec=True, do_upd=True, do_chk=False,
                 barrier=None, send=None, wait_send=None, wait_recv=None,
                 credit_wait=None, credit_signal=None, credit_drain=None,
                 encode=None, decode=None, update=None, chk_emit=None,
                 chk_arrive=None, dma_start=None, dma_wait=None,
                 local=None):
        self._unrolled = unrolled
        self._flow = flow_control
        self._do_rdma = do_rdma
        self._do_enc = do_enc
        self._do_dec = do_dec
        self._do_upd = do_upd
        self._do_chk = do_chk
        self._barrier = barrier
        self._send = send
        self._wait_send = wait_send
        self._wait_recv = wait_recv
        self._credit_wait = credit_wait
        self._credit_signal = credit_signal
        self._credit_drain = credit_drain
        self._encode = encode
        self._decode = decode
        self._update = update
        self._chk_emit = chk_emit
        self._chk_arrive = chk_arrive
        self._dma_start = dma_start
        self._dma_wait = dma_wait
        self._local = local

    def when(self, cond):
        return _when(cond, self._unrolled)

    def barrier(self):
        if self._flow and self._do_rdma:
            self._barrier()

    def send(self, q, src=None):
        if self._do_rdma:
            self._send(q, src)

    def wait_send(self, j):
        if self._do_rdma:
            self._wait_send(j)

    def wait_recv(self, g):
        if self._do_rdma:
            self._wait_recv(g)

    def credit_wait(self):
        if self._flow and self._do_rdma:
            self._credit_wait()

    def credit_signal(self):
        if self._flow and self._do_rdma:
            self._credit_signal()

    def credit_drain(self, k):
        if self._flow and self._do_rdma:
            self._credit_drain(k)

    def encode(self, q, src=None):
        if self._do_enc:
            self._encode(q, src)

    def decode(self, g):
        if self._do_dec:
            self._decode(g)

    def update(self, g):
        if self._do_upd:
            self._update(g)

    def chk_emit(self, msg, carry="wire", weight=None):
        if self._do_chk:
            self._chk_emit(msg)

    def chk_arrive(self, msg, carry="wire", weight=None):
        if self._do_chk:
            self._chk_arrive(msg)

    def local(self, name, *args):
        self._local(name, *args)

    def dma_start(self, chan, i, *conf):
        # conf (the checker's hazard-predecessor annotations) is
        # evidence for `check_dma_discipline`, not schedule — ignored
        self._dma_start(chan, i)

    def dma_wait(self, chan, i):
        self._dma_wait(chan, i)


# Default pipeline depth D of the reduce-scatter schedule: at steady
# state encode(g+D), RDMA(g+D-1 .. g+1), and decode+accumulate(g) are all
# in flight — the reference's keep-every-beat-busy discipline
# (hw/all_reduce.sv:891-1183) expressed as a comm-slot window of D+1
# frames.  D is capped by the slice plan (launch-ahead must not outrun the
# cross-hop RAW: send q reads what consume q-S accumulated), so deep
# pipelines need S >= D slices per chunk — which is what the sub-slice
# split below buys on big payloads.
_PIPE_DEPTH = 2

# Encode/decode VPU work is issued in sub-slice chunks of at most this
# many rows, so no single VPU op serializes against a whole slice's DMA;
# boundaries stay BFP-block-aligned, so the chunking is invisible to the
# bits (the blocks and the add order are unchanged).
_SUB_ROWS = 128


def _sub_rows(R: int, block_size: int) -> int:
    """Largest divisor of R that is <= _SUB_ROWS and a whole number of
    BFP blocks (rows group into blocks of block_size consecutive rows, so
    a sub-chunk boundary must never straddle a block)."""
    if R <= _SUB_ROWS:
        return R
    for d in range(_SUB_ROWS, block_size - 1, -1):
        if R % d == 0 and d % block_size == 0:
            return d
    return block_size                 # R % block_size == 0 by construction


def _rs_plan(n: int, S: int, depth: Optional[int]):
    """(D, n_slots, launch_first) for the deep-pipelined RS schedule —
    a delegate to THE plan definition in `verify.opstream.rs_plan`, so
    the emitted kernels and the graftmc model checker derive from one
    source (the three schedule invariants — RAW, SLOT, CAP — are stated
    there and exhaustively verified per plan by `make modelcheck`)."""
    from ..verify import opstream as _opstream
    return _opstream.rs_plan(n, S, depth, default_depth=_PIPE_DEPTH)


def _rs_offsets(ids, n: int, S: int, slice_rows: int):
    """(2, total) int32 schedule table — row 0: send-side acc row offset
    of emission q; row 1: recv-side offset of arrival g.  Hop s sends
    partial chunk idx-s-1 and accumulates into chunk idx-s-2 (the ring
    rotation of hw/all_reduce.sv's slice schedule).  Computed at trace
    time from the launch-data ring index, so the kernel's inner loop does
    one SMEM load per schedule decision instead of div/mod chains."""
    import numpy as np
    total = (n - 1) * S
    q = np.arange(total, dtype=np.int32)
    s, k = q // S, q % S
    idx = ids[0]
    chunk_rows = S * slice_rows
    send = ((idx - s - 1) % n) * chunk_rows + k * slice_rows
    recv = ((idx - s - 2) % n) * chunk_rows + k * slice_rows
    return jnp.stack([send, recv]).astype(jnp.int32)


def _rs_parse_refs(opt_kind: Optional[str], refs,
                   integrity: bool = False):
    """Split a fused-opt (or plain) RS kernel's positional refs into the
    named slots shared by both kernels: pallas passes inputs, then
    outputs, then scratch, and the fused variants add (hyper, w, *state)
    inputs and (w_new, *state_new) outputs.  With ``integrity`` the LAST
    output is the SMEM [2] uint32 (send_acc, recv_acc) checksum pair.
    Returns (hyper, x, w, st_in, out, w_out, st_out, chk, *scratch6)."""
    if opt_kind is None:
        x_ref, out_ref = refs[0], refs[1]
        rest = refs[2:]
        chk = None
        if integrity:
            chk, rest = rest[0], rest[1:]
        return (None, x_ref, None, (), out_ref, None, (), chk) \
            + tuple(rest)
    ns = OptimizerSpec(kind=opt_kind).n_state
    hyper_ref, x_ref, w_ref = refs[:3]
    st_in = tuple(refs[3:3 + ns])
    out_ref, w_out = refs[3 + ns], refs[4 + ns]
    st_out = tuple(refs[5 + ns:5 + 2 * ns])
    rest = refs[5 + 2 * ns:]
    chk = None
    if integrity:
        chk, rest = rest[0], rest[1:]
    return (hyper_ref, x_ref, w_ref, st_in, out_ref, w_out,
            st_out, chk) + tuple(rest)


def _frame_checksum(frame) -> jax.Array:
    """uint32 scalar: the ops.integrity odd-weighted word sum over one
    int8 wire frame, zero-extended byte-per-word — computed over the
    FULL (tile-padded) frame, which is exactly what the RDMA moves, so
    both ends of a hop sum identical bytes (pad rows are stale slot
    garbage, but the SAME stale garbage on both sides: the checksum is
    taken after encode on the send side and after wait_recv on the
    receive side, and nothing touches the slot in between)."""
    words = (frame[:].astype(jnp.int32) & 0xFF).astype(jnp.uint32)
    r, l = words.shape
    pos = (lax.broadcasted_iota(jnp.uint32, (r, l), 0) * jnp.uint32(l)
           + lax.broadcasted_iota(jnp.uint32, (r, l), 1))
    return jnp.sum(words * ((pos << 1) | jnp.uint32(1)),
                   dtype=jnp.uint32)


def _emission_weight(q) -> jax.Array:
    """Odd per-emission weight: my emission q is my right neighbor's
    arrival q, so sender and receiver weight the same message
    identically and the global conservation sum telescopes to zero iff
    every frame arrived bit-identical.  Delegates to
    ops.integrity.hop_weight — the kernel-side and host-side weight
    schemes MUST be one definition or conservation silently breaks."""
    from . import integrity
    return integrity.hop_weight(q)


def _rs_kernel(ids_ref, sched_ref, *refs, n: int, n_slices: int,
               slice_rows: int, block_size: int, mantissa_bits: int,
               rounding: str, flow_control: bool, unrolled: bool,
               depth: int, n_slots: int, launch_first: bool,
               ablate: Optional[str] = None,
               opt_kind: Optional[str] = None,
               integrity: bool = False):
    """The whole sliced ring reduce-scatter, one kernel invocation, as a
    depth-D pipeline: encode(g+D), RDMA(g+D-1 .. g+1), and
    decode+accumulate(g) proceed concurrently over an (D+1)-slot comm
    window with credit-based flow control (schedule invariants and their
    proof: _rs_plan).

    ids_ref:   SMEM [3] int32 — (my index, right neighbor, left neighbor),
               computed OUTSIDE the kernel: in-kernel axis_index arithmetic
               trips vma typing under the checked interpreter, and the ring
               position is launch-time data anyway
    sched_ref: SMEM (2, total) int32 — per-step acc row offsets
               (_rs_offsets), hoisting the div/mod bookkeeping out of the
               inner loop
    acc:       (L_rows, 128) f32 — running partials (starts as x)
    send_pkt:  (n_slots, R + R/B, 128) int8 — packed frames, slot-cycled
    recv_pkt:  (n_slots, R + R/B, 128) int8
    send/recv_sem: DMA (n_slots,) — one per comm slot
    credit_sem: REGULAR — downstream-consumed-slot credits (flow control)

    ablate (STAGE-ATTRIBUTION ONLY, compile-time): None runs the full
    pipeline; "encode" / "rdma" / "decode" run exactly one stage of the
    same schedule (the other stages compile away) and "skeleton" runs
    none of them — the bare loop + slot bookkeeping, the control-flow
    floor the cost model subtracts (ops.ring_cost).  Timing the variants
    answers which stage binds the pipelined hop — the per-stage breakdown
    the round-4 verdict ordered for the loopback microbench (the
    reference reads the same split from its stall counters,
    hw/all_reduce.sv:94-97).  Ablated outputs are garbage by design:
    "rdma" sends whatever is in the frames, "decode" decodes stale
    frames — timing is data-independent on the VPU/DMA so rates are
    unaffected.  Loopback/bench use only; never a collective.

    opt_kind (STATIC): None runs the plain reduce-scatter; "sgd" /
    "momentum" / "adamw" fuse the ZeRO-1 optimizer update into the
    final-hop decode — the reference's weight_update.sv sitting inside
    the decode datapath (SURVEY.md §3.2), generalized to pluggable
    formulas.  The refs then grow (hyper SMEM f32[HYPER_LEN], w shard,
    state shards) on the input side and (w_new, state_new) outputs
    aliased onto the shards; each owned sub-slice chunk updates in the
    same block-aligned `_sub_rows` pieces its decode retires, while the
    ring's remaining hops are still in flight.  The GRADIENT path (acc,
    out_ref) is bit-identical to the unfused kernel at every depth (same
    slices, same add order); the update formula is
    optim.fused_apply_blocks, bit-specified by optim.golden_fused_apply.
    ablate gains "update": ONLY the update stage of the same schedule
    (its VPU cost + nothing else), for ring_cost's fused-opt term."""
    assert ablate in (None, "encode", "rdma", "decode", "skeleton",
                      "update"), ablate
    assert ablate != "update" or opt_kind is not None, \
        "ablate='update' needs a fused optimizer"
    do_enc = ablate in (None, "encode")
    do_rdma = ablate in (None, "rdma")
    do_dec = ablate in (None, "decode")
    do_upd = opt_kind is not None and ablate in (None, "update")
    refs = _rs_parse_refs(opt_kind, refs, integrity)
    (hyper_ref, x_ref, w_ref, st_in, out_ref, w_out, st_out, chk_ref,
     acc, send_pkt, recv_pkt, send_sem, recv_sem, credit_sem) = refs
    # the integrity accumulators live in the SMEM output itself: pl.when
    # blocks mutate refs, never loop-carried values, and the wraparound
    # u32 sums are order-insensitive (addition mod 2^32 commutes)
    do_chk = integrity and ablate is None
    if integrity:
        # zero the SMEM output whenever it EXISTS (it is appended for
        # integrity=True regardless of ablate): an ablated kernel must
        # report a clean 0==0 conservation, never uninitialized SMEM
        chk_ref[0] = jnp.uint32(0)
        chk_ref[1] = jnp.uint32(0)
    idx = ids_ref[0]
    right = ids_ref[1]               # we send downstream (IKL ring order,
    left = ids_ref[2]                # sw/setup_route.sh:12-40)
    S = n_slices
    R = slice_rows
    B = block_size
    sub = _sub_rows(R, B)
    chunk_rows = S * R
    total = (n - 1) * S              # global send/consume count
    D = depth
    final_g0 = (n - 2) * S           # consumes >= this land in OUR chunk

    acc[:] = x_ref[:]

    def rdma(g):
        slot = g % n_slots
        return pltpu.make_async_remote_copy(
            src_ref=send_pkt.at[slot], dst_ref=recv_pkt.at[slot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)

    def encode_to_slot(g, _src=None):
        # rolled path: g = loop index + D can exceed the table under the
        # pl.when(q < total) guard — clamp the (guarded-dead) SMEM load
        # like the AG kernel's is_own_j does
        off = sched_ref[0, g if unrolled else jnp.clip(g, 0, total - 1)]
        slot = g % n_slots
        for c in range(0, R, sub):   # sub-slice chunks, block-aligned
            mant, scale = _encode_rows(acc[pl.ds(off + c, sub)], B,
                                       mantissa_bits, rounding)
            send_pkt[slot, pl.ds(c, sub)] = mant
            send_pkt[slot, pl.ds(R + c // B, sub // B)] = scale

    def chk_emit(q):
        # checksum the frame exactly as the RDMA will move it
        chk_ref[0] = chk_ref[0] + _emission_weight(q) \
            * _frame_checksum(send_pkt[q % n_slots])

    def chk_arrive(g):
        chk_ref[1] = chk_ref[1] + _emission_weight(g) \
            * _frame_checksum(recv_pkt[g % n_slots])

    def decode_slice(g):
        # decode slice g + accumulate into the chunk this hop owns
        off = sched_ref[1, g]
        slot = g % n_slots
        for c in range(0, R, sub):
            dec = _decode_rows(recv_pkt[slot, pl.ds(c, sub)],
                               recv_pkt[slot, pl.ds(R + c // B, sub // B)],
                               B)
            acc[pl.ds(off + c, sub)] = acc[pl.ds(off + c, sub)] + dec

    def update_slice(g):
        # fused ZeRO-1 optimizer update of the owned chunk this final-
        # hop decode just retired: the mean gradient is read straight
        # out of the accumulator rows, the master/state shards update in
        # place (aliased outputs) — the decode feeds weight_update with
        # no HBM round-trip in between, and the remaining ring hops
        # still overlap this VPU work.  Formula/bit contract:
        # optim.fused_apply_blocks.
        off = sched_ref[1, g]
        loc = off - idx * chunk_rows    # owned-shard row offset
        for c in range(0, R, sub):
            gblk = acc[pl.ds(off + c, sub)] / jnp.float32(n)
            wblk = w_ref[pl.ds(loc + c, sub)]
            stblks = tuple(s[pl.ds(loc + c, sub)] for s in st_in)
            w2, st2 = _optim.fused_apply_blocks(
                opt_kind, wblk, gblk, stblks, lambda i: hyper_ref[i])
            w_out[pl.ds(loc + c, sub)] = w2
            for so, sv in zip(st_out, st2):
                so[pl.ds(loc + c, sub)] = sv

    # The schedule itself — prologue pipe-fill, launch/consume order,
    # wait/credit placement, drain — is NOT written here: the kernel
    # consumes the shared emitter (`verify.opstream.RsEmitter`), the
    # same object graftmc explores exhaustively, through the sink
    # below.  flow_control=False only under the discharge interpreter,
    # whose lockstep emulation cannot execute remote semaphore signals;
    # the threaded interpreter and hardware run barrier + credits for
    # real (see _interp_args).
    emitter = _opstream.RsEmitter(n, S, depth, opt_kind=opt_kind,
                                  integrity=do_chk,
                                  default_depth=_PIPE_DEPTH)
    assert (emitter.n_slots, emitter.launch_first) == \
        (n_slots, launch_first), (emitter.n_slots, n_slots)
    sink = _KernelSink(
        unrolled=unrolled, flow_control=flow_control, do_rdma=do_rdma,
        do_enc=do_enc, do_dec=do_dec, do_upd=do_upd, do_chk=do_chk,
        barrier=lambda: _neighbor_barrier(left, right),
        send=lambda q, src: rdma(q).start(),
        wait_send=lambda j: rdma(j).wait_send(),
        wait_recv=lambda g: rdma(g).wait_recv(),
        credit_wait=lambda: pltpu.semaphore_wait(credit_sem, 1),
        credit_signal=lambda: pltpu.semaphore_signal(
            credit_sem, inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL),
        credit_drain=lambda k: pltpu.semaphore_wait(credit_sem, k),
        encode=encode_to_slot, decode=decode_slice, update=update_slice,
        chk_emit=chk_emit, chk_arrive=chk_arrive)

    emitter.prologue(sink)
    if unrolled:
        # static schedule (the interpreter path): every counter decision
        # is a python bool, no lax.cond joins for the vma checker to fight
        for g in range(total):
            emitter.step(sink, g)
    else:
        def body(g, _):
            emitter.step(sink, g)
            return 0
        lax.fori_loop(0, total, body, 0)
    emitter.epilogue(sink)

    out_ref[:] = acc[pl.ds(idx * chunk_rows, chunk_rows)]


def _ring_ids(axis_name: Optional[str]) -> jax.Array:
    """[my, right, left] int32 — ring coordinates as kernel data; all-self
    when axis_name is None (single-chip loopback mode).

    The values feed make_async_remote_copy's LOGICAL device id, which is
    the FLAT index into the whole mesh — equal to the ring-axis index only
    when every other manual axis has extent 1.  Guard that here at trace
    time: a silent mismatch would RDMA to the wrong chip."""
    if axis_name is None:
        return jnp.zeros((3,), jnp.int32)
    sizes = compat.mesh_axis_sizes()
    other = {a: s for a, s in sizes.items()
             if a != axis_name and s != 1}
    if other:
        raise ValueError(
            f"fused ring collectives need '{axis_name}' to be the only "
            f"nontrivial mesh axis (LOGICAL RDMA ids are flat mesh "
            f"indices); other axes with extent > 1: {other} — use the "
            f"XLA-op ring (ops.ring) on multi-axis meshes")
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    return jnp.stack([idx, (idx + 1) % n, (idx - 1) % n]).astype(jnp.int32)


@functools.partial(jax.jit, donate_argnames=("w2", "opt_st"),
                   static_argnames=(
    "axis_name", "block_size", "mantissa_bits", "rounding", "slice_elems",
    "interpret", "collective_id", "loopback_n", "ablate", "depth",
    "opt_kind", "integrity"))
def _rs_call(x2, axis_name: Optional[str], block_size: int,
             mantissa_bits: int, rounding: str, slice_elems: int,
             interpret: bool, collective_id: int,
             loopback_n: Optional[int] = None,
             ablate: Optional[str] = None,
             depth: Optional[int] = None,
             opt_kind: Optional[str] = None,
             w2: Optional[jax.Array] = None,
             opt_st: Tuple[jax.Array, ...] = (),
             hyper: Optional[jax.Array] = None,
             integrity: bool = False):
    n = loopback_n if axis_name is None else lax.axis_size(axis_name)
    L_rows = x2.shape[0]
    chunk_rows = L_rows // n
    R = slice_elems // LANES
    S = chunk_rows // R
    pkt_rows = _frame_rows(R, block_size)
    ids = _ring_ids(axis_name)
    sched = _rs_offsets(ids, n, S, R)
    D, n_slots, launch_first = _rs_plan(n, S, depth)
    _interp, _flow, _unrolled = _interp_args(interpret)
    kern = functools.partial(
        _rs_kernel, n=n, n_slices=S, slice_rows=R,
        block_size=block_size, mantissa_bits=mantissa_bits,
        rounding=rounding, flow_control=_flow, unrolled=_unrolled,
        depth=D, n_slots=n_slots, launch_first=launch_first,
        ablate=ablate, opt_kind=opt_kind, integrity=integrity)
    vma = jax.typeof(x2).vma | jax.typeof(ids).vma
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)

    def sds(shape):
        return compat.shape_dtype_struct(shape, jnp.float32, vma=vma)

    def chk_sds():
        return compat.shape_dtype_struct((2,), jnp.uint32, vma=vma)

    if opt_kind is None:
        out_shape = [sds((chunk_rows, LANES))]
        out_specs = [vmem]
        in_specs = [smem, smem, vmem]
        args = (ids, sched, x2)
        io_alias = {}
    else:
        ns = OptimizerSpec(kind=opt_kind).n_state
        assert w2 is not None and hyper is not None and len(opt_st) == ns
        # outputs: g_own (raw SUM — the gradient path stays bit-identical
        # to the unfused kernel), then w_new + state_new aliased onto the
        # donated shard operands (ZeRO-1: each replica owns 1/n of the
        # master + moments, updated in place)
        out_shape = [sds((chunk_rows, LANES))] * (2 + ns)
        out_specs = [vmem] * (2 + ns)
        in_specs = [smem, smem, smem] + [vmem] * (2 + ns)
        args = (ids, sched, hyper, x2, w2) + tuple(opt_st)
        io_alias = {4: 1, **{5 + i: 2 + i for i in range(ns)}}
    if integrity:
        # (send_acc, recv_acc) u32 pair — SMEM scalars, psum'd into the
        # conservation verdict OUTSIDE the kernel
        out_shape = out_shape + [chk_sds()]
        out_specs = out_specs + [smem]
    out = pl.pallas_call(
        kern,
        out_shape=(out_shape[0] if len(out_shape) == 1 else out_shape),
        in_specs=in_specs,
        out_specs=(out_specs[0] if len(out_specs) == 1 else out_specs),
        input_output_aliases=io_alias,
        scratch_shapes=[
            pltpu.VMEM((L_rows, LANES), jnp.float32),          # acc
            pltpu.VMEM((n_slots, pkt_rows, LANES), jnp.int8),  # send frames
            pltpu.VMEM((n_slots, pkt_rows, LANES), jnp.int8),  # recv frames
            pltpu.SemaphoreType.DMA((n_slots,)),
            pltpu.SemaphoreType.DMA((n_slots,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=compat.tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp,
    )(*args)
    if opt_kind is None:
        if integrity:
            return out[0], (out[1][0], out[1][1])
        return out
    if integrity:
        return (out[0], out[1], tuple(out[2:-1]),
                (out[-1][0], out[-1][1]))
    return (out[0], out[1], tuple(out[2:]))


# above this per-device payload, the whole-vector VMEM-resident kernel
# (input + acc copies) stops fitting on-chip; the streaming kernel keeps
# only two slices + frames in VMEM
_VMEM_RESIDENT_MAX_BYTES = 4 << 20


def ring_reduce_scatter_fused(x: jax.Array, axis_name: str, *,
                              compression: Optional[BFPConfig] = None,
                              slice_elems: int = 8192,
                              streaming: Optional[bool] = None,
                              interpret: Optional[bool] = None,
                              pipeline_depth: Optional[int] = None,
                              collective_id: int = 7,
                              integrity: bool = False):
    """Fused compress-into-hop ring reduce-scatter of a flat f32 [L].

    Drop-in for `ops.ring.ring_reduce_scatter(..., codec="pallas")` where
    the payload meets the tiling constraints below; bit-identical result.

    streaming=None picks by size: payloads over ~4 MiB/device stream
    HBM->VMEM slice by slice (the vector never lives on-chip, matching
    the reference's fixed 32 KiB working set over arbitrarily long
    vectors); smaller payloads use the VMEM-resident kernel.  Both are
    bit-identical — the choice is residency, not numerics.

    pipeline_depth picks the launch-ahead D of the slice schedule
    (default _PIPE_DEPTH, capped by the slice plan — _rs_plan): at
    steady state encode(g+D), D RDMAs, and decode(g) run concurrently.
    A schedule choice, never a numerics choice: every depth is
    bit-identical (the slice partition and add order are unchanged).

    integrity=True returns ``(owned, wire_ok)``: the kernel accumulates
    the ops.integrity exact frame checksums of every emission (at
    encode) and every arrival (at wait_recv) into its SMEM output, and
    the conservation psum OUTSIDE the kernel yields the replicated
    verdict — the gradient path is bit-identical to integrity=False at
    every depth (checksums only READ the frames), no checksum rides the
    wire, and the RDMA'd bytes are unchanged.  Validated under the
    interpreters like the rest of the kernel contract (the hardware
    canary discipline of CollectiveConfig.fused_kernel applies).

    Constraints (assert, don't silently repartition — changing the block
    partition would change the bits):
      - L % n == 0, chunk C = L/n
      - C % slice_elems == 0, slice_elems % (block_size * 128) == 0
    """
    cfg = compression or BFPConfig()
    n = lax.axis_size(axis_name)
    L = x.shape[0]
    if interpret is None:
        interpret = not _is_tpu()
    assert L % n == 0, (L, n)
    C = L // n
    if C % slice_elems or slice_elems % (cfg.block_size * LANES):
        raise ValueError(
            f"fused ring needs chunk {C} % slice_elems {slice_elems} == 0 "
            f"and slice_elems % {cfg.block_size * LANES} == 0")
    if n == 1:
        return (x, jnp.bool_(True)) if integrity else x
    if streaming is None:
        streaming = L * 4 > _VMEM_RESIDENT_MAX_BYTES
    x2 = x.astype(jnp.float32).reshape(-1, LANES)
    call = _rs_stream_call if streaming else _rs_call
    out = call(x2, axis_name, cfg.block_size, cfg.mantissa_bits,
               cfg.rounding, slice_elems, interpret, collective_id,
               depth=pipeline_depth, integrity=integrity)
    if not integrity:
        return out.reshape(C)
    out, (sa, ra) = out
    from . import integrity as _integrity
    return out.reshape(C), _integrity.conservation_ok(sa, ra, axis_name)


def _rs_stream_kernel(ids_ref, sched_ref, *refs, n: int, n_slices: int,
                      slice_rows: int, block_size: int, mantissa_bits: int,
                      rounding: str, flow_control: bool, unrolled: bool,
                      depth: int, n_slots: int, launch_first: bool,
                      ablate: Optional[str] = None,
                      opt_kind: Optional[str] = None,
                      integrity: bool = False):
    """HBM-streaming variant of _rs_kernel: the vector stays in HBM (acc
    aliases the input buffer) and only two slices of working f32 plus the
    int8 frames live in VMEM — the reference's exact memory shape, which
    streams arbitrarily long vectors through fixed 32 KiB slices and a
    handful of FIFOs (hw/all_reduce.sv:101-103,246-253) instead of
    buffering the vector on-chip.  The same depth-D comm window as
    _rs_kernel (invariants: _rs_plan) plus two streaming-only overlaps:
    the send-side slice load is prefetched ONE emission ahead (ld(q+1)
    starts before encode(q), hiding the HBM read behind the codec), and
    the recv-side load starts before the wire wait.  The cross-hop RAW
    hazard (hop s sends what hop s-1 wrote back) is guarded by the
    writeback wait discipline below.

    del x_hbm: the aliased acc ref IS the input buffer (same for the
    fused-opt w/state shards: their aliased OUTPUT refs are the buffers).

    opt_kind (STATIC): as in _rs_kernel — fuse the ZeRO-1 optimizer
    update into the final-hop decode.  Streaming adds the reference's
    memory shape to the update too: the owned master/state slice streams
    HBM->VMEM while the wire wait is in flight, updates in VMEM in the
    same `_sub_rows` chunks the decode retires, and writes back on its
    own DMA pair — so the optimizer's entire HBM traffic (read+write of
    w and moments, 1/n of the model per replica) hides under the ring's
    remaining hops instead of running as a separate exposed pass.
    """
    # Stage ablation (loopback attribution only — see _rs_kernel): each
    # variant keeps exactly one pipeline resource class of the SAME
    # schedule: "hbm" = slice load + store-load + writeback streaming,
    # "encode" = load + codec-in, "rdma" = the wire chain alone,
    # "decode" = store-load + codec-out+add + writeback, "update" = the
    # fused-optimizer stage alone (its state-slice DMAs + VPU update),
    # "skeleton" = none of them (the control-flow floor, ops.ring_cost).
    assert ablate in (None, "encode", "rdma", "decode", "hbm",
                      "skeleton", "update"), ablate
    assert ablate != "update" or opt_kind is not None, \
        "ablate='update' needs a fused optimizer"
    do_ld = ablate in (None, "encode", "hbm")
    do_enc = ablate in (None, "encode")
    do_rdma = ablate in (None, "rdma")
    do_stld = ablate in (None, "hbm", "decode")
    do_dec = ablate in (None, "decode")
    do_wb = ablate in (None, "hbm", "decode")
    do_upd = opt_kind is not None and ablate in (None, "update")
    do_chk = integrity and ablate is None
    ns = 0 if opt_kind is None else OptimizerSpec(kind=opt_kind).n_state
    n_t = 1 + ns                     # fused-opt tensors: w + state shards
    chk_ref = None
    if opt_kind is None:
        x_hbm = refs[0]
        hyper_ref = None
        acc = refs[1]
        opt_out = ()
        rest = refs[2:]
        if integrity:
            chk_ref, rest = rest[0], rest[1:]
        (ld, st, send_pkt, recv_pkt, ld_sem, st_ld_sem, wb_sem, send_sem,
         recv_sem, credit_sem) = rest
        opt_buf = opt_ld_sem = opt_wb_sem = None
    else:
        hyper_ref, x_hbm = refs[0], refs[1]
        # inputs w_hbm/st_hbm are aliased onto the outputs right after
        # acc — the out refs ARE the buffers (del the input handles)
        acc = refs[2 + n_t]
        opt_out = tuple(refs[3 + n_t:3 + 2 * n_t])
        rest = refs[3 + 2 * n_t:]
        if integrity:
            chk_ref, rest = rest[0], rest[1:]
        (ld, st, send_pkt, recv_pkt, opt_buf, ld_sem, st_ld_sem, wb_sem,
         opt_ld_sem, opt_wb_sem, send_sem, recv_sem,
         credit_sem) = rest
    del refs, x_hbm
    if integrity:
        # zeroed whenever the SMEM output exists (see _rs_kernel): an
        # ablated kernel reports clean 0==0 conservation, never garbage
        chk_ref[0] = jnp.uint32(0)
        chk_ref[1] = jnp.uint32(0)
    idx = ids_ref[0]
    right = ids_ref[1]
    left = ids_ref[2]
    S = n_slices
    R = slice_rows
    B = block_size
    sub = _sub_rows(R, B)
    chunk_rows = S * R
    total = (n - 1) * S
    D = depth
    final_g0 = (n - 2) * S           # consumes >= this land in OUR chunk

    def send_off(q):
        # clamp guarded-dead loads past the table (see _rs_kernel's
        # encode_to_slot): rolled-path q can exceed total under pl.when
        return sched_ref[0, q if unrolled else jnp.clip(q, 0, total - 1)]

    def recv_off(g):
        return sched_ref[1, g]

    def ld_dma(q):
        return pltpu.make_async_copy(acc.at[pl.ds(send_off(q), R)],
                                     ld.at[q % 2], ld_sem.at[q % 2])

    def stld_dma(g):
        return pltpu.make_async_copy(acc.at[pl.ds(recv_off(g), R)],
                                     st.at[g % 2], st_ld_sem.at[g % 2])

    def wb_dma(g):
        return pltpu.make_async_copy(st.at[g % 2],
                                     acc.at[pl.ds(recv_off(g), R)],
                                     wb_sem.at[g % 2])

    def rdma(g):
        slot = g % n_slots
        return pltpu.make_async_remote_copy(
            src_ref=send_pkt.at[slot], dst_ref=recv_pkt.at[slot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)

    def encode_from_ld(q, _src=None):
        slot = q % n_slots
        for c in range(0, R, sub):   # sub-slice chunks, block-aligned
            mant, scale = _encode_rows(ld[q % 2, pl.ds(c, sub)], B,
                                       mantissa_bits, rounding)
            send_pkt[slot, pl.ds(c, sub)] = mant
            send_pkt[slot, pl.ds(R + c // B, sub // B)] = scale

    def chk_emit(q):
        # checksum the frame exactly as the RDMA will move it
        chk_ref[0] = chk_ref[0] + _emission_weight(q) \
            * _frame_checksum(send_pkt[q % n_slots])

    def chk_arrive(g):
        chk_ref[1] = chk_ref[1] + _emission_weight(g) \
            * _frame_checksum(recv_pkt[g % n_slots])

    def decode_slice(g):
        slot = g % n_slots
        for c in range(0, R, sub):
            dec = _decode_rows(recv_pkt[slot, pl.ds(c, sub)],
                               recv_pkt[slot, pl.ds(R + c // B, sub // B)],
                               B)
            st[g % 2, pl.ds(c, sub)] = st[g % 2, pl.ds(c, sub)] + dec

    # -- fused-optimizer streaming plumbing (opt_kind only): the owned
    # master/state slice of final-hop consume g cycles through a 2-deep
    # VMEM window per tensor (opt_buf[t]), with its own ld/wb DMA pairs.
    # Each tensor's HBM rows for consume g are touched by exactly one
    # (load, update, writeback) triple, so the only hazard is VMEM slot
    # reuse: ld(g) must not overwrite a buffer wb(g-2) still drains —
    # guarded at consume entry; the last two writebacks drain at exit.
    def opt_loc(g):
        return recv_off(g) - idx * chunk_rows

    def opt_ld_dma(t, g):
        return pltpu.make_async_copy(
            opt_out[t].at[pl.ds(opt_loc(g), R)], opt_buf.at[t, g % 2],
            opt_ld_sem.at[t * 2 + g % 2])

    def opt_wb_dma(t, g):
        return pltpu.make_async_copy(
            opt_buf.at[t, g % 2], opt_out[t].at[pl.ds(opt_loc(g), R)],
            opt_wb_sem.at[t * 2 + g % 2])

    def update_slice(g):
        # mean-gradient slice straight from the decode buffer; update in
        # place in the VMEM window (formula: optim.fused_apply_blocks)
        for c in range(0, R, sub):
            gblk = st[g % 2, pl.ds(c, sub)] / jnp.float32(n)
            wblk = opt_buf[0, g % 2, pl.ds(c, sub)]
            stblks = tuple(opt_buf[1 + i, g % 2, pl.ds(c, sub)]
                           for i in range(ns))
            w2, st2 = _optim.fused_apply_blocks(
                opt_kind, wblk, gblk, stblks, lambda i: hyper_ref[i])
            opt_buf[0, g % 2, pl.ds(c, sub)] = w2
            for i, sv in enumerate(st2):
                opt_buf[1 + i, g % 2, pl.ds(c, sub)] = sv

    def dma_start(chan, i):
        # the abstract DMA channels of `RsStreamEmitter`, bound to this
        # kernel's copy descriptors (ablation filters per channel class)
        if chan == "ld":
            if do_ld:
                ld_dma(i).start()
        elif chan == "st":
            if do_stld:
                stld_dma(i).start()
        elif chan == "wb":
            if do_wb:
                wb_dma(i).start()
        elif chan.startswith("optld"):
            if do_upd:
                opt_ld_dma(int(chan[5:]), i).start()
        elif chan.startswith("optwb"):
            if do_upd:
                opt_wb_dma(int(chan[5:]), i).start()
        else:
            raise AssertionError(chan)

    def dma_wait(chan, i):
        if chan == "ld":
            if do_ld:
                ld_dma(i).wait()
        elif chan == "st":
            if do_stld:
                stld_dma(i).wait()
        elif chan == "wb":
            if do_wb:
                wb_dma(i).wait()
        elif chan.startswith("optld"):
            if do_upd:
                opt_ld_dma(int(chan[5:]), i).wait()
        elif chan.startswith("optwb"):
            if do_upd:
                opt_wb_dma(int(chan[5:]), i).wait()
        else:
            raise AssertionError(chan)

    # The schedule — prologue pipe-fill, one-ahead prefetch gate,
    # launch/consume order, the single-wait writeback discipline, the
    # fused-opt state windows, every drain — is NOT written here: the
    # kernel consumes the shared emitter (`verify.opstream.
    # RsStreamEmitter`), the same object graftmc explores exhaustively
    # and `check_dma_discipline` audits statically, through the sink
    # below.  flow_control=False only under the discharge interpreter
    # (see _interp_args).
    emitter = _opstream.RsStreamEmitter(n, S, depth, opt_kind=opt_kind,
                                        integrity=do_chk,
                                        default_depth=_PIPE_DEPTH)
    assert (emitter.n_slots, emitter.launch_first) == \
        (n_slots, launch_first), (emitter.n_slots, n_slots)
    sink = _KernelSink(
        unrolled=unrolled, flow_control=flow_control, do_rdma=do_rdma,
        do_enc=do_enc, do_dec=do_dec, do_upd=do_upd, do_chk=do_chk,
        barrier=lambda: _neighbor_barrier(left, right),
        send=lambda q, src: rdma(q).start(),
        wait_send=lambda j: rdma(j).wait_send(),
        wait_recv=lambda g: rdma(g).wait_recv(),
        credit_wait=lambda: pltpu.semaphore_wait(credit_sem, 1),
        credit_signal=lambda: pltpu.semaphore_signal(
            credit_sem, inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL),
        credit_drain=lambda k: pltpu.semaphore_wait(credit_sem, k),
        encode=encode_from_ld, decode=decode_slice, update=update_slice,
        chk_emit=chk_emit, chk_arrive=chk_arrive,
        dma_start=dma_start, dma_wait=dma_wait)

    emitter.prologue(sink)
    if unrolled:
        for g in range(total):
            emitter.step(sink, g)
    else:
        def body(g, _):
            emitter.step(sink, g)
            return 0
        lax.fori_loop(0, total, body, 0)
    emitter.epilogue(sink)


@functools.partial(jax.jit, donate_argnums=(0,),
                   donate_argnames=("w2", "opt_st"), static_argnames=(
    "axis_name", "block_size", "mantissa_bits", "rounding", "slice_elems",
    "interpret", "collective_id", "loopback_n", "ablate", "depth",
    "opt_kind", "integrity"))
def _rs_stream_call(x2, axis_name: Optional[str], block_size: int,
                    mantissa_bits: int, rounding: str, slice_elems: int,
                    interpret: bool, collective_id: int,
                    loopback_n: Optional[int] = None,
                    ablate: Optional[str] = None,
                    depth: Optional[int] = None,
                    opt_kind: Optional[str] = None,
                    w2: Optional[jax.Array] = None,
                    opt_st: Tuple[jax.Array, ...] = (),
                    hyper: Optional[jax.Array] = None,
                    integrity: bool = False):
    n = loopback_n if axis_name is None else lax.axis_size(axis_name)
    L_rows = x2.shape[0]
    chunk_rows = L_rows // n
    R = slice_elems // LANES
    S = chunk_rows // R
    pkt_rows = _frame_rows(R, block_size)
    ids = _ring_ids(axis_name)
    sched = _rs_offsets(ids, n, S, R)
    D, n_slots, launch_first = _rs_plan(n, S, depth)
    _interp, _flow, _unrolled = _interp_args(interpret)
    kern = functools.partial(
        _rs_stream_kernel, n=n, n_slices=S, slice_rows=R,
        block_size=block_size, mantissa_bits=mantissa_bits,
        rounding=rounding, flow_control=_flow, unrolled=_unrolled,
        depth=D, n_slots=n_slots, launch_first=launch_first,
        ablate=ablate, opt_kind=opt_kind, integrity=integrity)
    vma = jax.typeof(x2).vma | jax.typeof(ids).vma
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    hbm = pl.BlockSpec(memory_space=pl.ANY)

    def sds(shape):
        return compat.shape_dtype_struct(shape, jnp.float32, vma=vma)

    ns = 0 if opt_kind is None else OptimizerSpec(kind=opt_kind).n_state
    n_t = 1 + ns
    if opt_kind is None:
        out_shape = [sds((L_rows, LANES))]
        out_specs = [hbm]
        in_specs = [smem, smem, hbm]
        args = (ids, sched, x2)
        io_alias = {2: 0}
    else:
        assert w2 is not None and hyper is not None and len(opt_st) == ns
        out_shape = [sds((L_rows, LANES))] + [sds((chunk_rows, LANES))] * n_t
        out_specs = [hbm] * (1 + n_t)
        in_specs = [smem, smem, smem] + [hbm] * (1 + n_t)
        args = (ids, sched, hyper, x2, w2) + tuple(opt_st)
        io_alias = {3: 0, **{4 + i: 1 + i for i in range(n_t)}}
    if integrity:
        out_shape = out_shape \
            + [compat.shape_dtype_struct((2,), jnp.uint32, vma=vma)]
        out_specs = out_specs + [smem]
    res = pl.pallas_call(
        kern,
        out_shape=(out_shape[0] if len(out_shape) == 1 else out_shape),
        in_specs=in_specs,
        out_specs=(out_specs[0] if len(out_specs) == 1 else out_specs),
        input_output_aliases=io_alias,
        scratch_shapes=[
            pltpu.VMEM((2, R, LANES), jnp.float32),        # send loads
            pltpu.VMEM((2, R, LANES), jnp.float32),        # recv acc
            pltpu.VMEM((n_slots, pkt_rows, LANES), jnp.int8),  # send frames
            pltpu.VMEM((n_slots, pkt_rows, LANES), jnp.int8),  # recv frames
        ] + ([] if opt_kind is None else [
            pltpu.VMEM((n_t, 2, R, LANES), jnp.float32),   # w/state window
        ]) + [
            pltpu.SemaphoreType.DMA((2,)),                 # ld
            pltpu.SemaphoreType.DMA((2,)),                 # st load
            pltpu.SemaphoreType.DMA((2,)),                 # writeback
        ] + ([] if opt_kind is None else [
            pltpu.SemaphoreType.DMA((n_t * 2,)),           # state ld
            pltpu.SemaphoreType.DMA((n_t * 2,)),           # state wb
        ]) + [
            pltpu.SemaphoreType.DMA((n_slots,)),           # rdma send
            pltpu.SemaphoreType.DMA((n_slots,)),           # rdma recv
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=compat.tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp,
    )(*args)
    chk = None
    if integrity:
        chk = (res[-1][0], res[-1][1])
        res = res[:-1] if opt_kind is not None else res[0]
    acc = res if opt_kind is None else res[0]
    # the owned chunk lives at rows [idx*chunk_rows, +chunk_rows) of the
    # accumulated (aliased) vector
    idx = jnp.int32(0) if axis_name is None else lax.axis_index(axis_name)
    g_own = lax.dynamic_slice_in_dim(acc, idx * chunk_rows, chunk_rows,
                                     axis=0)
    if opt_kind is None:
        return g_own if chk is None else (g_own, chk)
    if chk is None:
        return (g_own, res[1], tuple(res[2:]))
    return (g_own, res[1], tuple(res[2:]), chk)


def _ag_kernel(ids_ref, own_ref, out_ref, send_pkt, recv_pkt, send_sem,
               recv_sem, credit_sem, *, n: int, block_size: int,
               mantissa_bits: int, rounding: str, flow_control: bool,
               unrolled: bool):
    """Fused compressed ring all-gather: encode the owned chunk ONCE, then
    forward the received frame VERBATIM each hop (BFP roundtrip is
    idempotent, so every replica sees identical bytes — the semantics of
    ops.ring.ring_all_gather and the golden model), decoding each arrival
    while its onward RDMA is in flight.  This is the phase that
    distributes updated weights in the fused collective
    (hw/all_reduce.sv FORWARD_OUTPUT/OUTPUT_SEND:996-1086)."""
    idx = ids_ref[0]
    right = ids_ref[1]
    left = ids_ref[2]
    R = own_ref.shape[0]             # chunk rows
    SB = R // block_size

    def rdma(s, src):
        slot = s % 2
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=recv_pkt.at[slot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)

    if flow_control:
        _neighbor_barrier(left, right)

    mant, scale = _encode_rows(own_ref[:], block_size, mantissa_bits,
                               rounding)
    send_pkt[pl.ds(0, R)] = mant
    send_pkt[pl.ds(R, SB)] = scale
    # the local replica stores the same quantized values it sends
    out_ref[pl.ds(idx * R, R)] = _decode_rows(mant, scale, block_size)
    rdma(0, send_pkt).start()

    def hop(s):
        p = (s - 1) % 2
        rdma(s - 1, send_pkt).wait_recv()     # frame s-1 has landed

        @_when(s < n - 1, unrolled)
        def _forward():
            @_when(s == 2, unrolled)
            def _initial_send_drained():
                # forward hop 2 reuses send_sem[0], which the INITIAL
                # owned-chunk RDMA signaled; without this wait the later
                # _done_fwd could consume that stale signal and credit the
                # slot while the forward is still reading it (every other
                # same-slot predecessor is a forward already waited in its
                # own _done_fwd)
                rdma(0, send_pkt).wait_send()
            if flow_control:
                @_when(s >= 2, unrolled)
                def _credit():                # remote slot s%2 freed?
                    pltpu.semaphore_wait(credit_sem, 1)
            rdma(s, recv_pkt.at[p]).start()

        # decode while the forward RDMA is on the wire
        chunk = (idx - s) % n
        dec = _decode_rows(recv_pkt[p, pl.ds(0, R)],
                           recv_pkt[p, pl.ds(R, SB)], block_size)
        out_ref[pl.ds(chunk * R, R)] = dec
        @_when(s < n - 1, unrolled)
        def _done_fwd():
            # our recv slot p is the upstream's NEXT delivery target; it
            # must not be freed until the onward send has drained it
            rdma(s, recv_pkt.at[p]).wait_send()
        if flow_control:
            pltpu.semaphore_signal(credit_sem, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)

    if unrolled:
        for s in range(1, n):
            hop(s)
    else:
        def body(s, _):
            hop(s)
            return 0
        lax.fori_loop(1, n, body, 0)
    if n <= 3:
        # rings without a forward at hop 2 never consumed the initial
        # send's semaphore in _initial_send_drained — drain it here
        rdma(0, send_pkt).wait_send()
    if flow_control:
        pltpu.semaphore_wait(credit_sem, 2 if n > 2 else 1)


@functools.partial(jax.jit, static_argnames=(
    "axis_name", "block_size", "mantissa_bits", "rounding", "interpret",
    "collective_id", "loopback_n"))
def _ag_call(own2, axis_name: Optional[str], block_size: int,
             mantissa_bits: int, rounding: str, interpret: bool,
             collective_id: int, loopback_n: Optional[int] = None):
    n = loopback_n if axis_name is None else lax.axis_size(axis_name)
    R = own2.shape[0]
    pkt_rows = _frame_rows(R, block_size)
    ids = _ring_ids(axis_name)
    _interp, _flow, _unrolled = _interp_args(interpret)
    kern = functools.partial(
        _ag_kernel, n=n, block_size=block_size,
        mantissa_bits=mantissa_bits, rounding=rounding,
        flow_control=_flow, unrolled=_unrolled)
    vma = jax.typeof(own2).vma | jax.typeof(ids).vma
    return pl.pallas_call(
        kern,
        out_shape=compat.shape_dtype_struct((n * R, LANES), jnp.float32,
                                       vma=vma),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((pkt_rows, LANES), jnp.int8),       # own frame
            pltpu.VMEM((2, pkt_rows, LANES), jnp.int8),    # recv frames
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=compat.tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp,
    )(ids, own2)


# THE interleaved emission schedule of the streaming gather — moved to
# the shared protocol IR (P1/P2/P3 asserted per (n, S) there; the
# exhaustive graftmc exploration of the full wait/credit protocol over
# this schedule is what retired the "statically asserted" ledger row,
# and what caught the fwd/own emission-index inversion whose one-credit
# under-wait the static sweep could not see).  tests/test_verify.py
# pins the delegation by identity.
_ag_schedule = _opstream.ag_schedule


class _SmemAgSchedule:
    """The rolled (hardware) path's schedule accessor: the same
    `ag_schedule` tables as `verify.opstream.AgSchedule`, read per
    decision from the kernel's SMEM copy (in-kernel jnp table constants
    are rejected by the Mosaic compiler).  Rows: 0 content, 1 fwd_j,
    2 own_at, 3 own-mask, 4 own_j — built in `_ag_stream_call` from the
    emitter's python tables."""

    def __init__(self, sched_ref, total):
        self._s = sched_ref
        self._total = total

    def fwd_j(self, m):
        return self._s[1, m]

    def own_at(self, m):
        return self._s[2, m]

    def own_j(self, k):
        return self._s[4, k]

    def is_own_j(self, j):
        return (j >= 0) & (self._s[3, jnp.clip(j, 0, self._total - 1)] == 1)


def _ag_stream_kernel(ids_ref, sched_ref, own_hbm, out_hbm, ld, own_st, st,
                      send_pkt, recv_pkt, ld_sem, own_wb_sem, wb_sem,
                      send_sem, recv_sem, credit_sem, *, n: int,
                      n_slices: int, n_slots: int, slice_rows: int,
                      block_size: int, mantissa_bits: int, rounding: str,
                      flow_control: bool, unrolled: bool, emitter):
    """HBM-streaming fused ring all-gather, interleaved emission order.

    Loop index m = arrival order (== upstream's emission order; wire slots
    and semaphores cycle by emission index j % n_slots on BOTH ends).
    Per m: consume arrival content(m) — wait recv, start the onward
    forward (emission j_fwd), decode into a VMEM slice, write back to the
    out vector in HBM — then emit the next own-slice send if this content
    step schedules one.  Single-wait semaphore discipline:

      send j:  forwards wait their own send right before crediting the
               recv slot; own sends are waited by the next same-slot
               emitter (pre-wait when j - n_slots is an own),
               tail-drained statically.
      wb m:    one-iteration-lag head wait + final drain.
      own_wb:  guarded at own_st slot reuse + tail drain.
      credit:  wait one before any send with j >= n_slots; signal per
               consume.

    Slot window: n_slots = S + 2 (capped at total).  The own phase emits
    two frames per consume step, so an emission index can lead its step
    by up to S (_ag_schedule property P2); S + 2 covers the lead with one
    slot of margin, which makes slot reuse safe in BOTH execution
    models — the interpreter's lockstep program order (overwrite of slot
    j % n_slots comes after the decode of arrival j - n_slots) and
    hardware's credit window (emission j waits a credit its downstream
    released at consume j - n_slots, a strictly earlier step by P2, so
    the wait-for graph is acyclic for arbitrary S and n — the proof is
    in _ag_schedule's docstring).
    """
    idx = ids_ref[0]
    right = ids_ref[1]
    left = ids_ref[2]
    S = n_slices
    R = slice_rows
    SB = R // block_size
    chunk_rows = S * R
    total = (n - 1) * S                 # arrivals == emissions

    def wslot(x):
        return x % n_slots

    # the static schedule arrives twice: as the emitter's python tables
    # (compile-time — drives the unrolled interpreter schedule and the
    # static tail-drain list) and as the sched_ref SMEM input (runtime —
    # the rolled hardware schedule reads it; in-kernel jnp table
    # constants are rejected by the Mosaic compiler: "kernel captures
    # constants ... pass them as inputs").  Both views read the SAME
    # `ag_schedule` tables; `_SmemAgSchedule` is only a reading style.
    if unrolled:
        acc_sched = emitter.sched

        def content(m):
            return emitter.sched.content_t[m]
    else:
        acc_sched = _SmemAgSchedule(sched_ref, total)

        def content(m):
            return sched_ref[0, m]

    def out_rdma(j, src):
        slot = wslot(j)
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=recv_pkt.at[slot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)

    def send(j, src):
        # src=None: own emission out of its send_pkt slot; src=m: the
        # onward forward straight out of arrival m's recv slot
        buf = send_pkt.at[wslot(j)] if src is None \
            else recv_pkt.at[wslot(src)]
        out_rdma(j, buf).start()

    def wait_send(j):
        # wait_send consumes emission j's send sem; frame shapes are
        # uniform, so any same-shape src is a valid descriptor
        out_rdma(j, send_pkt.at[wslot(j)]).wait_send()

    def wait_recv(m):
        out_rdma(m, send_pkt.at[wslot(m)]).wait_recv()

    def ld_dma(k):
        return pltpu.make_async_copy(
            own_hbm.at[pl.ds(k * R, R)], ld.at[k % 2], ld_sem.at[k % 2])

    def own_wb_dma(k):
        return pltpu.make_async_copy(
            own_st.at[k % 2],
            out_hbm.at[pl.ds(idx * chunk_rows + k * R, R)],
            own_wb_sem.at[k % 2])

    def wb_dma(m):
        t = content(m)
        s, k = t // S + 1, t % S
        off = ((idx - s) % n) * chunk_rows + k * R
        return pltpu.make_async_copy(st.at[m % 2],
                                     out_hbm.at[pl.ds(off, R)],
                                     wb_sem.at[m % 2])

    # mant/scale flow from the encode op to the own-store op of the SAME
    # send_own block (one `when` region — the emitter keeps them
    # adjacent), stashed here between the two sink calls
    last_enc = [None]

    def encode_own(j, k):
        """Encode own slice k into emission j's frame slot (the replica
        stores its own wire bytes — `own_store` below decodes the stash
        so every replica sees wire-identical values)."""
        mant, scale = _encode_rows(ld[k % 2], block_size, mantissa_bits,
                                   rounding)
        slot = wslot(j)
        send_pkt[slot, pl.ds(0, R)] = mant
        send_pkt[slot, pl.ds(R, SB)] = scale
        last_enc[0] = (mant, scale)

    def local_op(name, *args):
        assert name == "own_store", name
        k = args[0]
        mant, scale = last_enc[0]
        own_st[k % 2] = _decode_rows(mant, scale, block_size)

    def decode_arrival(m):
        # dst slot is the LOCAL st pipeline's (depth 2, cycled by
        # arrival index, drained by wb_dma(m) which reads st[m % 2]);
        # only the SRC uses the wire slot — conflating the two was a
        # real out-of-bounds bug the moment the wire window grew past
        # the st depth
        slot = wslot(m)
        st[m % 2] = _decode_rows(recv_pkt[slot, pl.ds(0, R)],
                                 recv_pkt[slot, pl.ds(R, SB)],
                                 block_size)

    def dma_start(chan, i):
        {"ld": lambda: ld_dma(i).start(),
         "ownwb": lambda: own_wb_dma(i).start(),
         "wb": lambda: wb_dma(i).start()}[chan]()

    def dma_wait(chan, i):
        {"ld": lambda: ld_dma(i).wait(),
         "ownwb": lambda: own_wb_dma(i).wait(),
         "wb": lambda: wb_dma(i).wait()}[chan]()

    # The schedule — the interleaved emission order, pre-wait rule,
    # credit placement, st/ownwb windows, tail drains — is NOT written
    # here: the kernel consumes the shared emitter
    # (`verify.opstream.AgStreamEmitter`), the same object graftmc
    # explores exhaustively with asynchronous landings (lockstep=True
    # is the interpreter primitive-lockstep ordering: all reads before
    # any same-step emission; hardware keeps forward-then-decode for
    # overlap, its slot occupancy credit-protected).
    sink = _KernelSink(
        unrolled=unrolled, flow_control=flow_control,
        barrier=lambda: _neighbor_barrier(left, right),
        send=send, wait_send=wait_send, wait_recv=wait_recv,
        credit_wait=lambda: pltpu.semaphore_wait(credit_sem, 1),
        credit_signal=lambda: pltpu.semaphore_signal(
            credit_sem, inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL),
        credit_drain=lambda k: pltpu.semaphore_wait(credit_sem, k),
        encode=lambda j, k: encode_own(j, k), decode=decode_arrival,
        dma_start=dma_start, dma_wait=dma_wait, local=local_op)

    emitter.prologue(sink, acc_sched)
    if unrolled:
        for m in range(total):
            emitter.step(sink, m, acc_sched, lockstep=True)
    else:
        def body(m, _):
            emitter.step(sink, m, acc_sched, lockstep=False)
            return 0
        lax.fori_loop(0, total, body, 0)
    emitter.epilogue(sink)


@functools.partial(jax.jit, static_argnames=(
    "axis_name", "block_size", "mantissa_bits", "rounding", "slice_elems",
    "interpret", "collective_id", "loopback_n"))
def _ag_stream_call(own2, axis_name: Optional[str], block_size: int,
                    mantissa_bits: int, rounding: str, slice_elems: int,
                    interpret: bool, collective_id: int,
                    loopback_n: Optional[int] = None):
    n = loopback_n if axis_name is None else lax.axis_size(axis_name)
    C_rows = own2.shape[0]
    R = slice_elems // LANES
    S = C_rows // R
    pkt_rows = _frame_rows(R, block_size)
    ids = _ring_ids(axis_name)
    # slot window sized to the slice plan: covers the own phase's maximum
    # emission lead (== S, ag_schedule P2) with one slot of margin — THE
    # rule lives in the IR (opstream.ag_n_slots), next to the emitter
    # graftmc explores
    n_slots = _opstream.ag_n_slots(n, S)
    _interp, _flow, _unrolled = _interp_args(interpret)
    emitter = _opstream.AgStreamEmitter(n, S)
    assert emitter.n_slots == n_slots, (emitter.n_slots, n_slots)
    sc = emitter.sched
    total = (n - 1) * S
    # SMEM copy of the emitter's schedule for the rolled (hardware)
    # path; rows: content / fwd_j / own_at / own-mask / own_j (padded
    # with -1) — read back through _SmemAgSchedule
    import numpy as np
    sched_np = np.full((5, total), -1, np.int32)
    sched_np[0] = sc.content_t
    sched_np[1] = sc.fwd_j_t
    sched_np[2] = sc.own_at_t
    sched_np[3] = [1 if j in sc.own_js else 0 for j in range(total)]
    sched_np[4, :S] = sc.own_j_t
    sched = jnp.asarray(sched_np)
    kern = functools.partial(
        _ag_stream_kernel, n=n, n_slices=S, n_slots=n_slots, slice_rows=R,
        block_size=block_size, mantissa_bits=mantissa_bits,
        rounding=rounding, flow_control=_flow, unrolled=_unrolled,
        emitter=emitter)
    vma = jax.typeof(own2).vma | jax.typeof(ids).vma
    return pl.pallas_call(
        kern,
        out_shape=compat.shape_dtype_struct((n * C_rows, LANES), jnp.float32,
                                       vma=vma),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, R, LANES), jnp.float32),        # own loads
            pltpu.VMEM((2, R, LANES), jnp.float32),        # own decode
            pltpu.VMEM((2, R, LANES), jnp.float32),        # recv decode
            pltpu.VMEM((n_slots, pkt_rows, LANES), jnp.int8),  # own frames
            pltpu.VMEM((n_slots, pkt_rows, LANES), jnp.int8),  # recv frames
            pltpu.SemaphoreType.DMA((2,)),                 # ld
            pltpu.SemaphoreType.DMA((2,)),                 # own wb
            pltpu.SemaphoreType.DMA((2,)),                 # recv wb
            pltpu.SemaphoreType.DMA((n_slots,)),           # rdma send
            pltpu.SemaphoreType.DMA((n_slots,)),           # rdma recv
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=compat.tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp,
    )(ids, sched, own2)


# Frame VMEM for the streaming gather is ~2 * (S+2)/S * (FR/(R*4)) bytes
# per chunk f32 element (send + recv windows), where FR = _frame_rows(R, B)
# includes the 8-row tile padding — 72/68 of the live 17/16 rate at the
# default R=64 plan, but up to 24/17 (~1.4x) at R=16; the binding
# constraint is the CHUNK size.  Larger chunks are gathered in sequential
# segments of at most this many elements (each segment is an independent
# all-gather — BFP blocks never straddle a segment boundary).
_AG_STREAM_MAX_CHUNK_ELEMS = 2 << 20      # ~4.5 MiB frame VMEM per segment


def ring_all_gather_fused(owned: jax.Array, axis_name: str, *,
                          compression: Optional[BFPConfig] = None,
                          slice_elems: int = 8192,
                          streaming: Optional[bool] = None,
                          interpret: Optional[bool] = None,
                          collective_id: int = 8) -> jax.Array:
    """Fused compressed ring all-gather of an owned chunk [C] -> [n*C].
    Bit-identical to ops.ring.ring_all_gather with codec="pallas" (the
    streaming kernel slices the chunk, but frames forward verbatim and
    blocks align to slice boundaries, so the bytes are unchanged).

    Routing: payloads whose gathered output fits the VMEM-resident budget
    (~4 MiB) use the whole-chunk resident kernel; larger payloads default
    to the HBM-streaming interleaved-emission kernel (slot window S + 2,
    deadlock-free for arbitrary slice plans — _ag_schedule P1/P2), gathered
    in sequential segments past the frame-VMEM budget.  streaming=False
    opts out to the separate-op XLA ring with the identical codec."""
    cfg = compression or BFPConfig()
    n = lax.axis_size(axis_name)
    C = owned.shape[0]
    if interpret is None:
        interpret = not _is_tpu()
    if C % (cfg.block_size * LANES):
        raise ValueError(
            f"fused ring gather needs chunk {C} % "
            f"{cfg.block_size * LANES} == 0")
    if n == 1:
        # quantize roundtrip via the same lane-layout codec kernels
        # (matches ops.ring's n==1 semantics: replicas see wire bytes);
        # inline entries — a nested jitted closed_call trips the vma
        # checker inside checked shard_maps
        from . import bfp_pallas
        mant, se = bfp_pallas.bfp_encode_inline(
            owned.astype(jnp.float32), cfg.block_size, cfg.mantissa_bits,
            cfg.rounding, interpret=interpret)
        return bfp_pallas.bfp_decode_inline(mant, se, cfg.block_size,
                                            owned.dtype,
                                            interpret=interpret)
    big = n * C * 4 > _VMEM_RESIDENT_MAX_BYTES
    if streaming is None:
        streaming = big
    if not streaming:
        if big:
            # explicit opt-out from the streaming kernel: the separate-op
            # ring with the SAME lane-layout codec — bit-identical bytes,
            # HBM-resident via XLA
            import dataclasses
            from . import ring as _ring_ops
            return _ring_ops.ring_all_gather(
                owned, axis_name,
                compression=dataclasses.replace(cfg, codec="pallas"))
        x2 = owned.astype(jnp.float32).reshape(-1, LANES)
        out = _ag_call(x2, axis_name, cfg.block_size, cfg.mantissa_bits,
                       cfg.rounding, interpret, collective_id)
        return out.reshape(n * C)

    # streaming kernel; frame VMEM scales with the chunk (not the slice
    # plan), so chunks beyond the budget gather in independent sequential
    # segments — blocks never straddle a segment boundary, so the bytes
    # match the whole-chunk gather exactly
    tile = cfg.block_size * LANES
    cap = _AG_STREAM_MAX_CHUNK_ELEMS - (_AG_STREAM_MAX_CHUNK_ELEMS % tile)

    def gather_seg(seg: jax.Array) -> jax.Array:
        sz = seg.shape[0]
        x2 = seg.astype(jnp.float32).reshape(-1, LANES)
        slice_e = pick_slice_elems(sz, slice_elems, cfg.block_size)
        out = _ag_stream_call(x2, axis_name, cfg.block_size,
                              cfg.mantissa_bits, cfg.rounding, slice_e,
                              interpret, collective_id)
        return out.reshape(n, sz)

    if C <= cap:
        return gather_seg(owned).reshape(n * C)
    outs = [gather_seg(owned[off:min(off + cap, C)])
            for off in range(0, C, cap)]
    return jnp.concatenate(outs, axis=1).reshape(n * C)


def ring_reduce_scatter_update_fused(
        x: jax.Array, w_own: jax.Array, opt_state, hyper: jax.Array,
        axis_name: str, *, opt_kind: str,
        compression: Optional[BFPConfig] = None,
        slice_elems: int = 8192, streaming: Optional[bool] = None,
        interpret: Optional[bool] = None,
        pipeline_depth: Optional[int] = None, collective_id: int = 9,
        integrity: bool = False):
    """Fused ring reduce-scatter + in-kernel ZeRO-1 optimizer update —
    the reference's defining datapath (decode feeds weight_update.sv with
    no host round-trip, SURVEY.md §3.2) plus ZeRO-1 weight-update
    sharding: each replica's owned slice of params + optimizer state
    updates AS its final-hop decode retires, inside the same depth-D
    pipelined kernel, so the optimizer costs zero exposed time.

    x:        flat f32 [L] local gradients (the collective input)
    w_own:    [L/n] owned f32 master shard (DONATED: updated in place)
    opt_state: dict of [L/n] f32 shards per OptimizerSpec(kind).state_keys
              (DONATED)
    hyper:    optim.fused_hyperparams(cfg, step) scalar vector — SMEM
              operand, so lr/schedule/weight-decay changes never recompile

    Returns ``(g_own_sum [L/n], w_new [L/n], new_state dict)`` —
    g_own_sum is the raw reduced SUM, bit-identical to
    ring_reduce_scatter_fused at every pipeline depth; the update formula
    is optim.fused_apply_blocks (bit spec: optim.golden_fused_apply
    composed with the codec's golden ring decode).  Same slicing/
    residency constraints and routing as ring_reduce_scatter_fused.

    integrity=True appends a replicated ``wire_ok`` bool: the SAME
    in-kernel frame-checksum accumulation as ring_reduce_scatter_fused
    (every emission at encode, every arrival at wait_recv), psum'd into
    the conservation verdict outside the kernel.  This is what lifts the
    old ``fused_optimizer x integrity_check`` construction error: the
    update consumed DONATED state, so nothing is left to gate a tripped
    verdict back to in-graph — instead the verdict invalidates the STEP
    (runtime.chaos.check_step_diag raises WireIntegrityError and the
    elastic restore/reshard ladder discards the poisoned state).  The
    gradient/update bits are identical to integrity=False at every depth
    (checksums only READ the frames) and the RDMA'd bytes are
    unchanged."""
    cfg = compression or BFPConfig()
    spec = OptimizerSpec(kind=opt_kind)
    n = lax.axis_size(axis_name)
    L = x.shape[0]
    if interpret is None:
        interpret = not _is_tpu()
    assert L % n == 0, (L, n)
    C = L // n
    assert n >= 2, "n == 1 is routed by ops.fused_update (no wire)"
    if C % slice_elems or slice_elems % (cfg.block_size * LANES):
        raise ValueError(
            f"fused ring needs chunk {C} % slice_elems {slice_elems} == 0 "
            f"and slice_elems % {cfg.block_size * LANES} == 0")
    if streaming is None:
        streaming = L * 4 > _VMEM_RESIDENT_MAX_BYTES
    x2 = x.astype(jnp.float32).reshape(-1, LANES)
    w2 = w_own.astype(jnp.float32).reshape(-1, LANES)
    st = tuple(opt_state[k].astype(jnp.float32).reshape(-1, LANES)
               for k in spec.state_keys)
    call = _rs_stream_call if streaming else _rs_call
    res = call(x2, axis_name, cfg.block_size, cfg.mantissa_bits,
               cfg.rounding, slice_elems, interpret,
               collective_id, depth=pipeline_depth,
               opt_kind=opt_kind, w2=w2, opt_st=st, hyper=hyper,
               integrity=integrity)
    if integrity:
        g2, w_new2, st2, (sa, ra) = res
    else:
        g2, w_new2, st2 = res
    out = (g2.reshape(C), w_new2.reshape(C),
           {k: v.reshape(C) for k, v in zip(spec.state_keys, st2)})
    if not integrity:
        return out
    from . import integrity as _integrity
    return out + (_integrity.conservation_ok(sa, ra, axis_name),)


def ring_all_reduce_fused(x: jax.Array, axis_name: str, *,
                          compression: Optional[BFPConfig] = None,
                          slice_elems: int = 8192,
                          interpret: Optional[bool] = None,
                          pipeline_depth: Optional[int] = None) -> jax.Array:
    """Fused all-reduce = fused reduce-scatter + fused all-gather."""
    owned = ring_reduce_scatter_fused(x, axis_name,
                                      compression=compression,
                                      slice_elems=slice_elems,
                                      interpret=interpret,
                                      pipeline_depth=pipeline_depth)
    return ring_all_gather_fused(owned, axis_name, compression=compression,
                                 interpret=interpret)


def pick_slice_elems(C: int, target: int, block_size: int) -> int:
    """Largest divisor of chunk C that is a multiple of block_size*LANES
    and <= target — the fused kernel's slice plan for arbitrary
    (padded-to-tile) payloads.  Slicing at block boundaries never changes
    the block partition, so this is a schedule choice, not a numerics
    choice."""
    tile = block_size * LANES
    assert C % tile == 0, (C, tile)
    k = C // tile
    best = 1
    d = 1
    while d * d <= k:
        if k % d == 0:
            for c in (d, k // d):
                if c * tile <= target and c > best:
                    best = c
        d += 1
    return best * tile


def _rs_op_stream(n: int, S: int, depth: Optional[int]):
    """The per-node op stream of the deep-pipelined RS schedule, as data —
    the exact wait/signal/transfer order _rs_kernel executes (every node
    runs the identical program).  A delegate to the shared protocol IR
    (`verify.opstream.rs_op_stream`), so the randomized simulator below,
    the exhaustive model checker (`make modelcheck`) and this kernel's
    schedule all derive from ONE definition."""
    from ..verify import opstream as _opstream
    return _opstream.rs_op_stream(n, S, depth, default_depth=_PIPE_DEPTH)


def simulate_rs_protocol(n: int, S: int, depth: Optional[int] = None,
                         seed: int = 0, max_events: int = 2_000_000) -> int:
    """Race/deadlock check of the credit protocol at model level: execute
    the RS op stream on n simulated nodes under a randomized scheduler
    with BLOCKING semaphores and asynchronous wire transfers (a started
    RDMA lands at an arbitrary later scheduler event, exactly the freedom
    real hardware has).  Fails on

      - deadlock: no node can advance and no transfer is in flight;
      - recv-slot overwrite: a frame lands in a slot whose previous frame
        is not yet decoded (the credit window's whole job);
      - send-slot overwrite: a node encodes into a slot whose previous
        transfer has not drained (wait_send's whole job);
      - ordering corruption: a decode finds a different emission than the
        schedule expects.

    Returns the number of scheduler events on success.  This is now the
    RANDOMIZED mode of the graftmc protocol checker (`verify.mc`): the
    op stream and the small-step semantics are the shared definitions
    the exhaustive checker explores completely for n <= 6, S <= 6,
    D <= 4 (`make modelcheck`); this entry point remains the seed-sweep
    fuzz beyond that envelope (n = 8 here: the threaded TPU interpreter
    needs a jaxlib newer than this one AND convoys on 1 core at n = 8 —
    the model checks the same wait-for graph without either limit)."""
    from ..verify import mc as _mc
    from ..verify import opstream as _opstream
    ops, n_slots = _rs_op_stream(n, S, depth)
    model = _opstream.RingModel(
        n, ops, n_slots,
        meta={"n": n, "S": S, "depth": depth, "seed": seed})
    # legacy fuzz semantics: no credit-bound assert and no at-exit
    # strictness (the exhaustive checker owns boundedness/leaks; a
    # mutated stream under this entry point must keep failing with the
    # overwrite/deadlock wording its callers match on)
    model.credit_bound = len(ops)
    model.strict_terminal = False
    return _mc.run_random(model, seed=seed, max_events=max_events)


def flow_control_selftest(n: int = 8, *, streaming: bool = False,
                          rng_seed: int = 0) -> None:
    """The REAL credit protocol at ring size n under the threaded TPU
    interpreter, with the codec ablated away (ablate="rdma": tiny VPU
    work, full barrier + credit + RDMA path) — the convoy-beating shape
    the round-5 diagnosis prescribed: one (16,128)-tile slice per chunk
    keeps every interpreter buffer-init copy small, so the 1-core
    allocation convoy that parked n=8 for 500+ s never forms.  With
    encode/decode compiled out the accumulator is untouched, so the
    result is exact: each device returns its own input chunk.  Raises on
    deadlock (test timeout), data race (interpreter detector), or
    mismatch.  Needs pltpu.InterpretParams (see _interp_args)."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    cfg = BFPConfig()
    C = cfg.block_size * LANES            # one native tile per chunk
    L = n * C
    x = jnp.asarray(np.random.default_rng(rng_seed).standard_normal(
        (n, L)), jnp.float32)
    call = _rs_stream_call if streaming else _rs_call
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))

    def rs(v):
        v2 = v.astype(jnp.float32).reshape(-1, LANES)
        out = call(v2, "dp", cfg.block_size, cfg.mantissa_bits,
                   cfg.rounding, C, "threaded", 7, ablate="rdma")
        return out.reshape(-1)

    got = jax.jit(jax.shard_map(rs, mesh=mesh, in_specs=P("dp"),
                                out_specs=P("dp"),
                                check_vma=False))(x.reshape(-1))
    # ablate="rdma" never touches the accumulator: device i's owned chunk
    # is its own input rows [i*C, (i+1)*C) of the per-device vector
    want = np.stack([np.asarray(x[i, i * C:(i + 1) * C]) for i in range(n)])
    np.testing.assert_array_equal(np.asarray(got).reshape(n, C), want)


def _loopback_shmap(fn, arg):
    """Run a self-addressed kernel call under a 1-device shard_map — the
    LOGICAL device-id space needs a mesh axis to resolve against, even
    for self-addressed copies."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec
    mesh = Mesh(np.array(jax.devices()[:1]), ("lb",))
    return jax.shard_map(fn, mesh=mesh, in_specs=PartitionSpec(),
                         out_specs=PartitionSpec(), check_vma=False)(arg)


def loopback_microbench(x: jax.Array, virtual_n: int = 4, *,
                        compression: Optional[BFPConfig] = None,
                        slice_elems: int = 8192,
                        streaming: bool = False,
                        interpret: Optional[bool] = None,
                        pipeline_depth: Optional[int] = None,
                        ablate: Optional[str] = None) -> jax.Array:
    """Single-chip exercise of the fused reduce-scatter pipeline: the same
    kernel with every RDMA addressed to this device (virtual ring of
    `virtual_n`); streaming=True runs the HBM-streaming variant.

    The numerics are a self-accumulation (not a real reduce-scatter), but
    the DATAFLOW — encode slice g+1 on the VPU while slice g's DMA is in
    flight, decode+accumulate on arrival, credit flow control — is
    identical, so its sustained GB/s bounds the compressed ring's per-hop
    rate on real multi-chip ICI (where the DMA engine drives the
    interconnect instead of a local loopback).  This exists because the
    bench surface has ONE chip (BASELINE.md); the multi-chip bit-exactness
    story runs on the CPU interpreter (tests/test_ring_pallas.py).
    """
    cfg = compression or BFPConfig()
    if interpret is None:
        interpret = not _is_tpu()
    L = x.shape[0]
    assert L % virtual_n == 0, (L, virtual_n)
    C = L // virtual_n
    if C % slice_elems or slice_elems % (cfg.block_size * LANES):
        raise ValueError((C, slice_elems, cfg.block_size * LANES))
    x2 = x.astype(jnp.float32).reshape(-1, LANES)
    if ablate == "hbm" and not streaming:
        raise ValueError("'hbm' ablates the streaming kernel's slice "
                         "load/store stages; the resident kernel has none")
    call = _rs_stream_call if streaming else _rs_call
    out = _loopback_shmap(
        lambda v: call(v, None, cfg.block_size, cfg.mantissa_bits,
                       cfg.rounding, slice_elems, interpret, 7,
                       loopback_n=virtual_n, ablate=ablate,
                       depth=pipeline_depth), x2)
    return out.reshape(C)


def loopback_update_microbench(x: jax.Array, virtual_n: int = 4, *,
                               opt_kind: str = "adamw",
                               hyper: Optional[jax.Array] = None,
                               compression: Optional[BFPConfig] = None,
                               slice_elems: int = 8192,
                               streaming: bool = False,
                               interpret: Optional[bool] = None,
                               pipeline_depth: Optional[int] = None,
                               ablate: Optional[str] = None) -> jax.Array:
    """Single-chip exercise of the fused reduce-scatter + IN-KERNEL
    optimizer pipeline (`loopback_microbench` with opt_kind): the same
    self-addressed virtual ring, plus chunk-sized master/state shards
    updated on the final-hop decodes.  Returns the updated w chunk
    (consuming any output runs the whole opaque kernel, so O(1)
    consumption is exact for slope timing).  ablate adds "update" — the
    optimizer stage alone on the same schedule — feeding ring_cost's
    fused-optimizer decomposition."""
    cfg = compression or BFPConfig()
    spec = OptimizerSpec(kind=opt_kind)
    if interpret is None:
        interpret = not _is_tpu()
    L = x.shape[0]
    assert L % virtual_n == 0, (L, virtual_n)
    C = L // virtual_n
    if C % slice_elems or slice_elems % (cfg.block_size * LANES):
        raise ValueError((C, slice_elems, cfg.block_size * LANES))
    if hyper is None:
        from ..utils.config import OptimizerConfig
        hyper = _optim.fused_hyperparams(
            OptimizerConfig(kind=opt_kind, learning_rate=1e-3),
            jnp.zeros((), jnp.int32))
    x2 = x.astype(jnp.float32).reshape(-1, LANES)
    w2 = jnp.zeros((C // LANES, LANES), jnp.float32)
    st = tuple(jnp.zeros((C // LANES, LANES), jnp.float32)
               for _ in spec.state_keys)
    call = _rs_stream_call if streaming else _rs_call
    if ablate == "hbm" and not streaming:
        raise ValueError("'hbm' ablates the streaming kernel's slice "
                         "load/store stages; the resident kernel has none")

    def run(v):
        res = call(v, None, cfg.block_size, cfg.mantissa_bits,
                   cfg.rounding, slice_elems, interpret, 9,
                   loopback_n=virtual_n, ablate=ablate,
                   depth=pipeline_depth, opt_kind=opt_kind,
                   w2=w2, opt_st=st, hyper=hyper)
        return res[1]
    return _loopback_shmap(run, x2).reshape(C)


def loopback_gather_microbench(owned: jax.Array, virtual_n: int = 4, *,
                               compression: Optional[BFPConfig] = None,
                               slice_elems: int = 8192,
                               streaming: bool = False,
                               interpret: Optional[bool] = None) -> jax.Array:
    """Single-chip exercise of the fused all-gather pipeline (resident or
    streaming), self-addressed like `loopback_microbench` — on one chip a
    node's arrival stream is its own emission stream, so the interleaved
    schedule, slot window, credits, and the encode/forward/decode overlap
    all execute exactly as on a real ring.  Output is [virtual_n * C]
    (deterministic; not a real gather)."""
    cfg = compression or BFPConfig()
    if interpret is None:
        interpret = not _is_tpu()
    C = owned.shape[0]
    if C % slice_elems or slice_elems % (cfg.block_size * LANES):
        raise ValueError((C, slice_elems, cfg.block_size * LANES))
    x2 = owned.astype(jnp.float32).reshape(-1, LANES)
    if streaming:
        out = _loopback_shmap(
            lambda v: _ag_stream_call(v, None, cfg.block_size,
                                      cfg.mantissa_bits, cfg.rounding,
                                      slice_elems, interpret, 8,
                                      loopback_n=virtual_n), x2)
    else:
        out = _loopback_shmap(
            lambda v: _ag_call(v, None, cfg.block_size, cfg.mantissa_bits,
                               cfg.rounding, interpret, 8,
                               loopback_n=virtual_n), x2)
    return out.reshape(virtual_n * C)
