"""Fused BFP-compressed ring reduce-scatter — ONE Pallas kernel.

The reference's bfp_adapter sits *inside* the wire datapath: the engine
streams 512b groups through compress -> Ethernet -> decompress without ever
materializing the compressed frame in host-visible memory
(hw/bfp_adapter.sv:33-741 between hw/all_reduce.sv's engine and the IKL
shell).  `ops.ring` approximates that with separate XLA ops (encode /
ppermute / decode) and leaves the overlap to XLA's scheduler; THIS module
is the real analogue: a single kernel that, per 32 KiB-class slice,

    encodes slice g+1 into a send buffer        (VPU compute)
  while
    slice g's RDMA is in flight on the ICI      (DMA engine)
  then
    decodes + accumulates the received slice    (VPU compute)

double-buffered over two comm slots with explicit credit-based flow
control — the same producer/consumer discipline the reference implements
with its dual-clock FIFOs and valid/ready handshakes (hw/fifo.v,
hw/bfp_adapter.sv:57-98).

Wire format: one int8 frame per slice packing `R` mantissa rows followed
by `R/B` shared-exponent rows (B = block_size) — the live rows carry the
reference's exact 17-flit rate (16 mantissa flits : 1 exponent flit,
hw/bfp_adapter.sv:30,63-77), and the RDMA'd frame rounds up to the int8
8-row tile (_frame_rows; 72/68 of the live bytes at the default R=64
plan).  One RDMA moves the whole compressed slice.

Numerics are bit-identical to `ops.ring.ring_reduce_scatter` with
codec="pallas" and the same slice_elems (same add order, same per-hop
lane-layout quantization): slicing and fusion change the schedule, never
the bits (tests/test_ring_pallas.py enforces this on the CPU interpreter).

Residency: two reduce-scatter kernels share the schedule.  The
VMEM-resident one holds the whole per-device vector on-chip (fastest for
payloads up to a few MiB); `_rs_stream_kernel` keeps the vector in HBM
(aliased with the input) and streams two slices of working f32 through
VMEM with load/writeback DMAs — the reference's memory shape exactly:
arbitrarily long vectors through a fixed 32 KiB-class working set
(hw/all_reduce.sv:101-103,246-253).  `ring_reduce_scatter_fused` picks by
payload size; both are bit-identical.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import compat

from .bfp_pallas import LANES, _is_tpu
from ..utils.config import BFPConfig


def _encode_rows(x, block_size: int, mantissa_bits: int, rounding: str):
    """(R, 128) f32 -> ((R, 128) int8 mantissas, (R/B, 128) int8 scales).
    Register-level port of bfp_pallas._encode_kernel (the bit spec is
    bfp_golden layout="sublane"; hw/bf16_to_bfp_core.sv:30-132)."""
    R = x.shape[0]
    T = R // block_size
    bits = pltpu.bitcast(x, jnp.uint32)
    e = jnp.right_shift(bits, 23).astype(jnp.int32) & 0xFF
    emax = jnp.max(e.reshape(T, block_size, LANES), axis=1)
    scale_e = jnp.clip(emax - 127 - (mantissa_bits - 2), -126, 126)
    inv = pltpu.bitcast(((127 - scale_e) << 23).astype(jnp.uint32),
                        jnp.float32)                 # 2.0**-scale_e, exact
    q = x * jnp.repeat(inv, block_size, axis=0)
    q = jnp.round(q) if rounding == "nearest" else jnp.trunc(q)
    lim = float(2 ** (mantissa_bits - 1) - 1)
    return (jnp.clip(q, -lim, lim).astype(jnp.int8),
            scale_e.astype(jnp.int8))


def _decode_rows(mant, scale, block_size: int):
    """Inverse of _encode_rows (hw/bfp_to_bf16_core.sv:30-125)."""
    se = scale.astype(jnp.int32)
    s = pltpu.bitcast(((se + 127) << 23).astype(jnp.uint32), jnp.float32)
    return mant.astype(jnp.float32) * jnp.repeat(s, block_size, axis=0)


# the threaded per-device TPU interpreter (blocking semaphores, race
# detection) arrived after this container's jaxlib — under its original
# TPUInterpretParams name on older releases that do ship it; the
# flow-control battery skips without it (the discharge interpreter
# still runs)
_InterpretParams = getattr(pltpu, "InterpretParams",
                           getattr(pltpu, "TPUInterpretParams", None))
HAS_THREADED_INTERPRET = _InterpretParams is not None

_FRAME_ALIGN = 8     # int8 VMEM sublane tile: DMA slice row extents align


def _frame_rows(R: int, block_size: int) -> int:
    """Rows of one RDMA'd wire frame: R mantissa rows + R/B scale rows,
    padded up to the int8 (8,128) sublane tile — the Mosaic compiler
    rejects DMA slices whose row extent is not tile-aligned (first
    hardware contact, v5e: "Slice shape along dimension 1 must be aligned
    to tiling (8), but is 17").  Pad rows ride the wire but are never
    written or decoded; at the default slice plan (R=64, B=16: 68 -> 72
    rows) the overhead is 5.9%, and the live rows keep the reference's
    exact 16:1 mantissa:exponent rate (hw/bfp_adapter.sv:30,63-77)."""
    live = R + R // block_size
    return -(-live // _FRAME_ALIGN) * _FRAME_ALIGN


def _neighbor_barrier(left, right):
    """All ring members must have entered the kernel before the first RDMA
    lands in a neighbor's scratch (the analogue of ikl_setup's reset
    barrier, sw/mlp_mpi_example_f32.cpp:50-63)."""
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)


def _interp_args(interpret):
    """Map the public tri-state ``interpret`` flag to (pallas interpret
    argument, flow_control, unrolled).

    False       hardware: compiled kernel, rolled schedule, flow control ON
    True        discharge interpreter (fast lockstep emulation; copies
                materialize at dma_start in SPMD program order): flow
                control OFF — it cannot execute remote semaphore signals —
                and safety rests on the static schedule's program-order
                properties (_ag_schedule P1/P2)
    "threaded"  pltpu.InterpretParams: one thread per device, BLOCKING
                semaphores, remote signals, race detection — the real
                flow-control protocol (neighbor barrier + credit window)
                executes end-to-end; a protocol deadlock hangs the test
                and a data race is reported by the interpreter.  This is
                the strongest off-hardware evidence the credit protocol
                admits (tests/test_ring_pallas.py::TestFlowControl).
    """
    if interpret == "threaded":
        if not HAS_THREADED_INTERPRET:
            raise NotImplementedError(
                "interpret='threaded' needs pltpu.InterpretParams (or the "
                "older TPUInterpretParams — the threaded TPU interpreter), "
                "which this jaxlib does not ship — run the flow-control "
                "battery on a newer JAX, or use interpret=True for the "
                "discharge interpreter")
        return _InterpretParams(detect_races=True), True, True
    return bool(interpret), not interpret, bool(interpret)


def _when(cond, static: bool):
    """pl.when for the rolled (compiled) schedule; a plain python ``if``
    for the statically-unrolled schedule the interpreter runs — the
    vma-checked interpreter rejects lax.cond branch joins inside kernels
    (invariant vs varying branch outputs), and every schedule decision is
    a static counter comparison anyway."""
    if static:
        def deco(f):
            if cond:
                f()
        return deco
    return pl.when(cond)


def _rs_kernel(ids_ref, x_ref, out_ref, acc, send_pkt, recv_pkt, send_sem,
               recv_sem, credit_sem, *, n: int, n_slices: int,
               slice_rows: int, block_size: int, mantissa_bits: int,
               rounding: str, flow_control: bool, unrolled: bool,
               ablate: Optional[str] = None):
    """The whole sliced ring reduce-scatter, one kernel invocation.

    ids_ref:   SMEM [3] int32 — (my index, right neighbor, left neighbor),
               computed OUTSIDE the kernel: in-kernel axis_index arithmetic
               trips vma typing under the checked interpreter, and the ring
               position is launch-time data anyway
    acc:       (L_rows, 128) f32 — running partials (starts as x)
    send_pkt:  (2, R + R/B, 128) int8 — packed frames, double-buffered
    recv_pkt:  (2, R + R/B, 128) int8
    send/recv_sem: DMA (2,) — one per comm slot
    credit_sem: REGULAR — downstream-consumed-slot credits (flow control)

    ablate (STAGE-ATTRIBUTION ONLY, compile-time): None runs the full
    pipeline; "encode" / "rdma" / "decode" run exactly one stage of the
    same schedule (the other stages compile away), so timing the four
    variants answers which stage binds the pipelined hop — the per-stage
    breakdown the round-4 verdict ordered for the loopback microbench
    (the reference reads the same split from its stall counters,
    hw/all_reduce.sv:94-97).  Ablated outputs are garbage by design:
    "rdma" sends whatever is in the frames, "decode" decodes stale
    frames — timing is data-independent on the VPU/DMA so rates are
    unaffected.  Loopback/bench use only; never a collective."""
    assert ablate in (None, "encode", "rdma", "decode"), ablate
    do_enc = ablate in (None, "encode")
    do_rdma = ablate in (None, "rdma")
    do_dec = ablate in (None, "decode")
    idx = ids_ref[0]
    right = ids_ref[1]               # we send downstream (IKL ring order,
    left = ids_ref[2]                # sw/setup_route.sh:12-40)
    S = n_slices
    R = slice_rows
    SB = R // block_size             # scale rows per slice
    chunk_rows = S * R
    total = (n - 1) * S              # global send/consume count

    acc[:] = x_ref[:]

    def rdma(g):
        slot = g % 2
        return pltpu.make_async_remote_copy(
            src_ref=send_pkt.at[slot], dst_ref=recv_pkt.at[slot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)

    def encode_to_slot(g):
        s, k = g // S, g % S
        chunk = (idx - s - 1) % n    # hop s sends partial chunk idx-s-1
        off = chunk * chunk_rows + k * R
        mant, scale = _encode_rows(acc[pl.ds(off, R)], block_size,
                                   mantissa_bits, rounding)
        slot = g % 2
        send_pkt[slot, pl.ds(0, R)] = mant
        send_pkt[slot, pl.ds(R, SB)] = scale

    # flow_control=False only under the discharge interpreter, whose
    # lockstep emulation cannot execute remote semaphore signals; the
    # threaded interpreter (interpret="threaded") and hardware both run
    # the barrier + credits for real (see _interp_args).
    if flow_control and do_rdma:
        _neighbor_barrier(left, right)

    # prologue: slice 0 has no in-flight RDMA to overlap with
    if do_enc:
        encode_to_slot(0)
    if do_rdma:
        rdma(0).start()

    def launch(q):
        # launch send q while RDMA q-1 is in flight — the encode/wire
        # overlap the reference gets by pipelining compress into the
        # egress path
        @_when(q < total, unrolled)
        def _launch():
            if do_rdma:
                @_when(q >= 2, unrolled)
                def _reuse():               # slot q%2 was used by RDMA
                    rdma(q - 2).wait_send()  # q-2: source must be drained
            if do_enc:
                encode_to_slot(q)

            if flow_control and do_rdma:
                @_when(q >= 2, unrolled)
                def _credit():            # destination slot safety: the
                    pltpu.semaphore_wait(credit_sem, 1)  # recvr freed q-2
            if do_rdma:
                rdma(q).start()

    def consume(g):
        # decode slice g + accumulate into the chunk this hop owns
        if do_rdma:
            rdma(g).wait_recv()
        if do_dec:
            s, k = g // S, g % S
            slot = g % 2
            chunk = (idx - s - 2) % n
            off = chunk * chunk_rows + k * R
            dec = _decode_rows(recv_pkt[slot, pl.ds(0, R)],
                               recv_pkt[slot, pl.ds(R, SB)], block_size)
            acc[pl.ds(off, R)] = acc[pl.ds(off, R)] + dec
        if flow_control and do_rdma:
            # free the slot for our upstream sender
            pltpu.semaphore_signal(credit_sem, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)

    # Send q's source chunk is finalized by consume q-S (hop s reads what
    # hop s-1 accumulated into the same slice index).  With S >= 2 slices
    # per chunk the launch-ahead at iteration g = q-1 is safe (q-S <= g-1
    # already consumed) and buys the encode/RDMA overlap; at S == 1 the
    # dependency is the CURRENT iteration's consume, so order flips —
    # single-slice hops cannot pipeline across the hop boundary (the
    # reference has the same serialization: a slice is forwarded only
    # after it is reduced, hw/all_reduce.sv REDUCE->FORWARD).
    if S >= 2:
        def step(g):
            launch(g + 1)
            consume(g)
    else:
        def step(g):
            consume(g)
            launch(g + 1)

    if unrolled:
        # static schedule (the interpreter path): every counter decision
        # is a python bool, no lax.cond joins for the vma checker to fight
        for g in range(total):
            step(g)
    else:
        def body(g, _):
            step(g)
            return 0
        lax.fori_loop(0, total, body, 0)

    # drain: the last two sends' source-buffer semaphores, and the two
    # residual credits our receiver signaled but no later send consumed
    if do_rdma:
        rdma(total - 1).wait_send()
        if total >= 2:
            rdma(total - 2).wait_send()
        if flow_control:
            pltpu.semaphore_wait(credit_sem, 2 if total >= 2 else 1)

    out_ref[:] = acc[pl.ds(idx * chunk_rows, chunk_rows)]


def _ring_ids(axis_name: Optional[str]) -> jax.Array:
    """[my, right, left] int32 — ring coordinates as kernel data; all-self
    when axis_name is None (single-chip loopback mode).

    The values feed make_async_remote_copy's LOGICAL device id, which is
    the FLAT index into the whole mesh — equal to the ring-axis index only
    when every other manual axis has extent 1.  Guard that here at trace
    time: a silent mismatch would RDMA to the wrong chip."""
    if axis_name is None:
        return jnp.zeros((3,), jnp.int32)
    sizes = compat.mesh_axis_sizes()
    other = {a: s for a, s in sizes.items()
             if a != axis_name and s != 1}
    if other:
        raise ValueError(
            f"fused ring collectives need '{axis_name}' to be the only "
            f"nontrivial mesh axis (LOGICAL RDMA ids are flat mesh "
            f"indices); other axes with extent > 1: {other} — use the "
            f"XLA-op ring (ops.ring) on multi-axis meshes")
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    return jnp.stack([idx, (idx + 1) % n, (idx - 1) % n]).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=(
    "axis_name", "block_size", "mantissa_bits", "rounding", "slice_elems",
    "interpret", "collective_id", "loopback_n", "ablate"))
def _rs_call(x2, axis_name: Optional[str], block_size: int,
             mantissa_bits: int, rounding: str, slice_elems: int,
             interpret: bool, collective_id: int,
             loopback_n: Optional[int] = None,
             ablate: Optional[str] = None):
    n = loopback_n if axis_name is None else lax.axis_size(axis_name)
    L_rows = x2.shape[0]
    chunk_rows = L_rows // n
    R = slice_elems // LANES
    S = chunk_rows // R
    pkt_rows = _frame_rows(R, block_size)
    ids = _ring_ids(axis_name)
    _interp, _flow, _unrolled = _interp_args(interpret)
    kern = functools.partial(
        _rs_kernel, n=n, n_slices=S, slice_rows=R,
        block_size=block_size, mantissa_bits=mantissa_bits,
        rounding=rounding, flow_control=_flow, unrolled=_unrolled,
        ablate=ablate)
    vma = jax.typeof(x2).vma | jax.typeof(ids).vma
    return pl.pallas_call(
        kern,
        out_shape=compat.shape_dtype_struct((chunk_rows, LANES), jnp.float32,
                                       vma=vma),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((L_rows, LANES), jnp.float32),      # acc
            pltpu.VMEM((2, pkt_rows, LANES), jnp.int8),    # send frames
            pltpu.VMEM((2, pkt_rows, LANES), jnp.int8),    # recv frames
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=compat.tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp,
    )(ids, x2)


# above this per-device payload, the whole-vector VMEM-resident kernel
# (input + acc copies) stops fitting on-chip; the streaming kernel keeps
# only two slices + frames in VMEM
_VMEM_RESIDENT_MAX_BYTES = 4 << 20


def ring_reduce_scatter_fused(x: jax.Array, axis_name: str, *,
                              compression: Optional[BFPConfig] = None,
                              slice_elems: int = 8192,
                              streaming: Optional[bool] = None,
                              interpret: Optional[bool] = None,
                              collective_id: int = 7) -> jax.Array:
    """Fused compress-into-hop ring reduce-scatter of a flat f32 [L].

    Drop-in for `ops.ring.ring_reduce_scatter(..., codec="pallas")` where
    the payload meets the tiling constraints below; bit-identical result.

    streaming=None picks by size: payloads over ~4 MiB/device stream
    HBM->VMEM slice by slice (the vector never lives on-chip, matching
    the reference's fixed 32 KiB working set over arbitrarily long
    vectors); smaller payloads use the VMEM-resident kernel.  Both are
    bit-identical — the choice is residency, not numerics.

    Constraints (assert, don't silently repartition — changing the block
    partition would change the bits):
      - L % n == 0, chunk C = L/n
      - C % slice_elems == 0, slice_elems % (block_size * 128) == 0
    """
    cfg = compression or BFPConfig()
    n = lax.axis_size(axis_name)
    L = x.shape[0]
    if interpret is None:
        interpret = not _is_tpu()
    assert L % n == 0, (L, n)
    C = L // n
    if C % slice_elems or slice_elems % (cfg.block_size * LANES):
        raise ValueError(
            f"fused ring needs chunk {C} % slice_elems {slice_elems} == 0 "
            f"and slice_elems % {cfg.block_size * LANES} == 0")
    if n == 1:
        return x
    if streaming is None:
        streaming = L * 4 > _VMEM_RESIDENT_MAX_BYTES
    x2 = x.astype(jnp.float32).reshape(-1, LANES)
    if streaming:
        out = _rs_stream_call(x2, axis_name, cfg.block_size,
                              cfg.mantissa_bits, cfg.rounding, slice_elems,
                              interpret, collective_id)
    else:
        out = _rs_call(x2, axis_name, cfg.block_size, cfg.mantissa_bits,
                       cfg.rounding, slice_elems, interpret, collective_id)
    return out.reshape(C)


def _rs_stream_kernel(ids_ref, x_hbm, acc, ld, st, send_pkt, recv_pkt,
                      ld_sem, st_ld_sem, wb_sem, send_sem, recv_sem,
                      credit_sem, *, n: int, n_slices: int, slice_rows: int,
                      block_size: int, mantissa_bits: int, rounding: str,
                      flow_control: bool, unrolled: bool,
                      ablate: Optional[str] = None):
    """HBM-streaming variant of _rs_kernel: the vector stays in HBM (acc
    aliases the input buffer) and only two slices of working f32 plus the
    int8 frames live in VMEM — the reference's exact memory shape, which
    streams arbitrarily long vectors through fixed 32 KiB slices and a
    handful of FIFOs (hw/all_reduce.sv:101-103,246-253) instead of
    buffering the vector on-chip.  Slice loads, accumulate-writebacks, the
    codec, and the RDMA all overlap through per-slot DMA semaphores; the
    cross-hop RAW hazard (hop s sends what hop s-1 wrote back) is guarded
    by waiting writeback q-S before the send-side load of q.

    del x_hbm: the aliased acc ref IS the input buffer.
    """
    del x_hbm
    # Stage ablation (loopback attribution only — see _rs_kernel): each
    # variant keeps exactly one pipeline resource class of the SAME
    # schedule: "hbm" = slice load + store-load + writeback streaming,
    # "encode" = load + codec-in, "rdma" = the wire chain alone,
    # "decode" = store-load + codec-out+add + writeback.
    assert ablate in (None, "encode", "rdma", "decode", "hbm"), ablate
    do_ld = ablate in (None, "encode", "hbm")
    do_enc = ablate in (None, "encode")
    do_rdma = ablate in (None, "rdma")
    do_stld = ablate in (None, "hbm", "decode")
    do_dec = ablate in (None, "decode")
    do_wb = ablate in (None, "hbm", "decode")
    idx = ids_ref[0]
    right = ids_ref[1]
    left = ids_ref[2]
    S = n_slices
    R = slice_rows
    SB = R // block_size
    chunk_rows = S * R
    total = (n - 1) * S

    def send_off(q):
        s, k = q // S, q % S
        return ((idx - s - 1) % n) * chunk_rows + k * R

    def recv_off(g):
        s, k = g // S, g % S
        return ((idx - s - 2) % n) * chunk_rows + k * R

    def ld_dma(q):
        return pltpu.make_async_copy(acc.at[pl.ds(send_off(q), R)],
                                     ld.at[q % 2], ld_sem.at[q % 2])

    def stld_dma(g):
        return pltpu.make_async_copy(acc.at[pl.ds(recv_off(g), R)],
                                     st.at[g % 2], st_ld_sem.at[g % 2])

    def wb_dma(g):
        return pltpu.make_async_copy(st.at[g % 2],
                                     acc.at[pl.ds(recv_off(g), R)],
                                     wb_sem.at[g % 2])

    def rdma(g):
        slot = g % 2
        return pltpu.make_async_remote_copy(
            src_ref=send_pkt.at[slot], dst_ref=recv_pkt.at[slot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)

    def encode_from_ld(q):
        mant, scale = _encode_rows(ld[q % 2], block_size, mantissa_bits,
                                   rounding)
        slot = q % 2
        send_pkt[slot, pl.ds(0, R)] = mant
        send_pkt[slot, pl.ds(R, SB)] = scale

    if flow_control and do_rdma:
        _neighbor_barrier(left, right)

    if do_ld:
        ld_dma(0).start()
        ld_dma(0).wait()
    if do_enc:
        encode_from_ld(0)
    if do_rdma:
        rdma(0).start()

    def launch(q):
        @_when(q < total, unrolled)
        def _launch():
            if do_ld:
                ld_dma(q).start()
            if do_rdma:
                @_when(q >= 2, unrolled)
                def _reuse():
                    rdma(q - 2).wait_send()    # frame slot q%2 drained
            if do_ld:
                ld_dma(q).wait()
            if do_enc:
                encode_from_ld(q)
            if flow_control and do_rdma:
                @_when(q >= 2, unrolled)
                def _credit():
                    pltpu.semaphore_wait(credit_sem, 1)
            if do_rdma:
                rdma(q).start()

    def consume(g):
        if do_stld:
            stld_dma(g).start()            # overlap load with the wire
        if do_rdma:
            rdma(g).wait_recv()
        if do_stld:
            stld_dma(g).wait()
        if do_dec:
            slot = g % 2
            dec = _decode_rows(recv_pkt[slot, pl.ds(0, R)],
                               recv_pkt[slot, pl.ds(R, SB)], block_size)
            st[slot] = st[slot] + dec
        if flow_control and do_rdma:
            pltpu.semaphore_signal(credit_sem, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
        if do_wb:
            wb_dma(g).start()

    # Writeback discipline: each wb_dma is waited EXACTLY ONCE, at a point
    # that dominates both of its consumers — the send-side RAW (launch q
    # reads what wb q-S wrote) and the st-slot reuse (stld g overwrites
    # what wb g-2 drained).  Two independent waits on one DMA signal would
    # deadlock on hardware (one signal per DMA), invisibly to the
    # interpreter (which does not block on semaphore counts).
    if S == 1:
        def step(g):                       # RAW is immediate at S=1: the
            consume(g)                     # next send reads THIS writeback
            if do_wb:
                wb_dma(g).wait()
            launch(g + 1)
    else:
        def step(g):
            if do_wb:
                @_when(g >= 1, unrolled)
                def _wb_prev():            # single wait, 1-iteration lag:
                    wb_dma(g - 1).wait()   # every wb <= g-1 complete here,
            launch(g + 1)                  # dominating RAW (q-S <= g-1 for
            consume(g)                     # S >= 2) and slot reuse (g-2)

    if unrolled:
        for g in range(total):
            step(g)
    else:
        def body(g, _):
            step(g)
            return 0
        lax.fori_loop(0, total, body, 0)

    if do_wb and S >= 2:
        wb_dma(total - 1).wait()           # S=1 waits each wb in-loop
    if do_rdma:
        rdma(total - 1).wait_send()
        if total >= 2:
            rdma(total - 2).wait_send()
        if flow_control:
            pltpu.semaphore_wait(credit_sem, 2 if total >= 2 else 1)


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=(
    "axis_name", "block_size", "mantissa_bits", "rounding", "slice_elems",
    "interpret", "collective_id", "loopback_n", "ablate"))
def _rs_stream_call(x2, axis_name: Optional[str], block_size: int,
                    mantissa_bits: int, rounding: str, slice_elems: int,
                    interpret: bool, collective_id: int,
                    loopback_n: Optional[int] = None,
                    ablate: Optional[str] = None):
    n = loopback_n if axis_name is None else lax.axis_size(axis_name)
    L_rows = x2.shape[0]
    chunk_rows = L_rows // n
    R = slice_elems // LANES
    S = chunk_rows // R
    pkt_rows = _frame_rows(R, block_size)
    ids = _ring_ids(axis_name)
    _interp, _flow, _unrolled = _interp_args(interpret)
    kern = functools.partial(
        _rs_stream_kernel, n=n, n_slices=S, slice_rows=R,
        block_size=block_size, mantissa_bits=mantissa_bits,
        rounding=rounding, flow_control=_flow, unrolled=_unrolled,
        ablate=ablate)
    vma = jax.typeof(x2).vma | jax.typeof(ids).vma
    acc = pl.pallas_call(
        kern,
        out_shape=compat.shape_dtype_struct((L_rows, LANES), jnp.float32,
                                       vma=vma),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        input_output_aliases={1: 0},
        scratch_shapes=[
            pltpu.VMEM((2, R, LANES), jnp.float32),        # send loads
            pltpu.VMEM((2, R, LANES), jnp.float32),        # recv acc
            pltpu.VMEM((2, pkt_rows, LANES), jnp.int8),    # send frames
            pltpu.VMEM((2, pkt_rows, LANES), jnp.int8),    # recv frames
            pltpu.SemaphoreType.DMA((2,)),                 # ld
            pltpu.SemaphoreType.DMA((2,)),                 # st load
            pltpu.SemaphoreType.DMA((2,)),                 # writeback
            pltpu.SemaphoreType.DMA((2,)),                 # rdma send
            pltpu.SemaphoreType.DMA((2,)),                 # rdma recv
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=compat.tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp,
    )(ids, x2)
    # the owned chunk lives at rows [idx*chunk_rows, +chunk_rows) of the
    # accumulated (aliased) vector
    idx = jnp.int32(0) if axis_name is None else lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(acc, idx * chunk_rows, chunk_rows,
                                    axis=0)


def _ag_kernel(ids_ref, own_ref, out_ref, send_pkt, recv_pkt, send_sem,
               recv_sem, credit_sem, *, n: int, block_size: int,
               mantissa_bits: int, rounding: str, flow_control: bool,
               unrolled: bool):
    """Fused compressed ring all-gather: encode the owned chunk ONCE, then
    forward the received frame VERBATIM each hop (BFP roundtrip is
    idempotent, so every replica sees identical bytes — the semantics of
    ops.ring.ring_all_gather and the golden model), decoding each arrival
    while its onward RDMA is in flight.  This is the phase that
    distributes updated weights in the fused collective
    (hw/all_reduce.sv FORWARD_OUTPUT/OUTPUT_SEND:996-1086)."""
    idx = ids_ref[0]
    right = ids_ref[1]
    left = ids_ref[2]
    R = own_ref.shape[0]             # chunk rows
    SB = R // block_size

    def rdma(s, src):
        slot = s % 2
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=recv_pkt.at[slot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)

    if flow_control:
        _neighbor_barrier(left, right)

    mant, scale = _encode_rows(own_ref[:], block_size, mantissa_bits,
                               rounding)
    send_pkt[pl.ds(0, R)] = mant
    send_pkt[pl.ds(R, SB)] = scale
    # the local replica stores the same quantized values it sends
    out_ref[pl.ds(idx * R, R)] = _decode_rows(mant, scale, block_size)
    rdma(0, send_pkt).start()

    def hop(s):
        p = (s - 1) % 2
        rdma(s - 1, send_pkt).wait_recv()     # frame s-1 has landed

        @_when(s < n - 1, unrolled)
        def _forward():
            @_when(s == 2, unrolled)
            def _initial_send_drained():
                # forward hop 2 reuses send_sem[0], which the INITIAL
                # owned-chunk RDMA signaled; without this wait the later
                # _done_fwd could consume that stale signal and credit the
                # slot while the forward is still reading it (every other
                # same-slot predecessor is a forward already waited in its
                # own _done_fwd)
                rdma(0, send_pkt).wait_send()
            if flow_control:
                @_when(s >= 2, unrolled)
                def _credit():                # remote slot s%2 freed?
                    pltpu.semaphore_wait(credit_sem, 1)
            rdma(s, recv_pkt.at[p]).start()

        # decode while the forward RDMA is on the wire
        chunk = (idx - s) % n
        dec = _decode_rows(recv_pkt[p, pl.ds(0, R)],
                           recv_pkt[p, pl.ds(R, SB)], block_size)
        out_ref[pl.ds(chunk * R, R)] = dec
        @_when(s < n - 1, unrolled)
        def _done_fwd():
            # our recv slot p is the upstream's NEXT delivery target; it
            # must not be freed until the onward send has drained it
            rdma(s, recv_pkt.at[p]).wait_send()
        if flow_control:
            pltpu.semaphore_signal(credit_sem, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)

    if unrolled:
        for s in range(1, n):
            hop(s)
    else:
        def body(s, _):
            hop(s)
            return 0
        lax.fori_loop(1, n, body, 0)
    if n <= 3:
        # rings without a forward at hop 2 never consumed the initial
        # send's semaphore in _initial_send_drained — drain it here
        rdma(0, send_pkt).wait_send()
    if flow_control:
        pltpu.semaphore_wait(credit_sem, 2 if n > 2 else 1)


@functools.partial(jax.jit, static_argnames=(
    "axis_name", "block_size", "mantissa_bits", "rounding", "interpret",
    "collective_id", "loopback_n"))
def _ag_call(own2, axis_name: Optional[str], block_size: int,
             mantissa_bits: int, rounding: str, interpret: bool,
             collective_id: int, loopback_n: Optional[int] = None):
    n = loopback_n if axis_name is None else lax.axis_size(axis_name)
    R = own2.shape[0]
    pkt_rows = _frame_rows(R, block_size)
    ids = _ring_ids(axis_name)
    _interp, _flow, _unrolled = _interp_args(interpret)
    kern = functools.partial(
        _ag_kernel, n=n, block_size=block_size,
        mantissa_bits=mantissa_bits, rounding=rounding,
        flow_control=_flow, unrolled=_unrolled)
    vma = jax.typeof(own2).vma | jax.typeof(ids).vma
    return pl.pallas_call(
        kern,
        out_shape=compat.shape_dtype_struct((n * R, LANES), jnp.float32,
                                       vma=vma),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((pkt_rows, LANES), jnp.int8),       # own frame
            pltpu.VMEM((2, pkt_rows, LANES), jnp.int8),    # recv frames
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=compat.tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp,
    )(ids, own2)


def _ag_schedule(n: int, S: int, n_slots: int):
    """Explicit interleaved emission schedule for the streaming gather.

    Every node runs the SAME emission sequence E (the reference's
    SEND_LOCAL/FORWARD beat multiplexing, hw/all_reduce.sv:891-1086),
    built by simulating one node: per arrival step m, emit own slice m+1
    (while the own phase lasts) and forward arrival m onward unless its
    content is at the last hop.  Because arrivals ARE the upstream's
    emissions in E order, wire slots and semaphores cycle by EMISSION
    index j (mod n_slots on BOTH ends), and a node's m-th arrival has the
    content of E[m] one hop deeper.  Simple closed forms exist only for
    n >= 4 or S <= 2 (for n == 3, S >= 3 the terminal arrivals interleave
    non-contiguously and punch holes in any arithmetic j assignment), so
    the schedule is built explicitly — it is static per (n, S).

    Two properties are asserted here per (n, S) because the kernel's
    safety rests on them (verified by sweep for n<=16, S<=16, and
    re-checked statically on every trace):

      P1  m_e(m) < m: arrival m's emission is issued at a consume step
          STRICTLY before step m on the identical upstream program — so
          in the interpreter's lockstep-primitive model the data has
          landed before consume(m) decodes it, and on hardware wait_recv
          can always be satisfied.
      P2  j - m_e(j) <= S: no emission runs more than S ahead of its
          consume step (the own phase emits two frames per step for S-1
          steps, which is the worst case).  With n_slots >= S + 1, the
          overwrite of wire slot j % n_slots (emission j) therefore comes
          after the decode of arrival j - n_slots in program order
          (interpreter safety), and the credit window never dead-ends
          (hardware): emission j's credit waits on downstream consume
          j - n_slots <= m_e(j) - 1, a strictly earlier step, so every
          cross-node dependency edge points from (step m, node) to
          (step < m, neighbor) and the dependency graph is acyclic for
          ARBITRARY S and n.  n_slots = S + 2 adds one slot of margin.

    Returns (content[m], fwd_j[m], own_at[m], own_j[k], own_js,
    tail_own_js):
      content[m]   (chunk_depth_hops - 1) * S + slice of arrival m
      fwd_j[m]     emission index of arrival m's onward forward, -1 if
                   terminal (content at depth n-2)
      own_at[m]    own slice emitted AFTER consuming arrival m (-1 none)
      own_j[k]     emission index of own slice k
      own_js       set(own_j) — membership drives the pre-wait rule
      tail_own_js  own emissions never followed by a same-slot emission
                   (their send semaphores drain at kernel exit)
    """
    total = (n - 1) * S
    own_j = [0] * S
    content = [0] * total
    fwd_j = [-1] * total
    own_at = [-1] * total
    step_at = {0: -1}                   # emission index -> consume step
    j = 0

    def emit_own(k):
        nonlocal j
        own_j[k] = j
        j += 1

    emit_own(0)
    # arrival m's content: my arrival stream is the upstream's emission
    # stream; its k-th own is my depth-0 content (chunk idx-1, slice k),
    # and its forward of ITS arrival m' is my (content[m'] + one hop)
    emissions = [("own", 0)]            # E, in order

    for m in range(total):
        kind, val = emissions[m]
        content[m] = val if kind == "own" else content[val] + S
        if m + 1 < S:
            own_at[m] = m + 1
            step_at[j] = m
            emit_own(m + 1)
            emissions.append(("own", m + 1))
        if content[m] < (n - 2) * S:    # not yet at the last hop
            fwd_j[m] = j
            step_at[j] = m
            j += 1
            emissions.append(("fwd", m))
    assert j == total and len(emissions) == total, (j, len(emissions))
    assert sorted(content) == list(range(total))
    assert all(step_at[m] < m for m in range(total)), (n, S)        # P1
    assert all(jj - st <= S for jj, st in step_at.items()), (n, S)  # P2

    # single-wait bookkeeping for send semaphores: a forward's send is
    # waited at its own consume step; an own send is waited by the NEXT
    # same-slot emission's pre-wait iff that emission exists AND the
    # preceding same-slot emission was an own (forwards self-wait)
    own_js = set(own_j)
    tail_own_js = [oj for oj in own_j
                   if oj + n_slots >= total]   # no same-slot successor
    return content, fwd_j, own_at, own_j, own_js, tail_own_js


def _ag_stream_kernel(ids_ref, sched_ref, own_hbm, out_hbm, ld, own_st, st,
                      send_pkt, recv_pkt, ld_sem, own_wb_sem, wb_sem,
                      send_sem, recv_sem, credit_sem, *, n: int,
                      n_slices: int, n_slots: int, slice_rows: int,
                      block_size: int, mantissa_bits: int, rounding: str,
                      flow_control: bool, unrolled: bool, schedule: tuple):
    """HBM-streaming fused ring all-gather, interleaved emission order.

    Loop index m = arrival order (== upstream's emission order; wire slots
    and semaphores cycle by emission index j % n_slots on BOTH ends).
    Per m: consume arrival content(m) — wait recv, start the onward
    forward (emission j_fwd), decode into a VMEM slice, write back to the
    out vector in HBM — then emit the next own-slice send if this content
    step schedules one.  Single-wait semaphore discipline:

      send j:  forwards wait their own send right before crediting the
               recv slot; own sends are waited by the next same-slot
               emitter (pre-wait when j - n_slots is an own),
               tail-drained statically.
      wb m:    one-iteration-lag head wait + final drain.
      own_wb:  guarded at own_st slot reuse + tail drain.
      credit:  wait one before any send with j >= n_slots; signal per
               consume.

    Slot window: n_slots = S + 2 (capped at total).  The own phase emits
    two frames per consume step, so an emission index can lead its step
    by up to S (_ag_schedule property P2); S + 2 covers the lead with one
    slot of margin, which makes slot reuse safe in BOTH execution
    models — the interpreter's lockstep program order (overwrite of slot
    j % n_slots comes after the decode of arrival j - n_slots) and
    hardware's credit window (emission j waits a credit its downstream
    released at consume j - n_slots, a strictly earlier step by P2, so
    the wait-for graph is acyclic for arbitrary S and n — the proof is
    in _ag_schedule's docstring).
    """
    idx = ids_ref[0]
    right = ids_ref[1]
    left = ids_ref[2]
    S = n_slices
    R = slice_rows
    SB = R // block_size
    chunk_rows = S * R
    total = (n - 1) * S                 # arrivals == emissions
    # the static schedule arrives twice: as python lists (compile-time —
    # drives the unrolled interpreter schedule and the static tail-drain
    # list) and as the sched_ref SMEM input (runtime — the rolled hardware
    # schedule reads it; in-kernel jnp table constants are rejected by the
    # Mosaic compiler: "kernel captures constants ... pass them as inputs")
    (content_t, fwd_j_t, own_at_t, own_j_t, own_js,
     tail_own_js) = schedule

    def wslot(x):
        return x % n_slots

    if unrolled:
        def content(m):
            return content_t[m]

        def fwd_j(m):
            return fwd_j_t[m]

        def own_at(m):
            return own_at_t[m]

        def own_j(k):
            return own_j_t[k]

        def is_own_j(j):
            return j >= 0 and j in own_js
    else:
        # static dispatch tables, one scalar SMEM load per schedule
        # decision (sched_ref rows: 0 content, 1 fwd_j, 2 own_at,
        # 3 own-mask, 4 own_j — built in _ag_stream_call)

        def content(m):
            return sched_ref[0, m]

        def fwd_j(m):
            return sched_ref[1, m]

        def own_at(m):
            return sched_ref[2, m]

        def own_j(k):
            return sched_ref[4, k]

        def is_own_j(j):
            return (j >= 0) & (sched_ref[3, jnp.clip(j, 0, total - 1)] == 1)

    def out_rdma(j, src):
        slot = wslot(j)
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=recv_pkt.at[slot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)

    def wait_send(j):
        # wait_send consumes emission j's send sem; frame shapes are
        # uniform, so any same-shape src is a valid descriptor
        out_rdma(j, send_pkt.at[wslot(j)]).wait_send()

    def ld_dma(k):
        return pltpu.make_async_copy(
            own_hbm.at[pl.ds(k * R, R)], ld.at[k % 2], ld_sem.at[k % 2])

    def own_wb_dma(k):
        return pltpu.make_async_copy(
            own_st.at[k % 2],
            out_hbm.at[pl.ds(idx * chunk_rows + k * R, R)],
            own_wb_sem.at[k % 2])

    def wb_dma(m):
        t = content(m)
        s, k = t // S + 1, t % S
        off = ((idx - s) % n) * chunk_rows + k * R
        return pltpu.make_async_copy(st.at[m % 2],
                                     out_hbm.at[pl.ds(off, R)],
                                     wb_sem.at[m % 2])

    if flow_control:
        _neighbor_barrier(left, right)

    def send_own(k):
        """Emit own slice k (emission own_j(k)): load, encode, locally
        decode (the replica stores its own wire bytes), send."""
        j = own_j(k)
        ld_dma(k).start()
        @_when(is_own_j(j - n_slots), unrolled)
        def _pre_wait():                  # previous same-slot emission was
            wait_send(j - n_slots)        # an own send (unwaited) AND its
                                          # frame lives in this buffer slot:
                                          # drain before overwriting below
        ld_dma(k).wait()
        mant, scale = _encode_rows(ld[k % 2], block_size, mantissa_bits,
                                   rounding)
        slot = wslot(j)
        send_pkt[slot, pl.ds(0, R)] = mant
        send_pkt[slot, pl.ds(R, SB)] = scale
        @_when(k >= 2, unrolled)
        def _own_slot():
            own_wb_dma(k - 2).wait()
        own_st[k % 2] = _decode_rows(mant, scale, block_size)
        own_wb_dma(k).start()
        if flow_control:
            @_when(j >= n_slots, unrolled)
            def _credit():
                pltpu.semaphore_wait(credit_sem, 1)
        out_rdma(j, send_pkt.at[slot]).start()

    def consume(m):
        @_when(m >= 1, unrolled)
        def _wb_prev():                   # 1-lag single wait: st slot
            wb_dma(m - 1).wait()          # reuse at m covers wb(m-2)
        slot = wslot(m)                   # arrival m's recv slot
        out_rdma(m, send_pkt.at[wslot(m)]).wait_recv()
        jf = fwd_j(m)                     # -1 when arrival m is terminal
        fwd = jf >= 0

        def start_forward():
            @_when(is_own_j(jf - n_slots), unrolled)
            def _pre_wait():
                wait_send(jf - n_slots)
            if flow_control:
                @_when(jf >= n_slots, unrolled)
                def _credit():
                    pltpu.semaphore_wait(credit_sem, 1)
            out_rdma(jf, recv_pkt.at[slot]).start()

        def decode_arrival():
            # dst slot is the LOCAL st pipeline's (depth 2, cycled by
            # arrival index, drained by wb_dma(m) which reads st[m % 2]);
            # only the SRC uses the wire slot — conflating the two was a
            # real out-of-bounds bug the moment the wire window grew past
            # the st depth
            st[m % 2] = _decode_rows(recv_pkt[slot, pl.ds(0, R)],
                                     recv_pkt[slot, pl.ds(R, SB)],
                                     block_size)

        if unrolled:
            # Interpreter primitive-lockstep hazard: a neighbor's emission
            # primitive in THIS step can land in my recv slot before my
            # decode primitive runs (the RS kernels are safe by a full
            # iteration of separation; the interleaved gather is not).
            # All reads first, then emissions — identical programs then
            # order every device's reads before any device's same-step
            # writes.  Hardware keeps forward-then-decode for overlap;
            # its slot occupancy is credit-protected.
            decode_arrival()
            @_when(fwd, unrolled)
            def _fwd_i():
                start_forward()
        else:
            @_when(fwd, unrolled)
            def _fwd_c():
                start_forward()
            decode_arrival()
        @_when(fwd, unrolled)
        def _fwd_done():                  # recv slot is upstream's next
            wait_send(jf)                 # target: drain my forward first
        if flow_control:
            pltpu.semaphore_signal(credit_sem, inc=1, device_id=left,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
        wb_dma(m).start()

    send_own(0)

    def step(m):
        consume(m)
        k = own_at(m)                     # next own-slice emission, if this
        @_when(k >= 0, unrolled)          # arrival step schedules one
        def _own():
            send_own(k)

    if unrolled:
        for m in range(total):
            step(m)
    else:
        def body(m, _):
            step(m)
            return 0
        lax.fori_loop(0, total, body, 0)

    wb_dma(total - 1).wait()
    own_wb_dma(S - 1).wait()
    if S >= 2:
        own_wb_dma(S - 2).wait()
    for jk in tail_own_js:                # own sends with no same-slot
        wait_send(jk)                     # successor (static list)
    if flow_control:
        # residual credits: consumes signal `total`, sends with
        # j >= n_slots consumed `total - n_slots` of them
        pltpu.semaphore_wait(credit_sem, min(total, n_slots))


@functools.partial(jax.jit, static_argnames=(
    "axis_name", "block_size", "mantissa_bits", "rounding", "slice_elems",
    "interpret", "collective_id", "loopback_n"))
def _ag_stream_call(own2, axis_name: Optional[str], block_size: int,
                    mantissa_bits: int, rounding: str, slice_elems: int,
                    interpret: bool, collective_id: int,
                    loopback_n: Optional[int] = None):
    n = loopback_n if axis_name is None else lax.axis_size(axis_name)
    C_rows = own2.shape[0]
    R = slice_elems // LANES
    S = C_rows // R
    pkt_rows = _frame_rows(R, block_size)
    ids = _ring_ids(axis_name)
    # slot window sized to the slice plan: covers the own phase's maximum
    # emission lead (== S, _ag_schedule P2) with one slot of margin
    n_slots = min((n - 1) * S, S + 2)
    _interp, _flow, _unrolled = _interp_args(interpret)
    schedule = _ag_schedule(n, S, n_slots)
    content_t, fwd_j_t, own_at_t, own_j_t, own_js, _tails = schedule
    total = (n - 1) * S
    # SMEM copy of the schedule for the rolled (hardware) path; rows:
    # content / fwd_j / own_at / own-mask / own_j (padded with -1)
    import numpy as np
    sched_np = np.full((5, total), -1, np.int32)
    sched_np[0] = content_t
    sched_np[1] = fwd_j_t
    sched_np[2] = own_at_t
    sched_np[3] = [1 if j in own_js else 0 for j in range(total)]
    sched_np[4, :S] = own_j_t
    sched = jnp.asarray(sched_np)
    kern = functools.partial(
        _ag_stream_kernel, n=n, n_slices=S, n_slots=n_slots, slice_rows=R,
        block_size=block_size, mantissa_bits=mantissa_bits,
        rounding=rounding, flow_control=_flow, unrolled=_unrolled,
        schedule=schedule)
    vma = jax.typeof(own2).vma | jax.typeof(ids).vma
    return pl.pallas_call(
        kern,
        out_shape=compat.shape_dtype_struct((n * C_rows, LANES), jnp.float32,
                                       vma=vma),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, R, LANES), jnp.float32),        # own loads
            pltpu.VMEM((2, R, LANES), jnp.float32),        # own decode
            pltpu.VMEM((2, R, LANES), jnp.float32),        # recv decode
            pltpu.VMEM((n_slots, pkt_rows, LANES), jnp.int8),  # own frames
            pltpu.VMEM((n_slots, pkt_rows, LANES), jnp.int8),  # recv frames
            pltpu.SemaphoreType.DMA((2,)),                 # ld
            pltpu.SemaphoreType.DMA((2,)),                 # own wb
            pltpu.SemaphoreType.DMA((2,)),                 # recv wb
            pltpu.SemaphoreType.DMA((n_slots,)),           # rdma send
            pltpu.SemaphoreType.DMA((n_slots,)),           # rdma recv
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=compat.tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp,
    )(ids, sched, own2)


# Frame VMEM for the streaming gather is ~2 * (S+2)/S * (FR/(R*4)) bytes
# per chunk f32 element (send + recv windows), where FR = _frame_rows(R, B)
# includes the 8-row tile padding — 72/68 of the live 17/16 rate at the
# default R=64 plan, but up to 24/17 (~1.4x) at R=16; the binding
# constraint is the CHUNK size.  Larger chunks are gathered in sequential
# segments of at most this many elements (each segment is an independent
# all-gather — BFP blocks never straddle a segment boundary).
_AG_STREAM_MAX_CHUNK_ELEMS = 2 << 20      # ~4.5 MiB frame VMEM per segment


def ring_all_gather_fused(owned: jax.Array, axis_name: str, *,
                          compression: Optional[BFPConfig] = None,
                          slice_elems: int = 8192,
                          streaming: Optional[bool] = None,
                          interpret: Optional[bool] = None,
                          collective_id: int = 8) -> jax.Array:
    """Fused compressed ring all-gather of an owned chunk [C] -> [n*C].
    Bit-identical to ops.ring.ring_all_gather with codec="pallas" (the
    streaming kernel slices the chunk, but frames forward verbatim and
    blocks align to slice boundaries, so the bytes are unchanged).

    Routing: payloads whose gathered output fits the VMEM-resident budget
    (~4 MiB) use the whole-chunk resident kernel; larger payloads default
    to the HBM-streaming interleaved-emission kernel (slot window S + 2,
    deadlock-free for arbitrary slice plans — _ag_schedule P1/P2), gathered
    in sequential segments past the frame-VMEM budget.  streaming=False
    opts out to the separate-op XLA ring with the identical codec."""
    cfg = compression or BFPConfig()
    n = lax.axis_size(axis_name)
    C = owned.shape[0]
    if interpret is None:
        interpret = not _is_tpu()
    if C % (cfg.block_size * LANES):
        raise ValueError(
            f"fused ring gather needs chunk {C} % "
            f"{cfg.block_size * LANES} == 0")
    if n == 1:
        # quantize roundtrip via the same lane-layout codec kernels
        # (matches ops.ring's n==1 semantics: replicas see wire bytes);
        # inline entries — a nested jitted closed_call trips the vma
        # checker inside checked shard_maps
        from . import bfp_pallas
        mant, se = bfp_pallas.bfp_encode_inline(
            owned.astype(jnp.float32), cfg.block_size, cfg.mantissa_bits,
            cfg.rounding, interpret=interpret)
        return bfp_pallas.bfp_decode_inline(mant, se, cfg.block_size,
                                            owned.dtype,
                                            interpret=interpret)
    big = n * C * 4 > _VMEM_RESIDENT_MAX_BYTES
    if streaming is None:
        streaming = big
    if not streaming:
        if big:
            # explicit opt-out from the streaming kernel: the separate-op
            # ring with the SAME lane-layout codec — bit-identical bytes,
            # HBM-resident via XLA
            import dataclasses
            from . import ring as _ring_ops
            return _ring_ops.ring_all_gather(
                owned, axis_name,
                compression=dataclasses.replace(cfg, codec="pallas"))
        x2 = owned.astype(jnp.float32).reshape(-1, LANES)
        out = _ag_call(x2, axis_name, cfg.block_size, cfg.mantissa_bits,
                       cfg.rounding, interpret, collective_id)
        return out.reshape(n * C)

    # streaming kernel; frame VMEM scales with the chunk (not the slice
    # plan), so chunks beyond the budget gather in independent sequential
    # segments — blocks never straddle a segment boundary, so the bytes
    # match the whole-chunk gather exactly
    tile = cfg.block_size * LANES
    cap = _AG_STREAM_MAX_CHUNK_ELEMS - (_AG_STREAM_MAX_CHUNK_ELEMS % tile)

    def gather_seg(seg: jax.Array) -> jax.Array:
        sz = seg.shape[0]
        x2 = seg.astype(jnp.float32).reshape(-1, LANES)
        slice_e = pick_slice_elems(sz, slice_elems, cfg.block_size)
        out = _ag_stream_call(x2, axis_name, cfg.block_size,
                              cfg.mantissa_bits, cfg.rounding, slice_e,
                              interpret, collective_id)
        return out.reshape(n, sz)

    if C <= cap:
        return gather_seg(owned).reshape(n * C)
    outs = [gather_seg(owned[off:min(off + cap, C)])
            for off in range(0, C, cap)]
    return jnp.concatenate(outs, axis=1).reshape(n * C)


def ring_all_reduce_fused(x: jax.Array, axis_name: str, *,
                          compression: Optional[BFPConfig] = None,
                          slice_elems: int = 8192,
                          interpret: Optional[bool] = None) -> jax.Array:
    """Fused all-reduce = fused reduce-scatter + fused all-gather."""
    owned = ring_reduce_scatter_fused(x, axis_name,
                                      compression=compression,
                                      slice_elems=slice_elems,
                                      interpret=interpret)
    return ring_all_gather_fused(owned, axis_name, compression=compression,
                                 interpret=interpret)


def pick_slice_elems(C: int, target: int, block_size: int) -> int:
    """Largest divisor of chunk C that is a multiple of block_size*LANES
    and <= target — the fused kernel's slice plan for arbitrary
    (padded-to-tile) payloads.  Slicing at block boundaries never changes
    the block partition, so this is a schedule choice, not a numerics
    choice."""
    tile = block_size * LANES
    assert C % tile == 0, (C, tile)
    k = C // tile
    best = 1
    d = 1
    while d * d <= k:
        if k % d == 0:
            for c in (d, k // d):
                if c * tile <= target and c > best:
                    best = c
        d += 1
    return best * tile


def _loopback_shmap(fn, arg):
    """Run a self-addressed kernel call under a 1-device shard_map — the
    LOGICAL device-id space needs a mesh axis to resolve against, even
    for self-addressed copies."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec
    mesh = Mesh(np.array(jax.devices()[:1]), ("lb",))
    return jax.shard_map(fn, mesh=mesh, in_specs=PartitionSpec(),
                         out_specs=PartitionSpec(), check_vma=False)(arg)


def loopback_microbench(x: jax.Array, virtual_n: int = 4, *,
                        compression: Optional[BFPConfig] = None,
                        slice_elems: int = 8192,
                        streaming: bool = False,
                        interpret: Optional[bool] = None,
                        ablate: Optional[str] = None) -> jax.Array:
    """Single-chip exercise of the fused reduce-scatter pipeline: the same
    kernel with every RDMA addressed to this device (virtual ring of
    `virtual_n`); streaming=True runs the HBM-streaming variant.

    The numerics are a self-accumulation (not a real reduce-scatter), but
    the DATAFLOW — encode slice g+1 on the VPU while slice g's DMA is in
    flight, decode+accumulate on arrival, credit flow control — is
    identical, so its sustained GB/s bounds the compressed ring's per-hop
    rate on real multi-chip ICI (where the DMA engine drives the
    interconnect instead of a local loopback).  This exists because the
    bench surface has ONE chip (BASELINE.md); the multi-chip bit-exactness
    story runs on the CPU interpreter (tests/test_ring_pallas.py).
    """
    cfg = compression or BFPConfig()
    if interpret is None:
        interpret = not _is_tpu()
    L = x.shape[0]
    assert L % virtual_n == 0, (L, virtual_n)
    C = L // virtual_n
    if C % slice_elems or slice_elems % (cfg.block_size * LANES):
        raise ValueError((C, slice_elems, cfg.block_size * LANES))
    x2 = x.astype(jnp.float32).reshape(-1, LANES)
    if ablate == "hbm" and not streaming:
        raise ValueError("'hbm' ablates the streaming kernel's slice "
                         "load/store stages; the resident kernel has none")
    call = _rs_stream_call if streaming else _rs_call
    out = _loopback_shmap(
        lambda v: call(v, None, cfg.block_size, cfg.mantissa_bits,
                       cfg.rounding, slice_elems, interpret, 7,
                       loopback_n=virtual_n, ablate=ablate), x2)
    return out.reshape(C)


def loopback_gather_microbench(owned: jax.Array, virtual_n: int = 4, *,
                               compression: Optional[BFPConfig] = None,
                               slice_elems: int = 8192,
                               streaming: bool = False,
                               interpret: Optional[bool] = None) -> jax.Array:
    """Single-chip exercise of the fused all-gather pipeline (resident or
    streaming), self-addressed like `loopback_microbench` — on one chip a
    node's arrival stream is its own emission stream, so the interleaved
    schedule, slot window, credits, and the encode/forward/decode overlap
    all execute exactly as on a real ring.  Output is [virtual_n * C]
    (deterministic; not a real gather)."""
    cfg = compression or BFPConfig()
    if interpret is None:
        interpret = not _is_tpu()
    C = owned.shape[0]
    if C % slice_elems or slice_elems % (cfg.block_size * LANES):
        raise ValueError((C, slice_elems, cfg.block_size * LANES))
    x2 = owned.astype(jnp.float32).reshape(-1, LANES)
    if streaming:
        out = _loopback_shmap(
            lambda v: _ag_stream_call(v, None, cfg.block_size,
                                      cfg.mantissa_bits, cfg.rounding,
                                      slice_elems, interpret, 8,
                                      loopback_n=virtual_n), x2)
    else:
        out = _loopback_shmap(
            lambda v: _ag_call(v, None, cfg.block_size, cfg.mantissa_bits,
                               cfg.rounding, interpret, 8,
                               loopback_n=virtual_n), x2)
    return out.reshape(virtual_n * C)
