"""Ring attention: exact attention over sequence shards with K/V blocks
rotating a unidirectional device ring.

The reference has no attention (MLP only — SURVEY.md §5 "long-context:
not present"), but its defining dataflow — stream a neighbor's block in,
combine locally, forward it on (hw/all_reduce.sv st_eth_t REDUCE/FORWARD
states) — is exactly the ring-attention schedule: each hop, the local query
block attends to the visiting K/V block with a numerically-stable online
softmax (flash-attention accumulation), while the K/V payload moves to the
next neighbor over ``lax.ppermute``.  XLA overlaps the permute with the
local attention compute the way the FPGA overlapped wire and adders.

Causal masking uses global token positions, so the result is bit-equivalent
to full attention on the unsharded sequence (up to fp reassociation).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# "minus infinity" that survives exp() safely.  A plain float, NOT a
# jnp scalar: creating a device array at import time initializes the XLA
# backend, which breaks jax.distributed.initialize() in every process
# that imports this package before calling it (multihost.initialize must
# come first)
_NEG = -1e30


def _init_acc(B, H, S, dh, vma=()):
    """Fresh online-softmax accumulators (running max / normalizer /
    output), widened to `vma` when the caller sits inside shard_map (scan
    carries must enter with the vma type the body produces)."""
    accs = (jnp.full((B, H, S, 1), _NEG, jnp.float32),
            jnp.zeros((B, H, S, 1), jnp.float32),
            jnp.zeros((B, H, S, dh), jnp.float32))
    vma = tuple(sorted(vma))
    return tuple(lax.pcast(z, vma, to="varying") if vma else z
                 for z in accs)


def _finish(o, l, out_dtype):
    """Normalize the accumulated output; rows with no visible keys keep a
    zero output (cannot happen causally: a token always sees itself)."""
    return (o / jnp.where(l == 0, 1.0, l)).astype(out_dtype)


def _block_attend(q, k, v, q_pos, k_pos, m, l, o, sm_scale, causal):
    """One online-softmax accumulation step against a visiting K/V block.

    q: [B,H,Sq,dh]; k,v: [B,H,Sk,dh]; positions: [Sq]/[Sk];
    m,l: [B,H,Sq,1] running max / normalizer; o: [B,H,Sq,dh] running output.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        mask = k_pos[None, :] > q_pos[:, None]           # [Sq, Sk]
        s = jnp.where(mask[None, None], _NEG, s)
    m_blk = jnp.max(s, axis=-1, keepdims=True)           # [B,H,Sq,1]
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)                           # rescale old state
    p = jnp.exp(s - m_new)                               # [B,H,Sq,Sk]
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                   v.astype(jnp.float32),
                                   preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def _attend_chunk(qf, k, v, q_pos, k_pos0, m, l, o, sm_scale, causal,
                  k_block: Optional[int],
                  remat_blocks: Optional[bool] = None):
    """Online-softmax accumulation against one visiting K/V chunk, scanning
    it in k-blocks so at most [B,H,Sq,k_block] scores materialize — the
    flash-attention blocking that keeps peak memory O(S*k_block) instead of
    O(S^2).  k_block=None (or >= S) processes the chunk whole.

    The streamed-block structure is the same move the reference makes in
    hardware: it never buffers a whole vector, it streams 32 KiB slices
    through fixed-size working sets (hw/all_reduce.sv:101-103)."""
    S = k.shape[2]
    if k_block is not None and S % k_block:
        # keep the memory bound for any S: largest divisor of S <= k_block
        # (smaller blocks cost iterations, never memory)
        k_block = next(d for d in range(min(k_block, S), 0, -1) if S % d == 0)
    if k_block is None or k_block >= S:
        k_pos = k_pos0 + lax.broadcasted_iota(jnp.int32, (S, 1), 0)[:, 0]
        return _block_attend(qf, k.astype(jnp.float32), v, q_pos, k_pos,
                             m, l, o, sm_scale, causal)

    def step(carry, j):
        m, l, o = carry
        ks = lax.dynamic_slice_in_dim(k, j * k_block, k_block, axis=2)
        vs = lax.dynamic_slice_in_dim(v, j * k_block, k_block, axis=2)
        kp = (k_pos0 + j * k_block
              + lax.broadcasted_iota(jnp.int32, (k_block, 1), 0)[:, 0])
        m, l, o = _block_attend(qf, ks.astype(jnp.float32), vs, q_pos, kp,
                                m, l, o, sm_scale, causal)
        return (m, l, o), None

    # remat_blocks: recompute each block's scores in the backward (the
    # flash-attention backward) — without it, differentiating the scan
    # saves every block's [Sq, k_block] residuals SIMULTANEOUSLY, which
    # at long S reconstitutes O(S^2/k_block * k_block) = O(S^2) memory
    # (measured: 22 GB at S=16384 where the forward needs < 2 GB).
    # None = auto: recompute only past a few blocks — at short S the
    # residuals are small and the recompute is a pure slowdown (measured
    # -2.5 MFU points on a S=1024 config with it always-on)
    if remat_blocks is None:
        remat_blocks = S // k_block > 4
    if remat_blocks:
        step = jax.checkpoint(step)
    (m, l, o), _ = lax.scan(step, (m, l, o), jnp.arange(S // k_block))
    return m, l, o


def pallas_route(impl: str, q_shape, kv_seq_len: Optional[int] = None
                 ) -> bool:
    """Shared attention-backend dispatch: the fused kernels when pinned
    or (auto) on TPU with tiling shapes; pinned-but-unsupported raises (a
    silent xla fallback would invalidate A/B runs).  ``q_shape`` is the
    [B, H, S, dh] tuple (or an array with that .shape); pass
    ``kv_seq_len`` for cross-attention (Sk != Sq) so auto can route a
    non-lane-tileable Sk to the xla path instead of raising downstream."""
    from . import flash_pallas
    q_shape = getattr(q_shape, "shape", q_shape)
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"attn impl {impl!r}: want auto|pallas|xla")
    if impl == "pallas" and not flash_pallas.supported(
            q_shape, kv_seq_len=kv_seq_len):
        raise ValueError(
            f"impl='pallas' pinned but q shape {q_shape} / kv_seq_len="
            f"{kv_seq_len} does not tile (need S % 128 == 0, "
            "head_dim % 8 == 0, head_dim <= 256, Sk % 128 == 0)")
    return (impl == "pallas" or (impl == "auto" and flash_pallas._is_tpu()
                                 and flash_pallas.supported(
                                     q_shape, kv_seq_len=kv_seq_len)))


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   *, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   k_block: Optional[int] = 512,
                   unroll: bool = False, impl: str = "auto") -> jax.Array:
    """Sequence-parallel exact attention inside ``shard_map``.

    q, k, v: [B, H, S_local, dh] — the local sequence shard; shards are
    contiguous: device i holds global positions [i*S_local, (i+1)*S_local).
    Returns [B, H, S_local, dh] in q's dtype.

    k_block: flash-style blocking of each visiting K/V chunk (see
    `_attend_chunk`); the default keeps peak score memory at
    [B, H, S_local, 512] regardless of sequence length.  None disables
    blocking (the whole-chunk reference schedule).

    unroll: unroll the n-1 hop loop at trace time — same knob and default
    as ``CollectiveConfig.unroll_hops`` (marginally better codegen at tiny
    n, O(n) compile-time blowup at pod scale; the rolled ``fori_loop`` is
    the default for the same reason as in ops.ring).

    impl: "auto" routes each hop's local attention through the fused
    Pallas kernels on TPU (ops.flash_pallas.ring_flash_attention — same
    K/V rotation, logsumexp hop merge, per-hop flash vjp); "xla"/"pallas"
    pin a backend.  The unroll and k_block=None knobs are XLA-path
    schedules: in auto mode requesting either keeps the XLA path (an
    explicitly-set knob must never be silently ignored); pinned "pallas"
    rejects them.
    """
    xla_only_knobs = unroll or k_block is None
    if impl == "pallas" and xla_only_knobs:
        raise ValueError(
            "impl='pallas' cannot honor unroll=True / k_block=None — "
            "the fused ring is a rolled scan of blocked kernels; drop "
            "the knob or use impl='xla'")
    if not xla_only_knobs and pallas_route(impl, q,
                                           kv_seq_len=k.shape[2]):
        from . import flash_pallas
        return flash_pallas.ring_flash_attention(
            q, k, v, axis_name, causal=causal, sm_scale=sm_scale,
            block_q=k_block, block_k=k_block)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, H, S, dh = q.shape
    if sm_scale is None:
        sm_scale = dh ** -0.5
    qf = q.astype(jnp.float32)
    q_pos = idx * S + lax.broadcasted_iota(jnp.int32, (S, 1), 0)[:, 0]

    # hop 0: attend the local block first (a causal token always sees
    # itself, so the row max is finite and the carry enters the ring loop
    # already device-varying — no variance-cast ops needed)
    # accumulators start device-varying: the k-block scan in _attend_chunk
    # carries them, and a scan carry's variance type must match its output
    # (which is varying as soon as it touches q/k)
    m0, l0, o0 = _init_acc(B, H, S, dh,
                           {axis_name} | set(jax.typeof(qf).vma))
    m, l, o = _attend_chunk(qf, k, v, q_pos, idx * S, m0, l0, o0,
                            sm_scale, causal, k_block)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(s_i, carry):
        m, l, o, kc, vc = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        src = (idx - s_i) % n                 # whose K/V we hold this hop

        def attend(mlo):
            return _attend_chunk(qf, kc, vc, q_pos, src * S, *mlo,
                                 sm_scale, causal, k_block)

        if causal:
            # blocks entirely in the future (src > idx: every key position
            # exceeds every local query position) are fully masked — skip
            # their attention compute, keep only the ring hop itself.  This
            # halves the attention FLOPs at large n, the same dead-beat
            # elision the reference's FSM gets by construction (it never
            # reduces slices it hasn't reached, hw/all_reduce.sv:923-987).
            m, l, o = lax.cond(src > idx, lambda mlo: mlo, attend, (m, l, o))
        else:
            m, l, o = attend((m, l, o))
        return m, l, o, kc, vc

    m, l, o, _, _ = lax.fori_loop(1, n, hop, (m, l, o, k, v), unroll=unroll)
    return _finish(o, l, q.dtype)


def full_attention(q, k, v, *, causal=True, sm_scale=None):
    """Unsharded reference implementation (the golden model for tests)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    S = q.shape[2]
    if causal:
        pos = lax.broadcasted_iota(jnp.int32, (S, 1), 0)[:, 0]
        s = jnp.where((pos[None, :] > pos[:, None])[None, None], _NEG, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


def flash_attention_remat(q, k, v, *, causal=True, sm_scale=None,
                          k_block: Optional[int] = 512, impl: str = "auto"):
    """Memory-bounded exact attention for model code — picks the best
    backward story available:

    - ``pallas`` (auto on TPU when shapes tile): the fused
      ops.flash_pallas kernels; the custom-vjp backward recomputes p
      from the saved logsumexp, so no ``jax.checkpoint`` wrapper is
      needed (wrapping one would only re-run the forward kernel).
    - ``xla`` (auto off-TPU / odd shapes): the k-block-scanned
      ``flash_attention`` under attention-only ``jax.checkpoint`` —
      without it the scan's per-block residuals reconstitute O(S^2)
      backward memory (measured 22 GB at S=16,384; models/llama.py
      carried this wrapper before round 5 moved the choice here)."""
    from . import flash_pallas
    if pallas_route(impl, q, kv_seq_len=k.shape[2]):
        b = k_block or flash_pallas._DEF_BLOCK
        return flash_pallas.flash_attention(q, k, v, causal=causal,
                                            sm_scale=sm_scale,
                                            block_q=b, block_k=b)
    return jax.checkpoint(
        lambda q2, k2, v2: flash_attention(q2, k2, v2, causal=causal,
                                           sm_scale=sm_scale,
                                           k_block=k_block))(q, k, v)


def gathered_attention(q, k, v, axis_name: str, *, causal=True,
                       sm_scale=None, k_block: Optional[int] = 512,
                       impl: str = "auto"):
    """Sequence-parallel attention via KV all-gather: queries stay
    sequence-sharded, keys/values gather once over `axis_name`, and the
    local attention runs the same flash-style k-blocked online softmax as
    ring_attention (`_attend_chunk`), so peak score memory stays
    O(S_local * k_block) — only the gathered K/V buffers are O(S_global).

    Why it exists next to ring_attention: the 1F1B schedulers run the
    attention inside stage-divergent `lax.cond` branches, and a
    collective-PERMUTE there is unsound — its source-target pairs span
    the whole mesh, so every device must execute it, while replica-
    GROUPED collectives (psum / all_gather / all_to_all) rendezvous per
    subgroup and only need the sp group, which does share one pp stage
    and one branch.  (Empirically: a ppermute inside a half-mesh cond
    crashes the CPU runtime outright; the sp-sharded 1F1B llama silently
    produced a 4% wrong loss.)  Numerics: identical online-softmax
    accumulation to ring_attention up to f32 summation order (both are
    exact attention).  Reference analogue: none — the reference has no
    attention; this is the standard all-gather sequence-parallel form.

    impl: "auto" keeps the (replica-grouped, cond-safe) all_gather and
    runs the LOCAL attention through the fused Pallas kernel with
    q_offset = idx*S_local (global-position causality); "xla"/"pallas"
    pin a backend.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Sl, dh = q.shape
    if sm_scale is None:
        sm_scale = dh ** -0.5
    kf = lax.all_gather(k, axis_name, axis=2, tiled=True)
    vf = lax.all_gather(v, axis_name, axis=2, tiled=True)
    if pallas_route(impl, q, kv_seq_len=kf.shape[2]):
        from . import flash_pallas
        b = k_block or flash_pallas._DEF_BLOCK
        return flash_pallas.flash_attention(
            q, kf, vf, causal=causal, sm_scale=sm_scale,
            q_offset=idx * Sl, block_q=b, block_k=b)
    qf = q.astype(jnp.float32)
    q_pos = idx * Sl + lax.broadcasted_iota(jnp.int32, (Sl, 1), 0)[:, 0]
    m0, l0, o0 = _init_acc(B, H, Sl, dh,
                           {axis_name} | set(jax.typeof(qf).vma))
    m, l, o = _attend_chunk(qf, kf, vf, q_pos, 0, m0, l0, o0,
                            sm_scale, causal, k_block)
    return _finish(o, l, q.dtype)


def flash_attention(q, k, v, *, causal=True, sm_scale=None,
                    k_block: Optional[int] = 512):
    """Single-device flash-blocked exact attention: the same
    `_attend_chunk` online-softmax accumulation the ring/gathered
    variants use, with no collectives — peak score memory
    O(S * k_block) instead of full_attention's O(S^2) f32 score matrix
    (which XLA also saves for the backward, forcing remat on long
    sequences).  Bit-differences vs full_attention are f32 summation
    order only; both are exact softmax attention."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    B, H, S, dh = q.shape
    qf = q.astype(jnp.float32)
    pos = lax.broadcasted_iota(jnp.int32, (S, 1), 0)[:, 0]
    # q may be batch-sharded under an outer shard_map even though this
    # attention itself is collective-free
    vma = (set(jax.typeof(qf).vma) | set(jax.typeof(k).vma)
           | set(jax.typeof(v).vma))
    m0, l0, o0 = _init_acc(B, H, S, dh, vma)
    m, l, o = _attend_chunk(qf, k, v, pos, 0, m0, l0, o0,
                            sm_scale, causal, k_block)
    return _finish(o, l, q.dtype)
