from . import (bfp, bfp_golden, bfp_pallas, bucketed, flash_pallas,
               fused_update, moe, ring, ring_attention, ring_cost,
               ring_golden, ring_hier, ring_pallas)  # noqa: F401

# explicit export surface (the codec subsystem made the implicit one
# stale: fused_update now also owns codec resolution / error feedback;
# the codecs themselves live in fpga_ai_nic_tpu.compress)
__all__ = [
    "bfp", "bfp_golden", "bfp_pallas", "bucketed", "flash_pallas",
    "fused_update", "moe", "ring", "ring_attention", "ring_cost",
    "ring_golden", "ring_hier", "ring_pallas",
]
