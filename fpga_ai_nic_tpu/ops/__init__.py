from . import bfp, bfp_golden, fused_update, ring, ring_golden  # noqa: F401
