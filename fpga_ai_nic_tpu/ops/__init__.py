from . import bfp, bfp_golden, bucketed, fused_update, ring, ring_golden  # noqa: F401
