from . import bfp, bfp_golden  # noqa: F401
