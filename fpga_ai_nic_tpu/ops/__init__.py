from . import (bfp, bfp_golden, bfp_pallas, bucketed, flash_pallas,
               fused_update, moe, ring, ring_attention, ring_cost,
               ring_golden, ring_pallas)  # noqa: F401
