"""JAX (XLA) implementation of the BFP codec.

Vectorized, jit-safe, grad-transparent (via a straight-through custom_vjp
wrapper).  Must agree bit-for-bit with `ops.bfp_golden` — enforced by
tests/test_bfp.py.  The Pallas kernel variant lives in `ops.bfp_pallas`.

Reference semantics: hw/bf16_to_bfp_core.sv / hw/bfp_to_bf16_core.sv as
instantiated by hw/bfp_adapter.sv:134,150,678 (see bfp_golden docstring for
the derivation).  TPU-first choices: int8 mantissa tensors feed the wire
(and can feed int8 MXU paths later); scales are int8 exponents so a
compressed payload is exactly ``n + n/block`` bytes.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from ..utils.config import BFPConfig


def _blocked(x: jax.Array, block: int) -> jax.Array:
    assert x.shape[-1] % block == 0, (x.shape, block)
    return x.reshape(*x.shape[:-1], x.shape[-1] // block, block)


def biased_exponent(x: jax.Array) -> jax.Array:
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    return ((bits >> 23) & 0xFF).astype(jnp.int32)


def _exp2_int(e: jax.Array) -> jax.Array:
    """2.0**e for int e in [-126, 127], exactly, via exponent-field bitcast."""
    bits = ((e + 127).astype(jnp.uint32)) << 23
    return jax.lax.bitcast_convert_type(bits, jnp.float32)


@functools.partial(jax.jit, static_argnames=("block_size", "mantissa_bits", "rounding"))
def bfp_encode(x: jax.Array, block_size: int = 16, mantissa_bits: int = 8,
               rounding: str = "nearest") -> Tuple[jax.Array, jax.Array]:
    """fp32/bf16 -> (int8 mantissas [...n], int8 scale exponents [...n/B])."""
    x = x.astype(jnp.float32)
    xb = _blocked(x, block_size)
    emax = jnp.max(biased_exponent(xb), axis=-1)
    scale_exp = jnp.clip(emax - 127 - (mantissa_bits - 2), -126, 126)
    q = xb * _exp2_int(-scale_exp)[..., None]
    q = jnp.round(q) if rounding == "nearest" else jnp.trunc(q)
    lim = float(2 ** (mantissa_bits - 1) - 1)
    mant = jnp.clip(q, -lim, lim).astype(jnp.int8).reshape(x.shape)
    return mant, scale_exp.astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("block_size", "dtype"))
def bfp_decode(mant: jax.Array, scale_exp: jax.Array, block_size: int = 16,
               dtype=jnp.float32) -> jax.Array:
    mb = _blocked(mant, block_size)
    scale = _exp2_int(scale_exp.astype(jnp.int32))
    x = mb.astype(jnp.float32) * scale[..., None]
    return x.reshape(mant.shape).astype(dtype)


def bfp_roundtrip(x: jax.Array, cfg: BFPConfig) -> jax.Array:
    """decode(encode(x)) — the quantization the wire applies."""
    mant, se = bfp_encode(x, cfg.block_size, cfg.mantissa_bits, cfg.rounding)
    return bfp_decode(mant, se, cfg.block_size, x.dtype)


@jax.custom_vjp
def bfp_ste(x: jax.Array, block_size: int = 16, mantissa_bits: int = 8):
    """Straight-through estimator: BFP quantization in fwd, identity grad.

    Lets models train *through* a simulated compressed channel (the
    reference ships lossy compression with zero accuracy evaluation —
    readme.pdf §3.3; this is our handle for convergence tests)."""
    mant, se = bfp_encode(x, block_size, mantissa_bits)
    return bfp_decode(mant, se, block_size, x.dtype)


def _ste_fwd(x, block_size=16, mantissa_bits=8):
    return bfp_ste(x, block_size, mantissa_bits), None


def _ste_bwd(_, g):
    return (g, None, None)


bfp_ste.defvjp(_ste_fwd, _ste_bwd)


def pad_to_block(x: jax.Array, block_size: int) -> Tuple[jax.Array, int]:
    """Flatten + zero-pad to a block multiple (the ring engine pads vectors
    to slice multiples the same way — hw/all_reduce.sv:403-409,428-433)."""
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % block_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat, pad


def wire_bytes(n_elems: int, cfg: BFPConfig) -> int:
    """Bytes on the wire: mantissas + one scale byte per block
    (ref: BFP_SIZE = EXP_SIZE + NUM_FP*MANT_SIZE, hw/bfp_adapter.sv:76)."""
    assert n_elems % cfg.block_size == 0
    mant_bytes = (n_elems * cfg.mantissa_bits + 7) // 8
    return mant_bytes + n_elems // cfg.block_size
