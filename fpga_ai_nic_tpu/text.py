"""Real-data text pipeline: tokenizer -> packed LM windows -> ShardedLoader.

The reference trains on host-random synthetic activations scattered once at
startup (sw/mlp_mpi_example_f32.cpp:414-424,452-460); its benchmark needs no
dataset.  A framework does: this module turns raw text (strings or files)
into the fixed-shape (tokens, labels) batches every Llama trainer in
`parallel/` consumes, streaming through `data.ShardedLoader` so host->HBM
copies overlap compute.

TPU-first choices:
- **Static shapes.** Documents are packed into fixed [seq_len] windows
  (concatenate with EOS separators, no padding inside a window), so every
  batch compiles once; ragged/padded per-document batches would recompile
  or waste MXU cycles on pad tokens.
- **Globally-shifted labels.** labels[i] = tokens[i+1] is computed at pack
  time, BEFORE any sequence sharding — the shift crosses sequence-shard
  boundaries, which is exactly the contract `models.llama.loss_fn`
  documents for sp meshes.  Cross-document positions are masked with -100
  (the loss's ignore value) so a token never predicts across an EOS.
- **No downloads.** The built-in tokenizer is byte-level (vocab = 256
  bytes + specials): self-contained, reversible, language-agnostic — the
  zero-egress environment cannot fetch BPE vocabularies.  Anything with
  ``encode/decode/vocab_size`` (e.g. a locally-cached HuggingFace
  tokenizer via `HFTokenizer`) plugs into the same pipeline.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np


@dataclass(frozen=True)
class ByteTokenizer:
    """Reversible byte-level tokenizer: ids 0..255 are raw bytes, then
    pad/bos/eos.  vocab_size is 259; size the model's vocab to any value
    >= this (round up to a multiple of 128 to keep the lm_head/embedding
    lane-aligned on TPU)."""

    pad_id: int = 256
    bos_id: int = 257
    eos_id: int = 258

    @property
    def vocab_size(self) -> int:
        return 259

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8",
                                                       errors="replace")


class HFTokenizer:
    """Adapter for a locally-available HuggingFace tokenizer (no downloads:
    pass a filesystem path; raises if the files are not already on disk)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer
        self._tok = AutoTokenizer.from_pretrained(path,
                                                  local_files_only=True)
        self.eos_id = self._tok.eos_token_id
        if self.eos_id is None:      # e.g. bert-style: no eos; sep works
            self.eos_id = self._tok.sep_token_id
        if self.eos_id is None:
            raise ValueError(f"tokenizer at {path} has neither eos nor sep "
                             "token; LM packing needs a document separator")
        bos, pad = self._tok.bos_token_id, self._tok.pad_token_id
        self.bos_id = bos if bos is not None else self.eos_id
        self.pad_id = pad if pad is not None else self.eos_id

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids)


def _iter_texts(source: Union[str, Iterable[str]]) -> Iterator[str]:
    """Yield documents: an iterable of strings, a text-file path (one doc
    per blank-line-separated block), or a directory of *.txt files."""
    if isinstance(source, str):
        if os.path.isdir(source):
            for name in sorted(os.listdir(source)):
                if name.endswith(".txt"):
                    yield from _iter_texts(os.path.join(source, name))
            return
        with open(source, encoding="utf-8") as f:
            block: List[str] = []
            for line in f:
                if line.strip():
                    block.append(line)
                elif block:
                    yield "".join(block)
                    block = []
            if block:
                yield "".join(block)
        return
    yield from source


def pack_windows(source: Union[str, Iterable[str]], tokenizer,
                 seq_len: int, *, epochs: Optional[int] = 1,
                 ) -> Iterator[np.ndarray]:
    """Tokenize documents and pack them into fixed [seq_len + 1] int32
    windows: [bos] doc [eos] doc [eos] ... concatenated, no padding (the
    final partial window is dropped — static shapes).

    Yields windows w; a training pair is (w[:-1], labels(w[1:])) — built
    with boundary masking by `lm_batches`.  The token buffer carries over
    between epochs, so a corpus smaller than one window still fills
    windows over repeated epochs instead of stalling; a corpus that yields
    no documents at all raises.

    A one-shot iterator source (a generator is its own iterator and
    cannot be re-iterated) is captured to a list during epoch 1 and
    replayed for later epochs, so epochs != 1 works for any documented
    Iterable[str] instead of crashing with "empty corpus" at the start of
    epoch 2 (round-3 advisor finding)."""
    one_shot = not isinstance(source, str) and iter(source) is source
    capture: Optional[List[str]] = [] if one_shot and epochs != 1 else None
    buf: List[int] = [tokenizer.bos_id]
    off = 0
    e = 0
    while epochs is None or e < epochs:
        any_doc = False
        docs = capture if (capture is not None and e > 0) \
            else _iter_texts(source)
        for doc in docs:
            if capture is not None and e == 0:
                capture.append(doc)
            any_doc = True
            buf.extend(tokenizer.encode(doc))
            buf.append(tokenizer.eos_id)
            # window off the buffer via a read offset (re-slicing the tail
            # per window would be quadratic in document length), overlap
            # by one token so every next-token target exists
            while len(buf) - off >= seq_len + 1:
                yield np.asarray(buf[off:off + seq_len + 1], np.int32)
                off += seq_len
            if off:
                buf = buf[off:]
                off = 0
        if not any_doc:
            raise ValueError("empty corpus: source yielded no documents")
        e += 1


def lm_batches(source: Union[str, Iterable[str]], tokenizer, *,
               batch_size: int, seq_len: int, seed: int = 0,
               shuffle_buffer: int = 256, epochs: Optional[int] = 1,
               mask_boundaries: bool = True) -> Iterator[tuple]:
    """(tokens [B, S], labels [B, S]) int32 batches for the Llama trainers
    (feed through ``data.ShardedLoader(stream, mesh, tr.batch_spec)``).

    Window-level shuffling with a bounded reservoir (documents stream;
    nothing is materialized beyond shuffle_buffer windows)."""
    rng = np.random.default_rng(seed)
    eos = tokenizer.eos_id

    def pairs():
        for w in pack_windows(source, tokenizer, seq_len, epochs=epochs):
            toks, labels = w[:-1], w[1:].copy()
            if mask_boundaries:
                # a target that STARTS a new document (its predecessor in
                # the stream is eos) carries no signal from this context
                labels[toks == eos] = -100
            yield toks, labels

    buf: List[tuple] = []
    batch: List[tuple] = []
    for p in pairs():
        if len(buf) < shuffle_buffer:
            buf.append(p)
            continue
        j = int(rng.integers(len(buf)))
        buf[j], p = p, buf[j]
        batch.append(p)
        if len(batch) == batch_size:
            yield (np.stack([t for t, _ in batch]),
                   np.stack([l for _, l in batch]))
            batch = []
    rng.shuffle(buf)
    for p in buf:
        batch.append(p)
        if len(batch) == batch_size:
            yield (np.stack([t for t, _ in batch]),
                   np.stack([l for _, l in batch]))
            batch = []
