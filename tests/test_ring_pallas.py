"""Fused compress-into-hop Pallas ring (ops.ring_pallas): bit-exactness vs
the XLA-op ring running the identical lane-layout codec, on the CPU
interpreter's multi-device emulation — the "3-instance testbench + golden
compare" discipline (readme.pdf §3.2-3.3) applied to the fused kernel.
Transitively golden: the XLA-op ring's pallas wire path is itself
bit-matched to ops.bfp_golden (tests/test_ring.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fpga_ai_nic_tpu.ops import ring as ring_ops
from fpga_ai_nic_tpu.ops import ring_pallas as rp
from fpga_ai_nic_tpu.utils.config import BFPConfig

CFG = BFPConfig(codec="pallas")
SLICE = CFG.block_size * rp.LANES          # one native tile per slice


def _run(fn, n):
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P("dp"),
                                 out_specs=P("dp"), check_vma=False))


@pytest.mark.parametrize("n,slices_per_chunk", [(8, 2), (4, 1), (2, 4)])
def test_fused_matches_xla_op_ring_bitexact(rng, n, slices_per_chunk):
    """Fusing encode/RDMA/decode into one kernel (and its double-buffered
    slice schedule + credit flow control) must not change a single bit vs
    the separate-ops ring with the same codec and slice plan."""
    C = SLICE * slices_per_chunk
    x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)

    got = _run(lambda v: rp.ring_reduce_scatter_fused(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    want = _run(lambda v: ring_ops.ring_reduce_scatter(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("n,slices_per_chunk", [(8, 2), (4, 1), (2, 4)])
def test_streaming_matches_resident_bitexact(rng, n, slices_per_chunk):
    """The HBM-streaming kernel (two VMEM slices, aliased HBM acc,
    load/writeback DMAs around the codec/RDMA pipeline) is a residency
    choice, never a numerics choice: bit-identical to the VMEM-resident
    kernel and the XLA-op ring."""
    C = SLICE * slices_per_chunk
    x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)

    got = _run(lambda v: rp.ring_reduce_scatter_fused(
        v, "dp", compression=CFG, slice_elems=SLICE,
        streaming=True), n)(x.reshape(-1))
    want = _run(lambda v: ring_ops.ring_reduce_scatter(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_mantissa_sweep_bitexact(rng):
    """Narrower mantissas (more quantization per hop) stay bit-identical
    too — error accumulation is part of the spec, not schedule-dependent."""
    n, C = 4, SLICE * 2
    x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)
    for m in (6, 4):
        cfg = BFPConfig(codec="pallas", mantissa_bits=m)
        got = _run(lambda v: rp.ring_reduce_scatter_fused(
            v, "dp", compression=cfg, slice_elems=SLICE), n)(x.reshape(-1))
        want = _run(lambda v: ring_ops.ring_reduce_scatter(
            v, "dp", compression=cfg, slice_elems=SLICE), n)(x.reshape(-1))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("n", [2, 4, 8])
def test_fused_all_gather_matches_xla_op_ring_bitexact(rng, n):
    """The fused gather forwards the encoded frame verbatim: every
    replica must hold the identical quantized bytes the XLA-op ring
    produces (the updated-weights distribution phase)."""
    C = SLICE * 2
    owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)

    got = _run(lambda v: rp.ring_all_gather_fused(
        v, "dp", compression=CFG), n)(owned.reshape(-1))
    want = _run(lambda v: ring_ops.ring_all_gather(
        v, "dp", compression=CFG), n)(owned.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("n,slices_per_chunk", [(8, 2), (4, 4), (4, 1),
                                                (2, 3), (3, 2)])
def test_streaming_all_gather_matches_xla_op_ring_bitexact(
        rng, n, slices_per_chunk):
    """The interleaved-emission streaming gather (HBM out, sliced frames,
    closed-form emission indices) forwards bytes verbatim: byte-identical
    to the whole-chunk XLA-op ring across ring sizes, odd/even slice
    counts, and S=1."""
    C = SLICE * slices_per_chunk
    owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)
    got = _run(lambda v: rp.ring_all_gather_fused(
        v, "dp", compression=CFG, slice_elems=SLICE,
        streaming=True), n)(owned.reshape(-1))
    want = _run(lambda v: ring_ops.ring_all_gather(
        v, "dp", compression=CFG), n)(owned.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_all_gather_large_payload_delegates(rng, monkeypatch):
    """Past the VMEM budget the gather auto-routes to the separate-op
    ring with the identical codec — byte-identical output."""
    monkeypatch.setattr(rp, "_VMEM_RESIDENT_MAX_BYTES", 1024)
    n, C = 4, SLICE * 2
    owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)
    got = _run(lambda v: rp.ring_all_gather_fused(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(owned.reshape(-1))
    want = _run(lambda v: ring_ops.ring_all_gather(
        v, "dp", compression=CFG), n)(owned.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_all_reduce_matches_xla_op_ring_bitexact(rng):
    n, C = 4, SLICE * 2
    x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)
    got = _run(lambda v: rp.ring_all_reduce_fused(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    want = _run(lambda v: ring_ops.ring_all_reduce(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pick_slice_elems():
    tile = CFG.block_size * rp.LANES            # 2048
    assert rp.pick_slice_elems(8 * tile, 8192, 16) == 8192
    assert rp.pick_slice_elems(6 * tile, 8192, 16) == 3 * tile
    assert rp.pick_slice_elems(7 * tile, 8192, 16) == tile  # 7*tile > cap
    assert rp.pick_slice_elems(13 * tile, 8192, 16) == tile
    assert rp.pick_slice_elems(tile, 8192, 16) == tile


def test_fused_rejects_bad_slice_plan(rng):
    """Silent repartitioning would change the block partition (and the
    bits): unsatisfiable slice plans must raise, not adapt."""
    n = 2
    x = jnp.asarray(rng.standard_normal((n, n * SLICE)), jnp.float32)
    with pytest.raises(ValueError, match="fused ring"):
        _run(lambda v: rp.ring_reduce_scatter_fused(
            v, "dp", compression=CFG, slice_elems=SLICE // 2), n)(
                x.reshape(-1))


def test_fused_kernel_trainer_integration(rng):
    """CollectiveConfig.fused_kernel end-to-end through a ZeRO-1 training
    step.  On this CPU surface the routing takes the documented off-TPU
    fallback (separate-op ring; the fused kernels themselves run only
    under the single-axis op-level tests above and on real TPU) — the
    test pins the routing, padding, and slice-plan plumbing: must track
    the uncompressed XLA-collective trainer within the m8 quantization
    band and descend."""
    import jax
    from fpga_ai_nic_tpu.models import mlp
    from fpga_ai_nic_tpu.parallel import DPTrainer
    from fpga_ai_nic_tpu.utils.config import (CollectiveConfig, MeshConfig,
                                              MLPConfig, OptimizerConfig,
                                              TrainConfig)
    mcfg = MLPConfig(layer_sizes=(128, 256, 32), dtype="float32")
    x = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 32, 64), jnp.int32)
    # single-axis mesh: the fused kernels' LOGICAL RDMA ids are flat mesh
    # indices (see ring_pallas._ring_ids)
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))

    def train(coll):
        cfg = TrainConfig(iters=4, global_batch=64,
                          mesh=MeshConfig(dp=8), collective=coll,
                          optimizer=OptimizerConfig(kind="momentum",
                                                    learning_rate=1e-2))
        tr = DPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg), mesh, cfg)
        # fresh identical params per run (init_state donates its input)
        st = tr.init_state(mlp.init(jax.random.PRNGKey(0), mcfg))
        out = []
        for _ in range(4):
            st, loss = tr.step(st, tr.shard_batch((x, y)))
            out.append(float(loss))
        return out

    ref = train(CollectiveConfig(impl="xla"))
    fused = train(CollectiveConfig(impl="ring", compression=BFPConfig(),
                                   fused_kernel=True))
    np.testing.assert_allclose(fused, ref, rtol=0.02)
    assert fused[-1] < fused[0], fused


def test_fused_kernel_config_validation():
    from fpga_ai_nic_tpu.utils.config import CollectiveConfig
    with pytest.raises(ValueError, match="fused_kernel"):
        CollectiveConfig(impl="xla", fused_kernel=True)
    with pytest.raises(ValueError, match="fused_kernel"):
        CollectiveConfig(impl="ring", fused_kernel=True)


def test_loopback_microbench_runs(rng):
    """The single-chip loopback mode (the TPU microbench surface) executes
    the same kernel with self-addressed RDMAs and produces finite output
    deterministically."""
    v_n = 4
    x = jnp.asarray(rng.standard_normal(v_n * SLICE), jnp.float32)
    a = np.asarray(rp.loopback_microbench(x, v_n, slice_elems=SLICE))
    b = np.asarray(rp.loopback_microbench(x, v_n, slice_elems=SLICE))
    assert a.shape == (SLICE,)
    assert np.isfinite(a).all()
    np.testing.assert_array_equal(a, b)
