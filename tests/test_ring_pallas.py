"""Fused compress-into-hop Pallas ring (ops.ring_pallas): bit-exactness vs
the XLA-op ring running the identical lane-layout codec, on the CPU
interpreter's multi-device emulation — the "3-instance testbench + golden
compare" discipline (readme.pdf §3.2-3.3) applied to the fused kernel.
Transitively golden: the XLA-op ring's pallas wire path is itself
bit-matched to ops.bfp_golden (tests/test_ring.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fpga_ai_nic_tpu.ops import ring as ring_ops
from fpga_ai_nic_tpu.ops import ring_pallas as rp
from fpga_ai_nic_tpu.utils.config import BFPConfig

CFG = BFPConfig(codec="pallas")
SLICE = CFG.block_size * rp.LANES          # one native tile per slice


def _run(fn, n):
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P("dp"),
                                 out_specs=P("dp"), check_vma=False))


@pytest.mark.parametrize("n,slices_per_chunk", [(8, 2), (4, 1), (2, 4)])
def test_fused_matches_xla_op_ring_bitexact(rng, n, slices_per_chunk):
    """Fusing encode/RDMA/decode into one kernel (and its double-buffered
    slice schedule + credit flow control) must not change a single bit vs
    the separate-ops ring with the same codec and slice plan."""
    C = SLICE * slices_per_chunk
    x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)

    got = _run(lambda v: rp.ring_reduce_scatter_fused(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    want = _run(lambda v: ring_ops.ring_reduce_scatter(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("n,slices_per_chunk", [(8, 2), (4, 1), (2, 4)])
def test_streaming_matches_resident_bitexact(rng, n, slices_per_chunk):
    """The HBM-streaming kernel (two VMEM slices, aliased HBM acc,
    load/writeback DMAs around the codec/RDMA pipeline) is a residency
    choice, never a numerics choice: bit-identical to the VMEM-resident
    kernel and the XLA-op ring."""
    C = SLICE * slices_per_chunk
    x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)

    got = _run(lambda v: rp.ring_reduce_scatter_fused(
        v, "dp", compression=CFG, slice_elems=SLICE,
        streaming=True), n)(x.reshape(-1))
    want = _run(lambda v: ring_ops.ring_reduce_scatter(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_mantissa_sweep_bitexact(rng):
    """Narrower mantissas (more quantization per hop) stay bit-identical
    too — error accumulation is part of the spec, not schedule-dependent."""
    n, C = 4, SLICE * 2
    x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)
    for m in (6, 4):
        cfg = BFPConfig(codec="pallas", mantissa_bits=m)
        got = _run(lambda v: rp.ring_reduce_scatter_fused(
            v, "dp", compression=cfg, slice_elems=SLICE), n)(x.reshape(-1))
        want = _run(lambda v: ring_ops.ring_reduce_scatter(
            v, "dp", compression=cfg, slice_elems=SLICE), n)(x.reshape(-1))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("n", [2, 4, 8])
def test_fused_all_gather_matches_xla_op_ring_bitexact(rng, n):
    """The fused gather forwards the encoded frame verbatim: every
    replica must hold the identical quantized bytes the XLA-op ring
    produces (the updated-weights distribution phase)."""
    C = SLICE * 2
    owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)

    got = _run(lambda v: rp.ring_all_gather_fused(
        v, "dp", compression=CFG), n)(owned.reshape(-1))
    want = _run(lambda v: ring_ops.ring_all_gather(
        v, "dp", compression=CFG), n)(owned.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
@pytest.mark.parametrize("n", [2, 3, 4, 8])
@pytest.mark.parametrize("slices_per_chunk", list(range(1, 9)))
def test_streaming_all_gather_matches_xla_op_ring_bitexact(
        rng, n, slices_per_chunk):
    """The interleaved-emission streaming gather (HBM out, sliced frames,
    slot window S+2) forwards bytes verbatim: byte-identical to the
    whole-chunk XLA-op ring across the full production regime — every
    ring size x slice plan up to S=8, including the deep own-phase plans
    the old depth-2 window could not run (round-3 verdict item 2)."""
    C = SLICE * slices_per_chunk
    owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)
    got = _run(lambda v: rp.ring_all_gather_fused(
        v, "dp", compression=CFG, slice_elems=SLICE,
        streaming=True), n)(owned.reshape(-1))
    want = _run(lambda v: ring_ops.ring_all_gather(
        v, "dp", compression=CFG), n)(owned.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_all_gather_big_payload_routes_to_streaming(rng, monkeypatch):
    """Past the VMEM-resident budget the gather now defaults to the
    STREAMING kernel (round-3 verdict item 2: the separate-op fallback is
    gone as the default route) — byte-identical output."""
    calls = []
    orig = rp._ag_stream_call

    def spy(*a, **k):
        calls.append(True)
        return orig(*a, **k)

    monkeypatch.setattr(rp, "_VMEM_RESIDENT_MAX_BYTES", 1024)
    monkeypatch.setattr(rp, "_ag_stream_call", spy)
    n, C = 4, SLICE * 2
    owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)
    got = _run(lambda v: rp.ring_all_gather_fused(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(owned.reshape(-1))
    want = _run(lambda v: ring_ops.ring_all_gather(
        v, "dp", compression=CFG), n)(owned.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert calls, "big payload did not route to the streaming kernel"


def test_fused_all_gather_streaming_false_delegates(rng, monkeypatch):
    """streaming=False on a big payload is the explicit opt-out to the
    separate-op ring with the identical codec — byte-identical output."""
    monkeypatch.setattr(rp, "_VMEM_RESIDENT_MAX_BYTES", 1024)
    n, C = 4, SLICE * 2
    owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)
    got = _run(lambda v: rp.ring_all_gather_fused(
        v, "dp", compression=CFG, slice_elems=SLICE,
        streaming=False), n)(owned.reshape(-1))
    want = _run(lambda v: ring_ops.ring_all_gather(
        v, "dp", compression=CFG), n)(owned.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_streaming_all_gather_segmented_bitexact(rng, monkeypatch):
    """Chunks past the frame-VMEM budget gather in sequential segments;
    blocks never straddle a segment boundary, so the reassembled output
    is byte-identical to the unsegmented gather."""
    n, C = 4, SLICE * 6
    owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)
    want = _run(lambda v: rp.ring_all_gather_fused(
        v, "dp", compression=CFG, slice_elems=SLICE,
        streaming=True), n)(owned.reshape(-1))
    monkeypatch.setattr(rp, "_AG_STREAM_MAX_CHUNK_ELEMS", SLICE * 2)
    got = _run(lambda v: rp.ring_all_gather_fused(
        v, "dp", compression=CFG, slice_elems=SLICE,
        streaming=True), n)(owned.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_all_reduce_matches_xla_op_ring_bitexact(rng):
    n, C = 4, SLICE * 2
    x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)
    got = _run(lambda v: rp.ring_all_reduce_fused(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    want = _run(lambda v: ring_ops.ring_all_reduce(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
@pytest.mark.skipif(not rp.HAS_THREADED_INTERPRET,
                    reason="this jaxlib ships no threaded TPU interpreter "
                           "(pltpu.InterpretParams)")
class TestFlowControl:
    """The REAL flow-control protocol — neighbor barrier, credit-window
    semaphores, blocking waits — executed end-to-end under the threaded
    TPU interpreter (pltpu.InterpretParams: one thread per emulated
    device, remote semaphore signals, race detection ON).  Round-3
    verdict missing #2 / advisor medium: this path had never executed
    anywhere, because the discharge interpreter skips it by design.  Here
    a protocol deadlock hangs the test (caught by CI's timeout), a slot
    race is reported by the interpreter's race detector, and the result
    must STILL be bit-identical to the XLA-op ring.

    Rings are capped at n=4 here: the threaded interpreter needs a live
    OS thread per emulated device and this container has ONE core.  n=8
    exceeds 500s before any kernel body runs.  Round-5 diagnosis
    (faulthandler stack dump during the hang): device 0 is parked in
    shared_memory.Semaphore.wait (the neighbor barrier — correct,
    blocking, GIL-released) while the other SEVEN threads all sit inside
    interpret_pallas_call._allocate_buffer's np.array(val) buffer-init
    copies under the interpreter's shared-memory lock and race-detector
    vector clocks — kernel-ENTRY allocation, serialized on one core, not
    our credit protocol (no cycle: the barrier participants simply never
    finish allocating).  Forcing sys.setswitchinterval(0.0005) does not
    help, ruling out GIL unfairness: the allocation work itself is the
    convoy.  An upstream report is not possible from this surface (zero
    egress) — this docstring is the record.  n=4 already exercises
    everything the protocol has: multi-hop forwards, credit waits
    (j >= n_slots), wire-slot reuse (total > n_slots), and the barrier;
    n=8 stays covered by the fast discharge-interpreter sweep above and
    the hardware canary (tools/first_contact.py)."""

    @pytest.mark.parametrize("n,slices_per_chunk", [(4, 2), (3, 1), (2, 2)])
    def test_rs_resident(self, rng, n, slices_per_chunk):
        C = SLICE * slices_per_chunk
        x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)
        got = _run(lambda v: rp.ring_reduce_scatter_fused(
            v, "dp", compression=CFG, slice_elems=SLICE,
            interpret="threaded"), n)(x.reshape(-1))
        want = _run(lambda v: ring_ops.ring_reduce_scatter(
            v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("n,slices_per_chunk", [(4, 3), (2, 1)])
    def test_rs_streaming(self, rng, n, slices_per_chunk):
        C = SLICE * slices_per_chunk
        x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)
        got = _run(lambda v: rp.ring_reduce_scatter_fused(
            v, "dp", compression=CFG, slice_elems=SLICE, streaming=True,
            interpret="threaded"), n)(x.reshape(-1))
        want = _run(lambda v: ring_ops.ring_reduce_scatter(
            v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("n", [4, 3])
    def test_ag_resident(self, rng, n):
        C = SLICE * 2
        owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)
        got = _run(lambda v: rp.ring_all_gather_fused(
            v, "dp", compression=CFG, streaming=False,
            interpret="threaded"), n)(owned.reshape(-1))
        want = _run(lambda v: ring_ops.ring_all_gather(
            v, "dp", compression=CFG), n)(owned.reshape(-1))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_ag_streaming_segmented(self, rng, monkeypatch):
        """Sequential segment kernels share one collective_id (barrier
        semaphore) — the composition must hold under the REAL protocol,
        not just the lockstep emulation."""
        n = 4
        C = SLICE * 4
        monkeypatch.setattr(rp, "_AG_STREAM_MAX_CHUNK_ELEMS", SLICE * 2)
        owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)
        got = _run(lambda v: rp.ring_all_gather_fused(
            v, "dp", compression=CFG, slice_elems=SLICE, streaming=True,
            interpret="threaded"), n)(owned.reshape(-1))
        want = _run(lambda v: ring_ops.ring_all_gather(
            v, "dp", compression=CFG), n)(owned.reshape(-1))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("n,slices_per_chunk", [(4, 4), (4, 2), (3, 5)])
    def test_ag_streaming(self, rng, n, slices_per_chunk):
        """The credit window (n_slots = S+2) under real concurrency: the
        own phase emits two frames per consume step — exactly the regime
        whose deadlock-freedom the round-3 ledger left unproven."""
        C = SLICE * slices_per_chunk
        owned = jnp.asarray(rng.standard_normal((n, C)), jnp.float32)
        got = _run(lambda v: rp.ring_all_gather_fused(
            v, "dp", compression=CFG, slice_elems=SLICE, streaming=True,
            interpret="threaded"), n)(owned.reshape(-1))
        want = _run(lambda v: ring_ops.ring_all_gather(
            v, "dp", compression=CFG), n)(owned.reshape(-1))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pick_slice_elems():
    tile = CFG.block_size * rp.LANES            # 2048
    assert rp.pick_slice_elems(8 * tile, 8192, 16) == 8192
    assert rp.pick_slice_elems(6 * tile, 8192, 16) == 3 * tile
    assert rp.pick_slice_elems(7 * tile, 8192, 16) == tile  # 7*tile > cap
    assert rp.pick_slice_elems(13 * tile, 8192, 16) == tile
    assert rp.pick_slice_elems(tile, 8192, 16) == tile


def test_fused_rejects_bad_slice_plan(rng):
    """Silent repartitioning would change the block partition (and the
    bits): unsatisfiable slice plans must raise, not adapt."""
    n = 2
    x = jnp.asarray(rng.standard_normal((n, n * SLICE)), jnp.float32)
    with pytest.raises(ValueError, match="fused ring"):
        _run(lambda v: rp.ring_reduce_scatter_fused(
            v, "dp", compression=CFG, slice_elems=SLICE // 2), n)(
                x.reshape(-1))


def test_fused_kernel_trainer_integration(rng):
    """CollectiveConfig.fused_kernel end-to-end through a ZeRO-1 training
    step.  On this CPU surface the routing takes the documented off-TPU
    fallback (separate-op ring; the fused kernels themselves run only
    under the single-axis op-level tests above and on real TPU) — the
    test pins the routing, padding, and slice-plan plumbing: must track
    the uncompressed XLA-collective trainer within the m8 quantization
    band and descend."""
    import jax
    from fpga_ai_nic_tpu.models import mlp
    from fpga_ai_nic_tpu.parallel import DPTrainer
    from fpga_ai_nic_tpu.utils.config import (CollectiveConfig, MeshConfig,
                                              MLPConfig, OptimizerConfig,
                                              TrainConfig)
    mcfg = MLPConfig(layer_sizes=(128, 256, 32), dtype="float32")
    x = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 32, 64), jnp.int32)
    # single-axis mesh: the fused kernels' LOGICAL RDMA ids are flat mesh
    # indices (see ring_pallas._ring_ids)
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))

    def train(coll):
        cfg = TrainConfig(iters=4, global_batch=64,
                          mesh=MeshConfig(dp=8), collective=coll,
                          optimizer=OptimizerConfig(kind="momentum",
                                                    learning_rate=1e-2))
        tr = DPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg), mesh, cfg)
        # fresh identical params per run (init_state donates its input)
        st = tr.init_state(mlp.init(jax.random.PRNGKey(0), mcfg))
        out = []
        for _ in range(4):
            st, loss = tr.step(st, tr.shard_batch((x, y)))
            out.append(float(loss))
        return out

    ref = train(CollectiveConfig(impl="xla"))
    fused = train(CollectiveConfig(impl="ring", compression=BFPConfig(),
                                   fused_kernel=True))
    np.testing.assert_allclose(fused, ref, rtol=0.02)
    assert fused[-1] < fused[0], fused


def test_fused_kernel_config_validation():
    from fpga_ai_nic_tpu.utils.config import CollectiveConfig
    with pytest.raises(ValueError, match="fused_kernel"):
        CollectiveConfig(impl="xla", fused_kernel=True)
    with pytest.raises(ValueError, match="fused_kernel"):
        CollectiveConfig(impl="ring", fused_kernel=True)


@pytest.mark.parametrize("streaming", [False, True])
def test_loopback_microbench_runs(rng, streaming):
    """The single-chip loopback mode (the TPU microbench + deadlock-canary
    surface) executes the same kernels with self-addressed RDMAs and
    produces finite output deterministically."""
    v_n = 4
    x = jnp.asarray(rng.standard_normal(v_n * 2 * SLICE), jnp.float32)
    a = np.asarray(rp.loopback_microbench(x, v_n, slice_elems=SLICE,
                                          streaming=streaming))
    b = np.asarray(rp.loopback_microbench(x, v_n, slice_elems=SLICE,
                                          streaming=streaming))
    assert a.shape == (2 * SLICE,)
    assert np.isfinite(a).all()
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("streaming", [False, True])
def test_loopback_gather_microbench_runs(rng, streaming):
    """The all-gather loopback (resident + streaming) — the canary that
    covers the gather kernels' flow-control path on hardware — runs the
    interleaved schedule self-addressed, finite and deterministic."""
    v_n = 4
    owned = jnp.asarray(rng.standard_normal(2 * SLICE), jnp.float32)
    a = np.asarray(rp.loopback_gather_microbench(
        owned, v_n, slice_elems=SLICE, streaming=streaming))
    b = np.asarray(rp.loopback_gather_microbench(
        owned, v_n, slice_elems=SLICE, streaming=streaming))
    assert a.shape == (v_n * 2 * SLICE,)
    assert np.isfinite(a).all()
    np.testing.assert_array_equal(a, b)


def test_loopback_stage_ablation(rng):
    """Stage-ablated loopback variants (round-5 per-stage attribution):
    each runs the same schedule with one stage compiled in.  Ablations
    that exclude decode+add (and whose writeback, if any, stores back
    unchanged content) never modify the accumulator, so the owned chunk
    comes back untouched — a structural check that the ablation really
    removed the stage rather than scrambling the schedule."""
    vn, SL = 4, SLICE
    x = jnp.asarray(rng.standard_normal(vn * 2 * SL), jnp.float32)
    C = x.shape[0] // vn
    for ab in ("encode", "rdma", "skeleton"):
        out = rp.loopback_microbench(x, vn, slice_elems=SL, ablate=ab)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x[:C]),
                                      err_msg=ab)
    out = rp.loopback_microbench(x, vn, slice_elems=SL, ablate="decode")
    assert out.shape == (C,)               # decodes stale frames: values
    full = rp.loopback_microbench(x, vn, slice_elems=SL)  # are garbage
    assert full.shape == (C,) and np.isfinite(np.asarray(full)).all()
    # the resident kernel has no HBM slice-streaming stage to ablate
    with pytest.raises(ValueError, match="hbm"):
        rp.loopback_microbench(x, vn, slice_elems=SL, ablate="hbm")


def test_loopback_stage_ablation_streaming(rng):
    """Streaming-kernel ablations: encode/rdma/skeleton touch nothing;
    'hbm' loads and writes back UNCHANGED slice content (pure memory
    streaming), so the accumulator is also untouched; decode mutates."""
    vn, SL = 4, SLICE
    x = jnp.asarray(rng.standard_normal(vn * 2 * SL), jnp.float32)
    C = x.shape[0] // vn
    for ab in ("encode", "rdma", "hbm", "skeleton"):
        out = rp.loopback_microbench(x, vn, slice_elems=SL,
                                     streaming=True, ablate=ab)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x[:C]),
                                      err_msg=ab)
    out = rp.loopback_microbench(x, vn, slice_elems=SL, streaming=True,
                                 ablate="decode")
    assert out.shape == (C,)
    full = rp.loopback_microbench(x, vn, slice_elems=SL, streaming=True)
    assert full.shape == (C,) and np.isfinite(np.asarray(full)).all()


@pytest.mark.parametrize("n,slices_per_chunk", [(4, 2), (8, 1), (2, 3)])
def test_fused_matches_numpy_golden_direct(rng, n, slices_per_chunk):
    """DIRECT golden compare (not just transitively through the XLA-op
    ring): the fused reduce-scatter's bits equal the numpy golden model
    running the identical sublane block layout — the 3-instance
    testbench + golden discipline (readme.pdf §3.2-3.3) applied to the
    deep-pipelined kernel itself."""
    from fpga_ai_nic_tpu.ops import ring_golden
    C = SLICE * slices_per_chunk
    shards = rng.standard_normal((n, n * C)).astype(np.float32)
    want = ring_golden.ring_reduce_scatter(shards, CFG, layout="sublane")
    for streaming in (False, True):
        got = _run(lambda v: rp.ring_reduce_scatter_fused(
            v, "dp", compression=CFG, slice_elems=SLICE,
            streaming=streaming), n)(jnp.asarray(shards).reshape(-1))
        np.testing.assert_array_equal(
            np.asarray(got).reshape(n, C), want,
            err_msg=f"streaming={streaming}")


# -- deep-pipelined schedule (PR: close the fused-ring 10x gap) ---------------

@pytest.mark.parametrize("depth", [1, 2, 3, 4])
@pytest.mark.parametrize("n,slices_per_chunk", [(8, 2), (4, 4), (2, 3)])
def test_pipeline_depth_bitexact(rng, n, slices_per_chunk, depth):
    """Every pipeline depth is a SCHEDULE choice, never a numerics
    choice: the depth-D kernels (resident and streaming) stay
    bit-identical to the separate-op XLA ring across the depth sweep —
    including depths the plan caps (depth > S falls back to S) and
    depth=1, which reproduces the old two-slot lockstep exactly."""
    C = SLICE * slices_per_chunk
    x = jnp.asarray(rng.standard_normal((n, n * C)), jnp.float32)
    want = _run(lambda v: ring_ops.ring_reduce_scatter(
        v, "dp", compression=CFG, slice_elems=SLICE), n)(x.reshape(-1))
    for streaming in (False, True):
        got = _run(lambda v: rp.ring_reduce_scatter_fused(
            v, "dp", compression=CFG, slice_elems=SLICE,
            streaming=streaming, pipeline_depth=depth), n)(x.reshape(-1))
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"depth={depth} streaming={streaming}")


@pytest.mark.parametrize("streaming", [False, True])
def test_rolled_schedule_matches_unrolled(rng, monkeypatch, streaming):
    """The ROLLED schedule (lax.fori_loop + pl.when + SMEM schedule-table
    loads — the code hardware actually compiles) executed under the
    discharge interpreter, bit-compared against the unrolled static
    schedule.  The old kernels never ran this path off-hardware; the
    deep pipeline's traced-counter guards (q >= n_slots, clamped table
    loads) make the coverage load-bearing.  jit caches key on static
    args only, so caches are cleared around the monkeypatched variant."""
    vn, SL = 4, SLICE
    x = jnp.asarray(rng.standard_normal(vn * 4 * SL), jnp.float32)
    refs = {}
    for depth in (1, 2, 3):
        refs[depth] = np.asarray(rp.loopback_microbench(
            x, vn, slice_elems=SL, streaming=streaming,
            pipeline_depth=depth))
    jax.clear_caches()
    monkeypatch.setattr(rp, "_interp_args",
                        lambda interpret: (True, False, False))
    try:
        for depth in (1, 2, 3):
            rolled = np.asarray(rp.loopback_microbench(
                x, vn, slice_elems=SL, streaming=streaming,
                pipeline_depth=depth))
            np.testing.assert_array_equal(rolled, refs[depth],
                                          err_msg=f"depth={depth}")
    finally:
        jax.clear_caches()       # drop rolled-schedule entries keyed on
        # the same static args before other tests reuse them


def test_rs_plan_invariants():
    """The plan's three invariants (RAW / SLOT / CAP — _rs_plan
    docstring) hold over the whole production regime."""
    for n in (2, 3, 4, 8, 16):
        for S in (1, 2, 3, 4, 8):
            for depth in (None, 1, 2, 3, 8):
                D, n_slots, launch_first = rp._rs_plan(n, S, depth)
                total = (n - 1) * S
                assert 1 <= D <= min(S, total)
                assert n_slots == min(total, D + 1)
                assert n_slots <= D + 1            # SLOT: window > depth
                if launch_first:
                    assert D <= S - 1              # RAW before consume
                else:
                    assert D <= S                  # RAW after consume
    # depth=1 must reproduce the pre-deep-pipeline schedule shape
    assert rp._rs_plan(4, 2, 1) == (1, 2, True)
    assert rp._rs_plan(2, 1, 1) == (1, 1, False)


def test_sub_rows_block_aligned():
    """Sub-slice chunks divide the slice and never straddle a BFP block
    (a straddle would change the shared exponents — the bits)."""
    for R in (16, 64, 128, 256, 512, 48):
        sub = rp._sub_rows(R, 16)
        assert R % sub == 0 and sub % 16 == 0 and sub <= max(rp._SUB_ROWS, R)
    assert rp._sub_rows(64, 16) == 64       # small slices stay whole
    assert rp._sub_rows(512, 16) == 128     # big slices split


# -- credit-protocol race check at n=8 (round-5 verdict missing #5) -----------

@pytest.mark.parametrize("n", [2, 3, 4, 8])
def test_rs_protocol_simulation(n):
    """The credit protocol executed at MODEL level under randomized
    interleavings with truly asynchronous transfers: every (S, depth)
    plan at ring sizes up to n=8 completes without deadlock, slot
    overwrite, or ordering corruption (simulate_rs_protocol's failure
    modes).  This runs the 8-ring wait-for graph this container's
    jaxlib cannot (no threaded interpreter) — the real-kernel check is
    TestFlowControl + test_flow_control_selftest_n8 on newer jaxlibs."""
    for S in (1, 2, 4):
        for depth in (1, 2, 3, None):
            for seed in (0, 1, 2):
                ev = rp.simulate_rs_protocol(n, S, depth, seed)
                assert ev > 0


def test_rs_protocol_simulation_catches_bad_window(monkeypatch):
    """The simulator is not a rubber stamp: shrinking the comm window
    below depth+1 (violating the SLOT invariant) must be caught as a
    recv-slot overwrite or deadlock within a few seeds."""
    real_stream = rp._rs_op_stream

    def bad_stream(n, S, depth):
        ops, n_slots = real_stream(n, S, depth)
        assert n_slots >= 2, "need a window to shrink"
        # drop every wait/credit tied to the last slot: emissions reuse
        # slots one step too early
        return [op for op in ops
                if op[0] not in ("credit_wait",)][:len(ops)], n_slots - 1

    monkeypatch.setattr(rp, "_rs_op_stream", bad_stream)
    with pytest.raises(AssertionError, match="overwrite|deadlock"):
        for seed in range(8):
            rp.simulate_rs_protocol(4, 2, 2, seed)


@pytest.mark.slow
@pytest.mark.skipif(not rp.HAS_THREADED_INTERPRET,
                    reason="this jaxlib ships no threaded TPU interpreter "
                           "(pltpu.InterpretParams)")
@pytest.mark.parametrize("streaming", [False, True])
def test_flow_control_selftest_n8(streaming):
    """The REAL credit protocol at n=8 under the threaded interpreter —
    the run the round-5 ledger could not land: ablate='rdma' compiles
    the codec away (tiny buffers, so the 1-core allocation convoy that
    parked the full kernels for 500+ s never forms) while the barrier,
    credit window, and remote copies execute end to end with race
    detection on.  Deadlock hangs the test (CI timeout), a race is
    reported by the interpreter, and the untouched-accumulator output
    is checked exactly."""
    rp.flow_control_selftest(8, streaming=streaming)
