"""Hierarchical (intra x inter) 2-stage ring collectives (ops.ring_hier).

Contracts under test (docs/TUNING.md "hierarchical topology contract"):

- bit-exact vs the codec-generic numpy golden twin
  (compress.golden.hier_reduce_scatter / hier_all_gather) for every
  registered codec and every factorization of the 8-device mesh;
- bit-IDENTICAL to the flat ring for codec=None whenever the additions
  are exact (integer-valued payloads — f32 association is the only
  difference, so exact adds erase it), allclose on generic floats;
- the codec rides ONLY the slow inter hop (asserted statically by the
  jaxpr classification the J9 lint rule uses);
- HierarchicalPlan.wire_bytes is EXACTLY what the lowered program's
  ppermutes move (the same accounting the tuner banks and obs-gate
  pins);
- trainer integration: DPTrainer(topology="hier") trains and matches
  the flat trainer's master shards.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fpga_ai_nic_tpu.compress import get_codec, golden as cgold
from fpga_ai_nic_tpu.ops import ring_hier, ring as ring_ops

N = 8
CODECS = (None, "bfp", "topk", "int8")
FACTORS = (1, 2, 4, 8)


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("dp",))


def _run(fn, per_dev):
    """shard_map a per-device collective over the dp mesh; per_dev is
    [n, k] numpy (device-major)."""
    out = jax.jit(jax.shard_map(
        fn, mesh=_mesh(), in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False))(jnp.asarray(per_dev.reshape(-1)))
    return np.asarray(out).reshape(N, -1)


def _payload(rng, codec, l_unit=64):
    unit = N * (codec.pad_elems if codec else 1) * 2
    L = l_unit * unit
    return rng.standard_normal((N, L)).astype(np.float32), L


class TestGoldenParity:
    @pytest.mark.parametrize("codec_name", CODECS)
    @pytest.mark.parametrize("ni", FACTORS)
    def test_reduce_scatter_matches_golden(self, rng, codec_name, ni):
        codec = get_codec(codec_name) if codec_name else None
        rt = cgold.roundtrip_fn(codec) if codec else None
        shards, L = _payload(rng, codec)
        out = _run(lambda v: ring_hier.hier_reduce_scatter(
            v, "dp", ni, compression=codec), shards)
        gold = cgold.hier_reduce_scatter(shards, ni, rt)
        np.testing.assert_array_equal(out, gold)

    @pytest.mark.parametrize("codec_name", CODECS)
    @pytest.mark.parametrize("ni", (2, 4))
    def test_all_gather_matches_golden(self, rng, codec_name, ni):
        codec = get_codec(codec_name) if codec_name else None
        rt = cgold.roundtrip_fn(codec) if codec else None
        shards, L = _payload(rng, codec)
        owned = cgold.hier_reduce_scatter(shards, ni, rt)
        out = _run(lambda v: ring_hier.hier_all_gather(
            v, "dp", ni, compression=codec), owned)
        gold = cgold.hier_all_gather(owned, ni, rt)
        np.testing.assert_array_equal(out, gold)
        # replica identity: every device reassembled the same vector
        assert np.array_equal(out, np.broadcast_to(out[0], out.shape))

    @pytest.mark.parametrize("ni", (2, 4))
    def test_sliced_inter_hop_is_bit_identical(self, rng, ni):
        """slice_elems on the slow hop changes the schedule, never the
        bits (the Codec.sliceable contract, inherited from the flat
        ring)."""
        codec = get_codec("bfp")
        shards, L = _payload(rng, codec)
        whole = _run(lambda v: ring_hier.hier_reduce_scatter(
            v, "dp", ni, compression=codec), shards)
        C = L // N
        sliced = _run(lambda v: ring_hier.hier_reduce_scatter(
            v, "dp", ni, compression=codec, slice_elems=C // 2), shards)
        np.testing.assert_array_equal(whole, sliced)


class TestFlatParity:
    @pytest.mark.parametrize("ni", (2, 4))
    def test_bit_identical_to_flat_ring_on_exact_payloads(self, rng, ni):
        """codec=None: the hierarchical schedule computes the same SUM
        under a different association; integer-valued payloads make
        every f32 add exact, so the results must be bit-identical."""
        L = N * 256
        shards = rng.integers(-64, 64, (N, L)).astype(np.float32)
        flat = _run(lambda v: ring_ops.ring_reduce_scatter(v, "dp"),
                    shards)
        hier = _run(lambda v: ring_hier.hier_reduce_scatter(v, "dp", ni),
                    shards)
        np.testing.assert_array_equal(flat, hier)
        fg = _run(lambda v: ring_ops.ring_all_gather(v, "dp"), flat)
        hg = _run(lambda v: ring_hier.hier_all_gather(v, "dp", ni), flat)
        np.testing.assert_array_equal(fg, hg)

    def test_float_payloads_allclose_to_flat(self, rng):
        L = N * 512
        shards = rng.standard_normal((N, L)).astype(np.float32)
        flat = _run(lambda v: ring_ops.ring_reduce_scatter(v, "dp"),
                    shards)
        hier = _run(lambda v: ring_hier.hier_reduce_scatter(v, "dp", 4),
                    shards)
        np.testing.assert_allclose(flat, hier, rtol=1e-5, atol=1e-5)

    def test_degenerate_factorizations_reduce_to_flat(self, rng):
        """ni=1 (all inter) runs the codec ring across everyone; ni=n
        (all intra) is the raw ring — both are the flat schedules."""
        codec = get_codec("bfp")
        shards, L = _payload(rng, codec)
        h1 = _run(lambda v: ring_hier.hier_reduce_scatter(
            v, "dp", 1, compression=codec), shards)
        f1 = _run(lambda v: ring_ops.ring_reduce_scatter(
            v, "dp", compression=codec), shards)
        np.testing.assert_array_equal(h1, f1)
        hn = _run(lambda v: ring_hier.hier_reduce_scatter(v, "dp", N),
                  shards)
        fn = _run(lambda v: ring_ops.ring_reduce_scatter(v, "dp"),
                  shards)
        np.testing.assert_array_equal(hn, fn)


class TestPlanAccounting:
    @pytest.mark.parametrize("codec_name", CODECS)
    @pytest.mark.parametrize("ni", (2, 4))
    def test_lowered_bytes_equal_plan_declaration(self, codec_name, ni):
        """The J9 invariant, asserted here per cell: classify every
        ppermute in the traced program and compare per-hop-class bytes
        against HierarchicalPlan — and the intra hop must be f32."""
        from fpga_ai_nic_tpu.lint.jaxpr_sweep import (_classify_perm,
                                                      _collect_ppermutes)
        codec = get_codec(codec_name) if codec_name else None
        L = N * (codec.pad_elems if codec else 1) * 128
        plan = ring_hier.plan_hier(L, N, ni, codec)

        def prog(x):
            owned = ring_hier.hier_reduce_scatter(
                x, "dp", ni, compression=codec)
            return ring_hier.hier_all_gather(
                owned, "dp", ni, compression=codec)

        jx = jax.make_jaxpr(jax.jit(jax.shard_map(
            prog, mesh=_mesh(), in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False)))(
            jax.ShapeDtypeStruct((N * L,), jnp.float32))
        got = {"intra": 0, "inter": 0}
        for p in _collect_ppermutes(jx.jaxpr):
            klass = _classify_perm(p["perm"], ni)
            assert klass != "other", p["perm"][:4]
            assert p["mult"] is not None
            got[klass] += p["mult"] * p["bytes"]
            if klass == "intra":
                assert p["f32_only"], p["dtypes"]
        assert got["intra"] == plan.intra_bytes("all_reduce")
        assert got["inter"] == plan.inter_bytes("all_reduce")
        assert got["intra"] + got["inter"] == \
            plan.wire_bytes("all_reduce") == \
            ring_hier.wire_bytes_per_device(L, N, ni, codec)

    def test_bad_factorization_fails_loudly(self):
        with pytest.raises(ValueError):
            ring_hier.plan_hier(N * 16, N, 3, None)   # 3 does not divide 8
        with pytest.raises(ValueError):
            ring_hier.plan_hier(N * 16 + 1, N, 2, None)


class TestTrainerIntegration:
    def _train(self, coll, steps=2):
        from fpga_ai_nic_tpu.models import mlp
        from fpga_ai_nic_tpu.parallel import mesh as mesh_lib
        from fpga_ai_nic_tpu.parallel.train import DPTrainer
        from fpga_ai_nic_tpu.utils.config import (MeshConfig, MLPConfig,
                                                  TrainConfig)
        mcfg = MLPConfig(layer_sizes=(64, 64, 32))
        cfg = TrainConfig(mesh=MeshConfig(dp=N), collective=coll,
                          global_batch=64)
        mesh = mesh_lib.make_mesh(cfg.mesh)
        tr = DPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg), mesh, cfg)
        st = tr.init_state(mlp.init(jax.random.PRNGKey(0), mcfg))
        r = np.random.default_rng(0)
        x = r.standard_normal((64, 64)).astype(np.float32)
        y = r.integers(0, 32, (64,)).astype(np.int32)
        batch = tr.shard_batch((jnp.asarray(x), jnp.asarray(y)))
        for _ in range(steps):
            st, loss = tr.step(st, batch)
        return tr, np.asarray(st.w_own), float(loss)

    def test_hier_trainer_matches_flat(self):
        from fpga_ai_nic_tpu.utils.config import CollectiveConfig
        trh, wh, lh = self._train(
            CollectiveConfig(impl="ring", topology="hier", intra_size=4))
        trf, wf, lf = self._train(CollectiveConfig(impl="ring"))
        assert np.isfinite(lh) and np.isfinite(lf)
        np.testing.assert_allclose(wh, wf, rtol=1e-5, atol=1e-6)
        sm = trh.obs_static_metrics()
        assert sm["topology"] == "hier"
        assert sm["hier_plan"]["n_intra"] == 4
        # the statics' declaration is the plan's, not a re-derivation
        assert sm["wire_bytes_per_allreduce"] == \
            sm["hier_plan"]["wire_bytes_all_reduce"]

    def test_hier_with_codec_and_fused_optimizer(self):
        """The EQuARX shape end to end: codec on the slow hop only, the
        ZeRO-1 update fused after the reduce (the PR-6 shared-formula
        decode path)."""
        from fpga_ai_nic_tpu.utils.config import CollectiveConfig
        tr, w, loss = self._train(CollectiveConfig(
            impl="ring", codec="bfp", topology="hier", intra_size=2,
            fused_optimizer=True))
        assert np.isfinite(loss)

    def test_hier_config_validation(self):
        from fpga_ai_nic_tpu.utils.config import CollectiveConfig
        with pytest.raises(ValueError):
            CollectiveConfig(impl="xla", topology="hier", intra_size=2)
        with pytest.raises(ValueError):
            CollectiveConfig(impl="ring", topology="hier")  # no intra
        with pytest.raises(ValueError):
            CollectiveConfig(impl="ring", codec="bfp", topology="hier",
                             intra_size=2, fused_kernel=True)
