"""ResNet-50 (BASELINE config 3): DP training with the fused SGD collective,
sync-BN equivalence to single-device numerics, and eval-stats calibration."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from fpga_ai_nic_tpu.models import resnet
from fpga_ai_nic_tpu.parallel import DPTrainer, make_mesh
from fpga_ai_nic_tpu.utils.config import (
    CollectiveConfig, MeshConfig, OptimizerConfig, TrainConfig)

CFG = resnet.ResNetConfig.tiny()


def _data(rng, n=32, hw=16):
    x = rng.standard_normal((n, hw, hw, 3)).astype(np.float32)
    y = rng.integers(0, CFG.num_classes, n).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def test_forward_shapes_and_param_count(rng):
    params = resnet.init(jax.random.PRNGKey(0), CFG)
    x, _ = _data(rng, n=4)
    logits = resnet.apply(params, x, CFG)
    assert logits.shape == (4, CFG.num_classes)
    # resnet50 parameter count sanity: ~25.5M
    full = resnet.ResNetConfig.resnet50()
    n = resnet.num_params(full)
    assert 25.0e6 < n < 26.0e6, n


def test_sync_bn_matches_single_device(rng):
    """Sync-BN over dp on a split batch == one device on the full batch —
    the invariant that makes DP training numerics batch-size invariant."""
    params = resnet.init(jax.random.PRNGKey(0), CFG)
    x, y = _data(rng, n=16)
    mesh = make_mesh(MeshConfig(dp=8))
    want = resnet.loss_fn(params, (x, y), CFG)

    got = jax.jit(jax.shard_map(
        lambda p, b: jax.lax.pmean(
            resnet.loss_fn(p, b, CFG, bn_axis="dp"), "dp"),
        mesh=mesh, in_specs=(P(), P("dp")), out_specs=P(),
        check_vma=False))(params, (x, y))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_dp_fused_sgd_descends(rng):
    cfg = TrainConfig(
        iters=6, global_batch=32, mesh=MeshConfig(dp=8),
        collective=CollectiveConfig(impl="xla"),
        optimizer=OptimizerConfig(kind="sgd", learning_rate=0.05))
    mesh = make_mesh(cfg.mesh)
    tr = DPTrainer(lambda p, b: resnet.loss_fn(p, b, CFG, bn_axis="dp"),
                   mesh, cfg)
    state = tr.init_state(resnet.init(jax.random.PRNGKey(0), CFG))
    batch = tr.shard_batch(_data(rng))
    losses = []
    for _ in range(6):
        state, loss = tr.step(state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_eval_stats_calibration(rng):
    params = resnet.init(jax.random.PRNGKey(0), CFG)
    x, y = _data(rng, n=16)
    stats = resnet.init_stats(CFG)
    calib = jax.jit(lambda p, xb, s: resnet.compute_stats(p, xb, CFG, s))
    for _ in range(3):
        stats = calib(params, x, stats)
    logits = resnet.apply(params, x, CFG, stats=stats)
    assert logits.shape == (16, CFG.num_classes)
    assert np.isfinite(np.asarray(logits, np.float32)).all()
    # calibrated stats give finite, near-train-mode logits
    train_logits = resnet.apply(params, x, CFG)
    corr = np.corrcoef(
        np.asarray(logits, np.float32).ravel(),
        np.asarray(train_logits, np.float32).ravel())[0, 1]
    assert corr > 0.5, corr
