"""Direct unit tests for utils.observability — the aggregate counters
every stats dump embeds (previously the only subsystem with zero direct
tests: its behavior was pinned only incidentally, through the chaos bench
and the queued trainer).

Covers the accounting contracts the rest of the stack relies on:
abandoned-ticket counting through recovery, compression_ratio's
wire_bytes=0 convention, the MTTR aggregates, json_line round-tripping,
RecoveryStats' bounded event log with honest drop accounting, and — the
round-4 cross-thread fix — that concurrent mutation from watchdog-worker
and trainer threads loses no updates."""

import json
import threading

import jax.numpy as jnp
import pytest

from fpga_ai_nic_tpu.runtime.queue import CollectiveQueue
from fpga_ai_nic_tpu.utils.config import CollectiveConfig
from fpga_ai_nic_tpu.utils.observability import (CollectiveStats, Profiler,
                                                 RecoveryStats)


# ---------------------------------------------------------------------------
# CollectiveStats
# ---------------------------------------------------------------------------

def test_compression_ratio_with_zero_wire_bytes_is_one():
    st = CollectiveStats()
    assert st.as_dict()["compression_ratio"] == 1.0
    st.record_issue(raw_bytes=400, wire_bytes=100)
    assert st.as_dict()["compression_ratio"] == 4.0


def test_wire_bytes_default_to_raw():
    st = CollectiveStats()
    st.record_issue(raw_bytes=128)           # wire omitted -> raw
    d = st.as_dict()
    assert d["wire_bytes"] == d["raw_bytes"] == 128


def test_latency_and_stall_aggregates():
    st = CollectiveStats()
    st.record_completion(latency_s=0.2, stall_s=0.05, overlap_s=0.15)
    st.record_completion(latency_s=0.4, stall_s=0.10, overlap_s=0.30)
    d = st.as_dict()
    assert d["completed"] == 2
    assert d["mean_latency_ms"] == pytest.approx(300.0)
    assert d["max_latency_ms"] == pytest.approx(400.0)
    assert d["stall_s"] == pytest.approx(0.15)
    assert d["overlap_s"] == pytest.approx(0.45)


def test_abandoned_ticket_counting_through_queue():
    """abandon() drops every inflight ticket, counts each exactly once,
    and a wait() on a dropped ticket records nothing."""
    prof = Profiler()
    q = CollectiveQueue(lambda x: x * 2.0, CollectiveConfig(impl="ring"),
                        prof)
    t1 = q.issue(jnp.ones(8), raw_bytes=32)
    t2 = q.issue(jnp.ones(8), raw_bytes=32)
    assert q.abandon() == 2
    assert prof.collectives.abandoned == 2
    q.wait(t1)                                # dead ticket: no stats
    q.wait(t2)
    d = prof.collectives.as_dict()
    assert d["issued"] == 2
    assert d["completed"] == 0
    assert d["abandoned"] == 2
    assert q.outstanding == 0
    # a live ticket after recovery records normally again
    t3 = q.issue(jnp.ones(8), raw_bytes=32)
    q.wait(t3)
    assert prof.collectives.as_dict()["completed"] == 1


# ---------------------------------------------------------------------------
# RecoveryStats
# ---------------------------------------------------------------------------

def test_mttr_aggregates():
    rs = RecoveryStats()
    ev = rs.record_fault("hang", step=3, site="queue.wait")
    rs.record_recovery(2.0, restored=True, event=ev)
    rs.record_fault("corruption", step=5)
    rs.record_recovery(1.0)
    d = rs.as_dict()
    assert d["faults"] == {"hang": 1, "corruption": 1}
    assert d["faults_total"] == 2
    assert d["recoveries"] == 2
    assert d["checkpoint_restores"] == 1
    assert d["mttr_mean_s"] == pytest.approx(1.5)
    assert d["mttr_max_s"] == pytest.approx(2.0)
    assert ev["recovered_in_s"] == pytest.approx(2.0)


def test_recovery_event_log_truncates_with_explicit_drop_count():
    """The bounded event log keeps the first max_events faults; everything
    past that increments events_dropped so the dump can never read as
    complete when it is not."""
    rs = RecoveryStats(max_events=4)
    for i in range(10):
        rs.record_fault("hang", step=i)
    d = rs.as_dict()
    assert len(d["events"]) == 4
    assert d["events_dropped"] == 6
    assert d["faults_total"] == 10            # the COUNT never truncates
    assert [e["step"] for e in d["events"]] == [0, 1, 2, 3]


def test_json_line_round_trip():
    prof = Profiler()
    with prof.bucket("grads"):
        pass
    prof.collectives.record_issue(raw_bytes=64, wire_bytes=16)
    ev = prof.recovery.record_fault("hang", step=1)
    prof.recovery.record_recovery(0.5, event=ev)
    parsed = json.loads(prof.json_line())
    assert parsed == prof.report()
    assert parsed["collectives"]["compression_ratio"] == 4.0
    assert parsed["recovery"]["events_dropped"] == 0
    assert parsed["counts"]["grads"] == 1
    assert parsed["events"]["schema_version"] == 1


# ---------------------------------------------------------------------------
# cross-thread mutation (the elastic watchdog / trainer interleaving)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n_threads,per_thread", [(8, 500)])
def test_threaded_counter_stress_loses_no_updates(n_threads, per_thread):
    """The elastic loop's reality: watchdog worker threads mutate
    CollectiveStats while the trainer thread mutates RecoveryStats and
    reads dumps.  Every record_* must land exactly once — the bare ``+=``
    these methods replaced drops updates under this schedule."""
    prof = Profiler()
    barrier = threading.Barrier(n_threads)

    def hammer(tid):
        barrier.wait()
        for i in range(per_thread):
            prof.collectives.record_issue(raw_bytes=4, wire_bytes=1)
            prof.collectives.record_completion(0.001, 0.0005, 0.0005)
            prof.collectives.record_abandoned()
            prof.recovery.record_fault("hang", step=i)
            prof.recovery.record_recovery(0.001)
            with prof.bucket(f"b{tid % 2}"):
                pass
            prof.collectives.as_dict()        # concurrent reads too

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    total = n_threads * per_thread
    c = prof.collectives.as_dict()
    assert c["issued"] == total
    assert c["completed"] == total
    assert c["abandoned"] == total
    assert c["raw_bytes"] == 4 * total
    assert c["wire_bytes"] == total
    assert c["stall_s"] == pytest.approx(0.0005 * total, rel=1e-6)
    r = prof.recovery.as_dict()
    assert r["faults_total"] == total
    assert r["recoveries"] == total
    assert len(r["events"]) + r["events_dropped"] == total
    assert sum(prof.counts.values()) == total
