"""ops.ring_cost: the per-stage pipeline cost model and the rebuilt
break-even table — pure math, so it is pinned exactly here (the TPU
artifacts consume it through bench_collective / first_contact)."""

import pytest

from fpga_ai_nic_tpu.ops import ring_cost


def test_model_pipeline_vpu_binds():
    """Serial VPU: encode + decode - one skeleton; rdma hidden under it."""
    m = ring_cost.model_pipeline(
        {"skeleton": 1.0, "encode": 3.0, "decode": 4.0, "rdma": 2.0},
        full_s=6.5)
    assert m["valid"]
    assert m["terms_s"]["vpu"] == pytest.approx(6.0)   # 3 + 4 - 1
    assert m["binding_stage"] == "vpu"
    assert m["pipeline_efficiency"] == pytest.approx(6.0 / 6.5)
    assert m["model_rel_err"] == pytest.approx(0.5 / 6.0)


def test_model_pipeline_wire_binds():
    m = ring_cost.model_pipeline(
        {"skeleton": 0.5, "encode": 1.0, "decode": 1.0, "rdma": 9.0,
         "hbm": 4.0}, full_s=10.0)
    assert m["binding_stage"] == "rdma"
    assert m["modeled_s"] == pytest.approx(9.0)
    assert m["terms_s"]["hbm"] == pytest.approx(4.0)


def test_model_pipeline_skeleton_floor():
    """A stage can never predict a schedule faster than the bare loop —
    stage slopes below the skeleton clamp up to it."""
    m = ring_cost.model_pipeline(
        {"skeleton": 2.0, "encode": 2.1, "decode": 2.05, "rdma": 0.1})
    assert m["terms_s"]["rdma"] == pytest.approx(2.0)
    assert m["terms_s"]["vpu"] == pytest.approx(2.15)


def test_model_pipeline_invalid_inputs():
    """Non-positive slopes are unmeasured, never rates; a VPU-less set is
    flagged invalid and emits NO confident model numbers."""
    m = ring_cost.model_pipeline({"encode": -0.1, "decode": 0.0,
                                  "rdma": 3.0}, full_s=5.0)
    assert not m["valid"]
    assert "vpu" not in m["terms_s"]
    assert m["binding_stage"] == "rdma"    # still reports what it has
    assert "modeled_s" not in m and "pipeline_efficiency" not in m


def test_model_pipeline_partial_vpu_is_invalid():
    """One codec stage's slope drowned: the half-formed VPU term is kept
    as a labeled floor, but valid flips False and no modeled time or
    efficiency is fabricated from half the serial chain."""
    m = ring_cost.model_pipeline(
        {"skeleton": 1.0, "encode": 3.0, "decode": -1.0, "rdma": 2.0},
        full_s=6.0)
    assert not m["valid"] and m["vpu_partial"]
    assert m["terms_s"]["vpu"] == pytest.approx(3.0)
    assert "modeled_s" not in m and "pipeline_efficiency" not in m


def test_codec_rates_skeleton_corrected():
    """break_even ADDS the stage costs, so the per-stage rates it is fed
    must have the shared schedule skeleton subtracted — raw ablated
    rates would count it twice and understate the combined codec."""
    stages = {"skeleton": {"t_ms": 2.0}, "encode": {"t_ms": 6.0},
              "decode": {"t_ms": 10.0}}
    payload = 8 * 10**9 // 1000           # 8 GB/s at 1 ms per ms-unit
    enc, dec = ring_cost.codec_rates(stages, payload)
    assert enc == pytest.approx(payload / 4e-3 / 1e9)   # 6-2 ms
    assert dec == pytest.approx(payload / 8e-3 / 1e9)   # 10-2 ms
    # skeleton-bound stage: no honest asymptotic rate exists
    assert ring_cost.codec_rates(
        {"skeleton": {"t_ms": 5.0}, "encode": {"t_ms": 5.0},
         "decode": {"t_ms": 6.0}}, payload) == (0.0, 0.0)
    assert ring_cost.codec_rates({"encode": {"t_ms": 1.0}}, payload) == \
        (0.0, 0.0)


def test_decompose_stage_crash_keeps_full_rate():
    """A crashing stage variant (fresh compile path on a scarce tunnel
    window) costs that stage only: the full-pipeline rate is banked, the
    error recorded, and no confident model claim is made."""
    def measure(ab):
        if ab == "hbm":
            raise RuntimeError("mosaic compile boom")
        return {None: 10e-3}.get(ab, 2e-3)
    out = ring_cost.decompose(measure, streaming=True,
                              payload_bytes=1 << 20)
    assert out["pipeline_gbps"] > 0 and out["t_ms"] == pytest.approx(10.0)
    assert not out["valid"]
    assert "mosaic" in out["stage_errors"]["hbm"]
    assert "modeled_t_ms" not in out and "pipeline_efficiency" not in out


def test_break_even_serial_vpu_model():
    """The codec bound is the SUM 1/enc + 1/dec (shared VPU): equal
    stage rates of 30 GB/s combine to 15 GB/s, which wins at a 5 GB/s
    link (needs 10) and loses at 12.5 (needs 25) — under the old max()
    model both links would have (wrongly) looked winnable."""
    be = ring_cost.break_even(30.0, 30.0, 3.5, 3.76)
    assert be["combined_codec_gbps"] == pytest.approx(15.0)
    assert be["per_link_rate"]["link_5GBps"]["bfp_wins"]
    assert not be["per_link_rate"]["link_12.5GBps"]["bfp_wins"]
    assert be["per_link_rate"]["link_12.5GBps"][
        "required_codec_gbps_to_win"] == 25.0
    # wire-bound regime: speedup caps at r_fused/2
    fast = ring_cost.break_even(1e6, 1e6, 3.5, 3.76)
    for row in fast["per_link_rate"].values():
        assert row["bfp_speedup_vs_bf16_psum"] == pytest.approx(
            3.5 / 2, abs=0.01)


def test_break_even_zero_rates():
    be = ring_cost.break_even(0.0, 0.0, 3.5, 3.76)
    assert be["combined_codec_gbps"] == 0.0
    assert not any(r["bfp_wins"] for r in be["per_link_rate"].values())


def test_decompose_end_to_end():
    """decompose() against a fake measurement: stage rows, model fields,
    and the artifact-ready rounding all land."""
    times = {None: 10e-3, "skeleton": 1e-3, "encode": 3e-3,
             "decode": 4e-3, "rdma": 6e-3, "hbm": 5e-3}
    out = ring_cost.decompose(lambda ab: times[ab], streaming=True,
                              payload_bytes=12 * (1 << 20))
    assert out["valid"]
    assert set(out["stages"]) == set(ring_cost.STAGES_STREAMING)
    assert out["binding_stage"] == "vpu"              # 3+4-1 = 6.0 == rdma
    assert out["modeled_t_ms"] == pytest.approx(6.0)
    assert out["pipeline_efficiency"] == pytest.approx(0.6)
    assert out["t_ms"] == pytest.approx(10.0)
    assert out["pipeline_gbps"] == pytest.approx(
        12 * (1 << 20) / 10e-3 / 1e9, rel=1e-2)


def test_decompose_failed_full_measurement():
    out = ring_cost.decompose(
        lambda ab: -1.0 if ab is None else 1e-3, streaming=False,
        payload_bytes=1 << 20)
    assert not out["valid"]
    assert "error" in out and "pipeline_gbps" not in out
