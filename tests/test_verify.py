"""graftmc: the exhaustive protocol model checker (fpga_ai_nic_tpu.verify).

Covers the ISSUE-9 battery:
  - op-stream equivalence: the extracted streams against the in-kernel
    `_rs_plan` invariants (RAW/SLOT/CAP) for every route, the jax-free
    twins against their jax-side definitions (intersection_table,
    residual_owners, OptimizerSpec.n_state, plan_hier hop counts);
  - exhaustive-grid green cells (the full envelope behind -m slow);
  - POR-vs-naive state count (>= 5x) and verdict agreement, on clean
    AND mutated cells;
  - counterexample replay: per-node pretty print + Perfetto export;
  - the H1 lockset pass fires on the seeded fixture and stays silent on
    the tree;
  - `make modelcheck` exit codes: green on HEAD, loud on both bad
    fixtures (the J6-style subprocess pattern).
"""

import json
import os
import subprocess
import sys

import pytest

from fpga_ai_nic_tpu.verify import lockset, mc, opstream, replay

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


# ---------------------------------------------------------------------------
# op-stream extraction: plan invariants + single-definition equivalence
# ---------------------------------------------------------------------------

class TestOpStreamInvariants:
    CELLS = [(n, S, D) for n in (2, 3, 4, 6)
             for S in (1, 2, 4, 6) for D in (1, 2, 4, None)]

    def test_rs_plan_is_the_kernel_plan(self):
        """ring_pallas._rs_plan is a delegate: ONE plan definition."""
        from fpga_ai_nic_tpu.ops import ring_pallas as rp
        for n, S, D in self.CELLS:
            assert rp._rs_plan(n, S, D) == opstream.rs_plan(
                n, S, D, default_depth=rp._PIPE_DEPTH)

    def test_rs_op_stream_is_the_kernel_stream(self):
        from fpga_ai_nic_tpu.ops import ring_pallas as rp
        for n, S, D in self.CELLS:
            assert rp._rs_op_stream(n, S, D) == opstream.rs_op_stream(
                n, S, D, default_depth=rp._PIPE_DEPTH)

    @pytest.mark.parametrize("streaming", [False, True])
    def test_raw_slot_cap_invariants(self, streaming):
        """The extracted stream satisfies the three `_rs_plan` schedule
        invariants STRUCTURALLY: CAP (exactly (n-1)*S emissions, each
        send-waited exactly once), RAW (send q after decode q-S), SLOT
        (send q after decode q-n_slots, and guarded by wait_send +
        credit_wait once past the window)."""
        build = (opstream.rs_stream_op_stream if streaming
                 else opstream.rs_op_stream)
        for n, S, D in self.CELLS:
            ops, n_slots = build(n, S, D)
            total = (n - 1) * S
            sends = {op[1]: i for i, op in enumerate(ops)
                     if op[0] == "send"}
            decodes = {op[1]: i for i, op in enumerate(ops)
                       if op[0] == "decode"}
            waits = [op[1] for op in ops if op[0] == "wait_send"]
            assert sorted(sends) == list(range(total))          # CAP
            assert sorted(decodes) == list(range(total))
            assert sorted(waits) == list(range(total))          # 1 wait
            for q, pos in sends.items():
                if q - S >= 0:                                   # RAW
                    assert decodes[q - S] < pos, (n, S, D, q)
                if q - n_slots >= 0:                             # SLOT
                    assert decodes[q - n_slots] < pos, (n, S, D, q)
                    guard = [i for i, op in enumerate(ops)
                             if op[0] == "wait_send"
                             and op[1] == q - n_slots]
                    assert min(guard) < pos, (n, S, D, q)

    @pytest.mark.parametrize("opt", [None, "sgd", "momentum", "adamw"])
    def test_streaming_dma_discipline_clean(self, opt):
        """The extracted streaming stream passes its own DMA discipline
        (single wait, ordered hazards, full drain) at every cell — the
        round-3 hardware-only semaphore deadlock classes, mechanically
        checked."""
        for n, S, D in self.CELLS:
            ops, _ = opstream.rs_stream_op_stream(n, S, D, opt_kind=opt)
            assert opstream.check_dma_discipline(ops) == [], (n, S, D)

    def test_streaming_prefetch_gate(self):
        """ld(q+1) starts before encode(q) exactly when the kernel's
        prefetch gate (launch_first and D+2 <= S) allows it."""
        for n, S, D in self.CELLS:
            ops, _ = opstream.rs_stream_op_stream(n, S, D)
            Dr, _, launch_first = opstream.rs_plan(n, S, D)
            lds = {op[2]: i for i, op in enumerate(ops)
                   if op[0] == "dma_start" and op[1] == "ld"}
            encs = {op[1]: i for i, op in enumerate(ops)
                    if op[0] == "encode"}
            total = (n - 1) * S
            prefetch = launch_first and Dr + 2 <= S
            if total > 1:
                assert (lds[1] < encs[0]) == prefetch, (n, S, D)

    def test_opt_state_counts_match_optimizer_spec(self):
        from fpga_ai_nic_tpu.optim import OptimizerSpec
        for kind, ns in opstream.OPT_N_STATE.items():
            assert OptimizerSpec(kind=kind).n_state == ns

    def test_dma_discipline_catches_dropped_wait(self):
        """Anti-vacuity: deleting one writeback wait must surface as a
        RAW/slot hazard (the class review caught by hand in round 3)."""
        ops, _ = opstream.rs_stream_op_stream(4, 4, 2, opt_kind="adamw")
        mutated = [op for op in ops
                   if op[:3] != ("dma_wait", "wb", 1)]
        msgs = opstream.check_dma_discipline(mutated)
        assert msgs and any("hazard" in m for m in msgs)

    def test_mutated_stream_fails_invariants(self):
        """A stream with one decode dropped must violate (the exhaustive
        checker sees an undecoded frame / ordering corruption)."""
        ops, n_slots = opstream.rs_op_stream(3, 2, 2)
        drop = next(i for i, op in enumerate(ops) if op[0] == "decode")
        model = opstream.RingModel(3, ops[:drop] + ops[drop + 1:],
                                   n_slots, meta={"mut": "no-decode"})
        res = mc.check(model)
        assert not res.ok


class TestHierStream:
    @pytest.mark.parametrize("n,ni", [(4, 2), (6, 2), (6, 3), (6, 1),
                                      (6, 6), (4, 4)])
    def test_hop_counts_match_plan(self, n, ni):
        """The stream's per-node send counts equal the
        HierarchicalPlan's hop structure: (ni-1) intra hops per
        direction, (ng-1) inter hops (sliced on the RS side)."""
        from fpga_ai_nic_tpu.ops import ring_hier
        ng = ring_hier.check_factorization(n, ni)
        for s_inter in (1, 3):
            streams = opstream.hier_op_stream(n, ni, s_inter)
            assert len(streams) == n
            for ops in streams:
                sends = [op for op in ops if op[0] == "send_to"]
                intra = [op for op in sends if op[2][0] == "rs_intra"]
                inter = [op for op in sends if op[2][0] == "rs_inter"]
                ag_inter = [op for op in sends if op[2][0] == "ag_inter"]
                ag_intra = [op for op in sends if op[2][0] == "ag_intra"]
                assert len(intra) == ni - 1
                assert len(inter) == (ng - 1) * s_inter
                assert len(ag_inter) == ng - 1
                assert len(ag_intra) == ni - 1

    def test_handoff_orders_intra_before_inter(self):
        streams = opstream.hier_op_stream(6, 3, 2)
        for ops in streams:
            kinds = [op[2][0] for op in ops if op[0] == "send_to"]
            if "rs_inter" in kinds and "rs_intra" in kinds:
                assert kinds.index("rs_inter") > max(
                    i for i, k in enumerate(kinds) if k == "rs_intra")


class TestReshardStream:
    LAYOUTS = [(48, 6, 8), (48, 8, 6), (37, 5, 7), (37, 7, 5),
               (100, 12, 5), (1, 1, 4), (17, 3, 3)]

    def test_segments_match_intersection_table(self):
        """The jax-free twin partitions exactly like
        parallel.reshard.intersection_table."""
        from fpga_ai_nic_tpu.parallel import reshard
        for live, cs, ct in self.LAYOUTS:
            ours = opstream.reshard_segments(live, cs, ct)
            theirs = reshard.intersection_table(live, cs, ct)
            assert [tuple(t) for t in ours] == [tuple(t) for t in theirs]

    def test_owners_match_residual_owners(self):
        from fpga_ai_nic_tpu.parallel import reshard
        for ns in range(1, 9):
            for nt in range(1, 9):
                assert opstream.reshard_owners(ns, nt) == \
                    reshard.residual_owners(ns, nt)

    def test_layout_matches_make_plan(self):
        """reshard_layout mirrors make_plan's union arithmetic for
        shrink AND grow."""
        from fpga_ai_nic_tpu.parallel import reshard
        for live in (37, 48, 100):
            for ns in (2, 3, 4, 6, 8):
                for nt in (2, 3, 4, 6, 8):
                    if ns == nt:
                        continue
                    padded_src = -(-live // ns) * ns
                    padded_tgt = -(-live // nt) * nt
                    plan = reshard.make_plan(live, ns, padded_src, nt,
                                             padded_tgt, n_flat_leaves=1)
                    cs, ct, nu = mc.reshard_layout(live, ns, nt)
                    assert (cs, ct, nu) == (plan.flat.chunk_src,
                                            plan.flat.chunk_tgt,
                                            plan.flat.n_union)

    def test_wire_sends_match_owner_changes(self):
        for live, ns, nt in ((48, 6, 4), (37, 6, 3), (37, 3, 6)):
            cs, ct, nu = mc.reshard_layout(live, ns, nt)
            owners = opstream.reshard_owners(ns, nt)
            streams = opstream.reshard_op_stream(live, cs, ct, nu, owners)
            sends = sum(1 for ops in streams for op in ops
                        if op[0] == "send_to" and op[2][0] == "seg")
            segs = opstream.reshard_segments(live, cs, ct)
            assert sends == sum(1 for t in segs if t.src != t.dst)
            rsends = sum(1 for ops in streams for op in ops
                         if op[0] == "send_to" and op[2][0] == "resid")
            assert rsends == sum(1 for i, o in enumerate(owners)
                                 if i != o)


# ---------------------------------------------------------------------------
# the exhaustive checker: green cells, POR, violations
# ---------------------------------------------------------------------------

class TestExhaustive:
    @pytest.mark.parametrize("route,cell", [
        ("flat", (6, 6, 4)), ("flat", (2, 1, 1)), ("flat", (5, 3, 3)),
        ("streaming", (6, 6, 4, None)), ("streaming", (6, 6, 4, "adamw")),
        ("streaming", (4, 4, 4, "momentum")),      # D == S branch
        ("hier", (6, 2, 2)), ("hier", (6, 3, 1)),
        ("reshard", (37, 6, 4, True)), ("reshard", (37, 4, 6, True)),
    ])
    def test_corner_cells_green(self, route, cell):
        res, _model = mc.run_cell(route, cell)
        assert res.ok, res.violation
        assert res.states > 0

    def test_por_vs_naive_agree_and_reduce(self):
        """On the reported comparison cells the naive full DFS and the
        POR exploration agree on the verdict and POR explores >= 5x
        fewer states (the acceptance bar; measured ~24-810x)."""
        for cell in mc.COMPARE_CELLS:
            por = mc.check(mc.build_flat(*cell), por=True)
            naive = mc.check(mc.build_flat(*cell), por=False)
            assert por.ok and naive.ok
            assert naive.states >= 5 * por.states, (cell, por.states,
                                                    naive.states)

    def test_por_catches_dropped_wait_recv(self):
        """Regression (review-caught POR soundness hole): a stream with
        one wait_recv dropped leaves its decode unguarded — the
        decode-before-landing interleaving must NOT be merged away by
        an eager landing.  POR must find the ordering violation the
        naive DFS finds."""
        ops, n_slots = opstream.rs_op_stream(3, 2, 1)
        bad = [op for op in ops if op != ("wait_recv", 1)]
        for por in (True, False):
            res = mc.check(opstream.RingModel(3, bad, n_slots), por=por)
            assert not res.ok and res.violation.kind == "ordering", por

    @pytest.mark.parametrize("cell", [(2, 2, 1), (2, 2, 2)])
    def test_mutation_sweep_verdict_agreement_fast(self, cell):
        """Single-op-drop adversarial sweep on small cells: POR and
        naive DFS must agree on EVERY mutant's verdict — the reduction
        may never hide a violation (nor invent one)."""
        self._sweep_cell(cell)

    @pytest.mark.slow
    @pytest.mark.parametrize("cell", [(2, 3, 2), (3, 2, 1), (3, 2, 2)])
    def test_mutation_sweep_verdict_agreement_full(self, cell):
        self._sweep_cell(cell)

    @staticmethod
    def _sweep_cell(cell):
        ops, n_slots = opstream.rs_op_stream(*cell)
        for drop in range(len(ops)):
            mut = ops[:drop] + ops[drop + 1:]
            p = mc.check(opstream.RingModel(cell[0], mut, n_slots),
                         por=True, max_states=300_000)
            q = mc.check(opstream.RingModel(cell[0], mut, n_slots),
                         por=False, max_states=300_000)
            assert not (p.inconclusive or q.inconclusive), (cell, drop)
            assert p.ok == q.ok, (cell, drop, ops[drop],
                                  p.violation, q.violation)

    def test_budget_exhaustion_is_inconclusive_not_a_violation(self):
        """A state-budget hit must be distinguishable from a protocol
        verdict: kind 'budget', CheckResult.inconclusive, and the
        message says inconclusive — never 'deadlock'/'overwrite'."""
        res = mc.check(mc.build_flat(4, 4, 2), por=False, max_states=50)
        assert not res.ok and res.inconclusive
        assert res.violation.kind == "budget"
        assert "INCONCLUSIVE" in str(res.violation)
        # a real violation is NOT inconclusive
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] != "credit_signal"]
        res2 = mc.check(opstream.RingModel(4, bad, n_slots))
        assert not res2.ok and not res2.inconclusive

    def test_por_vs_naive_agree_on_violation(self):
        """The reduction must not hide a violation: on a mutated stream
        both modes find one (kinds may differ by exploration order)."""
        ops, n_slots = opstream.rs_op_stream(3, 2, 2)
        bad = [op for op in ops if op[0] not in
               ("credit_wait", "credit_signal", "credit_drain")]
        m = lambda: opstream.RingModel(3, bad, n_slots)  # noqa: E731
        assert not mc.check(m(), por=True).ok
        assert not mc.check(m(), por=False).ok

    def test_dropped_credit_signal_deadlocks(self):
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] != "credit_signal"]
        res = mc.check(opstream.RingModel(4, bad, n_slots))
        assert not res.ok and res.violation.kind == "deadlock"
        assert "protocol deadlock" in str(res.violation)

    def test_removed_window_recv_overwrites(self):
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] not in
               ("credit_wait", "credit_signal", "credit_drain")]
        res = mc.check(opstream.RingModel(4, bad, n_slots))
        assert not res.ok and res.violation.kind == "recv_overwrite"
        assert "recv-slot overwrite" in str(res.violation)

    def test_shrunk_physical_window_overwrites(self):
        """One fewer physical slot than the protocol's window: an
        overwrite (send side surfaces first — the encode lands on the
        still-in-flight frame)."""
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] not in
               ("credit_wait", "credit_signal", "credit_drain")]
        res = mc.check(opstream.RingModel(4, bad, n_slots - 1))
        assert not res.ok and "overwrite" in str(res.violation)

    def test_mismatched_pair_order_deadlocks(self):
        """PairModel: two nodes receiving before sending (a mismatched
        SPMD order) deadlock."""
        streams = [[("recv_from", 1, ("x",)), ("send_to", 1, ("y",))],
                   [("recv_from", 0, ("y",)), ("send_to", 0, ("x",))]]
        res = mc.check(opstream.PairModel(streams))
        assert not res.ok and res.violation.kind == "deadlock"

    def test_orphan_payload_is_termination_violation(self):
        streams = [[("send_to", 1, ("x",))], []]
        res = mc.check(opstream.PairModel(streams))
        assert not res.ok and res.violation.kind == "termination"
        assert "orphan" in str(res.violation)

    def test_fuzz_backend_matches_exhaustive_on_mutants(self):
        """run_random (the simulate_rs_protocol backend) finds the same
        deadlock the exhaustive mode proves, within a few seeds."""
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] != "credit_signal"]
        with pytest.raises(AssertionError, match="deadlock"):
            for seed in range(8):
                m = opstream.RingModel(4, bad, n_slots)
                m.strict_terminal = False
                mc.run_random(m, seed=seed)

    @pytest.mark.slow
    def test_full_envelope_green(self):
        """The whole `make modelcheck` corpus inside pytest: every cell
        of every route exhaustively clean, POR >= 5x on the reported
        cells, fuzz clean at n=8."""
        findings, stats = mc.run_corpus()
        assert findings == [], [f.format() for f in findings]
        assert stats.cells >= 400
        for cmp in stats.compare:
            assert cmp["agree"] and cmp["reduction"] >= 5.0


# ---------------------------------------------------------------------------
# counterexample replay
# ---------------------------------------------------------------------------

class TestReplay:
    def _violation(self):
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] not in
               ("credit_wait", "credit_signal", "credit_drain")]
        model = opstream.RingModel(
            4, bad, n_slots,
            meta={"route": "flat", "n": 4, "S": 2, "depth": 2})
        res = mc.check(model)
        assert not res.ok and res.violation.trace
        return model, res.violation

    def test_per_node_trace_pretty_print(self):
        _model, v = self._violation()
        text = replay.format_trace(v)
        assert "per-node op trace" in text
        assert "node 0:" in text and "node 3:" in text
        assert "VIOLATION" in text and "recv-slot overwrite" in text

    def test_perfetto_export_structure(self, tmp_path):
        model, v = self._violation()
        trace = replay.perfetto_trace(v)
        events = trace["traceEvents"]
        assert any(e.get("ph") == "i" and "VIOLATION" in e.get("name", "")
                   for e in events)
        # wire transfers ride the queue lane as ticket spans
        assert any(e.get("pid") == 2 and e.get("ph") == "X"
                   for e in events)
        assert trace["otherData"]["stream_header"]["source"] == "graftmc"
        txt, js = replay.export_counterexample(model, v, str(tmp_path))
        assert os.path.exists(txt) and os.path.exists(js)
        with open(js) as fh:
            loaded = json.load(fh)
        assert loaded["traceEvents"]


# ---------------------------------------------------------------------------
# the H1 happens-before/lockset pass
# ---------------------------------------------------------------------------

class TestLockset:
    def test_tree_is_silent(self):
        fs = [f for f in lockset.run_lockset(repo_root=REPO)
              if not f.suppressed]
        assert fs == [], [f.format() for f in fs]

    def test_fires_on_seeded_unlocked_write(self):
        fs = lockset.run_lockset([os.path.join(FIXTURES, "h1_bad.py")])
        assert fs, "H1 must flag the unlocked cross-thread counter"
        assert any("Worker.processed" in f.message for f in fs)
        assert all(f.code == "H1" for f in fs)
        # the single-thread attr next to it stays silent
        assert not any("last_note" in f.message for f in fs)

    def test_silent_when_both_writes_share_the_lock(self):
        fs = lockset.run_lockset([os.path.join(FIXTURES, "h1_good.py")])
        assert fs == [], [f.format() for f in fs]

    def test_sees_the_real_worker_roots(self):
        """Anti-vacuity: on the real tree the pass must discover the
        watchdog worker and callback roots — silence has to come from
        locks, not from a blind call graph."""
        import ast as ast_mod
        from fpga_ai_nic_tpu.lint.engine import ModuleCtx
        graph = lockset._Graph()
        ctxs = []
        for p in lockset.default_scope(REPO):
            text = open(p).read()
            ctxs.append(ModuleCtx(p, text, ast_mod.parse(text)))
        for c in ctxs:
            lockset._collect_fns(c, graph)
        for c in ctxs:
            lockset._collect_instance_types(c, graph)
        for c in ctxs:
            lockset._scan_module(c, graph)
        names = {k[2] for k in graph.worker_roots}
        assert "ElasticTrainer._attempt" in names
        assert any(n.startswith("host") for n in names)  # callback taps
        worker = lockset._reach(graph, graph.worker_roots)
        shared = {(w.cls, w.attr) for w in graph.writes if w.fn in worker}
        assert ("CollectiveStats", "issued") in shared  # R1's territory


# ---------------------------------------------------------------------------
# the strict-annotated set (mypy is absent in this container — the PR-5
# precedent: pin disallow_untyped_defs-cleanliness by AST audit so the
# first real mypy run in CI starts from a verified baseline)
# ---------------------------------------------------------------------------

NEW_STRICT = ["fpga_ai_nic_tpu/parallel/reshard.py",
              "fpga_ai_nic_tpu/tune", "fpga_ai_nic_tpu/verify",
              "fpga_ai_nic_tpu/serve",
              "fpga_ai_nic_tpu/runtime/requests.py"]


class TestStrictAnnotations:
    def _files(self):
        import glob
        out = []
        for entry in NEW_STRICT:
            p = os.path.join(REPO, entry)
            out += [p] if p.endswith(".py") else \
                sorted(glob.glob(os.path.join(p, "*.py")))
        return out

    def test_fully_annotated(self):
        """Every def in the newly-strict modules carries a full
        signature (params + return) — what disallow_untyped_defs /
        disallow_incomplete_defs will enforce once mypy runs."""
        import ast as ast_mod
        gaps = []
        for path in self._files():
            tree = ast_mod.parse(open(path).read())
            for node in ast_mod.walk(tree):
                if not isinstance(node, (ast_mod.FunctionDef,
                                         ast_mod.AsyncFunctionDef)):
                    continue
                a = node.args
                named = a.posonlyargs + a.args + a.kwonlyargs
                missing = [x.arg for i, x in enumerate(named)
                           if x.annotation is None
                           and not (i == 0 and x.arg in ("self", "cls"))]
                for va in (a.vararg, a.kwarg):
                    if va is not None and va.annotation is None:
                        missing.append(va.arg)
                if node.returns is None:
                    missing.append("return")
                if missing:
                    gaps.append((os.path.basename(path), node.lineno,
                                 node.name, missing))
        assert gaps == [], gaps

    def test_strict_sets_do_not_drift(self):
        """pyproject [tool.mypy] files= and graftlint's STRICT_CORE
        (ruff scope) must list the same members."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "graftlint_cli", os.path.join(REPO, "tools", "graftlint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        text = open(os.path.join(REPO, "pyproject.toml")).read()
        for entry in mod.STRICT_CORE:
            assert f'"{entry}"' in text, entry
        for entry in NEW_STRICT:
            assert entry in mod.STRICT_CORE


# ---------------------------------------------------------------------------
# `make modelcheck` exit codes (the J6-style subprocess pattern)
# ---------------------------------------------------------------------------

def _run_mc(env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {}))
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
         "--mc"], cwd=REPO, env=env, capture_output=True, text=True,
        timeout=300)


def _clean_fixture_artifacts():
    adir = os.path.join(REPO, "artifacts")
    for fn in os.listdir(adir):
        if fn.startswith("mc_counterexample_fixture"):
            os.remove(os.path.join(adir, fn))


class TestMakeModelcheckExitCodes:
    def test_green_on_head(self):
        proc = _run_mc()
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "cells exhaustive" in proc.stdout
        assert "POR reduction" in proc.stdout

    def test_dropped_credit_signal_fixture_fails_loudly(self):
        try:
            proc = _run_mc({"GRAFTMC_FIXTURE":
                            os.path.join(FIXTURES, "mc_bad_credit.py")})
            assert proc.returncode != 0, proc.stdout + proc.stderr
            assert "M1:" in proc.stdout
            assert "protocol deadlock" in proc.stdout
        finally:
            _clean_fixture_artifacts()

    def test_shrunk_window_fixture_fails_loudly(self):
        try:
            proc = _run_mc({"GRAFTMC_FIXTURE":
                            os.path.join(FIXTURES, "mc_bad_window.py")})
            assert proc.returncode != 0, proc.stdout + proc.stderr
            assert "M1:" in proc.stdout
            assert "recv-slot overwrite" in proc.stdout
        finally:
            _clean_fixture_artifacts()
