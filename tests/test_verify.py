"""graftmc: the exhaustive protocol model checker (fpga_ai_nic_tpu.verify).

Covers the ISSUE-9 battery plus the ISSUE-14 promotion (graftmc v2):
  - one-definition delegation: every route's kernel/lowering consumes
    the SAME emitter/program object the checker explores — pinned by
    IDENTITY (and consumption-site inspection), not by structural
    comparison of two copies (there is no second copy left to compare);
  - plan invariants (RAW/SLOT/CAP) as properties of the emitted streams;
  - exhaustive-grid green cells across all six routes, integrity
    variants included (the full envelope behind -m slow);
  - the streaming-AG model (the retired "statically asserted" row):
    green over the envelope in both orderings, a recv-slot overwrite on
    the S+1 window shrink, POR-vs-naive agreement on the mutants;
  - the handoff pair model: green cells, deadlock on the hoisted
    verdict wait, orphan on the dropped scatter-wait;
  - M2, the static checksum-weight pass: green on every integrity
    route, red on the per-axis weight-product collision (the PR-12
    class), weights pinned to ops.integrity.hop_weight;
  - POR-vs-naive state count (>= 5x) and verdict agreement, on clean
    AND mutated cells;
  - counterexample replay: per-node pretty print + Perfetto export, now
    for AG- and handoff-shaped streams too;
  - the H1 lockset pass fires on the seeded fixture and stays silent on
    the tree;
  - `make modelcheck` exit codes: green on HEAD, loud on all six bad
    fixtures (the J6-style subprocess pattern), envelope record banked.
"""

import json
import os
import subprocess
import sys

import pytest

from fpga_ai_nic_tpu.verify import lockset, mc, opstream, replay

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


# ---------------------------------------------------------------------------
# one-definition delegation: the lowerings consume THE emitters
# ---------------------------------------------------------------------------

class TestDelegationIdentity:
    """The PR-14 contract: zero surviving hand-transcribed stream
    builders.  Where the lowering can share the object, identity is
    asserted; where it consumes an emitter inside a kernel, the
    consumption site is asserted and local schedule text is banned."""

    def test_rs_plan_is_the_kernel_plan(self):
        from fpga_ai_nic_tpu.ops import ring_pallas as rp
        for n, S, D in [(n, S, D) for n in (2, 3, 4, 6)
                        for S in (1, 2, 4, 6) for D in (1, 2, 4, None)]:
            assert rp._rs_plan(n, S, D) == opstream.rs_plan(
                n, S, D, default_depth=rp._PIPE_DEPTH)

    def test_rs_op_stream_is_the_kernel_stream(self):
        from fpga_ai_nic_tpu.ops import ring_pallas as rp
        for n, S, D in [(4, 2, 2), (6, 6, 4), (3, 4, None)]:
            assert rp._rs_op_stream(n, S, D) == opstream.rs_op_stream(
                n, S, D, default_depth=rp._PIPE_DEPTH)

    def test_ag_schedule_is_the_shared_definition(self):
        from fpga_ai_nic_tpu.ops import ring_pallas as rp
        assert rp._ag_schedule is opstream.ag_schedule

    def test_hier_perms_are_the_shared_definitions(self):
        from fpga_ai_nic_tpu.ops import ring_hier as rh
        assert rh._intra_perm is opstream.intra_perm
        assert rh._inter_perm is opstream.inter_perm

    def test_reshard_table_owners_layout_are_shared(self):
        from fpga_ai_nic_tpu.parallel import reshard
        assert reshard.Transfer is opstream.Seg
        assert reshard.intersection_table is opstream.reshard_segments
        assert reshard.residual_owners is opstream.reshard_owners

    def test_handoff_program_is_shared(self):
        from fpga_ai_nic_tpu.serve import handoff
        assert handoff.handoff_program is opstream.handoff_program

    def test_kernels_consume_the_emitters(self):
        """The Pallas kernels must drive their schedule through the
        shared emitters (prologue/step/epilogue over a sink) and carry
        no local launch/consume/step schedule text of their own — the
        structural-equivalence pins this replaces had exactly that
        drift window."""
        import inspect
        from fpga_ai_nic_tpu.ops import ring_pallas as rp
        for kern, emitter in ((rp._rs_kernel, "RsEmitter"),
                              (rp._rs_stream_kernel, "RsStreamEmitter"),
                              (rp._ag_stream_kernel, "AgStreamEmitter")):
            src = inspect.getsource(kern)
            assert (f"_opstream.{emitter}" in src
                    or "emitter.prologue" in src), emitter
            assert "emitter.prologue(" in src and \
                "emitter.step(" in src and "emitter.epilogue(" in src
            for banned in ("def launch(", "def consume(", "def step("):
                assert banned not in src, (emitter, banned)

    def test_lowerings_consume_the_action_programs(self):
        import inspect
        from fpga_ai_nic_tpu.parallel import reshard
        from fpga_ai_nic_tpu.ops import ring_hier as rh
        assert "_opstream.reshard_leaf_actions" in \
            inspect.getsource(reshard._move_chunk)
        assert "_opstream.reshard_residual_actions" in \
            inspect.getsource(reshard._move_residual)
        assert "_opstream.reshard_msg_bases" in \
            inspect.getsource(reshard.lower_apply)
        assert "_opstream.union_layout" in \
            inspect.getsource(reshard.make_plan)
        assert "_opstream.hier_program" in \
            inspect.getsource(rh.hier_reduce_scatter)
        assert "_opstream.hier_program" in \
            inspect.getsource(rh.hier_all_gather)

    def test_msg_weight_is_hop_weight(self):
        """The IR's jax-free weight formula == ops.integrity.hop_weight
        (one weight scheme, kernel side and host side)."""
        import jax
        from fpga_ai_nic_tpu.ops import integrity
        with jax.default_device(jax.devices("cpu")[0]):
            for msg in (0, 1, 7, 1000, 2**31 - 1):
                assert opstream.msg_weight(msg) == int(
                    integrity.hop_weight(msg))

    def test_ag_n_slots_is_the_call_rule(self):
        import inspect
        from fpga_ai_nic_tpu.ops import ring_pallas as rp
        assert "_opstream.ag_n_slots" in \
            inspect.getsource(rp._ag_stream_call)


# ---------------------------------------------------------------------------
# plan invariants as properties of the emitted streams
# ---------------------------------------------------------------------------

class TestOpStreamInvariants:
    CELLS = [(n, S, D) for n in (2, 3, 4, 6)
             for S in (1, 2, 4, 6) for D in (1, 2, 4, None)]

    @pytest.mark.parametrize("streaming", [False, True])
    def test_raw_slot_cap_invariants(self, streaming):
        """The emitted stream satisfies the three `rs_plan` schedule
        invariants STRUCTURALLY: CAP (exactly (n-1)*S emissions, each
        send-waited exactly once), RAW (send q after decode q-S), SLOT
        (send q after decode q-n_slots, and guarded by wait_send +
        credit_wait once past the window)."""
        build = (opstream.rs_stream_op_stream if streaming
                 else opstream.rs_op_stream)
        for n, S, D in self.CELLS:
            ops, n_slots = build(n, S, D)
            total = (n - 1) * S
            sends = {op[1]: i for i, op in enumerate(ops)
                     if op[0] == "send"}
            decodes = {op[1]: i for i, op in enumerate(ops)
                       if op[0] == "decode"}
            waits = [op[1] for op in ops if op[0] == "wait_send"]
            assert sorted(sends) == list(range(total))          # CAP
            assert sorted(decodes) == list(range(total))
            assert sorted(waits) == list(range(total))          # 1 wait
            for q, pos in sends.items():
                if q - S >= 0:                                   # RAW
                    assert decodes[q - S] < pos, (n, S, D, q)
                if q - n_slots >= 0:                             # SLOT
                    assert decodes[q - n_slots] < pos, (n, S, D, q)
                    guard = [i for i, op in enumerate(ops)
                             if op[0] == "wait_send"
                             and op[1] == q - n_slots]
                    assert min(guard) < pos, (n, S, D, q)

    @pytest.mark.parametrize("opt", [None, "sgd", "momentum", "adamw"])
    def test_streaming_dma_discipline_clean(self, opt):
        """The emitted streaming stream passes its own DMA discipline
        (single wait, ordered hazards, full drain) at every cell,
        integrity on or off — the round-3 hardware-only semaphore
        deadlock classes, mechanically checked."""
        for n, S, D in self.CELLS:
            for integ in (False, True):
                ops, _ = opstream.rs_stream_op_stream(
                    n, S, D, opt_kind=opt, integrity=integ)
                assert opstream.check_dma_discipline(ops) == [], \
                    (n, S, D, integ)

    def test_ag_dma_discipline_clean(self):
        for n in (2, 3, 4, 6):
            for S in (1, 2, 4, 6):
                for lockstep in (False, True):
                    ops, _ = opstream.ag_op_stream(n, S,
                                                   lockstep=lockstep)
                    assert opstream.check_dma_discipline(ops) == [], \
                        (n, S, lockstep)

    def test_opt_state_counts_match_optimizer_spec(self):
        from fpga_ai_nic_tpu.optim import OptimizerSpec
        for kind, ns in opstream.OPT_N_STATE.items():
            assert OptimizerSpec(kind=kind).n_state == ns

    def test_dma_discipline_catches_dropped_wait(self):
        """Anti-vacuity: deleting one writeback wait must surface as a
        RAW/slot hazard (the class review caught by hand in round 3)."""
        ops, _ = opstream.rs_stream_op_stream(4, 4, 2, opt_kind="adamw")
        mutated = [op for op in ops
                   if op[:3] != ("dma_wait", "wb", 1)]
        msgs = opstream.check_dma_discipline(mutated)
        assert msgs and any("hazard" in m for m in msgs)

    def test_mutated_stream_fails_invariants(self):
        """A stream with one decode dropped must violate (the exhaustive
        checker sees an undecoded frame / ordering corruption)."""
        ops, n_slots = opstream.rs_op_stream(3, 2, 2)
        drop = next(i for i, op in enumerate(ops) if op[0] == "decode")
        model = opstream.RingModel(3, ops[:drop] + ops[drop + 1:],
                                   n_slots, meta={"mut": "no-decode"})
        res = mc.check(model)
        assert not res.ok

    def test_ag_schedule_emission_order_matches_execution(self):
        """P3: emission indices follow the executed per-step order (the
        forward fires inside consume(m), the next own slice after) —
        the one-credit under-wait graftmc's first AG run caught would
        reappear exactly here."""
        for n in (3, 4, 5, 6):
            for S in (2, 4, 5, 6):
                (content, fwd_j, own_at, own_j, _own_js,
                 _tails) = opstream.ag_schedule(n, S,
                                                opstream.ag_n_slots(n, S))
                for m in range((n - 1) * S):
                    if fwd_j[m] >= 0 and own_at[m] >= 0:
                        assert fwd_j[m] < own_j[own_at[m]], (n, S, m)


class TestHierStream:
    def test_streams_expand_the_program(self):
        """The checker's per-node expansion is internally consistent
        with `hier_program` (hops x slices per phase) — a sanity check
        on the derivation, NOT an equivalence pin against a second
        definition (ring_hier consumes the same program)."""
        for n, ni, s in [(4, 2, 1), (6, 2, 3), (6, 3, 2), (6, 1, 1),
                         (6, 6, 1)]:
            prog = opstream.hier_program(n, ni, s)
            streams = opstream.hier_op_stream(n, ni, s)
            assert len(streams) == n
            for ops in streams:
                sends = [op for op in ops if op[0] == "send_to"]
                per = {k: sum(1 for op in sends if op[2][0] == k)
                       for k in ("rs_intra", "rs_inter", "ag_inter",
                                 "ag_intra")}
                assert per["rs_intra"] == prog.rs_intra.hops
                assert per["rs_inter"] == \
                    prog.rs_inter.hops * prog.rs_inter.slices
                assert per["ag_inter"] == prog.ag_inter.hops
                assert per["ag_intra"] == prog.ag_intra.hops

    def test_handoff_orders_intra_before_inter(self):
        streams = opstream.hier_op_stream(6, 3, 2)
        for ops in streams:
            kinds = [op[2][0] for op in ops if op[0] == "send_to"]
            if "rs_inter" in kinds and "rs_intra" in kinds:
                assert kinds.index("rs_inter") > max(
                    i for i, k in enumerate(kinds) if k == "rs_intra")

    def test_rs_carry_messages_are_distinct(self):
        """The program's shared RS carry (intra + sliced inter) never
        reuses a message id — the aliasing class M2 freezes."""
        prog = opstream.hier_program(6, 2, 3)
        msgs = [prog.rs_intra.msg(s) for s in range(prog.rs_intra.hops)]
        msgs += [prog.rs_inter.msg(s, k)
                 for s in range(prog.rs_inter.hops)
                 for k in range(prog.rs_inter.slices)]
        assert len(msgs) == len(set(msgs))


class TestReshardStream:
    def test_layout_matches_make_plan(self):
        """mc.reshard_layout (the grid-cell view) and make_plan both
        consume opstream.union_layout — pinned end to end."""
        from fpga_ai_nic_tpu.parallel import reshard
        for live in (37, 48, 100):
            for ns in (2, 3, 4, 6, 8):
                for nt in (2, 3, 4, 6, 8):
                    if ns == nt:
                        continue
                    padded_src = -(-live // ns) * ns
                    padded_tgt = -(-live // nt) * nt
                    plan = reshard.make_plan(live, ns, padded_src, nt,
                                             padded_tgt, n_flat_leaves=1)
                    cs, ct, nu = mc.reshard_layout(live, ns, nt)
                    assert (cs, ct, nu) == (plan.flat.chunk_src,
                                            plan.flat.chunk_tgt,
                                            plan.flat.n_union)

    def test_wire_sends_match_owner_changes(self):
        for live, ns, nt in ((48, 6, 4), (37, 6, 3), (37, 3, 6)):
            cs, ct, nu = mc.reshard_layout(live, ns, nt)
            owners = opstream.reshard_owners(ns, nt)
            streams = opstream.reshard_op_stream(live, cs, ct, nu, owners)
            sends = sum(1 for ops in streams for op in ops
                        if op[0] == "send_to" and op[2][0] == "seg")
            segs = opstream.reshard_segments(live, cs, ct)
            assert sends == sum(1 for t in segs if t.src != t.dst)
            rsends = sum(1 for ops in streams for op in ops
                         if op[0] == "send_to" and op[2][0] == "resid")
            assert rsends == sum(1 for i, o in enumerate(owners)
                                 if i != o)

    def test_multi_leaf_messages_are_distinct(self):
        """Across leaves + residual, every wire message id is unique
        (reshard_msg_bases) — the cross-leaf weight-product collision
        class."""
        streams = opstream.reshard_op_stream(
            37, *mc.reshard_layout(37, 6, 4),
            residual_owners_map=opstream.reshard_owners(6, 4),
            n_flat_leaves=3, integrity=True)
        msgs = [op[2] for ops in streams for op in ops
                if op[0] == "chk_emit"]
        assert msgs and len(msgs) == len(set(msgs))


# ---------------------------------------------------------------------------
# M2: the static checksum-weight pass
# ---------------------------------------------------------------------------

class TestM2WeightPass:
    def test_green_on_every_integrity_route(self):
        assert opstream.check_weight_conservation(
            opstream.rs_op_stream(4, 2, 2, integrity=True)[0]) == []
        assert opstream.check_weight_conservation(
            opstream.rs_stream_op_stream(4, 4, 2, opt_kind="adamw",
                                         integrity=True)[0]) == []
        assert opstream.check_weight_conservation(
            opstream.hier_op_stream(6, 2, 3, integrity=True)) == []
        assert opstream.check_weight_conservation(
            opstream.reshard_op_stream(
                37, *mc.reshard_layout(37, 6, 4),
                residual_owners_map=opstream.reshard_owners(6, 4),
                n_flat_leaves=2, integrity=True)) == []
        assert opstream.check_weight_conservation(
            opstream.handoff_op_stream(2, integrity=True)) == []

    def test_collision_rejected(self):
        """Two distinct messages sharing a weight — the PR-12 per-axis
        product class — must be an M2 finding."""
        a, b = opstream.ListSink(), opstream.ListSink()
        for s in range(2):
            for k in range(2):
                w = (2 * s + 1) * (2 * k + 1)
                a.chk_emit((s, k), weight=w)
                b.chk_arrive((s, k), weight=w)
        msgs = opstream.check_weight_conservation([a.ops, b.ops])
        assert any("weight collision" in m for m in msgs)

    def test_even_weight_rejected(self):
        a = opstream.ListSink()
        a.chk_emit(0, weight=4)
        a.chk_arrive(0, weight=4)
        msgs = opstream.check_weight_conservation(a.ops)
        assert any("EVEN weight" in m for m in msgs)

    def test_unpaired_emission_rejected(self):
        a = opstream.ListSink()
        a.chk_emit(0)
        msgs = opstream.check_weight_conservation(a.ops)
        assert any("arrival" in m for m in msgs)

    def test_mismatched_pair_weight_rejected(self):
        a = opstream.ListSink()
        a.chk_emit(0, weight=1)
        a.chk_arrive(0, weight=3)
        msgs = opstream.check_weight_conservation(a.ops)
        assert any("inconsistently" in m for m in msgs)

    def test_carries_are_independent(self):
        """hier's RS and AG carries legally reuse msg 0 — M2 must not
        cross-flag them (weights are program-distinct PER CARRY)."""
        a = opstream.ListSink()
        a.chk_emit(0, carry="rs")
        a.chk_arrive(0, carry="rs")
        a.chk_emit(0, carry="ag")
        a.chk_arrive(0, carry="ag")
        assert opstream.check_weight_conservation(a.ops) == []

    def test_runs_inside_run_cell(self):
        """run_cell applies M2 statically — a weight-colliding model is
        rejected with kind 'weights' before any exploration."""
        a, b = opstream.ListSink(), opstream.ListSink()
        for s in range(2):
            for k in range(2):
                w = (2 * s + 1) * (2 * k + 1)
                a.chk_emit((s, k), weight=w)
                a.ops.append(("send_to", 1, ("hop", s, k)))
                b.ops.append(("recv_from", 0, ("hop", s, k)))
                b.chk_arrive((s, k), weight=w)
        model = opstream.PairModel([a.ops, b.ops])
        static = mc._static_violations(model)
        assert static and static[0][0] == "weights"


# ---------------------------------------------------------------------------
# the exhaustive checker: green cells, POR, violations
# ---------------------------------------------------------------------------

class TestExhaustive:
    @pytest.mark.parametrize("route,cell", [
        ("flat", (6, 6, 4, False)), ("flat", (2, 1, 1, False)),
        ("flat", (5, 3, 3, True)),
        ("streaming", (6, 6, 4, None, False)),
        ("streaming", (6, 6, 4, "adamw", True)),
        ("streaming", (4, 4, 4, "momentum", False)),   # D == S branch
        ("ag", (6, 6)), ("ag", (2, 1)), ("ag", (5, 5)), ("ag", (3, 3)),
        ("hier", (6, 2, 2, True)), ("hier", (6, 3, 1, False)),
        ("reshard", (37, 6, 4, True, True)),
        ("reshard", (37, 4, 6, True, False)),
        ("handoff", (2, True)), ("handoff", (3, False)),
    ])
    def test_corner_cells_green(self, route, cell):
        res, _model = mc.run_cell(route, cell)
        assert res.ok, res.violation
        assert res.states > 0

    def test_por_vs_naive_agree_and_reduce(self):
        """On the reported comparison cells the naive full DFS and the
        POR exploration agree on the verdict and POR explores >= 5x
        fewer states (the acceptance bar; measured ~28-1142x)."""
        for cell in mc.COMPARE_CELLS:
            por = mc.check(mc.build_flat(*cell), por=True)
            naive = mc.check(mc.build_flat(*cell), por=False)
            assert por.ok and naive.ok
            assert naive.states >= 5 * por.states, (cell, por.states,
                                                    naive.states)

    def test_por_catches_dropped_wait_recv(self):
        """Regression (review-caught POR soundness hole): a stream with
        one wait_recv dropped leaves its decode unguarded — the
        decode-before-landing interleaving must NOT be merged away by
        an eager landing.  POR must find the ordering violation the
        naive DFS finds."""
        ops, n_slots = opstream.rs_op_stream(3, 2, 1)
        bad = [op for op in ops if op != ("wait_recv", 1)]
        for por in (True, False):
            res = mc.check(opstream.RingModel(3, bad, n_slots), por=por)
            assert not res.ok and res.violation.kind == "ordering", por

    @pytest.mark.parametrize("cell", [(2, 2, 1), (2, 2, 2)])
    def test_mutation_sweep_verdict_agreement_fast(self, cell):
        """Single-op-drop adversarial sweep on small cells: POR and
        naive DFS must agree on EVERY mutant's verdict — the reduction
        may never hide a violation (nor invent one)."""
        ops, n_slots = opstream.rs_op_stream(*cell)
        self._sweep(cell[0], ops, n_slots)

    @pytest.mark.slow
    @pytest.mark.parametrize("cell", [(2, 3, 2), (3, 2, 1), (3, 2, 2)])
    def test_mutation_sweep_verdict_agreement_full(self, cell):
        ops, n_slots = opstream.rs_op_stream(*cell)
        self._sweep(cell[0], ops, n_slots)

    def test_ag_mutation_sweep_verdict_agreement_fast(self):
        """The same adversarial single-op-drop sweep on the NEW route:
        POR-vs-naive agreement pinned on the AG mutants too."""
        ops, n_slots = opstream.ag_op_stream(2, 2)
        self._sweep(2, ops, n_slots)

    @pytest.mark.slow
    def test_ag_mutation_sweep_verdict_agreement_full(self):
        """The n=3 AG sweep: some mutants explode the naive DFS (three
        nodes x interleaved emissions), so this rides -m slow with a
        bigger naive budget."""
        ops, n_slots = opstream.ag_op_stream(3, 2)
        self._sweep(3, ops, n_slots, max_states=3_000_000)

    @staticmethod
    def _sweep(n, ops, n_slots, max_states=300_000):
        for drop in range(len(ops)):
            mut = ops[:drop] + ops[drop + 1:]
            p = mc.check(opstream.RingModel(n, mut, n_slots),
                         por=True, max_states=max_states)
            q = mc.check(opstream.RingModel(n, mut, n_slots),
                         por=False, max_states=max_states)
            assert not (p.inconclusive or q.inconclusive), (n, drop)
            assert p.ok == q.ok, (n, drop, ops[drop],
                                  p.violation, q.violation)

    def test_budget_exhaustion_is_inconclusive_not_a_violation(self):
        """A state-budget hit must be distinguishable from a protocol
        verdict: kind 'budget', CheckResult.inconclusive, and the
        message says inconclusive — never 'deadlock'/'overwrite'."""
        res = mc.check(mc.build_flat(4, 4, 2), por=False, max_states=50)
        assert not res.ok and res.inconclusive
        assert res.violation.kind == "budget"
        assert "INCONCLUSIVE" in str(res.violation)
        # a real violation is NOT inconclusive
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] != "credit_signal"]
        res2 = mc.check(opstream.RingModel(4, bad, n_slots))
        assert not res2.ok and not res2.inconclusive

    def test_por_vs_naive_agree_on_violation(self):
        """The reduction must not hide a violation: on a mutated stream
        both modes find one (kinds may differ by exploration order)."""
        ops, n_slots = opstream.rs_op_stream(3, 2, 2)
        bad = [op for op in ops if op[0] not in
               ("credit_wait", "credit_signal", "credit_drain")]
        m = lambda: opstream.RingModel(3, bad, n_slots)  # noqa: E731
        assert not mc.check(m(), por=True).ok
        assert not mc.check(m(), por=False).ok

    def test_dropped_credit_signal_deadlocks(self):
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] != "credit_signal"]
        res = mc.check(opstream.RingModel(4, bad, n_slots))
        assert not res.ok and res.violation.kind == "deadlock"
        assert "protocol deadlock" in str(res.violation)

    def test_removed_window_recv_overwrites(self):
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] not in
               ("credit_wait", "credit_signal", "credit_drain")]
        res = mc.check(opstream.RingModel(4, bad, n_slots))
        assert not res.ok and res.violation.kind == "recv_overwrite"
        assert "recv-slot overwrite" in str(res.violation)

    def test_shrunk_physical_window_overwrites(self):
        """One fewer physical slot than the protocol's window: an
        overwrite (send side surfaces first — the encode lands on the
        still-in-flight frame)."""
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] not in
               ("credit_wait", "credit_signal", "credit_drain")]
        res = mc.check(opstream.RingModel(4, bad, n_slots - 1))
        assert not res.ok and "overwrite" in str(res.violation)

    def test_ag_window_shrunk_to_s_plus_1_overwrites(self):
        """The ISSUE-14 AG mutant: the emitted S+2-window protocol run
        against S+1 physical slots must produce a recv-slot-overwrite
        counterexample, with POR and naive DFS agreeing there is a
        violation."""
        ops, n_slots = opstream.ag_op_stream(4, 4)
        por = mc.check(opstream.RingModel(4, ops, n_slots - 1))
        assert not por.ok and por.violation.kind == "recv_overwrite"
        assert "recv-slot overwrite" in str(por.violation)
        naive = mc.check(opstream.RingModel(4, ops, n_slots - 1),
                         por=False, max_states=1_500_000)
        assert not naive.ok and not naive.inconclusive

    def test_ag_integrity_of_fixed_schedule(self):
        """Regression for the fwd/own emission-index inversion graftmc
        caught on its first AG run (a one-credit under-wait -> recv
        overwrite at (5,5)/(6,5)/(6,6) under the OLD schedule): those
        exact cells must now be green."""
        for cell in ((5, 5), (6, 5), (6, 6)):
            res, _ = mc.run_cell("ag", cell)
            assert res.ok, (cell, res.violation)

    def test_handoff_dropped_scatter_wait_orphans(self):
        """Dropping the destination's per-block recvs leaves every sent
        page block landed-but-never-consumed — the ordering-corruption
        class (sends never block, so the SOURCE cannot deadlock); POR
        and naive agree."""
        src, dst = opstream.handoff_op_stream(2, integrity=True)
        bad_dst = [op for op in dst
                   if not (op[0] == "recv_from" and op[2][0] == "pool")]
        for por in (True, False):
            res = mc.check(opstream.PairModel([src, bad_dst]), por=por)
            assert not res.ok and res.violation.kind == "termination"
            assert "orphan" in str(res.violation)

    def test_handoff_hoisted_verdict_wait_deadlocks(self):
        """Hoisting the source's verdict wait ahead of its page sends is
        a wait-for cycle across the pair — deadlock, in both modes."""
        src, dst = opstream.handoff_op_stream(2, integrity=True)
        vote_wait = ("recv_from", 1, ("vote", 1))
        bad_src = [vote_wait] + [op for op in src if op != vote_wait]
        for por in (True, False):
            res = mc.check(opstream.PairModel([bad_src, dst]), por=por)
            assert not res.ok and res.violation.kind == "deadlock"

    def test_mismatched_pair_order_deadlocks(self):
        """PairModel: two nodes receiving before sending (a mismatched
        SPMD order) deadlock."""
        streams = [[("recv_from", 1, ("x",)), ("send_to", 1, ("y",))],
                   [("recv_from", 0, ("y",)), ("send_to", 0, ("x",))]]
        res = mc.check(opstream.PairModel(streams))
        assert not res.ok and res.violation.kind == "deadlock"

    def test_orphan_payload_is_termination_violation(self):
        streams = [[("send_to", 1, ("x",))], []]
        res = mc.check(opstream.PairModel(streams))
        assert not res.ok and res.violation.kind == "termination"
        assert "orphan" in str(res.violation)

    def test_fuzz_backend_matches_exhaustive_on_mutants(self):
        """run_random (the simulate_rs_protocol backend) finds the same
        deadlock the exhaustive mode proves, within a few seeds."""
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] != "credit_signal"]
        with pytest.raises(AssertionError, match="deadlock"):
            for seed in range(8):
                m = opstream.RingModel(4, bad, n_slots)
                m.strict_terminal = False
                mc.run_random(m, seed=seed)

    @pytest.mark.slow
    def test_full_envelope_green(self):
        """The whole `make modelcheck` corpus inside pytest: every cell
        of every route (integrity variants included) exhaustively
        clean, POR >= 5x on the reported cells, fuzz clean at n=8, the
        envelope record well-formed."""
        findings, stats = mc.run_corpus()
        assert findings == [], [f.format() for f in findings]
        assert stats.cells >= 900
        assert {r.route for r in stats.routes} == {
            "flat", "streaming", "ag", "hier", "reshard", "handoff",
            "gather", "sched"}
        for cmp in stats.compare:
            assert cmp["agree"] and cmp["reduction"] >= 5.0
        rec = mc.envelope_record(stats)
        assert rec["total_cells"] == stats.cells
        assert sum(r["states"] for r in rec["routes"]) == stats.states


# ---------------------------------------------------------------------------
# counterexample replay
# ---------------------------------------------------------------------------

class TestReplay:
    def _violation(self):
        ops, n_slots = opstream.rs_op_stream(4, 2, 2)
        bad = [op for op in ops if op[0] not in
               ("credit_wait", "credit_signal", "credit_drain")]
        model = opstream.RingModel(
            4, bad, n_slots,
            meta={"route": "flat", "n": 4, "S": 2, "depth": 2})
        res = mc.check(model)
        assert not res.ok and res.violation.trace
        return model, res.violation

    def test_per_node_trace_pretty_print(self):
        _model, v = self._violation()
        text = replay.format_trace(v)
        assert "per-node op trace" in text
        assert "node 0:" in text and "node 3:" in text
        assert "VIOLATION" in text and "recv-slot overwrite" in text

    def test_perfetto_export_structure(self, tmp_path):
        model, v = self._violation()
        trace = replay.perfetto_trace(v)
        events = trace["traceEvents"]
        assert any(e.get("ph") == "i" and "VIOLATION" in e.get("name", "")
                   for e in events)
        # wire transfers ride the queue lane as ticket spans
        assert any(e.get("pid") == 2 and e.get("ph") == "X"
                   for e in events)
        assert trace["otherData"]["stream_header"]["source"] == "graftmc"
        txt, js = replay.export_counterexample(model, v, str(tmp_path))
        assert os.path.exists(txt) and os.path.exists(js)
        with open(js) as fh:
            loaded = json.load(fh)
        assert loaded["traceEvents"]

    def test_ag_violation_replays_with_lane_and_tickets(self, tmp_path):
        """The ISSUE-14 replay satellite: an AG counterexample (RingModel
        trace with dma/local/interleaved-emission ops) exports with
        per-node lanes AND wire ticket spans."""
        ops, n_slots = opstream.ag_op_stream(4, 4)
        model = opstream.RingModel(4, ops, n_slots - 1,
                                   meta={"route": "ag", "n": 4, "S": 4})
        res = mc.check(model)
        assert not res.ok and res.violation.kind == "recv_overwrite"
        text = replay.format_trace(res.violation)
        assert "per-node op trace" in text and "node 3:" in text
        trace = replay.perfetto_trace(res.violation)
        events = trace["traceEvents"]
        # every node appears as a host-thread lane (the exporter's tids
        # are 1-based); wire tickets carry the emission between send
        # and landing on the queue lane
        lanes = {e.get("tid") for e in events
                 if e.get("pid") != 2 and e.get("tid") is not None}
        assert len(lanes) >= 4, lanes
        tickets = [e for e in events
                   if e.get("pid") == 2 and e.get("ph") == "X"]
        assert tickets
        txt, js = replay.export_counterexample(model, res.violation,
                                               str(tmp_path))
        assert os.path.exists(txt) and os.path.exists(js)

    def test_handoff_violation_replays_with_pair_tickets(self, tmp_path):
        """A handoff counterexample (PairModel trace, tagged payloads)
        exports with (src->dst, tag) ticket structure."""
        src, dst = opstream.handoff_op_stream(2, integrity=True)
        vote_wait = ("recv_from", 1, ("vote", 1))
        bad_src = [vote_wait] + [op for op in src if op != vote_wait]
        model = opstream.PairModel([bad_src, dst],
                                   meta={"route": "handoff",
                                         "n_layers": 2})
        res = mc.check(model)
        assert not res.ok and res.violation.kind == "deadlock"
        text = replay.format_trace(res.violation)
        assert "per-node op trace" in text
        trace = replay.perfetto_trace(res.violation)
        events = trace["traceEvents"]
        assert any("VIOLATION" in e.get("name", "") for e in events)
        txt, js = replay.export_counterexample(model, res.violation,
                                               str(tmp_path))
        assert os.path.exists(txt) and os.path.exists(js)
        assert "handoff" in os.path.basename(txt)


# ---------------------------------------------------------------------------
# the H1 happens-before/lockset pass
# ---------------------------------------------------------------------------

class TestLockset:
    def test_tree_is_silent(self):
        fs = [f for f in lockset.run_lockset(repo_root=REPO)
              if not f.suppressed]
        assert fs == [], [f.format() for f in fs]

    def test_fires_on_seeded_unlocked_write(self):
        fs = lockset.run_lockset([os.path.join(FIXTURES, "h1_bad.py")])
        assert fs, "H1 must flag the unlocked cross-thread counter"
        assert any("Worker.processed" in f.message for f in fs)
        assert all(f.code == "H1" for f in fs)
        # the single-thread attr next to it stays silent
        assert not any("last_note" in f.message for f in fs)

    def test_silent_when_both_writes_share_the_lock(self):
        fs = lockset.run_lockset([os.path.join(FIXTURES, "h1_good.py")])
        assert fs == [], [f.format() for f in fs]

    def test_sees_the_real_worker_roots(self):
        """Anti-vacuity: on the real tree the pass must discover the
        watchdog worker and callback roots — silence has to come from
        locks, not from a blind call graph."""
        import ast as ast_mod
        from fpga_ai_nic_tpu.lint.engine import ModuleCtx
        graph = lockset._Graph()
        ctxs = []
        for p in lockset.default_scope(REPO):
            text = open(p).read()
            ctxs.append(ModuleCtx(p, text, ast_mod.parse(text)))
        for c in ctxs:
            lockset._collect_fns(c, graph)
        for c in ctxs:
            lockset._collect_instance_types(c, graph)
        for c in ctxs:
            lockset._scan_module(c, graph)
        names = {k[2] for k in graph.worker_roots}
        assert "ElasticTrainer._attempt" in names
        assert any(n.startswith("host") for n in names)  # callback taps
        worker = lockset._reach(graph, graph.worker_roots)
        shared = {(w.cls, w.attr) for w in graph.writes if w.fn in worker}
        assert ("CollectiveStats", "issued") in shared  # R1's territory


# ---------------------------------------------------------------------------
# the strict-annotated set (mypy is absent in this container — the PR-5
# precedent: pin disallow_untyped_defs-cleanliness by AST audit so the
# first real mypy run in CI starts from a verified baseline)
# ---------------------------------------------------------------------------

NEW_STRICT = ["fpga_ai_nic_tpu/parallel/reshard.py",
              "fpga_ai_nic_tpu/utils/checkpoint.py",
              "fpga_ai_nic_tpu/tune", "fpga_ai_nic_tpu/verify",
              "fpga_ai_nic_tpu/serve",
              "fpga_ai_nic_tpu/runtime/requests.py"]


class TestStrictAnnotations:
    def _files(self):
        import glob
        out = []
        for entry in NEW_STRICT:
            p = os.path.join(REPO, entry)
            out += [p] if p.endswith(".py") else \
                sorted(glob.glob(os.path.join(p, "*.py")))
        return out

    def test_fully_annotated(self):
        """Every def in the newly-strict modules carries a full
        signature (params + return) — what disallow_untyped_defs /
        disallow_incomplete_defs will enforce once mypy runs."""
        import ast as ast_mod
        gaps = []
        for path in self._files():
            tree = ast_mod.parse(open(path).read())
            for node in ast_mod.walk(tree):
                if not isinstance(node, (ast_mod.FunctionDef,
                                         ast_mod.AsyncFunctionDef)):
                    continue
                a = node.args
                named = a.posonlyargs + a.args + a.kwonlyargs
                missing = [x.arg for i, x in enumerate(named)
                           if x.annotation is None
                           and not (i == 0 and x.arg in ("self", "cls"))]
                for va in (a.vararg, a.kwarg):
                    if va is not None and va.annotation is None:
                        missing.append(va.arg)
                if node.returns is None:
                    missing.append("return")
                if missing:
                    gaps.append((os.path.basename(path), node.lineno,
                                 node.name, missing))
        assert gaps == [], gaps

    def test_strict_sets_do_not_drift(self):
        """pyproject [tool.mypy] files= and graftlint's STRICT_CORE
        (ruff scope) must list the same members."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "graftlint_cli", os.path.join(REPO, "tools", "graftlint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        text = open(os.path.join(REPO, "pyproject.toml")).read()
        for entry in mod.STRICT_CORE:
            assert f'"{entry}"' in text, entry
        for entry in NEW_STRICT:
            assert entry in mod.STRICT_CORE


# ---------------------------------------------------------------------------
# `make modelcheck` exit codes (the J6-style subprocess pattern)
# ---------------------------------------------------------------------------

def _run_mc(env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", GRAFTMC_NO_BANK="1",
               **(env_extra or {}))
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
         "--mc"], cwd=REPO, env=env, capture_output=True, text=True,
        timeout=300)


def _clean_fixture_artifacts():
    adir = os.path.join(REPO, "artifacts")
    for fn in os.listdir(adir):
        if fn.startswith("mc_counterexample_fixture"):
            os.remove(os.path.join(adir, fn))


class TestMakeModelcheckExitCodes:
    def test_green_on_head(self):
        proc = _run_mc()
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "cells exhaustive" in proc.stdout
        assert "POR reduction" in proc.stdout
        for route in ("flat", "streaming", "ag", "hier", "reshard",
                      "handoff", "gather", "sched"):
            assert f"route {route}:" in proc.stdout

    def _fixture_fails(self, name, needle, env_extra=None):
        # fixture-only runs skip the corpus (it is green-tested once
        # above; re-paying ~5 s per mutant would push tier-1 past its
        # wall budget) — the exit-code contract is the fixture's
        try:
            proc = _run_mc({"GRAFTMC_FIXTURE":
                            os.path.join(FIXTURES, name),
                            "GRAFTMC_SKIP_CORPUS": "1",
                            **(env_extra or {})})
            assert proc.returncode != 0, proc.stdout + proc.stderr
            assert needle in proc.stdout, proc.stdout
            return proc
        finally:
            _clean_fixture_artifacts()

    def test_dropped_credit_signal_fixture_fails_loudly(self):
        proc = self._fixture_fails("mc_bad_credit.py",
                                   "protocol deadlock")
        assert "M1:" in proc.stdout

    def test_shrunk_window_fixture_fails_loudly(self):
        proc = self._fixture_fails("mc_bad_window.py",
                                   "recv-slot overwrite")
        assert "M1:" in proc.stdout

    def test_ag_window_fixture_fails_loudly(self):
        proc = self._fixture_fails("mc_bad_ag_window.py",
                                   "recv-slot overwrite")
        assert "M1:" in proc.stdout

    def test_handoff_wait_fixture_fails_loudly(self):
        proc = self._fixture_fails("mc_bad_handoff_wait.py",
                                   "orphan payload")
        assert "M1:" in proc.stdout

    def test_handoff_order_fixture_fails_loudly(self):
        proc = self._fixture_fails("mc_bad_handoff_order.py",
                                   "protocol deadlock")
        assert "M1:" in proc.stdout

    def test_weight_collision_fixture_fails_loudly(self):
        proc = self._fixture_fails("mc_bad_weights.py",
                                   "weight collision")
        assert "M2:" in proc.stdout

    def test_sched_leaked_eviction_fixture_fails_loudly(self):
        proc = self._fixture_fails("mc_sched_leak.py",
                                   "page ledger broken")
        assert "M1:" in proc.stdout

    def test_sched_overcommit_fixture_fails_loudly(self):
        proc = self._fixture_fails("mc_sched_overcommit.py",
                                   "over-commit")
        assert "M1:" in proc.stdout

    def test_envelope_artifact_schema(self):
        """The committed envelope record (MC_ENVELOPE_r*.json) carries
        the per-route rows obs-gate's mc.* keys extract."""
        import glob
        banked = sorted(glob.glob(os.path.join(REPO,
                                               "MC_ENVELOPE_r*.json")))
        assert banked, "make modelcheck must bank MC_ENVELOPE_r*.json"
        with open(banked[-1]) as fh:
            d = json.load(fh)
        routes = {r["route"] for r in d["routes"]}
        assert routes == {"flat", "streaming", "ag", "hier", "reshard",
                          "handoff", "gather", "sched"}
        for r in d["routes"]:
            assert r["cells"] > 0 and r["states"] > 0
        assert d["failures"] == 0 and d["ok"]
        assert d["wall_s"] <= d["wall_budget_s"]
        assert all(c["agree"] and c["reduction"] >= 5.0
                   for c in d["compare"])
