"""BERT + bucketed DDP all-reduce (BASELINE.json config 4).

Verifies: bucket planning (reverse-leaf issue order, BFP padding), bucketed
all-reduce == per-leaf psum mean, the DDP trainer against a single-device
reference SGD step, masked-token loss weighting under dp, and convergence
with the BFP-compressed bucketed ring.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from fpga_ai_nic_tpu.models import bert
from fpga_ai_nic_tpu.ops import bucketed
from fpga_ai_nic_tpu.parallel import DDPTrainer, make_mesh
from fpga_ai_nic_tpu.utils.config import (
    BFPConfig, CollectiveConfig, MeshConfig, OptimizerConfig, TrainConfig)

MCFG = bert.BertConfig.tiny()


def _cfg(**kw):
    base = dict(
        iters=4, global_batch=16, mesh=MeshConfig(dp=8),
        collective=CollectiveConfig(bucket_elems=4096),
        optimizer=OptimizerConfig(kind="sgd", learning_rate=0.1))
    base.update(kw)
    return TrainConfig(**base)


def _data(rng, n=16, S=32, mask_frac=0.15):
    """MLM batch: 15% of non-pad positions masked, labels -100 elsewhere."""
    toks = rng.integers(1, MCFG.vocab, (n, S)).astype(np.int32)
    toks[:, S - 4:] = MCFG.pad_id                    # padded tail
    labels = np.full((n, S), -100, np.int32)
    m = (rng.random((n, S)) < mask_frac) & (toks != MCFG.pad_id)
    m[:, 0] = True                                   # >=1 target per row
    labels[m] = toks[m]
    toks[m] = 3                                      # [MASK]-style id
    return jnp.asarray(toks), jnp.asarray(labels)


# -- bucket planning ---------------------------------------------------------

def test_plan_buckets_covers_all_leaves_in_reverse_order():
    params = bert.init(jax.random.PRNGKey(0), MCFG)
    coll = CollectiveConfig(bucket_elems=5000)
    plan = bucketed.plan_buckets(params, coll, 8)
    seen = [i for b in plan.buckets for i in b.leaf_ids]
    n_leaves = len(jax.tree_util.tree_leaves(params))
    assert sorted(seen) == list(range(n_leaves))
    # issue order is reverse flatten order (backward availability)
    assert seen == list(reversed(range(n_leaves)))
    sizes = [int(np.prod(s)) if s else 1 for s in plan.shapes]
    for b in plan.buckets[:-1]:
        assert sum(b.sizes) >= coll.bucket_elems or len(b.leaf_ids) == 1
    for b in plan.buckets:
        assert b.padded_len % 8 == 0
        assert b.padded_len >= sum(sizes[i] for i in b.leaf_ids)


def test_plan_buckets_pads_for_bfp_blocks():
    params = bert.init(jax.random.PRNGKey(0), MCFG)
    coll = CollectiveConfig(impl="ring", compression=BFPConfig(),
                            bucket_elems=5000)
    plan = bucketed.plan_buckets(params, coll, 8)
    for b in plan.buckets:
        assert b.padded_len % (8 * 16) == 0


# -- bucketed all-reduce -----------------------------------------------------

@pytest.mark.parametrize("impl", ["xla", "ring"])
def test_bucketed_all_reduce_is_mean(rng, impl):
    mesh = make_mesh(MeshConfig(dp=8))
    coll = CollectiveConfig(impl=impl, bucket_elems=500)
    trees = [
        {"a": jnp.asarray(rng.standard_normal((8, 40, 7)), jnp.float32),
         "b": [jnp.asarray(rng.standard_normal((8, 333)), jnp.float32),
               jnp.asarray(rng.standard_normal((8, 2, 3)), jnp.float32)]}]
    tree = trees[0]

    def run(t):
        out = bucketed.all_reduce_bucketed(t, "dp", coll)
        if impl == "xla":
            out = jax.tree_util.tree_map(
                lambda x: lax.pcast(x, "dp", to="varying"), out)
        return out

    got = jax.jit(jax.shard_map(run, mesh=mesh, in_specs=(P("dp"),),
                                out_specs=P("dp")))(tree)
    want = jax.tree_util.tree_map(lambda x: np.broadcast_to(
        np.mean(np.asarray(x), axis=0, keepdims=True), x.shape), tree)
    jax.tree_util.tree_map(
        lambda g, w: np.testing.assert_allclose(np.asarray(g), w, atol=1e-6),
        got, want)


def test_bucketed_flat_keeps_f32_for_bf16_leaves(rng):
    """The flat variant must not round the dp-mean through the leaf dtype
    (bf16 models keep f32 masters for exactly this reason)."""
    mesh = make_mesh(MeshConfig(dp=8))
    coll = CollectiveConfig(bucket_elems=64)
    tree = {"w": jnp.asarray(rng.standard_normal((8, 100)), jnp.bfloat16),
            "b": jnp.asarray(rng.standard_normal((8, 33)), jnp.bfloat16)}

    def run(t):
        flat = bucketed.all_reduce_bucketed_flat(t, "dp", coll)
        return lax.pcast(flat, "dp", to="varying")

    got = jax.jit(jax.shard_map(run, mesh=mesh, in_specs=(P("dp"),),
                                out_specs=P("dp")))(tree)
    got = np.asarray(got).reshape(8, -1)[0]
    assert got.dtype == np.float32
    want = np.concatenate([
        np.mean(np.asarray(tree["b"], np.float32), axis=0).reshape(-1),
        np.mean(np.asarray(tree["w"], np.float32), axis=0).reshape(-1)])
    # forward leaf order: dict flattens alphabetically -> b then w
    np.testing.assert_allclose(got, want, atol=1e-6)
    # and it is strictly more precise than the bf16-rounded tree path
    rounded = want.astype(jnp.bfloat16).astype(np.float32)
    assert np.any(got != rounded)


# -- DDP trainer -------------------------------------------------------------

def _loss(params, batch):
    return bert.loss_fn(params, batch, MCFG, dp_axis="dp")


def _reference_step(params, batch, lr):
    """Single-device global-mean MLM gradient + SGD."""
    g = jax.grad(lambda p, b: bert.loss_fn(p, b, MCFG))(params, batch)
    return jax.tree_util.tree_map(
        lambda w, gg: (w.astype(jnp.float32) - lr * gg.astype(jnp.float32)
                       ).astype(w.dtype), params, g)


@pytest.mark.parametrize("impl", ["xla", "ring"])
def test_ddp_matches_single_device_reference(rng, impl):
    cfg = _cfg(collective=CollectiveConfig(impl=impl, bucket_elems=4096))
    tr = DDPTrainer(_loss, make_mesh(cfg.mesh), cfg)
    params = bert.init(jax.random.PRNGKey(0), MCFG)
    state = tr.init_state(params)
    batch_host = _data(rng)
    # reference first: the trainer's donated step invalidates `params`
    want = _reference_step(params, batch_host, cfg.optimizer.learning_rate)
    ref_loss = float(bert.loss_fn(params, batch_host, MCFG))
    state, loss = tr.step(state, tr.shard_batch(batch_host))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-4, atol=2e-5), state.params, want)
    np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-4)


@pytest.mark.slow
def test_ddp_bfp_ring_converges(rng):
    cfg = _cfg(
        iters=8,
        collective=CollectiveConfig(impl="ring", compression=BFPConfig(),
                                    bucket_elems=4096),
        optimizer=OptimizerConfig(kind="adamw", learning_rate=3e-3))
    tr = DDPTrainer(_loss, make_mesh(cfg.mesh), cfg)
    state = tr.init_state(bert.init(jax.random.PRNGKey(0), MCFG))
    batch = tr.shard_batch(_data(rng))
    first = None
    for _ in range(cfg.iters):
        state, loss = tr.step(state, batch)
        first = float(loss) if first is None else first
    assert np.isfinite(float(loss))
    assert float(loss) < first, (float(loss), first)


def test_ddp_replicas_stay_identical(rng):
    """Master copy must remain bit-identical across devices after steps
    (the reference's invariant: every node's DDR holds the same weights)."""
    cfg = _cfg(collective=CollectiveConfig(impl="ring", bucket_elems=2048))
    tr = DDPTrainer(_loss, make_mesh(cfg.mesh), cfg)
    state = tr.init_state(bert.init(jax.random.PRNGKey(0), MCFG))
    for _ in range(2):
        state, _ = tr.step(state, tr.shard_batch(_data(rng)))
    shards = [np.asarray(s.data) for s in
              state.w_master.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


# -- model sanity ------------------------------------------------------------

def test_bert_forward_shapes_and_padding_mask(rng):
    params = bert.init(jax.random.PRNGKey(1), MCFG)
    toks, _ = _data(rng, n=4)
    logits = bert.apply(params, toks, MCFG)
    assert logits.shape == (4, 32, MCFG.vocab)
    assert np.all(np.isfinite(np.asarray(logits, np.float32)))
    # padding keys must not influence non-pad positions: perturb pad tokens
    toks2 = np.asarray(toks).copy()
    pads = toks2 == MCFG.pad_id
    toks2[pads] = 7
    mask = jnp.asarray(~pads)
    l1 = bert.apply(params, toks, MCFG)
    l2 = bert.apply(params, jnp.asarray(toks2), MCFG, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(l1[~pads]), np.asarray(l2[~pads]),
                               atol=1e-5)


def test_num_params_matches_init():
    params = bert.init(jax.random.PRNGKey(0), MCFG)
    total = sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(params))
    assert total == bert.num_params(MCFG)
