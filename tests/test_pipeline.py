"""Pipeline parallelism: GPipe schedule equivalence with sequential layers,
gradient parity through the ring, and full pp x dp training parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fpga_ai_nic_tpu.models import llama
from fpga_ai_nic_tpu.parallel import ShardedTrainer
from fpga_ai_nic_tpu.parallel import pipeline as pl
from fpga_ai_nic_tpu.utils.config import (
    CollectiveConfig, MeshConfig, OptimizerConfig, TrainConfig)

CFG = llama.LlamaConfig.tiny()
B, S = 4, 32


def _toy(rng, n_layers=8, d=16):
    layers = [{"w": jnp.asarray(rng.standard_normal((d, d)) * 0.3,
                                jnp.float32),
               "b": jnp.asarray(rng.standard_normal((d,)) * 0.1, jnp.float32)}
              for _ in range(n_layers)]
    x = jnp.asarray(rng.standard_normal((8, d)), jnp.float32)
    return layers, x


def _toy_block(lyr, x):
    return jnp.tanh(x @ lyr["w"] + lyr["b"])


def _seq(layers, x):
    for lyr in layers:
        x = _toy_block(lyr, x)
    return x


def _pp_mesh(pp):
    return Mesh(np.array(jax.devices()[:pp]), ("pp",))


@pytest.mark.parametrize("pp,n_mb", [(4, 2), (4, 4), (2, 8), (8, 1)])
def test_pipeline_apply_matches_sequential(rng, pp, n_mb):
    layers, x = _toy(rng)
    stacked = pl.stack_layers(layers)
    spec = {"w": P("pp", None, None), "b": P("pp", None)}

    def run(stacked, x):
        def stage(sp_, h):
            return pl.scan_layers(_toy_block, sp_, h)

        y = pl.pipeline_apply(stage, stacked, x, n_mb, "pp")
        return pl.from_last_stage(y, "pp")

    with _pp_mesh(pp):
        got = jax.jit(jax.shard_map(run, mesh=_pp_mesh(pp),
                                    in_specs=(spec, P()), out_specs=P()))(
            stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(_seq(layers, x)),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_grads_match_sequential(rng):
    layers, x = _toy(rng)
    stacked = pl.stack_layers(layers)
    spec = {"w": P("pp", None, None), "b": P("pp", None)}
    mesh = _pp_mesh(4)

    def pp_loss(stacked, x):
        def inner(sp_, xx):
            def stage(s, h):
                return pl.scan_layers(_toy_block, s, h)

            y = pl.pipeline_apply(stage, sp_, xx, 2, "pp")
            return pl.from_last_stage(jnp.sum(y * y), "pp")

        return jax.shard_map(inner, mesh=mesh, in_specs=(spec, P()),
                             out_specs=P())(stacked, x)

    def ref_loss(stacked, x):
        y = _seq(pl.unstack_layers(stacked), x)
        return jnp.sum(y * y)

    g_pp = jax.jit(jax.grad(pp_loss))(stacked, x)
    g_ref = jax.grad(ref_loss)(stacked, x)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def _head(hp, h, t):
    return jnp.mean(((h * hp["v"]).sum(-1) - t) ** 2)


@pytest.mark.parametrize("pp,n_mb", [(4, 4), (4, 2), (2, 8), (8, 1), (8, 2)])
def test_1f1b_matches_sequential_grads(rng, pp, n_mb):
    """The explicit 1F1B schedule (fused fwd+bwd ticks, counter-rotating
    cotangent ring, stage-granular recompute) must reproduce sequential
    loss AND gradients — for deep and shallow rings, M >= pp and the
    M < pp warmup-only edge."""
    layers, x = _toy(rng)
    stacked = pl.stack_layers(layers)
    spec = {"w": P("pp", None, None), "b": P("pp", None)}
    mesh = _pp_mesh(pp)
    hp = {"v": jnp.asarray(rng.standard_normal((16,)) * 0.3, jnp.float32)}
    tgt = jnp.asarray(rng.standard_normal((8,)), jnp.float32)

    def run(stacked, hp, x, tgt):
        def stage(sp_, hp_, h, c):
            out = pl.scan_layers(_toy_block, sp_, h)
            return out, jnp.sum(out) * 0.0

        return pl.pipeline_train_1f1b(stage, _head, stacked, hp, x, tgt,
                                      n_mb, "pp")

    loss, d_sp, d_hp, d_x = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(spec, P(), P(), P()),
        out_specs=(P(), spec, P(), P())))(stacked, hp, x, tgt)

    def ref_loss(stacked, hp, x):
        xs = x.reshape(n_mb, -1, 16)
        ts = tgt.reshape(n_mb, -1)
        losses = [_head(hp, _seq(pl.unstack_layers(stacked), xs[i]), ts[i])
                  for i in range(n_mb)]
        return sum(losses) / n_mb

    want_loss, (want_sp, want_hp, want_x) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2))(stacked, hp, x)
    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(d_sp),
                    jax.tree_util.tree_leaves(want_sp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(d_hp),
                    jax.tree_util.tree_leaves(want_hp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(d_x), np.asarray(want_x),
                               rtol=1e-4, atol=1e-5)


def test_1f1b_memory_independent_of_microbatches():
    """The 1F1B claim, measured on compiled programs: GPipe-differentiated
    temp memory grows with num_microbatches (jax saves every forward
    carry); 1F1B's stays ~flat (ring buffer of depth pp).  Compare M=4 vs
    M=16 growth for both schedules."""
    rng = np.random.default_rng(0)
    d, Btot, pp = 64, 64, 4
    layers = [{"w": jnp.asarray(rng.standard_normal((d, d)) * 0.2,
                                jnp.float32),
               "b": jnp.zeros((d,), jnp.float32)} for _ in range(pp)]
    stacked = pl.stack_layers(layers)
    spec = {"w": P("pp", None, None), "b": P("pp", None)}
    mesh = _pp_mesh(pp)
    hp = {"v": jnp.ones((d,), jnp.float32)}
    x = jnp.asarray(rng.standard_normal((Btot, d)), jnp.float32)
    tgt = jnp.zeros((Btot,), jnp.float32)

    def stage(sp_, h):
        return pl.scan_layers(_toy_block, sp_, h)

    def stage4(sp_, hp_, h, c):
        out = stage(sp_, h)
        return out, jnp.sum(out) * 0.0

    def temp_1f1b(M):
        fn = jax.jit(jax.shard_map(
            lambda sp_, hp_, xx, tt: pl.pipeline_train_1f1b(
                stage4, _head, sp_, hp_, xx, tt, M, "pp"),
            mesh=mesh, in_specs=(spec, P(), P(), P()),
            out_specs=(P(), spec, P(), P())))
        return fn.lower(stacked, hp, x, tgt).compile() \
                 .memory_analysis().temp_size_in_bytes

    def temp_gpipe(M):
        def loss(sp_, hp_, xx, tt):
            def inner(sp2, xx2, tt2):
                y = pl.pipeline_apply(stage, sp2, xx2, M, "pp")
                return pl.from_last_stage(_head(hp_, y, tt2), "pp")
            return jax.shard_map(inner, mesh=mesh,
                                 in_specs=(spec, P(), P()),
                                 out_specs=P())(sp_, xx, tt)
        fn = jax.jit(jax.grad(loss, argnums=(0, 1)))
        return fn.lower(stacked, hp, x, tgt).compile() \
                 .memory_analysis().temp_size_in_bytes

    grow_1f1b = temp_1f1b(16) / max(temp_1f1b(4), 1)
    grow_gpipe = temp_gpipe(16) / max(temp_gpipe(4), 1)
    # GPipe's differentiated temps scale with M; 1F1B's must not
    assert grow_1f1b < grow_gpipe, (grow_1f1b, grow_gpipe)
    assert grow_1f1b < 1.5, grow_1f1b


def test_1f1b_cost_model():
    cm = pl.cost_model(8, 4, schedule="1f1b")
    assert cm["ticks"] == 2 * (8 + 4) - 2
    assert cm["live_activations_per_stage"] == 4
    g = pl.cost_model(8, 4)
    assert g["live_activations_per_stage"] == 8
    with pytest.raises(ValueError):
        pl.cost_model(8, 4, schedule="nope")


def _batch(rng):
    tokens = rng.integers(0, CFG.vocab, (B, S + 1)).astype(np.int32)
    return jnp.asarray(tokens[:, :-1]), jnp.asarray(tokens[:, 1:])


def test_llama_pp_loss_matches_plain(rng):
    toks, labels = _batch(rng)
    params = llama.init(jax.random.PRNGKey(0), CFG)
    want = float(llama.loss_fn(params, (toks, labels), CFG))

    stacked = llama.stack_params(params)
    specs = llama.stacked_param_specs(CFG, pp_axis="pp", tp_axis=None)
    mesh = _pp_mesh(2)

    def run(p, b):
        return llama.loss_fn_pp(p, b, CFG, pp_axis="pp", num_microbatches=2)

    got = jax.jit(jax.shard_map(run, mesh=mesh, in_specs=(specs, P()),
                                out_specs=P()))(stacked, (toks, labels))
    np.testing.assert_allclose(float(got), want, rtol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("axes", ["pp", "pp_tp", "pp_dp"])
def test_llama_1f1b_matches_gpipe_grads(rng, axes):
    """llama.loss_and_grads_pp_1f1b == jax.grad(loss_fn_pp) — same loss,
    same gradients for every leaf (embedding via the returned d_x,
    head/norm leaves via the scheduler's recorded-axes psums), across
    pp-only, pp x tp (psums inside divergent schedule branches are
    uniform per tp group), and pp x dp (grads stay dp-varying per shard,
    masked-label weighting included)."""
    import dataclasses
    cfg = dataclasses.replace(CFG, n_layers=4)
    toks, labels = _batch(rng)
    if axes == "pp_dp":
        labels = labels.at[:, : S // 4].set(-100)   # exercise weighting
    params = llama.init(jax.random.PRNGKey(0), cfg)
    stacked = llama.stack_params(params)
    tp_axis = "tp" if axes == "pp_tp" else None
    dp_axis = "dp" if axes == "pp_dp" else None
    if axes == "pp":
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("pp",))
    elif axes == "pp_tp":
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", "tp"))
    else:
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "pp"))
    specs = llama.stacked_param_specs(cfg, pp_axis="pp", tp_axis=tp_axis)
    b_spec = (P("dp"), P("dp")) if dp_axis else (P(), P())
    M = 2 if dp_axis else 4

    kw = dict(pp_axis="pp", num_microbatches=M, tp_axis=tp_axis,
              dp_axis=dp_axis)

    def clear(loss):
        # numerically identity; clears the varying TYPE the same way the
        # trainer does before returning an invariant loss
        if tp_axis:
            loss = jax.lax.pmean(loss, tp_axis)
        if dp_axis:
            loss = jax.lax.pmean(loss, dp_axis)
        return loss

    def ref(p, b):
        return llama.loss_fn_pp(p, b, cfg, **kw)

    def ref_wrapped(p, b):
        loss, g = jax.value_and_grad(ref)(p, b)
        return clear(loss), g

    want_loss, want_g = jax.jit(jax.shard_map(
        ref_wrapped, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    def got_fn(p, b):
        loss, g = llama.loss_and_grads_pp_1f1b(p, b, cfg, **kw)
        return clear(loss), g

    got_loss, got_g = jax.jit(jax.shard_map(
        got_fn, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        got_g, want_g)


@pytest.mark.slow
def test_sharded_trainer_1f1b_matches_gpipe_training(rng):
    """The trainer knob: ShardedTrainer(loss_and_grads_fn=...) trains
    llama on the 1F1B schedule through the full fused-update path
    (flatten -> dp reduce-scatter -> sharded adamw -> gather) and must
    track the GPipe trainer's loss trajectory step for step."""
    import dataclasses
    cfg_m = dataclasses.replace(CFG, n_layers=4)
    toks, labels = _batch(rng)
    params = llama.stack_params(llama.init(jax.random.PRNGKey(0), cfg_m))
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 1, 1, 2),
                ("dp", "tp", "sp", "pp"))
    specs = llama.stacked_param_specs(cfg_m, pp_axis="pp", tp_axis=None)
    tcfg = TrainConfig(
        iters=3, global_batch=B, mesh=MeshConfig(dp=2, pp=2),
        collective=CollectiveConfig(impl="xla"),
        optimizer=OptimizerConfig(kind="adamw", learning_rate=1e-3))

    def losses(trainer):
        st = trainer.init_state(jax.tree_util.tree_map(jnp.copy, params))
        out = []
        for _ in range(3):
            st, loss = trainer.step(st, trainer.shard_batch((toks, labels)))
            out.append(float(loss))
        return out

    gpipe = ShardedTrainer(
        lambda p, b: llama.loss_fn_pp(p, b, cfg_m, pp_axis="pp",
                                      num_microbatches=2, dp_axis="dp",
                                      sp_axis="sp"),
        mesh, tcfg, specs, pp_axis="pp")
    onef1b = ShardedTrainer(
        None, mesh, tcfg, specs, pp_axis="pp",
        loss_and_grads_fn=lambda p, b: llama.loss_and_grads_pp_1f1b(
            p, b, cfg_m, pp_axis="pp", num_microbatches=2, dp_axis="dp",
            sp_axis="sp"))

    a, b = losses(gpipe), losses(onef1b)
    np.testing.assert_allclose(a, b, rtol=1e-4)
    assert a[-1] < a[0]


@pytest.mark.slow
def test_llama_1f1b_moe_matches_gpipe_grads(rng):
    """MoE on the 1F1B schedule: per-stage aux differentiates through the
    stage's own seeded loss channel (gradient-scale folded, n_dp/(M*w)),
    the display loss reconstructs from the raw report channel — loss AND
    every gradient leaf must match jax.grad(loss_fn_pp) on a dp x pp
    mesh."""
    import dataclasses
    cfg_m = dataclasses.replace(
        llama.LlamaConfig.tiny(n_layers=4, ffn_dim=64),
        moe_experts=4, moe_top_k=2, moe_capacity_factor=4.0)
    toks, labels = _batch(rng)
    labels = labels.at[:, : S // 4].set(-100)
    params = llama.init(jax.random.PRNGKey(0), cfg_m)
    stacked = llama.stack_params(params)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "pp"))
    specs = llama.stacked_param_specs(cfg_m, pp_axis="pp", tp_axis=None)
    b_spec = (P("dp"), P("dp"))
    M = 2
    kw = dict(pp_axis="pp", num_microbatches=M, dp_axis="dp")

    def clear(loss):
        return jax.lax.pmean(loss, "dp")

    def ref_wrapped(p, b):
        loss, g = jax.value_and_grad(
            lambda p2, b2: llama.loss_fn_pp(p2, b2, cfg_m, **kw))(p, b)
        return clear(loss), g

    want_loss, want_g = jax.jit(jax.shard_map(
        ref_wrapped, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    def got_fn(p, b):
        loss, g = llama.loss_and_grads_pp_1f1b(p, b, cfg_m, **kw,
                                               sp_axis=None)
        return clear(loss), g

    got_loss, got_g = jax.jit(jax.shard_map(
        got_fn, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5),
        got_g, want_g)


def test_trainer_rejects_1f1b_with_accum():
    from fpga_ai_nic_tpu.parallel.sharded import ShardedTrainer as ST
    import dataclasses
    tcfg = TrainConfig(iters=1, global_batch=8,
                       mesh=MeshConfig(dp=2, pp=2), accum_steps=2,
                       collective=CollectiveConfig(impl="xla"),
                       optimizer=OptimizerConfig(kind="sgd",
                                                 learning_rate=0.1))
    with pytest.raises(ValueError, match="loss_and_grads_fn"):
        ST(None, _pp_mesh(2), tcfg, {}, loss_and_grads_fn=lambda p, b: None)


@pytest.mark.xfail(
    strict=False,
    reason="jaxlib drift: this jaxlib's shard_map raises _SpecError at "
           "trace time on the MoE-under-pp out_specs (VMA rules changed "
           "across jax versions); fails before any numerics run — "
           "docs/KNOWN_FAILURES.md #3")
def test_llama_pp_moe_loss_matches_plain(rng):
    """MoE layers on the pipelined path: with one microbatch the aux loss
    rides the scan over exactly the same routing as the unpipelined
    forward, so loss_fn_pp must equal loss_fn; multi-microbatch averages
    per-microbatch routing (different statistic, still finite/positive)
    and gradients must flow into the expert weights through the ring."""
    import dataclasses
    cfg_m = dataclasses.replace(
        llama.LlamaConfig.tiny(n_layers=2, ffn_dim=32),
        moe_experts=4, moe_top_k=2, moe_capacity_factor=16.0)
    toks_l = jnp.asarray(np.random.default_rng(1).integers(
        0, cfg_m.vocab, (B, S + 1)), jnp.int32)
    batch = (toks_l[:, :-1], toks_l[:, 1:])
    params = llama.init(jax.random.PRNGKey(0), cfg_m)
    want = float(llama.loss_fn(params, batch, cfg_m))

    stacked = llama.stack_params(params)
    specs = llama.stacked_param_specs(cfg_m, pp_axis="pp", tp_axis=None)
    mesh = _pp_mesh(2)

    def run_pp(p, b, n_mb):
        return jax.shard_map(
            lambda p_, b_: llama.loss_fn_pp(p_, b_, cfg_m, pp_axis="pp",
                                            num_microbatches=n_mb),
            mesh=mesh, in_specs=(specs, P()), out_specs=P())(p, b)

    got = jax.jit(run_pp, static_argnums=2)(stacked, batch, 1)
    np.testing.assert_allclose(float(got), want, rtol=1e-5)

    got2 = jax.jit(run_pp, static_argnums=2)(stacked, batch, 2)
    assert np.isfinite(float(got2))

    g = jax.jit(jax.grad(lambda p: run_pp(p, batch, 2)))(stacked)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf, np.float32)).all()
    w1g = np.asarray(g["layers"]["moe"]["w1"], np.float32)
    assert np.abs(w1g).max() > 0.0


@pytest.mark.parametrize("dp,pp,remat,masked", [
    (2, 2, False, True), (1, 4, True, False), (4, 2, False, False)])
def test_pp_training_matches_unsharded(dp, pp, remat, masked):
    """dp x pp ZeRO-1 training must reproduce the single-device update —
    including with -100-masked labels spread unevenly over dp shards
    (loss_fn_pp dp_axis gradient-scale correction)."""
    n_mb = min(2, B // dp)          # local batch must split into microbatches
    cfg_m = llama.LlamaConfig.tiny(n_layers=4) if pp > 2 else CFG
    rng = np.random.default_rng(0)
    toks, labels = _batch(rng)
    if masked:
        lab = np.asarray(labels).copy()
        lab[: B // 2, : (3 * S) // 4] = -100
        labels = jnp.asarray(lab)
    params0 = llama.init(jax.random.PRNGKey(0), cfg_m)

    def ref_step(params):
        g = jax.grad(lambda p: llama.loss_fn(p, (toks, labels), cfg_m))(params)
        return jax.tree_util.tree_map(
            lambda w, gg: (w.astype(jnp.float32)
                           - 0.1 * gg.astype(jnp.float32)).astype(w.dtype),
            params, g)

    want = llama.stack_params(ref_step(ref_step(params0)))

    mesh = Mesh(np.array(jax.devices()[:dp * pp]).reshape(dp, 1, 1, pp),
                ("dp", "tp", "sp", "pp"))
    cfg = TrainConfig(iters=2, global_batch=B,
                      mesh=MeshConfig(dp=dp, pp=pp),
                      collective=CollectiveConfig(impl="xla"),
                      optimizer=OptimizerConfig(kind="sgd", learning_rate=0.1))
    dp_ax = "dp" if masked else None
    tr = ShardedTrainer(
        lambda p, b: llama.loss_fn_pp(p, b, cfg_m, pp_axis="pp",
                                      num_microbatches=n_mb, remat=remat,
                                      dp_axis=dp_ax),
        mesh, cfg, llama.stacked_param_specs(cfg_m), pp_axis="pp")
    state = tr.init_state(llama.stack_params(params0))
    batch = tr.shard_batch((toks, labels))
    for _ in range(2):
        state, loss = tr.step(state, batch)
    assert np.isfinite(float(loss))
    for pw, pg in zip(jax.tree_util.tree_leaves_with_path(want),
                      jax.tree_util.tree_leaves_with_path(state.params)):
        np.testing.assert_allclose(
            np.asarray(pg[1], np.float32), np.asarray(pw[1], np.float32),
            rtol=5e-4, atol=5e-5, err_msg=str(pw[0]))


def test_cost_model_bubble_arithmetic():
    from fpga_ai_nic_tpu.parallel import pipeline
    cm = pipeline.cost_model(num_microbatches=4, pp=2)
    assert cm["ticks"] == 5
    assert cm["bubble_ticks"] == 1
    assert cm["bubble_fraction"] == pytest.approx(0.2)
    assert cm["utilization"] == pytest.approx(0.8)
    # more microbatches amortize the bubble
    assert (pipeline.cost_model(16, 2)["bubble_fraction"]
            < cm["bubble_fraction"])
    with pytest.raises(ValueError):
        pipeline.cost_model(0, 2)


@pytest.mark.slow
def test_llama_1f1b_moe_ep_matches_gpipe_and_unsharded(rng):
    """ep on the 1F1B schedule — the last trainer-axis composition: on a
    dp x pp x ep mesh the all_to_all expert exchange and routing-stat
    psums execute inside stage-divergent schedule conds (uniform per ep
    group, like tp), expert leaves keep per-shard cotangents, and the
    token weighting spans ep (ep shards the batch alongside dp).

    Three-way check with UNEQUAL valid-token counts across ep shards
    (equal counts make mean-of-ratios == ratio-of-sums, hiding a missing
    ep psum in the weighting): 1F1B loss+grads == jax.grad(loss_fn_pp)
    leaf for leaf, and both losses == the unsharded single-device
    loss_fn value (generous capacity so no tokens drop on either side).
    """
    import dataclasses
    cfg_m = dataclasses.replace(
        llama.LlamaConfig.tiny(n_layers=2, ffn_dim=64),
        moe_experts=4, moe_top_k=2, moe_capacity_factor=16.0)
    B2 = 8
    toks = jnp.asarray(rng.integers(0, cfg_m.vocab, (B2, S)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg_m.vocab, (B2, S)), jnp.int32)
    labels = labels.at[:3, : S // 2].set(-100)   # unequal counts per shard
    params = llama.init(jax.random.PRNGKey(0), cfg_m)
    stacked = llama.stack_params(params)

    # unsharded ground truth (token-weighted global mean + aux)
    want_unsharded = float(jax.jit(
        lambda p, b: llama.loss_fn(p, b, cfg_m))(params, (toks, labels)))

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("dp", "pp", "ep"))
    specs = llama.stacked_param_specs(cfg_m, pp_axis="pp", tp_axis=None,
                                      ep_axis="ep")
    b_spec = (P(("dp", "ep")), P(("dp", "ep")))
    M = 2
    kw = dict(pp_axis="pp", num_microbatches=M, dp_axis="dp", ep_axis="ep")

    def clear(loss):
        return jax.lax.pmean(jax.lax.pmean(loss, "dp"), "ep")

    def ref_wrapped(p, b):
        loss, g = jax.value_and_grad(
            lambda p2, b2: llama.loss_fn_pp(p2, b2, cfg_m, **kw))(p, b)
        return clear(loss), g

    want_loss, want_g = jax.jit(jax.shard_map(
        ref_wrapped, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    def got_fn(p, b):
        loss, g = llama.loss_and_grads_pp_1f1b(p, b, cfg_m, **kw)
        return clear(loss), g

    got_loss, got_g = jax.jit(jax.shard_map(
        got_fn, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    # sharded-vs-single-device fp reordering is ~1e-4 here; a missing ep
    # psum in the weighting shows up at the percent level (the masked
    # shards make the per-rank ratios genuinely unequal)
    np.testing.assert_allclose(float(want_loss), want_unsharded, rtol=2e-4)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5),
        got_g, want_g)


@pytest.mark.parametrize("pp,v,n_mb", [(2, 2, 4), (2, 4, 4), (4, 2, 8)])
def test_interleaved_1f1b_matches_sequential_grads(rng, pp, v, n_mb):
    """Interleaved 1F1B == sequential loss+grads on a toy stack: chunk c
    on device s runs global virtual stage c*pp+s; the static schedule's
    slot-buffered arrivals must deliver every activation and cotangent
    to the right unit (gradients are exact, not approximate)."""
    L = pp * v
    layers, x = _toy(rng, n_layers=L)
    tgt = jnp.asarray(rng.standard_normal(x.shape), jnp.float32)
    M = n_mb

    def seq_loss(layers, xx):
        return jnp.sum((_seq(layers, xx) - tgt) ** 2) / M

    want_loss = float(seq_loss(layers, x))
    want_gl, want_gx = jax.grad(seq_loss, argnums=(0, 1))(layers, x)
    want_stack = pl.stack_layers(want_gl)

    stacked = pl.stack_layers(layers)
    ilv = pl.interleave_layers(stacked, pp, v)
    mesh = _pp_mesh(pp)

    def stage(sp, hp, xx, cc):
        h = pl.scan_layers(_toy_block, sp, xx)
        return h, jnp.sum(h) * 0.0

    def head(hp, h, cc):
        return jnp.sum((h - cc) ** 2)

    def run(sp, xx, tt):
        spc = jax.tree_util.tree_map(
            lambda a: a.reshape((v, a.shape[0] // v) + a.shape[1:]), sp)
        loss, d_sp, d_hp, d_x = pl.pipeline_train_1f1b_interleaved(
            stage, head, spc, {}, xx, tt, M, "pp", v)
        d_sp = jax.tree_util.tree_map(
            lambda a: a.reshape((-1,) + a.shape[2:]), d_sp)
        return loss, d_sp, d_x

    loss_i, d_sp_i, d_x_i = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(P("pp"), P(), P()),
        out_specs=(P(), P("pp"), P())))(ilv, x, tgt)

    np.testing.assert_allclose(float(loss_i), want_loss, rtol=1e-5)
    got_model_order = pl.deinterleave_layers(d_sp_i, pp, v)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        got_model_order, want_stack)
    np.testing.assert_allclose(np.asarray(d_x_i), np.asarray(want_gx),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_llama_interleaved_1f1b_matches_gpipe(rng):
    """llama on interleaved 1F1B (virtual_stages=2, dp x pp): loss and
    every gradient leaf == jax.grad(loss_fn_pp) after mapping the
    interleaved layer order back to model order."""
    import dataclasses
    cfg = dataclasses.replace(CFG, n_layers=4)
    toks, labels = _batch(rng)
    labels = labels.at[:, : S // 4].set(-100)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    stacked = llama.stack_params(params)
    pp, v, M = 2, 2, 2
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "pp"))
    specs = llama.stacked_param_specs(cfg, pp_axis="pp", tp_axis=None)
    b_spec = (P("dp"), P("dp"))
    kw = dict(pp_axis="pp", num_microbatches=M, dp_axis="dp")

    def clear(loss):
        return jax.lax.pmean(loss, "dp")

    def ref_wrapped(p, b):
        loss, g = jax.value_and_grad(
            lambda p2, b2: llama.loss_fn_pp(p2, b2, cfg, **kw))(p, b)
        return clear(loss), g

    want_loss, want_g = jax.jit(jax.shard_map(
        ref_wrapped, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    ilv = dict(stacked)
    ilv["layers"] = pl.interleave_layers(stacked["layers"], pp, v)

    def got_fn(p, b):
        loss, g = llama.loss_and_grads_pp_1f1b(p, b, cfg, **kw,
                                               virtual_stages=v)
        return clear(loss), g

    got_loss, got_g = jax.jit(jax.shard_map(
        got_fn, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(ilv, (toks, labels))

    got_g = dict(got_g)
    got_g["layers"] = pl.deinterleave_layers(got_g["layers"], pp, v)

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5),
        got_g, want_g)


def test_interleaved_cost_model():
    cm = pl.cost_model(8, 4, schedule="1f1b-interleaved", virtual_stages=2)
    plain = pl.cost_model(8, 4, schedule="1f1b")
    # same bubble in ticks, but interleaved ticks are half a stage:
    # absolute bubble time halves
    assert cm["bubble_full_stage_units"] == plain["bubble_ticks"] / 2
    assert cm["ticks"] == 38 and cm["bubble_ticks"] == 6


@pytest.mark.slow
def test_llama_interleaved_1f1b_moe_matches_gpipe(rng):
    """MoE on the interleaved schedule: per-stage aux channels and the
    raw report ride the shared unit function, so chunked virtual stages
    must reproduce GPipe loss+grads exactly (dp x pp, v=2)."""
    import dataclasses
    cfg_m = dataclasses.replace(
        llama.LlamaConfig.tiny(n_layers=4, ffn_dim=64),
        moe_experts=4, moe_top_k=2, moe_capacity_factor=16.0)
    toks, labels = _batch(rng)
    labels = labels.at[:, : S // 4].set(-100)
    params = llama.init(jax.random.PRNGKey(0), cfg_m)
    stacked = llama.stack_params(params)
    pp, v, M = 2, 2, 2
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "pp"))
    specs = llama.stacked_param_specs(cfg_m, pp_axis="pp", tp_axis=None)
    b_spec = (P("dp"), P("dp"))
    kw = dict(pp_axis="pp", num_microbatches=M, dp_axis="dp")

    def clear(loss):
        return jax.lax.pmean(loss, "dp")

    def ref_wrapped(p, b):
        loss, g = jax.value_and_grad(
            lambda p2, b2: llama.loss_fn_pp(p2, b2, cfg_m, **kw))(p, b)
        return clear(loss), g

    want_loss, want_g = jax.jit(jax.shard_map(
        ref_wrapped, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    ilv = dict(stacked)
    ilv["layers"] = pl.interleave_layers(stacked["layers"], pp, v)

    def got_fn(p, b):
        loss, g = llama.loss_and_grads_pp_1f1b(p, b, cfg_m, **kw,
                                               virtual_stages=v)
        return clear(loss), g

    got_loss, got_g = jax.jit(jax.shard_map(
        got_fn, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(ilv, (toks, labels))

    got_g = dict(got_g)
    got_g["layers"] = pl.deinterleave_layers(got_g["layers"], pp, v)

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5),
        got_g, want_g)


@pytest.mark.slow
@pytest.mark.parametrize("moe", [False, True])
def test_llama_1f1b_sp_matches_gpipe(rng, moe):
    """1F1B x sp (sequence parallelism): ring attention's sp-axis
    ppermutes and the sp token-weighting run inside the stage-divergent
    schedule conds (uniform per sp group, like tp/ep); the MoE arm
    additionally pins the aux-seed replication factor n_rep = n_sp
    (GPipe's pmean over batch axes seeds each shard 1/(M*n_sp))."""
    import dataclasses
    if moe:
        cfg = dataclasses.replace(
            llama.LlamaConfig.tiny(n_layers=4, ffn_dim=64),
            moe_experts=4, moe_top_k=2, moe_capacity_factor=16.0)
    else:
        cfg = dataclasses.replace(CFG, n_layers=4)
    toks, labels = _batch(rng)
    labels = labels.at[:, : S // 4].set(-100)   # unequal counts per shard
    params = llama.init(jax.random.PRNGKey(0), cfg)
    stacked = llama.stack_params(params)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", "sp"))
    specs = llama.stacked_param_specs(cfg, pp_axis="pp", tp_axis=None)
    b_spec = (P(None, "sp"), P(None, "sp"))
    M = 2
    kw = dict(pp_axis="pp", num_microbatches=M, sp_axis="sp")

    # unsharded value sanity: the gathered-KV softmax is the same math as
    # full attention, so the sp-sharded GPipe loss must match unsharded
    want_unsharded = float(jax.jit(
        lambda p, b: llama.loss_fn(p, b, cfg))(params, (toks, labels)))

    def clear(loss):
        return jax.lax.pmean(loss, "sp")

    def ref_wrapped(p, b):
        # GPipe with the SAME gathered-KV attention the 1F1B path uses —
        # ring vs gather differ only in f32 summation order, but exact
        # leaf-for-leaf parity needs identical primitives
        loss, g = jax.value_and_grad(
            lambda p2, b2: llama.loss_fn_pp(p2, b2, cfg, sp_attn="gather",
                                            **kw))(p, b)
        return clear(loss), g

    want_loss, want_g = jax.jit(jax.shard_map(
        ref_wrapped, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    def got_fn(p, b):
        loss, g = llama.loss_and_grads_pp_1f1b(p, b, cfg, **kw)
        return clear(loss), g

    got_loss, got_g = jax.jit(jax.shard_map(
        got_fn, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    np.testing.assert_allclose(float(want_loss), want_unsharded, rtol=2e-3)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5),
        got_g, want_g)


@pytest.mark.slow
@pytest.mark.parametrize("axes", ["tp", "ep", "sp"])
def test_llama_interleaved_1f1b_axis_matrix(rng, axes):
    """Interleaved 1F1B x {tp, ep, sp}: every in-stage collective the
    zoo uses (tp psum, ep all_to_all, sp KV all-gather) is replica-
    grouped and therefore sound inside the schedule's conds; each must
    reproduce GPipe leaf for leaf through the chunked virtual stages."""
    import dataclasses
    moe = axes == "ep"
    if moe:
        cfg = dataclasses.replace(
            llama.LlamaConfig.tiny(n_layers=4, ffn_dim=64),
            moe_experts=4, moe_top_k=2, moe_capacity_factor=16.0)
    else:
        cfg = dataclasses.replace(CFG, n_layers=4)
    toks, labels = _batch(rng)
    labels = labels.at[:, : S // 4].set(-100)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    stacked = llama.stack_params(params)
    pp, v, M = 2, 2, 2
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", axes))
    tp_axis = "tp" if axes == "tp" else None
    specs = llama.stacked_param_specs(cfg, pp_axis="pp", tp_axis=tp_axis,
                                      ep_axis="ep" if moe else None)
    if axes == "sp":
        b_spec = (P(None, "sp"), P(None, "sp"))
    elif axes == "ep":
        b_spec = (P("ep"), P("ep"))
    else:
        b_spec = (P(), P())
    kw = dict(pp_axis="pp", num_microbatches=M, tp_axis=tp_axis,
              sp_axis="sp" if axes == "sp" else None,
              ep_axis="ep" if moe else None)
    ref_kw = dict(kw)
    if axes == "sp":
        ref_kw["sp_attn"] = "gather"

    def clear(loss):
        return jax.lax.pmean(loss, axes)

    def ref_wrapped(p, b):
        loss, g = jax.value_and_grad(
            lambda p2, b2: llama.loss_fn_pp(p2, b2, cfg, **ref_kw))(p, b)
        return clear(loss), g

    want_loss, want_g = jax.jit(jax.shard_map(
        ref_wrapped, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(stacked, (toks, labels))

    ilv = dict(stacked)
    ilv["layers"] = pl.interleave_layers(stacked["layers"], pp, v)

    def got_fn(p, b):
        loss, g = llama.loss_and_grads_pp_1f1b(p, b, cfg, **kw,
                                               virtual_stages=v)
        return clear(loss), g

    got_loss, got_g = jax.jit(jax.shard_map(
        got_fn, mesh=mesh, in_specs=(specs, b_spec),
        out_specs=(P(), specs)))(ilv, (toks, labels))

    got_g = dict(got_g)
    got_g["layers"] = pl.deinterleave_layers(got_g["layers"], pp, v)

    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5),
        got_g, want_g)


@pytest.mark.slow
def test_sharded_trainer_interleaved_matches_gpipe_training(rng):
    """Trainer-level interleaved 1F1B: ShardedTrainer trains llama on the
    chunked virtual-stage schedule (interleaved layer layout end to end —
    masters, optimizer, gather) and must track the GPipe trainer's loss
    trajectory step for step."""
    import dataclasses
    cfg_m = dataclasses.replace(CFG, n_layers=4)
    toks, labels = _batch(rng)
    base = llama.stack_params(llama.init(jax.random.PRNGKey(0), cfg_m))
    pp, v = 2, 2
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 1, 1, 2),
                ("dp", "tp", "sp", "pp"))
    specs = llama.stacked_param_specs(cfg_m, pp_axis="pp", tp_axis=None)
    tcfg = TrainConfig(
        iters=3, global_batch=B, mesh=MeshConfig(dp=2, pp=2),
        collective=CollectiveConfig(impl="xla"),
        optimizer=OptimizerConfig(kind="adamw", learning_rate=1e-3))

    def losses(trainer, params):
        st = trainer.init_state(jax.tree_util.tree_map(jnp.copy, params))
        out = []
        for _ in range(3):
            st, loss = trainer.step(st, trainer.shard_batch((toks, labels)))
            out.append(float(loss))
        return out

    # sp_axis must be passed even at sp=1: the trainer's batch spec
    # mentions sp, typing tokens sp-varying, and the loss weighting is
    # what clears it (same contract as the plain 1F1B trainer test)
    gpipe = ShardedTrainer(
        lambda p, b: llama.loss_fn_pp(p, b, cfg_m, pp_axis="pp",
                                      num_microbatches=2, dp_axis="dp",
                                      sp_axis="sp"),
        mesh, tcfg, specs, pp_axis="pp")
    ilv_params = dict(base)
    ilv_params["layers"] = pl.interleave_layers(base["layers"], pp, v)
    ilv = ShardedTrainer(
        None, mesh, tcfg, specs, pp_axis="pp",
        loss_and_grads_fn=lambda p, b: llama.loss_and_grads_pp_1f1b(
            p, b, cfg_m, pp_axis="pp", num_microbatches=2, dp_axis="dp",
            sp_axis="sp", virtual_stages=v))

    a, b = losses(gpipe, base), losses(ilv, ilv_params)
    np.testing.assert_allclose(a, b, rtol=1e-4)
    assert a[-1] < a[0]


def test_interleaved_tables_property_sweep():
    """The interleaved schedule builder self-verifies (unit coverage,
    strict orderings, slot-lifetime disjointness) — sweep it across a
    wide (pp, v, M) grid so the invariants are CI-locked for shapes far
    beyond what the compiled parity tests can afford.  Pure Python: no
    jax tracing, runs in seconds."""
    for pp in (2, 3, 4, 6, 8):
        for v in (1, 2, 3, 4):
            for mult in (1, 2, 4):
                M = pp * mult
                t = pl._interleaved_tables(pp, v, M)
                total_units = 2 * v * M
                # every device runs exactly its units; tick table agrees
                kinds = t["KIND"]
                assert (kinds > 0).sum() == pp * total_units
                # ticks bounded: ideal + bubble should stay within the
                # non-interleaved bound scaled to chunk units
                assert t["T"] >= total_units
                assert t["T"] <= total_units + 4 * pp * v, (pp, v, M)
                # slot buffers stay near the analytic envelope
                assert t["n_aslots"] <= 3 * pp * v, (pp, v, M)
                assert t["n_cslots"] <= pp, (pp, v, M)
