"""graftsched: exhaustive control-plane model checking (ISSUE 20).

The serving control plane's discrete decisions — watermark admission,
LIFO eviction, least-loaded routing, the kill trichotomy, the CUSUM
detector and the scale/shed gates — are emitted ONCE
(`verify.opstream.SchedEmitter`) and consumed twice: by the real hot
paths as thin delegates and by the small-step model
(`verify.sched.SchedModel`) the graftmc corpus explores.  This battery
pins both halves:

  - state-name constants shared with `runtime.requests` by VALUE;
  - the clean envelope (>= 150 exhaustive cells over reqs x pages x
    replicas x fault) green, faults included;
  - each seeded mutant trips EXACTLY its intended violation kind
    (leaky eviction -> conservation, dropped watermark -> over-commit,
    no eviction -> livelock, disabled hysteresis -> flap) and never a
    different one;
  - POR-vs-naive verdict agreement on clean AND mutated cells;
  - randomized-scheduler fuzz green on clean cells;
  - counterexample replay export (pretty print + Perfetto JSON);
  - one-definition delegation by IDENTITY and by consumption-site
    inspection: zero surviving hand transcriptions in
    serve/scheduler.py and serve/autoscale.py (the acceptance bar);
  - `DriftDetector.update` == the pure `cusum_step` emitted rule,
    hysteresis included;
  - the admission watermark at the EXACT boundary (free == promised):
    defer, never thrash — and admit the moment one candidate's need is
    covered;
  - `PageAllocator` property-fuzzed against a jax-free reference
    ledger (conservation, all-or-None, dirty-LIFO recycling order,
    double-free detection), with the exhaustive sweep behind -m slow
    pinned to agree with the graftsched envelope verdicts on the
    overlapping cells.
"""

import inspect
import json
import os

import numpy as np
import pytest

from fpga_ai_nic_tpu.runtime.requests import (DECODE, FINISHED, PREFILL,
                                              WAITING, Request)
from fpga_ai_nic_tpu.serve.paged import PageAllocator, ServeConfig
from fpga_ai_nic_tpu.serve.scheduler import ContinuousBatcher
from fpga_ai_nic_tpu.verify import SCHED_RULES, build_sched, sched_cells
from fpga_ai_nic_tpu.verify.mc import Violation, check, run_random
from fpga_ai_nic_tpu.verify.opstream import (SCHED_DECODE, SCHED_FINISHED,
                                             SCHED_PREFILL, SCHED_WAITING,
                                             SchedEmitter)
from fpga_ai_nic_tpu.verify.replay import export_counterexample
from fpga_ai_nic_tpu.verify.sched import (SCHED_FAULTS, SCHED_MUTANTS,
                                          SchedModel)

# one cell per mutant, the smallest fault-free cell whose clean run
# provably reaches the mutated rule (probed exhaustively; the full
# sweeps below confirm these are not the only ones)
MUTANT_PIN = {
    "leak_evict": (2, 4, 1, "none"),
    "drop_watermark": (2, 2, 1, "none"),
    "no_evict": (3, 4, 2, "none"),
    "drop_cooldown": (3, 3, 2, "none"),
}


class TestEnvelopeShape:
    def test_state_constants_pinned_to_runtime(self):
        # the model's request-state strings ARE the runtime's: a rename
        # on either side breaks the delegation silently otherwise
        assert SCHED_WAITING == WAITING
        assert SCHED_PREFILL == PREFILL
        assert SCHED_DECODE == DECODE
        assert SCHED_FINISHED == FINISHED

    def test_envelope_meets_acceptance_floor(self):
        cells = list(sched_cells())
        assert len(cells) >= 150
        assert len(set(cells)) == len(cells)
        rs = {c[0] for c in cells}
        ps = {c[1] for c in cells}
        ks = {c[2] for c in cells}
        fs = {c[3] for c in cells}
        assert rs == {1, 2, 3, 4} and ps == {2, 3, 4, 5, 6}
        assert ks == {1, 2, 3} and fs == set(SCHED_FAULTS)


class TestCleanEnvelope:
    def test_full_envelope_green(self):
        # the headline guarantee: every cell, faults included, is
        # exhaustively explored with zero violations (~0.2 s total)
        states = 0
        for cell in sched_cells():
            res = check(build_sched(*cell))
            assert res.ok, (cell, res.violation and res.violation.message)
            assert res.terminal_paths >= 1
            states += res.states
        assert states > 10_000      # the exploration is not vacuous

    def test_random_fuzz_clean(self):
        for cell in [(2, 4, 2, "kill"), (3, 5, 3, "handoff-fail"),
                     (4, 6, 3, "kill"), (4, 6, 1, "none")]:
            for seed in range(4):
                assert run_random(build_sched(*cell), seed=seed) > 0


class TestMutants:
    def test_pinned_mutants_trip_their_kind(self):
        for mut, kind in SCHED_MUTANTS.items():
            res = check(build_sched(*MUTANT_PIN[mut], mutate=mut))
            assert not res.ok, mut
            assert res.violation.kind == kind, (mut, res.violation.kind)
            assert res.violation.message
            assert len(res.violation.trace) > 0

    def test_mutant_sweep_trips_only_its_kind(self):
        # full grid x all four mutants: a mutant may stay green on a
        # cell too small to reach its rule, but when it trips, the kind
        # is ALWAYS the intended one — and each trips a healthy share
        floors = {"leak_evict": 20, "drop_watermark": 60,
                  "no_evict": 8, "drop_cooldown": 15}
        for mut, kind in SCHED_MUTANTS.items():
            tripped = 0
            for cell in sched_cells():
                res = check(build_sched(*cell, mutate=mut))
                if not res.ok:
                    assert res.violation.kind == kind, (mut, cell)
                    tripped += 1
            assert tripped >= floors[mut], (mut, tripped)


class TestPorNaiveAgreement:
    def test_clean_cells_agree(self):
        for cell in sched_cells():
            a = check(build_sched(*cell), por=True)
            b = check(build_sched(*cell), por=False)
            assert a.ok and b.ok, cell

    def test_mutated_pins_agree(self):
        for mut, kind in SCHED_MUTANTS.items():
            a = check(build_sched(*MUTANT_PIN[mut], mutate=mut), por=True)
            b = check(build_sched(*MUTANT_PIN[mut], mutate=mut), por=False)
            assert (not a.ok) and (not b.ok), mut
            assert a.violation.kind == b.violation.kind == kind

    @pytest.mark.slow
    def test_mutated_full_grid_agrees(self):
        for mut in SCHED_MUTANTS:
            for cell in sched_cells():
                a = check(build_sched(*cell, mutate=mut), por=True)
                b = check(build_sched(*cell, mutate=mut), por=False)
                assert a.ok == b.ok, (mut, cell)
                if not a.ok:
                    assert a.violation.kind == b.violation.kind, (mut, cell)


class TestCounterexampleReplay:
    def test_export_txt_and_perfetto(self, tmp_path):
        model = build_sched(*MUTANT_PIN["leak_evict"],
                            mutate="leak_evict")
        res = check(model)
        assert not res.ok
        txt, js = export_counterexample(model, res.violation,
                                        str(tmp_path))
        assert os.path.exists(txt) and os.path.exists(js)
        body = open(txt).read()
        assert "conservation" in body
        with open(js) as f:
            doc = json.load(f)
        assert doc["traceEvents"], "Perfetto export is empty"

    def test_violation_is_assertion_error(self):
        # simulate_rs_protocol-style callers catch AssertionError
        assert issubclass(Violation, AssertionError)


class TestDelegationIdentity:
    """The PR-14 emitter discipline, applied to the control plane: the
    model checks the SAME rule objects the hot paths run, pinned by
    identity, and no hand transcription of any emitted rule survives in
    the consumers (the acceptance criterion)."""

    def test_singleton_shared_by_all_consumers(self):
        import fpga_ai_nic_tpu.serve.autoscale as autoscale
        import fpga_ai_nic_tpu.serve.fleet as fleet
        import fpga_ai_nic_tpu.serve.scheduler as scheduler
        import fpga_ai_nic_tpu.tune.adapt as adapt
        import fpga_ai_nic_tpu.verify.sched as vsched
        assert scheduler._RULES is SCHED_RULES
        assert fleet._RULES is SCHED_RULES
        assert autoscale._RULES is SCHED_RULES
        assert adapt._SCHED_RULES is SCHED_RULES
        assert vsched.SCHED_RULES is SCHED_RULES

    def test_scheduler_has_no_hand_transcriptions(self):
        b = ContinuousBatcher
        src = inspect.getsource(b.enqueue)
        assert "_RULES.replay_target" in src
        src = inspect.getsource(b._committed_outstanding)
        assert "_RULES.committed_outstanding" in src
        assert "_RULES.committed_target" in src
        assert "max(" not in src
        src = inspect.getsource(b.admit)
        assert "_RULES.admit_ok" in src
        assert "_RULES.admission_need" in src
        assert ">=" not in src          # the watermark compare lives once
        src = inspect.getsource(b._eviction_victim)
        assert "_RULES.pick_victim" in src
        assert "max(" not in src and "sorted(" not in src
        src = inspect.getsource(b.prefill_work)
        assert "_RULES.pick_oldest" in src
        assert "_RULES.prefill_chunk_len" in src
        assert "min(" not in src
        src = inspect.getsource(b.decode_batch)
        assert "_RULES.decode_order" in src
        assert "_RULES.committed_target" in src
        assert "sorted(" not in src and "n_tokens + 1" not in src

    def test_autoscaler_has_no_hand_transcriptions(self):
        from fpga_ai_nic_tpu.serve.autoscale import Autoscaler
        src = inspect.getsource(Autoscaler.observe_tick)
        assert "_RULES.load_residual" in src
        assert "- 1" not in src         # the residual arithmetic lives once
        src = inspect.getsource(Autoscaler._scale_up)
        assert "_RULES.scale_up_fallback" in src
        assert ">= 2" not in src
        src = inspect.getsource(Autoscaler._scale_down)
        assert "_RULES.scale_down_ok" in src
        assert "== 0" not in src
        src = inspect.getsource(Autoscaler._shed_valve)
        assert "_RULES.shed_action" in src

    def test_model_never_reimplements_rules(self):
        # the model file delegates every policy decision too: the
        # checker explores the shipped rules, not a transcription
        import fpga_ai_nic_tpu.verify.sched as vsched
        src = inspect.getsource(vsched)
        assert src.count("SCHED_RULES.") >= 10


class TestDriftDetectorDelegation:
    def test_update_equals_pure_cusum_step(self):
        from fpga_ai_nic_tpu.tune.adapt import DriftDetector
        det = DriftDetector(drift_rel=0.5, threshold=1.0,
                            cooldown_steps=3)
        pos = neg = 0.0
        cooldown = 0
        series = [0.3, 0.4, 2.0, -5.0, -5.0, -5.0, 0.0, -2.0, 0.1]
        for r in series:
            got = det.update(r)
            pos, neg, cooldown, want = SchedEmitter.cusum_step(
                pos, neg, cooldown, r, 0.5, 1.0, 3)
            assert got == want
            assert (det.pos, det.neg, det.cooldown) == (pos, neg, cooldown)

    def test_cooldown_blocks_opposite_trip(self):
        # the no-flap invariant the model checks, at the unit level: a
        # trip arms the cooldown, so the opposite trip cannot land
        # inside the window however hard the residual swings
        from fpga_ai_nic_tpu.tune.adapt import DriftDetector
        det = DriftDetector(drift_rel=0.5, threshold=1.0,
                            cooldown_steps=3)
        trip = det.update(2.0)
        assert trip is not None and trip[0] == "slow"
        for _ in range(3):
            assert det.update(-100.0) is None     # disarmed window
        trip = det.update(-100.0)
        assert trip is not None and trip[0] == "fast"

    def test_update_source_delegates(self):
        from fpga_ai_nic_tpu.tune.adapt import DriftDetector
        src = inspect.getsource(DriftDetector.update)
        assert "cusum_step" in src
        assert "max(" not in src        # the CUSUM arithmetic lives once


def _req(uid, plen, max_new):
    return Request(uid=uid,
                   prompt=np.arange(plen, dtype=np.int32),
                   max_new=max_new)


class TestWatermarkBoundary:
    """PR-10 admit-thrash regression, at the EXACT boundary: with
    free == promised the watermark defers (never admit-then-evict);
    with free - promised == need it admits, and that admission can run
    its replay + first decode without evicting anyone."""

    def _mk(self, n_pages):
        scfg = ServeConfig(max_reqs=2, page_size=1, n_pages=n_pages,
                           max_pages_per_seq=3, prefill_chunk=4)
        return scfg, ContinuousBatcher(scfg, PageAllocator(n_pages))

    def test_boundary_admit_then_defer(self):
        scfg, b = self._mk(n_pages=4)           # 3 usable pages
        a = _req(1, 2, 1)                       # replay 2 -> need 3
        b.enqueue(a)
        assert [r.uid for r in b.admit()] == [1]   # free - 0 == need: admit
        assert a.state == PREFILL
        c = _req(2, 1, 1)                       # need 2
        b.enqueue(c)
        # free == promised (3 == 3): defer, even though a slot is open
        assert any(s is None for s in b.slots)
        for _ in range(3):                      # stable, never oscillates
            assert b.admit() == []
        assert c.state == WAITING and b.waiting == [c]
        assert b.alloc.free == 3 and b.evictions == 0

    def test_boundary_admission_never_thrashes(self):
        scfg, b = self._mk(n_pages=4)
        a = _req(1, 2, 1)
        b.enqueue(a)
        b.admit()
        # the admitted request's whole promise (replay + first decode)
        # is claimable without a single eviction: need covered it
        assert b.ensure_pages(a, a.replay_len + 1)
        assert b.evictions == 0 and b.alloc.free == 0

    def test_one_page_past_boundary_admits(self):
        scfg, b = self._mk(n_pages=6)           # 5 usable pages
        b.enqueue(_req(1, 2, 1))                # promises 3
        c = _req(2, 1, 1)                       # need 2
        b.enqueue(c)
        # free - promised == need (5 - 3 == 2): the second admission
        # lands at ITS exact boundary
        assert [r.uid for r in b.admit()] == [1, 2]
        assert c.state == PREFILL

    def test_emitted_rule_is_the_boundary(self):
        assert not SCHED_RULES.admit_ok(3, 3, 2)
        assert not SCHED_RULES.admit_ok(4, 3, 2)
        assert SCHED_RULES.admit_ok(5, 3, 2)
        assert SCHED_RULES.admit_ok(6, 3, 2)


class _RefLedger:
    """jax-free reference model of PageAllocator: an explicit free list
    (page n_pages-1 .. 1), alloc pops from the end, free extends — so
    comparing RETURNED ids pins the dirty-LIFO recycling order, not
    just the counts."""

    def __init__(self, n_pages):
        self.n_pages = n_pages
        self.free = list(range(n_pages - 1, 0, -1))

    def alloc(self, n):
        if len(self.free) < n:
            return None                 # all-or-None
        return [self.free.pop() for _ in range(n)]

    def free_pages(self, pages):
        self.free.extend(pages)


class TestPageAllocatorFuzz:
    def _fuzz(self, seed, n_pages, n_ops):
        rng = np.random.default_rng(seed)
        a = PageAllocator(n_pages)
        ref = _RefLedger(n_pages)
        held = []
        for _ in range(n_ops):
            if held and rng.random() < 0.45:
                k = int(rng.integers(1, len(held) + 1))
                batch = [held.pop() for _ in range(k)]
                a.free_pages(batch)
                ref.free_pages(batch)
            else:
                n = int(rng.integers(0, 4))
                got = a.alloc(n)
                want = ref.alloc(n)
                assert got == want      # ids AND order: dirty LIFO
                if got is not None:
                    held.extend(got)
            # conservation, every step
            assert a.free == len(ref.free)
            assert a.free + a.in_use == n_pages - 1
            assert a.in_use == len(held)
            assert len(set(held)) == len(held)
        return a, held

    def test_seeded_fuzz_matches_reference(self):
        for seed in range(6):
            a, held = self._fuzz(seed, n_pages=9, n_ops=250)
            a.free_pages(held)
            assert a.free == 8 and a.in_use == 0

    def test_double_free_detected(self):
        a = PageAllocator(5)
        got = a.alloc(2)
        a.free_pages(got)
        with pytest.raises(RuntimeError, match="double-free"):
            a.free_pages(got)

    def test_out_of_range_rejected(self):
        a = PageAllocator(5)
        with pytest.raises(ValueError):
            a.free_pages([0])           # the reserved null page
        with pytest.raises(ValueError):
            a.free_pages([5])

    def test_alloc_all_or_none_leaves_state_intact(self):
        a = PageAllocator(4)
        assert a.alloc(5) is None
        assert a.free == 3 and a.in_use == 0
        assert a.alloc(3) is not None
        assert a.alloc(1) is None and a.in_use == 3

    @pytest.mark.slow
    def test_exhaustive_sweep_agrees_with_envelope(self):
        # exhaustive alloc/free sequence exploration per pool size,
        # pinned to AGREE with the graftsched envelope verdict on every
        # overlapping fault-free cell: both say conservation holds
        for p in range(2, 7):
            assert self._exhaust_ok(p)
            for r in range(1, 5):
                for k in (1, 2, 3):
                    assert check(build_sched(r, p, k, "none")).ok

    def _exhaust_ok(self, pool):
        # DFS over every alloc(1..2)/free-batch sequence to depth 2*pool
        def step(a, ref, held, depth):
            if depth == 0:
                return True
            for n in (1, 2):
                a2, r2, h2 = _clone(a, ref, held)
                got = a2.alloc(n)
                if got != r2.alloc(n):
                    return False
                if got is not None:
                    h2.extend(got)
                if a2.free + a2.in_use != pool or a2.free != len(r2.free):
                    return False
                if not step(a2, r2, h2, depth - 1):
                    return False
            if held:
                for k in (1, len(held)):
                    a2, r2, h2 = _clone(a, ref, held)
                    batch = [h2.pop() for _ in range(k)]
                    a2.free_pages(batch)
                    r2.free_pages(batch)
                    if a2.free != len(r2.free):
                        return False
                    if not step(a2, r2, h2, depth - 1):
                        return False
            return True

        def _clone(a, ref, held):
            a2 = PageAllocator(a.n_pages)
            a2._free = list(a._free)
            a2.in_use = a.in_use
            a2.peak_in_use = a.peak_in_use
            r2 = _RefLedger(ref.n_pages)
            r2.free = list(ref.free)
            return a2, r2, list(held)

        return step(PageAllocator(pool + 1), _RefLedger(pool + 1),
                    [], 2 * pool)


@pytest.mark.slow
class TestSlowEnvelope:
    def test_fuzz_whole_envelope(self):
        for cell in sched_cells():
            for seed in range(3):
                assert run_random(build_sched(*cell), seed=seed) > 0

    def test_mutant_pins_fuzzable(self):
        # the randomized scheduler finds the pinned violations too for
        # the deterministic-path mutants (no fault-timing branching)
        for mut in ("leak_evict", "drop_watermark"):
            with pytest.raises(AssertionError):
                run_random(build_sched(*MUTANT_PIN[mut], mutate=mut),
                           seed=0)
