"""The multi-chip conversion kit must stay runnable: a broken kit turns
the first real >=2-chip window into a debugging session instead of
evidence (round-5 verdict item 7).  The dryrun canary runs the full
parity checks (XLA psum + fused-vs-XLA BFP ring bit-exactness) on the
virtual mesh in a subprocess, exactly as `make multichip-dryrun` would."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dryrun_canary_passes(tmp_path):
    env = dict(os.environ)
    # state/artifacts isolated so the test never touches banked evidence
    env["MULTICHIP_DRYRUN"] = "1"
    p = subprocess.run(
        [sys.executable, "-u",
         os.path.join(REPO, "tools", "multichip_bench.py"),
         "--child", "canary"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    line = [l for l in p.stdout.splitlines() if l.startswith("{")][-1]
    res = json.loads(line)
    assert res["ok"], res
    assert res["checks"]["psum_parity"]["ok"]
    assert res["checks"]["fused_bfp_ring_parity"]["bit_exact"]


def test_stage_selection_skips_unlisted(monkeypatch, tmp_path):
    """--stages= must restrict the ladder (the CI hook runs canary only)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mcb", os.path.join(REPO, "tools", "multichip_bench.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    calls = []
    monkeypatch.setattr(m, "run_attempt",
                        lambda name, *a, **k: calls.append(name) or
                        {"ok": True})
    monkeypatch.setattr(m, "save_artifact", lambda *a, **k: None)
    monkeypatch.setattr(m, "git_commit_artifacts", lambda *a, **k: None)
    monkeypatch.setattr(m, "STATE_PATH", str(tmp_path / "state.json"))
    monkeypatch.setattr(sys, "argv",
                        ["multichip_bench.py", "--dryrun", "--force",
                         "--stages=canary"])
    assert m.main() == 0
    assert calls == ["canary"]


def test_stage_selection_rejects_unknown(monkeypatch, tmp_path):
    """A typo'd --stages must error, not 'complete' a zero-stage ladder."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mcb2", os.path.join(REPO, "tools", "multichip_bench.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    monkeypatch.setattr(m, "STATE_PATH", str(tmp_path / "state.json"))
    monkeypatch.setattr(sys, "argv",
                        ["multichip_bench.py", "--dryrun",
                         "--stages=busbwz"])
    assert m.main() == 2
    monkeypatch.setattr(sys, "argv",
                        ["multichip_bench.py", "--dryrun", "--stages="])
    assert m.main() == 2


def test_filtered_force_preserves_other_stages(monkeypatch, tmp_path):
    """--force --stages=busbw must clear only busbw: wiping the banked
    canary would make the filtered re-run refuse to escalate."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mcb3", os.path.join(REPO, "tools", "multichip_bench.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    calls = []
    monkeypatch.setattr(m, "run_attempt",
                        lambda name, *a, **k: calls.append(name) or
                        {"ok": True})
    monkeypatch.setattr(m, "save_artifact", lambda *a, **k: None)
    monkeypatch.setattr(m, "git_commit_artifacts", lambda *a, **k: None)
    monkeypatch.setattr(m, "STATE_PATH", str(tmp_path / "state.json"))
    m._save_state({"dryrun": {"canary": {"ok": True},
                              "busbw": {"ok": True}}})
    monkeypatch.setattr(sys, "argv",
                        ["multichip_bench.py", "--dryrun", "--force",
                         "--stages=busbw"])
    assert m.main() == 0
    assert calls == ["busbw"]                     # canary stayed banked
    assert m._load_state()["dryrun"]["canary"]["ok"]


@pytest.mark.slow
def test_zoo_configs_validate_on_cpu():
    """Every zoo config must trace cleanly off-hardware (zoo --validate):
    a config bug discovered on the TPU burns a healthy tunnel window —
    this caught a real one in round 5 (resnet50(dtype=...) didn't exist)."""
    from bench_common import cpu_env
    p = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "tools", "zoo_tpu.py"),
         "--validate"],
        env=cpu_env(1), cwd=REPO, capture_output=True, text=True,
        timeout=900)
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    line = [l for l in p.stdout.splitlines() if '"validated"' in l][-1]
    res = json.loads(line)
    assert res["failed"] == [], res
