"""Multi-host control plane (parallel/multihost.py) on the single-process
CPU mesh: initialize() no-op semantics, process_info readback,
local_batch_to_global == shard_host_batch in the degenerate case, and the
barrier.  True multi-process behavior rides jax.distributed /
make_array_from_process_local_data, which these wrap thinly; the contract
here is that single-process and multi-process use the SAME calls.
"""

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from fpga_ai_nic_tpu.parallel import make_mesh, multihost
from fpga_ai_nic_tpu.parallel.mesh import shard_host_batch
from fpga_ai_nic_tpu.utils.config import MeshConfig


def test_initialize_single_process_is_noop():
    multihost.initialize()          # no coordinator/env: must not raise
    info = multihost.process_info()
    assert info["num_processes"] == 1
    assert info["process_id"] == 0
    assert info["global_devices"] == info["local_devices"] == 8


def test_local_batch_to_global_matches_shard_host_batch(rng):
    mesh = make_mesh(MeshConfig(dp=8))
    x = rng.standard_normal((16, 4)).astype(np.float32)
    got = multihost.local_batch_to_global({"x": x}, mesh, P("dp"))
    want = shard_host_batch({"x": x}, mesh, P("dp"))
    assert got["x"].sharding == want["x"].sharding
    np.testing.assert_array_equal(np.asarray(got["x"]),
                                  np.asarray(want["x"]))
    # result is consumable by a jitted sum like any global array
    assert np.isfinite(float(jax.jit(lambda v: v.sum())(got["x"])))


def test_barrier_single_process():
    multihost.barrier("test")       # must return, not hang
