"""Multi-host control plane (parallel/multihost.py) on the single-process
CPU mesh: initialize() no-op semantics, process_info readback,
local_batch_to_global == shard_host_batch in the degenerate case, and the
barrier.  True multi-process behavior rides jax.distributed /
make_array_from_process_local_data, which these wrap thinly; the contract
here is that single-process and multi-process use the SAME calls.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from fpga_ai_nic_tpu.parallel import make_mesh, multihost
from fpga_ai_nic_tpu.parallel.mesh import shard_host_batch
from fpga_ai_nic_tpu.utils.config import MeshConfig


def test_initialize_single_process_is_noop():
    multihost.initialize()          # no coordinator/env: must not raise
    info = multihost.process_info()
    assert info["num_processes"] == 1
    assert info["process_id"] == 0
    assert info["global_devices"] == info["local_devices"] == 8


def test_local_batch_to_global_matches_shard_host_batch(rng):
    mesh = make_mesh(MeshConfig(dp=8))
    x = rng.standard_normal((16, 4)).astype(np.float32)
    got = multihost.local_batch_to_global({"x": x}, mesh, P("dp"))
    want = shard_host_batch({"x": x}, mesh, P("dp"))
    assert got["x"].sharding == want["x"].sharding
    np.testing.assert_array_equal(np.asarray(got["x"]),
                                  np.asarray(want["x"]))
    # result is consumable by a jitted sum like any global array
    assert np.isfinite(float(jax.jit(lambda v: v.sum())(got["x"])))


def test_barrier_single_process():
    multihost.barrier("test")       # must return, not hang


_WORKER_SRC = r"""
import json, os, sys
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.environ["_MH_REPO"])
from fpga_ai_nic_tpu.parallel import make_mesh, multihost
from fpga_ai_nic_tpu.utils.config import MeshConfig

# initialize() resolves coordinator/nproc/pid from the JAX_* env vars the
# parent set — the mpirun/hostlist ritual as one env-driven call
multihost.initialize()
info = multihost.process_info()
assert info["num_processes"] == 2, info
assert info["global_devices"] == 8, info
assert info["local_devices"] == 4, info

mesh = make_mesh(MeshConfig(dp=8))        # GLOBAL mesh over both processes

# each process contributes only ITS half of the batch (rank r owns rows
# [r*8, (r+1)*8) of the global 16) — the MPI_Scatter analogue
rank = info["process_id"]
local = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)[rank * 8:(rank + 1) * 8]
batch = multihost.local_batch_to_global({"x": local}, mesh, P("dp"))

# cross-process data plane: a jitted global reduction must see BOTH halves
total = float(jax.jit(lambda v: v.sum())(batch["x"]))

# cross-process psum through shard_map over the global mesh
ones = multihost.local_batch_to_global(
    {"o": np.full((4, 1), float(rank + 1), np.float32)}, mesh, P("dp"))
psummed = jax.jit(jax.shard_map(
    lambda v: jax.lax.psum(v.sum(), "dp"), mesh=mesh,
    in_specs=P("dp"), out_specs=P()))(ones["o"])

multihost.barrier("test-two-proc")
print(json.dumps({"rank": rank, "total": total,
                  "psum": float(psummed)}), flush=True)
"""


@pytest.mark.slow
def test_two_process_distributed_cpu():
    """The n_processes=2 control plane, actually exercised (round-3
    verdict item 4): two CPU processes (4 virtual devices each) form one
    8-device mesh via multihost.initialize (coordinator on localhost),
    assemble a global batch from process-local halves, run a jitted
    global reduction and a cross-process psum, and hit the barrier —
    the MPI init/scatter/allreduce/barrier lifecycle of the reference
    (sw/mlp_mpi_example_f32.cpp:195,452-470,688) on jax.distributed."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(rank),
            _MH_REPO=repo,
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER_SRC], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError("two-process run timed out (barrier or "
                                 "collective hang)")
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        # Gloo teardown chatter interleaves with stdout (observed appended
        # to the SAME line as the worker's JSON) — extract the result
        # object by pattern, not by line structure
        import re
        m = re.search(r'\{"rank".*?\}', out)
        assert m, f"no result JSON in worker stdout:\n{out}"
        outs.append(json.loads(m.group(0)))
    want_total = float(np.arange(16 * 4, dtype=np.float32).sum())
    want_psum = float(1.0 * 4 + 2.0 * 4)      # rank1 ones + rank2 twos
    for o in outs:
        assert o["total"] == want_total, outs
        assert o["psum"] == want_psum, outs
    assert {o["rank"] for o in outs} == {0, 1}
