"""The drift observatory + online plan adaptation (fpga_ai_nic_tpu.tune.adapt).

Battery (the ISSUE-13 contract):

- live calibration: the `live` tier overlays measured rates ABOVE every
  banked source with honest provenance (live: prefix, *_live flags,
  dryrun on a CPU mesh); the startup ring microbench produces a real
  calibrated rate on the live mesh;
- candidate set: tune_topk's element 0 is exactly tune()'s argmin, the
  runner-ups come from DISTINCT wire-format groups, the list is
  deterministic and bounded;
- attribution: warmup establishes the measured baseline, steady steps
  read ~zero residual, an injected slowdown reads as collective excess;
- detection: a spike is absorbed, a sustained shift trips, hysteresis
  suppresses re-trips, the fast direction is seen too;
- adaptation: the AdaptiveTrainer traces every candidate ONCE up front,
  a forced regime shift switches plans at a step boundary with ZERO new
  traces (the J13 contract, counted), same-codec switches are BITWISE
  on the training state, codec switches migrate the masters
  value-exactly, and the switch lands as an `adapt.switch` event with
  evidence;
- obs satellites: Ewma first-observation seeding, percentile/summary
  empty guards, the timeline attribution lane and the offset_unknown
  marker.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fpga_ai_nic_tpu import tune
from fpga_ai_nic_tpu.models import mlp
from fpga_ai_nic_tpu.obs import EventStream
from fpga_ai_nic_tpu.obs.metrics import Ewma, MetricsSink, use_sink
from fpga_ai_nic_tpu.parallel import make_mesh
from fpga_ai_nic_tpu.tune import adapt as adapt_lib
from fpga_ai_nic_tpu.tune.calibration import (Calibration, CodecRates,
                                              apply_live,
                                              fixture_calibration as
                                              _pkg_fixture)
from fpga_ai_nic_tpu.utils.config import (AdaptConfig, CollectiveConfig,
                                          MeshConfig, MLPConfig,
                                          OptimizerConfig, TrainConfig)

N = 8
MCFG = MLPConfig(layer_sizes=(32, 64, 10), dtype="float32")


def fixture_calibration(inter_gbps=50.0) -> Calibration:
    """The SHARED fixture regime (tune.calibration.fixture_calibration
    — also the J13 lint surface's and the adapt chaos cells'), with the
    slow-topk variant the stage-rate tests need."""
    return _pkg_fixture(inter_gbps=inter_gbps, topk_gbps=0.2)


def _loss_fn(params, batch):
    return mlp.loss_fn(params, batch, MCFG)


def _batch(rng=0, n=64):
    r = np.random.default_rng(rng)
    x = jnp.asarray(r.standard_normal((n, 32)).astype(np.float32))
    y = jnp.asarray(r.integers(0, 10, n).astype(np.int32))
    return (x, y)


def _cfg(**adapt_kw):
    kw = dict(enabled=True, n_candidates=2, live_calibration=False,
              warmup_steps=2, cooldown_steps=3)
    kw.update(adapt_kw)
    return TrainConfig(
        iters=8, global_batch=64, mesh=MeshConfig(dp=N),
        collective=CollectiveConfig(impl="ring", codec="auto"),
        optimizer=OptimizerConfig(),
        adapt=AdaptConfig(**kw))


def _adaptive(cfg=None, calib=None, events=None, plans=None):
    cfg = cfg or _cfg()
    at = adapt_lib.AdaptiveTrainer(
        _loss_fn, make_mesh(cfg.mesh), cfg, events=events,
        calibration=calib or fixture_calibration(), plans=plans)
    params = mlp.init(jax.random.PRNGKey(0), MCFG)
    state = at.init_state(params)
    batch = at.shard_batch(_batch())
    return at, state, batch


# ---------------------------------------------------------------------------
# live calibration
# ---------------------------------------------------------------------------

class TestLiveCalibration:
    def test_apply_live_overrides_with_provenance(self):
        base = fixture_calibration(inter_gbps=12.0)
        live = apply_live(base, inter_gbps=3.5, dryrun=True,
                          source="unit test")
        assert live.inter_gbps == 3.5
        assert live.inter_calibrated and live.inter_live
        assert live.inter_dryrun          # a CPU live rate stays dryrun
        assert live.inter_source.startswith("live:")
        d = live.describe()
        assert d["inter_live"] is True and d["intra_live"] is False
        # untouched components keep their banked provenance
        assert live.intra_source == base.intra_source

    def test_apply_live_codec_rates_merge(self):
        base = fixture_calibration()
        live = apply_live(base, codec_rates={
            "bfp": {"streaming": CodecRates(2.0, 3.0, "microbench",
                                            True)}},
            dryrun=True)
        enc, dec, measured = live.codec_stage_rates("bfp", "streaming")
        assert (enc, dec, measured) == (2.0, 3.0, True)
        # the live provenance is stamped by apply_live itself, never
        # trusted from the caller's string
        row = live.codec_rates["bfp"]["streaming"]
        assert row.live and row.dryrun
        assert row.source.startswith("live:")
        d = live.describe()["codec_rates"]["bfp"]["streaming"]
        assert d["live"] is True
        # other classes / codecs untouched (and not marked live)
        assert live.codec_stage_rates("bfp", "vmem")[0] == 8.0
        assert not live.codec_rates["bfp"]["vmem"].live
        assert live.codec_stage_rates("topk", "streaming")[0] == 0.2

    def test_apply_live_nothing_measured_is_identity(self):
        base = fixture_calibration()
        assert apply_live(base) is base

    def test_live_calibrate_measures_the_mesh(self):
        cfg = _cfg()
        mesh = make_mesh(cfg.mesh)
        calib = adapt_lib.live_calibrate(
            mesh, "dp", base=fixture_calibration(),
            payload_elems=1 << 12, measure_codecs=True)
        assert calib.inter_calibrated and calib.inter_live
        assert calib.inter_gbps > 0
        assert calib.inter_dryrun         # virtual CPU mesh
        assert "live:" in calib.inter_source
        # the codec microbenches landed at the live tier too
        enc, dec, measured = calib.codec_stage_rates("bfp", "streaming")
        assert measured and enc > 0 and dec > 0

    def test_dptrainer_startup_live_calibration(self):
        """codec='auto' + adapt armed: the trainer resolves its plan on
        live-calibrated rates, with the live provenance banked in
        obs_static_metrics."""
        from fpga_ai_nic_tpu.parallel import DPTrainer
        cfg = _cfg(live_calibration=True)
        tr = DPTrainer(_loss_fn, make_mesh(cfg.mesh), cfg)
        tr.init_state(mlp.init(jax.random.PRNGKey(0), MCFG))
        d = tr.obs_static_metrics()
        cal = d["tune"]["calibration"]
        assert cal["inter_live"] is True
        assert cal["inter_source"].startswith("live:")


# ---------------------------------------------------------------------------
# candidate set
# ---------------------------------------------------------------------------

class TestTuneTopK:
    def test_element_zero_is_the_argmin(self):
        calib = fixture_calibration()
        plans = tune.tune_topk(100000, N, 3, calibration=calib,
                               depths=(1,))
        assert plans[0].candidate == tune.tune(100000, N,
                                               calibration=calib,
                                               depths=(1,)).candidate

    def test_distinct_wire_format_groups(self):
        plans = tune.tune_topk(100000, N, 3,
                               calibration=fixture_calibration(),
                               depths=(1,))
        groups = [(p.candidate.codec, p.candidate.topology,
                   p.candidate.intra_size) for p in plans]
        assert len(set(groups)) == len(groups) == 3

    def test_bounded_and_deterministic(self):
        calib = fixture_calibration()
        a = tune.tune_topk(50000, N, 2, calibration=calib, depths=(1,))
        b = tune.tune_topk(50000, N, 2, calibration=calib, depths=(1,))
        assert len(a) == 2
        assert [p.candidate for p in a] == [p.candidate for p in b]

    def test_slow_wire_promotes_compressed_candidates(self):
        """The SparCML regime: at a crawling link rate the argmin (and
        hence plans[0]) must be a compressed wire format."""
        plans = tune.tune_topk(
            1 << 20, N, 2, calibration=fixture_calibration(0.05),
            depths=(1,))
        assert plans[0].candidate.codec is not None


# ---------------------------------------------------------------------------
# attribution + detection
# ---------------------------------------------------------------------------

class TestAttribution:
    def _attr(self, modeled_coll=0.002, warmup=3):
        return adapt_lib.Attribution(
            {"collective_s": modeled_coll, "stream_s": modeled_coll * 0.8,
             "overhead_s": modeled_coll * 0.2}, warmup_steps=warmup)

    def test_warmup_then_zero_residual(self):
        a = self._attr()
        assert a.observe(0.010) is None
        assert a.observe(0.010) is None
        assert a.observe(0.010) is None   # warmup completes here
        assert a.warmed_up and a.baseline_step_s == 0.010
        assert a.compute_s == pytest.approx(0.008)
        rec = a.observe(0.010)
        assert rec["resid_rel"] == pytest.approx(0.0)
        assert rec["collective_excess_s"] == pytest.approx(0.0)
        assert rec["measured_collective_s"] == pytest.approx(0.002)

    def test_slowdown_reads_as_collective_excess(self):
        a = self._attr()
        for _ in range(3):
            a.observe(0.010)
        rec = a.observe(0.060)            # a 50 ms regime shift
        assert rec["collective_excess_s"] == pytest.approx(0.050)
        assert rec["resid_rel"] == pytest.approx(5.0)
        assert rec["measured_collective_s"] == pytest.approx(0.052)

    def test_rebase_reenters_warmup(self):
        a = self._attr()
        for _ in range(4):
            a.observe(0.010)
        a.rebase({"collective_s": 0.001, "stream_s": 0.0008,
                  "overhead_s": 0.0002})
        assert not a.warmed_up
        assert a.observe(0.020) is None   # warming against the new plan

    def test_ewma_seeded_with_first_observation(self):
        """The satellite contract, on the shared helper: the first
        sample IS the EWMA — no decay up from zero."""
        e = Ewma(0.1)
        assert e.value is None
        assert e.update(42.0) == 42.0     # seeded EXACTLY, not 4.2
        assert e.update(42.0) == pytest.approx(42.0)
        assert e.update(0.0) == pytest.approx(42.0 * 0.9)

    def test_sink_ewma_rides_the_seeded_helper(self):
        sink = MetricsSink(ewma_alpha=0.5)
        sink.update({"loss": 8.0})
        assert sink.as_dict()["loss_ewma"] == 8.0
        sink.update({"loss": 4.0})
        assert sink.as_dict()["loss_ewma"] == 6.0


class TestDriftDetector:
    def test_spike_absorbed_sustained_trips(self):
        det = adapt_lib.DriftDetector(drift_rel=0.75, threshold=3.0,
                                      cooldown_steps=4)
        # one 2x spike: pos accumulates 1.25, then drains through calm
        assert det.update(2.0) is None
        for _ in range(3):
            assert det.update(0.0) is None
        assert det.pos == 0.0
        # a sustained 2x shift accumulates 1.25/step -> trips on step 3
        assert det.update(2.0) is None
        assert det.update(2.0) is None
        trip = det.update(2.0)
        assert trip is not None and trip[0] == "slow"
        assert det.trips == 1

    def test_hysteresis_cooldown(self):
        det = adapt_lib.DriftDetector(drift_rel=0.5, threshold=1.0,
                                      cooldown_steps=3)
        assert det.update(10.0) is not None
        # disarmed: residuals inside the cooldown neither trip nor
        # accumulate — the post-switch re-baselining window
        for _ in range(3):
            assert det.update(0.8) is None
        assert det.pos == 0.0
        # re-armed: a sustained 0.8 shift accumulates 0.3/step and
        # trips only once it crosses the threshold again
        for _ in range(3):
            assert det.update(0.8) is None
        assert det.update(0.8) is not None

    def test_fast_direction(self):
        det = adapt_lib.DriftDetector(drift_rel=0.3, threshold=1.0,
                                      cooldown_steps=2)
        trip = None
        for _ in range(4):
            trip = trip or det.update(-0.8)
        assert trip is not None and trip[0] == "fast"


# ---------------------------------------------------------------------------
# the adaptive trainer
# ---------------------------------------------------------------------------

class TestAdaptiveTrainer:
    def test_requires_auto_and_enabled(self):
        cfg = _cfg()
        cfg_static = dataclasses.replace(
            cfg, collective=CollectiveConfig(impl="ring", codec="bfp"))
        with pytest.raises(ValueError, match="auto"):
            adapt_lib.AdaptiveTrainer(_loss_fn, make_mesh(cfg.mesh),
                                      cfg_static)
        cfg_off = dataclasses.replace(cfg, adapt=AdaptConfig())
        with pytest.raises(ValueError, match="enabled"):
            adapt_lib.AdaptiveTrainer(_loss_fn, make_mesh(cfg.mesh),
                                      cfg_off)

    def test_candidates_traced_once_and_switch_is_trace_free(self):
        """THE acceptance: every candidate traced exactly once at
        prewarm; a forced regime shift switches plans at a step boundary
        with zero new traces."""
        events = EventStream()
        at, state, batch = _adaptive(events=events)
        at.prewarm(batch)
        assert set(at.trace_counts().values()) == {1}
        for _ in range(3):
            state, _ = at.step(state, batch)
        assert at.recompiles_across_switch == 0
        at.controller.inject_shift(1e-4, step=3)
        state, _ = at.step(state, batch)
        assert at.switches == 1 and at.active != 0
        for _ in range(2):
            state, _ = at.step(state, batch)
        assert at.recompiles_across_switch == 0, at.trace_counts()
        assert set(at.trace_counts().values()) == {1}
        # the switch landed as an event with evidence
        sw = [e for e in events.snapshot() if e["name"] == "adapt.switch"]
        assert len(sw) == 1
        a = sw[0]["attrs"]
        assert a["from_plan"] != a["to_plan"]
        assert a["step"] == 3 and "effective_inter_gbps" in a

    def test_same_codec_switch_is_bitwise(self):
        """Depth/bucket-class switches (same codec, same layout) must
        pass the training state through UNTOUCHED: the switched run is
        bitwise identical to never switching."""
        calib = fixture_calibration()
        base = tune.tune_topk(100000, N, 1, calibration=calib,
                              depths=(1,))[0]
        alt = dataclasses.replace(
            base, candidate=dataclasses.replace(
                base.candidate, bucket_elems=1 << 18))
        plans = [base, alt]
        at, state, batch = _adaptive(calib=calib, plans=plans)
        ref, rstate, rbatch = _adaptive(calib=calib, plans=[base])

        for i in range(5):
            if i == 2:
                at.controller.inject_shift(calib.inter_gbps, step=i)
                # force plan 1 regardless of scoring ties
                at.controller._pending = adapt_lib.SwitchDecision(
                    1, {"direction": "test", "detected_step": i})
            state, _ = at.step(state, batch)
            rstate, _ = ref.step(rstate, rbatch)
        assert at.switches == 1 and at.active == 1
        assert at.switch_events[0]["bitwise"] is True
        for a, b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(rstate)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_codec_switch_migrates_masters_value_exactly(self):
        """A codec switch re-pads the masters/moments onto the target
        layout: live elements value-exact, EF residual re-zeroed."""
        at, state, batch = _adaptive(_cfg(n_candidates=3))
        at.prewarm(batch)
        state, _ = at.step(state, batch)
        # find a candidate with a different codec than the active plan
        tgt = next(i for i, p in enumerate(at.plans)
                   if p.candidate.codec != at.plans[0].candidate.codec)
        src_tr, tgt_tr = at.trainers[0], at.trainers[tgt]
        live = sum(src_tr._meta.sizes)
        before = np.asarray(state.w_own)[:live]
        mstate = at._migrate(state, 0, tgt)
        after = np.asarray(mstate.w_own)
        assert after.shape[0] == tgt_tr._meta.padded_len
        np.testing.assert_array_equal(before, after[:live])
        assert np.all(after[live:] == 0)
        # and the migrated state steps on the target plan
        at.controller._pending = adapt_lib.SwitchDecision(
            tgt, {"direction": "test", "detected_step": 1})
        state, loss = at.step(state, batch)
        assert at.active == tgt
        assert np.isfinite(float(loss))
        assert at.recompiles_across_switch == 0, at.trace_counts()

    def test_detected_shift_with_same_argmin_rebases_only(self):
        at, state, batch = _adaptive()
        at.prewarm(batch)
        for _ in range(3):
            state, _ = at.step(state, batch)
        # at the calibrated rate the argmin IS the active plan
        at.controller.inject_shift(at.calibration.inter_gbps, step=3)
        state, _ = at.step(state, batch)
        assert at.switches == 0 and at.active == 0
        assert not at.controller.attribution.warmed_up  # rebased

    def test_drift_metrics_stream_to_sink_and_events(self):
        events = EventStream()
        sink = MetricsSink()
        at, state, batch = _adaptive(events=events)
        with use_sink(sink):
            for _ in range(5):
                state, _ = at.step(state, batch)
        assert "tune.drift.resid_rel" in sink.latest
        assert "tune.drift.modeled_collective_s" in sink.latest
        names = {e["name"] for e in events.snapshot()}
        assert "tune.drift.resid_rel_ewma" in names
        spans = [e for e in events.snapshot()
                 if e["kind"] == "span"
                 and (e.get("attrs") or {}).get("lane") == "attribution"]
        assert spans, "attribution lane spans missing"
        stages = {e["attrs"]["stage"] for e in spans}
        assert {"measured step", "compute (baseline)",
                "collective (modeled)"} <= stages

    def test_obs_static_metrics_banks_the_candidate_set(self):
        at, state, batch = _adaptive()
        d = at.obs_static_metrics()
        ad = d["adapt"]
        assert ad["n_candidates"] == 2 and ad["active"] == 0
        assert len(ad["candidates"]) == 2
        assert ad["recompiles_across_switch"] == 0
        assert ad["calibration"]["inter_source"] == "fixture"

    def test_controller_retarget_is_candidate_bounded(self):
        at, state, batch = _adaptive(_cfg(n_candidates=3))
        c = at.controller
        # dead-slow wire: cheapest wire format among the CANDIDATES
        tgt = c.retarget(1e-4)
        assert 0 <= tgt < len(at.plans)
        assert at.plans[tgt].candidate.codec is not None
        # fast wire: the original argmin
        assert c.retarget(at.calibration.inter_gbps) == 0


# ---------------------------------------------------------------------------
# obs satellites: empty-series guards + timeline
# ---------------------------------------------------------------------------

class TestObsSatellites:
    def test_percentile_empty_returns_nan(self):
        from fpga_ai_nic_tpu.obs.metrics import percentile
        assert np.isnan(percentile([], 95.0))

    def test_request_spans_empty_summary_flags(self):
        import json
        from fpga_ai_nic_tpu.obs.metrics import RequestSpans
        s = RequestSpans().summary()
        assert s["completed"] == 0
        assert s["ttft_empty"] is True and s["latency_empty"] is True
        # JSON-safe not-a-number: None (null), never float NaN — the
        # summary lands verbatim in banked artifacts and bare NaN is
        # not valid strict JSON
        assert s["ttft_p95_s"] is None and s["latency_mean_s"] is None
        json.loads(json.dumps(s, allow_nan=False))   # strict round-trip

    def test_request_spans_nonempty_has_no_empty_flags(self):
        from fpga_ai_nic_tpu.obs.metrics import RequestSpans
        rs = RequestSpans()
        rs.record(1, t_submit=0.0, t_admit=0.1, t_first=0.2, t_done=0.5,
                  n_tokens=4)
        s = rs.summary()
        assert "ttft_empty" not in s
        assert s["ttft_p95_s"] == pytest.approx(0.2)

    def test_timeline_attribution_lane(self):
        from fpga_ai_nic_tpu.obs import timeline
        ev = EventStream()
        ev.emit("span", "attr.step_measured", t_ns=ev.now_ns(),
                dur_ns=1000000,
                attrs={"lane": "attribution", "stage": "measured step"})
        ev.emit("span", "attr.collective_modeled", t_ns=ev.now_ns(),
                dur_ns=400000,
                attrs={"lane": "attribution",
                       "stage": "collective (modeled)"})
        trace = timeline.chrome_trace(ev.snapshot())
        xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        attrib = [e for e in xs if e["pid"] == 4]
        assert len(attrib) == 2
        # one thread per stage, named in the metadata
        metas = [e for e in trace["traceEvents"]
                 if e["ph"] == "M" and e["pid"] == 4
                 and e["name"] == "thread_name"]
        assert {m["args"]["name"] for m in metas} == {
            "measured step", "collective (modeled)"}

    def test_timeline_offset_unknown_marker(self):
        from fpga_ai_nic_tpu.obs import timeline
        ev = EventStream()
        with ev.span("host.work"):
            pass
        dev = [{"plane": "/device:TPU:0", "line": "XLA Ops",
                "name": "fusion.1", "start_ns": 1000, "end_ns": 5000,
                "cls": "sync"}]
        # no anchor span in the stream -> explicit offset_unknown
        trace = timeline.chrome_trace(ev.snapshot(), dev)
        assert trace["otherData"]["device_alignment"] == "offset_unknown"
        markers = [e for e in trace["traceEvents"]
                   if e["ph"] == "i" and e["name"] == "offset_unknown"]
        assert len(markers) == 1 and "anchor" in markers[0]["args"]["why"]

    def test_timeline_anchored_has_no_marker(self):
        from fpga_ai_nic_tpu.obs import timeline
        ev = EventStream()
        with ev.span("jax_profile"):
            pass
        dev = [{"plane": "/device:TPU:0", "line": "XLA Ops",
                "name": "fusion.1", "start_ns": 1000, "end_ns": 5000,
                "cls": "sync"}]
        trace = timeline.chrome_trace(ev.snapshot(), dev)
        assert trace["otherData"]["device_alignment"] == "anchored"
        assert not [e for e in trace["traceEvents"]
                    if e["name"] == "offset_unknown"]


# ---------------------------------------------------------------------------
# chaos: the sustained-fault helper
# ---------------------------------------------------------------------------

class TestSustainedPlan:
    def test_one_spec_per_step(self):
        from fpga_ai_nic_tpu.runtime import chaos
        plan = chaos.FaultPlan.sustained(
            "slowdown", "collective", start_step=5, n_steps=4,
            duration_s=0.01)
        assert len(plan.faults) == 4
        assert [s.step for s in plan.faults] == [5, 6, 7, 8]
        assert all(s.kind == "slowdown" and s.site == "collective"
                   for s in plan.faults)

    def test_adapt_config_validation(self):
        with pytest.raises(ValueError, match="n_candidates"):
            AdaptConfig(enabled=True, n_candidates=1)
        # disabled: a one-candidate config is fine (nothing armed)
        AdaptConfig(enabled=False, n_candidates=1)
