"""Test fixture: 8 virtual CPU devices.

The reference's only multi-node test story is a confidential, absent RTL
testbench simulating a 3-FPGA ring (readme.pdf §3.2, hw/README:1).  We make
multi-device testing first-class instead: every test runs on an 8-device
virtual CPU mesh so ring collectives, shardings and the full train step are
exercised without hardware.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
