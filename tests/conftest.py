"""Test fixture: 8 virtual CPU devices.

The reference's only multi-node test story is a confidential, absent RTL
testbench simulating a 3-FPGA ring (readme.pdf §3.2, hw/README:1).  We make
multi-device testing first-class instead: every test runs on an 8-device
virtual CPU mesh so ring collectives, shardings and the full train step are
exercised without hardware.

This container's sitecustomize eagerly registers the single-chip TPU (axon)
backend before any user code runs, so mutating JAX_PLATFORMS here is too
late — if we detect the wrong platform we re-exec pytest once with the CPU
mesh environment.
"""

import os
import re
import sys

# replace (not merely append) any inherited device-count flag: the suite is
# written against exactly 8 virtual devices
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""))
_flags = (_flags.strip() + " --xla_force_host_platform_device_count=8").strip()

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": _flags,
    "PALLAS_AXON_POOL_IPS": "",      # disable eager TPU-tunnel registration
    "_FPGA_AI_NIC_TPU_REEXEC": "1",
}


def _needs_reexec() -> bool:
    if os.environ.get("_FPGA_AI_NIC_TPU_REEXEC"):
        return False
    # Decide from env vars ALONE.  Importing jax here would initialize the
    # eagerly-registered TPU backend, whose import/first query can hang
    # outright when the tunnel is wedged — the deciding process must never
    # touch jax (same rule as __graft_entry__.dryrun_multichip).
    return (os.environ.get("JAX_PLATFORMS") != "cpu"
            or not re.search(r"--xla_force_host_platform_device_count=8\b",
                             os.environ.get("XLA_FLAGS", "")))


def pytest_configure(config):
    if _needs_reexec():
        # pytest captures at the fd level; release fds 1/2 before exec so the
        # replacement process writes to the real terminal.
        capman = config.pluginmanager.getplugin("capturemanager")
        if capman is not None:
            capman.stop_global_capturing()
        env = dict(os.environ, **_ENV)
        os.execvpe(sys.executable,
                   [sys.executable, "-m", "pytest"] + sys.argv[1:], env)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
