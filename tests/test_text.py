"""Real-data text pipeline: tokenizer round-trip, LM window packing, label
shift/masking, and an end-to-end tiny-Llama training run on real text."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fpga_ai_nic_tpu import text


TOK = text.ByteTokenizer()
DOCS = ["the quick brown fox jumps over the lazy dog. " * 4,
        "pack my box with five dozen liquor jugs! " * 5,
        "sphinx of black quartz, judge my vow — again. " * 6]


def test_byte_tokenizer_roundtrip():
    s = "héllo 世界 \U0001f680"
    ids = TOK.encode(s)
    assert all(0 <= i < 256 for i in ids)
    assert TOK.decode(ids) == s
    assert TOK.vocab_size == 259
    # specials sit above the byte range and survive decode as dropped
    assert TOK.decode([TOK.bos_id] + TOK.encode("ab") + [TOK.eos_id]) == "ab"


def test_pack_windows_static_and_contiguous():
    S = 32
    ws = list(text.pack_windows(DOCS, TOK, S, epochs=1))
    assert len(ws) >= 3
    assert all(w.shape == (S + 1,) and w.dtype == np.int32 for w in ws)
    # windows overlap by exactly one token (every target exists)
    for a, b in zip(ws, ws[1:]):
        assert a[-1] == b[0]
    # reconstruction: de-overlapped concatenation equals the packed stream
    stream = list(ws[0]) + [t for w in ws[1:] for t in w[1:]]
    want = [TOK.bos_id]
    for d in DOCS:
        want += TOK.encode(d) + [TOK.eos_id]
    assert stream == want[:len(stream)]


def test_pack_windows_generator_source_multi_epoch():
    """A one-shot iterator source must survive epochs != 1 (captured and
    replayed), matching the restartable-list behavior window for window —
    the round-3 advisor's mid-training 'empty corpus' crash."""
    S = 32
    want = list(text.pack_windows(DOCS, TOK, S, epochs=2))
    got = list(text.pack_windows(iter(DOCS), TOK, S, epochs=2))
    assert len(got) == len(want) > 0
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)
    # epochs=None (the train_llama path): take a few windows past the
    # first epoch boundary without exhausting the infinite stream
    n_take = len(want) + 2
    it = text.pack_windows(iter(DOCS), TOK, S, epochs=None)
    got_inf = [next(it) for _ in range(n_take)]
    assert len(got_inf) == n_take


def test_lm_batches_shift_and_boundary_mask():
    B, S = 4, 32
    batches = list(text.lm_batches(DOCS * 8, TOK, batch_size=B, seq_len=S,
                                   shuffle_buffer=8, epochs=1))
    assert batches, "corpus must yield at least one batch"
    for toks, labels in batches:
        assert toks.shape == (B, S) and labels.shape == (B, S)
        # unmasked labels are the next token; masked ones sit where the
        # context position is an eos (next token starts a foreign doc)
        mask = labels == -100
        np.testing.assert_array_equal(toks[mask], TOK.eos_id)
        assert not np.any(labels[~mask] < 0)


def test_lm_batches_deterministic_per_seed():
    kw = dict(batch_size=2, seq_len=16, shuffle_buffer=4, epochs=1)
    a = list(text.lm_batches(DOCS * 4, TOK, seed=3, **kw))
    b = list(text.lm_batches(DOCS * 4, TOK, seed=3, **kw))
    for (ta, la), (tb, lb) in zip(a, b):
        np.testing.assert_array_equal(ta, tb)
        np.testing.assert_array_equal(la, lb)


def test_directory_and_file_sources(tmp_path):
    (tmp_path / "a.txt").write_text("first doc\n\nsecond doc\n")
    (tmp_path / "b.txt").write_text("third doc\n")
    docs = list(text._iter_texts(str(tmp_path)))
    assert [d.strip() for d in docs] == ["first doc", "second doc",
                                         "third doc"]


def test_llama_trains_on_real_text():
    """End to end: byte-tokenized real text through ShardedLoader into the
    DP trainer; the loss on a fixed corpus must decrease."""
    from fpga_ai_nic_tpu import data
    from fpga_ai_nic_tpu.models import llama
    from fpga_ai_nic_tpu.parallel import DPTrainer, make_mesh
    from fpga_ai_nic_tpu.utils.config import (CollectiveConfig, MeshConfig,
                                              OptimizerConfig, TrainConfig)
    B, S, iters = 8, 32, 6
    # vocab rounded up to a lane multiple (the text module's sizing advice)
    mcfg = llama.LlamaConfig.tiny(vocab=384)
    cfg = TrainConfig(iters=iters, global_batch=B, mesh=MeshConfig(dp=4),
                      collective=CollectiveConfig(impl="xla"),
                      optimizer=OptimizerConfig(kind="adamw",
                                                learning_rate=3e-3))
    mesh = make_mesh(cfg.mesh)
    tr = DPTrainer(
        lambda p, b: llama.loss_fn(p, b, mcfg, dp_axis="dp"), mesh, cfg)
    state = tr.init_state(llama.init(jax.random.PRNGKey(0), mcfg))
    stream = text.lm_batches(DOCS * 40, TOK, batch_size=B, seq_len=S,
                             shuffle_buffer=16, epochs=None)
    loader = data.ShardedLoader(stream, mesh, tr.batch_spec, prefetch=2)
    losses = []
    for i, batch in enumerate(loader):
        state, loss = tr.step(state, batch)
        losses.append(float(loss))
        if i + 1 >= iters:
            break
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
