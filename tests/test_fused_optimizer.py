"""Fused decode+accumulate+optimizer numerics and wiring.

The spec-enforcement layer of the fused-optimizer subsystem
(docs/FUSED_OPTIMIZER.md):

- the in-kernel Pallas update (both residency variants, every pipeline
  depth) is bit-exact against the composed golden — the codec-generic
  numpy ring golden feeding optim.golden_fused_apply;
- the non-kernel route (separate-op ring / psum_scatter +
  optim.fused_apply_flat) meets the SAME golden for every registered
  codec, so the numerics contract is uniform across routes;
- the gradient path of the fused kernel stays bit-identical to the
  unfused kernel at every depth (fusion changes the schedule, never the
  gradient bits);
- hyperparameters are SMEM/traced scalars: an lr change never retraces
  the kernel;
- trainers thread the fused state (+ EF residual) and reject the
  configs the fused path cannot honor;
- multi-step fused-vs-unfused Adam trajectories agree within the
  codec's error envelope (convergence smoke).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fpga_ai_nic_tpu import compress, optim
from fpga_ai_nic_tpu.compress import golden
from fpga_ai_nic_tpu.models import mlp
from fpga_ai_nic_tpu.ops import bfp_golden, fused_update
from fpga_ai_nic_tpu.ops import ring_pallas as rp
from fpga_ai_nic_tpu.utils.config import (BFPConfig, CollectiveConfig,
                                          MeshConfig, MLPConfig,
                                          OptimizerConfig, OptimizerSpec,
                                          TrainConfig)

N = 8
KINDS = ("sgd", "momentum", "adamw")


def _mesh(n=N):
    return Mesh(np.array(jax.devices()[:n]), ("dp",))


def _opt_cfg(kind):
    return OptimizerConfig(kind=kind, learning_rate=3e-3,
                           momentum=0.9, weight_decay=0.01)


def _init_state(kind, C, rng):
    spec = OptimizerSpec(kind=kind)
    st = {}
    for k in spec.state_keys:
        v = rng.standard_normal(C).astype(np.float32) * 0.01
        st[k] = np.abs(v) if k == "v" else v
    return st


def _bfp_sublane_rt(cfg):
    def rt(v):
        mant, se = bfp_golden.bfp_encode(v, cfg.block_size,
                                         cfg.mantissa_bits, cfg.rounding,
                                         layout="sublane")
        return bfp_golden.bfp_decode(mant, se, cfg.block_size,
                                     layout="sublane")
    return rt


# ---------------------------------------------------------------------------
# golden twin sanity: the twin must BE an optimizer (not just a formula)
# ---------------------------------------------------------------------------

def test_golden_twin_close_to_reference_optimizer(rng):
    """The fused formula is a reformulation (EMA increments, reciprocal
    bias corrections), not a different optimizer: one step must agree
    with optim.apply to float32 roundoff."""
    C = 4096
    g = rng.standard_normal(C).astype(np.float32)
    w = rng.standard_normal(C).astype(np.float32) * 0.1
    for kind in KINDS:
        cfg = _opt_cfg(kind)
        st = _init_state(kind, C, rng)
        hyper = np.asarray(optim.fused_hyperparams(
            cfg, jnp.zeros((), jnp.int32)))
        w_twin, _ = optim.golden_fused_apply(kind, w, g * N, st, hyper, N)
        w_ref, _ = optim.apply(cfg, jnp.asarray(w), jnp.asarray(g),
                               {k: jnp.asarray(v) for k, v in st.items()},
                               jnp.zeros((), jnp.int32))
        np.testing.assert_allclose(w_twin, np.asarray(w_ref),
                                   rtol=5e-5, atol=5e-7)


def test_fused_apply_flat_bitexact_vs_twin(rng):
    """The jnp fused formula == the numpy twin, bit for bit, for every
    optimizer (the FMA-contraction contract on this container)."""
    C = 8192
    g_sum = (rng.standard_normal(C) * N).astype(np.float32)
    w = rng.standard_normal(C).astype(np.float32) * 0.1
    for kind in KINDS:
        cfg = _opt_cfg(kind)
        spec = OptimizerSpec(kind=kind)
        st = _init_state(kind, C, rng)
        hyper = optim.fused_hyperparams(cfg, jnp.zeros((), jnp.int32))
        w2, st2 = jax.jit(optim.fused_apply_flat, static_argnums=0)(
            spec, jnp.asarray(w), jnp.asarray(g_sum),
            {k: jnp.asarray(v) for k, v in st.items()}, hyper, N)
        w_t, st_t = optim.golden_fused_apply(kind, w, g_sum, st,
                                             np.asarray(hyper), N)
        assert np.array_equal(np.asarray(w2), w_t), kind
        for k in spec.state_keys:
            assert np.array_equal(np.asarray(st2[k]), st_t[k]), (kind, k)


# ---------------------------------------------------------------------------
# in-kernel fused update: bit-exact vs composed golden, both residencies,
# every pipeline depth
# ---------------------------------------------------------------------------

def _run_fused_kernel(x, w, st, hyper, kind, bcfg, slice_elems, streaming,
                      depth, n=N):
    def shard_fn(xv, wv, *stv):
        g, w2, st2 = rp.ring_reduce_scatter_update_fused(
            xv, wv, dict(zip(OptimizerSpec(kind=kind).state_keys, stv)),
            hyper, "dp", opt_kind=kind, compression=bcfg,
            slice_elems=slice_elems, interpret=True, streaming=streaming,
            pipeline_depth=depth)
        return (g, w2) + tuple(st2[k]
                               for k in OptimizerSpec(kind=kind).state_keys)

    spec = OptimizerSpec(kind=kind)
    args = (x.reshape(-1), w.reshape(-1)) + tuple(
        st[k].reshape(-1) for k in spec.state_keys)
    out = jax.jit(jax.shard_map(
        shard_fn, mesh=_mesh(n), in_specs=(P("dp"),) * len(args),
        out_specs=(P("dp"),) * (2 + spec.n_state), check_vma=False))(
        *(jnp.asarray(a) for a in args))
    C = x.shape[1] // n
    g_got = np.asarray(out[0]).reshape(n, C)
    w_got = np.asarray(out[1]).reshape(n, C)
    st_got = {k: np.asarray(v).reshape(n, C)
              for k, v in zip(spec.state_keys, out[2:])}
    return g_got, w_got, st_got


# tier-1 wall-budget split: the fast tier keeps the two most
# informative corners — adamw-streaming (2 state tensors + every
# streaming DMA window) and sgd-vmem (the cheapest other corner) — and
# the four redundant (kind, residency) combinations ride -m slow, which
# `make test` (the full CI gate) still runs.  Coverage is unchanged;
# only the fast tier's cost is.
@pytest.mark.parametrize("kind,streaming", [
    pytest.param("sgd", False, id="sgd-vmem"),
    pytest.param("sgd", True, id="sgd-streaming",
                 marks=pytest.mark.slow),
    pytest.param("momentum", False, id="momentum-vmem",
                 marks=pytest.mark.slow),
    pytest.param("momentum", True, id="momentum-streaming",
                 marks=pytest.mark.slow),
    pytest.param("adamw", False, id="adamw-vmem",
                 marks=pytest.mark.slow),
    pytest.param("adamw", True, id="adamw-streaming"),
])
def test_kernel_update_bitexact_vs_composed_golden(kind, streaming, rng):
    """{sgd, momentum, adamw} x {vmem, streaming} x depth: the fused
    Pallas kernels == codec ring golden -> optimizer twin, bit for bit,
    and the gradient output == the unfused kernel at every depth."""
    bcfg = BFPConfig()
    S, R = 4, 16                     # chunk = 4 slices of 16 rows
    C = S * R * rp.LANES
    L = N * C
    x = (rng.standard_normal((N, L)) * 3).astype(np.float32)
    w = rng.standard_normal((N, C)).astype(np.float32) * 0.1
    st = {k: v.reshape(N, C) for k, v in _init_state(
        kind, N * C, rng).items()}
    hyper = optim.fused_hyperparams(_opt_cfg(kind), jnp.zeros((), jnp.int32))
    hyp = np.asarray(hyper)
    g_want = golden.ring_reduce_scatter(x, _bfp_sublane_rt(bcfg))
    w_want = np.zeros_like(w)
    st_want = {k: np.zeros_like(v) for k, v in st.items()}
    for i in range(N):
        w_want[i], st_i = optim.golden_fused_apply(
            kind, w[i], g_want[i], {k: v[i] for k, v in st.items()},
            hyp, N)
        for k in st_i:
            st_want[k][i] = st_i[k]

    for depth in (1, 2, 3):
        g_got, w_got, st_got = _run_fused_kernel(
            x, w, st, hyper, kind, bcfg, R * rp.LANES, streaming, depth)
        assert np.array_equal(g_got, g_want), (kind, streaming, depth)
        assert np.array_equal(w_got, w_want), (kind, streaming, depth)
        for k in st_got:
            assert np.array_equal(st_got[k], st_want[k]), (
                kind, streaming, depth, k)


def test_depth1_gradient_path_matches_unfused_kernel(rng):
    """depth=1 (and every depth) must reproduce the unfused kernel's
    schedule bit-for-bit on the gradient path: the fused kernel's g_own
    output == ring_reduce_scatter_fused on identical inputs."""
    bcfg = BFPConfig()
    S, R = 2, 16
    C = S * R * rp.LANES
    L = N * C
    x = (rng.standard_normal((N, L)) * 3).astype(np.float32)
    w = np.zeros((N, C), np.float32)
    st = {"m": np.zeros((N, C), np.float32)}
    hyper = optim.fused_hyperparams(_opt_cfg("momentum"),
                                    jnp.zeros((), jnp.int32))
    for streaming in (False, True):
        for depth in (1, 2):
            g_got, _, _ = _run_fused_kernel(
                x, w, st, hyper, "momentum", bcfg, R * rp.LANES,
                streaming, depth)
            plain = jax.jit(jax.shard_map(
                lambda v: rp.ring_reduce_scatter_fused(
                    v, "dp", compression=bcfg, slice_elems=R * rp.LANES,
                    interpret=True, streaming=streaming,
                    pipeline_depth=depth),
                mesh=_mesh(), in_specs=P("dp"), out_specs=P("dp"),
                check_vma=False))(jnp.asarray(x.reshape(-1)))
            assert np.array_equal(g_got,
                                  np.asarray(plain).reshape(N, C)), (
                streaming, depth)


def test_hyperparams_do_not_recompile(monkeypatch, rng):
    """lr / weight-decay / step changes ride the SMEM hyper vector: one
    jitted step, called with different hyper VALUES, must trace the
    kernel exactly once — and produce different updates (the scalars are
    live, not baked)."""
    traces = []
    orig = rp._rs_kernel

    def counting(*a, **k):
        traces.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(rp, "_rs_kernel", counting)
    bcfg = BFPConfig()
    S, R = 2, 16
    C = S * R * rp.LANES
    x = (rng.standard_normal((N, N * C))).astype(np.float32)
    w = (rng.standard_normal((N, C)) * 0.1).astype(np.float32)
    st = {"m": np.zeros((N, C), np.float32)}

    def shard_fn(hy, xv, wv, mv):
        g, w2, st2 = rp.ring_reduce_scatter_update_fused(
            xv, wv, {"m": mv}, hy, "dp", opt_kind="momentum",
            compression=bcfg, slice_elems=R * rp.LANES, interpret=True,
            streaming=False, pipeline_depth=2)
        return w2

    step_fn = jax.jit(jax.shard_map(
        shard_fn, mesh=_mesh(),
        in_specs=(P(),) + (P("dp"),) * 3, out_specs=P("dp"),
        check_vma=False))
    outs, trace_counts = [], []
    for lr, step in ((1e-3, 0), (7e-2, 5)):
        hyper = optim.fused_hyperparams(
            OptimizerConfig(kind="momentum", learning_rate=lr),
            jnp.asarray(step, jnp.int32))
        outs.append(np.asarray(step_fn(
            hyper, jnp.asarray(x.reshape(-1)), jnp.asarray(w.reshape(-1)),
            jnp.asarray(st["m"].reshape(-1)))))
        trace_counts.append(sum(traces))
    # the kernel may already sit in jit caches from earlier tests (0
    # traces) or trace once fresh (1); the invariant is that the SECOND
    # hyper value adds nothing
    assert trace_counts[0] <= 1, trace_counts
    assert trace_counts[1] == trace_counts[0], \
        "hyper change retraced the fused kernel"
    assert not np.array_equal(outs[0], outs[1]), "hyper scalars are dead"


# ---------------------------------------------------------------------------
# route-level parity: every codec through reduce_scatter_update
# ---------------------------------------------------------------------------

ROUTE_CODECS = [
    (None, ()),
    ("bfp", ()),
    ("topk", (("bucket_elems", 512), ("k", 64))),
    ("int8", ()),
]


@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.parametrize("name,opts", ROUTE_CODECS,
                         ids=[n or "none" for n, o in ROUTE_CODECS])
def test_route_update_bitexact_vs_composed_golden(name, opts, kind, rng):
    """fused_update.reduce_scatter_update on the separate-op ring route
    (the CPU/off-TPU path, any codec): reduce == the codec-generic ring
    golden and update == the optimizer twin, bit for bit — the SAME
    numerics contract as the in-kernel path."""
    coll = CollectiveConfig(impl="ring", codec=name, codec_opts=opts,
                            fused_optimizer=True)
    codec = compress.resolve(coll)
    L = N * 2048
    C = L // N
    x = (rng.standard_normal((N, L)) * 3).astype(np.float32)
    w = rng.standard_normal((N, C)).astype(np.float32) * 0.1
    st = {k: v.reshape(N, C)
          for k, v in _init_state(kind, N * C, rng).items()}
    spec = OptimizerSpec(kind=kind)
    opt_cfg = _opt_cfg(kind)
    step = jnp.zeros((), jnp.int32)

    def shard_fn(xv, wv, *stv):
        g, w2, st2 = fused_update.reduce_scatter_update(
            xv, wv, dict(zip(spec.state_keys, stv)), step, "dp", coll,
            opt_cfg)
        return (g, w2) + tuple(st2[k] for k in spec.state_keys)

    args = (x.reshape(-1), w.reshape(-1)) + tuple(
        st[k].reshape(-1) for k in spec.state_keys)
    out = jax.jit(jax.shard_map(
        shard_fn, mesh=_mesh(), in_specs=(P("dp"),) * len(args),
        out_specs=(P("dp"),) * (2 + spec.n_state)))(
        *(jnp.asarray(a) for a in args))
    g_got = np.asarray(out[0]).reshape(N, C)
    w_got = np.asarray(out[1]).reshape(N, C)

    rt = golden.roundtrip_fn(codec) if codec is not None else None
    g_want = golden.ring_reduce_scatter(x, rt)
    assert np.array_equal(g_got, g_want), (name, kind)
    hyp = np.asarray(optim.fused_hyperparams(opt_cfg, step))
    for i in range(N):
        w_i, st_i = optim.golden_fused_apply(
            kind, w[i], g_want[i], {k: v[i] for k, v in st.items()},
            hyp, N)
        assert np.array_equal(w_got[i], w_i), (name, kind, i)
        for k in spec.state_keys:
            assert np.array_equal(
                np.asarray(out[2 + spec.state_keys.index(k)]
                           ).reshape(N, C)[i], st_i[k]), (name, kind, k)


# ---------------------------------------------------------------------------
# config / trainer wiring
# ---------------------------------------------------------------------------

def test_fused_optimizer_config_validation():
    # fused_optimizer + integrity_check constructs since PR 12: the exact
    # wire-checksum tier rides the fused path (in-kernel accumulation on
    # TPU, in-graph gate on the shared-formula routes) — the old
    # construction error is lifted (tests/test_integrity.py covers the
    # semantics)
    cfg = CollectiveConfig(impl="ring", codec="bfp", fused_optimizer=True,
                           integrity_check=True)
    assert cfg.fused_optimizer and cfg.integrity_check
    # spec sanity
    assert OptimizerSpec(kind="sgd").state_keys == ()
    assert OptimizerSpec(kind="momentum").state_keys == ("m",)
    assert OptimizerSpec(kind="adamw").state_keys == ("m", "v")
    with pytest.raises(AssertionError):
        OptimizerSpec(kind="lion")


def test_trainer_rejects_clip_norm_in_fused_mode():
    from fpga_ai_nic_tpu.parallel import DPTrainer, make_mesh
    cfg = TrainConfig(
        mesh=MeshConfig(dp=N), global_batch=16 * N,
        collective=CollectiveConfig(impl="ring", codec="bfp",
                                    fused_optimizer=True),
        optimizer=OptimizerConfig(kind="sgd", clip_norm=1.0))
    with pytest.raises(ValueError, match="clip_norm"):
        DPTrainer(lambda p, b: jnp.float32(0.0), make_mesh(cfg.mesh), cfg)


def _train(fused, kind="adamw", codec="bfp", steps=6, fsdp=False,
           opt_overrides=()):
    from fpga_ai_nic_tpu.parallel import DPTrainer, make_mesh
    from fpga_ai_nic_tpu.parallel.fsdp import FSDPTrainer
    mcfg = MLPConfig(layer_sizes=(64, 64, 10), dtype="float32")
    axis = "fsdp" if fsdp else "dp"
    cfg = TrainConfig(
        iters=steps, global_batch=16 * N,
        mesh=MeshConfig(**{axis: N}),
        collective=CollectiveConfig(impl="ring", codec=codec,
                                    fused_optimizer=fused),
        optimizer=OptimizerConfig(kind=kind, learning_rate=3e-3,
                                  **dict(opt_overrides)))
    cls = FSDPTrainer if fsdp else DPTrainer
    tr = cls(lambda p, b: mlp.loss_fn(p, b, mcfg), make_mesh(cfg.mesh), cfg)
    state = tr.init_state(mlp.init(jax.random.PRNGKey(0), mcfg))
    r = np.random.default_rng(0)
    x = jnp.asarray(r.standard_normal((16 * N, 64)).astype(np.float32))
    y = jnp.asarray(r.integers(0, 10, 16 * N).astype(np.int32))
    batch = tr.shard_batch((x, y))
    losses = []
    for _ in range(steps):
        state, loss = tr.step(state, batch)
        losses.append(float(loss))
    return losses, state, tr


def test_convergence_smoke_fused_matches_unfused_adam():
    """Multi-step fused Adam tracks the unfused Adam trajectory within
    the codec's error envelope (here: far tighter — the formulations
    differ only in sub-ulp update rounding)."""
    lf, sf, _ = _train(True, steps=6)
    lu, su, _ = _train(False, steps=6)
    assert all(np.isfinite(lf)) and lf[-1] < lf[0]
    np.testing.assert_allclose(lf, lu, rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(sf.params),
                    jax.tree_util.tree_leaves(su.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_fused_mode_threads_ef_residual():
    """topk (error-feedback codec) + fused optimizer: the residual carry
    must survive the fused step (nonzero after a step, same threading as
    the unfused path)."""
    losses, state, tr = _train(True, kind="momentum", codec="topk",
                               steps=2)
    assert all(np.isfinite(losses))
    assert state.codec_state is not None
    assert float(jnp.abs(state.codec_state).max()) > 0.0


def test_fsdp_fused_mode_steps():
    lf, sf, _ = _train(True, kind="adamw", codec="bfp", steps=3,
                       fsdp=True)
    lu, su, _ = _train(False, kind="adamw", codec="bfp", steps=3,
                       fsdp=True)
    np.testing.assert_allclose(lf, lu, rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(sf.w_own),
                    jax.tree_util.tree_leaves(su.w_own)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_checkpointer_roundtrips_fused_state_across_mesh_shapes(tmp_path):
    """The fused path's sharded optimizer/master state survives a
    checkpoint round-trip onto a DIFFERENT mesh shape: dp8 -> dp2 (the
    flat padding multiple changes with n, so restore must re-pad the
    live elements — fused_update.repad_flat), masters and moments
    value-exact, and the restored trainer steps."""
    from fpga_ai_nic_tpu.parallel import DPTrainer, make_mesh
    from fpga_ai_nic_tpu.utils import checkpoint as ckpt

    losses, state8, tr8 = _train(True, kind="adamw", codec="bfp", steps=2)
    live = sum(tr8._meta.sizes)
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    c.save(2, state8)

    mcfg = MLPConfig(layer_sizes=(64, 64, 10), dtype="float32")
    n2 = 2
    cfg2 = TrainConfig(
        iters=1, global_batch=16 * n2, mesh=MeshConfig(dp=n2),
        collective=CollectiveConfig(impl="ring", codec="bfp",
                                    fused_optimizer=True),
        optimizer=OptimizerConfig(kind="adamw", learning_rate=3e-3))
    tr2 = DPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg),
                    make_mesh(cfg2.mesh), cfg2)
    params_like = jax.eval_shape(
        lambda: mlp.init(jax.random.PRNGKey(0), mcfg))
    restored = tr2.restore_state(c.restore(2), params_like=params_like)

    # padding multiples genuinely differ between the two shapes
    assert tr2._meta.padded_len != tr8._meta.padded_len
    assert int(restored.step) == 2
    np.testing.assert_array_equal(
        np.asarray(restored.w_own)[:live],
        np.asarray(state8.w_own)[:live])
    for k in ("m", "v"):
        np.testing.assert_array_equal(
            np.asarray(restored.opt_state[k])[:live],
            np.asarray(state8.opt_state[k])[:live])
    # rematerialized params bit-match (block-aligned chunks: the gather
    # quantization grouping is mesh-shape invariant)
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state8.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the restored trainer actually trains
    r = np.random.default_rng(1)
    x = jnp.asarray(r.standard_normal((16 * n2, 64)).astype(np.float32))
    y = jnp.asarray(r.integers(0, 10, 16 * n2).astype(np.int32))
    state, loss = tr2.step(restored, tr2.shard_batch((x, y)))
    assert np.isfinite(float(loss))


def test_repad_flat_rejects_wrong_model():
    from fpga_ai_nic_tpu.ops.fused_update import FlatMeta, repad_flat
    meta = FlatMeta(None, ((8,),), (np.float32,), (8,), 16)
    with pytest.raises(ValueError, match="live elements"):
        repad_flat(jnp.zeros(4), meta)
    # a nonzero stripped tail is a DIFFERENT model's live data — loud
    # error, never a silent truncation
    with pytest.raises(ValueError, match="refusing to truncate"):
        repad_flat(jnp.arange(12.0), meta)
    # zero tail = genuine padding from another mesh shape: re-fit
    out = repad_flat(jnp.pad(jnp.arange(1.0, 9.0), (0, 4)), meta)
    assert out.shape == (16,)
    np.testing.assert_array_equal(np.asarray(out[:8]),
                                  np.arange(1.0, 9.0))
    assert float(jnp.abs(out[8:]).max()) == 0.0


def test_fused_mode_with_lr_schedule_and_decay():
    """Schedules + weight decay ride the hyper vector (no recompile is
    covered above; here: the trajectory stays finite and decays lr)."""
    losses, _, _ = _train(
        True, kind="adamw", steps=4,
        opt_overrides=(("schedule", "cosine"), ("warmup_steps", 1),
                       ("decay_steps", 4), ("weight_decay", 0.01)))
    assert all(np.isfinite(losses))
