"""Bitwise parity battery for the Pallas paged gather-attend kernel.

`ops.paged_attend_pallas.paged_gather_attend` must be BITWISE equal to
the reference `forward_paged` path (the gathered-view + `_cached_attend`
oracle) on the same backend — not close, equal: the serving plane's
determinism story (eviction replay, preemption recovery, the chaos SLO)
is built on greedy argmax over exact logits, so an off-by-one-ulp kernel
would silently fork token streams.  The battery runs the kernel in
interpret mode on CPU against the reference over GQA/MHA head layouts,
ragged page occupancy, dirty recycled pools, inactive null-page slots
and tp-sharded (including kv-replicated) meshes; the one-definition DMA
schedule it lowers is checked at the opstream layer (coverage + hazard
discipline + the graftmc gather family) in the same file.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fpga_ai_nic_tpu.models import llama
from fpga_ai_nic_tpu.models import llama_decode as dec
from fpga_ai_nic_tpu.ops import paged_attend_pallas as pa
from fpga_ai_nic_tpu.verify import mc, opstream

CFG = llama.LlamaConfig.tiny()
DT = jnp.dtype(CFG.dtype)
SMALL = llama.LlamaConfig.tiny(vocab=64, dim=32, n_layers=1, n_heads=4,
                               n_kv_heads=2, ffn_dim=64)


@pytest.fixture
def rng():
    return np.random.default_rng(17)


def _table(rng, R, P_, n_pages):
    """Unique random page assignment (never the null page)."""
    pages = rng.permutation(np.arange(1, n_pages))[:R * P_]
    assert pages.size == R * P_, "pool too small for a full table"
    return pages.reshape(R, P_).astype(np.int32)


def _kernel_vs_reference(rng, *, R, H, n_kv, T, hd, ps, PW, n_pages,
                         poss, dirty=False):
    """One direct kernel cell against `_cached_attend` over the gathered
    view — the exact reference contraction `forward_paged` runs."""
    q = jnp.asarray(rng.normal(size=(R, H, T, hd)), jnp.float32)
    scale = 1e6 if dirty else 1.0
    pk = jnp.asarray(rng.normal(size=(n_pages, n_kv, ps, hd)) * scale,
                     jnp.float32)
    pv = jnp.asarray(rng.normal(size=(n_pages, n_kv, ps, hd)) * scale,
                     jnp.float32)
    table = jnp.asarray(_table(rng, R, PW, n_pages))
    pos = jnp.asarray(poss, jnp.int32)
    ck = pk[table].transpose(0, 2, 1, 3, 4).reshape(R, n_kv, PW * ps, hd)
    cv = pv[table].transpose(0, 2, 1, 3, 4).reshape(R, n_kv, PW * ps, hd)
    want = dec._cached_attend(q, ck, cv, pos, H, n_kv, hd ** -0.5)
    got = pa.paged_gather_attend(q, pk, pv, table, pos, page_size=ps)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestKernelParity:
    """Kernel (interpret) vs the gathered-view oracle, cell by cell."""

    def test_gqa(self, rng):
        _kernel_vs_reference(rng, R=2, H=4, n_kv=2, T=1, hd=8, ps=4,
                             PW=3, n_pages=8, poss=[5, 0])

    def test_mha_matvec_row(self, rng):
        """MHA at T=1 is the G*T == 1 trap: a per-page score tiling
        drifts by an ulp here (XLA lowers the matvec differently), which
        is why the kernel contracts the full landed row at once."""
        _kernel_vs_reference(rng, R=2, H=4, n_kv=4, T=1, hd=8, ps=4,
                             PW=3, n_pages=8, poss=[11, 3])

    def test_kv_single_head_full_span(self, rng):
        _kernel_vs_reference(rng, R=3, H=4, n_kv=1, T=1, hd=8, ps=4,
                             PW=4, n_pages=16, poss=[0, 7, 15])

    def test_prefill_chunk(self, rng):
        _kernel_vs_reference(rng, R=2, H=4, n_kv=2, T=4, hd=8, ps=4,
                             PW=3, n_pages=8, poss=[4, 0])

    def test_dirty_recycled_pool(self, rng):
        """1e6-magnitude garbage beyond the mask: parity holds because
        masked weights are EXACT +0, not because garbage is small."""
        _kernel_vs_reference(rng, R=2, H=4, n_kv=2, T=1, hd=8, ps=4,
                             PW=3, n_pages=8, poss=[5, 2], dirty=True)

    def test_ragged_occupancy(self, rng):
        """Live page counts 1..PW in one batch: each row's dead span is
        skipped by the kernel and masked by the reference."""
        _kernel_vs_reference(rng, R=4, H=4, n_kv=2, T=1, hd=8, ps=4,
                             PW=4, n_pages=20, poss=[0, 4, 9, 15])

    @pytest.mark.slow
    def test_exhaustive_positions(self, rng):
        """Every position of the table span, GQA and MHA."""
        for n_kv in (2, 4):
            for pos in range(12):
                _kernel_vs_reference(rng, R=1, H=4, n_kv=n_kv, T=1,
                                     hd=8, ps=4, PW=3, n_pages=5,
                                     poss=[pos])


class TestForwardPagedSeam:
    """attend_impl= through the full model: reference is the oracle."""

    def _run_both(self, rng, cfg, active=None):
        params = llama.init(jax.random.PRNGKey(0), cfg)
        R, T, PW, ps, n_pages = 3, 1, 3, 4, 16
        dt = jnp.dtype(cfg.dtype)
        shape = (n_pages, cfg.n_kv_heads, ps, cfg.head_dim)
        pool = [{"k": jnp.asarray(rng.standard_normal(shape) * 1e6, dt),
                 "v": jnp.asarray(rng.standard_normal(shape) * 1e6, dt)}
                for _ in range(cfg.n_layers)]
        table = jnp.asarray(_table(rng, R, PW, n_pages))
        pos = jnp.asarray([5, 0, 9], jnp.int32)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (R, T)), jnp.int32)
        outs = {}
        for impl in ("reference", "pallas"):
            lg, pl = dec.forward_paged(params, tokens, pool, table, pos,
                                       cfg, page_size=ps, active=active,
                                       attend_impl=impl)
            outs[impl] = (lg, pl)
        lg_r, pl_r = outs["reference"]
        lg_p, pl_p = outs["pallas"]
        np.testing.assert_array_equal(np.asarray(lg_p), np.asarray(lg_r))
        for a, b in zip(pl_r, pl_p):
            np.testing.assert_array_equal(np.asarray(a["k"]),
                                          np.asarray(b["k"]))
            np.testing.assert_array_equal(np.asarray(a["v"]),
                                          np.asarray(b["v"]))

    def test_bitwise_logits_and_pool_dirty(self, rng):
        self._run_both(rng, CFG)

    def test_inactive_null_page_slots(self, rng):
        """Inactive slots aim at the null page and sit at pos 0 — both
        impls must agree on them too (their logits are ignored, but the
        POOL writes they gate are load-bearing)."""
        self._run_both(rng, CFG,
                       active=jnp.asarray([True, False, False]))

    def test_rejects_unknown_impl(self, rng):
        params = llama.init(jax.random.PRNGKey(0), SMALL)
        shape = (4, SMALL.n_kv_heads, 4, SMALL.head_dim)
        pool = [{"k": jnp.zeros(shape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.float32)}]
        with pytest.raises(ValueError, match="attend_impl"):
            dec.forward_paged(params, jnp.zeros((1, 1), jnp.int32), pool,
                              jnp.zeros((1, 2), jnp.int32),
                              jnp.zeros((1,), jnp.int32), SMALL,
                              page_size=4, attend_impl="fast")


class TestTpParity:
    """tp-sharded cells: the kernel inside shard_map, against the
    reference inside the SAME shard_map (same psum order both arms)."""

    def _tp_cell(self, rng, tp):
        cfg = SMALL
        params = llama.init(jax.random.PRNGKey(0), cfg)
        R, T, PW, ps, n_pages = 2, 1, 3, 4, 8
        kvl = dec.kv_local_heads(cfg, tp)
        table = jnp.asarray(_table(rng, R, PW, n_pages))
        pos = jnp.asarray([5, 2], jnp.int32)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (R, T)),
                             jnp.int32)
        mesh = Mesh(np.asarray(jax.devices()[:tp]), ("tp",))
        specs = llama.param_specs(cfg, tp_axis="tp", tp_size=tp)
        dt = jnp.dtype(cfg.dtype)

        def run(impl):
            def body(p, t):
                shape = (n_pages, kvl, ps, cfg.head_dim)
                pool = [{"k": jnp.zeros(shape, dt),
                         "v": jnp.zeros(shape, dt)}
                        for _ in range(cfg.n_layers)]
                lg, pool = dec.forward_paged(
                    p, t, pool, table, pos, cfg, page_size=ps,
                    tp_axis="tp", attend_impl=impl)
                lg2, _ = dec.forward_paged(
                    p, t, pool, table, pos + T, cfg, page_size=ps,
                    tp_axis="tp", attend_impl=impl)
                return jnp.stack([lg, lg2])
            return jax.jit(jax.shard_map(
                body, mesh=mesh, in_specs=(specs, P()), out_specs=P(),
                check_vma=False))(params, tokens)

        np.testing.assert_array_equal(np.asarray(run("pallas")),
                                      np.asarray(run("reference")))

    def test_tp2_divisible(self, rng):
        self._tp_cell(rng, tp=2)

    @pytest.mark.slow
    def test_tp4_kv_replication(self, rng):
        """tp=4 > n_kv=2: each rank pages a single replicated kv head —
        the kernel's head-group mapping must match the kv_rep slice.
        slow tier: tp=2 + the engine tick cover the sharded seam
        in-gate; this buys the kv_rep corner its own shard_map compile."""
        self._tp_cell(rng, tp=4)

    def test_tp_engine_tick_tokens_and_traces(self, rng):
        """The tp-sharded engine tick end to end: identical greedy token
        streams vs the unsharded engine, and exactly one trace per
        program across an admit/evict/recycle schedule (J10)."""
        from fpga_ai_nic_tpu.serve import ServeConfig, ServeEngine
        cfg = llama.LlamaConfig.tiny(vocab=64, dim=32, n_layers=1,
                                     n_heads=2, n_kv_heads=1, ffn_dim=64)
        params = llama.init(jax.random.PRNGKey(0), cfg)

        def scfg(**kw):
            return ServeConfig(max_reqs=3, page_size=4, n_pages=5,
                               max_pages_per_seq=4, prefill_chunk=4,
                               **kw)

        prompts = [rng.integers(0, cfg.vocab, (n,)).astype(np.int32)
                   for n in (5, 3, 7, 4)]

        def serve(**kw):
            eng = ServeEngine(params, cfg, scfg(page_integrity=False),
                              **kw)
            reqs = [eng.submit(p, 4) for p in prompts]
            eng.run()
            return [list(r.generated) for r in reqs], eng

        want, _ = serve()
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
        got, eng = serve(tp_mesh=mesh, attend_impl="pallas")
        assert got == want
        assert eng.batcher.evictions > 0, "schedule exercised no churn"
        assert eng.trace_counts() == {"prefill": 1, "decode": 1}
        assert eng.recompiles_steady() == 0

    def test_tp_rejects_page_integrity(self):
        from fpga_ai_nic_tpu.serve import ServeConfig, ServeEngine
        cfg = llama.LlamaConfig.tiny(vocab=64, dim=32, n_layers=1,
                                     n_heads=2, n_kv_heads=1, ffn_dim=64)
        params = llama.init(jax.random.PRNGKey(0), cfg)
        scfg = ServeConfig(max_reqs=3, page_size=4, n_pages=5,
                           max_pages_per_seq=4, prefill_chunk=4,
                           page_integrity=True)
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
        with pytest.raises(ValueError, match="page_integrity"):
            ServeEngine(params, cfg, scfg, tp_mesh=mesh)


class TestValidation:
    """Hard, named errors — the flash_pallas Sk-check contract."""

    def _args(self, ps=4, hd=8):
        q = jnp.zeros((1, 2, 1, hd), jnp.float32)
        pk = jnp.zeros((3, 2, ps, hd), jnp.float32)
        table = jnp.zeros((1, 2), jnp.int32)
        pos = jnp.zeros((1,), jnp.int32)
        return q, pk, pk, table, pos

    def test_hardware_requires_lane_tileable_page(self):
        q, pk, pv, table, pos = self._args(ps=4, hd=128)
        with pytest.raises(ValueError) as ei:
            pa.paged_gather_attend(q, pk, pv, table, pos, page_size=4,
                                   interpret=False)
        msg = str(ei.value)
        assert "page_size=4" in msg and "128" in msg
        assert "attend_impl='reference'" in msg

    def test_hardware_requires_lane_tileable_head_dim(self):
        q, pk, pv, table, pos = self._args(ps=128, hd=8)
        with pytest.raises(ValueError) as ei:
            pa.paged_gather_attend(q, pk, pv, table, pos, page_size=128,
                                   interpret=False)
        assert "head_dim=8" in str(ei.value)

    def test_supported_mirrors_the_check(self):
        assert pa.supported(128, 128, interpret=False)
        assert not pa.supported(8, 128, interpret=False)
        assert not pa.supported(128, 96, interpret=False)
        assert pa.supported(8, 96, interpret=True)

    def test_rejects_non_int32_table(self):
        q, pk, pv, _, pos = self._args()
        with pytest.raises(ValueError, match="int32"):
            pa.paged_gather_attend(q, pk, pv,
                                   jnp.zeros((1, 2), jnp.int16), pos,
                                   page_size=4)

    def test_rejects_gqa_mismatch(self):
        _, pk, pv, table, pos = self._args()
        q = jnp.zeros((1, 3, 1, 8), jnp.float32)   # 3 % kv=2 != 0
        with pytest.raises(ValueError, match="multiple"):
            pa.paged_gather_attend(q, pk, pv, table, pos, page_size=4)

    def test_rejects_pool_shape_mismatch(self):
        q, pk, pv, table, pos = self._args()
        with pytest.raises(ValueError, match="page_size"):
            pa.paged_gather_attend(q, pk, pv, table, pos, page_size=8)


class TestGatherOpstream:
    """The one-definition DMA schedule at the checker layer: the same
    emitter the kernel lowers must pass coverage + hazard discipline and
    trip loudly under mutation (graftmc runs the full cell family)."""

    def test_stream_green_small_cells(self):
        for P_ in range(1, 5):
            for nl in range(P_ + 1):
                for d in (1, 2):
                    ops = opstream.paged_attend_op_stream(P_, nl, d)
                    assert opstream.check_dma_discipline(ops) == []
                    assert opstream.check_gather_coverage(ops, P_,
                                                          nl) == []

    def test_dropped_wait_is_flagged(self):
        ops = opstream.paged_attend_op_stream(4, 4, 2)
        mut = [o for o in ops if o[:3] != ("dma_wait",
                                           opstream.PagedAttendEmitter
                                           .K_CHAN, 0)]
        msgs = opstream.check_dma_discipline(mut)
        assert any("hazard" in m or "never waited" in m for m in msgs)
        cov = opstream.check_gather_coverage(mut, 4, 4)
        assert any("before its" in m for m in cov)

    def test_double_read_is_flagged(self):
        ops = opstream.paged_attend_op_stream(3, 3, 2)
        i = next(k for k, o in enumerate(ops) if o[0] == "local"
                 and o[1] == "attend_tile")
        mut = ops[:i + 1] + [ops[i]] + ops[i + 1:]
        cov = opstream.check_gather_coverage(mut, 3, 3)
        assert cov, "duplicated attend must break exactly-once coverage"

    def test_dead_page_transfer_is_flagged(self):
        ops = opstream.paged_attend_op_stream(4, 2, 2)
        k = opstream.PagedAttendEmitter.K_CHAN
        mut = list(ops) + [("dma_start", k, 3, ()), ("dma_wait", k, 3)]
        cov = opstream.check_gather_coverage(mut, 4, 2)
        assert any("dead" in m for m in cov)

    def test_mc_gather_cell_green(self):
        res, _ = mc.run_cell("gather", (5, 3, 2))
        assert res.ok, res

    def test_mc_flags_overlapping_slot_read(self):
        """Hoist a start past the wait of its slot-sharing predecessor:
        the model must catch the aliased semaphore slot dynamically."""
        ops = opstream.paged_attend_op_stream(4, 4, 2)
        k = opstream.PagedAttendEmitter.K_CHAN
        i_start = next(j for j, o in enumerate(ops)
                       if o[:3] == ("dma_start", k, 2))
        i_wait = next(j for j, o in enumerate(ops)
                      if o[:3] == ("dma_wait", k, 0))
        assert i_wait < i_start
        hoisted = ops[i_start]
        mut = ops[:i_wait] + [hoisted] + [o for o in ops[i_wait:]
                                          if o is not hoisted]
        model = opstream.GatherModel(
            mut, 2, meta={"route": "gather", "P": 4, "n_live": 4,
                          "depth": 2})
        res = mc.check(model, por=True)
        assert not res.ok
        assert res.violation.kind == "dma"
        assert "overlapping-slot read" in res.violation.message

    @pytest.mark.slow
    def test_mc_gather_family_exhaustive(self):
        for cell in mc.gather_cells():
            res, _ = mc.run_cell("gather", cell)
            assert res.ok, (cell, res)
