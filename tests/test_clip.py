"""Global-norm gradient clipping: sharded trainers must clip by the SAME
global norm as a single-device optax reference — including the tricky case
of tp-replicated leaves (norm weights de-duplicate them in the cross-axis
psum)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fpga_ai_nic_tpu.models import llama, mlp
from fpga_ai_nic_tpu.parallel import (DDPTrainer, DPTrainer, FSDPTrainer,
                                      QueuedDDPTrainer, ShardedTrainer,
                                      make_mesh)
from fpga_ai_nic_tpu.utils.config import (
    CollectiveConfig, MeshConfig, MLPConfig, OptimizerConfig, TrainConfig)

CLIP = 0.5
MCFG = MLPConfig(layer_sizes=(16, 32, 8), dtype="float32")


def _ref_sgd_clipped(params, batch, loss_fn, lr):
    import optax
    g = jax.grad(loss_fn)(params)
    g, _ = optax.clip_by_global_norm(CLIP).update(g, optax.EmptyState())
    return jax.tree_util.tree_map(
        lambda w, gg: (w.astype(jnp.float32)
                       - lr * gg.astype(jnp.float32)).astype(w.dtype),
        params, g)


@pytest.mark.parametrize("trainer_cls", [DPTrainer, DDPTrainer,
                                         QueuedDDPTrainer, FSDPTrainer])
def test_dp_clip_matches_optax_reference(rng, trainer_cls):
    mesh_cfg = (MeshConfig(fsdp=8) if trainer_cls is FSDPTrainer
                else MeshConfig(dp=8))
    cfg = TrainConfig(
        iters=1, global_batch=16, mesh=mesh_cfg,
        collective=CollectiveConfig(),
        optimizer=OptimizerConfig(kind="sgd", learning_rate=0.1,
                                  clip_norm=CLIP))
    loss = lambda p, b: mlp.loss_fn(p, b, MCFG)  # noqa: E731
    if trainer_cls is FSDPTrainer:
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(1, 8, 1, 1, 1, 1),
                    ("dp", "fsdp", "tp", "sp", "pp", "ep"))
        tr = trainer_cls(loss, mesh, cfg)
    else:
        tr = trainer_cls(loss, make_mesh(cfg.mesh), cfg)
    params = mlp.init(jax.random.PRNGKey(0), MCFG)
    batch = (jnp.asarray(rng.standard_normal((16, 16)), jnp.float32),
             jnp.asarray(rng.integers(0, 8, 16), jnp.int32))
    want = _ref_sgd_clipped(params, batch, lambda p: loss(p, batch),
                            cfg.optimizer.learning_rate)
    # the clip actually engages (unclipped norm exceeds CLIP); computed
    # BEFORE stepping — the trainer's donated step invalidates `params`
    g = jax.grad(lambda p: loss(p, batch))(params)
    gn = float(jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                            for l in jax.tree_util.tree_leaves(g))))
    assert gn > CLIP, gn
    state = tr.init_state(params)
    state, _ = tr.step(state, tr.shard_batch(batch))
    got = (tr.gathered_params(state) if trainer_cls is FSDPTrainer
           else state.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-5, atol=1e-6), got, want)


@pytest.mark.slow
def test_sharded_tp_clip_matches_unsharded(rng):
    """dp x tp Llama with clipping == single-device clipped adamw step:
    tp-replicated leaves (norms) must not be double-counted in the norm."""
    from jax.sharding import Mesh
    mcfg = llama.LlamaConfig.tiny()
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2, 1),
                ("dp", "tp", "sp"))
    cfg = TrainConfig(
        iters=1, global_batch=8, mesh=MeshConfig(dp=4, tp=2),
        collective=CollectiveConfig(),
        optimizer=OptimizerConfig(kind="sgd", learning_rate=0.1,
                                  clip_norm=CLIP))
    loss_sharded = lambda p, b: llama.loss_fn(p, b, mcfg,  # noqa: E731
                                              tp_axis="tp")
    loss_single = lambda p, b: llama.loss_fn(p, b, mcfg)   # noqa: E731
    params = llama.init(jax.random.PRNGKey(0), mcfg)
    toks = jnp.asarray(rng.integers(0, mcfg.vocab, (8, 17)), jnp.int32)
    batch = (toks[:, :-1], toks[:, 1:])
    want = _ref_sgd_clipped(params, batch,
                            lambda p: loss_single(p, batch),
                            cfg.optimizer.learning_rate)
    tr = ShardedTrainer(loss_sharded, mesh, cfg, llama.param_specs(mcfg))
    state = tr.init_state(params)
    state, _ = tr.step(state, tr.shard_batch(batch))
    got = tr.gathered_params(state) if hasattr(tr, "gathered_params") \
        else state.params
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=5e-5, atol=5e-6), got, want)
