"""ZeRO-3 (fsdp) trainer: parity vs the ZeRO-1 DPTrainer and the memory
contract (no persistent replicated params)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from fpga_ai_nic_tpu.models import mlp
from fpga_ai_nic_tpu.parallel import DPTrainer, FSDPTrainer
from fpga_ai_nic_tpu.utils.config import (
    CollectiveConfig, MeshConfig, MLPConfig, OptimizerConfig, TrainConfig)

N = 8
MCFG = MLPConfig(layer_sizes=(64, 128, 128, 32), dtype="float32")


def _cfg(**kw):
    kw.setdefault("collective", CollectiveConfig(impl="xla"))
    return TrainConfig(
        iters=1, global_batch=64,
        optimizer=OptimizerConfig(kind="momentum", learning_rate=1e-2), **kw)


def _batch(rng):
    x = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 32, 64), jnp.int32)
    return x, y


def _loss(p, b):
    return mlp.loss_fn(p, b, MCFG)


def test_fsdp_matches_dp_trainer(rng):
    """Same model, batch, optimizer: ZeRO-3 and ZeRO-1 must produce the
    same loss trajectory (only the collective schedule differs)."""
    params = mlp.init(jax.random.PRNGKey(0), MCFG)
    batch_host = _batch(rng)

    fsdp_mesh = Mesh(np.array(jax.devices()[:N]).reshape(1, N, 1, 1, 1, 1),
                     ("dp", "fsdp", "tp", "sp", "pp", "ep"))
    tr_f = FSDPTrainer(_loss, fsdp_mesh, _cfg(mesh=MeshConfig(fsdp=N)))
    st_f = tr_f.init_state(params)

    dp_mesh = Mesh(jax.devices()[:N], ("dp",))
    tr_d = DPTrainer(_loss, dp_mesh, _cfg(mesh=MeshConfig(dp=N)))
    st_d = tr_d.init_state(params)

    losses_f, losses_d = [], []
    for _ in range(4):
        st_f, lf = tr_f.step(st_f, tr_f.shard_batch(batch_host))
        st_d, ld = tr_d.step(st_d, tr_d.shard_batch(batch_host))
        losses_f.append(float(lf))
        losses_d.append(float(ld))
    np.testing.assert_allclose(losses_f, losses_d, rtol=1e-5)
    assert losses_f[-1] < losses_f[0]
    # master shards end equal too (same updates, same layout)
    np.testing.assert_allclose(np.asarray(st_f.w_own), np.asarray(st_d.w_own),
                               rtol=1e-5, atol=1e-6)


def test_fsdp_state_is_sharded_only(rng):
    """The persistent state is O(L/n) per device: no leaf of FSDPState may
    be replicated (the ZeRO-3 memory claim)."""
    params = mlp.init(jax.random.PRNGKey(0), MCFG)
    mesh = Mesh(np.array(jax.devices()[:N]).reshape(1, N, 1, 1, 1, 1),
                ("dp", "fsdp", "tp", "sp", "pp", "ep"))
    tr = FSDPTrainer(_loss, mesh, _cfg(mesh=MeshConfig(fsdp=N)))
    st = tr.init_state(params)
    total = int(np.sum([np.prod(l.shape)
                        for l in jax.tree_util.tree_leaves(params)]))
    # per-device shard bytes ~ total/n (f32), never total
    for leaf in (st.w_own, *st.opt_state.values()):
        shard = leaf.addressable_shards[0].data
        assert shard.size <= total // N + N * 16, (leaf.shape, shard.shape)
    # and gathered_params reconstructs the ORIGINAL tree (init_state only
    # re-lays-out the params, so pre-step the gather must round-trip them;
    # f32 model => exact)
    got = tr.gathered_params(st)
    jax.tree_util.tree_map(
        lambda g, p: np.testing.assert_array_equal(
            np.asarray(g, np.float32), np.asarray(p, np.float32)),
        got, params)


def test_fsdp_ring_impl_matches_xla(rng):
    """impl='ring' (uncompressed) through the custom-VJP gather must track
    the XLA-collective path: same math, only hop/add schedule differs."""
    params = mlp.init(jax.random.PRNGKey(0), MCFG)
    batch_host = _batch(rng)
    mesh = Mesh(np.array(jax.devices()[:N]).reshape(1, N, 1, 1, 1, 1),
                ("dp", "fsdp", "tp", "sp", "pp", "ep"))
    tr_x = FSDPTrainer(_loss, mesh, _cfg(mesh=MeshConfig(fsdp=N)))
    tr_r = FSDPTrainer(_loss, mesh,
                       _cfg(mesh=MeshConfig(fsdp=N),
                            collective=CollectiveConfig(impl="ring")))
    st_x, st_r = tr_x.init_state(params), tr_r.init_state(params)
    for _ in range(4):
        st_x, lx = tr_x.step(st_x, tr_x.shard_batch(batch_host))
        st_r, lr = tr_r.step(st_r, tr_r.shard_batch(batch_host))
        np.testing.assert_allclose(float(lr), float(lx), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(st_r.w_own), np.asarray(st_x.w_own),
                               rtol=1e-4, atol=1e-6)


def test_fsdp_bfp_quantized_forward_semantics(rng):
    """ZeRO-3 with the BFP wire format (the round-2 review's missing
    composition): the first-step loss must equal the loss at the
    BFP-roundtripped parameters exactly — the gather distributes quantized
    bytes while the master stays f32 — and training must still descend
    through the compressed-cotangent backward ring."""
    from fpga_ai_nic_tpu.ops import bfp, fused_update
    from fpga_ai_nic_tpu.utils.config import BFPConfig
    params = mlp.init(jax.random.PRNGKey(0), MCFG)
    batch_host = _batch(rng)
    comp = BFPConfig()                              # the reference's m8
    coll = CollectiveConfig(impl="ring", compression=comp)
    mesh = Mesh(np.array(jax.devices()[:N]).reshape(1, N, 1, 1, 1, 1),
                ("dp", "fsdp", "tp", "sp", "pp", "ep"))
    tr = FSDPTrainer(_loss, mesh, _cfg(mesh=MeshConfig(fsdp=N),
                                       collective=coll))
    st = tr.init_state(params)

    # expected first loss: quantize the padded flat vector with the same
    # block partition the per-chunk wire encode uses (chunk length is a
    # block multiple, so partitions coincide)
    flat, meta = fused_update.flatten_tree(params, coll, N)
    mant, se = bfp.bfp_encode(flat, comp.block_size, comp.mantissa_bits,
                              comp.rounding)
    qparams = fused_update.unflatten_tree(
        bfp.bfp_decode(mant, se, comp.block_size, jnp.float32), meta)
    want = float(mlp.loss_fn(qparams, batch_host, MCFG))

    losses = []
    for _ in range(4):
        st, loss = tr.step(st, tr.shard_batch(batch_host))
        losses.append(float(loss))
    np.testing.assert_allclose(losses[0], want, rtol=1e-6)
    assert losses[-1] < losses[0], losses


def test_fsdp_grad_accumulation(rng):
    """accum_steps > 1 averages microbatches identically to one big batch
    (f32 model: tolerances are tight)."""
    params = mlp.init(jax.random.PRNGKey(0), MCFG)
    batch_host = _batch(rng)
    mesh = Mesh(np.array(jax.devices()[:N]).reshape(1, N, 1, 1, 1, 1),
                ("dp", "fsdp", "tp", "sp", "pp", "ep"))
    tr1 = FSDPTrainer(_loss, mesh, _cfg(mesh=MeshConfig(fsdp=N)))
    tr2 = FSDPTrainer(_loss, mesh, _cfg(mesh=MeshConfig(fsdp=N),
                                        accum_steps=2))
    st1 = tr1.init_state(params)
    st2 = tr2.init_state(params)
    st1, l1 = tr1.step(st1, tr1.shard_batch(batch_host))
    st2, l2 = tr2.step(st2, tr2.shard_batch(batch_host))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(st1.w_own), np.asarray(st2.w_own),
                               rtol=1e-5, atol=1e-6)


def test_fsdp_restore_with_params_like(tmp_path, rng):
    """Same restore contract as every other trainer: a fresh process
    restores from jax.eval_shape output with zero device work."""
    from fpga_ai_nic_tpu.utils import checkpoint as ckpt
    params = mlp.init(jax.random.PRNGKey(0), MCFG)
    mesh = Mesh(np.array(jax.devices()[:N]).reshape(1, N, 1, 1, 1, 1),
                ("dp", "fsdp", "tp", "sp", "pp", "ep"))
    cfg = _cfg(mesh=MeshConfig(fsdp=N))
    tr = FSDPTrainer(_loss, mesh, cfg)
    st = tr.init_state(params)
    batch = _batch(rng)
    st, _ = tr.step(st, tr.shard_batch(batch))
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    c.save(1, st)
    w_saved = np.asarray(jax.device_get(st.w_own))

    tr2 = FSDPTrainer(_loss, mesh, cfg)
    shapes = jax.eval_shape(lambda: mlp.init(jax.random.PRNGKey(1), MCFG))
    st2 = tr2.restore_state(c.restore(1), params_like=shapes)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(st2.w_own)), w_saved)
    # and it can train (step_fn builds off the params_like-derived meta)
    st2, loss = tr2.step(st2, tr2.shard_batch(batch))
    assert np.isfinite(float(loss))
    # loaders can use the uniform public handle
    assert tr2.batch_spec is not None
