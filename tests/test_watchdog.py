"""Failure detection: watchdog timeouts, heartbeat staleness, and
retry-from-known-good-state recovery."""

import time

import pytest

from fpga_ai_nic_tpu.runtime.watchdog import (
    DeviceHangError, Heartbeat, Watchdog, run_with_recovery)


def test_watchdog_passes_results_through():
    wd = Watchdog(timeout_s=5.0)
    assert wd.run(lambda a, b: a + b, 2, 3) == 5


def test_watchdog_detects_hang_and_recovers_worker():
    wd = Watchdog(timeout_s=0.1)
    with pytest.raises(DeviceHangError):
        wd.run(time.sleep, 2.0)
    # the wedged (daemon) worker must not block subsequent healthy calls
    assert wd.run(lambda: "ok") == "ok"


def test_watchdog_propagates_exceptions():
    wd = Watchdog(timeout_s=5.0)
    with pytest.raises(ValueError, match="boom"):
        wd.run(lambda: (_ for _ in ()).throw(ValueError("boom")))


def test_heartbeat_staleness():
    hb = Heartbeat(stall_after_s=0.05)
    hb.beat()
    assert not hb.stalled()
    time.sleep(0.1)
    assert hb.stalled()
    with pytest.raises(DeviceHangError):
        hb.assert_alive()
    hb.beat()
    hb.assert_alive()
    assert hb.beats == 2


def test_run_with_recovery_retries_transient_failure():
    calls = {"n": 0}

    def flaky_step(state, batch):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")
        return state + batch, float(batch)

    failures = []
    out, loss = run_with_recovery(flaky_step, 10, 5, max_retries=3,
                                  backoff_s=0.01,
                                  on_failure=failures.append)
    assert (out, loss) == (15, 5.0)
    assert calls["n"] == 3 and len(failures) == 2


def test_run_with_recovery_restores_state():
    seen = []

    def step(state, batch):
        seen.append(state)
        if len(seen) < 2:
            raise RuntimeError("bad state")
        return state, 0.0

    out, _ = run_with_recovery(step, "live", None, max_retries=2,
                               backoff_s=0.01, restore_fn=lambda: "ckpt")
    assert seen == ["live", "ckpt"] and out == "ckpt"


def test_run_with_recovery_exhausts_and_raises():
    def always_fail(state, batch):
        raise RuntimeError("permanent")

    with pytest.raises(RuntimeError, match="permanent"):
        run_with_recovery(always_fail, None, None, max_retries=1,
                          backoff_s=0.01)


def test_recovery_refuses_donated_state_without_restore_fn():
    """A failed jitted step with donate_argnums consumes its input buffers;
    retrying with the same pytree must raise a clear error, not crash on
    deleted arrays."""
    import jax
    import jax.numpy as jnp

    step = jax.jit(lambda s, b: (s + b, jnp.sum(b)), donate_argnums=(0,))
    state = jnp.ones((4,))
    step(state, jnp.ones((4,)))          # donates `state`

    calls = {"n": 0}

    def failing_step(s, b):
        calls["n"] += 1
        raise RuntimeError("transient")

    with pytest.raises(RuntimeError, match="donated the state buffers"):
        run_with_recovery(failing_step, state, jnp.ones((4,)),
                          max_retries=2, backoff_s=0.01)
    assert calls["n"] == 1               # no blind retry on dead buffers


def test_recovery_composes_with_watchdog():
    calls = {"n": 0}

    def sometimes_hangs(state, batch):
        calls["n"] += 1
        if calls["n"] == 1:
            time.sleep(1.0)
        return state, 1.0

    out, loss = run_with_recovery(sometimes_hangs, 7, None, max_retries=1,
                                  backoff_s=0.01,
                                  watchdog=Watchdog(timeout_s=0.1))
    assert (out, loss) == (7, 1.0) and calls["n"] == 2
