"""The benchmark supervisor (bench_common.run_attempt) — the machinery the
driver's BENCH/MULTICHIP checks ride on.  A hang here was round 1's only
failure mode, so the kill paths get direct tests: result parsing, silence
kill with forensic tail, budget kill, nonzero-exit annotation, and the
result-before-unclean-exit salvage."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_common import cpu_env, is_tpu_platform, run_attempt


def _cmd(body: str):
    return [sys.executable, "-u", "-c", body]


def test_returns_last_json_line():
    r = run_attempt("ok", _cmd(
        "print('[bench] phase=x')\n"
        "print('{\"value\": 1}')\n"
        "print('{\"value\": 2}')"), budget_s=30, silence_s=30)
    assert r == {"value": 2}


def test_silence_kill_carries_forensic_tail():
    with pytest.raises(RuntimeError) as e:
        run_attempt("hang", _cmd(
            "import time\n"
            "print('[bench] phase=import')\n"
            "print('[bench] phase=devices')\n"
            "time.sleep(60)"), budget_s=60, silence_s=2)
    msg = str(e.value)
    assert "silent for" in msg
    assert "phase=devices" in msg          # the hang is localizable


def test_budget_kill():
    with pytest.raises(RuntimeError) as e:
        run_attempt("slow", _cmd(
            "import time\n"
            "for i in range(100):\n"
            "    print(f'[bench] tick {i}', flush=True)\n"
            "    time.sleep(1)"), budget_s=3, silence_s=60)
    assert "total budget" in str(e.value)


def test_result_survives_unclean_exit():
    r = run_attempt("dirty", _cmd(
        "import sys\n"
        "print('{\"value\": 7}')\n"
        "sys.exit(3)"), budget_s=30, silence_s=30)
    assert r["value"] == 7
    assert "rc=3" in r["unclean_exit"]


def test_no_json_failure_raises_with_tail():
    with pytest.raises(RuntimeError) as e:
        run_attempt("nojson", _cmd("print('only noise'); raise SystemExit(1)"),
                    budget_s=30, silence_s=30)
    assert "only noise" in str(e.value)


def test_cpu_env_forces_platform_and_device_count():
    env = cpu_env(8)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["PALLAS_AXON_POOL_IPS"] == ""
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    # replaces (not appends to) an inherited count; restore the
    # conftest-set value afterwards
    saved = os.environ.get("XLA_FLAGS")
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    try:
        env2 = cpu_env(8)
        assert "device_count=2" not in env2["XLA_FLAGS"]
        assert "--xla_force_host_platform_device_count=8" in env2["XLA_FLAGS"]
    finally:
        if saved is None:
            del os.environ["XLA_FLAGS"]
        else:
            os.environ["XLA_FLAGS"] = saved


def test_is_tpu_platform():
    assert is_tpu_platform("tpu") and is_tpu_platform("axon")
    assert not is_tpu_platform("cpu")
