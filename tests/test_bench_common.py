"""The benchmark supervisor (bench_common.run_attempt) — the machinery the
driver's BENCH/MULTICHIP checks ride on.  A hang here was round 1's only
failure mode, so the kill paths get direct tests: result parsing, silence
kill with forensic tail, budget kill, nonzero-exit annotation, and the
result-before-unclean-exit salvage."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_common import cpu_env, is_tpu_platform, run_attempt


def _cmd(body: str):
    return [sys.executable, "-u", "-c", body]


def test_returns_last_json_line():
    r = run_attempt("ok", _cmd(
        "print('[bench] phase=x')\n"
        "print('{\"value\": 1}')\n"
        "print('{\"value\": 2}')"), budget_s=30, silence_s=30)
    assert r == {"value": 2}


def test_silence_kill_carries_forensic_tail():
    with pytest.raises(RuntimeError) as e:
        run_attempt("hang", _cmd(
            "import time\n"
            "print('[bench] phase=import')\n"
            "print('[bench] phase=devices')\n"
            "time.sleep(60)"), budget_s=60, silence_s=2)
    msg = str(e.value)
    assert "silent for" in msg
    assert "phase=devices" in msg          # the hang is localizable


def test_budget_kill():
    with pytest.raises(RuntimeError) as e:
        run_attempt("slow", _cmd(
            "import time\n"
            "for i in range(100):\n"
            "    print(f'[bench] tick {i}', flush=True)\n"
            "    time.sleep(1)"), budget_s=3, silence_s=60)
    assert "total budget" in str(e.value)


def test_result_survives_unclean_exit():
    r = run_attempt("dirty", _cmd(
        "import sys\n"
        "print('{\"value\": 7}')\n"
        "sys.exit(3)"), budget_s=30, silence_s=30)
    assert r["value"] == 7
    assert "rc=3" in r["unclean_exit"]


def test_no_json_failure_raises_with_tail():
    with pytest.raises(RuntimeError) as e:
        run_attempt("nojson", _cmd("print('only noise'); raise SystemExit(1)"),
                    budget_s=30, silence_s=30)
    assert "only noise" in str(e.value)


def test_cpu_env_forces_platform_and_device_count():
    env = cpu_env(8)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["PALLAS_AXON_POOL_IPS"] == ""
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    # replaces (not appends to) an inherited count; restore the
    # conftest-set value afterwards
    saved = os.environ.get("XLA_FLAGS")
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    try:
        env2 = cpu_env(8)
        assert "device_count=2" not in env2["XLA_FLAGS"]
        assert "--xla_force_host_platform_device_count=8" in env2["XLA_FLAGS"]
    finally:
        if saved is None:
            del os.environ["XLA_FLAGS"]
        else:
            os.environ["XLA_FLAGS"] = saved


def test_is_tpu_platform():
    assert is_tpu_platform("tpu") and is_tpu_platform("axon")
    assert not is_tpu_platform("cpu")


def test_save_artifact_provenance(tmp_path, monkeypatch):
    """Every artifact must carry the provenance that makes a perf claim
    checkable: timestamp, git sha, argv — the round-2 lesson codified."""
    import json

    import bench_common
    monkeypatch.setattr(os.path, "dirname", os.path.dirname)
    # redirect the artifacts dir by pointing the module's file anchor
    monkeypatch.setattr(bench_common, "__file__",
                        str(tmp_path / "bench_common.py"))
    path = bench_common.save_artifact("unittest", {"value": 42})
    assert os.path.dirname(path) == str(tmp_path / "artifacts")
    with open(path) as f:
        d = json.load(f)
    assert d["value"] == 42
    prov = d["_provenance"]
    assert len(prov["git_sha"]) >= 7 or prov["git_sha"] == "unknown"
    assert "timestamp_utc" in prov and "argv" in prov


def test_probe_tpu_reports_wedge_as_false(monkeypatch):
    """A probe that hangs (or dies) must come back False quickly — the
    ladder's reorder decision rides on this never raising."""
    import bench_common

    def fake_run_attempt(name, cmd, **kw):
        raise RuntimeError("attempt probe failed (silent for 35s)")

    monkeypatch.setattr(bench_common, "run_attempt", fake_run_attempt)
    assert bench_common.probe_tpu() is False


def test_probe_tpu_requires_tpu_platform(monkeypatch):
    """A healthy CPU-platform child is NOT a healthy tunnel."""
    import bench_common
    monkeypatch.setattr(
        bench_common, "run_attempt",
        lambda *a, **k: {"ok": True, "platform": "cpu", "n_devices": 1})
    assert bench_common.probe_tpu() is False
    monkeypatch.setattr(
        bench_common, "run_attempt",
        lambda *a, **k: {"ok": True, "platform": "axon", "n_devices": 1})
    assert bench_common.probe_tpu() is True


def test_hbm_peak_env_channel(monkeypatch):
    """hbm_peak mirrors bf16_peak's discipline: known generations map to
    their HBM bandwidth, unknown ones fall back with an explicit UNKNOWN
    label so a mislabeled roofline can never pass silently."""
    import bench_common
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5p")
    peak, label = bench_common.hbm_peak()
    assert peak == 2765e9 and "v5p" in label and "UNKNOWN" not in label
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v99")
    peak, label = bench_common.hbm_peak()
    assert peak == 819e9 and "UNKNOWN" in label
    monkeypatch.delenv("PALLAS_AXON_TPU_GEN")
    peak, label = bench_common.hbm_peak()
    assert peak == 819e9 and "UNKNOWN" not in label
