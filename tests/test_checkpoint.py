"""Checkpoint/resume with optional BFP-compressed master state —
a capability the reference lacks entirely (SURVEY.md §5)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fpga_ai_nic_tpu.models import mlp
from fpga_ai_nic_tpu.parallel import DPTrainer, make_mesh
from fpga_ai_nic_tpu.utils import checkpoint as ckpt
from fpga_ai_nic_tpu.utils.config import (
    BFPConfig, CollectiveConfig, MeshConfig, MLPConfig, OptimizerConfig,
    TrainConfig)


def test_compress_roundtrip_bound(rng):
    x = rng.standard_normal((257, 33)).astype(np.float32)  # forces padding
    blob = ckpt.compress_array(x, BFPConfig())
    out = ckpt.decompress_array(blob)
    assert out.shape == x.shape and out.dtype == x.dtype
    # compressed wire cost ~ 1.06 B/elem vs 4
    packed = blob["mant"].size + blob["scale"].size
    assert packed < 0.3 * x.nbytes
    assert np.abs(out - x).max() < 2 ** -6 * np.abs(x).max() * 2


def test_checkpointer_save_restore(tmp_path, rng):
    mcfg = MLPConfig(layer_sizes=(16, 32, 8), dtype="float32")
    cfg = TrainConfig(iters=1, global_batch=16, mesh=MeshConfig(dp=8),
                      collective=CollectiveConfig(),
                      optimizer=OptimizerConfig(kind="momentum"))
    tr = DPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg), make_mesh(cfg.mesh), cfg)
    state = tr.init_state(mlp.init(jax.random.PRNGKey(0), mcfg))
    x = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, 16), jnp.int32)
    state, _ = tr.step(state, tr.shard_batch((x, y)))

    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    c.save(1, state)
    assert c.latest_step() == 1
    restored = c.restore(1)
    np.testing.assert_array_equal(restored["w_own"], np.asarray(state.w_own))
    np.testing.assert_array_equal(restored["opt_state"]["m"],
                                  np.asarray(state.opt_state["m"]))


def test_resume_continuity(tmp_path, rng):
    """Save -> restore -> step must equal an uninterrupted run exactly
    (restore_state rebuilds replicated params from the master shards)."""
    mcfg = MLPConfig(layer_sizes=(16, 32, 8), dtype="float32")
    cfg = TrainConfig(iters=1, global_batch=16, mesh=MeshConfig(dp=8),
                      optimizer=OptimizerConfig(kind="momentum"))

    def mk():
        tr = DPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg),
                       make_mesh(cfg.mesh), cfg)
        return tr, tr.init_state(mlp.init(jax.random.PRNGKey(0), mcfg))

    batch = (jnp.asarray(rng.standard_normal((16, 16)), jnp.float32),
             jnp.asarray(rng.integers(0, 8, 16), jnp.int32))
    tr, state = mk()
    state, _ = tr.step(state, tr.shard_batch(batch))
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    c.save(1, state)
    state, _ = tr.step(state, tr.shard_batch(batch))

    tr2, _ = mk()
    state2 = tr2.restore_state(c.restore(1))
    state2, _ = tr2.step(state2, tr2.shard_batch(batch))
    np.testing.assert_allclose(np.asarray(state2.w_own),
                               np.asarray(state.w_own), atol=1e-7)


def test_checkpointer_compressed(tmp_path, rng):
    mcfg = MLPConfig(layer_sizes=(16, 32, 8), dtype="float32")
    cfg = TrainConfig(iters=1, global_batch=16, mesh=MeshConfig(dp=8),
                      collective=CollectiveConfig(),
                      optimizer=OptimizerConfig(kind="momentum"))
    tr = DPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg), make_mesh(cfg.mesh), cfg)
    state = tr.init_state(mlp.init(jax.random.PRNGKey(0), mcfg))

    c = ckpt.Checkpointer(str(tmp_path / "ck"), compress=BFPConfig())
    c.save(2, state)
    restored = c.restore(2)
    w = np.asarray(state.w_own)
    err = np.abs(restored["w_own"] - w).max()
    assert restored["w_own"].shape == w.shape
    assert err <= 2 ** -6 * max(np.abs(w).max(), 1e-9) * 2


def test_async_checkpointer_save_restore(tmp_path, rng):
    """async_save returns before commit; wait_until_finished makes the
    files readable; restored state matches the saved one exactly."""
    mcfg = MLPConfig(layer_sizes=(16, 32, 8), dtype="float32")
    cfg = TrainConfig(iters=1, global_batch=16, mesh=MeshConfig(dp=8),
                      collective=CollectiveConfig(),
                      optimizer=OptimizerConfig(kind="momentum"))
    tr = DPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg),
                   make_mesh(cfg.mesh), cfg)
    state = tr.init_state(mlp.init(jax.random.PRNGKey(0), mcfg))
    x = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, 16), jnp.int32)
    state, _ = tr.step(state, tr.shard_batch((x, y)))

    c = ckpt.Checkpointer(str(tmp_path / "ck"), async_save=True)
    c.save(3, state)
    # snapshot before stepping: the trainer donates its input state
    w_saved = np.asarray(state.w_own)
    step_saved = int(state.step)
    # training continues while the save commits in the background
    state, _ = tr.step(state, tr.shard_batch((x, y)))
    c.wait_until_finished()
    assert c.latest_step() == 3
    restored = tr.restore_state(ckpt.Checkpointer(str(tmp_path / "ck"))
                                .restore(3))
    np.testing.assert_array_equal(np.asarray(restored.w_own), w_saved)
    assert int(restored.step) == step_saved


def test_sharded_trainer_checkpoint_roundtrip(tmp_path, rng):
    """BASELINE config 5 shape: tp x dp Llama ZeRO-1 state checkpoints with
    BFP-compressed masters and restores to a training-identical state."""
    from fpga_ai_nic_tpu.models import llama
    from fpga_ai_nic_tpu.parallel import ShardedTrainer
    from jax.sharding import Mesh
    import numpy as onp

    mcfg = llama.LlamaConfig.tiny()
    mesh = Mesh(onp.array(jax.devices()[:8]).reshape(4, 2, 1),
                ("dp", "tp", "sp"))
    cfg = TrainConfig(iters=1, global_batch=8,
                      mesh=MeshConfig(dp=4, tp=2),
                      collective=CollectiveConfig(),
                      optimizer=OptimizerConfig(kind="adamw",
                                                learning_rate=1e-3))
    tr = ShardedTrainer(
        lambda p, b: llama.loss_fn(p, b, mcfg, tp_axis="tp"),
        mesh, cfg, llama.param_specs(mcfg))
    state = tr.init_state(llama.init(jax.random.PRNGKey(0), mcfg))
    toks = jnp.asarray(rng.integers(0, mcfg.vocab, (8, 17)), jnp.int32)
    batch = tr.shard_batch((toks[:, :-1], toks[:, 1:]))
    state, _ = tr.step(state, batch)

    c = ckpt.Checkpointer(str(tmp_path / "ck"), compress=BFPConfig())
    c.save(7, state)
    w_saved = onp.asarray(state.w_own)
    step_saved = int(state.step)
    # masters-only: the working params tree must NOT be persisted (orbax
    # OCDBT layout has no per-key files, so inspect the restored tree)
    assert "params" not in c.restore(7)

    # fresh trainer (simulating a new process): layout from eval_shape —
    # zero device work, no throwaway init_state
    tr2 = ShardedTrainer(
        lambda p, b: llama.loss_fn(p, b, mcfg, tp_axis="tp"),
        mesh, cfg, llama.param_specs(mcfg))
    shapes = jax.eval_shape(lambda: llama.init(jax.random.PRNGKey(1), mcfg))
    restored = tr2.restore_state(c.restore(7), params_like=shapes)
    # BFP-compressed masters: bounded quantization error, exact step count
    assert int(restored.step) == step_saved
    err = onp.max(onp.abs(onp.asarray(restored.w_own) - w_saved))
    assert err < 0.02, err
    # restored state trains (one more step, finite loss)
    _, loss = tr2.step(restored, batch)
    assert onp.isfinite(float(loss)), float(loss)


def test_ddp_trainer_checkpoint_roundtrip(tmp_path, rng):
    """DDP masters-only checkpoint restores params bit-exactly via
    unflatten (uncompressed path)."""
    from fpga_ai_nic_tpu.parallel import DDPTrainer
    mcfg = MLPConfig(layer_sizes=(16, 32, 8), dtype="float32")
    cfg = TrainConfig(iters=1, global_batch=16, mesh=MeshConfig(dp=8),
                      optimizer=OptimizerConfig(kind="momentum"))
    tr = DDPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg),
                    make_mesh(cfg.mesh), cfg)
    state = tr.init_state(mlp.init(jax.random.PRNGKey(0), mcfg))
    batch = (jnp.asarray(rng.standard_normal((16, 16)), jnp.float32),
             jnp.asarray(rng.integers(0, 8, 16), jnp.int32))
    state, _ = tr.step(state, tr.shard_batch(batch))
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    c.save(1, state)
    w_saved = np.asarray(state.w_master)
    params_saved = jax.device_get(state.params)

    tr2 = DDPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg),
                     make_mesh(cfg.mesh), cfg)
    shapes = jax.eval_shape(lambda: mlp.init(jax.random.PRNGKey(1), mcfg))
    restored = tr2.restore_state(c.restore(1), params_like=shapes)
    np.testing.assert_array_equal(np.asarray(restored.w_master), w_saved)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        restored.params, params_saved)
    st2, loss = tr2.step(restored, tr2.shard_batch(batch))
    assert np.isfinite(float(loss))


def test_layout_sidecar_enforced(tmp_path):
    """A checkpoint whose flat masters are in a permuted (interleaved-1F1B)
    layer order carries a layer_layout.json sidecar; restore() must refuse
    to hand those bytes to a run that does not declare the MATCHING layout
    (ADVICE r4: the sidecar used to be advisory — written on save, read by
    nobody)."""
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    layout = {"layers_order": "interleaved-device-major",
              "pp": 4, "virtual_stages": 2}
    c.save(1, {"w": np.ones(4, np.float32)}, layout=layout)
    assert c.saved_layout() == layout

    # no declared layout -> refuse (the silent-misinterpretation case)
    with pytest.raises(ValueError, match="sidecar"):
        c.restore(1)
    # wrong pp/virtual_stages -> refuse, naming the mismatched keys
    with pytest.raises(ValueError, match="virtual_stages"):
        c.restore(1, expect_layout=dict(layout, virtual_stages=4))
    # matching layout -> restores
    out = c.restore(1, expect_layout=dict(layout))
    np.testing.assert_array_equal(out["w"], np.ones(4, np.float32))

    # plain checkpoint + declared layout -> refuse too (bytes are in model
    # order; deinterleaving them would equally permute layers)
    c2 = ckpt.Checkpointer(str(tmp_path / "ck2"))
    c2.save(1, {"w": np.ones(4, np.float32)})
    with pytest.raises(ValueError, match="no .*sidecar|model order"):
        c2.restore(1, expect_layout=layout)
    assert c2.restore(1)["w"].shape == (4,)


def test_legacy_directory_sidecar_honored_and_migrated(tmp_path):
    """Checkpoints written by older revisions carry ONE directory-scoped
    layer_layout.json.  It must still govern restores of every step that
    lacks a per-step sidecar (silently treating permuted bytes as plain
    model order is the exact hazard the sidecar exists for), and the next
    save must migrate it into the step dirs so the per-step rules apply."""
    import json as _json
    import os as _os
    layout = {"layers_order": "interleaved-device-major",
              "pp": 2, "virtual_stages": 2}
    d = str(tmp_path / "ck")
    c = ckpt.Checkpointer(d)
    c.save(1, {"w": np.ones(2, np.float32)})
    # simulate the old revision: directory-scoped sidecar, none per step
    with open(_os.path.join(d, "layer_layout.json"), "w") as f:
        _json.dump(layout, f)

    c2 = ckpt.Checkpointer(d)
    assert c2.saved_layout(1) == layout             # legacy fallback read
    with pytest.raises(ValueError, match="sidecar"):
        c2.restore(1)                               # still enforced
    np.testing.assert_array_equal(
        c2.restore(1, expect_layout=dict(layout))["w"],
        np.ones(2, np.float32))

    # the next save migrates: per-step sidecar appears, legacy file goes,
    # and a plain-order save of ANOTHER step cannot strand step 1
    c2.save(2, {"w": np.zeros(2, np.float32)})
    assert not _os.path.exists(_os.path.join(d, "layer_layout.json"))
    assert c2.saved_layout(1) == layout
    assert c2.saved_layout(2) is None


def test_async_save_defers_layout_sidecar(tmp_path):
    """async_save must not block on the sidecar write: the layout is
    applied at the next sync point (wait_until_finished / restore) and is
    visible through saved_layout() in the meantime."""
    layout = {"layers_order": "interleaved-device-major",
              "pp": 2, "virtual_stages": 2}
    c = ckpt.Checkpointer(str(tmp_path / "ck"), async_save=True)
    c.save(1, {"w": np.ones(2, np.float32)}, layout=layout)
    assert c.saved_layout(1) == layout              # pending, pre-commit
    c.wait_until_finished()
    assert c.saved_layout(1) == layout              # now on disk
    with pytest.raises(ValueError, match="sidecar"):
        c.restore(1)
    np.testing.assert_array_equal(
        c.restore(1, expect_layout=dict(layout))["w"],
        np.ones(2, np.float32))
    # plain async re-save of the same step clears the sidecar on sync
    c.save(1, {"w": np.zeros(2, np.float32)})
    c.wait_until_finished()
    assert c.saved_layout(1) is None

    # crash window: a committed step dir with a still-staged pending file
    # (the process died between commit and flush) — a fresh Checkpointer
    # must honor and enforce the staged layout, not silently drop it
    c._stage_sidecar(1, layout)
    c2 = ckpt.Checkpointer(str(tmp_path / "ck"), async_save=True)
    assert c2.saved_layout(1) == layout
    with pytest.raises(ValueError, match="sidecar"):
        c2.restore(1)
    np.testing.assert_array_equal(
        c2.restore(1, expect_layout=dict(layout))["w"],
        np.zeros(2, np.float32))


def test_layout_sidecar_cleared_by_plain_save(tmp_path):
    """The sidecar is per-step: a later plain-order save must neither
    inherit an earlier step's layout (restore(2) would demand a layout
    its bytes are not in) nor DELETE it (restore(1) still depends on it —
    the ADVICE r5 hazard of the old directory-scoped sidecar)."""
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    layout = {"layers_order": "interleaved-device-major",
              "pp": 2, "virtual_stages": 2}
    c.save(1, {"w": np.ones(2, np.float32)}, layout=layout)
    c.save(2, {"w": np.zeros(2, np.float32)})       # plain model order
    assert c.saved_layout(2) is None
    assert c.saved_layout() is None                 # default: latest step
    np.testing.assert_array_equal(c.restore(2)["w"],
                                  np.zeros(2, np.float32))
    # the earlier step's sidecar survived the later plain save: restore(1)
    # still enforces — and accepts — its own layout
    assert c.saved_layout(1) == layout
    with pytest.raises(ValueError, match="sidecar"):
        c.restore(1)
    np.testing.assert_array_equal(c.restore(1, expect_layout=dict(layout))["w"],
                                  np.ones(2, np.float32))
    # re-saving the SAME step in plain order does clear that step's sidecar
    c.save(1, {"w": np.full(2, 3.0, np.float32)})
    assert c.saved_layout(1) is None
    np.testing.assert_array_equal(c.restore(1)["w"],
                                  np.full(2, 3.0, np.float32))


# ---------------------------------------------------------------------------
# durability plane v2: manifests, audits, peer repair, crash sweep, GC
# (docs/DURABILITY.md)
# ---------------------------------------------------------------------------

import os
import shutil

from fpga_ai_nic_tpu.utils.checkpoint import (
    MANIFEST_FILE, CheckpointIntegrityError, bytes_checksum,
    flip_stored_bit, peer_fetch)


def _flip_data_bit(step_dir, fname, byte_off=0):
    """One data-region bit of a stored npy flips (the shared
    damage-at-rest primitive — utils.checkpoint.flip_stored_bit)."""
    flip_stored_bit(os.path.join(step_dir, fname), byte_off=byte_off)


def _primary_files(step_dir):
    return sorted(f for f in os.listdir(step_dir)
                  if f.endswith(".npy") and not f.endswith(".m.npy"))


def test_manifest_committed_with_step_and_audit_clean(tmp_path):
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    g = np.arange(300, dtype=np.float32)
    c.save(1, {"w": g, "step": np.int32(1)})
    man = c.read_manifest(1)
    assert man is not None and man["step"] == 1 and not man["emergency"]
    # per-leaf exact checksums over the stored representation
    by_path = {tuple(e["path"]): e for e in man["leaves"]}
    assert by_path[("w",)]["checksum"] == bytes_checksum(g.tobytes())
    rep = c.audit_step(1)
    assert rep.ok and rep.restorable and rep.failures == []
    assert c.latest_step(verified=True) == 1


def test_single_bit_flip_every_leaf_refused_unmirrored(tmp_path):
    """THE acceptance matrix, refusal half: a single bit flip in ANY
    stored primary file (plain or BFP-compressed representation) is
    detected at restore and refused — never silently restored."""
    base = str(tmp_path / "base")
    c = ckpt.Checkpointer(base, compress=BFPConfig())
    g = np.linspace(-3, 3, 2048).astype(np.float32)
    c.save(1, {"w_own": g, "opt_state": {"m": g * 0.5},
               "step": np.int32(1)})
    files = _primary_files(c._path(1))
    assert len(files) >= 5        # mant/scale x2 + metadata leaves
    for fname in files:
        d = str(tmp_path / f"flip_{fname}")
        shutil.copytree(base, d)
        c2 = ckpt.Checkpointer(d, compress=BFPConfig())
        _flip_data_bit(c2._path(1), fname)
        with pytest.raises(CheckpointIntegrityError, match="refusing"):
            c2.restore(1)
        assert c2.latest_step(verified=True) is None


def test_single_bit_flip_repaired_bit_exact_from_peer(tmp_path):
    """THE acceptance matrix, repair half: with dp-peer mirrors armed, a
    flipped bit in ANY primary shard is repaired from the peer copy —
    restored bytes BIT-equal the uncorrupted golden, the primary healed
    in place, and the repair wire moved exactly the shard bytes."""
    base = str(tmp_path / "base")
    c = ckpt.Checkpointer(base, shards=4, mirror=True)
    g = np.arange(1024, dtype=np.float32)
    c.save(1, {"w": g})
    shard_files = [f for f in _primary_files(c._path(1)) if ".s" in f]
    assert len(shard_files) == 4
    for fname in shard_files:
        d = str(tmp_path / f"rep_{fname}")
        shutil.copytree(base, d)
        c2 = ckpt.Checkpointer(d, shards=4, mirror=True)
        _flip_data_bit(c2._path(1), fname)
        rep = c2.audit_step(1, repair=True)
        assert rep.restorable and len(rep.repaired) == 1
        assert rep.repair_wire_bytes == g.nbytes // 4
        np.testing.assert_array_equal(rep.tree["w"], g)     # bit-exact
        # healed in place: a fresh audit is fully clean
        assert c2.audit_step(1).ok
        np.testing.assert_array_equal(c2.restore(1)["w"], g)


def test_primary_and_mirror_both_corrupt_refuses(tmp_path):
    c = ckpt.Checkpointer(str(tmp_path / "ck"), shards=4, mirror=True)
    g = np.arange(1024, dtype=np.float32)
    c.save(1, {"w": g})
    _flip_data_bit(c._path(1), "leaf_00000.s02.npy")
    _flip_data_bit(c._path(1), "leaf_00000.s02.m.npy")
    with pytest.raises(CheckpointIntegrityError, match="also bad"):
        c.restore(1)


def test_stale_manifest_never_validates(tmp_path):
    """A previous step's (self-consistent!) manifest copied over a later
    step must read as torn — the step field pins a manifest to the
    directory whose bytes it describes."""
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    g = np.arange(64, dtype=np.float32)
    c.save(1, {"w": g})
    c.save(2, {"w": g + 1})
    shutil.copyfile(os.path.join(c._path(1), MANIFEST_FILE),
                    os.path.join(c._path(2), MANIFEST_FILE))
    assert c.read_manifest(2) is None
    assert c.latest_step(verified=True) == 1
    step, tree = c.restore_latest_verified()
    assert step == 1
    np.testing.assert_array_equal(tree["w"], g)


def test_peer_fetch_bit_exact_any_dtype():
    for arr in (np.arange(257, dtype=np.float32),
                np.arange(-8, 8, dtype=np.int8).reshape(4, 4),
                np.arange(6, dtype=np.int16)):
        landed, wire = peer_fetch(arr)
        np.testing.assert_array_equal(landed, arr)
        assert landed.dtype == arr.dtype and wire == arr.nbytes


class _SimCrash(Exception):
    """The sweep's injected mid-save process death."""


def _sweep_seed(d):
    c = ckpt.Checkpointer(d, shards=4, mirror=True, keep_last=1)
    g1 = np.arange(1024, dtype=np.float32)
    g2 = g1 * 2.0 + 1.0
    L1 = {"layers_order": "plain", "pp": 1}
    L2 = {"layers_order": "interleaved-device-major", "pp": 2}
    c.save(1, {"w": g1}, layout=L1)
    return c, (g1, L1), (g2, L2)


def test_crash_point_sweep_exhaustive(tmp_path):
    """THE crash-consistency acceptance: the save of step 2 (over an
    existing step 1, with a layout sidecar AND retention GC armed) as
    an explicit file-op sequence, truncated at EVERY op prefix.  At
    every truncation point a fresh Checkpointer must restore exactly
    step 1 or exactly step 2 — bit-exact, matching sidecar, never
    garbage, never a stranded layout — and a follow-up save must
    recover over the torn leftovers."""
    ref, _, (g2, L2) = _sweep_seed(str(tmp_path / "ref"))
    kinds = []
    ref.op_hook = lambda i, op: kinds.append(op.kind)
    ref.save(2, {"w": g2}, layout=L2)
    n_ops = len(kinds)
    assert n_ops > 12 and "gc_guard" in kinds     # GC armed, guard planned
    assert ref.latest_step(verified=True) == 2

    outcomes = set()
    for k in range(n_ops + 1):
        d = str(tmp_path / f"k{k:03d}")
        c, (g1, L1), _ = _sweep_seed(d)

        def hook(i, op, k=k):
            if i == k:
                raise _SimCrash()

        c.op_hook = hook
        try:
            c.save(2, {"w": g2}, layout=L2)
        except _SimCrash:
            pass
        # a FRESH Checkpointer = the restarting process
        c2 = ckpt.Checkpointer(d, shards=4, mirror=True, keep_last=1)
        step = c2.latest_step(verified=True)
        assert step in (1, 2), f"prefix {k}/{n_ops}: verified={step}"
        golden, layout = ((g1, L1) if step == 1 else (g2, L2))
        assert c2.saved_layout(step) == layout, f"prefix {k}"
        got_step, tree = c2.restore_latest_verified(
            expect_layout=dict(layout))
        assert got_step == step
        np.testing.assert_array_equal(tree["w"], golden)    # bit-exact
        if step == 1:
            # pre-commit crash: step 2 must be fully ABSENT (no torn
            # dir, no stranded sidecar a later commit would mismatch)
            assert not os.path.isdir(c2._path(2)), f"prefix {k}"
        outcomes.add(step)
        # the torn tmp/trash leftovers must not wedge the next save
        c2.save(3, {"w": g2 + 1.0})
        assert c2.latest_step(verified=True) == 3, f"prefix {k}"
    assert outcomes == {1, 2}     # both protocol outcomes exercised


def test_same_step_resave_crash_window_rolls_back(tmp_path):
    """Re-saving an EXISTING step steps the old dir aside before the
    commit rename; a crash in that window must not lose the step —
    journal recovery (_recover_leftovers) rolls the old verified copy
    back, so restore lands the step's OLD content, never a mixed dir
    and never a refusal.  Exercised with the step as the directory's
    ONLY one (the emergency-dump / keep_last=1 shape, where losing it
    would mean zero restorable steps)."""
    d = str(tmp_path / "ck")
    c = ckpt.Checkpointer(d)
    g = np.arange(128, dtype=np.float32)
    c.save(2, {"w": g})                       # the ONLY step

    crash_at = []

    def hook(i, op):
        if op.kind == "replace" and op.path == c._path(2):
            # the old step 2 just stepped aside; die before the commit
            crash_at.append(i)
        if crash_at and i == crash_at[0] + 1:
            raise _SimCrash()

    c.op_hook = hook
    with pytest.raises(_SimCrash):
        c.save(2, {"w": g + 99})
    c.op_hook = None
    # mid-window state on disk: step_2.replaced + step_2.tmp-write
    assert os.path.isdir(c._path(2) + ".replaced")
    # a fresh Checkpointer (the restarting process) heals at construction
    c2 = ckpt.Checkpointer(d)
    assert not os.path.isdir(c2._path(2) + ".replaced")   # rolled back
    assert not os.path.isdir(c2._tmp_path(2))             # garbage cleaned
    step, tree = c2.restore_latest_verified()
    assert step == 2
    np.testing.assert_array_equal(tree["w"], g)           # the OLD bytes
    # the same-process sync point heals too
    c.save(2, {"w": g + 7})
    np.testing.assert_array_equal(c.restore(2)["w"], g + 7)


def test_keep_last_gc_bounds_directory(tmp_path):
    c = ckpt.Checkpointer(str(tmp_path / "ck"), keep_last=2)
    g = np.arange(64, dtype=np.float32)
    for s in range(1, 5):
        c.save(s, {"w": g + s})
    assert c._all_steps() == [3, 4]
    np.testing.assert_array_equal(c.restore(4)["w"], g + 4)


def test_gc_never_deletes_newest_verified_step(tmp_path):
    """Standalone gc(): when the steps inside the retention window are
    corrupt, the newest VERIFIED step outside it must survive — deleting
    it would leave the directory with zero restorable steps."""
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    g = np.arange(256, dtype=np.float32)
    for s in (1, 2, 3):
        c.save(s, {"w": g + s})
    _flip_data_bit(c._path(2), "leaf_00000.npy")
    _flip_data_bit(c._path(3), "leaf_00000.npy")
    c.keep_last = 1
    deleted = c.gc()
    assert deleted == [2]                  # corrupt AND outside window
    assert c._all_steps() == [1, 3]        # 3 = window, 1 = last verified
    step, tree = c.restore_latest_verified()
    assert step == 1
    np.testing.assert_array_equal(tree["w"], g + 1)


def test_save_time_gc_guard_aborts_on_lying_write(tmp_path):
    """The save-path GC read-back guard: if the freshly committed step
    does not audit restorable on disk, the retention deletions must NOT
    run (the old step would have been the only restorable copy)."""
    c = ckpt.Checkpointer(str(tmp_path / "ck"), keep_last=1)
    g = np.arange(256, dtype=np.float32)
    c.save(1, {"w": g})

    def hook(i, op):
        if op.kind == "gc_guard":
            # the disk 'lies': damage the committed bytes before the
            # read-back verification
            _flip_data_bit(c._path(2), "leaf_00000.npy")

    c.op_hook = hook
    c.save(2, {"w": g + 2})
    c.op_hook = None
    assert c._all_steps() == [1, 2]        # deletion aborted
    step, tree = c.restore_latest_verified()
    assert step == 1
    np.testing.assert_array_equal(tree["w"], g)


def test_async_save_encodes_in_background_thread(tmp_path, monkeypatch):
    """The async+compress stall satellite: the BFP encode of the
    master/optimizer shards runs INSIDE the background thread (pinned by
    thread identity, not timing), so save() stalls only for the
    device_get snapshot."""
    import threading
    encode_threads = []
    orig = ckpt.compress_array

    def probe(x, cfg):
        encode_threads.append(threading.get_ident())
        return orig(x, cfg)

    monkeypatch.setattr(ckpt, "compress_array", probe)
    c = ckpt.Checkpointer(str(tmp_path / "ck"), compress=BFPConfig(),
                          async_save=True)
    g = np.arange(4096, dtype=np.float32)
    c.save(1, {"w_own": g, "opt_state": {"m": g}, "step": np.int32(1)})
    c.wait_until_finished()
    assert len(encode_threads) == 2       # w_own + one moment
    assert all(t != threading.get_ident() for t in encode_threads)
    out = c.restore(1)
    assert out["w_own"].shape == g.shape
    # sync saves keep the encode on the caller (the comparison arm)
    encode_threads.clear()
    cs = ckpt.Checkpointer(str(tmp_path / "ck2"), compress=BFPConfig())
    cs.save(1, {"w_own": g, "opt_state": {}, "step": np.int32(1)})
    assert encode_threads == [threading.get_ident()]


def test_async_save_background_error_reraised_at_sync(tmp_path):
    c = ckpt.Checkpointer(str(tmp_path / "ck"), async_save=True)

    def hook(i, op):
        raise OSError("injected ENOSPC")

    c.op_hook = hook
    c.save(1, {"w": np.arange(8, dtype=np.float32)})
    with pytest.raises(OSError, match="ENOSPC"):
        c.wait_until_finished()
    assert c.latest_step(verified=True) is None


def test_restore_latest_verified_refuses_when_nothing_clean(tmp_path):
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    g = np.arange(64, dtype=np.float32)
    c.save(1, {"w": g})
    _flip_data_bit(c._path(1), "leaf_00000.npy")
    with pytest.raises(CheckpointIntegrityError, match="no verified"):
        c.restore_latest_verified()
    with pytest.raises(CheckpointIntegrityError, match="no verified"):
        ckpt.Checkpointer(str(tmp_path / "empty")).restore_latest_verified()


def test_elastic_restore_walks_back_and_repairs(tmp_path, rng):
    """End-to-end through the trainer: a DPTrainer state checkpointed
    with mirrors, a primary shard flipped at rest, restored through the
    elastic tier's path — repaired, and the restored state trains with
    bytes BIT-equal to an undamaged restore."""
    mcfg = MLPConfig(layer_sizes=(16, 32, 8), dtype="float32")
    cfg = TrainConfig(iters=1, global_batch=16, mesh=MeshConfig(dp=8),
                      optimizer=OptimizerConfig(kind="momentum"))
    tr = DPTrainer(lambda p, b: mlp.loss_fn(p, b, mcfg),
                   make_mesh(cfg.mesh), cfg)
    state = tr.init_state(mlp.init(jax.random.PRNGKey(0), mcfg))
    batch = (jnp.asarray(rng.standard_normal((16, 16)), jnp.float32),
             jnp.asarray(rng.integers(0, 8, 16), jnp.int32))
    state, _ = tr.step(state, tr.shard_batch(batch))
    c = ckpt.Checkpointer(str(tmp_path / "ck"), shards=8, mirror=True)
    c.save(1, state)
    golden = np.asarray(jax.device_get(state.w_own))
    shard = next(f for f in _primary_files(c._path(1)) if ".s" in f)
    _flip_data_bit(c._path(1), shard)
    step, tree = c.restore_latest_verified()
    assert step == 1
    np.testing.assert_array_equal(tree["w_own"], golden)
    restored = tr.restore_state(tree)
    np.testing.assert_array_equal(np.asarray(restored.w_own), golden)


def test_bytes_checksum_is_the_wire_plane_word_sum():
    """The manifest checksum == compress.golden.golden_word_checksum
    over the little-endian u32 word view (the chunked implementation
    only regroups an associative modular sum), and any single flipped
    byte changes it (odd weights invertible mod 2^32)."""
    from fpga_ai_nic_tpu.compress.golden import golden_word_checksum
    from fpga_ai_nic_tpu.utils import checkpoint as ckpt_mod
    r = np.random.default_rng(0)
    for n in (0, 1, 3, 4, 5, 1024, 4097):
        buf = r.integers(0, 256, n, dtype=np.uint8).tobytes()
        pad = (-len(buf)) % 4
        words = np.frombuffer(buf + b"\x00" * pad, "<u4")
        assert bytes_checksum(buf) == int(golden_word_checksum(words)), n
    # chunk boundaries regroup but never change the sum
    big = r.integers(0, 256, 8 * 1024, dtype=np.uint8)
    whole = ckpt_mod._u8_checksum(big)
    try:
        ckpt_mod._CHK_CHUNK_WORDS = 128
        assert ckpt_mod._u8_checksum(big) == whole
    finally:
        ckpt_mod._CHK_CHUNK_WORDS = 1 << 22
    # single-byte-flip never vanishes
    base = bytearray(r.integers(0, 256, 64, dtype=np.uint8).tobytes())
    ref = bytes_checksum(bytes(base))
    for off in (0, 1, 31, 63):
        for bit in (0, 7):
            mut = bytearray(base)
            mut[off] ^= (1 << bit)
            assert bytes_checksum(bytes(mut)) != ref, (off, bit)


def test_reserved_template_keys_rejected_at_save(tmp_path):
    """A user payload dict carrying a template sentinel name would
    rebuild as the WRONG data — the audited store refuses it at save
    time instead of misrestoring silently."""
    c = ckpt.Checkpointer(str(tmp_path / "ck"))
    g = np.arange(8, dtype=np.float32)
    with pytest.raises(TypeError, match="reserved"):
        c.save(1, {"a": g, "b": {"__leaf__": 0}})
    with pytest.raises(TypeError, match="reserved"):
        c.save(1, {"nested": {"x": {"__tuple__": []}}})
    assert c.latest_step() is None        # nothing half-written


def test_one_save_interrupt_per_save(tmp_path):
    """Two kill/diskfull specs planned for the same step fire across
    TWO saves — popping both for one save would mark a fault as
    exercised that never happened."""
    from fpga_ai_nic_tpu.runtime import chaos
    plan = chaos.FaultPlan(
        [chaos.FaultSpec("kill", "ckpt.save", step=0, fraction=0.2),
         chaos.FaultSpec("diskfull", "ckpt.save", step=0, fraction=0.2)])
    plan.begin_step(0)
    c = ckpt.Checkpointer(str(tmp_path / "ck"), chaos=plan)
    g = np.arange(64, dtype=np.float32)
    with pytest.raises(chaos.InjectedFault):
        c.save(1, {"w": g})
    assert len(plan.fired) == 1           # the sibling stays armed
    with pytest.raises(OSError):
        c.save(1, {"w": g})
    assert len(plan.fired) == 2
    c.save(1, {"w": g})                   # both spent: clean save
    np.testing.assert_array_equal(c.restore(1)["w"], g)
